"""Sphinx configuration (reference parity: /root/reference/doc/conf.py
builds with sphinx + autodoc + the RTD theme).

The markdown sources in this directory are consumed via MyST; the API
reference additionally gets live autodoc.  Environments without Sphinx
use the stdlib-only ``build_docs.py`` instead — ``make docs`` at the
repo root tries Sphinx first and falls back automatically, so the docs
are buildable everywhere (the round-1 gap: markdown only, no build
system)."""

import os
import sys

sys.path.insert(0, os.path.abspath(".."))

project = "mpi4torch_tpu"
copyright = "2026, mpi4torch_tpu developers"
author = "mpi4torch_tpu developers"

extensions = [
    "myst_parser",
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

source_suffix = {".md": "markdown"}
master_doc = "index"
exclude_patterns = ["html", "_build"]

html_theme = "alabaster"
autodoc_member_order = "bysource"
autodoc_typehints = "description"

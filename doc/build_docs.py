#!/usr/bin/env python
"""Zero-dependency docs builder: doc/*.md + API autodoc -> doc/html/.

The reference ships a Sphinx + autodoc + ReadTheDocs build
(reference: doc/conf.py, .readthedocs.yaml:1-20).  This repo ships the
same Sphinx entry points (doc/conf.py here consumes the markdown via
MyST when Sphinx is available) *plus* this stdlib-only fallback so
``make docs`` produces HTML in any environment — including CI images
where Sphinx cannot be installed.  Sphinx output is preferred when
importable; the fallback renders the same sources.

Markdown subset: ATX headers, fenced code, ordered/unordered lists,
tables, blockquotes, inline code/bold/italic/links — the subset doc/*.md
actually uses (checked by tests/test_docs.py).
"""

from __future__ import annotations

import html
import inspect
import re
import sys
from pathlib import Path

DOC = Path(__file__).resolve().parent
OUT = DOC / "html"
PAGES = ["index", "basic_usage", "examples", "parallelism", "serving",
         "compression", "fusion", "algorithms", "schedule_ir", "overlap",
         "resilience", "reshard", "elasticity", "transport", "analysis",
         "observability", "self_tuning", "api_reference",
         "design_tpu", "glossary"]

CSS = """
body { font-family: -apple-system, "Segoe UI", Roboto, sans-serif;
       max-width: 56rem; margin: 2rem auto; padding: 0 1rem;
       line-height: 1.55; color: #1a1a2e; }
nav { border-bottom: 1px solid #ddd; padding-bottom: .6rem;
      margin-bottom: 1.2rem; }
nav a { margin-right: .9rem; text-decoration: none; color: #0b5cad; }
pre { background: #f6f8fa; padding: .8rem; overflow-x: auto;
      border-radius: 6px; }
code { background: #f6f8fa; padding: .1rem .25rem; border-radius: 4px;
       font-size: .92em; }
pre code { padding: 0; }
table { border-collapse: collapse; }
td, th { border: 1px solid #ccc; padding: .3rem .6rem; }
h1, h2, h3 { line-height: 1.25; }
blockquote { border-left: 4px solid #ccc; margin-left: 0;
             padding-left: 1rem; color: #444; }
.api-entry { margin: 1.2rem 0; padding: .8rem; border: 1px solid #e2e2e8;
             border-radius: 6px; }
.api-sig { font-family: ui-monospace, monospace; font-weight: 600; }
.api-doc { white-space: pre-wrap; font-size: .95em; margin-top: .5rem; }
"""


def _inline(text: str) -> str:
    text = html.escape(text, quote=False)
    text = re.sub(r"`([^`]+)`", r"<code>\1</code>", text)
    text = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", text)
    text = re.sub(r"(?<!\*)\*([^*\s][^*]*)\*(?!\*)", r"<em>\1</em>", text)
    text = re.sub(r"\[([^\]]+)\]\(([^)\s]+)\)",
                  lambda m: f'<a href="{m.group(2)}">{m.group(1)}</a>', text)
    return text


def md_to_html(src: str) -> str:
    out, i, lines = [], 0, src.splitlines()
    list_stack: list[str] = []

    def close_lists():
        while list_stack:
            out.append(f"</{list_stack.pop()}>")

    while i < len(lines):
        line = lines[i]
        if line.startswith("```"):
            close_lists()
            block = []
            i += 1
            while i < len(lines) and not lines[i].startswith("```"):
                block.append(lines[i])
                i += 1
            out.append("<pre><code>"
                       + html.escape("\n".join(block)) + "</code></pre>")
            i += 1
            continue
        m = re.match(r"^(#{1,6})\s+(.*)$", line)
        if m:
            close_lists()
            n = len(m.group(1))
            out.append(f"<h{n}>{_inline(m.group(2))}</h{n}>")
            i += 1
            continue
        if re.match(r"^\s*\|.*\|\s*$", line):
            close_lists()
            rows = []
            while i < len(lines) and re.match(r"^\s*\|.*\|\s*$", lines[i]):
                cells = [c.strip() for c in lines[i].strip().strip("|")
                         .split("|")]
                if not all(re.fullmatch(r":?-+:?", c) for c in cells):
                    rows.append(cells)
                i += 1
            out.append("<table>")
            for r, cells in enumerate(rows):
                tag = "th" if r == 0 else "td"
                out.append("<tr>" + "".join(
                    f"<{tag}>{_inline(c)}</{tag}>" for c in cells) + "</tr>")
            out.append("</table>")
            continue
        m = re.match(r"^(\s*)([-*]|\d+\.)\s+(.*)$", line)
        if m:
            kind = "ol" if m.group(2)[0].isdigit() else "ul"
            if not list_stack or list_stack[-1] != kind:
                close_lists()
                out.append(f"<{kind}>")
                list_stack.append(kind)
            out.append(f"<li>{_inline(m.group(3))}</li>")
            i += 1
            continue
        if line.startswith("> "):
            close_lists()
            out.append(f"<blockquote>{_inline(line[2:])}</blockquote>")
            i += 1
            continue
        if not line.strip():
            close_lists()
            i += 1
            continue
        close_lists()
        para = [line]
        while (i + 1 < len(lines) and lines[i + 1].strip()
               and not re.match(r"^(#|```|\s*[-*]\s|\s*\d+\.\s|\||> )",
                                lines[i + 1])):
            i += 1
            para.append(lines[i])
        out.append(f"<p>{_inline(' '.join(para))}</p>")
        i += 1
    close_lists()
    return "\n".join(out)


def page(title: str, body: str) -> str:
    nav = " ".join(
        f'<a href="{p}.html">{p.replace("_", " ")}</a>' for p in PAGES
    ) + ' <a href="api_autodoc.html">api autodoc</a>'
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)} — mpi4torch_tpu</title>"
            f"<style>{CSS}</style></head><body>"
            f"<nav>{nav}</nav>{body}</body></html>")


def autodoc_html() -> str:
    """Introspected API reference — the autodoc analogue (reference:
    doc/conf.py autodoc extension + api_reference.rst automethod
    directives)."""
    sys.path.insert(0, str(DOC.parent))   # build from a source checkout
    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import ops as mpi_ops

    sections = []

    def entry(obj, name):
        try:
            sig = name + str(inspect.signature(obj))
        except (TypeError, ValueError):
            sig = name
        doc = inspect.getdoc(obj) or "(no docstring)"
        return (f'<div class="api-entry"><div class="api-sig">'
                f"{html.escape(sig)}</div>"
                f'<div class="api-doc">{html.escape(doc)}</div></div>')

    sections.append("<h1>API autodoc</h1>"
                    "<p>Generated from live signatures and docstrings "
                    "(the reference builds this with Sphinx autodoc, "
                    "doc/conf.py).</p>")

    sections.append("<h2>mpi4torch_tpu (facade)</h2>")
    for name in sorted(mpi.__all__):
        obj = getattr(mpi, name)
        if inspect.isclass(obj):
            sections.append(entry(obj, name))
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith("_") or not callable(meth):
                    continue
                sections.append(entry(meth, f"{name}.{mname}"))
        elif callable(obj):
            sections.append(entry(obj, name))
        else:
            sections.append(
                f'<div class="api-entry"><div class="api-sig">'
                f"{html.escape(name)}</div>"
                f'<div class="api-doc">{html.escape(repr(obj))}</div></div>')

    sections.append("<h2>mpi4torch_tpu.ops</h2>")
    for name in sorted(mpi_ops.__all__):
        sections.append(entry(getattr(mpi_ops, name), name))
    return "\n".join(sections)


def main() -> int:
    OUT.mkdir(parents=True, exist_ok=True)
    for p in PAGES:
        src = (DOC / f"{p}.md").read_text()
        title = p.replace("_", " ")
        (OUT / f"{p}.html").write_text(page(title, md_to_html(src)))
    (OUT / "api_autodoc.html").write_text(page("API autodoc",
                                               autodoc_html()))
    n = len(list(OUT.glob("*.html")))
    print(f"built {n} pages -> {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

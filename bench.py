"""Headline benchmark: Allreduce fwd+bwd bandwidth + single-chip MFU.

Three measurements, all jitted XLA programs, printed as ONE JSON line:

1. **Allreduce forward+backward effective bandwidth** (the BASELINE.md
   primary metric).  On N>1 devices this uses ring-allreduce
   bytes-on-wire accounting ``2*(N-1)/N * size``; on a single chip there
   is no interconnect, so the number is the HBM-limited throughput of
   the same program (honestly labeled).
2. **Flash-attention fwd+bwd MFU** — the Pallas kernel
   (mpi4torch_tpu/ops/flash.py) on a chip-sized causal shape; achieved
   FLOP/s vs the chip's peak.  Chip-meaningful even on one device.
3. **Flagship-transformer train-step MFU** — forward + backward + SGD
   update of the decoder-only transformer
   (mpi4torch_tpu/models/transformer.py) using the standard
   ``6 * n_params * n_tokens`` dense-FLOPs accounting plus the causal
   attention term.

Robustness contract (round-1 postmortem): the externally-registered TPU
plugin (axon) can *hang* or *error* at backend init.  The TPU backend is
therefore probed in a subprocess with a timeout; on any failure the
bench pins the CPU platform and still emits a labeled JSON line with
``"tpu_unavailable": true`` — never a non-zero exit.

Baseline: the reference publishes no numbers (BASELINE.md); the working
target for the headline metric is 80% of ~45 GB/s/link v5e ICI
≈ 36 GB/s/chip, so ``vs_baseline = value / 36.0``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Known per-chip bf16 peak FLOP/s by PJRT device_kind substring.  The
# fallback (v5e) is the BASELINE.md reference hardware.
_PEAK_FLOPS = [
    ("v6", 918e12),       # Trillium
    ("v5p", 459e12),
    ("v5", 197e12),       # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
]
_DEFAULT_PEAK = 197e12


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return _DEFAULT_PEAK


def _probe_tpu(timeout: float = 120.0):
    """Initialize the TPU backend in a THROWAWAY subprocess.

    Returns ``(device_kind, n_devices)`` if a TPU came up, else None.
    Round 1 lost both driver artifacts to this init hanging (rc=124) or
    raising (rc=1) in-process; a subprocess is the only safe probe."""
    code = (
        "import jax; d = jax.devices(); "
        "print(d[0].platform + '|' + d[0].device_kind + '|' + str(len(d)))"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
    except (subprocess.TimeoutExpired, OSError):
        return None
    if r.returncode != 0:
        return None
    try:
        platform, kind, n = r.stdout.strip().splitlines()[-1].split("|")
    except ValueError:
        return None
    if platform != "tpu":
        return None
    return kind, int(n)


def _timeit(fn, *args, iters: int):
    import jax

    out = fn(*args)              # compile + warmup
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _bench_allreduce(on_tpu: bool):
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    n = len(jax.devices())
    # 256 MiB/chip on TPU (1B params would OOM nothing but adds no signal
    # beyond saturation); small on the CPU smoke path.
    nelem = (1 << 26) if on_tpu else (1 << 18)
    bytes_per_pass = nelem * 4

    comm = mpi.COMM_WORLD

    def loss(x):
        y = comm.Allreduce(x, mpi.MPI_SUM)
        return jnp.vdot(y, y)

    step = mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)
    x = jnp.ones((nelem,), jnp.float32)
    dt = _timeit(step, x, iters=20 if on_tpu else 3)

    if n > 1:
        wire = 2.0 * (n - 1) / n * bytes_per_pass
    else:
        wire = float(bytes_per_pass)
    gbps = 2.0 * wire / dt / 1e9       # fwd psum + adjoint psum per step
    return gbps, n, bytes_per_pass, dt


def _bench_flash(on_tpu: bool, peak: float):
    """Causal flash-attention fwd+bwd achieved FLOP/s and MFU."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.ops import flash

    if on_tpu:
        b, s, h, d, dtype, iters = 4, 4096, 8, 128, jnp.bfloat16, 20
    else:
        b, s, h, d, dtype, iters = 1, 256, 2, 64, jnp.float32, 2

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype) for kk in keys)

    def loss(q, k, v):
        out = flash.flash_attention(q, k, v, causal=True, impl="auto")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    dt = _timeit(step, q, k, v, iters=iters)

    # Causal fwd = 2 matmuls * 2 FLOP/MAC * B*H*S^2*D / 2 (masked half).
    # MFU uses *model* FLOPs only (PaLM convention): fwd + 2x bwd = 3x;
    # the flash backward's score recompute is excluded (that extra work
    # would make this HFU and overstate utilization).
    fwd = 2.0 * b * h * s * s * d
    flops = 3.0 * fwd
    achieved = flops / dt
    kernel_engaged = bool(
        on_tpu and flash._eligible(q, k))
    return {
        "tflops": round(achieved / 1e12, 3),
        "mfu": round(achieved / peak, 4),
        "shape": [b, s, h, d],
        "dtype": str(jnp.dtype(dtype)),
        "seconds_per_step": dt,
        "pallas_kernel": kernel_engaged,
    }


def _bench_train_step(on_tpu: bool, peak: float):
    """Flagship transformer fwd+bwd+update MFU (6*N*T accounting)."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.models import transformer as T

    if on_tpu:
        cfg = T.TransformerConfig(vocab=32768, d_model=2048, n_heads=16,
                                  n_layers=8, d_ff=8192, max_seq=2048)
        batch, dtype, iters = 8, jnp.bfloat16, 10
    else:
        cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                  n_layers=2, d_ff=128, max_seq=64)
        batch, dtype, iters = 2, jnp.float32, 2

    params = T.init_transformer(jax.random.PRNGKey(0), cfg, dtype=dtype)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.max_seq),
                                0, cfg.vocab, jnp.int32)

    @jax.jit
    def step(params, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: T.lm_loss(cfg, p, tokens))(params)
        new = jax.tree.map(lambda p, g: p - 1e-3 * g.astype(p.dtype),
                           params, grads)
        return loss, new

    dt = _timeit(step, params, tokens, iters=iters)

    n_params = sum(x.size for x in jax.tree.leaves(params))
    n_tokens = batch * cfg.max_seq
    s, hd = cfg.max_seq, cfg.d_model // cfg.n_heads
    # 6*N*T dense accounting + causal attention matmuls (fwd 2*2*B*H*S^2*
    # Dh/2 per layer, x3 for fwd+bwd model FLOPs — recompute excluded,
    # as in _bench_flash).
    attn = 3.0 * 2.0 * batch * cfg.n_heads * s * s * hd * cfg.n_layers
    flops = 6.0 * n_params * n_tokens + attn
    achieved = flops / dt
    return {
        "tflops": round(achieved / 1e12, 3),
        "mfu": round(achieved / peak, 4),
        "n_params": n_params,
        "tokens_per_step": n_tokens,
        "dtype": str(jnp.dtype(dtype)),
        "seconds_per_step": dt,
    }


def main() -> None:
    cpu_pinned = os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
    tpu_info = None if cpu_pinned else _probe_tpu()
    # tpu_unavailable marks a FAILED probe only; a deliberate
    # JAX_PLATFORMS=cpu smoke run reports cpu_requested instead.
    tpu_unavailable = not cpu_pinned and tpu_info is None

    if tpu_info is None:
        # Either the user pinned CPU or the TPU probe failed/timed out.
        # The env var alone does not stop an externally-registered TPU
        # plugin from initializing (and hanging); the config update does.
        import jax

        jax.config.update("jax_platforms", "cpu")
        device_kind, on_tpu = "cpu", False
        peak = _DEFAULT_PEAK
    else:
        device_kind, _n = tpu_info
        on_tpu = True
        peak = _peak_flops(device_kind)

    import jax

    platform = jax.devices()[0].platform
    gbps, n, bytes_per_pass, dt = _bench_allreduce(on_tpu)
    flash_res = _bench_flash(on_tpu, peak)
    train_res = _bench_train_step(on_tpu, peak)

    target_gbps = 36.0  # 0.8 * ~45 GB/s v5e ICI per-link (BASELINE.md)
    print(json.dumps({
        "metric": "allreduce_fwd_bwd_bandwidth_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / target_gbps, 4),
        "n_devices": n,
        "platform": platform,
        "device_kind": device_kind,
        "tpu_unavailable": tpu_unavailable,
        "cpu_requested": cpu_pinned,
        "tensor_mib": bytes_per_pass / (1 << 20),
        "seconds_per_step": dt,
        "peak_flops_assumed": peak,
        "flash_attention_fwd_bwd": flash_res,
        "train_step": train_res,
        "note": ("ring-allreduce bytes-on-wire accounting" if n > 1 else
                 "single chip: HBM-limited pipeline throughput, no ICI; "
                 "MFU sub-benches are the chip-meaningful numbers"),
    }))


if __name__ == "__main__":
    main()

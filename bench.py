"""Headline benchmark: Allreduce forward+backward effective bandwidth.

Measures the BASELINE.md primary metric — fwd+bwd Allreduce GB/s per chip —
on whatever devices are available: the full local device set as the mesh
(N real TPU chips, or the single tunneled chip).  The whole measured region
(forward psum, adjoint psum, elementwise loss) is ONE jitted XLA program.

Bytes-on-wire per chip per collective uses the standard ring-allreduce
accounting 2*(N-1)/N * size; on a single chip there is no interconnect, so
the reported number is the HBM-limited pipeline throughput of the same
program (bytes = tensor size per pass), honestly labeled in the JSON.

Baseline: the reference publishes no numbers (BASELINE.md); the working
target is 80% of ~45 GB/s/link v5e ICI ≈ 36 GB/s/chip, so
``vs_baseline = value / 36.0``.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The env var alone does not stop an externally-registered TPU
        # plugin (axon) from initializing — and its init can hang on a
        # flaky tunnel.  The explicit config update does (same pin as
        # tests/conftest.py).  Real-TPU runs leave JAX_PLATFORMS unset.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    devs = jax.devices()
    n = len(devs)
    platform = devs[0].platform

    # 256 MiB/chip on TPU (1B params would OOM nothing but adds no signal
    # beyond saturation); small on the CPU smoke path.
    nelem = (1 << 26) if platform == "tpu" else (1 << 18)
    dtype = jnp.float32
    bytes_per_pass = nelem * 4

    comm = mpi.COMM_WORLD

    def loss(x):
        y = comm.Allreduce(x, mpi.MPI_SUM)
        return jnp.vdot(y, y)

    step = mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)

    x = jnp.ones((nelem,), dtype)
    # Warmup: compile + first run.
    out = step(x)
    jax.block_until_ready(out)

    iters = 20 if platform == "tpu" else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters

    if n > 1:
        wire_per_collective = 2.0 * (n - 1) / n * bytes_per_pass
    else:
        wire_per_collective = float(bytes_per_pass)
    # fwd Allreduce + adjoint Allreduce per step.
    gbps = 2.0 * wire_per_collective / dt / 1e9

    target_gbps = 36.0  # 0.8 * ~45 GB/s v5e ICI per-link (BASELINE.md)
    print(json.dumps({
        "metric": "allreduce_fwd_bwd_bandwidth_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / target_gbps, 4),
        "n_devices": n,
        "platform": platform,
        "tensor_mib": bytes_per_pass / (1 << 20),
        "seconds_per_step": dt,
        "note": ("ring-allreduce bytes-on-wire accounting" if n > 1 else
                 "single chip: HBM-limited pipeline throughput, no ICI"),
    }))


if __name__ == "__main__":
    main()

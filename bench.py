"""Headline benchmark: Allreduce fwd+bwd bandwidth + single-chip MFU.

Three measurements, all jitted XLA programs, printed as ONE JSON line on
stdout (progress/partial lines go to stderr):

1. **Allreduce forward+backward effective bandwidth** (the BASELINE.md
   primary metric).  On N>1 devices this uses ring-allreduce
   bytes-on-wire accounting ``2*(N-1)/N * size``; on a single chip there
   is no interconnect, so the number is the HBM-limited throughput of
   the same program (honestly labeled, with the roofline fraction).
2. **Flash-attention fwd+bwd MFU** — the Pallas kernel
   (mpi4torch_tpu/ops/flash.py) on a chip-sized causal shape; achieved
   FLOP/s vs the chip's peak.  Chip-meaningful even on one device.
3. **Flagship-transformer train-step MFU** — forward + backward + SGD
   update of the decoder-only transformer
   (mpi4torch_tpu/models/transformer.py) using the standard
   ``6 * n_params * n_tokens`` dense-FLOPs accounting plus the causal
   attention term.

Robustness contract (round-1 + round-3 postmortems):
- the externally-registered TPU plugin (axon) can *hang* or *error* at
  backend init, so the TPU backend is probed in a subprocess with a
  timeout; on failure the bench pins the CPU platform and emits a
  labeled JSON with ``"tpu_unavailable": true``;
- EVERY sub-bench runs inside its own try/except: a crash records a
  ``{"error": ...}`` stanza for that sub-bench and the bench continues
  (round 3 lost its only on-chip Allreduce number to a later sub-bench's
  compile failure — a completed measurement must never be erased by a
  subsequent crash);
- partial results are flushed to stderr as they land, the final JSON is
  printed in a ``finally:``, and the process always exits 0.

Timing methodology (round-3 AND round-5 postmortems): each timed
iteration ends with a 1-element device->host fetch of its own output.
Round 3 found that blocking once after N async dispatches measured
23 TB/s on an 0.82 TB/s chip; round 5 found that even PER-ITERATION
``block_until_ready`` still under-measured on the tunnel runtime (a
0.5-TFLOP flash step "finished" in 82 µs — 30x the chip's peak;
allreduce read 11.9x the HBM roofline) — the barrier returns at remote
enqueue, not completion.  A data fetch cannot lie: the host bytes exist
only after the producing execution finished (see ``_force``).  The JSON
carries ``timing_floor_s`` (the fetch round-trip on a ready buffer) and
the HBM-roofline fraction so both sanity checks are visible.

Baseline: the reference publishes no numbers (BASELINE.md); the working
target for the headline metric is 80% of ~45 GB/s/link v5e ICI
≈ 36 GB/s/chip, so ``vs_baseline = value / 36.0``.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time
import traceback

# Known per-chip bf16 peak FLOP/s and HBM bandwidth (bytes/s) by PJRT
# device_kind substring.  The fallback (v5e) is the BASELINE.md reference
# hardware.
_CHIP_TABLE = [
    # (substring, peak bf16 FLOP/s, HBM GB/s)
    ("v6", 918e12, 1640.0),   # Trillium
    ("v5p", 459e12, 2765.0),
    ("v5", 197e12, 819.0),    # v5e / "TPU v5 lite"
    ("v4", 275e12, 1228.0),
    ("v3", 123e12, 900.0),
]
_DEFAULT_PEAK = 197e12
_DEFAULT_HBM = 819.0


def _chip_specs(device_kind: str):
    kind = device_kind.lower()
    for sub, peak, hbm in _CHIP_TABLE:
        if sub in kind:
            return peak, hbm
    return _DEFAULT_PEAK, _DEFAULT_HBM


def _probe_tpu(timeout: float = 300.0, attempts: int = 3,
               retry_wait: float = 60.0):
    """Initialize the TPU backend in a THROWAWAY subprocess.

    Returns ``(device_kind, n_devices)`` if a TPU came up, else None.
    Round 1 lost both driver artifacts to this init hanging (rc=124) or
    raising (rc=1) in-process; a subprocess is the only safe probe.
    The tunnel also has transient outages measured in minutes (observed
    in round 4: reachable, then ~an hour of hung/UNAVAILABLE inits, then
    reachable again) — so a failed probe is retried a bounded number of
    times before the bench concedes to the CPU fallback."""
    code = (
        "import jax; d = jax.devices(); "
        "print(d[0].platform + '|' + d[0].device_kind + '|' + str(len(d)))"
    )
    for attempt in range(attempts):
        if attempt:
            _note(f"tpu probe retry {attempt + 1}/{attempts} "
                  f"in {retry_wait:.0f}s")
            time.sleep(retry_wait)
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout)
        except (subprocess.TimeoutExpired, OSError):
            continue
        if r.returncode != 0:
            continue
        try:
            platform, kind, n = r.stdout.strip().splitlines()[-1].split("|")
        except ValueError:
            continue
        if platform == "tpu":
            return kind, int(n)
    return None


def _force(out):
    """Host round-trip on ONE element of the result — the only completion
    barrier the tunnel runtime honors.

    Round-5 on-chip finding (the round-3 postmortem's fix was not enough):
    per-iteration ``block_until_ready`` STILL under-measured on the remote
    tunnel — a 0.5-TFLOP flash step "completed" in 82 µs (30x faster than
    the chip's absolute peak) and a 256-MiB-traffic allreduce step in
    55 µs (11.9x the HBM roofline).  ``block_until_ready`` evidently
    returns at remote enqueue, not completion; only programs big enough to
    hit allocator backpressure (the 1-GiB-output train step) timed
    honestly.  Data cannot lie: fetching a single element of an output
    buffer to the host requires the producing execution to have finished,
    so every timed iteration ends with a 1-element device->host fetch.
    The fetch adds one tunnel round-trip (~tens of µs) per iteration —
    visible floor, reported as ``timing_floor_s`` in the final JSON."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves = jax.tree.leaves(out)
    # The transferred scalar depends on EVERY output leaf (a runtime
    # tracking per-buffer readiness could otherwise service the fetch
    # from the ready subset — e.g. a value_and_grad loss buffer exists
    # after the forward alone) and, per leaf, on its full leading axis
    # (run_spmd outputs lead with the rank axis; a [0,...,0] element
    # could be served from device 0's shard while other devices still
    # execute).  Each leaf contributes a [:, 0, ..., 0] column sum —
    # reads at most leading-dim elements, never the buffer (jnp.ravel
    # would dispatch a full-buffer COPY, the same order of HBM traffic
    # as the steps being measured).  The whole probe is ONE cached
    # jitted executable so a timed iteration pays one dispatch + one
    # 4-byte fetch regardless of leaf count.
    global _PROBE
    if _PROBE is None:
        def probe(ls):
            tot = jnp.zeros((), jnp.float32)
            for leaf in ls:
                col = (leaf if leaf.ndim == 0
                       else leaf[(slice(None),) + (0,) * (leaf.ndim - 1)])
                tot = tot + jnp.sum(col.astype(jnp.float32))
            return tot
        _PROBE = jax.jit(probe)
    # jit's dispatch cache keys on the leaves' structure/avals itself —
    # each distinct output shape compiles once (at warmup) and the timed
    # iterations pay one cached dispatch.
    return np.asarray(_PROBE(leaves))


_PROBE = None


def _timeit(fn, *args, iters: int):
    """Median seconds/step, each iteration closed by a device->host fetch
    of one result element (see _force: ``block_until_ready`` is not a
    completion barrier on the tunnel runtime)."""
    _force(fn(*args))     # compile + warmup
    _force(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _force(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _note(msg: str) -> None:
    print(f"bench.py: {msg}", file=sys.stderr, flush=True)


def _bench_allreduce(on_tpu: bool, hbm_gbps: float):
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    n = len(jax.devices())
    # 256 MiB/chip on TPU (1B params would OOM nothing but adds no signal
    # beyond saturation); small on the CPU smoke path.
    nelem = (1 << 26) if on_tpu else (1 << 18)
    bytes_per_pass = nelem * 4

    comm = mpi.COMM_WORLD

    def loss(x):
        y = comm.Allreduce(x, mpi.MPI_SUM)
        return jnp.vdot(y, y)

    step = mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)
    x = jnp.ones((nelem,), jnp.float32)
    dt = _timeit(step, x, iters=20 if on_tpu else 3)

    if n > 1:
        wire = 2.0 * (n - 1) / n * bytes_per_pass
    else:
        wire = float(bytes_per_pass)
    gbps = 2.0 * wire / dt / 1e9       # fwd psum + adjoint psum per step
    # Single chip: the same accounting (2 x tensor bytes / step) is the
    # program's minimum HBM traffic (read x + write grad), so gbps/HBM-peak
    # is a true roofline fraction — >1.0 would mean the measurement is
    # broken, which is exactly what round 3 shipped.
    roofline = gbps / hbm_gbps if n == 1 else None
    return {
        "gbps": round(gbps, 3),
        "n_devices": n,
        "tensor_mib": bytes_per_pass / (1 << 20),
        "seconds_per_step": dt,
        "hbm_roofline_fraction": (round(roofline, 4)
                                  if roofline is not None else None),
        "suspect": bool(roofline is not None and roofline > 1.0),
    }


def _bench_allreduce_compressed(on_tpu: bool):
    """Compressed Allreduce (mpi4torch_tpu.compress) vs the fp32 exact
    path at the same shape: bytes-on-wire per codec (measured from the
    real encoded buffers — the CPU harness's ground truth) and wall-clock
    per step (chip-meaningful when ICI is in the path; on one device the
    quantize/dequantize compute rides HBM only, so wall-clock there
    mostly prices the codec arithmetic).  The ISSUE 1 acceptance bar:
    q8's wire reduction vs fp32 must be >= 3.5x."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.compress import get_codec

    n = len(jax.devices())
    nelem = (1 << 24) if on_tpu else (1 << 18)
    fp32_bytes = nelem * 4
    comm = mpi.COMM_WORLD
    iters = 20 if on_tpu else 3

    def step_fn(compression):
        def loss(x):
            y = comm.Allreduce(x, mpi.MPI_SUM, compression=compression)
            return jnp.vdot(y, y)

        return mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)

    x = jnp.ones((nelem,), jnp.float32)
    dt_fp32 = _timeit(step_fn(False), x, iters=iters)

    out = {
        "n_devices": n,
        "tensor_mib": fp32_bytes / (1 << 20),
        "fp32_seconds_per_step": dt_fp32,
        "codecs": {},
    }
    for name in ("q8", "q8_ef", "bf16"):
        def _one(name=name):
            codec = get_codec(name)
            enc_bytes = codec.wire_bytes((nelem,), jnp.float32)
            dt = _timeit(step_fn(name), x, iters=iters)
            return {
                "encoded_bytes": enc_bytes,
                "wire_reduction_vs_fp32": round(fp32_bytes / enc_bytes, 3),
                "seconds_per_step": dt,
                "step_speedup_vs_fp32": round(dt_fp32 / dt, 4),
            }

        out["codecs"][name] = _guarded(f"allreduce_compressed.{name}", _one)

    q8 = out["codecs"].get("q8", {})
    out["q8_wire_reduction_target_met"] = bool(
        q8.get("wire_reduction_vs_fp32", 0.0) >= 3.5)
    return out


# The (codec × algorithm) combos of the multipath wire table: the ISSUE 6
# composition claim is read off the q8-bidir vs fp32-bidir rows; q8-ring
# is the PR 1 reference point, q8_ef_hop-bidir prices the per-hop EF
# variant's wire, q8-torus covers the striped-channel leg (skipped with a
# recorded error on worlds with no 2-level factorization).
_MULTIPATH_WIRE_TABLE = (
    ("fp32-ring", False, "ring"),
    ("fp32-bidir", False, "bidir"),
    ("q8-ring", "q8", "ring"),
    ("q8-bidir", "q8", "bidir"),
    ("q8_ef_hop-bidir", "q8_ef_hop", "bidir"),
    ("q8-torus", "q8", "torus"),
)

def _hlo_wire_bytes_per_device(txt: str):
    """Deterministic per-device bytes-on-wire of a lowered StableHLO
    program, from the collective ops' operand types under the standard
    ring accountings: a collective_permute ships its operand once; an
    all_gather over groups of size s ships the local shard (s-1) times;
    an all_reduce 2(s-1)/s of the payload; a reduce_scatter (s-1)/s;
    an all_to_all keeps 1/s local and ships the rest.
    Returns ``(total_bytes, per-op-kind breakdown)``.

    Since the static verifier landed, the parsing and the accounting
    live in :func:`mpi4torch_tpu.analyze.wire_bytes_per_device` (one
    pass over the shared StableHLO parse); this wrapper keeps the
    historical bench entry point, with the recorded wire tables
    (q8-bidir 7280 B, the (8,)->(2,4) reshard migration 98304 B, the
    serve decode step) regression-pinned bit-identical in
    tests/test_analyze.py."""
    from mpi4torch_tpu.analyze import wire_bytes_per_device

    return wire_bytes_per_device(txt)


def _multipath_wire_census(nelem: int = 1 << 12):
    """Lower every `_MULTIPATH_WIRE_TABLE` combo on the attached
    (multi-)device mesh and read the per-device wire bytes off the
    StableHLO — the deterministic half of the multipath stanza, valid on
    any platform (op counts and operand widths don't depend on where the
    program would run).  Also checks the tentpole census criterion:
    int8 collective_permutes on BOTH rotations of the q8-bidir dual
    ring."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu._compat import shard_map

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError("multipath wire census needs >= 2 devices")
    mesh = Mesh(np.asarray(devs), ("w",))
    c = mpi.comm_from_mesh(mesh, "w")
    x = jnp.ones((nelem,), jnp.float32)

    out = {"n_devices": n, "nelem": nelem,
           "fp32_payload_bytes": nelem * 4, "table": {}}
    texts = {}
    for label, codec, algo in _MULTIPATH_WIRE_TABLE:
        def _one(label=label, codec=codec, algo=algo):
            fn = shard_map(
                lambda a: c.Allreduce(a, mpi.MPI_SUM, compression=codec,
                                      algorithm=algo),
                mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
            txt = jax.jit(fn).lower(x).as_text()
            texts[label] = txt
            wire, counts = _hlo_wire_bytes_per_device(txt)
            return {"wire_bytes_per_device": wire, "collectives": counts}

        out["table"][label] = _guarded(f"multipath_census.{label}", _one)

    def wire(label):
        ent = out["table"].get(label) or {}
        return ent.get("wire_bytes_per_device")

    q8b, fpb, q8r = wire("q8-bidir"), wire("fp32-bidir"), wire("q8-ring")
    if q8b and fpb:
        out["wire_advantage_q8_bidir_vs_fp32_bidir"] = round(fpb / q8b, 3)
        out["wire_advantage_target_met"] = bool(fpb / q8b >= 3.5)
    if q8b and q8r:
        # bidir moves the same bytes as ring over 2x the links; the
        # composition win is utilization, not fewer bytes — the table
        # records that the codec leg costs no extra wire on the dual ring.
        out["q8_bidir_vs_q8_ring_wire_ratio"] = round(q8b / q8r, 3)

    if "q8-bidir" in texts:
        from mpi4torch_tpu.compress import int8_rotation_census

        perms, fwd, bwd = int8_rotation_census(texts["q8-bidir"], n)
        out["int8_permutes_on_both_rotations"] = bool(
            fwd in perms and bwd in perms)
    return out


def _multipath_wire_census_subprocess():
    """Run :func:`_multipath_wire_census` on a forced 8-virtual-device
    CPU mesh in a subprocess — the wire table for a bench world with a
    single device (where bidir/torus lower to the identity and there is
    nothing to count)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("import json, bench; "
            "print(json.dumps(bench._multipath_wire_census()))")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"multipath census subprocess failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-300:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_allreduce_compressed_multipath(on_tpu: bool):
    """Compressed allreduce ON the bandwidth tier (ISSUE 6): the
    wire-bytes × algorithm table (q8-on-ring vs q8-on-bidir vs
    fp32-on-bidir, plus the per-hop-EF and torus legs) with wall-clock
    numbers per combo alongside.

    The headline is DETERMINISTIC: per-device wire bytes are read off
    each combo's lowered StableHLO (collective operand widths × the
    standard ring accountings), so the ≥3.5x q8-bidir-vs-fp32-bidir
    verdict and the both-rotations int8 census hold identically on the
    CPU smoke sweep and on hardware.  Wall-clock seconds are
    chip-meaningful only with ICI in the path; a 1-device world runs
    the census on a forced 8-virtual-device subprocess mesh so the
    verdict is recorded either way."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    n = len(jax.devices())
    nelem = (1 << 24) if on_tpu else (1 << 18)
    comm = mpi.COMM_WORLD
    iters = 20 if on_tpu else 3

    def step_fn(compression, algorithm):
        def loss(x):
            y = comm.Allreduce(x, mpi.MPI_SUM, compression=compression,
                               algorithm=algorithm)
            return jnp.vdot(y, y)

        return mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)

    x = jnp.ones((nelem,), jnp.float32)
    out = {
        "n_devices": n,
        "tensor_mib": nelem * 4 / (1 << 20),
        "combos": {},
    }
    for label, codec, algo in _MULTIPATH_WIRE_TABLE:
        def _one(codec=codec, algo=algo):
            return {"seconds_per_step": _timeit(step_fn(codec, algo), x,
                                                iters=iters)}

        out["combos"][label] = _guarded(f"allreduce_multipath.{label}", _one)
    base = out["combos"].get("fp32-ring", {})
    if "seconds_per_step" in base:
        for label, ent in out["combos"].items():
            if label != "fp32-ring" and "seconds_per_step" in ent:
                ent["step_speedup_vs_fp32_ring"] = round(
                    base["seconds_per_step"] / ent["seconds_per_step"], 4)

    census = _guarded(
        "allreduce_multipath.census",
        _multipath_wire_census if n > 1 else _multipath_wire_census_subprocess)
    if "error" not in census:
        out["census_n_devices"] = census.get("n_devices")
        out["wire_table"] = census.get("table")
        for key in ("wire_advantage_q8_bidir_vs_fp32_bidir",
                    "wire_advantage_target_met",
                    "q8_bidir_vs_q8_ring_wire_ratio",
                    "int8_permutes_on_both_rotations"):
            if key in census:
                out[key] = census[key]
        out["note"] = (
            "wire bytes are deterministic (read off the lowered StableHLO"
            " per combo); wall-clock is chip-meaningful only with ICI in "
            "the path" + ("" if n > 1 else
                          " — census ran on a forced 8-virtual-device "
                          "subprocess mesh"))
    else:
        out["census_error"] = census["error"]
    return out


def _bench_guard_overhead(on_tpu: bool):
    """Integrity-guard overhead census (mpi4torch_tpu.resilience,
    ISSUE 7): a DETERMINISTIC HLO proof that the guards are free when
    off and a priced, censused addition when on.

    * ``comm_finite_guard="off"`` (default) and checksum-off lowerings
      are BIT-IDENTICAL to the pre-guard program — checked structurally
      by re-lowering the same facade call with the guard hook
      monkeypatched out entirely (the guard-less build) and comparing
      the full StableHLO text, not just op counts;
    * guard-on ("warn") records the per-collective op deltas: one
      ``is_finite`` + reduce feeding one host callback ``custom_call``;
    * ``comm_wire_checksum`` is a Mode B (rendezvous wire) leg only —
      toggling it must leave the Mode A lowering untouched, and that
      claim is censused here too."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu._compat import shard_map
    from mpi4torch_tpu.resilience import guards as _rguards

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    x = jnp.ones((1 << 14,), jnp.float32)

    def lowered(compression=False):
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM,
                                   compression=compression),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(x).as_text()

    def counts(text):
        return {"is_finite": text.count("stablehlo.is_finite"),
                "custom_call": text.count("stablehlo.custom_call")}

    out = {"n_devices": n, "modes": {}}
    # Guard off (the default): must match the guard-LESS build bit for
    # bit.  The bypass monkeypatch removes the hook structurally, so the
    # comparison is against a program in which the guard code never ran.
    mpi.config.set_comm_finite_guard("off")
    mpi.config.set_comm_wire_checksum(False)
    text_off = lowered()
    text_off_q8 = lowered("q8")
    hook = _rguards.spmd_finite_value
    try:
        _rguards.spmd_finite_value = lambda v, where: v
        text_bypassed = lowered()
        text_bypassed_q8 = lowered("q8")
    finally:
        _rguards.spmd_finite_value = hook
    out["guard_off_identical_to_guardless_build"] = (
        text_off == text_bypassed and text_off_q8 == text_bypassed_q8)
    out["modes"]["off"] = counts(text_off)

    # Checksum on: a Mode B wire leg — the Mode A lowering must not move.
    mpi.config.set_comm_wire_checksum(True)
    try:
        out["checksum_on_lowering_identical"] = lowered() == text_off
    finally:
        mpi.config.set_comm_wire_checksum(False)

    # Guard on: the priced deltas.
    mpi.config.set_comm_finite_guard("warn")
    try:
        text_on = lowered()
        text_on_q8 = lowered("q8")
    finally:
        mpi.config.set_comm_finite_guard("off")
    out["modes"]["warn"] = counts(text_on)
    out["guard_on_op_delta"] = {
        k: counts(text_on)[k] - counts(text_off)[k]
        for k in ("is_finite", "custom_call")}
    out["guard_on_op_delta_q8"] = {
        k: counts(text_on_q8)[k] - counts(text_off_q8)[k]
        for k in ("is_finite", "custom_call")}
    out["zero_overhead_off_path"] = bool(
        out["guard_off_identical_to_guardless_build"]
        and out["checksum_on_lowering_identical"]
        and out["modes"]["off"]["is_finite"] == 0)
    out["note"] = ("deterministic lowering census — identical on CPU "
                   "smoke and hardware; wall-clock guard cost is the "
                   "is_finite reduce + host callback and only exists "
                   "when the guard is on")
    return out


def _bench_obs_overhead(on_tpu: bool):
    """Observability-layer overhead census (mpi4torch_tpu.obs,
    ISSUE 12): the guard-overhead discipline applied to tracing.

    * obs OFF (no tracer — the default) lowers BIT-IDENTICAL to an
      obs-less build (the Mode A step-event hook monkeypatched out
      structurally), plain and q8;
    * a Mode B-only tracer must not move the Mode A lowering either
      (it keys into nothing trace-time);
    * a ``mode_a`` tracer records the priced delta: one host-callback
      ``custom_call`` per collective entry;
    * Mode B determinism: the same traced workload run twice yields
      the SAME per-rank logical event census (counts and wire bytes,
      identical across ranks and runs) — what makes reconcile() a
      contract rather than a sampled profile."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs
    from mpi4torch_tpu._compat import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    x = jnp.ones((1 << 14,), jnp.float32)

    def lowered(compression=False):
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM,
                                   compression=compression),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(x).as_text()

    out = {"n_devices": n}
    text_off = lowered()
    text_off_q8 = lowered("q8")
    hook = obs.tracing.spmd_collective_event
    try:
        obs.tracing.spmd_collective_event = lambda v, where: v
        out["obs_off_identical_to_obsless_build"] = (
            lowered() == text_off and lowered("q8") == text_off_q8)
    finally:
        obs.tracing.spmd_collective_event = hook
    with obs.trace():
        out["modeb_tracer_lowering_identical"] = lowered() == text_off
    with obs.trace(mode_a=True):
        out["mode_a_custom_call_delta"] = (
            lowered().count("stablehlo.custom_call")
            - text_off.count("stablehlo.custom_call"))

    # Mode B census determinism: two traced runs of one workload.
    from mpi4torch_tpu import COMM_WORLD as comm

    def body(rank):
        v = jnp.arange(512, dtype=jnp.float32) * (rank + 1)
        return comm.Allreduce(v, mpi.MPI_SUM, algorithm="ring")

    tables = []
    for _ in range(2):
        with obs.trace() as t:
            mpi.run_ranks(body, min(n, 4) if n > 1 else 2)
        mt = obs.measured_wire_table(t.events)
        tables.append({"wire_bytes": mt["wire_bytes"],
                       "counts": mt["counts"],
                       "logical_events": mt["logical_events"],
                       "per_rank_consistent":
                           mt["per_rank_consistent"]})
    out["modeb_census"] = tables[0]
    out["modeb_census_deterministic"] = bool(
        tables[0] == tables[1] and tables[0]["per_rank_consistent"])
    out["zero_overhead_off_path"] = bool(
        out["obs_off_identical_to_obsless_build"]
        and out["modeb_tracer_lowering_identical"])
    out["note"] = ("deterministic lowering + event census — identical "
                   "on CPU smoke and hardware; tracing cost exists "
                   "only while a tracer is installed (one attribute "
                   "read per chokepoint otherwise)")
    return out


def _bench_degraded_mode(on_tpu: bool):
    """Gray-failure degraded-mode census (mpi4torch_tpu.resilience,
    ISSUE 15) — deterministic, like every resilience verdict:

    * **per-rank wire census**: the schedule-failover policy re-ranks
      candidates by bytes through the SLOW rank
      (``resilience.rank_wire_bytes``); the verdict pins that the
      failover winner strictly reduces bytes through the slow rank vs
      the ring default (tree rooted away from it: ``2B`` vs
      ``4B(N-1)/N``), and that the model is self-consistent (every
      candidate moves the same TOTAL wire — same traffic, different
      concentration);
    * **zero-overhead off path**: with the gray-failure detector
      constructed (and a Mode B-only tracer installed), the Mode A
      lowering is BIT-IDENTICAL to the detector-less build — the
      detector only reads events the chokepoints already record, so
      "detector off" and "detector on" cannot diverge in compiled
      code."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs
    from mpi4torch_tpu._compat import shard_map
    from mpi4torch_tpu.resilience import (GrayFailureDetector,
                                          failover_schedule,
                                          rank_wire_bytes)

    n_dev = len(jax.devices())
    n = n_dev if n_dev > 1 else 8   # census is pure arithmetic
    nbytes = 64 * 1024
    slow = 3 % n
    winner, table = failover_schedule(slow, n, nbytes)
    totals = {a: sum(t) for a, t in table.items()}
    out = {
        "n_ranks": n,
        "nbytes": nbytes,
        "slow_rank": slow,
        "failover_winner": winner,
        "slow_rank_bytes": {a: t[slow] for a, t in table.items()},
        "per_rank_bytes": {a: list(t) for a, t in table.items()},
        "census_total_consistent": len(set(totals.values())) == 1,
        "failover_reduces_slow_rank_bytes": bool(
            table[winner][slow] < table["ring"][slow]),
        "slow_rank_byte_reduction": round(
            table["ring"][slow] / max(table[winner][slow], 1), 3),
    }
    # Sanity vs the hand formula: ring per-rank = 4(N-1)B/N.
    out["ring_matches_formula"] = (
        table["ring"][slow] == int(round(4 * (n - 1) * nbytes / n)))
    assert rank_wire_bytes("ring", n, nbytes)[0] == table["ring"][0]

    # Off-path census: detector + Mode B tracer move NOTHING trace-time.
    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    x = jnp.ones((1 << 13,), jnp.float32)

    def lowered():
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(x).as_text()

    text_off = lowered()
    with obs.trace() as tracer:
        det = GrayFailureDetector(tracer)
        text_on = lowered()
        det.check()   # reads events only; no trace-time effect
    out["detector_off_path_bit_identical"] = text_on == text_off
    out["note"] = ("deterministic per-rank wire census + off-path "
                   "lowering equality — identical on CPU smoke and "
                   "hardware; wall-clock degrade latency is one "
                   "consensus round (see elastic bench)")
    return out


def _reshard_census(nrows: int = 1024, ncols: int = 256):
    """Deterministic reshard stanza core (ISSUE 9): lower the
    (8,)->(2,4) checkpoint-migration transition — rows over the flat
    world to rows x cols over the 2x4 mesh — planned vs the
    gather-everything baseline, and read BOTH estimators off each
    StableHLO: per-device wire bytes (the ring accountings of
    ``_hlo_wire_bytes_per_device``) and peak live bytes (the
    ``reshard.peak_live_bytes`` liveness census).  The verdict
    ``peak_memory_bounded`` is the strict inequality between the two
    programs under the one shared estimator."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import reshard as rs
    from mpi4torch_tpu._compat import shard_map

    devs = jax.devices()
    n = len(devs)
    if n < 2:
        raise RuntimeError("reshard census needs >= 2 devices")
    a = next((a for a in range(2, n) if n % a == 0 and n // a > 1), None)
    if a is None:
        raise RuntimeError(f"{n} ranks have no 2D factorization")
    fl = rs.layout((n,), 0, None)
    tl = rs.layout((a, n // a), 0, 1)
    G = (nrows, ncols)
    mesh = Mesh(np.asarray(devs), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    x = jnp.zeros(fl.shard_shape(G), jnp.float32)

    def lowered(strategy):
        fn = shard_map(
            lambda v: cm.Reshard(v, fl, tl, strategy=strategy),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
        return jax.jit(fn).lower(x).as_text()

    plan = rs.plan_reshard(fl, tl, G, np.float32)
    out = {"n_devices": n, "transition": plan.transition,
           "strategy": plan.strategy,
           "shard_bytes": int(np.prod(fl.shard_shape(G))) * 4,
           "table": {}}
    for label, strategy in (("planned", None), ("gather", "gather")):
        txt = lowered(strategy)
        wire, counts = _hlo_wire_bytes_per_device(txt)
        out["table"][label] = {
            "wire_bytes_per_device": wire,
            "peak_live_bytes": rs.peak_live_bytes(txt),
            "collectives": counts,
        }
    p, g = out["table"]["planned"], out["table"]["gather"]
    out["peak_memory_bounded"] = bool(
        p["peak_live_bytes"] < g["peak_live_bytes"])
    if p["wire_bytes_per_device"]:
        out["wire_advantage_vs_gather"] = round(
            g["wire_bytes_per_device"] / p["wire_bytes_per_device"], 3)
    return out


def _reshard_census_subprocess():
    """The reshard census on a forced 8-virtual-device CPU mesh (for
    1-device bench worlds, where every transition lowers to slices and
    there is nothing to compare)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("import json, bench; "
            "print(json.dumps(bench._reshard_census()))")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"reshard census subprocess failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-300:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_reshard(on_tpu: bool):
    """Resharding stanza (ISSUE 9): the deterministic planned-vs-gather
    census for the (8,)->(2,4) migration (wire bytes + peak live bytes
    + the ``peak_memory_bounded: true`` verdict) with wall-clock per
    strategy alongside where a multi-device world exists."""
    import jax

    n = len(jax.devices())
    if n >= 2:
        res = _reshard_census()
    else:
        res = _reshard_census_subprocess()
        res["note"] = ("1-device world: census from a forced "
                       "8-virtual-device subprocess mesh")
        return res

    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import reshard as rs

    a = next(a for a in range(2, n) if n % a == 0 and n // a > 1)
    fl = rs.layout((n,), 0, None)
    tl = rs.layout((a, n // a), 0, 1)
    G = (1024, 256)
    x0 = jnp.ones(fl.shard_shape(G), jnp.float32)
    for label, strategy in (("planned", None), ("gather", "gather")):
        def step(v, strategy=strategy):
            return mpi.COMM_WORLD.Reshard(v, fl, tl, strategy=strategy)

        fn = mpi.run_spmd(lambda: step(x0), nranks=n)
        _force(fn())          # compile + warm
        res["table"][label]["seconds_per_step"] = _timeit(fn, iters=10)
    return res


def _bench_elastic(on_tpu: bool):
    """Elastic world-resize stanza (ISSUE 13): the deterministic
    wire-bytes census of the shrink replan vs the full-restart restore,
    plus wall-clock of a live (8,)->(6,) drain on the thread world.

    The comparison is the planner's own per-device accounting
    (reshard.plan_resize: the same ``_estimates`` currency every
    reshard number uses): ``planned`` is the auto-selected live-drain
    program (chunk-permute rounds — O(moved chunks) wire), ``restart``
    is the ``gather`` strategy (every rank re-materializes the full
    state then slices — exactly what a naive full-job restart's
    restore does on the wire).  The verdict
    ``replan_cheaper_than_restart`` is deterministic; wall-clock rides
    alongside (Mode B rendezvous — scheduler noise on CPU, the census
    is the headline)."""
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import reshard as rs
    from mpi4torch_tpu.elastic import (ElasticRuntime, replan_axis0)

    W, M = 8, 6
    # A representative re-layed state set: a TP head bank + two ZeRO
    # flat leaves (the elastic matrix's shapes, scaled up).
    states = {
        "tp_bank": (48, (256,)),       # 48 heads x 256 f32
        "zero_w": (12 * 4096, ()),     # flat padded elements
        "zero_b": (4096, ()),
    }
    embed_from = tuple(range(W))
    embed_to = tuple(range(M))
    table = {}
    planned_wire = restart_wire = 0
    planned_peak = restart_peak = 0
    for name, (n, row) in states.items():
        p = rs.plan_resize(n, row, W, M, np.float32,
                           embed_from=embed_from, embed_to=embed_to,
                           exec_size=W)
        g = rs.plan_resize(n, row, W, M, np.float32,
                           embed_from=embed_from, embed_to=embed_to,
                           exec_size=W, strategy="gather")
        table[name] = {
            "planned_strategy": p.strategy,
            "planned_wire_bytes": p.wire_bytes,
            "planned_peak_bytes": p.peak_bytes,
            "restart_wire_bytes": g.wire_bytes,
            "restart_peak_bytes": g.peak_bytes,
        }
        planned_wire += p.wire_bytes
        restart_wire += g.wire_bytes
        planned_peak = max(planned_peak, p.peak_bytes)
        restart_peak = max(restart_peak, g.peak_bytes)

    # Wall-clock: one live drain of the TP bank on the thread world,
    # planned vs gather (same data, same embeds, same world).
    n, row = states["tp_bank"]
    per = n // W
    bank = np.arange(n * row[0], dtype=np.float32).reshape((n,) + row)
    wall = {}
    for label, strategy in (("planned", None), ("restart", "gather")):
        rt = ElasticRuntime(W, probe_timeout=0.5, world_timeout=30.0)
        view0 = rt.view

        def drain_body(pos, rid, old_view, new_view, strategy=strategy):
            x = jnp.asarray(bank[pos * per:(pos + 1) * per])
            return np.asarray(replan_axis0(
                mpi.COMM_WORLD, x, n, old_view, new_view,
                mode="drain", strategy=strategy))

        t0 = _time.perf_counter()
        outs = rt.drain(drain_body, leaving=[6, 7])
        wall[label] = _time.perf_counter() - t0
        per_m = n // M
        for j, rid in enumerate(rt.view.alive):
            assert np.array_equal(outs[view0.position(rid)],
                                  bank[j * per_m:(j + 1) * per_m]), \
                f"{label} drain diverged"

    return {
        "worlds": f"({W},)->({M},)",
        "table": table,
        "planned_wire_bytes_total": planned_wire,
        "restart_wire_bytes_total": restart_wire,
        "wire_advantage": round(restart_wire / max(planned_wire, 1), 3),
        "planned_peak_bytes_max": planned_peak,
        "restart_peak_bytes_max": restart_peak,
        "replan_cheaper_than_restart": bool(
            planned_wire < restart_wire
            and planned_peak < restart_peak),
        "drain_seconds": wall,
        "note": ("census = reshard plan accounting (deterministic); "
                 "wall-clock is Mode B rendezvous incl. the consensus "
                 "round"),
    }


def _bench_allreduce_fused(on_tpu: bool):
    """Fused bucketed vs per-leaf Allreduce on a real DP ResNet gradient
    tree (mpi4torch_tpu.fuse, ISSUE 2): collective-launch counts read off
    the lowered StableHLO (ground truth on any platform), bytes-on-wire,
    and wall-clock per step — each with and without the q8 codec.  The
    acceptance bar: >= 5x fewer launches fused, wall-time no worse."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu._compat import shard_map
    from mpi4torch_tpu.compress import get_codec
    from mpi4torch_tpu.fuse import bucket_layout
    from mpi4torch_tpu.models import resnet as R

    n = len(jax.devices())
    # ResNet-18-ish widths on TPU; a narrow stack on the CPU smoke path.
    if on_tpu:
        cfg = R.ResNetConfig()
        iters = 20
    else:
        cfg = R.ResNetConfig(widths=(8, 16, 32, 64),
                             stage_sizes=(2, 2, 2, 2), num_classes=10)
        iters = 3
    params, _state = R.init_resnet(jax.random.PRNGKey(0), cfg)
    grads = jax.tree.map(jnp.asarray, params)   # stand-in gradient tree
    leaves = jax.tree.leaves(grads)
    total_bytes = sum(x.size * x.dtype.itemsize for x in leaves)

    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    comm = mpi.comm_from_mesh(mesh, "w")

    COLL = ("all_reduce", "all_gather", "reduce_scatter",
            "collective_permute", "all_to_all")

    def launches(fn):
        wrapped = shard_map(fn, mesh=mesh, in_specs=P(), out_specs=P(),
                            check_vma=False)
        txt = jax.jit(wrapped).lower(grads).as_text()
        return sum(txt.count(f"stablehlo.{c}") for c in COLL)

    def perleaf(compression):
        def f(t):
            return jax.tree.map(
                lambda g: comm.Allreduce(g, mpi.MPI_SUM,
                                         compression=compression)
                / comm.size, t)
        return f

    def fused(compression):
        def f(t):
            return comm.Allreduce_tree(t, mpi.MPI_SUM, mean=True,
                                       compression=compression)
        return f

    def timed(fn):
        step = mpi.run_spmd(fn, mesh=mesh, axis_name="w")
        return _timeit(step, grads, iters=iters)

    layout = bucket_layout(grads, mpi.config.default_bucket_bytes())
    out = {
        "n_devices": n,
        "n_leaves": len(leaves),
        "n_buckets": layout.num_buckets,
        "grad_tree_mib": round(total_bytes / (1 << 20), 3),
        "bucket_bytes": mpi.config.default_bucket_bytes(),
        "variants": {},
    }

    codec = get_codec("q8")
    q8_leaf_bytes = sum(codec.wire_bytes(x.shape, x.dtype) for x in leaves)
    q8_bucket_bytes = sum(
        codec.wire_bytes((sz,), dt)
        for sz, dt in zip(layout.bucket_sizes, layout.bucket_dtypes))
    for name, compression, wire in (
            ("perleaf_fp32", False, total_bytes),
            ("fused_fp32", False, total_bytes),
            ("perleaf_q8", "q8", q8_leaf_bytes),
            ("fused_q8", "q8", q8_bucket_bytes)):
        build = fused if name.startswith("fused") else perleaf

        def _one(build=build, compression=compression, wire=wire):
            return {
                "launches": launches(build(compression)),
                "wire_bytes": int(wire),
                "seconds_per_step": timed(build(compression)),
            }

        out["variants"][name] = _guarded(f"allreduce_fused.{name}", _one)

    pl, fu = out["variants"].get("perleaf_fp32", {}), \
        out["variants"].get("fused_fp32", {})
    if "launches" in pl and "launches" in fu:
        out["launch_reduction"] = round(
            pl["launches"] / max(fu["launches"], 1), 2)
        out["step_speedup_vs_perleaf"] = round(
            pl["seconds_per_step"] / fu["seconds_per_step"], 4)
        out["launch_reduction_target_met"] = bool(
            out["launch_reduction"] >= 5.0)
        # One device: a 1-rank psum compiles to identity, so the
        # per-leaf "collectives" are free while the fused path still
        # pays its concat/slice HBM traffic — the wall-time verdict only
        # means something where a wire exists, so (like the allreduce
        # stanza's roofline handling) it is None rather than a spurious
        # false on the single-chip harness.
        out["walltime_no_worse"] = (
            bool(fu["seconds_per_step"] <= pl["seconds_per_step"] * 1.05)
            if n > 1 else None)
        if n == 1:
            out["note"] = ("single device: no wire; launch counts are "
                           "ground truth, wall-time comparison is not")
    return out


def _overlap_zero_setup(on_tpu: bool):
    """Model, optimizer and stand-in gradient tree shared by the
    overlap_zero wall-clock measurement and its schedule census
    (including the forced-multi-device censusing subprocess a 1-device
    run spawns — both sides must build the SAME step programs)."""
    import jax

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.models import transformer as T

    if on_tpu:
        cfg = T.TransformerConfig(vocab=8192, d_model=512, n_heads=8,
                                  n_layers=8, d_ff=2048, max_seq=256)
        iters = 10
        bucket_bytes = mpi.config.default_bucket_bytes()
    else:
        cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                  n_layers=2, d_ff=128, max_seq=32)
        iters = 5
        # Small buckets so the smoke tree still splits into a real
        # multi-bucket window (the default 4 MiB would make it one
        # bucket — nothing for the scheduler to keep in flight).
        bucket_bytes = 1 << 15
    params = T.init_transformer(jax.random.PRNGKey(0), cfg)
    # Stand-in UN-reduced local gradient tree (the fused bench's trick):
    # the wire and optimizer cost are shape-determined, not
    # value-determined.
    grads = jax.tree.map(lambda p: p * 1e-3, params)

    class _Sgd:
        def init(self, p):
            return None

        def update(self, g, s, p):
            return jax.tree.map(lambda x: -0.1 * x, g), None

    return params, grads, _Sgd(), bucket_bytes, iters


def _overlap_zero_step_fn(comm, opt, params, bucket_bytes, overlap):
    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.parallel import zero as Z

    def f(g):
        with mpi.config.fusion_scope(bucket_bytes):
            st = Z.zero_init(comm, opt, params)
            new_p, _ = Z.zero_step(comm, opt, params, g, st,
                                   overlap=overlap)
        return new_p
    return f


def _overlap_zero_census(on_tpu: bool = False):
    """Schedule census of the ZeRO step's two forms (mpi4torch_tpu.
    overlap.scheduled_exposure): the fraction of bucket collectives the
    lowered program leaves with NOTHING else in flight to hide them.
    Deterministic on every platform — blocking steps census to 1.0 by
    construction, the windowed split-phase step strictly lower."""
    import jax

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.overlap import scheduled_exposure

    params, grads, opt, bucket_bytes, _ = _overlap_zero_setup(on_tpu)
    comm = mpi.COMM_WORLD
    out = {"n_devices": len(jax.devices())}
    for name, ov in (("blocking", False), ("overlap", True)):
        f = _overlap_zero_step_fn(comm, opt, params, bucket_bytes, ov)
        out[name] = scheduled_exposure(
            jax.jit(mpi.run_spmd(f)).lower(grads))
    return out


def _overlap_zero_census_subprocess():
    """Run :func:`_overlap_zero_census` on a forced 8-virtual-device CPU
    mesh in a subprocess — the multi-device smoke sweep for a bench run
    whose own world has a single device (collectives lower away there,
    so the in-process census would have nothing to count)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("import json, bench; "
            "print(json.dumps(bench._overlap_zero_census(False)))")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"census subprocess failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-300:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_overlap_zero(on_tpu: bool):
    """ZeRO step on a models/ transformer grad tree, blocking vs
    split-phase overlap (mpi4torch_tpu.overlap, ISSUE 5): persists the
    *exposed-comm fraction* for both schedules, plus the overlap
    speedup.  Two estimators of the same quantity:

    * ``exposed_comm_fraction_measured`` — wall-clock,
      ``(t_full - t_compute_only) / t_full``: the share of the step the
      wire is NOT hidden behind compute.  The real number on multi-chip
      hardware with an async collective runtime; on the CPU smoke mesh
      the in-process rendezvous is synchronous and the comparison is
      scheduler noise (measured here and kept, but informational).
    * ``exposed_comm_fraction_scheduled`` — the deterministic schedule
      census (:func:`mpi4torch_tpu.overlap.scheduled_exposure`): the
      fraction of bucket collectives whose start→wait window the
      lowered program leaves EMPTY (nothing in flight to hide them).
      Blocking steps census to 1.0 by construction; the windowed
      split-phase step strictly lower.

    The headline per-variant ``exposed_comm_fraction`` (and the
    ``overlap_fraction_lower`` verdict) is the measured one on TPU and
    the scheduled one on the CPU smoke sweep — best available estimator
    per platform.  A 1-device bench world runs the census on a forced
    8-virtual-device subprocess mesh so the multi-device verdict is
    recorded either way."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.fuse import bucket_layout
    from mpi4torch_tpu.parallel import zero as Z

    n = len(jax.devices())
    params, grads, opt, bucket_bytes, iters = _overlap_zero_setup(on_tpu)
    leaves = jax.tree.leaves(grads)
    total_bytes = sum(x.size * x.dtype.itemsize for x in leaves)

    comm = mpi.COMM_WORLD

    def full_step(overlap):
        return _overlap_zero_step_fn(comm, opt, params, bucket_bytes,
                                     overlap)

    def compute_only(g):
        # The step's compute with the wire legs cut: shard locally
        # (pure slicing), update — no reduce-scatter, no all-gather.
        st = Z.zero_init(comm, opt, params)
        g_shards = Z.zero3_shard_params(comm, g)
        p_shards = Z.zero3_shard_params(comm, params)
        updates, _ = opt.update(g_shards, st, p_shards)
        return jax.tree.map(jnp.add, p_shards, updates)

    def timed(fn):
        step = mpi.run_spmd(fn)
        return _timeit(step, grads, iters=iters)

    layout = bucket_layout(grads, bucket_bytes)
    out = {
        "n_devices": n,
        "n_leaves": len(leaves),
        "n_buckets": layout.num_buckets,
        "grad_tree_mib": round(total_bytes / (1 << 20), 3),
        "bucket_bytes": bucket_bytes,
    }
    t_compute = _guarded("overlap_zero.compute_only", timed, compute_only)
    variants = {}
    for name, ov in (("blocking", False), ("overlap", True)):
        def _one(ov=ov):
            t_full = timed(full_step(ov))
            exposed = max(0.0, t_full - t_compute) / t_full \
                if isinstance(t_compute, float) and t_full > 0 else None
            return {"seconds_per_step": t_full,
                    "exposed_comm_fraction_measured": (
                        round(exposed, 4) if exposed is not None
                        else None)}
        variants[name] = _guarded(f"overlap_zero.{name}", _one)

    # The deterministic half: census the two step programs' schedules.
    # A 1-device world's collectives lower away, so the census runs on a
    # forced 8-virtual-device subprocess mesh there (the multi-device
    # smoke sweep); otherwise in-process on the measuring world.
    census = _guarded(
        "overlap_zero.census",
        _overlap_zero_census if n > 1 else _overlap_zero_census_subprocess,
        *((on_tpu,) if n > 1 else ()))
    if "error" not in census:
        out["census_n_devices"] = census.get("n_devices")
        for name in ("blocking", "overlap"):
            cv = census.get(name) or {}
            if isinstance(variants.get(name), dict):
                variants[name]["exposed_comm_fraction_scheduled"] = \
                    cv.get("exposed_fraction")
                variants[name]["census_buckets"] = cv.get("n_buckets")
    else:
        out["census_error"] = census["error"]
    # Headline fraction: the best available estimator per platform —
    # wall-clock where the collective runtime is genuinely async (TPU),
    # the schedule census on the CPU smoke path (the synchronous
    # in-process rendezvous makes wall-clock deltas scheduler noise).
    headline_key = ("exposed_comm_fraction_measured" if on_tpu
                    else "exposed_comm_fraction_scheduled")
    for name in ("blocking", "overlap"):
        if isinstance(variants.get(name), dict):
            variants[name]["exposed_comm_fraction"] = \
                variants[name].get(headline_key)
    out["compute_only_seconds"] = t_compute
    out["variants"] = variants
    blk, ovl = variants.get("blocking", {}), variants.get("overlap", {})
    ef_b = blk.get("exposed_comm_fraction")
    ef_o = ovl.get("exposed_comm_fraction")
    if ef_b is not None and ef_o is not None:
        out["overlap_fraction_lower"] = bool(ef_o < ef_b)
        if not on_tpu:
            out["note"] = (
                "cpu smoke: exposed-comm fractions are the scheduled "
                "census (deterministic; blocking = 1.0 by construction)"
                " — the synchronous in-process collective runtime makes "
                "the wall-clock _measured fractions scheduler noise; on "
                "multi-chip hardware the measured fractions are the "
                "headline")
    elif "error" not in census:
        out["overlap_fraction_lower"] = None
    if "seconds_per_step" in blk and "seconds_per_step" in ovl:
        out["overlap_speedup"] = round(
            blk["seconds_per_step"] / ovl["seconds_per_step"], 4)
        if n == 1:
            # One device: a 1-rank psum_scatter/all_gather pair is local
            # data movement — there is no wire to hide, so the wall-clock
            # numbers are slicing/copy overhead (the scheduled census
            # above ran on the forced multi-device subprocess mesh and
            # still carries the real verdict).
            out["wall_clock_note"] = (
                "single device: no wire; measured fractions are "
                "slicing/copy overhead, not communication")
    return out


def _serve_setup():
    """Smoke serving config shared by the measuring engine and the
    census: small enough to step quickly on CPU, big enough that the
    decode collectives are real."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4torch_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=8,
                              n_layers=4, d_ff=128, max_seq=64)
    params = T.init_transformer(jax.random.PRNGKey(0), cfg,
                                dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab, size=int(n))
               for n in (5, 9, 3, 7, 4, 6)]
    return cfg, params, prompts, 8   # max_new per request


def _serve_census(on_tpu: bool):
    """Deterministic serve verdicts off the LOWERED decode step: the
    scheduled-exposure fractions of the overlap vs blocking schedules,
    the per-device wire bytes per step (→ per-token wire bytes at full
    occupancy), and the latency-tier selection under a measured (or
    stand-in) crossover."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import serve
    from mpi4torch_tpu._compat import lowered_text

    n = len(jax.devices())
    cfg, params, prompts, max_new = _serve_setup()
    slots = 4
    out = {"n_devices": n}

    prev = mpi.config.latency_crossover_bytes()
    assumed = prev is None
    if assumed:
        # No measured crossover on this host: a stand-in lets the
        # selection verdict stay deterministic; flagged below.
        mpi.config.set_latency_crossover_bytes(1 << 14)
    try:
        for name, ov in (("overlap", True), ("blocking", False)):
            eng = serve.Engine(cfg, params,
                               serve.ServeConfig(slots=slots,
                                                 overlap=ov),
                               spmd=True, nranks=n)
            eng.submit(prompts[0], max_new=3)
            eng.step()
            txt = lowered_text(eng.lower_step(), debug_info=True)
            census = mpi.overlap.scheduled_exposure(txt)
            wire, counts = _hlo_wire_bytes_per_device(txt)
            out[name] = {
                "exposed_fraction": census["exposed_fraction"],
                "n_buckets": census["n_buckets"],
                "wire_bytes_per_step": wire,
                "wire_bytes_per_token": round(wire / slots, 1),
                "wire_op_counts": counts,
            }
        rep = serve.latency_report(cfg, serve.ServeConfig(slots=slots),
                                   n, jnp.float32)
        rep["crossover_assumed"] = assumed
        out["latency_tier"] = rep
    finally:
        mpi.config.set_latency_crossover_bytes(prev)
    return out


def _serve_census_subprocess():
    """Run :func:`_serve_census` on a forced 8-virtual-device CPU mesh
    in a subprocess — the multi-device verdict for a 1-device bench
    world (collectives lower away in-process there)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    code = ("import json, bench; "
            "print(json.dumps(bench._serve_census(False)))")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"serve census subprocess failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-300:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_serve(on_tpu: bool):
    """Serving throughput/latency: the continuous-batching engine
    (slots=4, decode comm on the overlap scheduler) vs the no-overlap,
    no-continuous-batching baseline (slots=1, blocking collectives —
    the same TP decode path serving requests one at a time), on the
    smoke transformer.

    Persists tokens/sec and p50/p99 per-token latency for both, the
    continuous-batching speedup, and — the regression currency on the
    CPU smoke path, where wall-clock is scheduler noise — the
    deterministic census verdicts: scheduled exposure of the decode
    step (overlap strictly below the blocking 1.0), per-token wire
    bytes off the lowered StableHLO, and the latency-tier selection for
    the real decode message sizes."""
    import time as _time

    import jax

    from mpi4torch_tpu import serve

    n = len(jax.devices())
    cfg, params, prompts, max_new = _serve_setup()

    def run_one(slots, overlap):
        eng = serve.Engine(
            cfg, params, serve.ServeConfig(slots=slots, overlap=overlap),
            spmd=(n > 1), nranks=(n if n > 1 else None))
        for p in prompts:
            eng.submit(p, max_new=max_new)
        token_lat = []
        t0 = _time.perf_counter()
        while eng.pending():
            s0 = _time.perf_counter()
            ev = eng.step()
            dt = _time.perf_counter() - s0
            n_emitted = sum(len(v) for v in ev["emitted"].values())
            token_lat.extend([dt] * n_emitted)
        wall = _time.perf_counter() - t0
        total = sum(len(p) for p in prompts)
        new_tokens = sum(len(r) for r in eng.results().values()) - total

        def pct(q):
            # ONE percentile rule repo-wide (mpi4torch_tpu.obs): the
            # same nearest-rank-floor helper ServeStats.snapshot's
            # p50/p99 aggregates use — this stanza's historical rule,
            # now shared instead of duplicated.
            from mpi4torch_tpu.obs import percentile
            v = percentile(token_lat, q)
            return None if v is None else round(v * 1e3, 3)

        return {
            "slots": slots,
            "new_tokens": new_tokens,
            "steps": eng.stats.snapshot()["steps"],
            "occupancy": eng.stats.snapshot()["occupancy"],
            "wall_s": round(wall, 4),
            "tokens_per_s": round(new_tokens / wall, 2),
            "p50_token_latency_ms": pct(0.50),
            "p99_token_latency_ms": pct(0.99),
        }

    out = {"n_devices": n, "n_requests": len(prompts),
           "max_new": max_new}
    engine = _guarded("serve.engine", run_one, 4, True)
    baseline = _guarded("serve.baseline", run_one, 1, False)
    out["engine"] = engine
    out["baseline"] = baseline
    if "tokens_per_s" in engine and "tokens_per_s" in baseline \
            and baseline["tokens_per_s"]:
        out["continuous_batching_speedup"] = round(
            engine["tokens_per_s"] / baseline["tokens_per_s"], 3)
    census = _guarded("serve.census",
                      _serve_census if n > 1 else
                      _serve_census_subprocess,
                      *((on_tpu,) if n > 1 else ()))
    out["census"] = census
    if "error" not in census:
        co = census.get("overlap") or {}
        cb = census.get("blocking") or {}
        if co.get("exposed_fraction") is not None \
                and cb.get("exposed_fraction") is not None:
            out["overlap_exposure_lower"] = bool(
                co["exposed_fraction"] < cb["exposed_fraction"])
        lt = census.get("latency_tier") or {}
        out["latency_tier_selected"] = lt.get("latency_tier")
    if not on_tpu:
        out["note"] = (
            "cpu smoke: wall-clock tokens/sec is host-loop overhead, "
            "not wire time, and the p99 tail holds the one-time "
            "step/prefill compiles (cold engine, like a cold server) — "
            "the deterministic census verdicts (exposure, per-token "
            "wire bytes, latency-tier selection) are the regression "
            "currency here; the throughput/latency numbers become the "
            "headline on real multi-chip hardware")
    return out


def _bench_serve_paged(on_tpu: bool):
    """Paged KV cache vs the dense slot table (ISSUE 17) under a
    KV-BYTE-BUDGET-MATCHED comparison on a long-tailed length
    distribution with a shared system prompt: the dense engine reserves
    every occupied slot's full ``max_seq`` rows, the paged engine only
    the pages requests actually wrote (shared prefix pages once).

    The headline is DETERMINISTIC: ``kv_bytes_resident()`` is a census
    of reserved cache bytes, integrated per step and divided by tokens
    emitted — ``paged_occupancy_gain`` is the dense/paged ratio of
    KV-bytes-resident·steps per token (the effective-occupancy claim:
    how many more concurrent sequences the same HBM holds).  Tokens/sec
    rides along for the hardware runs; on CPU smoke it is host-loop
    noise and the census is the regression currency."""
    import time as _time

    import jax
    import numpy as np

    from mpi4torch_tpu import serve

    n = len(jax.devices())
    cfg, params, _, max_new = _serve_setup()
    # Long-tailed lengths: mostly short chats, two long documents —
    # the distribution dense slot tables waste max_seq rows on.  Four
    # of the short ones share a 16-token system prompt (prefix pages
    # shared, prefilled once).
    rng = np.random.default_rng(7)
    sys_prompt = rng.integers(1, cfg.vocab, size=16)
    prompts = [np.concatenate([sys_prompt,
                               rng.integers(1, cfg.vocab, size=k)])
               for k in (3, 5, 4, 6)]
    prompts += [rng.integers(1, cfg.vocab, size=int(k))
                for k in (4, 6, 40, 48)]
    slots, bs = 4, 8
    # Byte-budget match: dense reserves slots*max_seq rows; the paged
    # pool gets exactly that many rows' worth of pages.
    num_blocks = slots * cfg.max_seq // bs

    def run_one(paged):
        eng = serve.Engine(
            cfg, params,
            serve.ServeConfig(slots=slots,
                              block_size=(bs if paged else 0),
                              num_blocks=(num_blocks if paged
                                          else None)),
            spmd=(n > 1), nranks=(n if n > 1 else None))
        for p in prompts:
            eng.submit(p, max_new=max_new)
        resident_byte_steps = 0
        t0 = _time.perf_counter()
        while eng.pending():
            eng.step()
            resident_byte_steps += eng.kv_bytes_resident()
        wall = _time.perf_counter() - t0
        snap = eng.stats.snapshot()
        new_tokens = snap["decode_tokens"] + snap["admitted"]
        out = {
            "steps": snap["steps"],
            "new_tokens": new_tokens,
            "occupancy": snap["occupancy"],
            "kv_byte_steps_resident": int(resident_byte_steps),
            "kv_bytes_per_token": round(
                resident_byte_steps / max(new_tokens, 1), 1),
            "wall_s": round(wall, 4),
            "tokens_per_s": round(new_tokens / wall, 2),
        }
        if paged:
            out.update({
                "block_size": bs, "num_blocks": num_blocks,
                "prefix_hits": snap["prefix_hits"],
                "prefix_misses": snap["prefix_misses"],
                "prefill_tokens": snap["prefill_tokens"],
                "cow_copies": snap["cow_copies"],
            })
        return out

    out = {"n_devices": n, "n_requests": len(prompts),
           "max_new": max_new,
           "prompt_lengths": [int(len(p)) for p in prompts],
           "shared_prefix_tokens": int(len(sys_prompt))}
    paged = _guarded("serve_paged.paged", run_one, True)
    dense = _guarded("serve_paged.dense", run_one, False)
    out["paged"] = paged
    out["dense"] = dense
    if "kv_bytes_per_token" in paged and "kv_bytes_per_token" in dense \
            and paged["kv_bytes_per_token"]:
        # The deterministic headline: same KV byte budget, how much
        # less cache each emitted token holds resident.
        out["paged_occupancy_gain"] = round(
            dense["kv_bytes_per_token"] / paged["kv_bytes_per_token"],
            3)
        out["paged_occupancy_gain_ok"] = \
            bool(out["paged_occupancy_gain"] > 1.0)
    if not on_tpu:
        out["note"] = (
            "cpu smoke: the kv_bytes_per_token census (and the "
            "occupancy-gain ratio) is deterministic and is the "
            "regression currency; tokens/sec is host-loop overhead "
            "here and becomes meaningful on real hardware")
    return out


def _bench_allreduce_algorithms(on_tpu: bool):
    """Per-algorithm allreduce size sweep (mpi4torch_tpu.tune):
    1 KiB → 64 MiB on hardware (three points on the CPU smoke path),
    per-algorithm GB/s under ring-allreduce wire accounting for every
    registered algorithm — the latency tier (rhd/tree), hier, and the
    multipath bandwidth tier (bidir/torus) — the measured latency AND
    bandwidth crossovers, and the persistent autotuner's picks.  The autotuner stanza round-trips its JSON cache: the first
    bench run measures and persists, a second run reports
    ``tuned_from_cache: true`` with the same picks and zero tuning
    overhead — the ISSUE 3 acceptance evidence."""
    import jax

    from mpi4torch_tpu import tune

    import jax.numpy as jnp

    from mpi4torch_tpu.tune.autotuner import DEFAULT_SIZES, SMOKE_SIZES

    n = len(jax.devices())
    # The autotuner's own sweep grids: the cache keys this stanza
    # probes/persists MUST be the ones ensure_tuned/`make tune-smoke`
    # use, or tuned_from_cache goes permanently false on a grid drift.
    sizes = DEFAULT_SIZES if on_tpu else SMOKE_SIZES
    iters = 20 if on_tpu else 3

    # Cache state BEFORE this run's sweep overwrites it: a prior bench
    # run's persisted winners covering every size are the
    # `tuned_from_cache` evidence (a steady-state process would select
    # tuned algorithms with zero measurement).
    def _had_disk():
        return all(
            tune.lookup("allreduce", jnp.float32, s, n) is not None
            and tune.entry_from_disk("allreduce", jnp.float32, s, n)
            for s in sizes)

    had_disk = _guarded("allreduce_algorithms.cache_probe", _had_disk)

    # ONE sweep implementation: the autotuner's own (per-algorithm
    # seconds + ring-wire GB/s + winner + crossover, with per-candidate
    # error stanzas inside) — the bench must never fork its own copy of
    # the measurement/crossover rules.  This pass IS the tuning run:
    # winners persist to the JSON cache and the measured crossover is
    # applied, so the next process (and the next bench run) selects
    # tuned algorithms without measuring.
    rep = tune.autotune_allreduce(sizes=sizes, nranks=n, iters=iters)

    # The flat sweep table (sizes × algorithms → GB/s) — algorithm-
    # selection quality tracked across rounds (BENCH_r*.json): every
    # registered algorithm, including the bandwidth tier bidir/torus,
    # shows its measured throughput next to the winner column.
    sweep = {}
    for size_str, ent in rep["entries"].items():
        sweep[size_str] = {
            name: meas.get("gbps", meas.get("error"))
            for name, meas in ent.get("algorithms", {}).items()}
    out = {
        "n_devices": n,
        "dtype": rep["dtype"],
        "algorithms": list(tune.available_algorithms()),
        "sizes": rep["entries"],
        "sweep_gbps": sweep,
        # The crossover table's headline: the largest size where a
        # latency-optimal schedule still beats the ring, and the
        # smallest from which the multipath bandwidth tier wins through
        # the top (None = that regime not reached on this hardware).
        "crossover_bytes": rep["crossover_bytes"],
        "bandwidth_crossover_bytes": rep["bandwidth_crossover_bytes"],
        "autotuner": {
            "tuned_from_cache": bool(had_disk is True),
            "cache_file": rep["cache_file"],
            "crossover_bytes": rep["crossover_bytes"],
            "bandwidth_crossover_bytes":
                rep["bandwidth_crossover_bytes"],
            "picks": {k: v.get("winner")
                      for k, v in rep["entries"].items()},
        },
    }
    if n == 1:
        out["note"] = ("single device: no wire; per-algorithm timings "
                       "price schedule arithmetic only — the crossover "
                       "is meaningful where ICI/DCN is in the path")
    return out


def _bench_flash(on_tpu: bool, peak: float):
    """Causal flash-attention fwd+bwd achieved FLOP/s and MFU."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.ops import flash

    if on_tpu:
        b, s, h, d, dtype, iters = 4, 4096, 8, 128, jnp.bfloat16, 20
    else:
        b, s, h, d, dtype, iters = 1, 256, 2, 64, jnp.float32, 2

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype) for kk in keys)

    def loss(q, k, v, window=0):
        out = flash.flash_attention(q, k, v, causal=True, impl="auto",
                                    window=window)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    dt = _timeit(step, q, k, v, iters=iters)

    def kernel_flags(window=0):
        fwd = bool(on_tpu and flash._eligible(q, k)
                   and flash._pallas_compiles(s, s, d, dtype, True,
                                              window=window))
        bwd = bool(on_tpu and flash._bwd_eligible(q, k)
                   and flash._pallas_bwd_compiles(s, s, d, dtype, True,
                                                  window=window))
        return fwd, bwd


    # Sliding-window variant at the same shape: the two-frontier tile
    # skip should make cost ~O(window/seq) of full causal — report the
    # measured ratio so the claim is a number, not a comment.  Guarded
    # separately: a windowed-variant failure must degrade to an error
    # stanza inside "windowed", never erase the full-causal measurement
    # above (the module's robustness contract).
    window = s // 4
    try:
        wstep = jax.jit(jax.value_and_grad(
            functools.partial(loss, window=window), argnums=(0, 1, 2)))
        dt_w = _timeit(wstep, q, k, v, iters=iters)
        windowed = {
            "window": window,
            "seconds_per_step": dt_w,
            # Full causal touches ~s/2 keys per query, the window ~w:
            # ideal ratio ~ 2w/s (0.5 at w = s/4).  >=1.0 with the
            # kernel engaged means the tile skip is not working; check
            # the pallas flags first — a windowed-probe failure falls
            # back to jnp and balloons the time for a different reason.
            "time_ratio_vs_full": round(dt_w / dt, 4),
        }
        windowed["pallas_fwd"], windowed["pallas_bwd"] = \
            kernel_flags(window)
    except BaseException as e:  # noqa: BLE001 — sub-measurement guard
        windowed = {"window": window,
                    "error": f"{type(e).__name__}: {str(e)[:300]}"}

    # Causal fwd = 2 matmuls * 2 FLOP/MAC * B*H*S^2*D / 2 (masked half).
    # MFU uses *model* FLOPs only (PaLM convention): fwd + 2x bwd = 3x;
    # the flash backward's score recompute is excluded (that extra work
    # would make this HFU and overstate utilization).
    fwd = 2.0 * b * h * s * s * d
    flops = 3.0 * fwd
    achieved = flops / dt
    # The timed step is fwd+bwd: report each kernel's engagement — the
    # backward is ~2/3 of the FLOPs and gates independently (its own
    # eligibility + compile probe), so a single flag would mislabel a
    # jnp-backward run as fully fused.
    fwd_kernel, bwd_kernel = kernel_flags()
    return {
        "tflops": round(achieved / 1e12, 3),
        "mfu": round(achieved / peak, 4),
        "shape": [b, s, h, d],
        "dtype": str(jnp.dtype(dtype)),
        "seconds_per_step": dt,
        "pallas_kernel": fwd_kernel and bwd_kernel,
        "pallas_fwd": fwd_kernel,
        "pallas_bwd": bwd_kernel,
        "windowed": windowed,
    }


def _bench_flash_reference_ratio(on_tpu: bool):
    """Race our Pallas flash kernel against JAX's own TPU flash attention
    (``jax.experimental.pallas.ops.tpu.flash_attention``) fwd+bwd at the
    bench shape — the one head-to-head opponent measurable on a single
    chip, so "matching-or-beating on perf" has a number (VERDICT r4
    item 2).  ``ratio`` is ours_tflops / jax_tflops = jax_s / ours_s;
    >= 1.0 means ours wins.  On CPU the opponent kernel has no lowering,
    so the smoke path races the module's own jnp reference instead
    (harness check only; the ratio is labeled)."""
    import math

    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.ops import flash

    if on_tpu:
        b, s, h, d, dtype, iters = 4, 4096, 8, 128, jnp.bfloat16, 20
    else:
        b, s, h, d, dtype, iters = 1, 256, 2, 64, jnp.float32, 2

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype) for kk in keys)

    def ours_loss(q, k, v):
        out = flash.flash_attention(q, k, v, causal=True, impl="auto")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    ours = jax.jit(jax.value_and_grad(ours_loss, argnums=(0, 1, 2)))
    dt_ours = _timeit(ours, q, k, v, iters=iters)

    sm_scale = 1.0 / math.sqrt(d)   # our kernel's fixed convention
    if on_tpu:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa

        # JAX's kernel wants (batch, heads, seq, head_dim).  Hand it
        # pre-transposed inputs so the timed region is kernel-only on both
        # sides — a transpose inside the jitted opponent would charge it
        # ~6 layout copies per fwd+bwd step and bias the ratio our way.
        def jax_loss(qh, kh, vh):
            out = jfa.flash_attention(qh, kh, vh, causal=True,
                                      sm_scale=sm_scale)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        opponent = "jax.experimental.pallas.ops.tpu.flash_attention"
        jq, jk, jv = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    else:
        def jax_loss(qh, kh, vh):
            out = flash.flash_attention(qh, kh, vh, causal=True, impl="jnp")
            return jnp.sum(out.astype(jnp.float32) ** 2)

        opponent = "jnp reference (cpu smoke; no TPU opponent available)"
        jq, jk, jv = q, k, v

    theirs = jax.jit(jax.value_and_grad(jax_loss, argnums=(0, 1, 2)))
    dt_jax = _timeit(theirs, jq, jk, jv, iters=iters)

    # Same computation check: fwd outputs must agree to dtype tolerance.
    ours_out = flash.flash_attention(q, k, v, causal=True, impl="auto")
    if on_tpu:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa

        jax_out = jfa.flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=True,
            sm_scale=sm_scale).transpose(0, 2, 1, 3)
    else:
        jax_out = flash.flash_attention(q, k, v, causal=True, impl="jnp")
    max_diff = float(jnp.max(jnp.abs(ours_out.astype(jnp.float32)
                                     - jax_out.astype(jnp.float32))))

    fwd = 2.0 * b * h * s * s * d          # causal: half of 2*2*B*H*S^2*D
    flops = 3.0 * fwd
    res = {
        "shape": [b, s, h, d],
        "dtype": str(jnp.dtype(dtype)),
        "opponent": opponent,
        "ours_s": dt_ours,
        "jax_s": dt_jax,
        "ours_tflops": round(flops / dt_ours / 1e12, 3),
        "jax_tflops": round(flops / dt_jax / 1e12, 3),
        "ratio": round(dt_jax / dt_ours, 4),
        "fwd_max_abs_diff": max_diff,
    }

    if on_tpu:
        # GQA head-to-head (guarded: must never erase the MHA ratio).
        # Our kernels resolve the q-head -> shared-KV-head mapping in
        # their BlockSpec index maps (KV never duplicated in HBM); the
        # opponent has no GQA entry point, so it runs the standard
        # realization — KV repeated to full head count before the
        # kernel.  The repeat is OUTSIDE the timed jit (pre-staged like
        # the layout transposes) so the timed gap is pure kernel-side
        # HBM traffic, not the repeat op itself.
        try:
            g = 4                                   # 8 q heads, 2 KV heads
            kg, vg = k[:, :, ::g, :], v[:, :, ::g, :]
            # `ours` retraces for the narrower KV shape automatically.
            dt_g_ours = _timeit(ours, q, kg, vg, iters=iters)
            krep = jnp.repeat(kg, g, axis=2).transpose(0, 2, 1, 3)
            vrep = jnp.repeat(vg, g, axis=2).transpose(0, 2, 1, 3)
            dt_g_jax = _timeit(theirs, jq, krep, vrep, iters=iters)
            res["gqa"] = {
                "q_heads": h, "kv_heads": h // g,
                "ours_s": dt_g_ours, "jax_repeated_kv_s": dt_g_jax,
                "ratio": round(dt_g_jax / dt_g_ours, 4),
            }
        except BaseException as e:  # noqa: BLE001 — sub-measurement guard
            res["gqa"] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    return res


def _bench_train_step(on_tpu: bool, peak: float):
    """Flagship transformer fwd+bwd+update MFU (6*N*T accounting)."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.models import transformer as T

    if on_tpu:
        cfg = T.TransformerConfig(vocab=32768, d_model=2048, n_heads=16,
                                  n_layers=8, d_ff=8192, max_seq=2048)
        batch, dtype, iters = 8, jnp.bfloat16, 10
        # The dense (batch, seq, vocab) logits alone are 1 GiB bf16 (+
        # f32 softmax intermediates) per step at this config; the
        # chunked-vocab loss never materializes them
        # (models/transformer.py _chunked_ce) — 8 x 4096-wide slabs.
        vocab_chunk = 4096
    else:
        cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                  n_layers=2, d_ff=128, max_seq=64)
        batch, dtype, iters = 2, jnp.float32, 2
        vocab_chunk = 64

    params = T.init_transformer(jax.random.PRNGKey(0), cfg, dtype=dtype)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.max_seq),
                                0, cfg.vocab, jnp.int32)

    def _variant_step(vc):
        def f(params, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: T.lm_loss(cfg, p, tokens, vocab_chunk=vc))(params)
            new = jax.tree.map(lambda p, g: p - 1e-3 * g.astype(p.dtype),
                               params, grads)
            return loss, new
        return jax.jit(f)

    step = _variant_step(vocab_chunk)
    dt = _timeit(step, params, tokens, iters=iters)

    n_params = sum(x.size for x in jax.tree.leaves(params))
    n_tokens = batch * cfg.max_seq
    s, hd = cfg.max_seq, cfg.d_model // cfg.n_heads
    # 6*N*T dense accounting + causal attention matmuls (fwd 2*2*B*H*S^2*
    # Dh/2 per layer, x3 for fwd+bwd model FLOPs — recompute excluded,
    # as in _bench_flash).
    attn = 3.0 * 2.0 * batch * cfg.n_heads * s * s * hd * cfg.n_layers
    flops = 6.0 * n_params * n_tokens + attn
    achieved = flops / dt

    # Where-does-the-time-go breakdown (VERDICT r4 item 8: if MFU misses
    # the 0.4 bar, the committed artifact must identify the next
    # optimization).  Each stage is timed as its own jitted program; the
    # differences attribute the step time: forward vs backward
    # (value_and_grad minus forward), optimizer update (full step minus
    # value_and_grad), the loss head (forward-with-loss minus
    # forward-to-logits), and attention share (the flash sub-bench at
    # this model's per-layer shape x n_layers).  Guarded: a breakdown
    # failure must never erase the headline number.
    def _breakdown():
        fwd_loss = jax.jit(lambda p: T.lm_loss(cfg, p, tokens,
                                               vocab_chunk=vocab_chunk))
        fwd_bwd = jax.jit(jax.value_and_grad(
            lambda p: T.lm_loss(cfg, p, tokens, vocab_chunk=vocab_chunk)))
        hidden = jax.jit(lambda p: T.forward(cfg, p, tokens,
                                             return_hidden=True))
        t_fwd_loss = _timeit(fwd_loss, params, iters=max(iters // 2, 2))
        t_fwd_bwd = _timeit(fwd_bwd, params, iters=max(iters // 2, 2))
        t_hidden = _timeit(hidden, params, iters=max(iters // 2, 2))

        from mpi4torch_tpu.ops import flash as _flash

        kq = jax.random.normal(jax.random.PRNGKey(2),
                               (batch, s, cfg.n_heads, hd), dtype)
        # Grad w.r.t. ALL of q/k/v: requesting only dq would let XLA
        # dead-code-eliminate the dkv backward kernel and under-report
        # attention's true share.
        att = jax.jit(jax.value_and_grad(lambda q, k, v: jnp.sum(
            _flash.flash_attention(q, k, v, causal=True,
                                   impl="auto").astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        t_attn_layer = _timeit(att, kq, kq, kq, iters=max(iters // 2, 2))
        return {
            "forward_with_loss_s": t_fwd_loss,
            "forward_to_hidden_s": t_hidden,
            "loss_head_s": max(t_fwd_loss - t_hidden, 0.0),
            "fwd_bwd_s": t_fwd_bwd,
            "backward_s": max(t_fwd_bwd - t_fwd_loss, 0.0),
            "optimizer_s": max(dt - t_fwd_bwd, 0.0),
            "attention_fwd_bwd_all_layers_s": t_attn_layer * cfg.n_layers,
            "attention_share_of_step": round(
                t_attn_layer * cfg.n_layers / dt, 4),
        }

    breakdown = _guarded("train_step.breakdown", _breakdown)

    # Ground the hand accounting against the compiler's own count: XLA's
    # cost analysis of the compiled step vs the 6*N*T model FLOPs.  Two
    # opposite-signed deviations are expected: XLA additionally counts
    # the flash recompute + optimizer arithmetic (ratio up), while 6*N*T
    # charges the embedding table as if it were a matmul when the actual
    # lookup is a gather (ratio down — dominant at small configs where
    # the table is a large parameter share, e.g. 0.85 on the CPU smoke
    # config).  A ratio far below the embedding share would mean the
    # accounting — and therefore the MFU — is inflated.
    def _xla_flops():
        # step is already @jax.jit — lower it directly (cache-friendly,
        # no redundant re-wrap/trace).
        ca = step.lower(params, tokens).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if "flops" not in ca:
            raise KeyError(
                f"no 'flops' in cost_analysis keys {sorted(ca)[:10]}")
        return float(ca["flops"])

    xla_flops = _guarded("train_step.xla_cost", _xla_flops)
    if isinstance(xla_flops, dict):   # error stanza: count unavailable
        xla_ratio = None
    else:
        xla_ratio = round(xla_flops / flops, 3) if flops else None

    # Ablation: what the TPU-native pieces buy at this exact config,
    # measured, not argued.  (a) The Pallas flash kernels swapped for
    # the module's jnp blockwise fallback — still O(seq) memory, so the
    # opponent is the best non-kernel implementation, not a dense-scores
    # strawman; forced by patching the TRACE-TIME eligibility predicates
    # around a fresh jit closure.  (b) The dense unchunked CE head
    # (vocab_chunk=0): materializes the (batch, seq, vocab) logits this
    # config's chunking exists to avoid — may legitimately OOM, which
    # its own guard records.  Ordered last so neither can disturb the
    # numbers above.
    def _ablation():
        from mpi4torch_tpu.ops import flash as _flash

        qs = jax.ShapeDtypeStruct((batch, s, cfg.n_heads, hd), dtype)
        out = {
            "full_pipeline_s": dt,
            # False (e.g. the CPU smoke path, or a failed lowering probe
            # on the experimental tunnel runtime) means both timed
            # variants ran the same jnp code and the "speedup" is pure
            # noise.  Mirrors the impl="auto" dispatch exactly:
            # eligibility AND the compile probes.
            "pallas_in_baseline": bool(
                on_tpu and _flash._eligible(qs, qs)
                and _flash._bwd_eligible(qs, qs)
                and _flash._pallas_compiles(s, s, hd, dtype, True)
                and _flash._pallas_bwd_compiles(s, s, hd, dtype, True)),
        }
        saved = _flash._eligible, _flash._bwd_eligible
        _flash._eligible = lambda q, k: False
        _flash._bwd_eligible = lambda q, k: False
        try:
            dt_jnp = _timeit(_variant_step(vocab_chunk), params, tokens,
                             iters=max(iters // 2, 2))
        finally:
            _flash._eligible, _flash._bwd_eligible = saved
        out["attn_jnp_blockwise_s"] = dt_jnp
        out["pallas_kernel_step_speedup"] = round(dt_jnp / dt, 4)

        def _dense_ce():
            dt_dense = _timeit(_variant_step(0), params, tokens,
                               iters=max(iters // 2, 2))
            return {"seconds_per_step": dt_dense,
                    "chunked_ce_step_speedup": round(dt_dense / dt, 4)}

        out["dense_ce"] = _guarded("train_step.ablation.dense_ce",
                                   _dense_ce)
        return out

    ablation = _guarded("train_step.ablation", _ablation)

    return {
        "tflops": round(achieved / 1e12, 3),
        "mfu": round(achieved / peak, 4),
        "xla_flops_vs_model_flops": xla_ratio,
        "n_params": n_params,
        "tokens_per_step": n_tokens,
        "vocab_chunk": vocab_chunk,
        "dtype": str(jnp.dtype(dtype)),
        "seconds_per_step": dt,
        "breakdown": breakdown,
        "ablation": ablation,
    }


def _bench_schedule_synthesis(on_tpu: bool):
    """Schedule synthesis (mpi4torch_tpu.csched.synth): the
    deterministic synthesized-vs-ring census sweep.  For each (world
    shape, size bucket) the census-ranked winner of the bounded IR
    program family is compared against the hand-written DETERMINISTIC
    ring (the ordered fold — the schedule a synthesized winner actually
    replaces) on wire bytes per rank and sequential steps; the verdict
    is hardware-independent (the repo's census regression currency), so
    it is recorded even when no TPU is attached."""
    import jax

    from mpi4torch_tpu import csched

    ndev = len(jax.devices())
    worlds = sorted({ndev, max(2, ndev // 2), 2} - {0, 1})
    sizes = (1 << 10, 1 << 14, 1 << 18, 1 << 22)
    entries = {}
    any_beats = False
    for n in worlds:
        per = {}
        for nbytes in sizes:
            res = csched.synthesize(n, nbytes, 4)
            beats = bool(res["synthesis_beats_ring"])
            any_beats = any_beats or beats
            per[str(nbytes)] = {
                "winner": res["winner"],
                "chain": res["chain"],
                "wire_bytes_per_rank":
                    res["census"]["wire_bytes_per_rank"],
                "seq_steps": res["census"]["seq_steps"],
                "ring_wire_bytes_per_rank":
                    res["ring_census"]["wire_bytes_per_rank"],
                "ring_seq_steps": res["ring_census"]["seq_steps"],
                "wire_advantage": round(
                    res["ring_census"]["wire_bytes_per_rank"]
                    / max(1, res["census"]["wire_bytes_per_rank"]), 3),
                "synthesis_beats_ring": beats,
            }
        entries[str(n)] = per
    return {
        "mode": "deterministic census sweep (wire bytes / seq steps)",
        "worlds": worlds,
        "entries": entries,
        "synthesis_beats_ring": any_beats,
    }


def _bench_allreduce_tiers(on_tpu: bool):
    """Tier-stack synthesis stanza (ISSUE 18): the bandwidth-weighted
    census verdict of the multi-pod tier-dimension search.  Per nested
    factorization of the attached world and size bucket, the weighted
    winner under a skewed slow-outer bandwidth profile (outer tier 20x
    under the inner — the DCN-under-ICI shape) is compared against the
    flat ``bidir`` baseline: the per-tier wire table, the weighted
    cost, and ``tier_weighted_gain`` (baseline weighted cost over
    winner's — > 1.0 is a win).  Deterministic census arithmetic, so
    recorded on any hardware, like the flat synthesis stanza."""
    import jax

    from mpi4torch_tpu import csched

    ndev = len(jax.devices())
    stacks = [s for s in ((2, 2, 2), (4, 2), (2, 4))
              if _prod(s) == ndev] or ([(2, ndev // 2)]
                                       if ndev >= 4 and ndev % 2 == 0
                                       else [])
    sizes = (1 << 10, 1 << 14, 1 << 18)
    entries = {}
    any_gain = False
    for stack in stacks:
        skew = tuple([1.0] * (len(stack) - 1) + [0.05])
        per = {}
        for nbytes in sizes:
            res = csched.synthesize_tiers(ndev, nbytes, 4, tiers=stack,
                                          tier_bandwidths=skew)
            gain = (res["bidir_weighted_cost"]
                    / max(res["weighted_cost"], 1e-12))
            any_gain = any_gain or res["beats_bidir"]
            per[str(nbytes)] = {
                "winner": res["winner"],
                "chain": res["chain"],
                "composition": res["composition"],
                "tier_wire": res["tier_wire"],
                "bidir_tier_wire": res["bidir_tier_wire"],
                "weighted_cost": res["weighted_cost"],
                "bidir_weighted_cost": res["bidir_weighted_cost"],
                "tier_weighted_gain": round(gain, 3),
                "outer_tier_wire_reduction": (
                    res["bidir_tier_wire"][-1] - res["tier_wire"][-1]),
                "beats_bidir": res["beats_bidir"],
            }
        entries["x".join(map(str, stack))] = per
    return {
        "mode": ("deterministic bandwidth-weighted census sweep "
                 "(slow-outer skew 20:1)"),
        "nranks": ndev,
        "stacks": ["x".join(map(str, s)) for s in stacks],
        "entries": entries,
        "tier_weighted_gain": any_gain,
    }


def _prod(t):
    p = 1
    for v in t:
        p *= int(v)
    return p


def _bench_transport(on_tpu: bool):
    """Transport-runtime stanza (ISSUE 16): the first HONEST wall-clock
    numbers for Mode B — ``process_parallel_speedup`` is thread-backend
    wall time over process-backend wall time for a GIL-bound per-rank
    compute step + allreduce, recorded next to the cpu_count that
    bounds it (on a 1-core container the honest number is ~1.0; the
    claim the repo stands behind everywhere is the DETERMINISTIC wire
    census, which must be identical across backends and is asserted
    here, not just reported)."""
    import os as _os
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs
    from mpi4torch_tpu.obs.reconcile import measured_wire_table

    NR, SPIN = 3, 120_000

    def body(rank):
        # Pure-Python FNV spin: holds the GIL, so rank-threads serialize
        # and worker processes don't — the workload that makes the
        # speedup a statement about the transport, not about numpy.
        h = 0x811C9DC5
        for i in range(SPIN):
            h = ((h ^ (rank + i)) * 0x01000193) & 0xFFFFFFFF
        x = jnp.full(256, float(h % 97), jnp.float32) * (rank + 1)
        return np.asarray(mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM))

    def timed(backend):
        with obs.trace() as t:
            t0 = _time.perf_counter()
            out = mpi.run_ranks(body, NR, backend=backend)
            dt = _time.perf_counter() - t0
        census = measured_wire_table(t.events)
        return dt, out, {"wire_bytes": census["wire_bytes"],
                         "counts": census["counts"],
                         "logical_events": census["logical_events"]}

    # Warm both paths once (jit + worker-pool spawn) so the measured
    # pass prices the steady state the pool exists to provide.
    timed("thread")
    timed("process")
    t_thread, out_t, census_t = timed("thread")
    t_process, out_p, census_p = timed("process")

    for r in range(NR):
        assert np.array_equal(out_t[r], out_p[r]), \
            f"transport parity broke at rank {r}"
    assert census_t == census_p, \
        f"wire census diverged across backends: {census_t} vs {census_p}"

    from mpi4torch_tpu.transport.pool import shared_pool
    return {
        "ranks": NR,
        "cpu_count": _os.cpu_count(),
        "thread_wall_s": round(t_thread, 4),
        "process_wall_s": round(t_process, 4),
        "process_parallel_speedup": round(t_thread / max(t_process, 1e-9),
                                          3),
        "wire_census": census_t,
        "wire_census_identical": True,     # asserted above
        "pool_workers_spawned": shared_pool().spawned_total,
        "note": ("GIL-bound spin + allreduce; speedup is bounded by "
                 "cpu_count and IPC overhead — ~1.0 on a 1-core box "
                 "is the honest reading, the bitwise census is the "
                 "portable claim"),
    }


def _bench_ctl(on_tpu: bool):
    """Self-tuning controller stanza (ISSUE 19): the deterministic
    closed loop — a per-byte brownout on the episode's one collective
    drives the EWMA goodput estimate under the low watermark, the
    controller escalates to the q8/synth_q8 winner through an
    epoch-fenced consensus (the escalated phase is asserted bitwise
    against the explicit-q8 oracle), the fault clears, and the
    de-escalation restores the pre-episode configuration bitwise.  The
    recorded verdict is census arithmetic (weighted cost, per-tier
    wire) plus the ledger's own account of WHY it switched; also pinned
    here: the controller-off discipline — constructing and polling a
    disabled controller leaves the jitted lowering text bit-identical."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu._compat import shard_map
    from mpi4torch_tpu.ctl import SelfTuningController
    from mpi4torch_tpu.ctl.__main__ import closed_loop_episode

    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    probe = jnp.arange(256, dtype=jnp.float32)

    def lowered():
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(probe).as_text()

    text_before = lowered()
    off = SelfTuningController(n_ranks=8, tiers=(2, 2, 2))
    off.poll()
    off_identical = lowered() == text_before

    ev = closed_loop_episode(n=8, tiers=(2, 2, 2), backend="thread")
    esc, rec = ev["escalation"], ev["recovery"]
    bitwise_escalated = all(
        np.array_equal(g, w)
        for g, w in zip(ev["escalated"], ev["oracle_q8"]))
    bitwise_recovered = all(
        np.array_equal(g, w)
        for g, w in zip(ev["recovered"], ev["exact_before"]))
    return {
        "mode": "deterministic closed loop (eager thread backend)",
        "escalation_trigger": esc.trigger if esc else None,
        "escalation_epoch": esc.epoch if esc else None,
        "weighted_cost_before": esc.old["weighted_cost"] if esc else None,
        "weighted_cost_after": esc.new["weighted_cost"] if esc else None,
        "tier_wire_before": esc.old["tier_wire"] if esc else None,
        "tier_wire_after": esc.new["tier_wire"] if esc else None,
        "cost_reduction": round(
            esc.old["weighted_cost"] / max(esc.new["weighted_cost"], 1e-9),
            3) if esc else None,
        "compression_during": ev["compression_during"],
        "bitwise_vs_q8_oracle": bitwise_escalated,
        "stale_view_fenced": ev["stale_fenced"],
        "recovery_trigger": rec.trigger if rec else None,
        "recovery_epoch": rec.epoch if rec else None,
        "compression_after": ev["compression_after"],
        "bitwise_vs_pre_episode": bitwise_recovered,
        "ledger_triggers": ev["ledger"].triggers(),
        "controller_off_lowering_identical": off_identical,
        "note": ("brownout -> crossover escalation -> recovery; every "
                 "switch consensus-ratified, both phase results bitwise "
                 "against their oracles"),
    }


def _guarded(name: str, fn, *args):
    """Run one sub-bench; on ANY failure return an error stanza instead of
    propagating (a completed earlier measurement must survive a later
    crash — round-3 postmortem)."""
    try:
        res = fn(*args)
        _note(f"{name}: {json.dumps(res)}")
        return res
    except BaseException as e:  # noqa: BLE001 — even SystemExit must not kill the bench
        tail = traceback.format_exc().strip().splitlines()[-6:]
        _note(f"{name} FAILED: {e!r}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}",
                "traceback_tail": tail}


def main() -> None:
    result = {
        "metric": "allreduce_fwd_bwd_bandwidth_per_chip",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }
    try:
        cpu_pinned = os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
        tpu_info = None if cpu_pinned else _probe_tpu()
        # tpu_unavailable marks a FAILED probe only; a deliberate
        # JAX_PLATFORMS=cpu smoke run reports cpu_requested instead.
        tpu_unavailable = not cpu_pinned and tpu_info is None

        if tpu_info is None:
            # Either the user pinned CPU or the TPU probe failed/timed
            # out.  The env var alone does not stop an externally-
            # registered TPU plugin from initializing (and hanging); the
            # config update does.
            import jax

            jax.config.update("jax_platforms", "cpu")
            device_kind, on_tpu = "cpu", False
            peak, hbm = _DEFAULT_PEAK, _DEFAULT_HBM
        else:
            device_kind, _n = tpu_info
            on_tpu = True
            peak, hbm = _chip_specs(device_kind)

        import jax

        platform = jax.devices()[0].platform
        _note(f"platform={platform} device_kind={device_kind}")

        # The per-iteration completion fetch (see _force) costs one tunnel
        # round-trip; measure that floor on an already-materialized buffer
        # so every seconds_per_step below can be read against it.  Guarded
        # like any sub-bench: a transient tunnel hiccup here must not
        # erase the measurements that follow.
        def _floor():
            import jax.numpy as jnp

            # Two leaves, like every real (loss, grads) output.  This is
            # a LOWER bound on the probe overhead: multi-device outputs
            # additionally pay a cross-device reduce inside the probe
            # (their [:,0,..] column spans the rank-sharded axis), which
            # an unsharded floor buffer cannot represent.
            ready = (jnp.zeros((8,), jnp.float32),
                     jnp.zeros((8,), jnp.float32))
            return _timeit(lambda: ready, iters=10)

        result["timing_floor_s"] = _guarded("timing_floor", _floor)

        ar = _guarded("allreduce", _bench_allreduce, on_tpu, hbm)
        arc = _guarded("allreduce_compressed", _bench_allreduce_compressed,
                       on_tpu)
        arm = _guarded("allreduce_compressed_multipath",
                       _bench_allreduce_compressed_multipath, on_tpu)
        arf = _guarded("allreduce_fused", _bench_allreduce_fused, on_tpu)
        ara = _guarded("allreduce_algorithms", _bench_allreduce_algorithms,
                       on_tpu)
        ovz = _guarded("overlap_zero", _bench_overlap_zero, on_tpu)
        gov = _guarded("guard_overhead", _bench_guard_overhead, on_tpu)
        obsov = _guarded("obs_overhead", _bench_obs_overhead, on_tpu)
        deg = _guarded("degraded_mode", _bench_degraded_mode, on_tpu)
        rsh = _guarded("reshard", _bench_reshard, on_tpu)
        ela = _guarded("elastic", _bench_elastic, on_tpu)
        srv = _guarded("serve", _bench_serve, on_tpu)
        srvp = _guarded("serve_paged", _bench_serve_paged, on_tpu)
        syn = _guarded("schedule_synthesis", _bench_schedule_synthesis,
                       on_tpu)
        tirs = _guarded("allreduce_tiers", _bench_allreduce_tiers, on_tpu)
        trn = _guarded("transport", _bench_transport, on_tpu)
        ctlr = _guarded("ctl", _bench_ctl, on_tpu)
        flash_res = _guarded("flash", _bench_flash, on_tpu, peak)
        ratio_res = _guarded("flash_reference_ratio",
                             _bench_flash_reference_ratio, on_tpu)
        train_res = _guarded("train_step", _bench_train_step, on_tpu, peak)

        target_gbps = 36.0  # 0.8 * ~45 GB/s v5e ICI per-link (BASELINE.md)
        gbps = float(ar.get("gbps", 0.0)) if "error" not in ar else 0.0
        # vs_baseline compares against the ICI target ONLY when ICI is in
        # the path (n > 1).  A single chip's allreduce is HBM traffic —
        # against a 36 GB/s wire target it reads as an absurd win
        # (r04 recorded 271x) — so there vs_baseline reports the
        # HBM-roofline fraction: 1.0 = the chip's own ceiling.
        n_chips = ar.get("n_devices") or 1
        if n_chips > 1:
            vs_baseline = gbps / target_gbps
        elif ar.get("suspect"):
            vs_baseline = 0.0   # broken measurement must not read as a win
        else:
            vs_baseline = ar.get("hbm_roofline_fraction") or 0.0
        result.update({
            "value": round(gbps, 3),
            "vs_baseline": round(vs_baseline, 4),
            "n_devices": ar.get("n_devices"),
            "platform": platform,
            "device_kind": device_kind,
            "tpu_unavailable": tpu_unavailable,
            "cpu_requested": cpu_pinned,
            "allreduce": ar,
            "allreduce_compressed": arc,
            "allreduce_compressed_multipath": arm,
            "allreduce_fused": arf,
            "allreduce_algorithms": ara,
            "overlap_zero": ovz,
            "guard_overhead": gov,
            "obs_overhead": obsov,
            "degraded_mode": deg,
            "reshard": rsh,
            "elastic": ela,
            "serve": srv,
            "serve_paged": srvp,
            "schedule_synthesis": syn,
            "allreduce_tiers": tirs,
            "transport": trn,
            "ctl": ctlr,
            "peak_flops_assumed": peak,
            "hbm_gbps_assumed": hbm,
            "flash_attention_fwd_bwd": flash_res,
            "flash_reference_ratio": ratio_res,
            "train_step": train_res,
            "note": ("ring-allreduce bytes-on-wire accounting"
                     if n_chips > 1 else
                     "single chip: HBM-limited pipeline throughput, no "
                     "ICI; MFU sub-benches are the chip-meaningful "
                     "numbers"),
        })
    except BaseException as e:  # noqa: BLE001
        result["error"] = f"{type(e).__name__}: {str(e)[:300]}"
        result["traceback_tail"] = (
            traceback.format_exc().strip().splitlines()[-6:])
    finally:
        print(json.dumps(result), flush=True)
        # Robustness contract: never a non-zero exit.
        os._exit(0)


if __name__ == "__main__":
    main()

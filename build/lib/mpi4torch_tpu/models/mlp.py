"""Data-parallel MLP: the minimal end-to-end training slice.

The canonical usage pattern of the reference (reference:
examples/simple_linear_regression.py:27-35, doc/examples.rst:24-65) scaled
from a 3-parameter polynomial to a real model: the loss contains exactly one
communication call — ``Allreduce(localloss, MPI_SUM)`` — and its adjoint
(another Allreduce) sums the per-rank gradients, so N ranks optimizing on N
data shards stay in lock-step with the single-rank run on the full data.

Everything here is a pure function of (params, batch); distribution enters
only through the ``comm`` argument, which may be bound to the eager
thread-SPMD runtime, an SPMD mesh axis, or the size-1 default world.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_params(key, sizes: Sequence[int], dtype=jnp.float32) -> List:
    """Glorot-ish init for an MLP with layer widths ``sizes``."""
    params = []
    for m, n in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (m, n), dtype) / jnp.sqrt(jnp.asarray(m, dtype))
        b = jnp.zeros((n,), dtype)
        params.append((w, b))
    return params


def apply(params, x):
    """Forward pass; GELU hidden activations (MXU-friendly: all compute is
    batched matmul)."""
    for w, b in params[:-1]:
        x = jax.nn.gelu(x @ w + b)
    w, b = params[-1]
    return x @ w + b


def local_loss(params, batch):
    x, y = batch
    pred = apply(params, x)
    return jnp.mean((pred - y) ** 2)


def dp_loss(comm, params, batch):
    """Global data-parallel loss via :func:`mpi4torch_tpu.parallel.dp.dp_loss`
    (the reference's two-Allreduce recipe; the parameter-averaging Allreduce
    is load-bearing — see parallel/dp.py)."""
    from ..parallel import dp as _dp
    return _dp.dp_loss(comm, local_loss, params, batch)


def dp_train_step(comm, params, batch, lr: float = 1e-2) -> Tuple:
    """One SGD step on the data-parallel loss; returns (loss, new_params).

    Jittable under both backends; under ``run_spmd`` the whole step —
    forward, adjoint collective, update — compiles to one XLA program."""
    from ..parallel import dp as _dp
    loss, grads = _dp.dp_value_and_grad(comm, local_loss)(params, batch)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return loss, new_params

"""Utilities: eager optimizers and test helpers."""

from .lbfgs import LBFGS, minimize_lbfgs

__all__ = ["LBFGS", "minimize_lbfgs"]

// Native runtime kernels for the thread-SPMD eager executor.
//
// The reference implements its whole runtime in one C++ translation unit
// (reference: csrc/extension.cpp, 1437 LoC: MPI binding, dtype mapping,
// request-descriptor plumbing, misuse-detector hashing).  The TPU-native
// framework's compute path is XLA; what remains native here is the host
// runtime around the eager executor:
//
//  * ordered_reduce_*: fused ascending-rank-order reductions over N rank
//    buffers in ONE memory pass — the deterministic "MPI linear order"
//    oracle (BASELINE.md bit-exactness target) without N-1 sequential
//    array ops.  The fold order is identical to constants.reduce_ordered,
//    so results are bit-equal to the pure-JAX fallback.
//  * fnv1a32: the 32-bit descriptor fingerprint (the analogue of the
//    data-pointer hash the reference smuggles into its request descriptor,
//    csrc/extension.cpp:1100, re-checked at 1231-1237).
//
// Built as a plain C-ABI shared library (no pybind11) and loaded via
// ctypes; every entry point has a pure-Python fallback, so the framework
// works without a toolchain.

#include <cmath>
#include <cstdint>
#include <cstddef>

extern "C" {

// Reduction op codes — must match mpi4torch_tpu/constants.py (which in
// turn uses the reference's library-stable codes,
// csrc/extension.cpp:204-217).
enum OpCode : int32_t {
  OP_MAX = 1,
  OP_MIN = 2,
  OP_SUM = 3,
  OP_PROD = 4,
  OP_LAND = 5,
  OP_BAND = 6,
  OP_LOR = 7,
  OP_BOR = 8,
  OP_LXOR = 9,
  OP_BXOR = 10,
};

uint32_t fnv1a32(const uint8_t* data, int64_t n) {
  uint32_t h = 0x811C9DC5u;
  for (int64_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 0x01000193u;
  }
  return h & 0x7FFFFFFFu;
}

}  // extern "C" (templates below need C++ linkage)

namespace {

template <typename T>
inline T combine_arith(int32_t op, T a, T b) {
  switch (op) {
    case OP_SUM:  return a + b;
    case OP_PROD: return a * b;
    // MAX/MIN propagate NaN from either operand and resolve signed-zero
    // ties toward +0.0 (MAX) / -0.0 (MIN), matching jnp.maximum/minimum,
    // so the native path stays bit-equal to the pure-JAX fold.
    case OP_MAX:
      if (a != a) return a;
      if (b != b) return b;
      if (a == b) return std::signbit(a) ? b : a;
      return a > b ? a : b;
    case OP_MIN:
      if (a != a) return a;
      if (b != b) return b;
      if (a == b) return std::signbit(a) ? a : b;
      return a < b ? a : b;
    default:      return a;  // validated on the Python side
  }
}

template <typename T>
inline T combine_int(int32_t op, T a, T b) {
  switch (op) {
    case OP_SUM:  return a + b;
    case OP_PROD: return a * b;
    case OP_MAX:  return a > b ? a : b;
    case OP_MIN:  return a < b ? a : b;
    case OP_BAND: return a & b;
    case OP_BOR:  return a | b;
    case OP_BXOR: return a ^ b;
    case OP_LAND: return (T)((a != 0) && (b != 0));
    case OP_LOR:  return (T)((a != 0) || (b != 0));
    case OP_LXOR: return (T)((a != 0) != (b != 0));
    default:      return a;
  }
}

// Fold nbufs rank buffers elementwise in ascending rank order.  The inner
// loop runs over elements with the rank fold innermost, keeping exactly the
// same floating-point association as the sequential rank-order fold while
// touching each output element once.
template <typename T, T (*Combine)(int32_t, T, T)>
void ordered_reduce(const T* const* bufs, int32_t nbufs, int64_t n,
                    int32_t op, T* out) {
  for (int64_t i = 0; i < n; ++i) {
    T acc = bufs[0][i];
    for (int32_t r = 1; r < nbufs; ++r) {
      acc = Combine(op, acc, bufs[r][i]);
    }
    out[i] = acc;
  }
}

}  // namespace

extern "C" {

void ordered_reduce_f32(const float* const* bufs, int32_t nbufs, int64_t n,
                        int32_t op, float* out) {
  ordered_reduce<float, combine_arith<float>>(bufs, nbufs, n, op, out);
}

void ordered_reduce_f64(const double* const* bufs, int32_t nbufs, int64_t n,
                        int32_t op, double* out) {
  ordered_reduce<double, combine_arith<double>>(bufs, nbufs, n, op, out);
}

void ordered_reduce_i32(const int32_t* const* bufs, int32_t nbufs, int64_t n,
                        int32_t op, int32_t* out) {
  ordered_reduce<int32_t, combine_int<int32_t>>(bufs, nbufs, n, op, out);
}

void ordered_reduce_i64(const int64_t* const* bufs, int32_t nbufs, int64_t n,
                        int32_t op, int64_t* out) {
  ordered_reduce<int64_t, combine_int<int64_t>>(bufs, nbufs, n, op, out);
}

}  // extern "C"

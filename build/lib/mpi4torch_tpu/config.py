"""Framework configuration flags.

The reference has no config system (SURVEY.md §5: three compile-time toggles
total).  This framework adds exactly one semantic knob:

``deterministic_reductions`` — when True, SPMD-mode SUM reductions are
computed as an all-gather followed by a fixed ascending-rank-order fold,
which is bit-identical to the eager thread-SPMD oracle (the 'MPI linear
order' reference) at the cost of bandwidth; when False (default), they lower
to ``lax.psum`` — the XLA/ICI-native reduction, fastest but with
compiler-chosen combining order (ulp-level differences possible).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_state = threading.local()


def deterministic_reductions() -> bool:
    return getattr(_state, "deterministic", False)


def set_deterministic_reductions(value: bool) -> None:
    _state.deterministic = bool(value)


@contextmanager
def deterministic_mode(value: bool = True):
    prev = deterministic_reductions()
    set_deterministic_reductions(value)
    try:
        yield
    finally:
        set_deterministic_reductions(prev)

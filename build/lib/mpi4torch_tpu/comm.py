"""User-facing communicator facade.

Mirrors the reference's Python API layer (reference: src/__init__.py:89-245):
``MPI_Communicator`` with the full op-method surface, the ``COMM_WORLD``
singleton, and ``WaitHandle``.  The same facade dispatches to one of two
backends:

* **eager thread-SPMD** (Mode B, :mod:`mpi4torch_tpu.runtime`): inside
  :func:`mpi4torch_tpu.run_ranks` each rank-thread sees a concrete Python-int
  ``rank`` — the analogue of an MPI process under ``mpirun``.
* **SPMD mesh** (Mode A, :mod:`mpi4torch_tpu.ops.spmd`): inside
  ``run_spmd``/``shard_map`` over a named mesh axis, ops lower to XLA
  collectives over ICI/DCN and ``rank`` is ``lax.axis_index``.

Outside both, ``COMM_WORLD`` is a single-rank world (size 1), exactly like
running an MPI binary without ``mpirun``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from . import constants as C
from .ops import eager as _eager
from .runtime import RankContext, current_rank_context, effective_rank_context


class WaitHandle:
    """A wait handle, as returned by the non-blocking communication calls.

    Wraps the raw 3-tensor handle ``[descriptor, buffer, loopthrough]``
    (reference: src/__init__.py:27-40; descriptor layout
    csrc/extension.cpp:1094-1107)."""

    def __init__(self, raw_handle: List):
        self._handle = list(raw_handle)

    @property
    def dummy(self):
        """A dummy variable for use as one of the second arguments of
        :func:`JoinDummies` / :func:`JoinDummiesHandle`
        (reference: src/__init__.py:34-40)."""
        return self._handle[0]


def JoinDummies(loopthrough, dummies: Sequence):
    """Join dummy dependencies into the AD graph (reference:
    src/__init__.py:42-67, csrc/extension.cpp:989-1046).

    Forward is (almost) a no-op returning ``loopthrough``; the ``dummies``
    are tied in via an XLA optimization barrier so the communication that
    produced them can be neither reordered nor dead-code-eliminated, and in
    the backward pass each dummy receives a zero gradient that still carries
    the dependency chain."""
    ctx = current_rank_context()
    if ctx is not None or _spmd_context() is None:
        return _eager.join_dummies(loopthrough, dummies)
    from .ops import spmd as _spmd
    return _spmd.join_dummies(loopthrough, dummies)


def JoinDummiesHandle(handle: WaitHandle, dummies: Sequence) -> WaitHandle:
    """Like :func:`JoinDummies` but for :class:`WaitHandle` (reference:
    src/__init__.py:69-87): the dummies are joined onto the descriptor slot
    only."""
    raw = handle._handle
    return WaitHandle([JoinDummies(raw[0], dummies), raw[1], raw[2]])


def _spmd_context():
    from .ops import spmd as _spmd
    return _spmd.current_spmd_context()


class MPI_Communicator:
    """Communicator wrapper (reference: src/__init__.py:89-240).

    Construct via :data:`COMM_WORLD`, :func:`comm_from_mesh`, or
    :func:`comm_from_mpi4py`.  Methods with an underscore suffix are
    in-place operations in the reference; here they are functionally pure
    but keep the names and observable semantics (returned tensor, zeroed
    non-root results, reuse guard)."""

    def __init__(self, backend_resolver=None):
        self._resolver = backend_resolver

    # ------------------------------------------------------------- pickling

    def __reduce__(self):
        """Serialization, world-only (reference: csrc/extension.cpp:1283-1297
        ``def_pickle``).

        The reference serializes only ``MPI_COMM_WORLD`` — and its
        deserializer's condition is inverted, throwing precisely on the
        valid string it wrote (SURVEY.md §2.1, the documented latent bug).
        This build keeps the world-only restriction (a mesh-axis
        communicator captures live device objects that have no stable
        serialized identity) but with working semantics: the round trip
        restores the :data:`COMM_WORLD` singleton, which re-resolves its
        backend in the deserializing process."""
        if self._resolver is None:
            return (_restore_comm_world, ())
        import pickle
        raise pickle.PicklingError(
            "Unsupported communicator for serialization: only COMM_WORLD "
            "can be pickled (mesh-derived communicators hold live device "
            "references; rebuild them with comm_from_mesh after loading)")

    def __copy__(self):
        # Handle semantics: a communicator denotes a process group, it is
        # not data — copying a structure that contains one (train-state
        # pytrees, configs) must hand back the same handle, for every
        # communicator kind, decoupled from the world-only pickle rule.
        return self

    def __deepcopy__(self, memo):
        return self

    # -------------------------------------------------------------- backend

    def _backend(self):
        if self._resolver is not None:
            return self._resolver()
        return _default_resolver()

    @property
    def rank(self) -> int:
        """Rank of the local process within this communicator (reference:
        src/__init__.py:104-111).  A Python int in the eager runtime; a
        symbolic rank (materializing to ``lax.axis_index``) under SPMD
        tracing."""
        return self._backend().rank

    @property
    def size(self) -> int:
        """Number of processes in the communicator (reference:
        src/__init__.py:113-116)."""
        return self._backend().size

    # ----------------------------------------------------------- collectives

    def Allreduce(self, tensor, op: int):
        """Element-wise combine across all ranks, result on every rank
        (reference: src/__init__.py:125-152, csrc/extension.cpp:274-308).
        Only ``MPI_SUM`` is differentiable; other ops raise in backward."""
        return self._backend().allreduce(tensor, op)

    def Bcast_(self, tensor, root: int):
        """Broadcast from ``root`` (reference: src/__init__.py:154-175)."""
        return self._backend().bcast_(tensor, root)

    def Reduce_(self, tensor, op: int, root: int):
        """Reduce to ``root``; non-root results are zeroed and the input is
        consumed (reference: src/__init__.py:177-210,
        csrc/extension.cpp:405-464)."""
        return self._backend().reduce_(tensor, op, root)

    def Gather(self, tensor, gatheraxis: int, root: int):
        """Concatenate per-rank tensors along ``gatheraxis`` on ``root``;
        per-rank axis lengths may differ (reference: src/__init__.py:212-213,
        csrc/extension.cpp:497-599)."""
        return self._backend().gather(tensor, gatheraxis, root)

    def Allgather(self, tensor, gatheraxis: int):
        """Gather with the result on every rank (reference:
        src/__init__.py:215-216, csrc/extension.cpp:633-734)."""
        return self._backend().allgather(tensor, gatheraxis)

    def Scatter(self, tensor, scatteraxis: int, numelem: int, root: int):
        """Split ``root``'s tensor along ``scatteraxis``; this rank keeps
        ``numelem`` entries.  Non-root input shapes are ignored (reference:
        src/__init__.py:218-219, csrc/extension.cpp:769-884)."""
        return self._backend().scatter(tensor, scatteraxis, numelem, root)

    def Alltoall(self, tensor, gatheraxis: int, scatteraxis: int, numelem: int):
        """Combined gather/redistribute (reference: src/__init__.py:221-223,
        csrc/extension.cpp:917-987)."""
        return self._backend().alltoall(tensor, gatheraxis, scatteraxis, numelem)

    # ------------------------------------------------------------------ p2p

    def Isend(self, tensor, dest: int, tag: int) -> WaitHandle:
        """Nonblocking send (reference: src/__init__.py:225-226)."""
        return WaitHandle(self._backend().isend(tensor, dest, tag))

    def Irecv(self, tensor, source: int, tag: int) -> WaitHandle:
        """Nonblocking receive into ``tensor``'s shape (reference:
        src/__init__.py:228-229)."""
        return WaitHandle(self._backend().irecv(tensor, source, tag))

    def Wait(self, waithandle: WaitHandle):
        """Complete a nonblocking request (reference: src/__init__.py:231-232,
        csrc/extension.cpp:1220-1265)."""
        return self._backend().wait(waithandle._handle)

    def Send(self, tensor, dest: int, tag: int):
        """Blocking send = Isend + Wait (reference: src/__init__.py:234-236)."""
        b = self._backend()
        return b.wait(b.isend(tensor, dest, tag))

    def Recv(self, tensor, source: int, tag: int):
        """Blocking receive = Irecv + Wait (reference:
        src/__init__.py:238-240)."""
        b = self._backend()
        return b.wait(b.irecv(tensor, source, tag))


class _EagerBackend:
    """Binds the op table to a concrete (world, rank) thread context."""

    def __init__(self, ctx: RankContext):
        self._ctx = ctx

    @property
    def rank(self) -> int:
        return self._ctx.rank

    @property
    def size(self) -> int:
        return self._ctx.world.size

    def allreduce(self, x, op):
        return _eager.allreduce(self._ctx, x, op)

    def bcast_(self, x, root):
        return _eager.bcast_(self._ctx, x, root)

    def reduce_(self, x, op, root):
        return _eager.reduce_(self._ctx, x, op, root)

    def gather(self, x, gatheraxis, root):
        return _eager.gather(self._ctx, x, gatheraxis, root)

    def allgather(self, x, gatheraxis):
        return _eager.allgather(self._ctx, x, gatheraxis)

    def scatter(self, x, scatteraxis, numelem, root):
        return _eager.scatter(self._ctx, x, scatteraxis, numelem, root)

    def alltoall(self, x, gatheraxis, scatteraxis, numelem):
        return _eager.alltoall(self._ctx, x, gatheraxis, scatteraxis, numelem)

    def isend(self, x, dest, tag):
        return _eager.isend(self._ctx, x, dest, tag)

    def irecv(self, x, source, tag):
        return _eager.irecv(self._ctx, x, source, tag)

    def wait(self, handle):
        return _eager.wait(self._ctx, handle)


def _default_resolver():
    """COMM_WORLD backend resolution: active SPMD trace context first, then
    the current rank-thread, then the size-1 default world."""
    spmd_ctx = _spmd_context()
    if spmd_ctx is not None and current_rank_context() is None:
        from .ops import spmd as _spmd
        return _spmd.SpmdBackend(spmd_ctx)
    return _EagerBackend(effective_rank_context())


def _restore_comm_world():
    """Unpickle target: the COMM_WORLD singleton (its backend re-resolves
    in the loading process, so a communicator pickled on rank r of one run
    is THE world of whatever context deserializes it — the only portable
    meaning, and what the reference's broken deserializer intended)."""
    return COMM_WORLD


COMM_WORLD = MPI_Communicator()
"""World communicator (reference: src/__init__.py:242-245).  Resolves
dynamically: to the current rank-thread inside :func:`run_ranks`, to the
mesh axis inside ``run_spmd``, and to a size-1 world otherwise."""


def comm_from_mesh(mesh, axis_name: str) -> MPI_Communicator:
    """Adopt a foreign :class:`jax.sharding.Mesh` axis as a communicator —
    the TPU-native analogue of the reference's mpi4py/Fortran-handle interop
    (csrc/extension.cpp:168-171, src/__init__.py:247-261): the mesh is the
    process group, the named axis is the communicator."""
    from .ops import spmd as _spmd
    return _spmd.comm_from_mesh(mesh, axis_name)


def comm_from_mpi4py(comm) -> MPI_Communicator:
    """Convert an mpi4py communicator (reference: src/__init__.py:247-261).

    Provided for API parity: this framework replaces the MPI process group
    with a JAX device mesh, so mpi4py interop only applies when mpi4py is
    co-installed and the process layout matches; otherwise use
    :func:`comm_from_mesh`."""
    try:
        from mpi4py import MPI as _MPI  # noqa: F401
    except ModuleNotFoundError:
        raise RuntimeError("mpi4py is not available!")
    raise RuntimeError(
        "mpi4py interop requires an MPI-launched process layout; use "
        "comm_from_mesh(mesh, axis_name) to adopt a JAX mesh instead"
    )


def deactivate_cuda_aware_mpi_support() -> None:
    """API-parity no-op for the reference's CUDA-awareness kill-switch
    (csrc/extension.cpp:54-59, 1404-1414).  The TPU backend has no
    CUDA-aware-MPI staging decision — collectives always run device-native
    over ICI/DCN — so there is nothing to toggle; the function exists so
    reference scripts import and run unmodified."""

"""Differentiable ring transport: the CP/pipeline building block.

The reference's nonblocking trio composed into the ring pattern of its own
example (reference: examples/isend-recv-wait.py:8-13, tests/
test_nonblocking.py:10-16), with the full JoinDummies/WaitHandle token
discipline (SURVEY.md §3.4) applied internally so users get a one-call,
AD-transparent ring shift.  Backward is the mirror-image ring in the
opposite direction — gradients physically travel the reverse ring
(reference: csrc/extension.cpp:1159-1218).

Under the SPMD mesh backend each matched Isend/Irecv pair lowers to ONE
``collective_permute`` riding the ICI torus — the optimal topology mapping
for a ring on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..comm import JoinDummies, JoinDummiesHandle


def ring_shift(comm, x, shift: int = 1, tag: int = 0):
    """Send ``x`` to rank ``(rank + shift) % size``; return the tensor
    received from ``(rank - shift) % size``.

    Differentiable: the adjoint is a ring shift by ``-shift`` of the
    cotangent (the reverse-direction gradient ring).  ``shift`` must be a
    Python int (a static ring displacement)."""
    size = comm.size
    if size == 1 or shift % size == 0:
        return x
    dest = (comm.rank + shift) % size
    source = (comm.rank - shift) % size
    handle = comm.Isend(x, dest, tag)
    buf = JoinDummies(jnp.zeros_like(x), [handle.dummy])
    received = comm.Recv(buf, source, tag)
    ret = comm.Wait(JoinDummiesHandle(handle, [received]))
    return JoinDummies(received, [ret])


def halo_exchange(comm, x, halo: int, axis: int = 0, tag: int = 0):
    """Periodic halo exchange along ``axis``: returns ``x`` padded with its
    neighbors' boundary slices, shape grown by ``2 * halo`` on ``axis``.

    The distributed-stencil primitive (BASELINE.md parity config #5): rank
    r's result is ``[right edge of rank r-1 | x | left edge of rank r+1]``.
    Fully differentiable — boundary gradients flow back to the neighbor
    that owns them over the reverse ring."""
    if halo <= 0:
        raise ValueError(f"halo must be positive, got {halo}")
    n = x.shape[axis]
    if halo > n:
        raise ValueError(
            f"halo {halo} exceeds local axis length {n} (axis {axis})")

    def take(start, count):
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(start, start + count)
        return x[tuple(idx)]

    if comm.size == 1:
        left = take(n - halo, halo)
        right = take(0, halo)
    else:
        # My left neighbor's rightmost slice reaches me via a +1 ring shift;
        # my right neighbor's leftmost slice via a -1 shift.
        left = ring_shift(comm, take(n - halo, halo), 1, tag)
        right = ring_shift(comm, take(0, halo), -1, tag + 1)
    return jnp.concatenate([left, x, right], axis=axis)

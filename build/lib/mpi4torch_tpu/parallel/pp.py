"""Pipeline parallelism: microbatch transport over Isend/Irecv/Wait.

The reference ships PP as "primitives only": the differentiable nonblocking
trio plus ``JoinDummies`` ordering is exactly the stage-to-stage microbatch
transport, and the backward pass auto-generates the reverse-direction sends
(SURVEY.md §2.5 PP row; reference: csrc/extension.cpp:1048-1265,
doc/basic_usage.rst:194-457).  This module packages the discipline:

* :func:`send_activation` / :func:`recv_activation` — one hop of the
  pipeline with the full token discipline applied, returning the
  dependency token (send) or the received tensor (recv);
* :func:`pipeline_step` — a GPipe-style fill-drain schedule: stage ``r`` =
  rank ``r``, microbatches streamed through with per-microbatch tags, last
  stage computes the loss.  Each rank's *surrogate output* joins its send
  tokens, so backward on every rank triggers the mirror-image reverse
  pipeline: cotangents physically travel rank ``r+1 -> r`` on ``tag+10``
  (the reference's reverse-flow discipline, csrc/extension.cpp:1159-1218)
  and stage parameters receive their exact gradients.

The schedule runs on the eager thread-SPMD backend (per-rank programs —
pipeline stages are inherently MIMD; the reference's PP story is likewise
per-rank user programs).  On a TPU mesh the same model can instead be
pipelined with stacked stage weights + ``ppermute`` under ``shard_map``;
see doc/parallelism.md.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..comm import JoinDummies


def send_activation(comm, x, dest: int, tag: int):
    """Ship activation ``x`` to the next stage; returns the dependency
    token that MUST be joined onto the rank's differentiated output (via
    ``JoinDummies``) — that keeps the transfer on the backward path, where
    its adjoint *receives* the downstream cotangent over the network."""
    handle = comm.Isend(x, dest, tag)
    return comm.Wait(handle)


def recv_activation(comm, like, source: int, tag: int, deps: Sequence = ()):
    """Receive an activation shaped/typed like ``like`` from the previous
    stage.  ``deps`` are dependency values joined onto the receive buffer;
    they MUST include something that depends on the parameters being
    differentiated — otherwise the receive is invisible to the
    linearization, its adjoint (which sends this activation's cotangent
    back to ``source``) never runs, and the peer's backward deadlocks.
    This is the reference's recv-buffer JoinDummies discipline (reference:
    doc/basic_usage.rst:400-421, tests/test_nonblocking.py:10-16 — the
    buffer is joined with the rank's own grad-requiring send)."""
    buf = JoinDummies(jnp.zeros_like(like), list(deps)) if deps \
        else jnp.zeros_like(like)
    return comm.Recv(buf, source, tag)


def pipeline_step(comm, apply_stage: Callable[[Any, Any], Any], params,
                  microbatches: List, loss_fn: Callable[[Any, int], Any],
                  recv_like=None, tag: int = 0):
    """One training step of a GPipe fill-drain pipeline; returns
    ``(loss, grads)`` on every rank.

    Stage ``r`` = rank ``r``.  ``apply_stage(params, x) -> y`` is this
    rank's stage function with this rank's ``params``; ``microbatches``
    feed rank 0 (other ranks may pass the same list — only its length is
    used); ``loss_fn(y, i)`` reduces the last stage's output for microbatch
    ``i`` to a scalar; ``recv_like`` is an array shaped like this rank's
    incoming activation (required on ranks > 0 — static shapes are the
    XLA-native analogue of the reference's shape broadcast,
    csrc/extension.cpp:788-796).

    The returned ``loss`` is the total over microbatches, broadcast to all
    ranks; ``grads`` is the gradient of that total w.r.t. this rank's stage
    params — produced by the reverse pipeline, not by any parameter
    exchange."""
    rank, size = int(comm.rank), comm.size
    n_mb = len(microbatches)
    if size == 1:
        def solo(p):
            return sum(loss_fn(apply_stage(p, mb), i)
                       for i, mb in enumerate(microbatches))
        return jax.value_and_grad(solo)(params)
    if rank > 0 and recv_like is None:
        raise ValueError("ranks > 0 need recv_like (incoming activation "
                         "shape/dtype)")

    def surrogate(p):
        tokens = []
        total = jnp.zeros(())
        # Ties every receive to the differentiated parameters so the
        # reverse-pipeline sends appear in this rank's backward (see
        # recv_activation's docstring).
        p_dep = jax.tree.leaves(p)[0]
        for i in range(n_mb):
            t = tag + i
            if rank == 0:
                x = microbatches[i]
            else:
                x = recv_activation(comm, recv_like, rank - 1, t,
                                    deps=[p_dep] + tokens[-1:])
            y = apply_stage(p, x)
            if rank < size - 1:
                tokens.append(send_activation(comm, y, rank + 1, t))
            else:
                total = total + loss_fn(y, i)
        # Joining the send tokens keeps every transfer on the DAG path from
        # params to output — the docs' cardinal rule (all communication must
        # lie on an input->output path or backward deadlocks, reference
        # doc/basic_usage.rst:459-464).
        return JoinDummies(total, tokens) if tokens else total

    loss, grads = jax.value_and_grad(surrogate)(params)
    # Only the last stage holds the real loss; replicate it (in-place Bcast
    # keeps reference semantics: non-root inputs are overwritten).
    loss = comm.Bcast_(loss, size - 1)
    return loss, grads


def pipeline_spmd(comm, apply_stage: Callable[[Any, Any], Any],
                  stage_params, microbatches: List,
                  loss_fn: Callable[[Any, int], Any]):
    """Single-trace GPipe for the SPMD mesh backend: returns the total
    pipeline loss, identical on every rank.

    The MIMD fill-drain schedule of :func:`pipeline_step` re-expressed as
    one uniform program (SURVEY.md §7 hard part 4 — rank-dependent behavior
    becomes array masking): every rank holds its stage's params
    (``stage_params``, already sliced — e.g. ``shard_axis`` of a stacked
    ``(size, ...)`` tree), activations advance one hop per step over the
    differentiable ring (``ppermute`` on ICI), rank 0 injects microbatches,
    and the last rank's masked contributions accumulate into the loss.
    ``n_mb + size - 1`` steps total; each step's compute is live on the
    ranks inside the fill-drain window and masked elsewhere.  Gradients
    need no token plumbing: the ring transport's adjoint is the reverse
    ring, generated by ``jax.grad`` of the returned loss."""
    from .ring import ring_shift
    from ..constants import MPI_SUM

    size = comm.size
    n_mb = len(microbatches)
    rank = jnp.asarray(comm.rank)
    x = jnp.zeros_like(microbatches[0])
    total = jnp.zeros(())
    for step in range(n_mb + size - 1):
        if step < n_mb:
            x = jnp.where(rank == 0, microbatches[step], x)
        y = apply_stage(stage_params, x)
        mb_idx = step - (size - 1)
        if 0 <= mb_idx < n_mb:
            total = total + jnp.where(rank == size - 1,
                                      loss_fn(y, mb_idx), 0.0)
        if step + 1 < n_mb + size - 1:
            x = ring_shift(comm, y, 1, tag=step)
    if size > 1:
        total = comm.Allreduce(total, MPI_SUM)
    return total

"""Measure the documented lowering trade-offs on the current backend.

Three code comments in ``ops/spmd.py`` argue trade-offs from HLO text
(round-3 verdict: argued, never timed); this harness times them so the
comments can carry measured numbers:

1. **Bcast_ tree/psum crossover** (`config.bcast_tree_max_bytes`):
   sweep tensor sizes across the 256 KiB threshold, timing the
   binomial-tree lowering vs the masked-psum lowering head-to-head.
2. **Gather all-gather-then-mask cost**: Gather-to-root vs plain
   Allgather of the same shards (the overhead of masking to the root)
   and vs the theoretically cheaper psum_scatter-style adjoint path.
3. **Deterministic-reductions overhead**: the same Allreduce fwd+bwd
   step with the ordered-fold lowering vs the native psum.

Run on a TPU host (``MPI4TORCH_TPU_REAL_DEVICES=1`` irrelevant here —
this is not pytest; the script uses whatever platform JAX resolves, and
labels it).  On CPU the numbers are only a smoke check of the harness.
Emits one JSON document on stdout; per-point progress on stderr.
"""

from __future__ import annotations

import json
import sys


# Share bench.py's timing rule (every timed iteration ends with a
# device->host fetch of one element derived from every output leaf — the
# round-3 AND round-5 postmortems' hard-won measurement contract; see
# bench.py _force) rather than copy it: both harnesses must always
# measure under the same rules.
from bench import _timeit  # noqa: E402


def _note(msg):
    print(f"bench_tradeoffs: {msg}", file=sys.stderr, flush=True)


def _on_tpu():
    import jax

    return jax.devices()[0].platform == "tpu"


def bench_bcast_crossover(n):
    """Tree vs masked-psum Bcast_ lowering across sizes (bytes/step)."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.ops import spmd

    results = []
    # 16 KiB .. 16 MiB on hardware, bracketing the 256 KiB documented
    # threshold; two points on the CPU smoke path (compiles dominate).
    sweep = range(14, 25) if _on_tpu() else (16, 20)
    for log2_bytes in sweep:
        nelem = (1 << log2_bytes) // 4
        x = jnp.ones((nelem,), jnp.float32)
        point = {"bytes": nelem * 4}
        for mode, max_bytes in (("tree", 1 << 62), ("psum", 0)):
            saved = mpi.config.bcast_tree_max_bytes()
            mpi.config.set_bcast_tree_max_bytes(max_bytes)
            try:
                step = mpi.run_spmd(
                    lambda x: mpi.COMM_WORLD.Bcast_(x, 0), nranks=n)
                point[f"{mode}_s"] = _timeit(step, x, iters=10)
            finally:
                mpi.config.set_bcast_tree_max_bytes(saved)
            _note(f"bcast {point['bytes']}B {mode}: {point[f'{mode}_s']:.2e}s")
        point["tree_faster"] = point["tree_s"] < point["psum_s"]
        results.append(point)
    return results


def bench_gather_cost(n):
    """Gather-to-root (all_gather+mask lowering) vs plain Allgather."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    results = []
    for log2_bytes in ((16, 20, 24) if _on_tpu() else (16,)):
        nelem = (1 << log2_bytes) // 4
        x = jnp.ones((nelem,), jnp.float32)
        gather = mpi.run_spmd(
            lambda x: mpi.COMM_WORLD.Gather(x, 0, 0), nranks=n)
        allgather = mpi.run_spmd(
            lambda x: mpi.COMM_WORLD.Allgather(x, 0), nranks=n)
        g, ag = (_timeit(gather, x, iters=10),
                 _timeit(allgather, x, iters=10))
        results.append({"shard_bytes": nelem * 4, "gather_s": g,
                        "allgather_s": ag,
                        "mask_overhead": g / ag - 1.0})
        _note(f"gather {nelem * 4}B: {g:.2e}s vs allgather {ag:.2e}s")
    return results


def bench_deterministic_overhead(n):
    """Ordered-fold Allreduce vs native psum, fwd+bwd (the bit-exactness
    tax; config.py deterministic_reductions)."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import config

    nelem = ((1 << 24) if _on_tpu() else (1 << 18)) // 4
    x = jnp.ones((nelem,), jnp.float32)

    def loss(x):
        y = mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
        return jnp.vdot(y, y)

    step = mpi.run_spmd(lambda x: jax.value_and_grad(loss)(x), nranks=n)
    out = {}
    for det in (False, True):
        saved = config.deterministic_reductions()
        config.set_deterministic_reductions(det)
        try:
            out["ordered_s" if det else "native_s"] = _timeit(step, x,
                                                              iters=10)
        finally:
            config.set_deterministic_reductions(saved)
    out["tensor_bytes"] = nelem * 4
    out["overhead"] = out["ordered_s"] / out["native_s"] - 1.0
    _note(f"deterministic overhead: {out['overhead']:.1%}")
    return out


def bench_ordered_fold_paths(n):
    """Gather-fold vs chunked-ring-fold deterministic Allreduce (VERDICT r4
    item 3): both are bit-identical; this measures the memory/latency trade
    to calibrate ``config.ordered_fold_gather_max_bytes``.  Native psum is
    the
    speed-of-light reference at each size."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import config
    from mpi4torch_tpu.ops import spmd

    results = []
    for log2_bytes in ((18, 21, 24, 27) if _on_tpu() else (16, 18)):
        nelem = (1 << log2_bytes) // 4
        x = jnp.ones((nelem,), jnp.float32)
        point = {"bytes": nelem * 4}
        step = mpi.run_spmd(
            lambda x: mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM), nranks=n)
        point["psum_s"] = _timeit(step, x, iters=10)
        saved_det = config.deterministic_reductions()
        saved_thresh = config.ordered_fold_gather_max_bytes()
        config.set_deterministic_reductions(True)
        try:
            for mode, thresh in (("gather_fold", 1 << 62), ("ring_fold", 0)):
                config.set_ordered_fold_gather_max_bytes(thresh)
                step = mpi.run_spmd(
                    lambda x: mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM),
                    nranks=n)
                point[f"{mode}_s"] = _timeit(step, x, iters=10)
        finally:
            config.set_deterministic_reductions(saved_det)
            config.set_ordered_fold_gather_max_bytes(saved_thresh)
        point["ring_vs_gather"] = point["ring_fold_s"] / point["gather_fold_s"]
        _note(f"ordered fold {point['bytes']}B: gather "
              f"{point['gather_fold_s']:.2e}s ring {point['ring_fold_s']:.2e}s "
              f"psum {point['psum_s']:.2e}s")
        results.append(point)
    return results


def bench_flash_tiling(n):
    """Sweep the Pallas flash kernels' Q/KV tile sizes at the bench shape
    — the first knob to turn if the head-to-head `flash_reference_ratio`
    lands under 1.0 on chip.  Every point is oracle-checked against the
    jnp reference before it is timed (a mis-lowering must never be
    reported as a fast configuration); failures degrade to error stanzas.
    On CPU the sweep is a harness smoke over the jnp path only."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.ops import flash

    if _on_tpu():
        b, s, h, d, dtype, iters = 4, 4096, 8, 128, jnp.bfloat16, 10
        sweep = [(128, 128), (256, 128), (512, 128),
                 (128, 256), (256, 256), (512, 512)]
        impl, tol = "pallas", 2e-2
    else:
        b, s, h, d, dtype, iters = 1, 256, 2, 64, jnp.float32, 2
        sweep = [(128, 128), (256, 256)]
        impl, tol = "jnp", 1e-5

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype) for kk in keys)

    def loss_of(which):
        return lambda q, k, v: jnp.sum(flash.flash_attention(
            q, k, v, causal=True, impl=which).astype(jnp.float32) ** 2)

    ref = flash.flash_attention(q, k, v, causal=True, impl="jnp")
    gref = jax.jit(jax.grad(loss_of("jnp"), argnums=(0, 1, 2)))(q, k, v)
    results = []
    saved = (flash._Q_TILE, flash._KV_TILE)
    try:
        for qt, kt in sweep:
            flash._Q_TILE, flash._KV_TILE = qt, kt
            point = {"q_tile": qt, "kv_tile": kt}
            try:
                out = flash.flash_attention(q, k, v, causal=True, impl=impl)
                err = float(jnp.max(jnp.abs(
                    out.astype(jnp.float32) - ref.astype(jnp.float32))))
                # The timed program is fwd+bwd, so the gate must check the
                # GRADIENTS too — a mis-lowered backward (the path the
                # wide-tile _stat_tile branch feeds) must never be
                # reported as a fast configuration.
                g = jax.jit(jax.grad(loss_of(impl),
                                     argnums=(0, 1, 2)))(q, k, v)
                gerr = max(float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - b.astype(jnp.float32))))
                    for a, b in zip(g, gref))
                # Grad entries scale with the loss's 2*out factor; give
                # the same relative budget an order of magnitude slack.
                if err > tol or gerr > 50 * tol:
                    raise AssertionError(
                        f"tile ({qt},{kt}) wrong: fwd diff {err}, "
                        f"grad diff {gerr}")
                step = jax.jit(jax.value_and_grad(
                    loss_of(impl), argnums=(0, 1, 2)))
                point["fwd_bwd_s"] = _timeit(step, q, k, v, iters=iters)
                point["max_abs_diff_vs_jnp"] = err
                point["max_grad_diff_vs_jnp"] = gerr
            except Exception as e:  # noqa: BLE001 — per-point guard
                point["error"] = f"{type(e).__name__}: {str(e)[:160]}"
            results.append(point)
            _note(f"flash tiling {qt}x{kt}: {point}")
    finally:
        flash._Q_TILE, flash._KV_TILE = saved
    return results


def bench_vocab_chunk(n):
    """Sweep the chunked-vocab CE chunk width at the bench train config
    (``bench.py`` pins 4096 by analysis, never measured): time the full
    loss fwd+bwd per chunk width, plus the dense head (vocab_chunk=0 —
    the (batch, seq, vocab) logits it exists to avoid; may legitimately
    OOM on chip, its own guard records that).  On CPU this is a harness
    smoke at toy shapes."""
    import jax
    import jax.numpy as jnp

    from mpi4torch_tpu.models import transformer as T

    if _on_tpu():
        cfg = T.TransformerConfig(vocab=32768, d_model=2048, n_heads=16,
                                  n_layers=2, d_ff=8192, max_seq=2048)
        batch, dtype, iters = 8, jnp.bfloat16, 5
        sweep = (1024, 2048, 4096, 8192, 0)
    else:
        cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                  n_layers=1, d_ff=128, max_seq=64)
        batch, dtype, iters = 2, jnp.float32, 2
        sweep = (64, 0)

    params = T.init_transformer(jax.random.PRNGKey(0), cfg, dtype=dtype)
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (batch, cfg.max_seq), 0, cfg.vocab,
                                jnp.int32)
    results = []
    ref_loss = None
    for vc in sweep:
        point = {"vocab_chunk": vc}
        try:
            step = jax.jit(jax.value_and_grad(
                lambda p, _vc=vc: T.lm_loss(cfg, p, tokens,
                                            vocab_chunk=_vc)))
            # Correctness gate before the timing counts (the flash
            # sweep's rule: a mis-lowering must never be reported as a
            # fast configuration): every chunking computes the SAME
            # mathematical loss — compare each point's value against the
            # first successful one, at reduction-reassociation tolerance.
            loss = float(step(params)[0])
            point["loss"] = loss
            if ref_loss is None:
                ref_loss = loss
            rel = abs(loss - ref_loss) / max(abs(ref_loss), 1e-30)
            point["loss_rel_dev"] = rel
            tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
            if rel > tol:
                point["error"] = (f"loss deviates {rel:.2e} from the "
                                  "sweep's reference — not timing a "
                                  "mis-lowered configuration")
                results.append(point)
                _note(f"vocab_chunk {vc}: {point}")
                continue
            point["loss_fwd_bwd_s"] = _timeit(step, params, iters=iters)
        except Exception as e:  # noqa: BLE001 — per-point guard (OOM etc.)
            point["error"] = f"{type(e).__name__}: {str(e)[:200]}"
        results.append(point)
        _note(f"vocab_chunk {vc}: {point}")
    return results


def bench_native_reduce_crossover(n):
    """``_NATIVE_REDUCE_MIN_SIZE``: the fused native C ordered fold vs the
    pure-jnp fold for CPU-RESIDENT operands (constants.py:102-104 — the
    threshold only gates data already on the host, so this sweep is valid
    on any platform; operands are pinned to the CPU backend).  Both paths
    are documented bit-equal; each point cross-checks that before its
    timings count.  Host numpy is synchronous, so plain perf_counter
    brackets are a sound barrier here (no tunnel in the path)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4torch_tpu import MPI_SUM, _native
    from mpi4torch_tpu import constants as C

    if not _native.available():
        return {"skipped": "native library unavailable"}

    from contextlib import contextmanager

    @contextmanager
    def forced_path(thresh):
        saved = C._NATIVE_REDUCE_MIN_SIZE
        C._NATIVE_REDUCE_MIN_SIZE = thresh
        try:
            yield
        finally:
            C._NATIVE_REDUCE_MIN_SIZE = saved

    modes = (("native", 0), ("jnp_fold", 1 << 62))
    cpu = jax.devices("cpu")[0]
    rng = np.random.default_rng(0)
    results = []
    for log2_elems in range(8, 23, 2):          # 256 .. 4M elements
        nelem = 1 << log2_elems
        with jax.default_device(cpu):
            vals = [jnp.asarray(rng.standard_normal(nelem), jnp.float32)
                    for _ in range(8)]
            point = {"elements": nelem, "bytes": nelem * 4}
            outs = {}
            for mode, thresh in modes:
                with forced_path(thresh):
                    outs[mode] = np.asarray(C.reduce_ordered(MPI_SUM, vals))
            point["bit_equal"] = bool(
                np.array_equal(outs["native"], outs["jnp_fold"]))
            if not point["bit_equal"]:
                # Timings of a wrong kernel are not data: a point that
                # fails the bit-equality contract reports only the
                # failure (never a speedup someone might act on).
                results.append(point)
                _note(f"native_reduce {nelem} elems: BIT-EQUALITY BROKEN")
                continue
            for mode, thresh in modes:
                with forced_path(thresh):
                    iters = 30 if nelem <= (1 << 18) else 10
                    ts = []
                    for _ in range(iters):
                        t0 = time.perf_counter()
                        np.asarray(C.reduce_ordered(MPI_SUM, vals))
                        ts.append(time.perf_counter() - t0)
                    ts.sort()
                    point[f"{mode}_s"] = ts[len(ts) // 2]
            point["native_speedup"] = point["jnp_fold_s"] / point["native_s"]
        results.append(point)
        _note(f"native_reduce {nelem} elems: native {point['native_s']:.2e}s"
              f" vs jnp {point['jnp_fold_s']:.2e}s"
              f" (bit_equal={point['bit_equal']})")
    return results


def bench_reduce_scatter(n):
    """Reduce_scatter vs Allreduce-then-slice (the ZeRO gradient path;
    parallel/zero.py).  On a multi-chip mesh the native psum_scatter is
    half the allreduce's wire; on one chip both are HBM-bound but the
    slice variant still writes the full-length result first."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    results = []
    for log2_bytes in ((20, 24, 26) if _on_tpu() else (16,)):
        nelem = (1 << log2_bytes) // 4
        nelem -= nelem % n
        x = jnp.ones((nelem,), jnp.float32)
        shard = nelem // n

        def rs(x):
            return mpi.COMM_WORLD.Reduce_scatter(x, mpi.MPI_SUM, 0)

        def ar_slice(x):
            full = mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
            start = jnp.asarray(mpi.COMM_WORLD.rank) * shard
            return jax.lax.dynamic_slice_in_dim(full, start, shard, 0)

        t_rs = _timeit(mpi.run_spmd(rs, nranks=n), x, iters=10)
        t_ar = _timeit(mpi.run_spmd(ar_slice, nranks=n), x, iters=10)
        results.append({"bytes": nelem * 4, "reduce_scatter_s": t_rs,
                        "allreduce_slice_s": t_ar,
                        "speedup": t_ar / t_rs})
        _note(f"reduce_scatter {nelem * 4}B: {t_rs:.2e}s vs "
              f"allreduce+slice {t_ar:.2e}s")
    return results


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The env var alone does not stop an externally-registered TPU
        # plugin from initializing (and possibly hanging on a flaky
        # tunnel); the config update does (bench.py, same contract).
        jax.config.update("jax_platforms", "cpu")

    n = min(len(jax.devices()), 8)
    platform = jax.devices()[0].platform
    _note(f"platform={platform} devices={n}")
    result = {"platform": platform,
              "device_kind": jax.devices()[0].device_kind,
              "n_devices": n}
    for name, fn in (("bcast_crossover", bench_bcast_crossover),
                     ("gather_cost", bench_gather_cost),
                     ("deterministic", bench_deterministic_overhead),
                     ("ordered_fold_paths", bench_ordered_fold_paths),
                     ("flash_tiling", bench_flash_tiling),
                     ("native_reduce_crossover", bench_native_reduce_crossover),
                     ("vocab_chunk", bench_vocab_chunk),
                     ("reduce_scatter", bench_reduce_scatter)):
        try:
            result[name] = fn(n)
        except Exception as e:  # noqa: BLE001 — partial results still print
            result[name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()

"""`python -m mpi4torch_tpu.compress --smoke` — the quant-smoke lane.

Exercises the in-schedule quantized pipeline end to end on whatever
devices are attached (the Makefile's ``quant-smoke`` target runs it on
the 8-virtual-device CPU mesh):

1. compressed-bidir BITWISE parity: the compiled Mode A q8 dual-ring
   allreduce against :func:`mpi4torch_tpu.constants.reduce_q8_hop` — the
   eager fold oracle that IS Mode B's side of the parity contract — for
   ``q8`` and the stochastic per-hop-EF ``q8_ef_hop`` codec, plus the
   striped ``torus`` leg on factorable worlds;
2. HLO census: the lowered q8-bidir program must carry int8
   collective_permutes on BOTH source_target_pairs rotations of the
   dual ring (the tentpole's census criterion);
3. hop-kernel equivalence: the Pallas dequant→accumulate→requant kernel
   (interpret mode off-TPU) against the jnp fallback, bit for bit,
   round-to-nearest and stochastic.

Exits non-zero on any divergence, so the lane is a real check, not a
demo.
"""

from __future__ import annotations

import sys


def _smoke() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import constants as C
    from mpi4torch_tpu._compat import shard_map
    from mpi4torch_tpu.compress import get_codec
    from mpi4torch_tpu.ops import quant_kernels as qk

    comm = mpi.COMM_WORLD
    n = len(jax.devices())
    print(f"quant-smoke: {n} device(s), platform "
          f"{jax.devices()[0].platform}")
    if n < 2:
        print("FAIL: the compressed-bidir check needs a multi-device "
              "world — run via `make quant-smoke` (8-virtual-device "
              "CPU mesh)")
        return 1

    rng = np.random.default_rng(0)
    data = rng.standard_normal((n, 700)).astype(np.float32) * 3.0
    stacked = jnp.asarray(data)
    rows = [jnp.asarray(d) for d in data]
    block = get_codec("q8").base().block

    def spmd(codec, algo):
        def fn(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression=codec,
                                  algorithm=algo)

        return np.asarray(mpi.run_spmd(fn, nranks=n)(stacked))

    combos = [("q8", "bidir", None), ("q8_ef_hop", "bidir", None)]
    try:
        from mpi4torch_tpu.tune import resolve_hier_group

        combos.append(("q8", "torus", resolve_hier_group(n)))
    except Exception:
        print(f"torus leg skipped: {n} ranks have no 2-level "
              "factorization")
    for codec, algo, inner in combos:
        base = get_codec(codec).base()
        got = spmd(codec, algo)
        want = np.asarray(C.reduce_q8_hop(
            rows, block=block, algorithm=algo, inner=inner,
            stochastic=getattr(base, "stochastic", False),
            hop_ef=getattr(base, "hop_ef", False)))
        for r in range(n):
            if not np.array_equal(got[r], want):
                print(f"FAIL: Mode A {codec}-on-{algo} diverges from the "
                      f"fold oracle on rank {r}")
                return 1
        print(f"parity: {codec}-on-{algo} == reduce_q8_hop oracle "
              "(bitwise, all ranks)")

    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    lowered = jax.jit(shard_map(
        lambda a: cm.Allreduce(a, mpi.MPI_SUM, compression="q8",
                               algorithm="bidir"),
        mesh=mesh, in_specs=P(), out_specs=P(),
        check_vma=False)).lower(jnp.ones((1 << 12,), jnp.float32)).as_text()
    from mpi4torch_tpu.compress import int8_rotation_census

    perms, fwd, bwd = int8_rotation_census(lowered, n)
    if fwd not in perms or bwd not in perms:
        print(f"FAIL: int8 permutes must ride both dual-ring rotations; "
              f"saw {sorted(perms)}")
        return 1
    print("census: int8 collective_permutes on both source_target_pairs "
          "rotations of the q8-bidir dual ring")

    q = jnp.asarray(rng.integers(-127, 128, (300, block)), jnp.int8)
    # wire scales are power-of-two by construction (qk.po2_scale) — the
    # exactness that makes kernel/fallback bit-identity possible at all
    scale = qk.po2_scale(jnp.asarray(
        rng.uniform(0.01, 2.0, (300,)), jnp.float32))
    mine = jnp.asarray(rng.standard_normal((300, block)), jnp.float32)
    noise = qk.hop_noise(qk.schedule_key(0, 0, 0), 300, block)
    for label, nz in (("round-to-nearest", None), ("stochastic", noise)):
        a = qk.dequant_accum_requant(q, scale, mine, noise=nz,
                                     want_resid=True, impl="pallas")
        b = qk.dequant_accum_requant(q, scale, mine, noise=nz,
                                     want_resid=True, impl="jnp")
        for name, av, bv in zip(("q", "scale", "resid"), a, b):
            if not np.array_equal(np.asarray(av), np.asarray(bv)):
                print(f"FAIL: Pallas hop kernel vs jnp fallback diverge "
                      f"on {name} ({label})")
                return 1
    print("kernel: Pallas hop (interpret off-TPU) == jnp fallback "
          "(bitwise, incl. stochastic rounding + residual)")
    print("quant-smoke: OK")
    return 0


def main(argv) -> int:
    if "--smoke" in argv:
        return _smoke()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Mode A: compressed collectives over a shard_map mesh axis.

The AllReduce pipeline is the EQuARX shape (arxiv 2506.17615): quantize →
ring reduce-scatter in low precision → dequantize → all-gather of the
encoded shards.  Each ring hop ships the *encoded* partial sum (int8
payload + per-block scales for ``q8``; bf16 words for the bf16 family)
through ``lax.ppermute`` and re-quantizes after accumulating in f32, so
bytes-on-wire drop by the codec ratio on every link; the final all-gather
also travels encoded, and every rank decodes the same gathered payload —
making the result bit-identical across ranks by construction (the same
invariant the exact ``_ring_fold_*`` machinery in ops/spmd.py provides).

AD transparency is preserved the same way as the exact ops: each public
op is a ``jax.custom_vjp`` whose backward is *itself a compressed
collective* — the adjoint of a compressed sum-AllReduce is a compressed
sum-AllReduce of the cotangents, the adjoint of a compressed Allgather is
a compressed reduce-scatter (the paper's adjoint-is-a-collective
invariant, SURVEY.md §2.2, carried over to the quantized wire).

Ring schedule (chunk ``c`` is delivered, fully reduced, to rank ``c``):
at step ``s`` rank ``r`` sends the partial of chunk ``(r - 1 - s) mod n``
and receives the partial of chunk ``(r - 2 - s) mod n``, adding its own
contribution — ``n - 1`` hops, unrolled statically (axis sizes on a TPU
slice axis are O(tens); a ``lax.scan`` form like ops/spmd.py's
``_ring_fold_*`` is the scaling follow-up when slices grow).

Stochastic codecs (``bf16r``) get a per-rank, per-hop PRNG key (base key
folded with ``lax.axis_index``, the hop counter, and a fingerprint of the
encoded values) so rounding noise is independent across contributions;
correlated noise would bias the sum.  See :func:`_hop_key` for the
traced-program limitation on identical repeated inputs.

The block-q8 codec family (``Codec.hop_fused``: ``q8``, ``q8_ef``,
``q8_ef_hop``) takes the IN-SCHEDULE pipeline instead
(:func:`_fused_channel`): the payload stays encoded on the wire
end-to-end, and each ring hop runs dequantize → accumulate →
requantize-with-fresh-block-scales as ONE fused op
(ops/quant_kernels.py — a Pallas TPU kernel with a bit-identical jnp
fallback, dispatched by ``config.quant_hop_impl``), so block scales
travel with their chunks and precision loss stops compounding across
hops (EQuARX §3.2).  These codecs also ride the multipath bandwidth
tier: ``bidir`` runs the quantized ring on each counter-rotating half
(int8 permutes on BOTH link rotations), ``torus`` on each transposed
grid walk (:func:`constants.multipath_ring_orders` is the shared
channel rule).  The eager backend folds the SAME schedule through
:func:`constants.reduce_q8_hop`, so Mode A and Mode B are BIT-identical
per (algorithm × codec) — including the schedule-keyed stochastic
``q8_ef_hop``, whose per-hop rounding noise is a pure function of
(salt, hop, rank) shared between the compiled pipeline and the oracle.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import config as _config
from .. import constants as C
from ..ops import quant_kernels as _qk
from ..runtime import CommError
from .codecs import Codec


def _hop_key(codec: Codec, axis_name: str, salt: int,
             data=None) -> Optional[jax.Array]:
    """Per-rank, per-hop PRNG key for stochastic codecs; when ``data`` is
    given, a value fingerprint (bitcast of its f32 sum) is folded in so
    different payloads round with different noise.  Limitation, by
    construction: a traced program has no step counter, so re-executing
    the SAME compiled collective on the IDENTICAL tensor reuses the same
    rounding noise — exact-constant accumulation degenerates to
    deterministic rounding on this backend (the eager backend advances a
    real per-call counter; see compress/eager.py)."""
    if not getattr(codec, "stochastic", False):
        return None
    key = jax.random.fold_in(jax.random.PRNGKey(0), salt)
    key = jax.random.fold_in(key, lax.axis_index(axis_name))
    if data is not None:
        fp = lax.bitcast_convert_type(
            jnp.sum(jnp.asarray(data, jnp.float32)), jnp.uint32)
        key = jax.random.fold_in(key, fp)
    return key


def _tree_ppermute(payload, axis_name: str, ring):
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm=ring), payload)


def _tree_all_gather(payload, axis_name: str):
    return jax.tree_util.tree_map(
        lambda a: lax.all_gather(a, axis_name, axis=0, tiled=False), payload)


def _tree_index(payload, r: int):
    return jax.tree_util.tree_map(lambda a: a[r], payload)


def _ring_reduce_scatter_chunks(ctx, xc, codec: Codec, salt: int,
                                track_err: bool = False):
    """Quantized ring reduce-scatter over pre-chunked data.

    ``xc``: (n, m) f32 — row ``c`` is this rank's contribution to chunk
    ``c``.  Returns ``(part, err)``: the (m,) f32 fully-reduced chunk
    owned by this rank (chunk ``r`` lands on rank ``r``) and, when
    ``track_err``, an (n, m) buffer holding THIS rank's quantization
    residual per hop, stored at the row of the chunk it encoded (the hops
    encode pairwise-distinct chunks, so rows never collide).  Every hop
    encodes the running partial, permutes the payload one step along the
    ring, decodes, and accumulates in f32 — low precision on the wire,
    full precision in the accumulator."""
    n = ctx.size
    axis = ctx.axis_name
    idx = lax.axis_index(axis)
    ring = [(i, (i + 1) % n) for i in range(n)]

    err = jnp.zeros_like(xc) if track_err else None
    part = lax.dynamic_index_in_dim(xc, (idx - 1) % n, 0, keepdims=False)
    for s in range(n - 1):
        payload, meta = codec.encode(part, _hop_key(codec, axis,
                                                    salt * 1000 + s,
                                                    data=part))
        if track_err:
            err = lax.dynamic_update_index_in_dim(
                err, part - codec.decode(payload, meta),
                (idx - 1 - s) % n, axis=0)
        recv = _tree_ppermute(payload, axis, ring)
        c = (idx - 2 - s) % n
        mine = lax.dynamic_index_in_dim(xc, c, 0, keepdims=False)
        part = mine + codec.decode(recv, meta)
    return part, err


def _allreduce_round(ctx, x, codec: Codec, salt: int,
                     track_err: bool = False):
    """One compressed sum-AllReduce round: chunk → quantized ring
    reduce-scatter → encoded all-gather → decode & reassemble.

    With ``track_err``, also returns this rank's total quantization
    residual as a tensor of ``x``'s shape: every encode the rank
    performed (ring hops + the final gather encode) contributes
    ``value - decode(encode(value))`` at the chunk it encoded.  Summing
    the per-rank residuals over ranks reproduces the round's entire
    first-order error — that sum is exactly what the error-feedback
    round transfers."""
    n = ctx.size
    shape, dtype = x.shape, x.dtype
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    total = flat.size
    seg = -(-max(total, 1) // n)
    pad = seg * n - total
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    xc = flat.reshape(n, seg)

    part, err = _ring_reduce_scatter_chunks(ctx, xc, codec, salt,
                                            track_err=track_err)

    payload, meta = codec.encode(part, _hop_key(codec, ctx.axis_name,
                                                salt * 1000 + n,
                                                data=part))
    gathered = _tree_all_gather(payload, ctx.axis_name)
    pieces = [codec.decode(_tree_index(gathered, r), meta) for r in range(n)]
    out = jnp.concatenate(pieces)[:total]
    out = out.reshape(shape).astype(dtype)
    if not track_err:
        return out
    idx = lax.axis_index(ctx.axis_name)
    err = lax.dynamic_update_index_in_dim(
        err, lax.dynamic_index_in_dim(err, idx, 0, keepdims=False)
        + (part - codec.decode(payload, meta)), idx, axis=0)
    resid = err.reshape(-1)[:total].reshape(shape).astype(dtype)
    return out, resid


def _fused_channel(ctx, flat, codec: Codec, salt: int, sigma, d: int,
                   track: bool):
    """One in-schedule quantized ring channel on flat f32 data: block-q8
    ring reduce-scatter whose payload (int8 blocks + per-block f32
    scales) stays encoded on the wire end-to-end, with the
    dequantize→accumulate→requantize of every hop fused into one kernel
    pass (:func:`ops.quant_kernels.dequant_accum_requant` — fresh block
    scales per hop, so error never compounds through stale scales).

    ``sigma``/``d`` give the ring walk (position → rank permutation and
    step direction — :func:`constants.multipath_ring_orders`); the final
    hop's requant IS the wire encode, so the trailing all-gather ships
    the already-encoded chunks and every rank decodes the same payload
    (bit-identical results across ranks by construction).

    For the stochastic ``q8_ef_hop`` codec, each hop's rounding noise
    comes from the schedule key (salt × hop × rank) as a kernel
    OPERAND, and the hop's quantization residual is carried on the
    encoding rank and folded into its next in-schedule contribution
    (per-hop error feedback at single-round wire cost).  With ``track``
    (the ``q8_ef`` residual round), every residual this rank produced is
    recorded at the row of the chunk it encoded instead.

    Returns ``(reduced_flat, residual_flat|None)``.  Bit-for-bit
    mirrored by :func:`constants._sim_quant_ring` — the Mode B oracle;
    any change here must change there."""
    n = ctx.size
    axis = ctx.axis_name
    idx = lax.axis_index(axis)
    total = flat.size
    block = codec.block
    xcb, nb = _qk.chunk_blocks(flat, n, block)
    if sigma is None:
        pos = idx
        perm = [(p, (p + d) % n) for p in range(n)]
        sig = list(range(n))
    else:
        sig = list(sigma)
        inv = [0] * n
        for p, r in enumerate(sig):
            inv[r] = p
        pos = jnp.asarray(inv)[idx]
        perm = [(sig[p], sig[(p + d) % n]) for p in range(n)]
    stochastic = getattr(codec, "stochastic", False)
    hop_ef = getattr(codec, "hop_ef", False)

    def noise(t):
        if not stochastic:
            return None
        return _qk.hop_noise(_qk.schedule_key(salt, t, idx), nb, block)

    c0 = (pos - d) % n
    mine0 = lax.dynamic_index_in_dim(xcb, c0, 0, keepdims=False)
    q, s = _qk.requant_blocks(mine0, noise(0))
    err = jnp.zeros_like(xcb) if track else None
    carry = None
    if hop_ef or track:
        res = _qk.block_residual(mine0, q, s)
        if hop_ef:
            carry = res
        if track:
            err = lax.dynamic_update_index_in_dim(err, res, c0, 0)
    for t in range(1, n):
        q = lax.ppermute(q, axis, perm=perm)
        s = lax.ppermute(s, axis, perm=perm)
        c = (pos - d * (t + 1)) % n
        mine = lax.dynamic_index_in_dim(xcb, c, 0, keepdims=False)
        if hop_ef:
            mine = mine + carry
        q, s, res = _qk.dequant_accum_requant(
            q, s, mine, noise=noise(t), want_resid=hop_ef or track)
        if hop_ef:
            carry = res
        if track:
            err = lax.dynamic_update_index_in_dim(err, res, c, 0)
    gq = lax.all_gather(q, axis, axis=0, tiled=False)
    gs = lax.all_gather(s, axis, axis=0, tiled=False)
    pieces = [(gq[sig[c]].astype(jnp.float32)
               * gs[sig[c]][:, None]).reshape(-1) for c in range(n)]
    out = jnp.concatenate(pieces)[:total]
    resid = err.reshape(-1)[:total] if track else None
    return out, resid


def _fused_allreduce_value(ctx, x, codec: Codec, algorithm: str,
                           reverse: bool):
    """Block-q8 allreduce on the in-schedule pipeline, composed over the
    multipath channels of ``algorithm`` and the codec's error-feedback
    rounds.  Each channel is an independent quantized ring on its
    element range (disjoint halves at ``constants.multipath_split``);
    ``q8_ef`` residual rounds ride the same channel as the values they
    correct.  ``reverse`` swaps ``bidir``'s channel directions (the
    backward pass).

    Since ISSUE 14 this hand-composed form is the bit-identity
    REFERENCE (`make ir-smoke` pins it): production traffic routes
    through the IR instead — :func:`_allreduce_value` rewrites the
    algorithm's exact program with per-step ``q8_ring_channel`` steps
    (csched.rewrite_codec) and lowers them through the one emitter,
    whose channel bodies are this module's :func:`_fused_channel`."""
    base = codec.base()
    n = ctx.size
    shape, dtype = x.shape, x.dtype
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    total = flat.size
    inner = None
    if algorithm == "torus":
        from .. import tune as _tune
        inner = _tune.resolve_hier_group(n)
    orders = C.multipath_ring_orders(n, algorithm, inner=inner,
                                     reverse=reverse)
    m = C.multipath_split(total) if len(orders) > 1 else total
    outs = []
    for k, (sigma, d) in enumerate(orders):
        if k > 0 and m >= total:
            break
        part = flat[:m] if k == 0 else flat[m:]
        out, resid = _fused_channel(ctx, part, base, _qk.ring_salt(0, k),
                                    sigma, d, track=codec.ef_rounds > 1)
        for r in range(1, codec.ef_rounds):
            last = r == codec.ef_rounds - 1
            more, resid = _fused_channel(ctx, resid, base,
                                         _qk.ring_salt(r, k), sigma, d,
                                         track=not last)
            out = out + more
        outs.append(out)
    flat_out = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    return flat_out.reshape(shape).astype(dtype)


def _allreduce_value(ctx, x, codec: Codec, algorithm: str = "ring",
                     reverse: bool = False):
    if ctx.size == 1:
        return x
    base = codec.base()
    if getattr(base, "hop_fused", False):
        # The in-schedule pipeline as a PROGRAM REWRITE: the exact
        # algorithm's IR program with every multipath channel replaced
        # by a q8_ring_channel step, lowered by the one csched emitter
        # — bit-identical to _fused_allreduce_value (pinned by
        # `make ir-smoke`), with the per-algorithm channel forks gone.
        from .. import csched

        prog = csched.q8_allreduce_program(algorithm, ctx.size,
                                           codec.name, base.block,
                                           reverse=reverse)
        return csched.lower_q8_allreduce(prog, ctx, x, codec)
    if codec.ef_rounds <= 1:
        return _allreduce_round(ctx, x, base, salt=0)
    # In-call error feedback: round 1 tracks every quantization residual
    # this rank produced (ring hops + final gather encode); their
    # cross-rank sum IS the round's first-order error, so transferring
    # the residuals through a second compressed round cancels it
    # (EF-SGD, Karimireddy et al. 2019, folded into the collective so
    # ``compression="q8_ef"`` needs no carried state).  Remaining error
    # is second-order: the residual round's own quantization of
    # already-small values.
    y, resid = _allreduce_round(ctx, x, base, salt=0, track_err=True)
    for round_idx in range(1, codec.ef_rounds - 1):
        more, resid = _allreduce_round(ctx, resid, base, salt=round_idx,
                                       track_err=True)
        y = y + more
    return y + _allreduce_round(ctx, resid, base,
                                salt=codec.ef_rounds - 1)


def _reduce_scatter_value(ctx, g, ax: int, codec: Codec):
    """Compressed sum-reduce-scatter along ``ax`` (equal segments): the
    adjoint of the compressed Allgather.  Delivers segment ``r`` of the
    cross-rank sum to rank ``r`` via the quantized ring — no full-tensor
    broadcast.  Error-feedback rounds are honored like the forward: the
    tracked hop residuals ride a further quantized ring, so a ``q8_ef``
    Allgather's gradients are as tight as its values (no silent
    downgrade of the backward to the single-round base)."""
    n = ctx.size
    if n == 1:
        return g
    if g.shape[ax] % n != 0:
        raise CommError(
            f"compressed reduce-scatter axis {ax} length {g.shape[ax]} "
            f"must be divisible by the communicator size {n}")
    base = codec.base()
    m = g.shape[ax] // n
    gm = jnp.moveaxis(g, ax, 0)
    rest = gm.shape[1:]
    xc = jnp.asarray(gm, jnp.float32).reshape(n, m * math.prod(rest))
    track = codec.ef_rounds > 1
    part, err = _ring_reduce_scatter_chunks(ctx, xc, base, salt=7,
                                            track_err=track)
    for round_idx in range(1, codec.ef_rounds):
        # ``err`` holds this rank's per-hop residuals at the rows of the
        # chunks it encoded; rechunking it row-for-row feeds the same
        # segment partition, so the residual ring delivers each rank the
        # correction for ITS segment.  (The delivered chunk itself is
        # never re-encoded, so no final-encode residual exists here.)
        last = round_idx == codec.ef_rounds - 1
        more, err = _ring_reduce_scatter_chunks(ctx, err, base,
                                                salt=7 + round_idx,
                                                track_err=not last)
        part = part + more
    seg = part.reshape((m,) + rest).astype(g.dtype)
    return jnp.moveaxis(seg, 0, ax)


def _allgather_round(ctx, x, ax: int, codec: Codec, salt: int):
    n = ctx.size
    payload, meta = codec.encode(x, _hop_key(codec, ctx.axis_name, salt,
                                             data=x))
    gathered = _tree_all_gather(payload, ctx.axis_name)
    pieces = [codec.decode(_tree_index(gathered, r), meta) for r in range(n)]
    return jnp.concatenate(pieces, axis=ax)


def _allgather_value(ctx, x, ax: int, codec: Codec):
    if ctx.size == 1:
        return x
    base = codec.base()
    out = _allgather_round(ctx, x, ax, base, salt=11)
    for round_idx in range(1, codec.ef_rounds):
        key = _hop_key(base, ctx.axis_name, -100 - round_idx)
        resid = jnp.asarray(x, jnp.float32) \
            - jnp.asarray(base.roundtrip(x, key), jnp.float32)
        resid = resid.astype(x.dtype)
        out = out + _allgather_round(ctx, resid, ax, base,
                                     salt=11 + round_idx)
    return out


def _bwd_scope(opname: str, codec: Codec):
    return jax.named_scope(f"mpi4torch.{opname}Backward.{codec.name}")


def resolve_algorithm(ctx_size: int, x, codec: Codec, algorithm,
                      algorithm_explicit: bool) -> str:
    """Concrete wire algorithm for a compressed collective: ``None`` =
    codec-aware auto selection (the tune selector restricted to the
    algorithms the codec declares — so ``auto`` picks the compressed
    ``bidir`` at/above the measured bandwidth crossover); named requests
    arrive pre-reconciled by the facade (``Codec.algorithms`` ×
    ``AlgorithmSpec.codec_capable``).  ``torus`` additionally validates
    the 2-level group rule against THIS communicator (a set
    ``config.hier_group_size`` can void the registry's static gate):
    explicit requests raise, scope/auto picks degrade to ``ring`` — the
    standard rule.  Non-hop-fused codecs pin ``ring`` (their pipeline is
    the generic encoded ring; the facade never routes them elsewhere)."""
    if not getattr(codec.base(), "hop_fused", False):
        return "ring"
    algo = algorithm
    if algo is None:
        from .. import tune as _tune

        xa = jnp.asarray(x)
        algo = _tune.select_auto(
            collective="allreduce",
            nbytes=xa.size * xa.dtype.itemsize, dtype=xa.dtype,
            nranks=ctx_size,
            deterministic=_config.deterministic_reductions(),
            codec=codec)
    if algo == "torus" and ctx_size > 1:
        from .. import tune as _tune

        try:
            _tune.resolve_hier_group(ctx_size)
        except CommError:
            if algorithm_explicit:
                raise
            algo = "ring"
    return algo


def allreduce(ctx, x, op: int, codec: Codec, algorithm=None,
              algorithm_explicit: bool = False):
    """Compressed SPMD Allreduce.  Sum-only (quantized partial-sum
    accumulation has no meaning for MAX/bitwise ops — use the exact
    path); the adjoint is the same compressed collective applied to the
    cotangents, so gradients ride the int8/bf16 wire too.

    ``algorithm`` picks the wire schedule among the codec's declared
    set: the block-q8 family rides ``ring``/``bidir``/``torus`` through
    the in-schedule pipeline (``None`` = codec-aware auto selection);
    the backward uses the MATCHING schedule — ``bidir``'s adjoint swaps
    the two chains' directions, like the exact multipath backward."""
    if op != C.MPI_SUM:
        raise CommError(
            f"compressed Allreduce supports MPI_SUM only; got "
            f"{C.op_name(op)} — drop compression= for non-sum reductions")
    # Finite guard hook (mpi4torch_tpu.resilience): off = x untouched,
    # zero added ops; a non-finite gradient entering the quantized
    # pipeline would otherwise saturate block scales silently.
    from ..resilience import guards as _guards
    x = _guards.spmd_finite_value(x, f"Allreduce[{codec.name}]")
    # Mode A step-event hook (mpi4torch_tpu.obs) — the compressed
    # pipeline's entry reports with its codec label; zero ops when no
    # mode_a tracer is installed (see ops/spmd.py allreduce).
    from ..obs.trace import spmd_collective_event
    x = spmd_collective_event(x, f"Allreduce[{codec.name}]")
    algo = resolve_algorithm(ctx.size, x, codec, algorithm,
                             algorithm_explicit)

    @jax.custom_vjp
    def f(v):
        return _allreduce_value(ctx, v, codec, algo)

    def bwd(_, g):
        with _bwd_scope("Allreduce", codec):
            return (_allreduce_value(ctx, g, codec, algo, reverse=True),)

    f.defvjp(lambda v: (_allreduce_value(ctx, v, codec, algo), None), bwd)
    return f(x)


def allgather(ctx, x, gatheraxis: int, codec: Codec):
    """Compressed SPMD Allgather: the local shard travels encoded through
    one ``lax.all_gather``; every rank decodes the same payload (results
    bit-identical across ranks).  Adjoint: compressed reduce-scatter of
    the cotangents — itself a collective on the quantized wire."""
    from ..ops.eager import _norm_axis

    ax = _norm_axis(gatheraxis, jnp.ndim(x))

    @jax.custom_vjp
    def f(v):
        return _allgather_value(ctx, v, ax, codec)

    def bwd(_, g):
        with _bwd_scope("Allgather", codec):
            return (_reduce_scatter_value(ctx, g, ax, codec),)

    f.defvjp(lambda v: (_allgather_value(ctx, v, ax, codec), None), bwd)
    return f(x)

"""Compressed collectives: AD-transparent block-scaled quantized wire.

The dominant cost of collectives at scale is bytes over ICI/DCN; this
package cuts them with wire-compression codecs while preserving the
framework's core invariant — the backward pass of every compressed
collective is itself a compressed collective (the paper's
adjoint-is-a-collective property, on a quantized wire).  Design
references: EQuARX (arxiv 2506.17615, block-scaled quantized AllReduce
native to XLA) and "The Big Send-off" (arxiv 2504.18658, per-topology
tunability — hence the codec registry, which later topology-aware
autotuning plugs into).

Usage — pick a codec per call, per scope, or process-wide::

    y = comm.Allreduce(g, mpi.MPI_SUM, compression="q8")

    with mpi.config.compression_scope("q8_ef"):
        y = comm.Allreduce(g, mpi.MPI_SUM)          # scope default

    mpi.config.set_default_compression("bf16")      # process default

Both backends honor the same argument: under ``run_spmd``/``shard_map``
(Mode A) the op lowers to the quantized ring reduce-scatter + encoded
all-gather pipeline (compress/spmd.py, int8-width transfers visible in
the lowered HLO and in profiler traces as ``mpi4torch.Allreduce.q8``
spans); under ``run_ranks`` (Mode B) the codec runs at the rendezvous
(compress/eager.py), so parity tests cover the same codec code path.

Modules: :mod:`.codecs` (registry + q8/bf16/bf16r/q8_ef),
:mod:`.spmd` (Mode A pipeline), :mod:`.eager` (Mode B rendezvous codec),
:mod:`.ef` (cross-step error-feedback state for training loops).
"""

from __future__ import annotations

from ..config import (compression_scope, default_compression,
                      set_default_compression)
from .codecs import (BF16Codec, BF16StochasticCodec, BlockQ8Codec, Codec,
                     ErrorFeedbackCodec, HopEFQ8Codec, available_codecs,
                     get_codec, register_codec)
from .ef import ef_allreduce, ef_init


def codec_rides_algorithm(codec, algorithm) -> bool:
    """THE codec/algorithm composition predicate: True when ``codec``
    may ride wire algorithm ``algorithm``.  Consulted dynamically on
    BOTH sides — the codec's own declaration (``Codec.algorithms``: the
    block-q8 family declares ring/bidir/torus, the bf16 family is
    ring-only) and the registry's (``AlgorithmSpec.codec_capable``:
    only the ring-shaped schedules can host a per-hop requantizing
    pipeline) — so registering a new codec or algorithm extends or
    restricts composition without touching this gate.  One shared rule
    for the facade reconcile (comm._reconcile_codec_algorithm), the
    tune selector, and the fused per-bucket picker."""
    if codec is None:
        return False
    from ..tune import codec_algorithms, get_algorithm

    if algorithm not in codec_algorithms(codec):
        return False
    return get_algorithm(algorithm).codec_capable


def codec_applicable(codec, dtype, algorithm=None) -> bool:
    """True when ``codec`` may legally touch a tensor of ``dtype`` (and,
    when ``algorithm`` is given, ride that wire algorithm).

    Quantizing integer/bool payloads (counts, masks, descriptors) would
    silently truncate rather than approximate, so only floating tensors
    are compressible.  This is THE dtype gate — the facade applies it
    per tensor (comm.py ``_codec_for``) and the fused bucketed
    collectives per dtype-homogeneous bucket (fuse/collectives.py), so
    the degrade/raise behavior cannot drift between the two paths.

    The ``algorithm`` leg is :func:`codec_rides_algorithm` — the
    codec's declared set × the registry's ``codec_capable`` gate,
    consulted dynamically: the tune selector respects it when
    auto-choosing an algorithm under an active compression scope (so
    ``auto`` can pick the compressed ``bidir`` past the bandwidth
    crossover), and the fused per-bucket picker uses it to keep each
    compressed bucket on an algorithm its codec declares while exact
    tail buckets take the latency algorithm."""
    import jax.numpy as jnp

    if codec is None or not jnp.issubdtype(jnp.dtype(dtype),
                                           jnp.floating):
        return False
    if algorithm is not None:
        return codec_rides_algorithm(codec, algorithm)
    return True


def int8_rotation_census(lowered: str, nranks: int):
    """Both-rotations census of a lowered q8 dual-ring program: returns
    ``(seen, fwd, bwd)`` where ``seen`` is the set of
    ``source_target_pairs`` tables appearing on int8-typed
    ``collective_permute`` ops in ``lowered`` and ``fwd``/``bwd`` are
    the forward/backward full-ring tables for ``nranks`` (all
    whitespace-normalized, so ``fwd in seen and bwd in seen`` is the
    tentpole's census criterion).  ONE matcher shared by the test census
    matrix (tests/test_tune.py), the ``make quant-smoke`` lane
    (compress/__main__.py), and the bench verdict (bench.py) — the
    StableHLO pattern cannot drift between CI, the smoke lane, and the
    persisted wire table."""
    import re

    seen = set()
    for m in re.finditer(
            r'stablehlo\.collective_permute.*?'
            r'source_target_pairs\s*=\s*dense<(\[\[.*?\]\])>'
            r'.*?:\s*\(tensor<[^>]*i8>', lowered):
        seen.add(m.group(1).replace(" ", ""))
    fwd = str([[i, (i + 1) % nranks]
               for i in range(nranks)]).replace(" ", "")
    bwd = str([[i, (i - 1) % nranks]
               for i in range(nranks)]).replace(" ", "")
    return seen, fwd, bwd


__all__ = [
    "codec_applicable",
    "codec_rides_algorithm",
    "int8_rotation_census",
    "HopEFQ8Codec",
    "Codec",
    "BlockQ8Codec",
    "BF16Codec",
    "BF16StochasticCodec",
    "ErrorFeedbackCodec",
    "available_codecs",
    "get_codec",
    "register_codec",
    "compression_scope",
    "default_compression",
    "set_default_compression",
    "ef_init",
    "ef_allreduce",
]

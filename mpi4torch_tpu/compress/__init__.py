"""Compressed collectives: AD-transparent block-scaled quantized wire.

The dominant cost of collectives at scale is bytes over ICI/DCN; this
package cuts them with wire-compression codecs while preserving the
framework's core invariant — the backward pass of every compressed
collective is itself a compressed collective (the paper's
adjoint-is-a-collective property, on a quantized wire).  Design
references: EQuARX (arxiv 2506.17615, block-scaled quantized AllReduce
native to XLA) and "The Big Send-off" (arxiv 2504.18658, per-topology
tunability — hence the codec registry, which later topology-aware
autotuning plugs into).

Usage — pick a codec per call, per scope, or process-wide::

    y = comm.Allreduce(g, mpi.MPI_SUM, compression="q8")

    with mpi.config.compression_scope("q8_ef"):
        y = comm.Allreduce(g, mpi.MPI_SUM)          # scope default

    mpi.config.set_default_compression("bf16")      # process default

Both backends honor the same argument: under ``run_spmd``/``shard_map``
(Mode A) the op lowers to the quantized ring reduce-scatter + encoded
all-gather pipeline (compress/spmd.py, int8-width transfers visible in
the lowered HLO and in profiler traces as ``mpi4torch.Allreduce.q8``
spans); under ``run_ranks`` (Mode B) the codec runs at the rendezvous
(compress/eager.py), so parity tests cover the same codec code path.

Modules: :mod:`.codecs` (registry + q8/bf16/bf16r/q8_ef),
:mod:`.spmd` (Mode A pipeline), :mod:`.eager` (Mode B rendezvous codec),
:mod:`.ef` (cross-step error-feedback state for training loops).
"""

from __future__ import annotations

from ..config import (compression_scope, default_compression,
                      set_default_compression)
from .codecs import (BF16Codec, BF16StochasticCodec, BlockQ8Codec, Codec,
                     ErrorFeedbackCodec, available_codecs, get_codec,
                     register_codec)
from .ef import ef_allreduce, ef_init


def codec_applicable(codec, dtype, algorithm=None) -> bool:
    """True when ``codec`` may legally touch a tensor of ``dtype`` (and,
    when ``algorithm`` is given, ride that wire algorithm).

    Quantizing integer/bool payloads (counts, masks, descriptors) would
    silently truncate rather than approximate, so only floating tensors
    are compressible.  This is THE dtype gate — the facade applies it
    per tensor (comm.py ``_codec_for``) and the fused bucketed
    collectives per dtype-homogeneous bucket (fuse/collectives.py), so
    the degrade/raise behavior cannot drift between the two paths.

    The ``algorithm`` leg consults the codec's own declaration
    (``Codec.algorithms``; ring-only for every shipped codec — the
    quantized pipeline is a ring): the tune selector respects it when
    auto-choosing an algorithm under an active compression scope, and
    the fused per-bucket picker uses it to keep compressed buckets on
    the ring while exact tail buckets take the latency algorithm."""
    import jax.numpy as jnp

    if codec is None or not jnp.issubdtype(jnp.dtype(dtype),
                                           jnp.floating):
        return False
    if algorithm is not None and algorithm != "ring":
        from ..tune import codec_algorithms

        return algorithm in codec_algorithms(codec)
    return True


__all__ = [
    "codec_applicable",
    "Codec",
    "BlockQ8Codec",
    "BF16Codec",
    "BF16StochasticCodec",
    "ErrorFeedbackCodec",
    "available_codecs",
    "get_codec",
    "register_codec",
    "compression_scope",
    "default_compression",
    "set_default_compression",
    "ef_init",
    "ef_allreduce",
]

"""Mode B: compressed collectives for the thread-SPMD eager runtime.

The codec runs at the rendezvous: each rank encodes its tensor and ships
the *encoded* payload (plus its static meta) through ``World.exchange``,
and every rank decodes the full payload list and folds in ascending rank
order — so the semantics/parity path covers the same codec code as the
SPMD pipeline, results are bit-identical across ranks (everyone decodes
the same list with the same deterministic fold), and the misuse
detectors (signature checks, consumed-input guard, tracing rejection)
apply to compressed ops exactly as to exact ones.

Large payloads take the fold-once path the exact Allreduce uses
(ops/eager.py ``_FOLD_ONCE_MIN``): rank 0 decodes and folds once and a
second rendezvous shares the (immutable jnp) result, instead of W ranks
each decoding and folding W payloads redundantly.

Stochastic codecs (``bf16r``) fold a per-(world, rank) call counter into
their PRNG key, so repeated collectives round with fresh noise — the
unbiased-accumulation property holds across optimizer steps here (the
traced Mode A pipeline documents its weaker key schedule in
compress/spmd.py).

AD transparency matches compress/spmd.py: each op is a
``jax.custom_vjp`` whose backward is itself a compressed collective, and
the backward honors the codec's error-feedback rounds like the forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import config as _config
from .. import constants as C
from ..resilience import guards as _guards
from ..runtime import CommError, RankContext
from ..ops.eager import _FOLD_ONCE_MIN, _check_concrete, _norm_axis, \
    _shape_sig
from .codecs import Codec


def _wire_exchange(world, rank: int, sig, meta, payload, opname: str):
    """Ship an encoded wire tuple through the rendezvous, with the
    optional checksum leg (``config.comm_wire_checksum``): each rank's
    payload travels with the CRC of its wire bytes and every rank
    verifies the full list on receipt — a corrupted block (e.g. an
    injected bit-flip on the int8 wire) raises
    :class:`~mpi4torch_tpu.IntegrityError` NAMING the corrupt
    contributor instead of folding silently into everyone's result.
    Off (default): the wire tuple and signature are exactly the
    pre-checksum format.  Returns the rank-ordered ``(meta, payload)``
    list."""
    if _config.comm_wire_checksum():
        # The CRC covers meta AND payload: a corrupted block scale in
        # the meta mis-steers the decode exactly like a flipped block.
        item = (meta, payload, _guards.wire_checksum((meta, payload)))
        vals = world.exchange(rank, sig + ("crc",), item)
        return _guards.verify_wire(vals, opname)
    return world.exchange(rank, sig, (meta, payload))


def _rank_key(codec: Codec, ctx: RankContext, salt: int):
    if not getattr(codec, "stochastic", False):
        return None
    # Per-(world, rank) monotonic call counter: each rank touches only its
    # own slot, so the dict needs no lock beyond the GIL's atomic ops.
    seq = ctx.world.__dict__.setdefault("_compress_call_seq", {})
    n = seq.get(ctx.rank, 0)
    seq[ctx.rank] = n + 1
    key = jax.random.fold_in(jax.random.PRNGKey(0), salt)
    key = jax.random.fold_in(key, ctx.rank)
    return jax.random.fold_in(key, n)


def _resolve_algorithm(nranks: int, x, codec: Codec, algorithm,
                       explicit: bool) -> str:
    """Concrete algorithm for a compressed eager collective — literally
    Mode A's resolver (compress/spmd.py ``resolve_algorithm``): one
    implementation, so auto-selected compressed traffic CANNOT drift
    off the bitwise cross-mode contract (both modes consult the same
    codec-aware tune selector, crossover knobs, and torus group rule;
    the facade's ``tune.resolve_request`` has already normalized
    ``False``/``"auto"`` to ``None`` by the time either backend runs)."""
    from .spmd import resolve_algorithm

    return resolve_algorithm(nranks, x, codec, algorithm, explicit)


def _hop_oracle_allreduce(ctx: RankContext, x, codec: Codec, algo: str):
    """Compressed eager Allreduce for the block-q8 codec family: the
    ranks exchange their RAW contributions and every result comes from
    :func:`mpi4torch_tpu.constants.reduce_q8_hop` — the bit-exact
    simulation of the Mode A in-schedule pipeline (same chunk layout,
    same per-hop fresh-scale requantization, same schedule-keyed noise
    for ``q8_ef_hop``), composed over the same multipath channels and
    error-feedback rounds.  This is what makes compressed Mode A/B
    parity BITWISE per (algorithm × codec) rather than statistical.

    Rank 0 simulates once and a second rendezvous shares the (immutable
    jnp) result — unconditionally, not just above ``_FOLD_ONCE_MIN``:
    the oracle walks EVERY rank's hops (O(world × hops) jitted chunk
    sims), so even a small tensor's redundant per-rank folds cost W×
    the whole schedule, unlike the elementwise rendezvous fold whose
    cheap small-tensor folds stay local below the threshold.  The
    adjoint is the same oracle on the cotangents with ``bidir``'s
    channel directions swapped, mirroring the SPMD backward."""
    world, rank = ctx.world, ctx.rank
    world.check_not_consumed(rank, x)
    base = codec.base()
    inner = None
    if algo == "torus" and world.size > 1:
        # (one-rank collectives are the identity before the oracle runs,
        # so there is no group to resolve — same carve-out as
        # _resolve_algorithm's validation)
        from ..tune import resolve_hier_group

        inner = resolve_hier_group(world.size)

    def fold(vals, reverse):
        return C.reduce_q8_hop(
            vals, block=base.block, algorithm=algo, inner=inner,
            reverse=reverse, stochastic=getattr(base, "stochastic", False),
            hop_ef=getattr(base, "hop_ef", False),
            ef_rounds=codec.ef_rounds)

    def impl(v, reverse=False):
        _check_concrete(v)
        if world.size == 1:
            return jnp.asarray(v)
        sig = ("Allreduce.q8hop", codec.name, algo, bool(reverse),
               _shape_sig(v))
        vals = world.exchange(rank, sig, jnp.asarray(v))
        # Finite guard on the raw contributions (every rank holds the
        # same list — symmetric raise) before the hop oracle folds them.
        _guards.check_contributions(vals, f"Allreduce[{codec.name}]")
        red = fold(vals, reverse) if rank == 0 else None
        return world.exchange(rank, sig + ("fold",), red)[0]

    @jax.custom_vjp
    def f(v):
        return impl(v)

    def bwd(_, g):
        return (impl(g, reverse=(algo == "bidir")),)

    f.defvjp(lambda v: (impl(v), None), bwd)
    return f(x)


def allreduce(ctx: RankContext, x, op: int, codec: Codec,
              algorithm=None, algorithm_explicit: bool = False):
    """Compressed eager Allreduce: encoded payloads meet at the
    rendezvous; the decoded contributions fold in ascending rank order
    (once, shared, above the fold-once threshold).  Sum-only, like the
    SPMD path; the adjoint is the same compressed collective on the
    cotangents.

    The block-q8 codec family takes :func:`_hop_oracle_allreduce`
    instead — the bit-exact simulation of the Mode A in-schedule
    pipeline, on the requested ``algorithm``'s multipath channels — so
    cross-mode parity is bitwise for those codecs.  The bf16 family
    keeps the rendezvous-codec fold here (``bf16r``'s per-call noise
    counter makes its parity contract statistical by design)."""
    if op != C.MPI_SUM:
        raise CommError(
            f"compressed Allreduce supports MPI_SUM only; got "
            f"{C.op_name(op)} — drop compression= for non-sum reductions")
    algo = _resolve_algorithm(ctx.world.size, x, codec, algorithm,
                              algorithm_explicit)
    if getattr(codec.base(), "hop_fused", False):
        return _hop_oracle_allreduce(ctx, x, codec, algo)
    world, rank = ctx.world, ctx.rank
    world.check_not_consumed(rank, x)
    base = codec.base()

    def one_round(v, salt: int):
        """Returns (cross-rank sum of decoded payloads, own roundtrip)."""
        payload, meta = base.encode(v, _rank_key(base, ctx, salt))
        sig = ("Allreduce.c", codec.name, salt, _shape_sig(v))
        vals = _wire_exchange(world, rank, sig, meta, payload,
                              f"Allreduce[{codec.name}]")
        if jnp.asarray(v).size >= _FOLD_ONCE_MIN:
            # Fold-once: rank 0 decodes + folds all payloads, the result
            # (an immutable jnp array) is shared through a second
            # rendezvous; every other rank decodes only its own payload
            # (needed for the EF residual) — W-1 redundant W-way
            # decode+folds saved, mirroring ops/eager.py's exact path.
            # The finite guard runs on rank 0's full decode (the only
            # rank holding it); its typed IntegrityError becomes the
            # job's primary error through the world-failure path.
            own_m, own_p = vals[rank]
            own = base.decode(own_p, own_m)
            if rank == 0:
                decoded_all = [base.decode(p, m) for (m, p) in vals]
                _guards.check_contributions(decoded_all,
                                            f"Allreduce[{codec.name}]")
                red = C.reduce_ordered(C.MPI_SUM, decoded_all)
            else:
                red = None
            out = world.exchange(
                rank, ("Allreduce.c.fold", codec.name, salt, _shape_sig(v)),
                red)[0]
            return out, own
        decoded = [base.decode(p, m) for (m, p) in vals]
        _guards.check_contributions(decoded, f"Allreduce[{codec.name}]")
        return C.reduce_ordered(C.MPI_SUM, decoded), decoded[rank]

    def impl(v):
        _check_concrete(v)
        if world.size == 1:
            return jnp.asarray(v)
        out, own = one_round(v, 0)
        for round_idx in range(1, codec.ef_rounds):
            # In-call error feedback: sum the compressed local residuals
            # (``own`` IS this rank's roundtrip, so the residual costs no
            # extra encode).
            resid = jnp.asarray(v) - own
            more, own_r = one_round(resid, round_idx)
            out = out + more
            own = own + own_r
        return out

    @jax.custom_vjp
    def f(v):
        return impl(v)

    def bwd(_, g):
        return (impl(g),)

    f.defvjp(lambda v: (impl(v), None), bwd)
    return f(x)


def allgather(ctx: RankContext, x, gatheraxis: int, codec: Codec):
    """Compressed eager Allgather along an arbitrary axis; per-rank axis
    lengths may differ (each payload carries its own meta, like the exact
    op ships concrete arrays).  Adjoint: compressed reduce-scatter —
    every rank's cotangent ships encoded and each rank folds its own
    segment of the decoded gradients in ascending rank order, with the
    codec's error-feedback rounds honored like the forward."""
    world, rank = ctx.world, ctx.rank
    world.check_not_consumed(rank, x)
    ax = _norm_axis(gatheraxis, jnp.ndim(x))
    base = codec.base()

    def gather_round(v, salt: int):
        payload, meta = base.encode(v, _rank_key(base, ctx, salt))
        othershape = tuple(s for i, s in enumerate(v.shape) if i != ax)
        sig = ("Allgather.c", codec.name, salt, ax, othershape,
               str(jnp.asarray(v).dtype))
        vals = _wire_exchange(world, rank, sig, meta, payload,
                              f"Allgather[{codec.name}]")
        decoded = [base.decode(p, m) for (m, p) in vals]
        _guards.check_contributions(decoded, f"Allgather[{codec.name}]")
        return decoded

    def impl(v):
        _check_concrete(v)
        if world.size == 1:
            return jnp.asarray(v)
        decoded = gather_round(v, 0)
        out = jnp.concatenate(decoded, axis=ax)
        counts = tuple(d.shape[ax] for d in decoded)
        for round_idx in range(1, codec.ef_rounds):
            resid = jnp.asarray(v) - decoded[rank]
            decoded2 = gather_round(resid, round_idx)
            out = out + jnp.concatenate(decoded2, axis=ax)
            decoded = [d + d2 for d, d2 in zip(decoded, decoded2)]
        return out, counts

    def bwd_round(g, counts, salt: int):
        payload, meta = base.encode(g, _rank_key(base, ctx, salt))
        sig = ("Allgather.c.bwd", codec.name, salt, ax, _shape_sig(g))
        vals = _wire_exchange(world, rank, sig, meta, payload,
                              f"Allgather.bwd[{codec.name}]")
        offset = sum(counts[:rank])
        index = [slice(None)] * jnp.ndim(g)
        index[ax] = slice(offset, offset + counts[rank])
        pieces = [base.decode(p, m)[tuple(index)] for (m, p) in vals]
        own_m, own_p = vals[rank]
        own_full = base.decode(own_p, own_m)
        return C.reduce_ordered(C.MPI_SUM, pieces), own_full

    def bwd_impl(counts, g):
        _check_concrete(g)
        seg, own = bwd_round(g, counts, 100)
        for round_idx in range(1, codec.ef_rounds):
            resid = jnp.asarray(g) - own
            more, own_r = bwd_round(resid, counts, 100 + round_idx)
            seg = seg + more
            own = own + own_r
        return seg

    @jax.custom_vjp
    def f(v):
        out = impl(v)
        return out if world.size == 1 else out[0]

    def fwd(v):
        out = impl(v)
        if world.size == 1:
            return out, (tuple(jnp.shape(v))[ax] if jnp.ndim(v) else 1,)
        return out[0], out[1]

    def bwd(counts, g):
        if world.size == 1:
            return (g,)
        return (bwd_impl(counts, g),)

    f.defvjp(fwd, bwd)
    return f(x)

"""Wire-compression codecs: block-scaled int8 and (stochastic) bfloat16.

A codec is a pure, shape-polymorphic pair of maps

    encode(x, key=None) -> (payload, meta)      # payload: dict of arrays
    decode(payload, meta) -> x_approx           # original shape & dtype

where ``payload`` holds the arrays that actually ride the wire (the
collectives in compress/spmd.py ship its leaves through
``ppermute``/``all_gather``; compress/eager.py ships it through the
rendezvous) and ``meta`` is static Python data (shape/dtype bookkeeping)
that never leaves the host.  Codecs are deterministic given their inputs
(plus the PRNG key for stochastic codecs), so every rank decoding the
same payload reconstructs bit-identical values — the property the
all-gather stage of the compressed collectives relies on.

Shipped codecs (EQuARX, arxiv 2506.17615, is the design reference for the
block-scaled int8 family; "The Big Send-off", arxiv 2504.18658, motivates
keeping the choice per-callsite tunable):

=============  =====================================  ============  ========
name           scheme                                 wire (f32 in)  rounds
=============  =====================================  ============  ========
``q8``         per-256-block absmax-scaled int8       ~3.94x less    1
``q8_ef``      q8 + one error-feedback round          ~1.97x less    2
``q8_ef_hop``  q8 with per-hop stochastic rounding    ~3.94x less    1
               + per-hop error feedback (the hop
               residual folds into this rank's next
               in-schedule contribution)
``bf16``       round-to-nearest bfloat16              2x less        1
``bf16r``      stochastic-rounded bfloat16 (keyed)    2x less        1
=============  =====================================  ============  ========

The registry is the extension point the ROADMAP's topology-aware
autotuning will plug into: register a codec object under a name and every
facade op accepts ``compression="<name>"``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Payload = Dict[str, Any]
Meta = Tuple


def _default_key():
    return jax.random.PRNGKey(0)


@dataclasses.dataclass(frozen=True)
class Codec:
    """Base codec: identity behaviour hooks plus the registry contract.

    ``ef_rounds`` > 1 marks an error-feedback codec: the collectives run
    the base scheme, then compress-and-sum the local quantization
    residuals in a second round (in-call error feedback), which cancels
    the first-order quantization error of the sum.  ``stochastic`` codecs
    consume a PRNG key per encode; the collectives derive per-rank,
    per-hop keys so rounding noise is independent across contributions
    (correlated noise would bias the sum).

    ``algorithms`` declares which collective wire algorithms
    (:mod:`mpi4torch_tpu.tune`) the codec composes with.  The compressed
    pipeline re-quantizes the partial sum at each ring hop
    (compress/spmd.py); that per-hop structure generalizes to every
    schedule whose channels are rings — ``ring`` itself, ``bidir``'s two
    counter-rotating chains, and ``torus``'s two striped grid walks —
    but not to the butterfly/tree/hierarchical schedules.  The
    in-schedule (``hop_fused``) block-q8 family declares the full
    ring-shaped trio; the bf16 family stays ring-only (its pipeline is
    the generic encoded ring).  The tune selector restricts auto choice
    to the declared algorithms, and explicit mismatched requests raise
    at the facade (comm.Allreduce); the registry side of the same
    predicate is ``AlgorithmSpec.codec_capable`` (tune/registry.py) —
    both must agree before a codec rides a wire.

    ``schedule_keyed`` marks stochastic codecs whose rounding noise is a
    pure function of the collective schedule (salt × hop × rank — no
    call counters, no data fingerprints): their Mode A and Mode B
    executions consume identical noise bits, so the quantized fold
    oracle (:func:`mpi4torch_tpu.constants.reduce_q8_hop`) holds them to
    BIT-identical cross-mode parity like the deterministic codecs.
    ``bf16r`` is deliberately not schedule-keyed (Mode B advances a
    per-call counter for fresh noise across steps), so its parity
    contract is statistical, not bitwise.

    ``hop_fused``/``hop_ef`` describe the in-schedule hop: ``hop_fused``
    codecs encode block-shaped data with exactly the
    ``ops/quant_kernels.py`` requant op sequence, so the pipeline may
    run dequantize→accumulate→requantize as ONE fused kernel per hop
    (bit-identical to ``decode``→add→``encode`` through the codec — a
    subclass that overrides ``encode``/``decode`` must reset it);
    ``hop_ef`` additionally folds each hop's quantization residual into
    the same rank's next in-schedule contribution (per-hop error
    feedback at single-round wire cost).
    """

    name: str
    stochastic: bool = False
    ef_rounds: int = 1
    algorithms: Tuple[str, ...] = ("ring",)
    schedule_keyed: bool = False
    hop_fused: bool = False
    hop_ef: bool = False

    def base(self) -> "Codec":
        """The single-round codec used for each error-feedback round."""
        return self

    # -- subclass surface ---------------------------------------------------
    def encode(self, x, key=None) -> Tuple[Payload, Meta]:
        raise NotImplementedError

    def decode(self, payload: Payload, meta: Meta):
        raise NotImplementedError

    # -- shared helpers -----------------------------------------------------
    def roundtrip(self, x, key=None):
        """decode(encode(x)) — the local lossy approximation; its
        difference from ``x`` is the residual error-feedback rounds
        compensate."""
        payload, meta = self.encode(x, key)
        return self.decode(payload, meta)

    def wire_bytes(self, shape, dtype) -> int:
        """Bytes a tensor of ``shape``/``dtype`` occupies on the wire once
        encoded (the sum of the payload leaves' sizes) — the bench's
        bytes-on-wire accounting, computed from real encoded buffers so
        the number cannot drift from the implementation."""
        x = jnp.zeros(shape, dtype)
        payload, _ = self.encode(x, _default_key() if self.stochastic
                                 else None)
        return int(sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree_util.tree_leaves(payload)))

    def _meta(self, x) -> Tuple[Tuple[int, ...], str]:
        xa = jnp.asarray(x)
        return tuple(xa.shape), str(xa.dtype)


@dataclasses.dataclass(frozen=True)
class BlockQ8Codec(Codec):
    """Block-scaled int8: each 256-element block of the flattened tensor
    is scaled and rounded to int8 (EQuARX's block-scaled quantization,
    arxiv 2506.17615 §3), with the scale a POWER OF TWO — block floating
    point (``ops/quant_kernels.po2_scale``): the smallest ``2^k`` with
    ``127·2^k ≥ absmax``.  Exact-by-construction arithmetic (the
    division and every dequantize product round nowhere) is what lets
    the in-schedule pipeline hold bitwise Mode A/B parity under any XLA
    fusion, and integer-valued blocks (ones gradients) roundtrip
    exactly.  Per-element error is bounded by half the power-of-two
    step — at most one int8 step of the block's absmax.  The f32 scale
    adds 4 bytes per block, so the wire ratio is 4 / (1 + 4/256) ≈
    3.94x for f32."""

    name: str = "q8"
    algorithms: Tuple[str, ...] = ("ring", "bidir", "torus")
    hop_fused: bool = True
    block: int = 256

    def _blocks(self, x):
        """Flatten + zero-pad ``x`` to (nblocks, block) f32 — the block
        layout shared with the in-schedule pipeline's ``chunk_blocks``
        (zero pad is inert under the power-of-two absmax scale)."""
        flat = jnp.asarray(x, jnp.float32).reshape(-1)
        total = max(flat.size, 1)
        nb = -(-total // self.block)
        pad = nb * self.block - flat.size
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        return flat.reshape(nb, self.block)

    def encode(self, x, key=None):
        # requant_blocks IS this codec's encode on block-shaped data
        # (ops/quant_kernels: po2_scale block-floating-point scales,
        # exact products/division) — one op sequence for the standalone
        # encode and the fused hop's requant, so the hop_fused
        # bit-equality contract cannot drift.
        from ..ops.quant_kernels import requant_blocks

        shape, dtype = self._meta(x)
        q, scale = requant_blocks(self._blocks(x))
        return {"q": q, "scale": scale}, ("q8", shape, dtype)

    def decode(self, payload, meta):
        _, shape, dtype = meta
        blocks = payload["q"].astype(jnp.float32) \
            * payload["scale"][:, None].astype(jnp.float32)
        total = math.prod(shape)
        return blocks.reshape(-1)[:total].reshape(shape).astype(dtype)


@dataclasses.dataclass(frozen=True)
class HopEFQ8Codec(BlockQ8Codec):
    """``q8`` with per-hop stochastic rounding and per-hop error
    feedback, at single-round (~3.94x) wire cost.

    Two changes relative to :class:`BlockQ8Codec`, both living inside
    the in-schedule pipeline (compress/spmd.py):

    * every requantization rounds stochastically — ``floor(v + u)``
      with ``u ~ U[0, 1)`` drawn from the *schedule* key (salt × hop ×
      rank; the noise enters ``ops/quant_kernels.py`` as an operand, so
      the Pallas kernel and the jnp fallback consume identical bits) —
      making each hop's requant unbiased, so quantization error
      accumulates as zero-mean noise instead of a systematic floor;
    * each hop's residual ``part - decode(requant(part))`` is carried on
      the encoding rank and folded into its NEXT in-schedule
      contribution (a different chunk of the same tensor — the EF-SGD
      move applied across hops instead of steps), so apart from each
      rank's final-hop residual nothing is lost to quantization within
      the call.

    The cross-chunk reinjection preserves the tensor's total mass to
    first order while the stochastic hops keep the per-element leakage
    zero-mean; for gradient traffic this recovers ``q8_ef``-grade
    convergence (regression-tested on the smoke transformer) without
    ``q8_ef``'s second wire round.  ``schedule_keyed`` means Mode A and
    Mode B reproduce the exact same noise, so cross-mode parity is
    bitwise like the deterministic codecs.  Outside a ring-shaped
    schedule (the standalone ``encode``, the compressed Allgather legs)
    it behaves as stochastically-rounded q8."""

    name: str = "q8_ef_hop"
    stochastic: bool = True
    schedule_keyed: bool = True
    hop_ef: bool = True

    def encode(self, x, key=None):
        from ..ops.quant_kernels import hop_noise, requant_blocks

        shape, dtype = self._meta(x)
        if key is None:
            key = _default_key()
        blocks = self._blocks(x)
        noise = hop_noise(key, blocks.shape[0], self.block)
        q, scale = requant_blocks(blocks, noise)
        return {"q": q, "scale": scale}, ("q8", shape, dtype)


@dataclasses.dataclass(frozen=True)
class BF16Codec(Codec):
    """Round-to-nearest bfloat16: exact halving of f32 wire bytes with
    ~2^-9 relative error; deterministic and key-free."""

    name: str = "bf16"

    def encode(self, x, key=None):
        shape, dtype = self._meta(x)
        q = jnp.asarray(x, jnp.float32).astype(jnp.bfloat16).reshape(-1)
        return {"q": q}, ("bf16", shape, dtype)

    def decode(self, payload, meta):
        _, shape, dtype = meta
        return payload["q"].astype(jnp.float32) \
            .reshape(shape).astype(dtype)


@dataclasses.dataclass(frozen=True)
class BF16StochasticCodec(Codec):
    """Stochastic-rounded bfloat16: adds uniform 16-bit noise to the f32
    mantissa before truncating to the high 16 bits, so rounding is
    unbiased (E[decode(encode(x))] = x) — the property that keeps
    many-step gradient accumulation drift-free where round-to-nearest
    introduces a systematic floor.  Keyed: the collectives fold rank and
    hop indices into the key so per-contribution noise is independent."""

    name: str = "bf16r"
    stochastic: bool = True

    def encode(self, x, key=None):
        shape, dtype = self._meta(x)
        if key is None:
            key = _default_key()
        x32 = jnp.asarray(x, jnp.float32).reshape(-1)
        bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
        noise = jax.random.bits(key, x32.shape, jnp.uint32) \
            & jnp.uint32(0xFFFF)
        hi = ((bits + noise) >> 16).astype(jnp.uint16)
        q = jax.lax.bitcast_convert_type(hi, jnp.bfloat16)
        return {"q": q}, ("bf16r", shape, dtype)

    def decode(self, payload, meta):
        _, shape, dtype = meta
        return payload["q"].astype(jnp.float32) \
            .reshape(shape).astype(dtype)


@dataclasses.dataclass(frozen=True)
class ErrorFeedbackCodec(Codec):
    """A base codec run with one in-call error-feedback round: the
    collective transfers ``base(x)`` and then ``base(x - decode(base(x)))``
    and sums both, cancelling each rank's first-order quantization error
    (EF-SGD, Karimireddy et al. 2019, folded into the collective).  Wire
    cost is 2x the base codec — for ``q8_ef`` still ~2x under fp32 — and
    accuracy improves by roughly another factor of 127."""

    name: str = "q8_ef"
    ef_rounds: int = 2
    # The residual round tracks per-hop residuals at the rows of the
    # chunks this rank encoded — a property of the ring walk itself, so
    # it holds on every ring-shaped channel (ring, bidir's two chains,
    # torus's two grid walks) and the residual round rides the same
    # channel as the values it corrects.
    algorithms: Tuple[str, ...] = ("ring", "bidir", "torus")
    _base: Codec = dataclasses.field(default_factory=BlockQ8Codec)

    def base(self) -> Codec:
        return self._base

    def encode(self, x, key=None):
        return self._base.encode(x, key)

    def decode(self, payload, meta):
        return self._base.decode(payload, meta)

    def wire_bytes(self, shape, dtype) -> int:
        return self.ef_rounds * self._base.wire_bytes(shape, dtype)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Codec] = {}


def register_codec(codec: Codec) -> Codec:
    """Register ``codec`` under ``codec.name`` (later topology-aware
    autotuners select among registered codecs per callsite).  Returns the
    codec so registration can wrap construction."""
    if not codec.name:
        raise ValueError("codec must have a non-empty name")
    _REGISTRY[codec.name] = codec
    return codec


def available_codecs() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def get_codec(spec) -> Optional[Codec]:
    """Resolve a ``compression=`` argument to a codec object.

    ``None``/``False``/``"none"`` mean no compression; a string looks up
    the registry; a :class:`Codec` instance passes through — ad-hoc
    codecs need no *registration*, but they must subclass :class:`Codec`
    (the pipeline relies on its full contract: ``name`` for spans and
    rendezvous signatures, ``ef_rounds``/``base()`` for the
    error-feedback rounds), so a bare encode/decode object is rejected
    here rather than crashing mid-collective."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, str):
        if spec in ("none", "off"):
            return None
        codec = _REGISTRY.get(spec)
        if codec is None:
            raise ValueError(
                f"unknown compression codec {spec!r}; available: "
                f"{', '.join(available_codecs())}")
        return codec
    if isinstance(spec, Codec):
        return spec
    raise TypeError(
        f"compression must be a registered codec name, a Codec subclass "
        f"instance, or None; got {spec!r}")


register_codec(BlockQ8Codec())
register_codec(HopEFQ8Codec())
register_codec(BF16Codec())
register_codec(BF16StochasticCodec())
register_codec(ErrorFeedbackCodec())

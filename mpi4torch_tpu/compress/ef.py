"""Cross-step error feedback for compressed gradient AllReduce.

``compression="q8_ef"`` compensates quantization error *within* one
collective (a second residual round, 2x the q8 wire).  This module is the
cheaper alternative for iterative training: carry the residual *across*
optimizer steps (EF-SGD / EF21 style, Karimireddy et al. 2019) so each
step pays single-round q8 wire while the un-transmitted error is added
back into the next step's gradient — over a run, nothing is lost to
quantization except a one-step delay.

The state is a plain pytree (functional, jit/scan-friendly)::

    resid = ef_init(grads)                       # zeros like grads
    for step in range(n_steps):
        grads = grad_fn(params)
        synced, resid = ef_allreduce(comm, grads, resid,
                                     compression="q8")
        params = update(params, synced)

Works on both backends: the collective inside is the facade
``Allreduce(..., compression=...)``, so Mode A runs it as the quantized
ring pipeline and Mode B at the rendezvous.

Interplay with the in-schedule hop codecs (``hop_fused``): ``q8``'s
carried residual stays exact because hop 0 of the fused pipeline
requantizes with the codec's own block layout and power-of-two scales —
``base.roundtrip`` reproduces precisely what this rank put on the wire,
bit for bit, even though later hops re-quantize downstream partials.
``q8_ef_hop`` lands in the stochastic carve-out below and carries a
zero residual: its per-hop error feedback already re-injects residuals
*inside* the schedule, and its unbiased rounding leaves no systematic
error for cross-step EF to recover.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import constants as C
from .codecs import get_codec

__all__ = ["ef_init", "ef_allreduce"]


def ef_init(tree):
    """Zero residual state shaped like ``tree`` (one leaf per gradient
    leaf, same dtype — the residual lives in the gradient's own
    precision)."""
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def ef_allreduce(comm, tree, residual, op: int = C.MPI_SUM,
                 compression="q8"):
    """Error-compensated compressed AllReduce over a gradient pytree.

    Each leaf is corrected by its carried residual, summed across ranks
    through ``comm.Allreduce(..., compression=...)``, and the new
    residual (what this rank's codec failed to transmit this step) is
    returned for the next call.  Returns ``(synced_tree, new_residual)``.
    """
    codec = get_codec(compression)
    if codec is None:
        synced = jax.tree_util.tree_map(
            lambda g: comm.Allreduce(g, op, compression=False), tree)
        return synced, residual

    # The carried residual must be computed against what the wire actually
    # transmitted.  Cross-step EF *replaces* in-call EF, so a multi-round
    # codec (q8_ef) is reduced to its single-round base here: otherwise
    # the collective would transmit ~all of `corrected` (second-order
    # error) while the carried residual still recorded the full
    # first-order error — re-injecting already-transmitted gradient every
    # step.
    base = codec.base()
    leaves_g, treedef = jax.tree_util.tree_flatten(tree)
    leaves_r = treedef.flatten_up_to(residual)
    synced_leaves, resid_leaves = [], []
    for g, r in zip(leaves_g, leaves_r):
        corrected = g + r.astype(g.dtype)
        synced_leaves.append(comm.Allreduce(corrected, op,
                                            compression=base))
        if getattr(base, "stochastic", False):
            # A stochastic codec's wire keys (per rank/hop inside the
            # collective) cannot be reproduced locally, so a residual
            # computed here would be uncorrelated noise, not the
            # transmission error.  Unbiased rounding needs no error
            # feedback anyway (E[decode(encode(x))] = x): carry zero.
            new_r = jnp.zeros_like(corrected)
        else:
            new_r = corrected - base.roundtrip(corrected)
        resid_leaves.append(new_r.astype(r.dtype))
    return (jax.tree_util.tree_unflatten(treedef, synced_leaves),
            jax.tree_util.tree_unflatten(treedef, resid_leaves))

"""Mesh construction helpers: ICI-topology-aware and hybrid ICI x DCN.

The reference's transport scaling story is "MPI handles it" — one flat
communicator regardless of how ranks map onto the physical network
(SURVEY.md §2.6).  On TPU the network is two-tier: chips within a slice
connect over ICI (torus links, ~45 GB/s/link on v5e), slices connect
over DCN (data-center network, ~an order of magnitude slower).  Which
mesh axes cross which tier decides whether a collective rides ICI or
DCN, so the framework exposes the mapping explicitly:

* :func:`device_mesh` — single-slice (or CPU-harness) mesh with the axis
  order chosen so the *innermost* (fastest-varying) axes map onto
  physically adjacent chips — put the heaviest-traffic axis (TP, then
  SP) last, DP first.
* :func:`hybrid_mesh` — multi-slice: DCN-crossing axes are declared
  separately and are laid out as the outermost factors, so only the axes
  you *say* cross slices produce DCN traffic (the standard layout: DP
  over DCN, TP/SP over ICI — jax ``mesh_utils.create_hybrid_device_mesh``
  underneath).

Both return a plain ``jax.sharding.Mesh`` — everything downstream
(``comm_from_mesh``, ``shard_map``, the §2.5 strategy layer) is
mesh-source-agnostic.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional, Sequence

import numpy as np

__all__ = ["device_mesh", "hybrid_mesh", "mesh_coords", "rank_of_coords"]


def mesh_coords(rank: int, mesh_shape: Sequence[int]) -> tuple:
    """Row-major mesh coordinates of a flat rank — THE rank <-> coords
    convention of the whole framework (the torus schedules' virtual 2D
    factorization and the :mod:`mpi4torch_tpu.reshard` layouts both key
    off it): the LAST mesh axis varies fastest, matching
    :func:`device_mesh`'s axis-significance order."""
    rank = int(rank)
    total = math.prod(mesh_shape)
    if not (0 <= rank < total):
        raise ValueError(f"rank {rank} out of range for mesh "
                         f"{tuple(mesh_shape)} ({total} ranks)")
    coords = []
    for m in reversed(tuple(mesh_shape)):
        coords.append(rank % m)
        rank //= m
    return tuple(reversed(coords))


def rank_of_coords(coords: Sequence[int], mesh_shape: Sequence[int]) -> int:
    """Inverse of :func:`mesh_coords`: the flat rank of row-major mesh
    coordinates."""
    coords, mesh_shape = tuple(coords), tuple(mesh_shape)
    if len(coords) != len(mesh_shape):
        raise ValueError(
            f"coords {coords} do not match mesh {mesh_shape}")
    r = 0
    for c, m in zip(coords, mesh_shape):
        if not (0 <= int(c) < m):
            raise ValueError(f"coords {coords} out of mesh {mesh_shape}")
        r = r * m + int(c)
    return r


def _check_sizes(shape: Mapping[str, int], n: int, what: str) -> None:
    total = math.prod(shape.values())
    if total != n:
        raise ValueError(
            f"{what} axis sizes {dict(shape)} multiply to {total}, but "
            f"{n} devices are available")


def device_mesh(axes: Mapping[str, int], *, devices: Optional[Sequence] = None):
    """A ``Mesh`` over one slice (or the CPU test harness).

    ``axes`` maps axis name -> size, in significance order: the LAST axis
    varies fastest over the physical device order, so it lands on
    adjacent chips — put the axis with the heaviest collective traffic
    (usually TP or SP) last and DP first.  Uses jax's topology-aware
    device ordering on real TPU slices (``mesh_utils.create_device_mesh``
    maps the trailing mesh dims onto the ICI torus) and a plain reshape
    on other platforms."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    _check_sizes(axes, len(devices), "device_mesh")
    shape = tuple(axes.values())
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=list(devices))
    else:
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axes.keys()))


def hybrid_mesh(ici_axes: Mapping[str, int], dcn_axes: Mapping[str, int],
                *, devices: Optional[Sequence] = None):
    """A ``Mesh`` spanning multiple slices/hosts with explicit tier
    assignment.

    ``dcn_axes`` axes cross the slice boundary (their total size must
    equal the number of slices/granules); ``ici_axes`` axes stay inside a
    slice.  The returned mesh carries the DCN axes first (outermost) then
    the ICI axes, so e.g. ``hybrid_mesh({"tp": 4}, {"dp": 2})`` gives
    axis names ``("dp", "tp")`` where only ``dp`` collectives touch DCN.

    On a single granule (one slice, or the CPU harness where every
    device reports process 0), all ``dcn_axes`` sizes must be 1 and the
    call degrades to :func:`device_mesh` — the same program then runs
    unchanged on a pod."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    both = {**dcn_axes, **ici_axes}
    if len(both) != len(dcn_axes) + len(ici_axes):
        raise ValueError(
            f"axis names must be disjoint between tiers; got ICI "
            f"{tuple(ici_axes)} and DCN {tuple(dcn_axes)}")
    _check_sizes(both, len(devices), "hybrid_mesh")

    # TPU granulates by slice (processes within one slice are still
    # ICI-connected); every other platform's slow tier is the process
    # boundary.  Attribute probing is NOT a platform test: CPU devices
    # also expose slice_index (always 0) under the distributed runtime.
    by_process = devices[0].platform != "tpu"
    n_granules = len({d.process_index if by_process
                      else getattr(d, "slice_index", 0) for d in devices})
    dcn_total = math.prod(dcn_axes.values())
    if n_granules == 1:
        if dcn_total != 1:
            raise ValueError(
                f"dcn axes {dict(dcn_axes)} require {dcn_total} "
                "slices/processes but all devices are in one granule — "
                "move those factors to ici_axes (single-slice) or launch "
                "multi-process (init_distributed)")
        return device_mesh(both, devices=devices)
    if dcn_total != n_granules:
        raise ValueError(
            f"dcn axes {dict(dcn_axes)} multiply to {dcn_total}, but the "
            f"devices span {n_granules} slices/granules")

    from jax.experimental import mesh_utils

    ici_shape = [1] * len(dcn_axes) + list(ici_axes.values())
    dcn_shape = list(dcn_axes.values()) + [1] * len(ici_axes)
    arr = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=list(devices),
        # Mirror the granule choice above (jax hard-requires slice_index
        # unless told to granulate by process).
        process_is_granule=by_process)
    return Mesh(arr, tuple(both.keys()))

"""mpi4torch_tpu.reshard — AD-transparent sharding -> sharding
redistribution with memory-bounded portable-collective plans.

The transitions production actually hits — train on ``(8,)``, serve on
``(2,4)``; ZeRO-shard -> TP-shard at the train/serve boundary; MoE
expert rebalancing; topology-migrating checkpoint restore — become one
differentiable facade call::

    y = comm.Reshard(tree, from_spec, to_spec)

following "Memory-efficient array redistribution through portable
collective communication" (PAPERS.md, arXiv 2112.01075): the planner
(:mod:`.plan`) decomposes any (mesh, spec) -> (mesh', spec') pair into a
short program of portable steps — all-gather / all-to-all /
collective-permute / dynamic-slice — whose peak live bytes stay
``O(shard + chunk)`` instead of the gather-everything baseline's
``O(full array)``; the executor (:mod:`.executor`) lowers the same plan
to native collectives under SPMD and replays it through the rendezvous
on the eager thread world (bitwise-identical, fault-grammar-covered);
the VJP executes the *reverse* plan, so cotangents redistribute
spec' -> spec.  ``python -m mpi4torch_tpu.reshard --smoke`` sweeps the
representative transitions against the gather-then-slice oracle (`make
reshard-smoke`).
"""

from .census import peak_live_bytes, tensor_bytes
from .executor import (apply_plan, execute_plan, gather_then_slice,
                       global_template, reshard_blocks, reshard_tree,
                       reshard_value, shard_of, shard_template,
                       slice_shard)
from .plan import (STEP_KINDS, STRATEGIES, Layout, ReshardPlan, layout,
                   plan_permutation, plan_reshard, plan_resize)
from .rules import match_partition_rules, tree_paths

__all__ = [
    "Layout",
    "layout",
    "ReshardPlan",
    "STEP_KINDS",
    "STRATEGIES",
    "plan_reshard",
    "plan_permutation",
    "plan_resize",
    "apply_plan",
    "execute_plan",
    "reshard_value",
    "reshard_tree",
    "reshard_blocks",
    "gather_then_slice",
    "slice_shard",
    "shard_of",
    "shard_template",
    "global_template",
    "match_partition_rules",
    "tree_paths",
    "peak_live_bytes",
    "tensor_bytes",
]

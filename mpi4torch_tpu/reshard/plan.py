"""The reshard planner: (mesh, spec) -> (mesh', spec') as a program of
portable collective steps with bounded peak memory.

"Memory-efficient array redistribution through portable collective
communication" (PAPERS.md, arXiv 2112.01075) frames every sharding
transition as a short sequence of portable collectives — all-gather /
all-to-all / collective-permute / dynamic-slice — chosen so peak live
bytes stay ``O(shard + chunk)`` instead of the ``O(full array)`` of the
gather-everything-then-slice default.  This module is the planning half:

* :class:`Layout` — a ``(mesh_shape, spec)`` pair over a FLAT world of
  ``prod(mesh_shape)`` ranks (rank -> mesh coordinates row-major, the
  repo's standard 8-as-(2,4) convention).  ``spec`` assigns mesh axes to
  array axes exactly like a ``PartitionSpec``; unused mesh axes mean
  replication.
* :func:`plan_reshard` — normalizes a transition onto the common chunk
  grid (per-axis ``lcm`` of the two sharding factors) and emits the
  cheapest applicable strategy:

  ========== ================================================= ==========
  strategy   shape of the transition                           wire steps
  ========== ================================================= ==========
  local      every rank already holds its target shard         none
  permute    whole shards move bijectively between ranks       1 permute
  allgather  pure coarsening (sharding drops / replication     1 gather
             grows), aligned blocks                            per axis
  alltoall   uniform chunk exchange within disjoint rank       1 all-to-
             groups (the (8,)->(2,4) migration shape)          all
  rounds     anything else: chunk-granular permute rounds,     <=R
             one chunk per rank in flight per round            permutes
  gather     the baseline/oracle: gather everything, slice     1 gather
  ========== ================================================= ==========

  ``gather`` is never auto-selected — it is the explicit baseline the
  acceptance tests compare against.  Auto selection walks the preference
  order above (each next row strictly cheaper in peak memory than
  ``gather``), with a measured :mod:`mpi4torch_tpu.tune` cache winner
  overriding when one exists for this transition (the autotuner cache
  key grows a ``transition`` dimension, mirroring the codec dimension).
* :meth:`ReshardPlan.adjoint` — the reverse plan.  Every step kind's
  adjoint is itself a step kind in the same grammar (permute ->
  inverse permute, all-to-all -> table-swapped all-to-all, all-gather ->
  reduce-scatter, slice -> pad), so the VJP of a reshard is a reshard —
  the adjoint-is-itself-a-collective contract of the paper.  For
  replication-free transitions the adjoint IS the spec' -> spec
  redistribution bitwise (pure data movement both ways).

Plans are cached per (transition, global shape, dtype, strategy) like
``fuse/`` caches bucket layouts; ``run_spmd`` keys its jit cache on the
config fingerprint + tune generation, so a strategy-knob or cache change
retraces instead of silently reusing an old lowering.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from .. import config as _config
from ..mesh import mesh_coords
from ..runtime import CommError

# Registered plan-step kinds.  The registry-sync guard (tests/
# test_reshard.py + `make reshard-smoke`) fails when a kind exists
# without executor, adjoint, census AND parity coverage — the PR 4/6/7
# pattern, structural here because the executor dispatch tables and the
# adjoint map are checked against this literal.
STEP_KINDS = ("slice", "pad", "permute", "alltoall", "allgather",
              "reduce_scatter")

# Planner strategies ("auto" = preference order + tune-cache winner).
STRATEGIES = ("local", "permute", "allgather", "alltoall", "rounds",
              "gather")

_MOVE_KINDS = ("slice", "pad", "permute", "alltoall")


def _norm_entry(e) -> Tuple[int, ...]:
    if e is None:
        return ()
    if isinstance(e, (int, np.integer)):
        return (int(e),)
    return tuple(int(i) for i in e)


@dataclass(frozen=True)
class Layout:
    """A sharding layout: ``mesh`` is the virtual mesh shape over the
    flat world (``prod(mesh)`` ranks, coordinates row-major — the same
    8-as-(2,4) convention as the torus schedules); ``spec[a]`` names the
    mesh axes (by index, major-to-minor) sharding array axis ``a``.
    Mesh axes used by no array axis replicate the data."""

    mesh: Tuple[int, ...]
    spec: Tuple[Tuple[int, ...], ...]

    def __post_init__(self):
        mesh = tuple(int(m) for m in self.mesh)
        spec = tuple(_norm_entry(e) for e in self.spec)
        object.__setattr__(self, "mesh", mesh)
        object.__setattr__(self, "spec", spec)
        if not mesh or any(m < 1 for m in mesh):
            raise CommError(f"invalid mesh shape {mesh}")
        used = [i for e in spec for i in e]
        for i in used:
            if not (0 <= i < len(mesh)):
                raise CommError(
                    f"spec names mesh axis {i}, but the mesh has "
                    f"{len(mesh)} axes")
        if len(set(used)) != len(used):
            raise CommError(
                f"each mesh axis may shard at most one array axis; "
                f"spec {spec} reuses one")

    @property
    def size(self) -> int:
        return math.prod(self.mesh)

    @property
    def ndim(self) -> int:
        return len(self.spec)

    def factor(self, a: int) -> int:
        return math.prod(self.mesh[i] for i in self.spec[a])

    @property
    def factors(self) -> Tuple[int, ...]:
        return tuple(self.factor(a) for a in range(self.ndim))

    @property
    def replica_axes(self) -> Tuple[int, ...]:
        used = {i for e in self.spec for i in e}
        return tuple(i for i in range(len(self.mesh)) if i not in used)

    def block(self, rank: int) -> Tuple[int, ...]:
        """Per-array-axis block index of ``rank``'s shard."""
        coords = mesh_coords(rank, self.mesh)
        out = []
        for e in self.spec:
            b = 0
            for i in e:
                b = b * self.mesh[i] + coords[i]
            out.append(b)
        return tuple(out)

    def shard_shape(self, global_shape) -> Tuple[int, ...]:
        gs = tuple(int(s) for s in global_shape)
        if len(gs) != self.ndim:
            raise CommError(
                f"layout has {self.ndim} array axes but the array has "
                f"{len(gs)}")
        for a, s in enumerate(gs):
            if s % self.factor(a):
                raise CommError(
                    f"axis {a} length {s} is not divisible by its "
                    f"sharding factor {self.factor(a)} under layout "
                    f"{self.describe()}")
        return tuple(s // self.factor(a) for a, s in enumerate(gs))

    def global_shape(self, shard_shape) -> Tuple[int, ...]:
        ss = tuple(int(s) for s in shard_shape)
        if len(ss) != self.ndim:
            raise CommError(
                f"layout has {self.ndim} array axes but the shard has "
                f"{len(ss)}")
        return tuple(s * self.factor(a) for a, s in enumerate(ss))

    def describe(self) -> str:
        spec = ",".join(
            "r" if not e else "m" + "".join(str(i) for i in e)
            for e in self.spec)
        return f"{'x'.join(str(m) for m in self.mesh)}[{spec}]"


def layout(mesh, *spec) -> Layout:
    """Convenience constructor: ``layout((2, 4), (0, 1), None)`` shards
    array axis 0 over both mesh axes and replicates axis 1."""
    return Layout(tuple(mesh), tuple(spec))


# ---------------------------------------------------------------------------
# Steps.  All fields are static tuples (plans are cached); per-rank
# tables are tuples indexed by rank, lowered to jnp constant tables +
# dynamic slices under SPMD and plain indexing on the eager backend.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LocalStep:
    """Local chunk moves: extract ``src_chunk``-shaped blocks from the
    current value and place (``pad``: accumulate) them into the output
    buffer.  ``moves[r]`` is a tuple of ``(valid, src_start, dst_start)``
    triples, padded to a uniform length across ranks."""
    kind: str                      # "slice" | "pad"
    moves: Tuple                   # per rank: ((valid, src, dst), ...)
    src_chunk: Tuple[int, ...]
    dst_chunk: Tuple[int, ...]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]

    def adjoint(self) -> "LocalStep":
        flipped = tuple(
            tuple((v, d, s) for (v, s, d) in per_rank)
            for per_rank in self.moves)
        return LocalStep(
            kind="pad" if self.kind == "slice" else "slice",
            moves=flipped, src_chunk=self.dst_chunk,
            dst_chunk=self.src_chunk, in_shape=self.out_shape,
            out_shape=self.in_shape)


@dataclass(frozen=True)
class PermuteStep:
    """One chunk per rank rides one ``collective_permute``.  ``table``
    is the completed send bijection; ``send[r] = (valid, src_start)``,
    ``recv[r] = (valid, dst_start)``.  ``accumulate`` marks adjoint
    placement (cotangents of a replicated chunk add up)."""
    kind: str
    table: Tuple[int, ...]
    send: Tuple
    recv: Tuple
    chunk: Tuple[int, ...]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]
    accumulate: bool = False

    def adjoint(self) -> "PermuteStep":
        inv = [0] * len(self.table)
        for s, d in enumerate(self.table):
            inv[d] = s
        return PermuteStep(
            kind="permute", table=tuple(inv), send=self.recv,
            recv=self.send, chunk=self.chunk, in_shape=self.out_shape,
            out_shape=self.in_shape, accumulate=not self.accumulate)


@dataclass(frozen=True)
class AllToAllStep:
    """Uniform chunk exchange within disjoint, equally-sized rank
    groups: each rank packs ``slots`` chunks (``cpr`` per group peer, in
    group-position order), one grouped ``all_to_all`` swaps them, each
    rank places the ``slots`` received chunks.  ``send[r]``/``recv[r]``
    are the per-slot element offsets."""
    kind: str
    groups: Tuple[Tuple[int, ...], ...]
    cpr: int                       # chunks per (src, dst) pair
    send: Tuple                    # per rank: (src_start, ...) per slot
    recv: Tuple                    # per rank: (dst_start, ...) per slot
    chunk: Tuple[int, ...]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]
    accumulate: bool = False

    def adjoint(self) -> "AllToAllStep":
        return AllToAllStep(
            kind="alltoall", groups=self.groups, cpr=self.cpr,
            send=self.recv, recv=self.send, chunk=self.chunk,
            in_shape=self.out_shape, out_shape=self.in_shape,
            accumulate=not self.accumulate)


@dataclass(frozen=True)
class AllGatherStep:
    """Value -> value transform: concatenate the group members' values
    along ``axis`` in group order (``axis=None``: stack the whole
    world's values along a new leading axis — the gather-baseline's
    wide hop, the one a wire codec may ride)."""
    kind: str
    groups: Optional[Tuple[Tuple[int, ...], ...]]
    axis: Optional[int]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]

    def adjoint(self) -> "ReduceScatterStep":
        return ReduceScatterStep(
            kind="reduce_scatter", groups=self.groups, axis=self.axis,
            in_shape=self.out_shape, out_shape=self.in_shape)


@dataclass(frozen=True)
class ReduceScatterStep:
    """The all-gather adjoint: sum the group members' cotangents
    (ascending group order under ``deterministic_mode`` — the eager
    oracle's association) and keep this rank's segment/slot."""
    kind: str
    groups: Optional[Tuple[Tuple[int, ...], ...]]
    axis: Optional[int]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]

    def adjoint(self) -> AllGatherStep:
        return AllGatherStep(
            kind="allgather", groups=self.groups, axis=self.axis,
            in_shape=self.out_shape, out_shape=self.in_shape)


@dataclass(frozen=True)
class ReshardPlan:
    """A compiled transition: the step program plus its static
    metadata.  ``wire_bytes``/``peak_bytes`` are the deterministic
    per-device estimates the strategy ranking (and the bench stanza's
    verdict) use."""
    steps: Tuple
    strategy: str
    size: int
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]
    dtype: str
    wire_bytes: int
    peak_bytes: int
    transition: str

    def adjoint(self) -> "ReshardPlan":
        steps = tuple(s.adjoint() for s in reversed(self.steps))
        return ReshardPlan(
            steps=steps, strategy=self.strategy + ".adjoint",
            size=self.size, in_shape=self.out_shape,
            out_shape=self.in_shape, dtype=self.dtype,
            wire_bytes=self.wire_bytes, peak_bytes=self.peak_bytes,
            transition=self.transition + ".adjoint")


# ---------------------------------------------------------------------------
# Route computation: the transition on the common chunk grid.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Routes:
    """Per-transition chunk routing: ``local[r]`` are (src_start,
    dst_start) element-offset pairs of chunks rank ``r`` already holds;
    ``wire`` is the global list of (src, dst, src_start, dst_start)
    moves."""
    size: int
    chunk: Tuple[int, ...]
    in_shape: Tuple[int, ...]
    out_shape: Tuple[int, ...]
    local: Tuple
    wire: Tuple


def _owners_map(lay: Layout):
    """block-vector -> sorted rank list (replicas included)."""
    owners = {}
    for r in range(lay.size):
        owners.setdefault(lay.block(r), []).append(r)
    return owners


def _routes_from_wants(size, chunk, in_shape, out_shape, wants):
    """``wants``: iterable of (dst_rank, src_owner_ranks, src_start,
    dst_start).  Splits into local/wire with the replica-spreading
    source pick."""
    local = [[] for _ in range(size)]
    wire = []
    for d, owners, src_start, dst_start in wants:
        if d in owners:
            local[d].append((src_start, dst_start))
        else:
            s = owners[d % len(owners)]
            wire.append((s, d, src_start, dst_start))
    return _Routes(size=size, chunk=chunk, in_shape=tuple(in_shape),
                   out_shape=tuple(out_shape),
                   local=tuple(tuple(m) for m in local),
                   wire=tuple(wire))


def _compute_routes(src_lay: Layout, dst_lay: Layout,
                    global_shape) -> _Routes:
    gs = tuple(int(s) for s in global_shape)
    nd = len(gs)
    Ff, Ft = src_lay.factors, dst_lay.factors
    G = tuple(math.lcm(Ff[a], Ft[a]) for a in range(nd))
    chunk = tuple(gs[a] // G[a] for a in range(nd))
    qin = tuple(G[a] // Ff[a] for a in range(nd))
    qout = tuple(G[a] // Ft[a] for a in range(nd))
    in_shape = src_lay.shard_shape(gs)
    out_shape = dst_lay.shard_shape(gs)
    owners = _owners_map(src_lay)
    size = src_lay.size

    wants = []
    for d in range(size):
        bt = dst_lay.block(d)
        for lt in np.ndindex(*qout):
            c = tuple(bt[a] * qout[a] + lt[a] for a in range(nd))
            bf = tuple(c[a] // qin[a] for a in range(nd))
            src_start = tuple((c[a] - bf[a] * qin[a]) * chunk[a]
                              for a in range(nd))
            dst_start = tuple(lt[a] * chunk[a] for a in range(nd))
            wants.append((d, owners[bf], src_start, dst_start))
    return _routes_from_wants(size, chunk, in_shape, out_shape, wants)


def _permutation_routes(lay: Layout, axis: int, perm, global_shape
                        ) -> _Routes:
    """Routes for a block permutation along one array axis: new unit
    ``u`` of the chunk grid holds old unit ``perm[u]`` (both layouts =
    ``lay``).  Used by MoE expert rebalancing, where the units are the
    stacked experts."""
    gs = tuple(int(s) for s in global_shape)
    nd = len(gs)
    perm = tuple(int(p) for p in perm)
    n_units = len(perm)
    if sorted(perm) != list(range(n_units)):
        raise CommError(f"perm {perm} is not a permutation of "
                        f"0..{n_units - 1}")
    F = lay.factors
    if n_units % F[axis] or gs[axis] % n_units:
        raise CommError(
            f"{n_units} permutation units must be a multiple of the "
            f"axis-{axis} sharding factor {F[axis]} and divide the "
            f"axis length {gs[axis]}")
    G = tuple(n_units if a == axis else F[a] for a in range(nd))
    chunk = tuple(gs[a] // G[a] for a in range(nd))
    qin = tuple(G[a] // F[a] for a in range(nd))
    in_shape = lay.shard_shape(gs)
    owners = _owners_map(lay)
    size = lay.size

    wants = []
    for d in range(size):
        bt = lay.block(d)
        for lt in np.ndindex(*qin):
            # New chunk at my slot lt along `axis` maps to old unit
            # perm[global unit]; other axes are untouched.
            c_new = tuple(bt[a] * qin[a] + lt[a] for a in range(nd))
            c_old = tuple(perm[c_new[a]] if a == axis else c_new[a]
                          for a in range(nd))
            bf = tuple(c_old[a] // qin[a] for a in range(nd))
            src_start = tuple((c_old[a] - bf[a] * qin[a]) * chunk[a]
                              for a in range(nd))
            dst_start = tuple(lt[a] * chunk[a] for a in range(nd))
            wants.append((d, owners[bf], src_start, dst_start))
    return _routes_from_wants(size, chunk, in_shape, in_shape, wants)


# ---------------------------------------------------------------------------
# Strategy builders.  Each returns a step tuple or None (inapplicable).
# ---------------------------------------------------------------------------


def _pad_moves(local, nd):
    """Per-rank move lists padded to uniform length with invalid
    entries (clipped-to-zero starts keep the lowered dynamic slices in
    range)."""
    zero = (0,) * nd
    n = max((len(m) for m in local), default=0)
    return tuple(
        tuple((True, s, d) for s, d in m)
        + ((False, zero, zero),) * (n - len(m))
        for m in local)


def _local_steps(routes: _Routes):
    """The shared local-placement step (chunks that never touch the
    wire), or () when every chunk moves."""
    if not any(routes.local):
        return ()
    return (LocalStep(kind="slice",
                      moves=_pad_moves(routes.local, len(routes.chunk)),
                      src_chunk=routes.chunk, dst_chunk=routes.chunk,
                      in_shape=routes.in_shape,
                      out_shape=routes.out_shape),)


def _build_local(routes: _Routes):
    if routes.wire:
        return None
    if routes.in_shape == routes.out_shape and all(
            src == dst for per in routes.local for src, dst in per):
        return ()                  # identity transition: empty plan
    return _local_steps(routes)


def _build_permute(routes: _Routes):
    """Whole shards move bijectively: every rank sends its entire shard
    to one destination (chunk == shard, contiguous) and receives one.
    Ranks that keep their shard become self-pairs of the same
    ``collective_permute``."""
    if (routes.in_shape != routes.out_shape
            or routes.chunk != routes.in_shape):
        return None
    table = [None] * routes.size
    recv_from = [None] * routes.size
    for r in range(routes.size):
        if len(routes.local[r]) == 1:
            table[r] = r
            recv_from[r] = r
        elif routes.local[r]:
            return None
    for s, d, ss, ds in routes.wire:
        if table[s] is not None or recv_from[d] is not None:
            return None
        table[s] = d
        recv_from[d] = s
    if any(t is None for t in table) or any(s is None for s in recv_from):
        return None
    shard = routes.in_shape
    zero = (0,) * len(shard)
    valid = tuple((True, zero) for _ in range(routes.size))
    return (PermuteStep(kind="permute", table=tuple(table), send=valid,
                        recv=valid, chunk=shard, in_shape=shard,
                        out_shape=shard),)


def _build_allgather(src_lay: Layout, dst_lay: Layout, global_shape):
    """Pure coarsening with aligned blocks on a replication-free
    source: one grouped all-gather per coarsened axis."""
    if src_lay.replica_axes:
        return None
    gs = tuple(int(s) for s in global_shape)
    Ff, Ft = src_lay.factors, dst_lay.factors
    nd = len(gs)
    ratios = []
    for a in range(nd):
        if Ff[a] % Ft[a]:
            return None
        ratios.append(Ff[a] // Ft[a])
    if all(r == 1 for r in ratios):
        return None
    size = src_lay.size
    blocks = [src_lay.block(r) for r in range(size)]
    for r in range(size):
        if dst_lay.block(r) != tuple(blocks[r][a] // ratios[a]
                                     for a in range(nd)):
            return None
    steps = []
    cur = list(src_lay.shard_shape(gs))
    for a in range(nd):
        k = ratios[a]
        if k == 1:
            continue
        groups = {}
        for r in range(size):
            key = blocks[r][:a] + (blocks[r][a] // k,) + blocks[r][a + 1:]
            groups.setdefault(key, []).append(r)
        glist = tuple(
            tuple(sorted(g, key=lambda r: blocks[r][a]))
            for _, g in sorted(groups.items()))
        if any(len(g) != k for g in glist):
            return None
        nxt = list(cur)
        nxt[a] = cur[a] * k
        steps.append(AllGatherStep(kind="allgather", groups=glist,
                                   axis=a, in_shape=tuple(cur),
                                   out_shape=tuple(nxt)))
        cur = nxt
    return tuple(steps)


def _build_alltoall(routes: _Routes):
    """Uniform grouped exchange: the (src, dst) pair graph (self pairs
    included) decomposes into equal-size groups in which every ordered
    pair exchanges exactly ``cpr`` chunks."""
    if not routes.wire:
        return None
    size = routes.size
    pairs = {}
    for s, d, ss, ds in routes.wire:
        pairs.setdefault((s, d), []).append((ss, ds))
    for r in range(size):
        for ss, ds in routes.local[r]:
            pairs.setdefault((r, r), []).append((ss, ds))
    parent = list(range(size))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for (s, d) in pairs:
        rs, rd = find(s), find(d)
        if rs != rd:
            parent[rs] = rd
    comps = {}
    for r in range(size):
        comps.setdefault(find(r), []).append(r)
    groups = tuple(tuple(sorted(g)) for g in
                   sorted(comps.values(), key=lambda g: g[0]))
    g = len(groups[0])
    if g < 2 or any(len(grp) != g for grp in groups):
        return None
    cprs = {len(v) for v in pairs.values()}
    if len(cprs) != 1:
        return None
    cpr = cprs.pop()
    if len(pairs) != len(groups) * g * g:
        return None
    slots = g * cpr
    nd = len(routes.chunk)
    send = [[None] * slots for _ in range(size)]
    recv = [[None] * slots for _ in range(size)]
    pos = {}
    for grp in groups:
        for p, r in enumerate(grp):
            pos[r] = p
    for (s, d), moves in pairs.items():
        moves = sorted(moves)
        for k, (ss, ds) in enumerate(moves):
            send[s][pos[d] * cpr + k] = ss
            recv[d][pos[s] * cpr + k] = ds
    return (AllToAllStep(kind="alltoall", groups=groups, cpr=cpr,
                         send=tuple(tuple(x) for x in send),
                         recv=tuple(tuple(x) for x in recv),
                         chunk=routes.chunk, in_shape=routes.in_shape,
                         out_shape=routes.out_shape),)


def _build_rounds(routes: _Routes):
    """The general fallback: greedy matching packs the wire moves into
    rounds of at most one send + one receive per rank; each round is
    one chunk-sized ``collective_permute``.  Peak live bytes:
    in-shard + out-shard + two chunks in flight."""
    if not routes.wire:
        return None
    size = routes.size
    nd = len(routes.chunk)
    zero = (0,) * nd
    remaining = list(routes.wire)
    steps = list(_local_steps(routes))
    while remaining:
        used_s, used_d = set(), set()
        this, rest = [], []
        for mv in remaining:
            s, d = mv[0], mv[1]
            if s in used_s or d in used_d:
                rest.append(mv)
            else:
                used_s.add(s)
                used_d.add(d)
                this.append(mv)
        remaining = rest
        table = [None] * size
        send = [(False, zero)] * size
        recv = [(False, zero)] * size
        for s, d, ss, ds in this:
            table[s] = d
            send[s] = (True, ss)
            recv[d] = (True, ds)
        free_d = [d for d in range(size) if d not in {m[1] for m in this}]
        it = iter(free_d)
        for s in range(size):
            if table[s] is None:
                table[s] = next(it)
        steps.append(PermuteStep(
            kind="permute", table=tuple(table), send=tuple(send),
            recv=tuple(recv), chunk=routes.chunk,
            in_shape=routes.in_shape, out_shape=routes.out_shape))
    return tuple(steps)


def _build_gather(routes: _Routes):
    """The gather-then-slice baseline: stack every rank's shard (the
    full array lives on every rank — the peak the planner exists to
    avoid), then slice the target shard from the stack.  Kept as the
    explicit oracle strategy; never auto-selected."""
    size = routes.size
    nd = len(routes.chunk)
    stacked = (size,) + routes.in_shape
    qin = tuple(routes.in_shape[a] // routes.chunk[a] for a in range(nd))
    moves = [[] for _ in range(size)]
    for r in range(size):
        for ss, ds in routes.local[r]:
            moves[r].append(((r,) + ss, ds))
    for s, d, ss, ds in routes.wire:
        moves[d].append(((s,) + ss, ds))
    padded = _pad_moves(tuple(tuple(m) for m in moves), nd + 1)
    # _pad_moves pads dst starts to nd+1 too; trim them back to nd.
    padded = tuple(tuple((v, s, d[:nd] if len(d) > nd else d)
                         for v, s, d in per) for per in padded)
    return (AllGatherStep(kind="allgather", groups=None, axis=None,
                          in_shape=routes.in_shape, out_shape=stacked),
            LocalStep(kind="slice", moves=padded,
                      src_chunk=(1,) + routes.chunk,
                      dst_chunk=routes.chunk, in_shape=stacked,
                      out_shape=routes.out_shape))


# ---------------------------------------------------------------------------
# Estimates + assembly
# ---------------------------------------------------------------------------


def _estimates(steps, in_shape, out_shape, itemsize, size):
    """Deterministic per-device (wire_bytes, peak_bytes) of a step
    program — the ranking currency (and the bench stanza's headline).
    Wire follows the bench.py ring accountings; peak counts the shard
    buffers plus each step's own live buffers."""
    nbytes = lambda shape: int(math.prod(shape)) * itemsize  # noqa: E731
    in_b, out_b = nbytes(in_shape), nbytes(out_shape)
    wire = 0
    peak = in_b + out_b
    for st in steps:
        if st.kind == "permute":
            wire += nbytes(st.chunk)
            peak = max(peak, in_b + out_b + 2 * nbytes(st.chunk))
        elif st.kind == "alltoall":
            g = len(st.groups[0])
            slots_b = st.cpr * g * nbytes(st.chunk)
            wire += (g - 1) * st.cpr * nbytes(st.chunk)
            peak = max(peak, in_b + out_b + 2 * slots_b)
        elif st.kind in ("allgather", "reduce_scatter"):
            g = len(st.groups[0]) if st.groups else size
            small = min(nbytes(st.in_shape), nbytes(st.out_shape))
            wire += (g - 1) * small
            peak = max(peak, nbytes(st.in_shape) + nbytes(st.out_shape))
        else:  # slice / pad: local
            peak = max(peak, nbytes(st.in_shape) + nbytes(st.out_shape))
    return wire, peak


def _transition_key(src_lay, dst_lay, global_shape) -> str:
    return (f"{src_lay.describe()}->{dst_lay.describe()}"
            f"@{'x'.join(str(s) for s in global_shape)}")


def _assemble(steps, strategy, size, routes, dtype, transition):
    import numpy as _np

    itemsize = _np.dtype(dtype).itemsize
    wire, peak = _estimates(steps, routes.in_shape, routes.out_shape,
                            itemsize, size)
    return ReshardPlan(steps=tuple(steps), strategy=strategy, size=size,
                       in_shape=routes.in_shape,
                       out_shape=routes.out_shape, dtype=str(dtype),
                       wire_bytes=wire, peak_bytes=peak,
                       transition=transition)


def _candidates(src_lay, dst_lay, global_shape, routes,
                with_gather=None):
    """(strategy, steps) for every applicable strategy, in auto
    preference order (cheapest peak memory first; ``gather`` last and
    never auto-picked).  ``with_gather`` overrides the historical
    src_lay-presence gate (resize routes have no source Layout but DO
    want the gather baseline — it is the full-restart oracle the bench
    compares the live replan against)."""
    if with_gather is None:
        with_gather = src_lay is not None
    out = []
    for name in STRATEGIES:
        if name == "local":
            steps = _build_local(routes)
        elif name == "permute":
            steps = _build_permute(routes)
        elif name == "allgather":
            steps = (_build_allgather(src_lay, dst_lay, global_shape)
                     if dst_lay is not None else None)
        elif name == "alltoall":
            steps = _build_alltoall(routes)
        elif name == "rounds":
            steps = _build_rounds(routes)
        else:
            steps = _build_gather(routes) if with_gather else None
        if steps is not None:
            out.append((name, steps))
    return out


def _pick(cands, dtype, nbytes, size, transition):
    """Auto selection: the measured tune-cache winner for this
    transition when one names an applicable strategy, else the first
    (cheapest-peak) applicable candidate.  ``gather`` only ever wins
    through the cache."""
    names = [n for n, _ in cands]
    from ..tune import lookup_algorithm

    winner = lookup_algorithm("reshard", dtype, nbytes, size,
                              transition=transition)
    if winner in names:
        return winner
    for n in names:
        if n != "gather":
            return n
    return names[0]


def _resolve_strategy(strategy) -> Optional[str]:
    if strategy is None:
        strategy = _config.default_reshard_strategy()
    if strategy in (None, "auto"):
        return None
    if strategy not in STRATEGIES:
        raise CommError(
            f"unknown reshard strategy {strategy!r}; expected one of "
            f"{STRATEGIES} or 'auto'")
    return strategy


@functools.lru_cache(maxsize=256)
def _plan_cached(src_lay, dst_lay, global_shape, dtype, strategy,
                 _gen):
    routes = _compute_routes(src_lay, dst_lay, global_shape)
    cands = _candidates(src_lay, dst_lay, global_shape, routes)
    trans = _transition_key(src_lay, dst_lay, global_shape)
    import numpy as _np

    nbytes = int(math.prod(routes.in_shape)) * _np.dtype(dtype).itemsize
    if strategy is None:
        name = _pick(cands, dtype, nbytes, src_lay.size, trans)
    else:
        name = strategy
        if name not in [n for n, _ in cands]:
            raise CommError(
                f"reshard strategy {name!r} cannot serve the transition "
                f"{trans} (applicable: {[n for n, _ in cands]})")
    steps = dict(cands)[name]
    return _assemble(steps, name, src_lay.size, routes, dtype, trans)


def plan_reshard(from_layout: Layout, to_layout: Layout, global_shape,
                 dtype, strategy=None) -> ReshardPlan:
    """Plan the (mesh, spec) -> (mesh', spec') transition of one array.

    ``strategy=None`` defers to :func:`mpi4torch_tpu.config.
    default_reshard_strategy` (``"auto"`` = preference order + the
    autotuner cache's transition-keyed winner); an explicit strategy
    that cannot serve the transition raises.  Plans are cached per
    (transition, shape, dtype, strategy) and invalidated with the tune
    cache generation."""
    if from_layout.size != to_layout.size:
        raise CommError(
            f"transition changes the world size: {from_layout.size} "
            f"ranks -> {to_layout.size} (elastic resize must go through "
            "checkpoint restore, utils/checkpoint.restore_resharded)")
    import numpy as _np

    from ..tune import generation

    return _plan_cached(from_layout, to_layout,
                        tuple(int(s) for s in global_shape),
                        str(_np.dtype(dtype)), _resolve_strategy(strategy),
                        generation())


@functools.lru_cache(maxsize=256)
def _perm_plan_cached(lay, axis, perm, global_shape, dtype, strategy,
                      _gen):
    routes = _permutation_routes(lay, axis, perm, global_shape)
    cands = [(n, s) for n, s in _candidates(None, None, global_shape,
                                            routes)]
    trans = (f"{lay.describe()}@perm{axis}:"
             f"{'x'.join(str(s) for s in global_shape)}")
    import numpy as _np

    nbytes = int(math.prod(routes.in_shape)) * _np.dtype(dtype).itemsize
    if strategy is None:
        name = _pick(cands, dtype, nbytes, lay.size, trans)
    else:
        name = strategy
        if name not in [n for n, _ in cands]:
            raise CommError(
                f"reshard strategy {name!r} cannot serve the block "
                f"permutation {trans}")
    steps = dict(cands)[name]
    return _assemble(steps, name, lay.size, routes, dtype, trans)


def plan_permutation(lay: Layout, axis: int, perm, global_shape, dtype,
                     strategy=None) -> ReshardPlan:
    """Plan a block permutation along ``axis`` under a fixed layout —
    the MoE expert-rebalancing transition: unit ``u`` of the result
    holds old unit ``perm[u]``.  Same strategies, caching and adjoint
    contract as :func:`plan_reshard` (``gather`` is deliberately
    excluded from the candidate set here — a permutation never wants
    the full-materialization baseline)."""
    from ..tune import generation

    import numpy as _np

    return _perm_plan_cached(lay, int(axis), tuple(int(p) for p in perm),
                             tuple(int(s) for s in global_shape),
                             str(_np.dtype(dtype)),
                             _resolve_strategy(strategy), generation())


# ---------------------------------------------------------------------------
# Elastic world resize: axis-0 redistribution ACROSS world sizes.
# ---------------------------------------------------------------------------
#
# plan_reshard deliberately refuses transitions that change the world
# size — within one world there is nothing a size change could mean.
# The elastic runtime (mpi4torch_tpu.elastic) needs exactly that
# transition: state dealt over W ranks re-dealt over M ranks, executed
# on whichever world holds both memberships (the OLD world for a
# graceful drain — every source rank still alive — or the NEW world for
# a grow, with the survivors embedded among the joiners).  The from/to
# deals are the repo's standard axis-0 conventions: ``n`` leading units
# (ZeRO's padded flat elements, TP's heads, MoE's stacked experts)
# ceil-split into ``per = ceil(n / size)`` units per rank, the tail
# rank zero-padded.  Because every shard boundary is a multiple of
# ``gcd(per_from, per_to)``, chunking at that gcd puts each chunk
# inside exactly one source shard and one target shard — the same
# uniform-chunk _Routes the existing strategy builders and BOTH
# executors already serve, so a resize plan is an ordinary ReshardPlan:
# permute/alltoall/rounds candidates, the gather baseline (= the
# full-restart restore every rank re-materializes — the bench's
# comparison), adjoint() = the reverse (grow-back) plan, and the
# custom_vjp discipline via executor.apply_plan.


def _resize_routes(n: int, row: Tuple[int, ...], from_size: int,
                   to_size: int, embed_from, embed_to,
                   exec_size: int) -> _Routes:
    per_f = -(-n // from_size)
    per_t = -(-n // to_size)
    c = math.gcd(per_f, per_t)
    nd = 1 + len(row)
    in_shape = (per_f,) + row
    out_shape = (per_t,) + row
    chunk = (c,) + row
    # Route every chunk that carries logical data (start < n); chunks
    # fully inside the padding are zeros on both sides and the output
    # buffer starts as zeros, so routing them would be wire for nothing.
    wants = []
    zero_tail = (0,) * len(row)
    for k in range(min(-(-n // c), (per_t * to_size) // c)):
        start = k * c
        i = start // per_f               # source deal position
        j = start // per_t               # target deal position
        wants.append((embed_to[j], [embed_from[i]],
                      (start - i * per_f,) + zero_tail,
                      (start - j * per_t,) + zero_tail))
    return _routes_from_wants(exec_size, chunk, in_shape, out_shape,
                              wants)


@functools.lru_cache(maxsize=256)
def _resize_plan_cached(n, row, from_size, to_size, embed_from,
                        embed_to, exec_size, dtype, strategy, _gen):
    routes = _resize_routes(n, row, from_size, to_size, embed_from,
                            embed_to, exec_size)
    cands = _candidates(None, None, (n,) + row, routes,
                        with_gather=True)
    trans = (f"resize[{from_size}->{to_size}]"
             f"@{'x'.join(str(s) for s in (n,) + row)}"
             f"/exec{exec_size}:{_fnv_embed(embed_from, embed_to)}")
    import numpy as _np

    nbytes = int(math.prod(routes.in_shape)) * _np.dtype(dtype).itemsize
    if strategy is None:
        name = _pick(cands, dtype, nbytes, exec_size, trans)
    else:
        name = strategy
        if name not in [nm for nm, _ in cands]:
            raise CommError(
                f"reshard strategy {name!r} cannot serve the resize "
                f"{trans} (applicable: {[nm for nm, _ in cands]})")
    steps = dict(cands)[name]
    return _assemble(steps, name, exec_size, routes, dtype, trans)


def _fnv_embed(embed_from, embed_to) -> str:
    """Short stable fingerprint of the embedding maps for the
    transition key (full tuples would make tune-cache keys unwieldy on
    big worlds)."""
    h = 0x811C9DC5
    for v in (*embed_from, -1, *embed_to):
        h ^= (v + 2) & 0xFF
        h = (h * 0x01000193) & 0xFFFFFFFF
    return f"{h:08x}"


def plan_resize(n: int, row_shape, from_size: int, to_size: int, dtype,
                *, embed_from, embed_to, exec_size: int,
                strategy=None) -> ReshardPlan:
    """Plan the elastic axis-0 re-deal of ``n`` leading units (each of
    shape ``row_shape``) from a ``from_size``-way ceil-split to a
    ``to_size``-way ceil-split, executed on a world of ``exec_size``
    ranks that embeds both memberships:

    * ``embed_from[i]`` — the executing rank holding source deal
      position ``i``'s shard (ranks outside the map feed a zeros
      buffer of the source shard shape);
    * ``embed_to[j]`` — the executing rank that ends with target deal
      position ``j``'s shard (ranks outside the map end with zeros).

    A shrink drain runs on the OLD world (``exec_size == from_size``,
    ``embed_from`` identity, ``embed_to`` = the survivors' old ranks);
    a grow runs on the NEW world (``embed_to`` identity, ``embed_from``
    = the survivors' new ranks).  Same strategy set, caching, adjoint
    (= the reverse resize) and executor contract as
    :func:`plan_reshard`; ``gather`` is the explicit full-restart
    baseline and is never auto-picked."""
    n = int(n)
    from_size, to_size = int(from_size), int(to_size)
    exec_size = int(exec_size)
    if n < 1 or from_size < 1 or to_size < 1:
        raise CommError(
            f"plan_resize needs n >= 1 and positive world sizes; got "
            f"n={n}, {from_size}->{to_size}")
    embed_from = tuple(int(r) for r in embed_from)
    embed_to = tuple(int(r) for r in embed_to)
    if len(embed_from) != from_size or len(embed_to) != to_size:
        raise CommError(
            f"embed_from/embed_to must map every deal position: need "
            f"lengths {from_size}/{to_size}, got "
            f"{len(embed_from)}/{len(embed_to)}")
    for name, emb in (("embed_from", embed_from), ("embed_to", embed_to)):
        if any(not (0 <= r < exec_size) for r in emb):
            raise CommError(
                f"{name} names ranks outside the executing world "
                f"(size {exec_size}): {emb}")
        if len(set(emb)) != len(emb):
            raise CommError(
                f"{name} maps two deal positions onto one executing "
                f"rank ({emb}) — each rank holds ONE uniform shard "
                "buffer per side")
    import numpy as _np

    from ..tune import generation

    return _resize_plan_cached(
        n, tuple(int(s) for s in row_shape), from_size, to_size,
        embed_from, embed_to, exec_size, str(_np.dtype(dtype)),
        _resolve_strategy(strategy), generation())

"""Partition rules: regex -> Layout over whole state pytrees.

The facade pattern of SNIPPETS.md [3] (``match_partition_rules``):
instead of hand-writing a Layout per leaf of a transformer state tree,
write a short ordered rule list — first regex matching the leaf's
``/``-joined tree path wins::

    rules = [
        (r"embed",        reshard.layout((2, 4), (0, 1))),   # rows
        (r"attn/w_[qkvo]", reshard.layout((2, 4), None, 1)),  # columns
        (r".*",           reshard.layout((2, 4), None)),      # replicate
    ]
    to_specs = reshard.match_partition_rules(rules, params)
    sharded = comm.Reshard(shards, from_specs, to_specs)

Scalar leaves never partition (the snippet's rule) — they take the
replicated layout of the first rule's mesh.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import numpy as np

from ..runtime import CommError
from .plan import Layout

__all__ = ["tree_paths", "match_partition_rules"]


def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def tree_paths(tree, sep: str = "/"):
    """A pytree of the same structure whose leaves are the
    ``sep``-joined key paths (``{"a": {"b": [x]}} -> "a/b/0"``)."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree.unflatten(
        treedef, [sep.join(_key_str(k) for k in path)
                  for path, _ in paths_leaves])


def match_partition_rules(rules: Sequence[Tuple[str, Layout]], tree,
                          sep: str = "/"):
    """A Layout pytree for ``tree``: each leaf takes the first rule
    whose regex ``re.search``-matches its path.  Scalar (0-d or
    1-element) leaves take the replicated form of the first rule's
    mesh; a leaf no rule matches raises (a silent default would shard
    a tensor the author never considered)."""
    rules = [(p, lay) for p, lay in rules]
    if not rules:
        raise CommError("match_partition_rules needs at least one rule")
    mesh = rules[0][1].mesh

    def pick(path, leaf):
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return Layout(mesh, ((),) * len(shape))
        for pattern, lay in rules:
            if re.search(pattern, path) is not None:
                if lay.ndim != len(shape):
                    raise CommError(
                        f"rule {pattern!r} assigns a {lay.ndim}-axis "
                        f"layout to {path!r} of shape {shape}")
                return lay
        raise CommError(f"no partition rule matches leaf {path!r} "
                        f"(shape {shape}); add a catch-all rule")

    paths = tree_paths(tree, sep)
    return jax.tree.map(pick, paths, tree)

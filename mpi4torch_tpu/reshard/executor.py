"""The reshard executor: one plan, two backends, bit-identical results.

Mode A (SPMD mesh) lowers each plan step to the native collective —
``collective_permute`` for permute rounds, grouped ``all_to_all`` /
``all_gather`` / ``psum_scatter`` for the exchange and coarsening steps,
``dynamic_slice``/``dynamic_update_slice`` with per-rank constant tables
for the local moves.  Mode B (eager thread world) replays the SAME plan
through the rendezvous (``World.exchange``), which buys two things for
free: bitwise cross-mode parity (every step is pure data movement; the
one reduction — the all-gather adjoint — folds in ascending group order
under ``deterministic_mode``, the eager oracle's association), and the
:mod:`mpi4torch_tpu.resilience` fault grammar (the rendezvous and p2p
mailboxes are the chokepoints every injected fault rides).

The facade entry (:func:`reshard_value` / :func:`reshard_tree`, surfaced
as ``comm.Reshard``) wraps the whole plan in ONE ``jax.custom_vjp``
whose backward executes :meth:`ReshardPlan.adjoint` — the reverse plan —
on the cotangents: spec' -> spec redistribution, the
adjoint-is-itself-a-collective contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import config as _config
from ..runtime import CommError
from .plan import (Layout, ReshardPlan, _MOVE_KINDS, plan_permutation,
                   plan_reshard)

__all__ = [
    "apply_plan", "execute_plan", "reshard_value", "reshard_tree",
    "gather_then_slice", "slice_shard", "shard_of", "shard_template",
    "global_template",
]


def as_layout(spec) -> Layout:
    if isinstance(spec, Layout):
        return spec
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        return Layout(tuple(spec[0]), tuple(spec[1]))
    raise CommError(
        f"expected a reshard Layout (or a (mesh, spec) pair); got "
        f"{spec!r}")


# ---------------------------------------------------------------------------
# Shared pipeline driver
# ---------------------------------------------------------------------------


def _run(plan: ReshardPlan, x, move_fn, transform_fn):
    """Thread the value through the step program: move steps
    (slice/pad/permute/alltoall) fill a zeros output buffer; transform
    steps (allgather/reduce_scatter) map value -> value; a transform
    following a move phase consumes that phase's buffer."""
    if tuple(x.shape) != plan.in_shape:
        raise CommError(
            f"Reshard input shard has shape {tuple(x.shape)}, but the "
            f"plan for {plan.transition} expects {plan.in_shape}")
    v, out = x, None
    for i, st in enumerate(plan.steps):
        with jax.named_scope(f"mpi4torch.Reshard.{st.kind}"):
            if st.kind in _MOVE_KINDS:
                if out is None:
                    out = jnp.zeros(st.out_shape, x.dtype)
                out = move_fn(i, st, v, out)
            else:
                if out is not None:
                    v, out = out, None
                v = transform_fn(i, st, v)
    return out if out is not None else v


def _dslice(buf, starts, shape):
    return lax.dynamic_slice(buf, tuple(starts), shape)


def _dput(buf, starts, val):
    return lax.dynamic_update_slice(buf, val, tuple(starts))


def _place(out, starts, val, valid, accumulate, chunk):
    cur = _dslice(out, starts, chunk)
    if accumulate:
        new = cur + jnp.where(valid, val, jnp.zeros_like(val))
    else:
        new = jnp.where(valid, val, cur)
    return _dput(out, starts, new)


# ---------------------------------------------------------------------------
# Mode A: SPMD lowering
# ---------------------------------------------------------------------------


def _rank_row(ctx, table):
    """This rank's row of a static per-rank table, as traced values."""
    idx = lax.axis_index(ctx.axis_name)
    return jnp.asarray(np.asarray(table))[idx]


def _spmd_local(ctx, st, v, out):
    valids = tuple(tuple(m[0] for m in per) for per in st.moves)
    srcs = tuple(tuple(m[1] for m in per) for per in st.moves)
    dsts = tuple(tuple(m[2] for m in per) for per in st.moves)
    nmoves = len(st.moves[0])
    vtab = _rank_row(ctx, valids)
    stab = _rank_row(ctx, srcs)
    dtab = _rank_row(ctx, dsts)
    accumulate = st.kind == "pad"
    for m in range(nmoves):
        chunk = _dslice(v, [stab[m, i] for i in range(len(st.src_chunk))],
                        st.src_chunk)
        chunk = chunk.reshape(st.dst_chunk)
        out = _place(out, [dtab[m, i] for i in range(len(st.dst_chunk))],
                     chunk, vtab[m], accumulate, st.dst_chunk)
    return out


def _spmd_permute(ctx, st, v, out):
    nd = len(st.chunk)
    sv = _rank_row(ctx, tuple(bool(s[0]) for s in st.send))
    ss = _rank_row(ctx, tuple(s[1] for s in st.send))
    rv = _rank_row(ctx, tuple(bool(r[0]) for r in st.recv))
    rs = _rank_row(ctx, tuple(r[1] for r in st.recv))
    buf = _dslice(v, [ss[i] for i in range(nd)], st.chunk)
    buf = jnp.where(sv, buf, jnp.zeros_like(buf))
    n = len(st.table)
    pairs = [(i, st.table[i]) for i in range(n) if st.table[i] != i]
    if pairs:
        got = lax.ppermute(buf, ctx.axis_name, perm=pairs)
        selfs = tuple(st.table[i] == i for i in range(n))
        if any(selfs):
            # Self-pairs are local hand-offs (the emitted permute only
            # carries the real moves); those ranks keep their own chunk.
            got = jnp.where(_rank_row(ctx, selfs), buf, got)
    else:
        got = buf
    return _place(out, [rs[i] for i in range(nd)], got, rv,
                  st.accumulate, st.chunk)


def _spmd_alltoall(ctx, st, v, out):
    nd = len(st.chunk)
    slots = len(st.send[0])
    stab = _rank_row(ctx, st.send)       # (slots, nd)
    rtab = _rank_row(ctx, st.recv)
    pieces = [
        _dslice(v, [stab[t, i] for i in range(nd)], st.chunk)
        for t in range(slots)]
    buf = jnp.stack(pieces)
    got = lax.all_to_all(buf, ctx.axis_name, split_axis=0, concat_axis=0,
                         axis_index_groups=[list(g) for g in st.groups],
                         tiled=True)
    true = jnp.asarray(True)
    for t in range(slots):
        out = _place(out, [rtab[t, i] for i in range(nd)], got[t], true,
                     st.accumulate, st.chunk)
    return out


def _spmd_allgather(ctx, st, v, codec=None):
    if st.axis is None:
        if codec is not None:
            from ..compress import spmd as _cspmd

            return _cspmd.allgather(ctx, v[None], 0, codec)
        return lax.all_gather(v, ctx.axis_name, axis=0, tiled=False)
    return lax.all_gather(v, ctx.axis_name, axis=st.axis, tiled=True,
                          axis_index_groups=[list(g) for g in st.groups])


def _group_pos(groups, size):
    pos = [0] * size
    for g in groups:
        for p, r in enumerate(g):
            pos[r] = p
    return tuple(pos)


def _spmd_reduce_scatter(ctx, st, v):
    if st.axis is None:
        # Stack form: input (N, *shard); each rank keeps the rank-sum's
        # row at its own index.
        n = ctx.size
        if _config.deterministic_reductions():
            stacked = lax.all_gather(v, ctx.axis_name, axis=0, tiled=False)
            acc = stacked[0]
            for i in range(1, n):
                acc = acc + stacked[i]
            idx = lax.axis_index(ctx.axis_name)
            return lax.dynamic_index_in_dim(acc, idx, 0, keepdims=False)
        flat = v.reshape(n, -1)
        part = lax.psum_scatter(flat, ctx.axis_name, scatter_dimension=0,
                                tiled=True)
        return part.reshape(st.out_shape)
    groups = [list(g) for g in st.groups]
    g = len(groups[0])
    if _config.deterministic_reductions():
        stacked = lax.all_gather(v, ctx.axis_name, axis=0, tiled=False,
                                 axis_index_groups=groups)
        acc = stacked[0]
        for i in range(1, g):
            acc = acc + stacked[i]
        pos = _rank_row(ctx, _group_pos(st.groups, ctx.size))
        seg = st.out_shape[st.axis]
        return lax.dynamic_slice_in_dim(acc, pos * seg, seg, st.axis)
    return lax.psum_scatter(v, ctx.axis_name, scatter_dimension=st.axis,
                            axis_index_groups=groups, tiled=True)


_SPMD_EXEC = {
    "slice": _spmd_local,
    "pad": _spmd_local,
    "permute": _spmd_permute,
    "alltoall": _spmd_alltoall,
    "allgather": _spmd_allgather,
    "reduce_scatter": _spmd_reduce_scatter,
}


def _exec_spmd(ctx, plan: ReshardPlan, x, codec=None):
    def move(i, st, v, out):
        return _SPMD_EXEC[st.kind](ctx, st, v, out)

    def transform(i, st, v):
        if st.kind == "allgather":
            return _spmd_allgather(ctx, st, v, codec)
        return _SPMD_EXEC[st.kind](ctx, st, v)

    return _run(plan, jnp.asarray(x), move, transform)


# ---------------------------------------------------------------------------
# Mode B: rendezvous replay
# ---------------------------------------------------------------------------


def _npslice(buf, starts, shape):
    return buf[tuple(slice(int(s), int(s) + c)
                     for s, c in zip(starts, shape))]


def _npput(buf, starts, val, accumulate):
    idx = tuple(slice(int(s), int(s) + c)
                for s, c in zip(starts, val.shape))
    return buf.at[idx].add(val) if accumulate else buf.at[idx].set(val)


def _esig(st, i, v):
    """Rendezvous signature of one eager plan step.  Carries the step's
    replica-group size (plan state — identical on every rank) so the
    obs tracer can price grouped steps with the standard accountings
    (a grouped all_to_all ships (g-1)/g of the payload, not
    (world-1)/world; mpi4torch_tpu.obs.reconcile); ``None`` means the
    whole communicator participates."""
    groups = getattr(st, "groups", None)
    gs = len(groups[0]) if groups else None
    return (f"Reshard.{st.kind}", i, gs, tuple(v.shape),
            str(jnp.asarray(v).dtype))


def _eager_local(ectx, i, st, v, out):
    accumulate = st.kind == "pad"
    for valid, src, dst in st.moves[ectx.rank]:
        if not valid:
            continue
        chunk = _npslice(v, src, st.src_chunk).reshape(st.dst_chunk)
        out = _npput(out, dst, chunk, accumulate)
    return out


def _eager_permute(ectx, i, st, v, out):
    world, rank = ectx.world, ectx.rank
    sv, ss = st.send[rank]
    buf = (_npslice(v, ss, st.chunk) if sv
           else jnp.zeros(st.chunk, v.dtype))
    vals = world.exchange(rank, _esig(st, i, buf), buf)
    src = st.table.index(rank)
    rv, rs = st.recv[rank]
    if rv:
        out = _npput(out, rs, vals[src], st.accumulate)
    return out


def _eager_alltoall(ectx, i, st, v, out):
    world, rank = ectx.world, ectx.rank
    buf = jnp.stack([_npslice(v, s, st.chunk) for s in st.send[rank]])
    vals = world.exchange(rank, _esig(st, i, buf), buf)
    grp = next(g for g in st.groups if rank in g)
    pos = grp.index(rank)
    for t, dst in enumerate(st.recv[rank]):
        p, k = divmod(t, st.cpr)
        piece = vals[grp[p]][pos * st.cpr + k]
        out = _npput(out, dst, piece, st.accumulate)
    return out


def _eager_allgather(ectx, i, st, v, codec=None):
    world, rank = ectx.world, ectx.rank
    if st.axis is None and codec is not None:
        from ..compress import eager as _ceager

        return _ceager.allgather(ectx, v[None], 0, codec)
    vals = world.exchange(rank, _esig(st, i, v), v)
    if st.axis is None:
        return jnp.stack(vals)
    grp = next(g for g in st.groups if rank in g)
    return jnp.concatenate([vals[m] for m in grp], axis=st.axis)


def _eager_reduce_scatter(ectx, i, st, v):
    world, rank = ectx.world, ectx.rank
    vals = world.exchange(rank, _esig(st, i, v), v)
    if st.axis is None:
        acc = vals[0]
        for w in vals[1:]:
            acc = acc + w
        return acc[rank]
    grp = next(g for g in st.groups if rank in g)
    acc = vals[grp[0]]
    for m in grp[1:]:
        acc = acc + vals[m]
    pos = grp.index(rank)
    seg = st.out_shape[st.axis]
    sl = [slice(None)] * acc.ndim
    sl[st.axis] = slice(pos * seg, (pos + 1) * seg)
    return acc[tuple(sl)]


_EAGER_EXEC = {
    "slice": _eager_local,
    "pad": _eager_local,
    "permute": _eager_permute,
    "alltoall": _eager_alltoall,
    "allgather": _eager_allgather,
    "reduce_scatter": _eager_reduce_scatter,
}


def _exec_eager(ectx, plan: ReshardPlan, x, codec=None):
    from ..ops.eager import _check_concrete

    x = jnp.asarray(x)
    _check_concrete(x)

    def move(i, st, v, out):
        return _EAGER_EXEC[st.kind](ectx, i, st, v, out)

    def transform(i, st, v):
        if st.kind == "allgather":
            return _eager_allgather(ectx, i, st, v, codec)
        return _EAGER_EXEC[st.kind](ectx, i, st, v)

    return _run(plan, x, move, transform)


# ---------------------------------------------------------------------------
# Dispatch + facade
# ---------------------------------------------------------------------------


def execute_plan(comm, plan: ReshardPlan, x, codec=None):
    """Run a compiled plan on ``comm``'s backend (no AD wrapper — use
    :func:`reshard_value` for the differentiable form)."""
    from ..comm import _EagerBackend
    from ..ops.spmd import SpmdBackend, TierStackBackend

    backend = comm._backend()
    if isinstance(backend, TierStackBackend):
        raise CommError(
            "Reshard needs a flat communicator (the virtual mesh lives "
            "in the Layouts); use comm_from_mesh with ONE axis name or "
            "COMM_WORLD")
    size = backend.size
    if size != plan.size:
        raise CommError(
            f"plan for {plan.transition} spans {plan.size} ranks, but "
            f"this communicator has {size}")
    if isinstance(backend, SpmdBackend):
        return _exec_spmd(backend._ctx, plan, x, codec)
    if isinstance(backend, _EagerBackend):
        return _exec_eager(backend._ctx, plan, x, codec)
    raise CommError(
        "Reshard needs the eager thread world (run_ranks) or an SPMD "
        "mesh communicator; this backend supports neither")


def _resolve_reshard_codec(compression, dtype, plan):
    """Reshard transports state, not gradients: scope/process codec
    defaults are deliberately ignored (a lossy migration must be
    explicitly requested).  An explicit codec needs a floating dtype and
    a wide hop (a full-world gather step) to ride."""
    if compression is None or compression is False or \
            compression == "none":
        return None
    from ..compress import codec_applicable, get_codec

    codec = get_codec(compression)
    if codec is None:
        return None
    if not codec_applicable(codec, dtype):
        raise ValueError(
            f"compression={codec.name!r} requires a floating tensor; "
            f"got dtype {dtype}")
    wide = any(st.kind == "allgather" and st.axis is None
               for st in plan.steps)
    if not wide:
        raise ValueError(
            f"compression={codec.name!r} rides the wide full-world "
            f"gather hop, and the {plan.strategy!r} plan for "
            f"{plan.transition} has none — drop compression= (the "
            "planned exchange already moves O(shard) bytes) or pin "
            "strategy='gather'")
    return codec


def _apply_plan_vjp(comm, plan: ReshardPlan, x, codec):
    @jax.custom_vjp
    def f(v):
        return execute_plan(comm, plan, v, codec)

    def bwd(_, g):
        # The reverse plan: cotangents redistribute spec' -> spec.  The
        # adjoint is exact even when the forward hop was compressed
        # (compression is an opt-in forward transport, not a gradient
        # codec here).
        with jax.named_scope("mpi4torch.ReshardBackward"):
            return (execute_plan(comm, plan.adjoint(), g, None),)

    f.defvjp(lambda v: (execute_plan(comm, plan, v, codec), None), bwd)
    return f(x)


def apply_plan(comm, plan: ReshardPlan, x, *, differentiable=True):
    """Execute an already-compiled :class:`ReshardPlan` on ``comm`` —
    the entry the elastic resize plans use (:func:`~mpi4torch_tpu.
    reshard.plan_resize` builds plans outside the Layout-pair facade,
    so there is no from/to spec to re-derive them from).
    ``differentiable=True`` wraps the execution in the standard
    custom_vjp whose backward runs ``plan.adjoint()`` — for a resize
    plan that reverse IS the grow-back (or re-shrink) program, so
    training graphs that cross a resize stay AD-transparent."""
    if differentiable:
        return _apply_plan_vjp(comm, plan, x, None)
    return execute_plan(comm, plan, x)


def reshard_value(comm, x, from_spec, to_spec, strategy=None,
                  compression=None):
    """Redistribute one array shard from ``from_spec`` to ``to_spec``
    (both :class:`Layout`); differentiable, the VJP being the reverse
    plan."""
    x = jnp.asarray(x)
    fl, tl = as_layout(from_spec), as_layout(to_spec)
    gshape = fl.global_shape(x.shape)
    plan = plan_reshard(fl, tl, gshape, x.dtype, strategy)
    codec = _resolve_reshard_codec(compression, x.dtype, plan)
    return _apply_plan_vjp(comm, plan, x, codec)


def _spec_tree(spec, tree):
    """Broadcast a single Layout over the tree, or validate a matching
    Layout pytree (Layout is not a registered pytree node, so Layouts
    are leaves)."""
    if isinstance(spec, Layout):
        return jax.tree.map(lambda _: spec, tree)
    lays = jax.tree.map(as_layout, spec)
    if jax.tree.structure(lays) != jax.tree.structure(tree):
        raise CommError(
            "from_spec/to_spec must be one Layout or a pytree of "
            f"Layouts matching the state tree; got structure "
            f"{jax.tree.structure(lays)} vs {jax.tree.structure(tree)}")
    return lays


def reshard_tree(comm, tree, from_spec, to_spec, strategy=None,
                 compression=None):
    """The pytree form behind ``comm.Reshard``: per-leaf layouts (one
    Layout broadcast over the tree, or a matching pytree of Layouts —
    build one from regex rules with :func:`mpi4torch_tpu.reshard.
    match_partition_rules`)."""
    fls = _spec_tree(from_spec, tree)
    tls = _spec_tree(to_spec, tree)
    return jax.tree.map(
        lambda x, fl, tl: reshard_value(comm, x, fl, tl,
                                        strategy=strategy,
                                        compression=compression),
        tree, fls, tls)


def gather_then_slice(comm, x, from_spec, to_spec):
    """The baseline/oracle transition: gather the full array on every
    rank, slice the target shard — ``O(full array)`` peak live bytes,
    which is exactly what the planner exists to avoid.  Every planned
    transition must be bitwise-equal to this."""
    return reshard_value(comm, x, from_spec, to_spec, strategy="gather")


def reshard_blocks(comm, tree, lay, axis, perm, strategy=None):
    """Apply a block permutation along ``axis`` (see
    :func:`mpi4torch_tpu.reshard.plan_permutation`) to every leaf — the
    MoE expert-rebalancing transport.  Differentiable; the VJP applies
    the inverse permutation."""
    lay = as_layout(lay)

    def one(x):
        x = jnp.asarray(x)
        plan = plan_permutation(lay, axis, perm, lay.global_shape(x.shape),
                                x.dtype, strategy)
        return _apply_plan_vjp(comm, plan, x, None)

    return jax.tree.map(one, tree)


# ---------------------------------------------------------------------------
# Host-side shard helpers (checkpoint migration I/O)
# ---------------------------------------------------------------------------


def slice_shard(arr, lay: Layout, rank: int):
    """``rank``'s shard of a GLOBAL array under ``lay`` (host-side
    slicing — the simulation of orbax's native sharded restore on the
    CPU harness)."""
    lay = as_layout(lay)
    shard = lay.shard_shape(np.shape(arr))
    block = lay.block(int(rank))
    idx = tuple(slice(b * s, (b + 1) * s) for b, s in zip(block, shard))
    return jnp.asarray(arr)[idx]


def _leaf_dtype(x):
    return getattr(x, "dtype", None) or jnp.asarray(x).dtype


def shard_of(tree, spec, rank: int):
    """Tree-mapped :func:`slice_shard`."""
    lays = _spec_tree(spec, tree)
    return jax.tree.map(lambda x, l: slice_shard(x, l, rank), tree, lays)


def shard_template(tree, spec):
    """ShapeDtypeStruct tree of the per-rank shards of a global-shaped
    template under ``spec`` (rank-independent: every shard has the same
    shape)."""
    lays = _spec_tree(spec, tree)
    return jax.tree.map(
        lambda x, l: jax.ShapeDtypeStruct(l.shard_shape(np.shape(x)),
                                          _leaf_dtype(x)),
        tree, lays)


def global_template(tree, spec):
    """ShapeDtypeStruct tree of the GLOBAL arrays whose shards a
    shard-shaped template describes under ``spec``."""
    lays = _spec_tree(spec, tree)
    return jax.tree.map(
        lambda x, l: jax.ShapeDtypeStruct(l.global_shape(np.shape(x)),
                                          _leaf_dtype(x)),
        tree, lays)

"""Deterministic peak-live-bytes census of a lowered StableHLO program.

The repo's perf-evidence currency is deterministic estimators read off
the lowering (HLO op counts, wire bytes, scheduled exposure — ROADMAP);
this module adds the memory leg: a last-use liveness scan over the
module's SSA values.  Each ``%v = op ... : ... -> tensor<...>`` line
defines a value of known byte size; a value stays live from its
definition to its last textual use; the census is the maximum over
program points of the live-set byte total (function arguments included).

This is an *estimator* — XLA's buffer assignment can alias and fuse —
but it is exact about what the planner controls: a program that
materializes an ``N x shard`` gather carries an N-times-shard tensor
through its liveness range no matter how it is scheduled, while the
planned exchange never defines one.  Planned-vs-gather comparisons run
both programs through the same scan, so systematic bias cancels; the
``peak_memory_bounded`` verdict (bench.py ``_bench_reshard``, `make
reshard-smoke`) is the strict inequality between the two.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

__all__ = ["peak_live_bytes", "tensor_bytes"]

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_DEF_RE = re.compile(r"^\s*(%[\w.#-]+)(?::\d+)?\s*=")
_ARG_RE = re.compile(r"(%arg\d+):\s*tensor<([^>]*)>")
_VAL_RE = re.compile(r"%[\w.#-]+")


def tensor_bytes(desc: str) -> int:
    """Bytes of a ``tensor<...>`` type description (``8x128xf32``)."""
    parts = desc.replace(" ", "").split("x")
    n = _DTYPE_BYTES.get(parts[-1])
    if n is None:
        return 0  # token/tuple/unknown element types carry no buffer
    for d in parts[:-1]:
        if not d.isdigit():
            return 0  # dynamic dims: not produced by these lowerings
        n *= int(d)
    return n


def _result_bytes(line: str) -> int:
    """Byte size of a definition line's result(s): the tensor types
    after ``->`` when the op spells a function type, else the trailing
    type annotation."""
    if "->" in line:
        tail = line.rsplit("->", 1)[1]
    elif ":" in line:
        tail = line.rsplit(":", 1)[1]
    else:
        return 0
    return sum(tensor_bytes(m.group(1))
               for m in _TENSOR_RE.finditer(tail))


def peak_live_bytes(txt: str) -> int:
    """Max over program points of the summed byte sizes of live SSA
    values (see module docstring).  SSA names are per-function scopes,
    so the module is censused function by function and the maximum
    wins (the shard_map body is where the collectives live)."""
    peaks = [0]
    chunk: list = []
    for ln in txt.splitlines():
        if "func.func" in ln and chunk:
            peaks.append(_peak_one(chunk))
            chunk = []
        chunk.append(ln)
    if chunk:
        peaks.append(_peak_one(chunk))
    return max(peaks)


def _peak_one(lines) -> int:
    size: Dict[str, int] = {}
    born: Dict[str, int] = {}
    last: Dict[str, int] = {}
    for i, ln in enumerate(lines):
        for m in _ARG_RE.finditer(ln):
            name, desc = m.group(1), m.group(2)
            if name not in size:
                size[name] = tensor_bytes(desc)
                born[name] = i
                last[name] = i
        d = _DEF_RE.match(ln)
        defined = d.group(1) if d else None
        if defined is not None and defined not in size:
            size[defined] = _result_bytes(ln)
            born[defined] = i
        for m in _VAL_RE.finditer(ln):
            name = m.group(0)
            if name in size:
                last[name] = max(last.get(name, i), i)

    events: Dict[int, Tuple[int, int]] = {}
    for name, b in size.items():
        s, e = events.get(born[name], (0, 0))
        events[born[name]] = (s + b, e)
        s, e = events.get(last[name], (0, 0))
        events[last[name]] = (s, e + b)
    live = peak = 0
    for i in sorted(events):
        add, drop = events[i]
        live += add
        peak = max(peak, live)
        live -= drop
    return peak

"""Deterministic peak-live-bytes census of a lowered StableHLO program.

The repo's perf-evidence currency is deterministic estimators read off
the lowering (HLO op counts, wire bytes, scheduled exposure — ROADMAP);
this module adds the memory leg: a last-use liveness scan over the
module's SSA values.  Each ``%v = op ... : ... -> tensor<...>`` line
defines a value of known byte size; a value stays live from its
definition to its last textual use; the census is the maximum over
program points of the live-set byte total (function arguments included).

This is an *estimator* — XLA's buffer assignment can alias and fuse —
but it is exact about what the planner controls: a program that
materializes an ``N x shard`` gather carries an N-times-shard tensor
through its liveness range no matter how it is scheduled, while the
planned exchange never defines one.  Planned-vs-gather comparisons run
both programs through the same scan, so systematic bias cancels; the
``peak_memory_bounded`` verdict (bench.py ``_bench_reshard``, `make
reshard-smoke`) is the strict inequality between the two.

Since the static verifier landed (:mod:`mpi4torch_tpu.analyze`), the
scan itself lives there as a pass over the shared StableHLO parse
(per-``func.func`` scoping and all) — this module keeps the historical
entry points (and their recorded census values, regression-pinned
bit-identical in tests/test_analyze.py) as delegations.
"""

from __future__ import annotations

from ..analyze.accounting import peak_live_bytes as _peak_live_bytes
from ..analyze.parse import tensor_bytes

__all__ = ["peak_live_bytes", "tensor_bytes"]


def peak_live_bytes(txt: str) -> int:
    """Max over program points of the summed byte sizes of live SSA
    values (see module docstring).  SSA names are per-function scopes,
    so the module is censused function by function and the maximum
    wins (the shard_map body is where the collectives live)."""
    return _peak_live_bytes(txt)

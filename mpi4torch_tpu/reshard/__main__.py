"""`python -m mpi4torch_tpu.reshard --smoke` — the reshard-smoke lane.

An 8-virtual-device sweep (the Makefile's ``reshard-smoke`` target) of
representative (mesh, spec) -> (mesh', spec') transitions.  Every cell:

1. the compiled Mode A result is compared BITWISE against two oracles —
   the numpy assemble-and-slice reference and the executed
   gather-then-slice baseline strategy;
2. the lowered StableHLO of the planned program is censused: its peak
   live bytes (:func:`mpi4torch_tpu.reshard.peak_live_bytes`) must be
   STRICTLY below the gather baseline's — the memory-bounded claim as a
   deterministic inequality, not a wall-clock anecdote;
3. one cell re-runs under ``deterministic_mode`` and one runs its VJP
   (cotangents must land as the reverse redistribution).

Plus the registry-sync guard: the step-kind registry, both executor
dispatch tables, the adjoint closure, and the kinds actually exercised
by the sweep (forward + adjoint plans) must agree — a step kind without
coverage fails the lane.  Exits non-zero on any divergence.
"""

from __future__ import annotations

import sys


def _cases(n: int, factors):
    from . import layout

    cases = [
        ("axis-move", layout((n,), 0, None), layout((n,), None, 0), None),
        ("replicate", layout((n,), 0, None), layout((n,), None, None),
         None),
        ("slice", layout((n,), None, None), layout((n,), 0, None), None),
    ]
    if factors is not None:
        a, b = factors
        cases += [
            ("migrate", layout((n,), 0, None), layout((a, b), 0, 1),
             None),
            ("migrate-T", layout((n,), 0, None), layout((b, a), 0, 1),
             None),
            ("migrate-rounds", layout((n,), 0, None),
             layout((a, b), 0, 1), "rounds"),
            ("coarsen", layout((n,), 0, None), layout((a, b), (0,), None),
             None),
            ("refine", layout((a, b), (0,), None), layout((n,), 0, None),
             None),
            ("block-permute", layout((a, b), (0, 1), None),
             layout((a, b), (1, 0), None), None),
            ("zero-to-tp", layout((n,), 0, None),
             layout((a, b), None, 1), None),
        ]
    return cases


def _smoke() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import reshard as rs
    from mpi4torch_tpu._compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n = len(jax.devices())
    print(f"reshard-smoke: {n} device(s), platform "
          f"{jax.devices()[0].platform}")
    if n < 2:
        print("FAIL: the sweep needs a multi-device world — run via "
              "`make reshard-smoke` (8-virtual-device CPU mesh)")
        return 1
    factors = None
    for a in range(2, n):
        if n % a == 0 and n // a > 1:
            factors = (a, n // a)
            break

    G = (2 * n * 2, n)                       # divisible by every factor
    rng = np.random.default_rng(0)
    full = rng.standard_normal(G).astype(np.float32)

    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    comm = mpi.comm_from_mesh(mesh, "w")

    def np_shard(lay, r):
        return np.asarray(rs.slice_shard(full, lay, r))

    def run_mode_a(fl, tl, strategy, det=False):
        shard = fl.shard_shape(G)
        starts = np.asarray(
            [[b * s for b, s in zip(fl.block(r), shard)]
             for r in range(n)])

        def body():
            c = mpi.COMM_WORLD
            row = jnp.asarray(starts)[jnp.asarray(c.rank + 0)]
            sl = jax.lax.dynamic_slice(
                jnp.asarray(full), tuple(row[i] for i in range(2)), shard)
            with mpi.config.deterministic_mode(det):
                return c.Reshard(sl, fl, tl, strategy=strategy)

        return np.asarray(mpi.run_spmd(body, nranks=n)())

    def lowered(fl, tl, strategy):
        fn = shard_map(
            lambda a: comm.Reshard(a, fl, tl, strategy=strategy),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
        return jax.jit(fn).lower(
            jnp.zeros(fl.shard_shape(G), jnp.float32)).as_text()

    exercised = set()
    failures = 0
    for name, fl, tl, strategy in _cases(n, factors):
        plan = rs.plan_reshard(fl, tl, G, np.float32, strategy)
        adj = plan.adjoint()
        exercised |= {s.kind for s in plan.steps}
        exercised |= {s.kind for s in adj.steps}
        gplan = rs.plan_reshard(fl, tl, G, np.float32, "gather")
        exercised |= {s.kind for s in gplan.steps}
        exercised |= {s.kind for s in gplan.adjoint().steps}

        got = run_mode_a(fl, tl, strategy)
        oracle_np = np.stack([np_shard(tl, r) for r in range(n)])
        oracle_gather = run_mode_a(fl, tl, "gather")
        ok = (np.array_equal(got, oracle_np)
              and np.array_equal(oracle_gather, oracle_np))
        peak_p = rs.peak_live_bytes(lowered(fl, tl, strategy))
        peak_g = rs.peak_live_bytes(lowered(fl, tl, "gather"))
        bounded = (plan.strategy == "gather") or peak_p < peak_g
        if not ok or not bounded:
            failures += 1
            print(f"FAIL {name}: bitwise={ok} peak {peak_p} vs "
                  f"gather {peak_g} (strategy {plan.strategy})")
            continue
        print(f"cell {name:14s} strategy={plan.strategy:9s} "
              f"steps={[s.kind for s in plan.steps]} bitwise=ok "
              f"peak_live {peak_p} < gather {peak_g}")

    # Deterministic-mode leg on the migration cell.
    if factors is not None:
        fl = rs.layout((n,), 0, None)
        tl = rs.layout(factors, 0, 1)
        got = run_mode_a(fl, tl, None, det=True)
        if not np.array_equal(
                got, np.stack([np_shard(tl, r) for r in range(n)])):
            failures += 1
            print("FAIL: deterministic_mode migration diverges")
        else:
            print("cell migrate/deterministic_mode bitwise=ok")

        # VJP leg: cotangents must redistribute spec' -> spec (run on
        # the eager world, where each rank holds a concrete shard).
        w = rng.standard_normal((n,) + tl.shard_shape(G)).astype(
            np.float32)

        def egbody():
            c = mpi.COMM_WORLD
            sl = jnp.asarray(np_shard(fl, c.rank))
            wr = jnp.asarray(w)[c.rank]
            return jax.grad(
                lambda v: jnp.vdot(c.Reshard(v, fl, tl), wr))(sl)

        g = mpi.run_ranks(egbody, n)
        wfull = np.zeros(G, np.float32)
        sh = tl.shard_shape(G)
        for r in range(n):
            blk = tl.block(r)
            wfull[tuple(slice(b * s, (b + 1) * s)
                        for b, s in zip(blk, sh))] = w[r]
        ok = all(
            np.array_equal(np.asarray(g[r]), np_shard_of(wfull, fl, r))
            for r in range(n))
        if not ok:
            failures += 1
            print("FAIL: VJP cotangents did not redistribute "
                  "spec' -> spec")
        else:
            print("cell migrate/vjp: cotangents redistribute "
                  "spec'->spec bitwise")

    # Registry-sync guard (the shared checker in
    # mpi4torch_tpu.analyze.registry; messages unchanged).
    from mpi4torch_tpu.analyze.registry import reshard_step_problems

    kinds = set(rs.STEP_KINDS)
    probs = reshard_step_problems(exercised)
    if probs:
        failures += 1
        print("FAIL registry-sync: " + "; ".join(probs))
    else:
        print(f"registry-sync: {len(kinds)} step kinds == both "
              "executors == sweep coverage (fwd+adjoint)")

    if failures:
        print(f"reshard-smoke: {failures} FAILURE(S)")
        return 1
    print("reshard-smoke: OK")
    return 0


def np_shard_of(arr, lay, r):
    import numpy as np

    from . import slice_shard

    return np.asarray(slice_shard(arr, lay, r))


def main(argv) -> int:
    if "--smoke" in argv:
        return _smoke()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

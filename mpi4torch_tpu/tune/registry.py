"""Collective-algorithm registry.

Production collective stacks select the wire *algorithm* per message
size and topology (GC3, arXiv:2201.11840; "The Big Send-off",
arXiv:2504.18658): a bandwidth-optimal schedule for large payloads, a
latency-optimal one for the small control tensors (loss scalars, norms,
MoE router counts) that pay ``O(nranks)`` ring steps for a few bytes.
This registry names the schedules the SPMD backend can emit
(ops/spmd.py) and their applicability constraints; the selector
(:mod:`mpi4torch_tpu.tune`) and the persistent autotuner
(:mod:`.autotuner`) choose among them.

Shipped algorithms (wire accounting for payload S over N ranks):

=========  ===========================================  ==============
name       schedule                                      regime
=========  ===========================================  ==============
``ring``   ``lax.psum`` — XLA's bandwidth-optimal ring   large payloads
           (reduce-scatter + all-gather,                 (default)
           2·S·(N-1)/N on the wire, ~2(N-1) hops)
``rhd``    recursive halving/doubling butterfly:         small payloads,
           2·log2(N) ``collective_permute`` hops of      power-of-two N
           halving/doubling width — latency-optimal,
           same 2·S·(N-1)/N wire
``tree``   binomial reduce-to-root + tree broadcast:     small payloads,
           2·ceil(log2 N) full-payload hops — the        any N
           non-power-of-two latency fallback
``hier``   2-level hierarchical: intra-group             2D meshes /
           reduce-scatter → inter-group allreduce →      grouped
           intra-group all-gather, groups from the       topologies
           mesh axis sizes (``comm_from_mesh``) or a
           divisor of N
``bidir``  bidirectional dual-ring: payload halves       large payloads,
           ride two counter-rotating                     bidirectional
           ``collective_permute`` ring RS+AG chains      links (ICI)
           concurrently — ~2× link utilization, same
           per-half 2·(S/2)·(N-1)/N wire each way
``torus``  multi-axis multipath: payload halves stripe   large payloads,
           across the two tiers of a 2-level             multi-axis tori
           factorization (mesh axes under
           ``comm_from_mesh``, or the ``hier``
           grouping of a flat axis), one concurrent
           grouped RS→AR→AG channel per axis
=========  ===========================================  ==============

``bidir``/``torus`` form the *bandwidth tier* ("The Big Send-off",
arXiv:2504.18658 multipath schedules; GC3's multi-channel programs): the
selector reaches them only at/above the measured
``config.bandwidth_crossover_bytes`` — the third tier of auto selection.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class AlgorithmSpec:
    """A registered collective algorithm and its applicability rules.

    ``latency_optimal`` marks the algorithms the selector prefers below
    the measured latency/bandwidth crossover.  Applicability is static
    (rank-count shape), so selection is a pure function of the call
    signature plus the autotuner cache — deterministic per jit cache
    key."""

    name: str
    collectives: Tuple[str, ...] = ("allreduce",)
    latency_optimal: bool = False
    # Marks the multipath bandwidth tier: the selector prefers these at/
    # above the measured config.bandwidth_crossover_bytes, and the
    # autotuner derives that crossover from the sizes they win.
    bandwidth_optimal: bool = False
    # The registry's side of the codec/algorithm composition predicate
    # (compress.codec_rides_algorithm): True for the ring-shaped
    # schedules, whose channels can host the in-schedule per-hop
    # requantizing pipeline (compress/spmd.py).  A codec additionally
    # has to declare the algorithm in Codec.algorithms — both sides
    # must agree before compressed traffic rides this schedule.
    codec_capable: bool = False
    requires_power_of_two: bool = False
    requires_factorable: bool = False
    # The algorithm's VJP-symmetry declaration, checked structurally by
    # the static verifier (mpi4torch_tpu.analyze, `make analyze-smoke`):
    # "self" declares that the backward pass re-runs the same schedule
    # (allreduce is self-adjoint — psum's VJP is psum — so every
    # shipped allreduce schedule's backward census equals its forward
    # census), a dict declares a kind->kind transpose mapping (e.g.
    # {"all_gather": "reduce_scatter"} for a gather-shaped schedule
    # whose adjoint scatters).  A newly registered algorithm must
    # declare its symmetry here; the analyze sweep lints the claim
    # against the actual value_and_grad lowering.
    vjp_census: object = "self"
    description: str = ""

    def applicable(self, nranks: int,
                   collective: str = "allreduce") -> bool:
        if collective not in self.collectives:
            return False
        if nranks <= 1:
            # A one-rank collective is the identity; every schedule
            # degenerates, so only the default needs to claim it.
            return self.name == "ring"
        if self.requires_power_of_two and (nranks & (nranks - 1)):
            return False
        if self.requires_factorable and best_group(nranks) is None:
            return False
        return True

    def why_not(self, nranks: int,
                collective: str = "allreduce") -> Optional[str]:
        """Human reason this algorithm cannot serve the call, or None."""
        if collective not in self.collectives:
            return (f"algorithm {self.name!r} serves "
                    f"{'/'.join(self.collectives)}, not {collective}")
        if nranks > 1 and self.requires_power_of_two \
                and (nranks & (nranks - 1)):
            return (f"algorithm {self.name!r} (recursive halving/"
                    f"doubling) needs a power-of-two world; got "
                    f"{nranks} ranks — use 'tree' for the logarithmic "
                    "schedule at this size, or 'ring'")
        if nranks > 1 and self.requires_factorable \
                and best_group(nranks) is None:
            return (f"algorithm {self.name!r} needs a 2-level group "
                    f"factorization of the world size; {nranks} has no "
                    "nontrivial divisor")
        return None


def best_group(n: int) -> Optional[int]:
    """Default intra-group size for the 2-level ``hier`` schedule on a
    flat axis of ``n`` ranks: the divisor closest to ``sqrt(n)`` (ties
    to the smaller — the intra tier is usually the faster one, so keep
    groups tight), or None when ``n`` is prime or < 4."""
    if n < 4:
        return None
    best, dist = None, None
    for g in range(2, n):
        if n % g:
            continue
        d = abs(g - n // g)
        if dist is None or d < dist:
            best, dist = g, d
    return best


_REGISTRY: Dict[str, AlgorithmSpec] = {}


def register_algorithm(spec: AlgorithmSpec) -> AlgorithmSpec:
    """Register an algorithm spec under ``spec.name`` (the selector and
    the ``algorithm=`` facade argument accept it immediately).  The
    schedule itself must be known to the backend — this registry names
    and gates, it does not carry lowering code."""
    if not spec.name:
        raise ValueError("algorithm must have a non-empty name")
    _REGISTRY[spec.name] = spec
    return spec


def available_algorithms() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def get_algorithm(spec) -> AlgorithmSpec:
    """Resolve an ``algorithm=`` argument to its spec; raises on names
    the registry does not know (catching typos at the facade instead of
    deep inside a trace)."""
    if isinstance(spec, AlgorithmSpec):
        return spec
    if isinstance(spec, str):
        got = _REGISTRY.get(spec)
        if got is None:
            raise ValueError(
                f"unknown collective algorithm {spec!r}; available: "
                f"{', '.join(available_algorithms())}")
        return got
    raise TypeError(
        f"algorithm must be a registered name or an AlgorithmSpec; "
        f"got {spec!r}")


register_algorithm(AlgorithmSpec(
    name="ring",
    codec_capable=True,
    collectives=("allreduce", "reduce", "bcast"),
    description="XLA-native bandwidth-optimal ring (lax.psum / masked "
                "psum); ~2(N-1) pipelined hops, 2·S·(N-1)/N wire",
))
register_algorithm(AlgorithmSpec(
    name="rhd",
    collectives=("allreduce",),
    latency_optimal=True,
    requires_power_of_two=True,
    description="recursive halving/doubling butterfly: 2·log2(N) "
                "collective_permute hops of halving width — "
                "latency-optimal allreduce for power-of-two worlds",
))
register_algorithm(AlgorithmSpec(
    name="tree",
    collectives=("allreduce", "reduce", "bcast"),
    latency_optimal=True,
    description="binomial reduce-to-root + tree broadcast: "
                "2·ceil(log2 N) full-payload hops; the any-N "
                "logarithmic schedule",
))
register_algorithm(AlgorithmSpec(
    name="hier",
    collectives=("allreduce",),
    requires_factorable=True,
    description="2-level hierarchical allreduce: intra-group "
                "reduce-scatter → inter-group allreduce → intra-group "
                "all-gather; groups from mesh axis sizes or a divisor "
                "of N",
))
register_algorithm(AlgorithmSpec(
    name="bidir",
    codec_capable=True,
    collectives=("allreduce",),
    bandwidth_optimal=True,
    description="bidirectional dual-ring allreduce: the payload halves "
                "ride two counter-rotating collective_permute ring "
                "reduce-scatter + all-gather chains concurrently — "
                "~2x link utilization on bidirectional links, any N",
))
register_algorithm(AlgorithmSpec(
    name="torus",
    codec_capable=True,
    collectives=("allreduce",),
    bandwidth_optimal=True,
    requires_factorable=True,
    description="multi-axis torus multipath allreduce: payload halves "
                "stripe across the two tiers of a 2-level factorization "
                "(mesh axes under comm_from_mesh, or the hier grouping "
                "of a flat axis) — one concurrent grouped channel per "
                "axis",
))

"""Size- and topology-aware collective algorithm selection.

The SPMD backend can emit several schedules for the same collective
(``ring``/``rhd``/``tree``/``hier`` plus the multipath bandwidth tier
``bidir``/``torus`` — see :mod:`.registry`); which one is fastest
depends on message size, rank count, and topology.  This package
decides:

* **per call** — ``comm.Allreduce(x, op, algorithm="rhd")``;
* **per scope** — ``with mpi.config.algorithm_scope("tree"): ...``
  (or process-wide via :func:`mpi4torch_tpu.config.set_default_algorithm`);
* **by default** — the selector, in three tiers: the persisted
  autotuner cache's measured winner for the ``(collective, dtype,
  nbytes-bucket, nranks, platform)`` key when one exists; below the
  measured latency crossover
  (:func:`mpi4torch_tpu.config.latency_crossover_bytes`) the
  latency-optimal algorithm (``rhd``/``tree``); at or above the
  measured bandwidth crossover
  (:func:`mpi4torch_tpu.config.bandwidth_crossover_bytes`) the
  multipath bandwidth tier (``bidir``); and ``ring`` in between or when
  nothing is measured — auto-selection never deviates from the
  XLA-native ring on a guess, only on measurement.

Degrade/raise rule (mirrors the compression scope's): a *scope or
process default* that cannot legally serve a call — ``rhd`` on a
non-power-of-two world, ``hier`` on a prime world, any non-ring
algorithm under a wire codec that does not declare it (``bf16`` off the
ring, any codec on the butterfly/tree/hier schedules) —
silently falls back to auto selection (``ring`` unless measured
evidence says otherwise, and for ``Bcast_``/``Reduce_`` the normal
size dispatch); an *explicit per-call* ``algorithm=`` raises with the
reason instead.

Run the measurement with :func:`autotune_allreduce` (or ``make
tune-smoke`` / ``python -m mpi4torch_tpu.tune.autotuner``); winners
persist to a versioned JSON cache file (safe to delete — see
:mod:`.autotuner`) so later processes select tuned algorithms with zero
measurement overhead.
"""

from __future__ import annotations

from typing import Optional

from .. import config as _config
from ..runtime import CommError
from .autotuner import (autotune_allreduce, bucket_nbytes, cache_path,
                        clear, ensure_tuned_allreduce, entry_from_disk,
                        generation, lookup, lookup_algorithm, make_key,
                        record)
from .registry import (AlgorithmSpec, available_algorithms, best_group,
                       get_algorithm, register_algorithm)

__all__ = [
    "AlgorithmSpec",
    "available_algorithms",
    "best_group",
    "get_algorithm",
    "register_algorithm",
    "resolve_request",
    "resolve_hier_group",
    "resolve_tier_stack",
    "select_auto",
    "codec_algorithms",
    "autotune_allreduce",
    "bucket_nbytes",
    "ensure_tuned_allreduce",
    "lookup",
    "lookup_algorithm",
    "entry_from_disk",
    "record",
    "make_key",
    "cache_path",
    "generation",
    "clear",
]


def codec_algorithms(codec) -> tuple:
    """The wire algorithms ``codec`` declares it composes with
    (``Codec.algorithms``; codecs predating the field are ring-only —
    the conservative reading, since the compressed pipeline is a ring)."""
    return tuple(getattr(codec, "algorithms", ("ring",)))


def resolve_request(requested, *, collective: str = "allreduce",
                    nranks: int = 1,
                    explicit: bool = False) -> Optional[str]:
    """Resolve a facade ``algorithm=`` request to a concrete algorithm
    name, or ``None`` for selector-driven auto choice.

    ``requested`` is the explicit per-call argument when ``explicit``,
    else the scope/process default.  Unknown names always raise (a typo
    is a bug at any level); an *applicability* failure raises only for
    explicit requests and voids scope defaults back to auto selection
    (None) — NOT to a pinned ``"ring"``, so e.g. an allreduce-oriented
    ``algorithm_scope("rhd")`` leaves a small ``Bcast_``'s tree/psum
    size dispatch untouched instead of silently pinning the psum form.
    ``False``/``"auto"`` mean selector-driven choice (the explicit
    spelling overrides an active ``algorithm_scope``)."""
    if requested is None or requested is False or requested == "auto":
        return None
    if isinstance(requested, str) and requested.startswith("synth:"):
        # A synthesized IR schedule (csched.synth): serves allreduce
        # only, and only when its program is installed for THIS world —
        # the usual degrade/raise rule otherwise.
        from ..csched import synth as _synth

        if collective != "allreduce":
            if explicit:
                raise CommError(
                    f"synthesized schedule {requested!r} serves "
                    f"allreduce, not {collective}")
            return None
        if _synth.synth_applicable(requested, nranks):
            return requested
        if explicit:
            raise CommError(
                f"synthesized schedule {requested!r} is not installed "
                f"for a {nranks}-rank world (run "
                "csched.synth.autotune_synthesis or load its "
                "tune-cache entry)")
        return None
    spec = get_algorithm(requested)  # raises on unknown names
    reason = spec.why_not(nranks, collective)
    if reason is None:
        return spec.name
    if explicit:
        raise CommError(reason)
    return None


def resolve_hier_group(nranks: int) -> int:
    """THE intra-group size of the flat-axis ``hier`` schedule for an
    ``nranks`` communicator — the single source both backends consult
    (ops/spmd.py ``_hier_group_for`` and the eager rendezvous fold), so
    the validity rule can never drift between Mode A and Mode B.

    ``config.hier_group_size()`` when set (validated against THIS
    communicator), else the divisor of ``nranks`` closest to its square
    root.  Raises :class:`CommError` when no valid 2-level split
    exists — callers holding a scope default catch it and fall back to
    auto selection (the degrade/raise rule); explicit requests let it
    propagate."""
    g = _config.hier_group_size()
    if g is not None:
        if nranks % g or not (1 < g < nranks):
            raise CommError(
                f"config.hier_group_size={g} does not define a 2-level "
                f"split of the {nranks}-rank communicator (need a "
                f"divisor with 1 < g < {nranks})")
        return g
    ts = _config.tier_stack()
    if ts is not None:
        # The tier stack generalizes hier_group_size: its innermost
        # factor IS the intra-group size of the 2-level view (the
        # outer tiers merge into the inter-group stage).
        stack = resolve_tier_stack(nranks)
        if len(stack) < 2:
            raise CommError(
                f"config.tier_stack={stack} is a single flat tier — "
                f"the 'hier' schedule needs >= 2 levels")
        return stack[0]
    g = best_group(nranks)
    if g is None:
        raise CommError(
            f"the 'hier' schedule needs a 2-level group factorization "
            f"of the world size; {nranks} has no nontrivial divisor — "
            "use 'tree' or 'ring'")
    return g


def resolve_tier_stack(nranks: int):
    """THE flat-axis tier-stack factorization (innermost first) of an
    ``nranks`` communicator — the single source the grouped-fold chain
    builders and the weighted census consult.
    ``config.tier_stack()`` when set (validated against THIS
    communicator), else the 2-level ``(g, nranks // g)`` split of
    :func:`resolve_hier_group` — so with nothing configured the stack
    IS today's hier pair and nothing changes."""
    ts = _config.tier_stack()
    if ts is not None:
        stack = tuple(int(g) for g in ts)
        p = 1
        for g in stack:
            p *= g
        if p != nranks or any(g < 2 for g in stack):
            raise CommError(
                f"config.tier_stack={stack} does not factor the "
                f"{nranks}-rank communicator into tiers of >= 2")
        return stack
    g = resolve_hier_group(nranks)
    return (g, nranks // g)


def select_auto(*, collective: str = "allreduce", nbytes: int,
                dtype, nranks: int, deterministic: bool = False,
                codec=None) -> str:
    """The selector: concrete algorithm for an auto (no explicit
    request, no scope default) collective call.  Pure function of the
    call signature, the config knobs, and the autotuner cache — the
    same inputs always pick the same algorithm (``run_spmd`` keys its
    jit cache on the config fingerprint and the cache generation, so a
    cache update retraces rather than silently diverging).

    Order: deterministic mode pins ``ring`` (the bit-exact ordered
    fold); a measured cache winner wins; below the measured latency
    crossover the latency-optimal algorithm wins (``rhd`` on
    power-of-two worlds, else ``tree``); at or above the measured
    bandwidth crossover the multipath bandwidth tier wins (``bidir``,
    the dual-ring — applicable on any world); otherwise ``ring``.  A
    codec restricts candidates to the algorithms it declares × the
    registry's ``codec_capable`` gate (the block-q8 family rides
    ring/bidir/torus, the bf16 family is ring-only) and reads measured
    winners from the cache's codec-keyed dimension."""
    if nranks <= 1:
        return "ring"
    if deterministic:
        # Deterministic mode pins ring — UNLESS a synthesized IR
        # schedule (csched.synth — an exact grouped ordered fold, so
        # deterministic by construction) won this bucket on the census
        # sweep and its program is installed: the one evidence-backed
        # deviation, like measured winners in the wall-clock tiers.
        # Synthesis entries live under their own codec="synth" key
        # slot, so they never collide with measured winners.
        from ..csched import synth as _synth

        if codec is None:
            w = lookup_algorithm(collective, dtype, nbytes, nranks,
                                 codec="synth")
            if (_synth.is_synth_name(w)
                    and _synth.synth_applicable(w, nranks)):
                return w
        return "ring"

    def ok(name: str) -> bool:
        if not get_algorithm(name).applicable(nranks, collective):
            return False
        if name in ("hier", "torus"):
            # The registry gate is static (a nontrivial divisor
            # exists); a set config.hier_group_size can still void it
            # for THIS communicator — auto selection must never return
            # an algorithm the backend would reject.
            try:
                resolve_hier_group(nranks)
            except CommError:
                return False
        if codec is None:
            return True
        # One enforcement path for codec/algorithm composition: the
        # same predicate the facade and the fused per-bucket picker
        # consult (compress.codec_applicable, algorithm leg).
        from ..compress import codec_applicable

        return codec_applicable(codec, dtype, algorithm=name)

    # The cache key grows a codec dimension: compressed traffic reads
    # its own measured winners (autotune_allreduce(codecs=...)) and can
    # never hijack — or be hijacked by — exact selection.
    winner = lookup_algorithm(collective, dtype, nbytes, nranks,
                              codec=codec)
    if winner is not None and winner.startswith("synth:"):
        # Synthesized winners are deterministic-census verdicts; they
        # serve deterministic mode (above) and must not steer the
        # wall-clock-measured non-deterministic tiers.
        winner = None
    crossover = _config.latency_crossover_bytes()
    if winner is not None and ok(winner):
        if (codec is None and crossover is not None
                and nbytes <= crossover
                and get_algorithm(winner).bandwidth_optimal):
            # Latency-tier guard (ISSUE 10 satellite): decode-sized
            # messages share power-of-two nbytes buckets with training
            # tail buckets, so a bandwidth-tier winner (bidir/torus)
            # recorded under such a key must never be applied BELOW the
            # measured latency crossover — a multipath schedule on a
            # few-KiB per-token payload pays 2x the latency hops for
            # bandwidth it cannot use.  The cached winner is voided and
            # the tier dispatch below decides (latency-optimal winners
            # and mid-tier ring winners are honored as recorded).
            # Exact traffic only: decode payloads are always exact
            # (compression=False), so codec-keyed winners carry no
            # decode-aliasing hazard — and voiding one would strand a
            # compressed message on ring, since the latency algorithms
            # below never pass a codec's declared-algorithm gate.
            winner = None
        else:
            return winner
    if crossover is not None and nbytes <= crossover:
        if ok("rhd"):
            return "rhd"
        if ok("tree"):
            return "tree"
    bandwidth = _config.bandwidth_crossover_bytes()
    if bandwidth is not None and nbytes >= bandwidth:
        # The third tier: multipath at/above the measured crossover.
        # `bidir` is the any-world pick (for compressed traffic too —
        # the block-q8 family declares it); `torus` wins only through a
        # measured cache entry (its grouping quality is topology-bound).
        if ok("bidir"):
            return "bidir"
    return "ring"

"""Measurement-driven algorithm autotuner with a persistent cache.

The selector (:mod:`mpi4torch_tpu.tune`) deviates from ``ring`` only on
evidence.  This module produces that evidence: it benchmarks every
applicable algorithm per ``(collective, dtype, nbytes-bucket, nranks,
platform)`` key, records the winner in an in-process table, and
persists the table to a JSON cache file so later *processes* skip the
measurement entirely — steady-state steps pay zero tuning overhead.

Cache file contract:

* location — ``$MPI4TORCH_TPU_TUNE_CACHE`` if set, else
  ``~/.cache/mpi4torch_tpu/tune_cache.json``;
* versioned — the top-level ``version`` field must equal
  :data:`CACHE_VERSION`; a mismatched, corrupt, truncated, or
  hand-edited-beyond-recognition file is silently ignored (selection
  falls back to the defaults — the cache is *safe to delete at any
  time*);
* written atomically (tmp + rename) and best-effort: an unwritable
  cache directory degrades to in-process-only tuning, never an error.

Message sizes are bucketed to the next power of two, so one
measurement covers the whole bucket — the same coarse keying
production autotuners use (a 3 KiB and a 4 KiB allreduce want the same
schedule).

``python -m mpi4torch_tpu.tune.autotuner [--smoke]`` runs the sweep
from the command line and prints the JSON report (``make tune-smoke``
drives the CPU smoke variant).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence

from .. import config as _config
from .registry import available_algorithms, get_algorithm

# v2: per-size measurement switched from median-of-k to MIN-of-k
# (ISSUE 7 satellite — a single preempted/GC-hit sample could poison a
# persisted winner under the median with few iters); winners measured
# under the old rule are discarded by the version gate.
# v3: synthesized-program keys grew a tier dimension (|tiers=AxB...)
# and fold steps carry tier annotations (Step.tier) that change synth
# digests — v2 entries naming pre-tier digests are silently discarded
# by the version gate (selection falls back to the defaults until the
# census sweep re-records; _load ignores mismatched versions).
CACHE_VERSION = 3

_mem: Dict[str, dict] = {}
_from_disk: set = set()
_file_loaded = False
_generation = 0


def cache_path() -> str:
    """Path of the persistent cache file (see module docstring)."""
    env = os.environ.get("MPI4TORCH_TPU_TUNE_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "mpi4torch_tpu", "tune_cache.json")


def _bucket(nbytes: int) -> int:
    """Next power of two ≥ nbytes (≥ 1) — the cache's size key."""
    nbytes = max(int(nbytes), 1)
    return 1 << (nbytes - 1).bit_length()


def bucket_nbytes(nbytes: int) -> int:
    """Public form of the cache's size-bucket rule: the power-of-two
    bucket a payload of ``nbytes`` keys into.  Serving's latency report
    (:func:`mpi4torch_tpu.serve.latency_report`) uses it to show which
    cache bucket the real decode message sizes share — the aliasing the
    ``select_auto`` latency-tier guard exists for: a decode-sized key
    can hold a winner recorded by a training tail bucket of the same
    power-of-two size, so tier membership, not the cache alone, gates
    sub-crossover selection."""
    return _bucket(nbytes)


def _platform() -> str:
    import jax

    return jax.devices()[0].platform


def _codec_name(codec) -> Optional[str]:
    """Normalize a codec dimension value (Codec object, name string, or
    None) to a cache-key token."""
    if codec is None:
        return None
    return getattr(codec, "name", codec)


def _tiers_token(tiers) -> Optional[str]:
    """Normalize a tier-stack key dimension value (a tuple of factors,
    an ``AxBxC`` string, or None) to a cache-key token."""
    if tiers is None:
        return None
    if isinstance(tiers, str):
        return tiers
    return "x".join(str(int(g)) for g in tiers)


def make_key(collective: str, dtype, nbytes: int, nranks: int,
             platform: Optional[str] = None, codec=None,
             tiers=None, transition: Optional[str] = None) -> str:
    import numpy as np

    if platform is None:
        platform = _platform()
    key = "|".join([collective, str(np.dtype(dtype)),
                    str(_bucket(nbytes)), str(int(nranks)), platform])
    # The codec dimension: compressed traffic gets its OWN winner keys
    # (a q8 bucket's crossover differs from fp32's — ~4x fewer wire
    # bytes per element), and exact traffic keeps the codec-less keys it
    # always had, so compressed measurements can never hijack exact
    # selection (or vice versa).
    name = _codec_name(codec)
    if name is not None:
        key += "|codec=" + str(name)
    # The tier dimension (mpi4torch_tpu.csched tier-stack synthesis): a
    # winner ranked by the bandwidth-weighted census is specific to the
    # tier-stack factorization it was searched under — a (2,2,2) stack's
    # winner must never serve a (4,2) world.  Same growth pattern as the
    # codec dimension; flat (un-tiered) keys stay byte-identical.
    tok = _tiers_token(tiers)
    if tok is not None:
        key += "|tiers=" + str(tok)
    # The transition dimension (mpi4torch_tpu.reshard): a measured
    # redistribution winner is specific to its (layout, layout', shape)
    # transition — the same growth pattern as the codec dimension, so
    # reshard entries can never collide with collective-algorithm keys.
    if transition is not None:
        key += "|transition=" + str(transition)
    return key


def _validate_winner(collective: str, algorithm: str,
                     ent: Optional[dict] = None) -> None:
    """Winner names are validated against the registry that owns them:
    reshard entries name a planner strategy, ``synth:<digest>`` entries
    a synthesized IR program (the entry must carry the serialized
    program, which installs on successful validation — so a persisted
    winner is lowerable right after lookup), everything else a
    collective algorithm.  Raises on unknown names (record) — lookup
    callers catch and ignore stale entries."""
    if isinstance(algorithm, str) and algorithm.startswith("synth:"):
        from ..csched import synth as _synth

        _synth.validate_entry(algorithm,
                              None if ent is None else ent.get("program"))
        return
    if collective == "reshard":
        from ..reshard.plan import STRATEGIES

        if algorithm not in STRATEGIES:
            raise ValueError(
                f"unknown reshard strategy {algorithm!r}; expected one "
                f"of {STRATEGIES}")
        return
    get_algorithm(algorithm)


def _load() -> None:
    """Lazily merge the disk cache into the in-process table.  Any
    defect — missing file, bad JSON, wrong version, malformed entries —
    is treated as 'no cache': defaults apply, nothing crashes."""
    global _file_loaded
    if _file_loaded:
        return
    _file_loaded = True
    try:
        with open(cache_path(), "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return
    if not isinstance(data, dict) or data.get("version") != CACHE_VERSION:
        return
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return
    for key, ent in entries.items():
        if (isinstance(key, str) and isinstance(ent, dict)
                and isinstance(ent.get("algorithm"), str)
                and key not in _mem):
            _mem[key] = ent
            _from_disk.add(key)


def _save() -> None:
    """Atomic, concurrency-safe, best-effort persist of the in-process
    table.

    Two rules make simultaneous tuners (multi-host jobs, a bench next
    to a training run) safe:

    * the payload is written to a UNIQUE tempfile in the cache
      directory (``tempfile.mkstemp`` — a fixed ``.tmp`` name would let
      two processes interleave writes into the same staging file) and
      ``os.replace``d over the cache, so readers only ever see a
      complete JSON document;
    * the read-merge-replace sequence runs under an exclusive
      ``flock`` on a sidecar ``<cache>.lock`` file, and entries another
      process persisted while we tuned are merged into the written
      snapshot (disk keys we do not hold in memory) — concurrent tuning
      work is unioned rather than lost to last-writer-wins, with no
      lost-update window between the read and the replace.  On
      filesystems without ``flock`` the lock is skipped (the merge
      still narrows the race to the read→replace window; readers are
      never blocked or torn either way)."""
    import contextlib
    import tempfile

    path = cache_path()
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

        @contextlib.contextmanager
        def _locked():
            try:
                import fcntl
                fd = os.open(path + ".lock",
                             os.O_CREAT | os.O_RDWR, 0o644)
            except (ImportError, OSError):
                yield
                return
            try:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                except OSError:
                    pass  # NFS & co: fall back to merge-only safety
                yield
            finally:
                os.close(fd)

        with _locked():
            entries = dict(_mem)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    on_disk = json.load(f)
                if (isinstance(on_disk, dict)
                        and on_disk.get("version") == CACHE_VERSION
                        and isinstance(on_disk.get("entries"), dict)):
                    for key, ent in on_disk["entries"].items():
                        if (isinstance(key, str) and isinstance(ent, dict)
                                and isinstance(ent.get("algorithm"), str)
                                and key not in entries):
                            entries[key] = ent
            except (OSError, ValueError):
                pass
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path) or ".", prefix=".tune_cache.",
                suffix=".tmp")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(
                        {"version": CACHE_VERSION, "entries": entries},
                        f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
    except OSError:
        pass


def lookup(collective: str, dtype, nbytes: int, nranks: int,
           platform: Optional[str] = None, codec=None, tiers=None,
           transition: Optional[str] = None) -> Optional[dict]:
    """The cached entry for this key, or None.  Entries naming an
    algorithm (or reshard strategy) the owning registry no longer knows
    (stale cache across versions) are ignored."""
    from ..obs import metrics as _metrics

    _load()
    ent = _mem.get(make_key(collective, dtype, nbytes, nranks, platform,
                            codec=codec, tiers=tiers,
                            transition=transition))
    if ent is None:
        _metrics.inc("tune_cache_misses_total",
                     help="autotuner cache lookups that found no winner")
        return None
    try:
        _validate_winner(collective, ent["algorithm"], ent)
    except (ValueError, KeyError, TypeError):
        _metrics.inc("tune_cache_misses_total")
        return None
    _metrics.inc("tune_cache_hits_total",
                 help="autotuner cache lookups serving a cached winner")
    return ent


def lookup_algorithm(collective: str, dtype, nbytes: int, nranks: int,
                     platform: Optional[str] = None,
                     codec=None, tiers=None,
                     transition: Optional[str] = None) -> Optional[str]:
    ent = lookup(collective, dtype, nbytes, nranks, platform, codec=codec,
                 tiers=tiers, transition=transition)
    return None if ent is None else ent["algorithm"]


def entry_from_disk(collective: str, dtype, nbytes: int, nranks: int,
                    platform: Optional[str] = None, codec=None,
                    tiers=None) -> bool:
    """True when this key's entry was loaded from the persisted file
    (rather than measured in this process) — the bench's
    ``tuned_from_cache`` evidence."""
    _load()
    return make_key(collective, dtype, nbytes, nranks,
                    platform, codec=codec, tiers=tiers) in _from_disk


def record(collective: str, dtype, nbytes: int, nranks: int,
           algorithm: str, platform: Optional[str] = None,
           measurements: Optional[dict] = None,
           persist: bool = True, codec=None, tiers=None,
           transition: Optional[str] = None,
           program: Optional[dict] = None,
           ctl: Optional[dict] = None) -> str:
    """Store a winner for a key (and persist).  Bumps the selection
    generation so ``run_spmd`` jit cache keys see the change and
    retrace instead of reusing a lowering picked under the old table.
    ``program`` carries a synthesized winner's serialized IR program
    (mpi4torch_tpu.csched) — required for ``synth:<digest>`` names, so
    a later process can re-install and lower the schedule straight from
    the cache entry.  ``ctl`` carries the online-switch provenance the
    self-tuning controller stamps on winners it installs between steps
    ({"provenance": "online-switched", "epoch": N, "trigger": ...} —
    rendered by ``tune --show`` so an operator can tell a measured
    winner from one a live drift episode installed)."""
    global _generation
    _load()
    key = make_key(collective, dtype, nbytes, nranks, platform,
                   codec=codec, tiers=tiers, transition=transition)
    ent = {"algorithm": algorithm, "measured_at": time.time()}
    if program is not None:
        ent["program"] = program
    if ctl is not None:
        ent["ctl"] = dict(ctl)
    _validate_winner(collective, algorithm, ent)
    name = _codec_name(codec)
    if name is not None:
        ent["codec"] = str(name)
    tok = _tiers_token(tiers)
    if tok is not None:
        ent["tiers"] = str(tok)
    if measurements:
        ent["measurements"] = measurements
    _mem[key] = ent
    _from_disk.discard(key)
    _generation += 1
    if persist:
        _save()
    return key


def generation() -> int:
    """Monotonic counter bumped on every cache mutation; part of
    ``run_spmd``'s jit cache key."""
    return _generation


def clear(remove_file: bool = False) -> None:
    """Drop the in-process table (and optionally the persisted file);
    the next lookup re-reads the file, so ``clear()`` alone round-trips
    the persisted entries while ``clear(remove_file=True)`` resets
    selection to the defaults."""
    global _file_loaded, _generation
    _mem.clear()
    _from_disk.clear()
    _file_loaded = False
    _generation += 1
    if remove_file:
        try:
            os.remove(cache_path())
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Measurement
# ---------------------------------------------------------------------------

SMOKE_SIZES = (1 << 10, 1 << 14, 1 << 18)           # 1 KiB → 256 KiB
DEFAULT_SIZES = tuple(1 << s for s in range(10, 27, 2))   # 1 KiB → 64 MiB


def _candidates(nranks: int, collective: str = "allreduce") -> List[str]:
    out = []
    for name in available_algorithms():
        if get_algorithm(name).applicable(nranks, collective):
            out.append(name)
    return out


def _time_step(step, x, iters: int) -> float:
    """MIN-of-k seconds/step with a host fetch per iteration (the only
    completion barrier remote runtimes honor — see bench.py ``_force``;
    ``np.asarray`` of one output leaf is the cheap equivalent here).

    Min, not median/mean: timing noise on shared or preemptible
    capacity is strictly one-sided — a preempted slice, a GC pause, or
    a noisy neighbor only ever makes a sample SLOWER — so the minimum
    is the robust estimator of the true step cost, and one bad sample
    can no longer flip a persisted cache winner (with the old
    median-of-5, TWO outliers among five samples poisoned the key for
    every later process).  Keyed into :data:`CACHE_VERSION`."""
    import jax
    import numpy as np

    def force(out):
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf[(slice(None),) + (0,) * (leaf.ndim - 1)])

    force(step(x))          # compile + warmup
    force(step(x))
    times = []
    for _ in range(max(int(iters), 1)):
        t0 = time.perf_counter()
        force(step(x))
        times.append(time.perf_counter() - t0)
    return min(times)


def autotune_allreduce(sizes: Optional[Sequence[int]] = None,
                       nranks: Optional[int] = None,
                       dtype=None, iters: int = 5,
                       persist: bool = True,
                       apply_crossover: bool = True,
                       codecs: Sequence = (None,)) -> dict:
    """Benchmark every applicable allreduce algorithm at each payload
    size, record the winners in the cache, and (by default) set
    :func:`config.set_latency_crossover_bytes` AND
    :func:`config.set_bandwidth_crossover_bytes` from the measured
    crossovers so three-tier auto-selection (latency algorithms below,
    ring in the middle, multipath ``bidir``/``torus`` above) reflects
    the measurement.

    ``codecs`` is the sweep's codec dimension: each non-``None`` entry
    (a codec name like ``"q8"``) re-runs the per-algorithm sweep with
    that compression, restricted to the algorithms the codec declares
    (compress.codec_applicable), and records winners under the cache's
    codec-keyed dimension — so auto selection can pick the compressed
    ``bidir`` at/above the bandwidth crossover without the compressed
    measurements hijacking exact traffic's winners.  The crossover
    derivation reads only the exact (``None``) sweep.

    Returns the report dict (also the bench's JSON stanza):
    per-size per-algorithm seconds and GB/s, the winner table, the
    crossover, and ``tuned_from_cache: False`` (a report served
    without measuring — :func:`ensure_tuned_allreduce` — says True,
    with ``from_disk`` distinguishing a persisted-file round-trip from
    same-process memory)."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    if dtype is None:
        dtype = jnp.float32
    n = nranks or len(jax.devices())
    sizes = tuple(sizes) if sizes else DEFAULT_SIZES
    platform = _platform()
    itemsize = jnp.dtype(dtype).itemsize
    comm = mpi.COMM_WORLD

    report = {
        "collective": "allreduce",
        "nranks": n,
        "dtype": str(jnp.dtype(dtype)),
        "platform": platform,
        "cache_file": cache_path(),
        "tuned_from_cache": False,
        "entries": {},
    }

    def step_fn(algorithm, compression):
        def body(x):
            return comm.Allreduce(x, mpi.MPI_SUM, algorithm=algorithm,
                                  compression=compression or False)

        return mpi.run_spmd(body, nranks=n)

    def sweep_one(nbytes, x, wire, codec):
        from ..compress import codec_applicable, get_codec

        if codec is None:
            names = _candidates(n)
        else:
            cobj = get_codec(codec)
            names = [a for a in _candidates(n)
                     if codec_applicable(cobj, dtype, algorithm=a)]
        per = {}
        for name in names:
            try:
                dt = _time_step(step_fn(name, codec), x, iters)
            except Exception as e:  # noqa: BLE001 — sweep must finish
                per[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
                continue
            per[name] = {"seconds_per_step": dt,
                         "gbps": round(wire / dt / 1e9, 4)}
        timed = {k: v for k, v in per.items()
                 if "seconds_per_step" in v}
        if not timed:
            return {"algorithms": per}
        winner = min(timed, key=lambda k: timed[k]["seconds_per_step"])
        record("allreduce", dtype, int(nbytes), n, winner,
               platform=platform, measurements={
                   k: v["seconds_per_step"] for k, v in timed.items()},
               persist=persist, codec=codec)
        return {
            "algorithms": per,
            "winner": winner,
            "winner_latency_optimal":
                get_algorithm(winner).latency_optimal,
            "winner_bandwidth_optimal":
                get_algorithm(winner).bandwidth_optimal,
        }

    for nbytes in sizes:
        nelem = max(1, int(nbytes) // itemsize)
        x = jnp.ones((nelem,), dtype)
        wire = 2.0 * (n - 1) / n * nelem * itemsize if n > 1 \
            else float(nelem * itemsize)
        ent = sweep_one(nbytes, x, wire, None) \
            if None in tuple(codecs) else {"algorithms": {}}
        for codec in codecs:
            if codec is None:
                continue
            ent.setdefault("codecs", {})[str(_codec_name(codec))] = \
                sweep_one(nbytes, x, wire, codec)
        report["entries"][str(int(nbytes))] = ent

    crossover = _crossover_from(report["entries"])
    report["crossover_bytes"] = crossover
    if apply_crossover and crossover is not None:
        _config.set_latency_crossover_bytes(crossover)
        report["applied_latency_crossover_bytes"] = crossover
    bandwidth = _bandwidth_crossover_from(report["entries"])
    report["bandwidth_crossover_bytes"] = bandwidth
    if apply_crossover and bandwidth is not None:
        _config.set_bandwidth_crossover_bytes(bandwidth)
        report["applied_bandwidth_crossover_bytes"] = bandwidth
    return report


def _crossover_from(entries: dict) -> Optional[int]:
    """Largest measured payload size whose winner is latency-optimal —
    the ring/latency-algorithm crossover point (None when ring wins
    everywhere, i.e. the latency regime was not reached)."""
    best = None
    for size_str, ent in entries.items():
        if ent.get("winner_latency_optimal"):
            size = int(size_str)
            best = size if best is None else max(best, size)
    return best


def _bandwidth_crossover_from(entries: dict) -> Optional[int]:
    """Smallest measured payload size from which a bandwidth-tier
    multipath algorithm (``bidir``/``torus``) wins *at every larger
    measured size too* — the ring/multipath crossover, the upper edge
    of three-tier auto selection.  None when the largest measured size
    is not won by the bandwidth tier (the multipath regime was not
    reached, or a single noisy mid-size win must not flip steady-state
    selection)."""
    sized = sorted((int(s), ent) for s, ent in entries.items()
                   if "winner" in ent)
    best = None
    for size, ent in reversed(sized):
        if not ent.get("winner_bandwidth_optimal"):
            break
        best = size
    return best


def ensure_tuned_allreduce(sizes: Optional[Sequence[int]] = None,
                           nranks: Optional[int] = None,
                           dtype=None, iters: int = 5,
                           persist: bool = True,
                           apply_crossover: bool = True) -> dict:
    """Like :func:`autotune_allreduce`, but when every requested size
    already has a cached winner, build the report from the cache
    (``tuned_from_cache: True``) and skip the measurement — the
    steady-state zero-overhead path.  ``from_disk`` in the report says
    whether ALL served entries came from the persisted file (a real
    cross-process round-trip) rather than this process's own earlier
    measurement."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    n = nranks or len(jax.devices())
    sizes = tuple(sizes) if sizes else DEFAULT_SIZES
    platform = _platform()

    cached = {}
    from_disk = True
    for nbytes in sizes:
        ent = lookup("allreduce", dtype, int(nbytes), n, platform)
        if ent is None:
            return autotune_allreduce(sizes=sizes, nranks=n, dtype=dtype,
                                      iters=iters, persist=persist,
                                      apply_crossover=apply_crossover)
        from_disk = from_disk and entry_from_disk(
            "allreduce", dtype, int(nbytes), n, platform)
        cached[str(int(nbytes))] = {
            "winner": ent["algorithm"],
            "winner_latency_optimal":
                get_algorithm(ent["algorithm"]).latency_optimal,
            "winner_bandwidth_optimal":
                get_algorithm(ent["algorithm"]).bandwidth_optimal,
            "measurements": ent.get("measurements"),
        }
    crossover = _crossover_from(cached)
    if apply_crossover and crossover is not None:
        _config.set_latency_crossover_bytes(crossover)
    bandwidth = _bandwidth_crossover_from(cached)
    if apply_crossover and bandwidth is not None:
        _config.set_bandwidth_crossover_bytes(bandwidth)
    return {
        "collective": "allreduce",
        "nranks": n,
        "dtype": str(jnp.dtype(dtype)),
        "platform": platform,
        "cache_file": cache_path(),
        "tuned_from_cache": True,
        "from_disk": from_disk,
        "entries": cached,
        "crossover_bytes": crossover,
        "bandwidth_crossover_bytes": bandwidth,
    }


def _main(argv: Iterable[str]) -> int:
    smoke = "--smoke" in argv
    sizes = SMOKE_SIZES if smoke else DEFAULT_SIZES
    if "--sweep" in argv:
        # The fast bench lane (`make bench-sweep`): ALWAYS measure —
        # the point is a fresh sizes × algorithms throughput table
        # (winners still persist, so it doubles as a tuning run).
        report = autotune_allreduce(sizes=sizes, iters=2 if smoke else 5)
    else:
        report = ensure_tuned_allreduce(sizes=sizes,
                                        iters=2 if smoke else 5)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))

"""Cache-inspection CLI for the collective-algorithm autotuner.

``python -m mpi4torch_tpu.tune``           — print the cached winners
table (collective, dtype, size bucket, nranks, platform → algorithm),
so tuned picks are debuggable without reading raw JSON.

* ``--show``  — the table (the default action);
* ``--json``  — the raw cache document instead of the table;
* ``--clear`` — delete the persisted cache file (selection falls back
  to the defaults; the file is safe to delete at any time).

The measurement sweep itself lives one module deeper:
``python -m mpi4torch_tpu.tune.autotuner [--smoke]`` (``make
tune-smoke``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, List, Optional

from .autotuner import CACHE_VERSION, cache_path

_COLUMNS = ("collective", "dtype", "size<=", "nranks", "platform",
            "tiers", "algorithm", "source")


def _load_raw() -> Optional[dict]:
    try:
        with open(cache_path(), "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _program_steps(ent: dict) -> int:
    """Step count of a synthesized entry's serialized IR program."""
    try:
        return sum(len(ph.get("steps", ()))
                   for ph in ent["program"]["phases"])
    except (KeyError, TypeError):
        return 0


def _rows(data: dict) -> List[tuple]:
    """Decode ``collective|dtype|bucket|nranks|platform`` keys into table
    rows; malformed entries are skipped, not fatal — this is a debugging
    surface over a best-effort cache.  Trailing key dimensions are
    optional and ordered (``|codec=…`` then ``|tiers=…``): codec-keyed
    winners render with the slot tag on the collective column,
    tier-keyed winners (csched tier-stack synthesis) fill the ``tiers``
    column (``-`` for flat keys).  Synthesized-program winners
    (``synth:<digest>`` entries carrying their serialized IR program,
    mpi4torch_tpu.csched) render distinctly from named algorithms: the
    digest in the algorithm column, ``synthesized(<n> steps)`` as the
    source.  Entries the self-tuning controller installed ONLINE
    (mpi4torch_tpu.ctl — a live drift/crossover episode, not an
    offline sweep) carry a ``ctl`` provenance stamp and render as
    ``online-switched(<trigger>@epoch <n>, k steps)`` so an operator
    can tell which winners a controller episode picked."""
    rows = []
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return rows
    for key, ent in sorted(entries.items()):
        if not (isinstance(key, str) and isinstance(ent, dict)):
            continue
        parts = key.split("|")
        algo = ent.get("algorithm")
        if not isinstance(algo, str):
            continue
        tiers = "-"
        if len(parts) > 5 and parts[-1].startswith("tiers="):
            tiers = parts[-1][len("tiers="):]
            parts = parts[:-1]
        if len(parts) == 6 and parts[5].startswith("codec="):
            # Codec-keyed winners (compressed traffic's own slots, and
            # codec=synth / codec=synth_q8 — the synthesis dimensions)
            # render with the slot tag on the collective column.
            parts = [parts[0] + "[" + parts[5][len("codec="):] + "]"] \
                + parts[1:5]
        if len(parts) != 5:
            continue
        collective, dtype, bucket, nranks, platform = parts
        ctl = ent.get("ctl")
        if isinstance(ctl, dict) and ctl.get("provenance") \
                == "online-switched":
            source = (f"online-switched({ctl.get('trigger', '?')}"
                      f"@epoch {ctl.get('epoch', '?')}")
            if isinstance(ent.get("program"), dict):
                source += f", {_program_steps(ent)} steps"
            source += ")"
        elif algo.startswith("synth:") and isinstance(ent.get("program"),
                                                      dict):
            source = f"synthesized({_program_steps(ent)} steps)"
        elif ent.get("measurements"):
            source = "measured"
        else:
            source = "recorded"
        rows.append((collective, dtype, bucket, nranks, platform, tiers,
                     algo, source))
    return rows


def _print_table(rows: List[tuple]) -> None:
    widths = [max(len(str(c)) for c in col)
              for col in zip(_COLUMNS, *rows)] if rows else \
        [len(c) for c in _COLUMNS]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*_COLUMNS))
    print(fmt.format(*("-" * w for w in widths)))
    for row in rows:
        print(fmt.format(*row))


def _main(argv: Iterable[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi4torch_tpu.tune",
        description="Inspect or clear the persistent autotuner cache.")
    parser.add_argument("--show", action="store_true",
                        help="print the cached winners table (default)")
    parser.add_argument("--json", action="store_true",
                        help="print the raw cache JSON instead")
    parser.add_argument("--clear", action="store_true",
                        help="delete the persisted cache file")
    args = parser.parse_args(list(argv))

    path = cache_path()
    if args.clear:
        try:
            os.remove(path)
            print(f"removed {path}")
        except FileNotFoundError:
            print(f"no cache file at {path}")
        except OSError as e:
            print(f"could not remove {path}: {e}", file=sys.stderr)
            return 1
        return 0

    data = _load_raw()
    if args.json:
        print(json.dumps(data, indent=1, sort_keys=True))
        return 0
    print(f"cache file: {path}")
    if data is None:
        print("no cache (missing or unreadable file) — auto selection "
              "uses the defaults")
        return 0
    if data.get("version") != CACHE_VERSION:
        print(f"cache version {data.get('version')!r} != expected "
              f"{CACHE_VERSION} — the file is ignored by selection "
              "(safe to --clear)")
        return 0
    rows = _rows(data)
    if not rows:
        print("cache holds no winners yet — run the sweep "
              "(python -m mpi4torch_tpu.tune.autotuner / make tune-smoke)")
        return 0
    _print_table(rows)
    print(f"{len(rows)} cached winner(s)")
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))

"""mpi4torch_tpu.analyze — the static collective-schedule verifier.

The paper's core contract — every collective is an AD node whose
backward is itself a collective, with handle machinery encoding the
cross-rank ordering the per-rank DAG cannot see — is exactly the class
of property a static pass can verify *before* the wire runs (GC3,
PAPERS.md: collective schedules are programs you can analyze and
transform).  This package is that pass, in four layers:

* **one parser** (:mod:`.parse`): :func:`parse_program` turns any
  lowered program into typed :class:`CollectiveOp` records — kind,
  ``replica_groups``, ``source_target_pairs``, channel, payload
  dtype/bytes, and the named-scope label recovered from the debug-info
  loc table — replacing the regex censuses that had grown in
  overlap/census.py, reshard/census.py, bench.py, and tests/.
* **soundness lints** (:mod:`.lints`): permute tables form valid
  partial permutations, replica groups exactly partition the
  participating axis, split-phase start→wait spans pair up per bucket
  with no dangling or double-completed handle, and each registered
  algorithm's backward census is its declared transpose
  (``AlgorithmSpec.vjp_census``) — today's runtime-only failure modes
  (DeadlockError, BifurcationError, silent corruption) as trace-time
  diagnoses.
* **unified accounting** (:mod:`.accounting`):
  :func:`wire_bytes_per_device`, :func:`peak_live_bytes`,
  :func:`scheduled_exposure` re-expressed on the shared parse; the
  historical entry points delegate here and their recorded BENCH/smoke
  numbers are regression-pinned bit-identical.
* **the registry-wide sweep** (:mod:`.sweep`, ``python -m
  mpi4torch_tpu.analyze --sweep``): lowers every registered
  (algorithm × codec) pair, reshard strategy, and overlap/serve decode
  schedule on the attached mesh and fails non-zero on any lint
  violation; the **seeded-defect corpus** (:mod:`.defects`,
  ``--defects``) proves every lint fires on a mutated schedule — the
  fired-fault-ledger discipline, applied to static analysis.

:mod:`.registry` additionally hosts the deduped registry-sync guards
every subsystem's smoke lane and test file had been carrying as
copies.  ``make analyze-smoke`` runs sweep + defect corpus on the
8-virtual-device CPU mesh.  See doc/analysis.md.
"""

from .accounting import (peak_live_bytes, scheduled_exposure,
                         tier_wire_table, weighted_wire_cost,
                         wire_bytes_per_device, wire_contribution)
from .defects import (DEFECTS, Defect, DefectPrograms,
                      defect_ledger_problems, run_defect_corpus)
from .lints import (LINT_NAMES, LintViolation, check_vjp_symmetry,
                    run_lints)
from .parse import (COLLECTIVE_KINDS, WIRE_OPS, CollectiveOp, OpEvent,
                    ParsedProgram, bucket_of, parse_program,
                    tensor_bytes)
from .sweep import run_sweep, sweep_worlds

__all__ = [
    "COLLECTIVE_KINDS",
    "WIRE_OPS",
    "CollectiveOp",
    "OpEvent",
    "ParsedProgram",
    "bucket_of",
    "parse_program",
    "tensor_bytes",
    "LINT_NAMES",
    "LintViolation",
    "run_lints",
    "check_vjp_symmetry",
    "wire_bytes_per_device",
    "wire_contribution",
    "tier_wire_table",
    "weighted_wire_cost",
    "peak_live_bytes",
    "scheduled_exposure",
    "DEFECTS",
    "Defect",
    "DefectPrograms",
    "run_defect_corpus",
    "defect_ledger_problems",
    "run_sweep",
    "sweep_worlds",
]

"""Soundness lints over the shared StableHLO parse.

The runtime failure modes of the collective machinery — a permute
schedule that loses or duplicates a shard, a grouped collective whose
groups drop a rank, a split-phase handle that deadlocks un-waited or
double-completes, a backward pass that is not the forward's transpose —
exist today as *runtime* errors (``DeadlockError``,
``BifurcationError``, ``IntegrityError``) that need the wire to run
before they surface.  Each lint here diagnoses the same class of
defect from the lowered program alone, at trace time:

=========================  =============================================
lint name                  property checked
=========================  =============================================
``permute-pairs``          every ``collective_permute``'s
                           ``source_target_pairs`` form a valid partial
                           permutation: no duplicated source, no
                           duplicated target, endpoints inside the
                           participating axis — a duplicated target is
                           two ranks writing one buffer (the runtime
                           analogue: silently dropped contribution).
``replica-groups``         every grouped collective's
                           ``replica_groups`` exactly partition the
                           participating axis (``mhlo.num_partitions``):
                           no rank in two groups, no rank in none — a
                           non-partitioning group is a rank whose
                           contribution never merges (the runtime
                           analogue: a hang or a wrong sum).
``split-phase``            split-phase bucket spans pair up: every
                           ``.start`` span has a ``.wait`` (a dangling
                           start is the trace-time ``DeadlockError``),
                           every ``.wait`` has a ``.start``, and no
                           bucket's wait phase completes the same wire
                           collective twice (the trace-time
                           ``BifurcationError``).
``vjp-symmetry``           a registered algorithm's backward census is
                           the declared transpose of its forward
                           (``AlgorithmSpec.vjp_census``) — the paper's
                           "backward of a collective is itself a
                           collective", checked structurally.
=========================  =============================================

:func:`run_lints` runs the single-program lints; the VJP lint compares
two lowerings (forward, forward+backward) via
:func:`check_vjp_symmetry`.  Every lint is proven live by the
seeded-defect corpus (:mod:`.defects`): a mutated schedule per lint
that must be caught *by name* — the fired-fault-ledger discipline of
``make faults-smoke``, applied to static analysis.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Optional, Union

from .parse import COLLECTIVE_KINDS, ParsedProgram, parse_program

__all__ = [
    "LINT_NAMES",
    "LintViolation",
    "run_lints",
    "check_vjp_symmetry",
    "lint_permute_pairs",
    "lint_replica_groups",
    "lint_split_phase",
]

# The closed lint registry.  The defect-corpus ledger (defects.py)
# cross-checks it: every name here must be the named catcher of at
# least one seeded defect, so a lint cannot ship without proof that it
# fires.
LINT_NAMES = ("permute-pairs", "replica-groups", "split-phase",
              "vjp-symmetry")


@dataclass(frozen=True)
class LintViolation:
    """One soundness violation, attributed to a program site."""
    lint: str                  # LINT_NAMES entry
    detail: str                # human diagnosis
    line: Optional[int] = None         # 0-based line in the lowering
    scope: str = ""                    # named-scope path, when known

    def __str__(self):
        at = f" @ line {self.line}" if self.line is not None else ""
        span = f" [{self.scope}]" if self.scope else ""
        return f"{self.lint}{at}{span}: {self.detail}"


def _dups(values) -> List:
    return sorted(v for v, c in Counter(values).items() if c > 1)


def lint_permute_pairs(parsed: ParsedProgram) -> List[LintViolation]:
    """``source_target_pairs`` must be a valid partial permutation."""
    out: List[LintViolation] = []
    n = parsed.num_partitions
    for op in parsed.collectives:
        if op.kind != "collective_permute" or not op.source_target_pairs:
            continue
        srcs = [s for s, _ in op.source_target_pairs]
        tgts = [t for _, t in op.source_target_pairs]
        for what, dups in (("source", _dups(srcs)), ("target",
                                                     _dups(tgts))):
            if dups:
                out.append(LintViolation(
                    "permute-pairs",
                    f"duplicated {what} rank(s) {dups} in "
                    f"source_target_pairs {list(op.source_target_pairs)}"
                    " — not a partial permutation",
                    line=op.line, scope=op.scope))
        if n is not None:
            bad = sorted({v for v in srcs + tgts
                          if not 0 <= v < n})
            if bad:
                out.append(LintViolation(
                    "permute-pairs",
                    f"rank(s) {bad} outside the {n}-partition axis in "
                    f"source_target_pairs {list(op.source_target_pairs)}",
                    line=op.line, scope=op.scope))
    return out


def lint_replica_groups(parsed: ParsedProgram) -> List[LintViolation]:
    """``replica_groups`` must exactly partition the participating
    axis."""
    out: List[LintViolation] = []
    n = parsed.num_partitions
    for op in parsed.collectives:
        if op.replica_groups is None:
            continue
        flat = [v for g in op.replica_groups for v in g if v >= 0]
        dups = _dups(flat)
        if dups:
            out.append(LintViolation(
                "replica-groups",
                f"rank(s) {dups} appear in more than one replica group "
                f"of {op.kind} {list(map(list, op.replica_groups))}",
                line=op.line, scope=op.scope))
        if n is not None:
            missing = sorted(set(range(n)) - set(flat))
            if missing:
                out.append(LintViolation(
                    "replica-groups",
                    f"replica groups "
                    f"{list(map(list, op.replica_groups))} of {op.kind} "
                    f"do not partition the {n}-partition axis — "
                    f"rank(s) {missing} are in no group",
                    line=op.line, scope=op.scope))
    return out


def lint_split_phase(parsed: ParsedProgram) -> List[LintViolation]:
    """Split-phase ``.start``/``.wait`` bucket spans must pair up, and
    no bucket may complete the same wire collective twice."""
    out: List[LintViolation] = []
    phases: Dict[tuple, Dict[str, List[int]]] = {}
    for ev in parsed.events:
        b = ev.bucket
        if b is None or b[3] is None:
            continue
        phases.setdefault(b[:3], {"start": [], "wait": []})[
            b[3]].append(ev.line)

    for key in sorted(phases):
        op, i, tot = key
        label = f"{op}.bucket{i}of{tot}"
        slot = phases[key]
        if slot["start"] and not slot["wait"]:
            out.append(LintViolation(
                "split-phase",
                f"{label}: started but never waited — an un-waited "
                "split-phase handle deadlocks its region "
                "(DeadlockError at run time)",
                line=min(slot["start"]), scope=label))
        if slot["wait"] and not slot["start"]:
            out.append(LintViolation(
                "split-phase",
                f"{label}: waited but never started — the handle this "
                "wait completes was issued nowhere in the program",
                line=min(slot["wait"]), scope=label))

    # Double completion: the same wire collective signature twice
    # inside one bucket's wait phase (a WaitHandle completes exactly
    # once — BifurcationError at run time).
    waits: Dict[tuple, Counter] = {}
    firsts: Dict[tuple, int] = {}
    for cop in parsed.collectives:
        b = cop.bucket
        if b is None or b[3] != "wait":
            continue
        sig = (cop.kind, cop.operand_types, cop.result_types,
               cop.replica_groups, cop.source_target_pairs)
        waits.setdefault(b[:3], Counter())[sig] += 1
        firsts.setdefault(b[:3] + (sig,), cop.line)
    for key, sigs in sorted(waits.items()):
        op, i, tot = key
        label = f"{op}.bucket{i}of{tot}"
        for sig, count in sigs.items():
            if count > 1:
                out.append(LintViolation(
                    "split-phase",
                    f"{label}: wait phase completes the same "
                    f"{sig[0]} {count}x — a split-phase handle "
                    "completes exactly once (BifurcationError at run "
                    "time)",
                    line=firsts[key + (sig,)], scope=label))
    return out


def run_lints(lowered_or_text) -> List[LintViolation]:
    """Run every single-program soundness lint; returns the (possibly
    empty) violation list.  The VJP-symmetry lint needs a forward AND a
    forward+backward lowering — see :func:`check_vjp_symmetry`."""
    parsed = lowered_or_text if isinstance(lowered_or_text,
                                           ParsedProgram) \
        else parse_program(lowered_or_text)
    out: List[LintViolation] = []
    out += lint_permute_pairs(parsed)
    out += lint_replica_groups(parsed)
    out += lint_split_phase(parsed)
    return out


def _transpose_census(census: Dict[str, int],
                      declaration: Union[str, Dict[str, str]]
                      ) -> Dict[str, int]:
    """The declared backward census of a forward census.  ``"self"``
    (the self-adjoint declaration every shipped allreduce schedule
    makes: psum's adjoint is psum, so the backward re-runs the same
    machinery) maps each kind to itself; a dict declaration maps op
    kinds to their transposed kinds (``{"all_gather":
    "reduce_scatter", ...}``)."""
    if declaration == "self":
        return dict(census)
    if isinstance(declaration, dict):
        out = {k: 0 for k in COLLECTIVE_KINDS}
        for kind, count in census.items():
            out[declaration.get(kind, kind)] += count
        return out
    raise ValueError(
        f"unknown vjp_census declaration {declaration!r}; declare "
        "'self' or a kind->kind transpose mapping")


def check_vjp_symmetry(fwd, fwdbwd,
                       declaration: Union[str, Dict[str, str]] = "self",
                       context: str = "") -> List[LintViolation]:
    """Check that the backward half of ``fwdbwd`` (a ``value_and_grad``
    lowering of the same program as ``fwd``) adds exactly the declared
    transpose of the forward census — the paper's AD-transparency
    contract, structurally: the backward of a collective schedule is
    itself a collective schedule, with the declared op mix.

    ``declaration`` comes from the registered
    ``AlgorithmSpec.vjp_census`` (how a new algorithm declares its
    symmetry — see doc/analysis.md)."""
    fwd_p = fwd if isinstance(fwd, ParsedProgram) else parse_program(fwd)
    bwd_p = fwdbwd if isinstance(fwdbwd, ParsedProgram) \
        else parse_program(fwdbwd)
    fc, bc = fwd_p.census(), bwd_p.census()
    added = {k: bc[k] - fc[k] for k in COLLECTIVE_KINDS}
    expected = _transpose_census(
        {k: v for k, v in fc.items() if v}, declaration)
    want = {k: expected.get(k, 0) for k in COLLECTIVE_KINDS}
    if added != want:
        tag = f"{context}: " if context else ""
        return [LintViolation(
            "vjp-symmetry",
            f"{tag}backward census is not the declared transpose of "
            f"the forward: forward {_short(fc)}, backward adds "
            f"{_short(added)}, declaration {declaration!r} expects "
            f"{_short(want)}")]
    return []


def _short(census: Dict[str, int]) -> Dict[str, int]:
    return {k: v for k, v in census.items() if v}

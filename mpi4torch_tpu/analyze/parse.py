"""One StableHLO parser for every census in the repo.

The paper's contract — every collective is an AD node whose backward is
itself a collective, with handle machinery encoding cross-rank ordering
the per-rank DAG cannot see — is a *structural* property of the lowered
program, and the repo grew four independent regex readers of that
structure: the scheduled-exposure census (overlap/census.py), the
peak-liveness scan (reshard/census.py), the wire-bytes accounting
(bench.py), and ~45 ad-hoc matchers in tests/test_hlo.py.  This module
replaces the *parsing* layer under all of them with one pass:

:func:`parse_program` turns any lowered program (a ``jax.stages.
Lowered`` or its ``as_text()``/``debug_info=True`` text) into a
:class:`ParsedProgram` carrying

* typed :class:`CollectiveOp` records for every wire op —
  kind, ``replica_groups`` (values AND declared shape),
  ``source_target_pairs``, channel handle, operand/result tensor types,
  payload dtype/bytes, and the named-scope label recovered from the
  debug-info loc table (``mpi4torch.Allreduce.q8``,
  ``mpi4torch.Allreduce_tree.bucket0of3.start``, ...);
* an :class:`OpEvent` stream of EVERY ``stablehlo.*`` op in program
  order with its scope — the substrate of the scheduled-exposure
  census, kept event-for-event identical to the original
  overlap/census.py reader so the recorded exposure fractions stay
  bit-identical;
* the module's ``mhlo.num_partitions`` (the participating axis the
  replica-group lints check partitioning against) and the per-function
  line structure (the liveness scan's scoping rule).

The soundness lints (:mod:`.lints`), the unified accounting passes
(:mod:`.accounting`), and the registry-wide sweep (:mod:`.sweep`,
``python -m mpi4torch_tpu.analyze --sweep``) are all passes over this
parse; its op records are the structural seed for the GC3-style
schedule IR (ROADMAP item 1).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import cached_property
from typing import Dict, List, Optional, Tuple

__all__ = [
    "COLLECTIVE_KINDS",
    "WIRE_OPS",
    "CollectiveOp",
    "OpEvent",
    "ParsedProgram",
    "bucket_of",
    "dtype_bytes",
    "parse_program",
    "tensor_bytes",
]

# The StableHLO op kinds that put bytes on the wire (or rendezvous
# ranks).  One definition: the exposure census's in-flight-company set,
# the wire-bytes accounting's op table, and the lints' structural
# domain all read it from here.
COLLECTIVE_KINDS = ("all_reduce", "all_gather", "reduce_scatter",
                    "all_to_all", "collective_permute")
WIRE_OPS = frozenset(COLLECTIVE_KINDS)

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

# Loc-table grammar.  `scope` keeps the semantics the original census
# readers relied on: the leading name string of the op line's loc
# definition (`#locN = loc("jit(..)/../mpi4torch.Allreduce.q8/.."`), an
# inline `loc("...")`, or "" — pure-callsite locs carry Python frames,
# not named-scope paths, and resolving them would silently re-key the
# recorded exposure censuses.
_LOC_DEF = re.compile(r'^#loc(\d+) = loc\("([^"]*)"')
_LOC_REF = re.compile(r"loc\(#loc(\d+)\)")
_LOC_INLINE = re.compile(r'loc\("([^"]*)"')
_OP_KIND = re.compile(r'"?stablehlo\.([a-z_0-9]+)"?')
_BUCKET = re.compile(
    r"mpi4torch\.(?P<op>[A-Za-z_]+)\.bucket(?P<i>\d+)of(?P<n>\d+)"
    r"(?P<rest>(?:\.\w+)*)")
_LABEL = re.compile(r"mpi4torch\.[A-Za-z_0-9.]+")

_NUM_PARTITIONS = re.compile(r"mhlo\.num_partitions = (\d+)")
_COLLECTIVE_HEAD = re.compile(
    r'"?stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|'
    r'collective_permute)"?\(')
_REPLICA_GROUPS = re.compile(
    r"replica_groups = dense<([^>]*)> : tensor<(\d+)x(\d+)xi64>")
_SOURCE_TARGET = re.compile(
    r"source_target_pairs = dense<([^>]*)> : tensor<(\d+)x2xi64>")
_CHANNEL = re.compile(
    r"#stablehlo\.channel_handle<handle = (\d+)")
_SIGNATURE = re.compile(r":\s*\(([^)]*)\)\s*->\s*(.*)$")
_REGION_CLOSE = re.compile(r"^\s*\}\)\s*:")
_TENSOR = re.compile(r"tensor<([^>]*)>")
_FUNC = re.compile(r"func\.func\s+(?:public\s+|private\s+)?@([\w.$-]+)")


def dtype_bytes(element_type: str) -> Optional[int]:
    """Bytes per element of a StableHLO element type (``f32`` -> 4), or
    None for token/tuple/unknown types that carry no priceable
    buffer."""
    return _DTYPE_BYTES.get(element_type)


def tensor_bytes(desc: str) -> int:
    """Bytes of a ``tensor<...>`` type description (``8x128xf32``).
    Token/tuple/unknown element types and dynamic dims carry 0 — they
    have no buffer the accountings could price.  (A zero-sized dim is
    a legitimate 0, not unknown — :func:`dtype_bytes` distinguishes.)"""
    parts = desc.replace(" ", "").split("x")
    n = _DTYPE_BYTES.get(parts[-1])
    if n is None:
        return 0
    for d in parts[:-1]:
        if not d.isdigit():
            return 0
        n *= int(d)
    return n


def bucket_of(scope: str):
    """``(op, bucket, total, phase)`` of the outermost
    ``mpi4torch.<Op>.bucket<i>of<n>[...]`` span in a location path, or
    None — the bucket_scope grammar of utils/profiling.py, shared by
    the exposure census and the split-phase lints."""
    m = _BUCKET.search(scope)
    if m is None:
        return None
    rest = m.group("rest").split(".")
    phase = ("start" if "start" in rest
             else "wait" if "wait" in rest else None)
    return (m.group("op"), int(m.group("i")), int(m.group("n")), phase)


def _parse_dense_int(literal: str, rows: int, cols: int
                     ) -> Tuple[Tuple[int, ...], ...]:
    """A `dense<...>` integer literal as row tuples: bracketed tables
    (``[[0, 1], [2, 3]]``) verbatim, splats (``dense<0>``) expanded to
    the declared shape."""
    body = literal.strip()
    if body.startswith("["):
        return tuple(
            tuple(int(v) for v in re.findall(r"-?\d+", row))
            for row in re.findall(r"\[([^\[\]]*)\]", body))
    v = int(body)
    return tuple((v,) * cols for _ in range(rows))


@dataclass(frozen=True)
class OpEvent:
    """One ``stablehlo.*`` op occurrence in program order."""
    line: int          # 0-based line index in the lowered text
    kind: str          # op mnemonic ("all_reduce", "add", ...)
    scope: str         # named-scope path of the op line's loc, or ""

    @property
    def bucket(self):
        return bucket_of(self.scope)


@dataclass(frozen=True)
class CollectiveOp:
    """A typed record of one wire collective in a lowered program."""
    kind: str                                    # COLLECTIVE_KINDS entry
    line: int                                    # head-line index
    scope: str                                   # named-scope path or ""
    operand_types: Tuple[str, ...]               # tensor<..> descs
    result_types: Tuple[str, ...]
    replica_groups: Optional[Tuple[Tuple[int, ...], ...]] = None
    group_shape: Optional[Tuple[int, int]] = None   # declared RxC
    source_target_pairs: Optional[Tuple[Tuple[int, int], ...]] = None
    channel: Optional[int] = None

    @property
    def dtype(self) -> Optional[str]:
        """Element type of the payload (first operand)."""
        if not self.operand_types:
            return None
        return self.operand_types[0].replace(" ", "").split("x")[-1]

    @property
    def payload_bytes(self) -> int:
        """Bytes of the first operand — what one device contributes."""
        return tensor_bytes(self.operand_types[0]) \
            if self.operand_types else 0

    @property
    def group_size(self) -> Optional[int]:
        """Participants per replica group (the declared column count —
        the ``s`` of the standard ring wire accountings)."""
        return self.group_shape[1] if self.group_shape else None

    @property
    def label(self) -> Optional[str]:
        """The outermost ``mpi4torch.*`` span of the scope path (e.g.
        ``mpi4torch.Allreduce.q8``), or None."""
        m = _LABEL.search(self.scope)
        return m.group(0) if m else None

    @property
    def bucket(self):
        return bucket_of(self.scope)


@dataclass
class ParsedProgram:
    """The shared parse every analysis pass consumes."""
    text: str
    lines: List[str] = field(repr=False)
    num_partitions: Optional[int]
    events: Tuple[OpEvent, ...] = field(repr=False)
    collectives: Tuple[CollectiveOp, ...]

    def census(self) -> Dict[str, int]:
        """Collective-kind -> occurrence count, every kind present (the
        tests/test_hlo.py ``census()``/``only()`` shape)."""
        out = {k: 0 for k in COLLECTIVE_KINDS}
        for op in self.collectives:
            out[op.kind] += 1
        return out

    def ops(self, kind: Optional[str] = None,
            dtype: Optional[str] = None) -> Tuple[CollectiveOp, ...]:
        """Collective records filtered by kind and/or payload dtype."""
        got = self.collectives
        if kind is not None:
            got = tuple(op for op in got if op.kind == kind)
        if dtype is not None:
            got = tuple(op for op in got if op.dtype == dtype)
        return got

    def scopes(self) -> Tuple[str, ...]:
        """Every distinct non-empty scope path, in first-seen order."""
        seen: Dict[str, None] = {}
        for ev in self.events:
            if ev.scope:
                seen.setdefault(ev.scope)
        return tuple(seen)

    @cached_property
    def function_chunks(self) -> List[List[str]]:
        """The text split at ``func.func`` boundaries — SSA values are
        per-function scopes, so the liveness scan censuses chunk by
        chunk (the reshard/census.py scoping rule)."""
        chunks: List[List[str]] = []
        cur: List[str] = []
        for ln in self.lines:
            if "func.func" in ln and cur:
                chunks.append(cur)
                cur = []
            cur.append(ln)
        if cur:
            chunks.append(cur)
        return chunks


def _as_text(lowered_or_text, debug_info: bool = True) -> str:
    if isinstance(lowered_or_text, str):
        return lowered_or_text
    from .._compat import lowered_text
    return lowered_text(lowered_or_text, debug_info=debug_info)


def _scope_of(line: str, loc_names: Dict[str, str]) -> str:
    ref = _LOC_REF.search(line)
    scope = loc_names.get(ref.group(1), "") if ref is not None else ""
    if not scope:
        im = _LOC_INLINE.search(line)
        scope = im.group(1) if im is not None else ""
    return scope


def _collective_at(lines: List[str], idx: int, kind: str,
                   loc_names: Dict[str, str]) -> CollectiveOp:
    """Assemble the typed record of the collective whose head is on
    ``lines[idx]``.  Attributes live on the head line; ``all_reduce``/
    ``reduce_scatter`` carry a multi-line reduction region, so their
    type signature (and authoritative loc) sit on the ``}) :`` closing
    line."""
    head = lines[idx]
    sig_line = head
    if _SIGNATURE.search(_strip_loc(head)) is None:
        for j in range(idx + 1, len(lines)):
            if _REGION_CLOSE.match(lines[j]):
                sig_line = lines[j]
                break

    groups = shape = None
    m = _REPLICA_GROUPS.search(head)
    if m is not None:
        shape = (int(m.group(2)), int(m.group(3)))
        groups = _parse_dense_int(m.group(1), *shape)
    pairs = None
    m = _SOURCE_TARGET.search(head)
    if m is not None:
        pairs = tuple(
            (int(a), int(b))
            for a, b in _parse_dense_int(m.group(1), int(m.group(2)), 2))
    cm = _CHANNEL.search(head)
    channel = int(cm.group(1)) if cm is not None else None

    operand_types: Tuple[str, ...] = ()
    result_types: Tuple[str, ...] = ()
    sm = _SIGNATURE.search(_strip_loc(sig_line))
    if sm is not None:
        operand_types = tuple(
            t.group(1) for t in _TENSOR.finditer(sm.group(1)))
        result_types = tuple(
            t.group(1) for t in _TENSOR.finditer(sm.group(2)))

    scope = _scope_of(head, loc_names)
    if not scope and sig_line is not head:
        scope = _scope_of(sig_line, loc_names)
    return CollectiveOp(
        kind=kind, line=idx, scope=scope,
        operand_types=operand_types, result_types=result_types,
        replica_groups=groups, group_shape=shape,
        source_target_pairs=pairs, channel=channel)


def _strip_loc(line: str) -> str:
    """Drop the trailing ``loc(...)`` so the signature regex's greedy
    tail captures only type text."""
    i = line.rfind(" loc(")
    return line[:i] if i >= 0 else line


def parse_program(lowered_or_text,
                  debug_info: bool = True) -> ParsedProgram:
    """Parse a lowered program (``jax.stages.Lowered`` or its text)
    into the shared :class:`ParsedProgram`.  ``debug_info`` only
    matters when a ``Lowered`` is passed: the named-scope labels
    (bucket spans, codec suffixes) live in the debug-info loc table, so
    scope-reading passes need it on (the default)."""
    text = _as_text(lowered_or_text, debug_info=debug_info)
    lines = text.splitlines()

    loc_names: Dict[str, str] = {}
    for ln in lines:
        m = _LOC_DEF.match(ln)
        if m is not None:
            loc_names[m.group(1)] = m.group(2)

    mp = _NUM_PARTITIONS.search(text)
    num_partitions = int(mp.group(1)) if mp is not None else None

    events: List[OpEvent] = []
    collectives: List[CollectiveOp] = []
    for idx, ln in enumerate(lines):
        if ln.startswith("#loc"):
            continue
        km = _OP_KIND.search(ln)
        if km is None:
            continue
        events.append(OpEvent(line=idx, kind=km.group(1),
                              scope=_scope_of(ln, loc_names)))
        cm = _COLLECTIVE_HEAD.search(ln)
        if cm is not None:
            collectives.append(
                _collective_at(lines, idx, cm.group(1), loc_names))

    return ParsedProgram(
        text=text, lines=lines, num_partitions=num_partitions,
        events=tuple(events), collectives=tuple(collectives))

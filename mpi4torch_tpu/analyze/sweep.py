"""Registry-wide lint sweep: lower everything registered, lint it all.

The lints (:mod:`.lints`) only help if they run over the schedules the
registries can actually emit — all of them, not the handful a test
happened to lower.  This module enumerates, from the LIVE registries,

* every (algorithm × codec) Allreduce pair
  (``tune.available_algorithms()`` × the codecs declaring each
  algorithm, via the same ``codec_rides_algorithm`` predicate the
  facade enforces), forward AND ``value_and_grad`` backward, with the
  VJP-symmetry lint checking each algorithm's declared
  ``AlgorithmSpec.vjp_census`` transpose;
* the Bcast_/Reduce_ forms of the algorithms serving those collectives;
* every reshard strategy (``reshard.STRATEGIES``), each on a transition
  that exercises it, forward and adjoint — feeding the step-kind
  coverage leg of the reshard registry guard;
* the overlap schedules (windowed fused tree + the serve decode
  primitive ``overlap_split_allreduce``) — the split-phase lint's
  real-program coverage;
* the serve decode schedule (``Engine.lower_step``), overlap and
  blocking.

Every lowering runs the full structural lint set; a single violation
anywhere fails the sweep (``python -m mpi4torch_tpu.analyze --sweep``
exits non-zero — the ``make analyze-smoke`` lane).  Schedules a world
cannot serve (rhd on a non-power-of-two world, torus without a
factorization) are recorded as *skipped with the registry's own
reason*, never silently dropped.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .accounting import scheduled_exposure
from .lints import check_vjp_symmetry, run_lints
from .parse import parse_program

__all__ = ["run_sweep", "sweep_worlds"]


def sweep_worlds(ndev: int) -> List[Tuple]:
    """The standard sweep worlds an ``ndev``-device harness can serve:
    the full flat world, the (3,) non-power-of-two world, the
    single-rank world, and the (2,4) two-axis mesh on 8 devices."""
    worlds: List[Tuple] = [(ndev,)]
    if ndev >= 3:
        worlds.append((3,))
    worlds.append((1,))
    if ndev == 8:
        worlds.append((2, 4))
    return worlds


def _flat_lowerer(nranks: int):
    """(lower, comm) over a fresh mesh of the first ``nranks``
    devices: ``lower(body, *args)`` -> debug-info StableHLO text of the
    shard_mapped ``body(comm, *args)``."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from .._compat import lowered_text, shard_map

    mesh = Mesh(np.asarray(jax.devices()[:nranks]), ("w",))
    comm = mpi.comm_from_mesh(mesh, "w")

    def lower(body, *args):
        fn = shard_map(lambda *a: body(comm, *a), mesh=mesh,
                       in_specs=P(), out_specs=P(), check_vma=False)
        return lowered_text(jax.jit(fn).lower(*args), debug_info=True)

    return lower, comm


def _mesh2d_lowerer(shape: Tuple[int, int]):
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from .._compat import lowered_text, shard_map

    a, b = shape
    mesh = Mesh(np.asarray(jax.devices()[:a * b]).reshape(a, b),
                ("outer", "inner"))
    comm = mpi.comm_from_mesh(mesh, ("outer", "inner"))

    def lower(body, *args):
        fn = shard_map(lambda *a_: body(comm, *a_), mesh=mesh,
                       in_specs=P(), out_specs=P(), check_vma=False)
        return lowered_text(jax.jit(fn).lower(*args), debug_info=True)

    return lower, comm


def _lint_case(records: List[dict], case: str, fwd_text: str,
               fwdbwd_text: Optional[str] = None,
               vjp_declaration=None, extra: Optional[dict] = None):
    """Run the structural lints (and, when a declaration is given, the
    VJP-symmetry lint) and append one sweep record."""
    fwd = parse_program(fwd_text)
    violations = run_lints(fwd)
    if fwdbwd_text is not None:
        bwd = parse_program(fwdbwd_text)
        violations += run_lints(bwd)
        if vjp_declaration is not None:
            violations += check_vjp_symmetry(
                fwd, bwd, vjp_declaration, context=case)
    rec = {"case": case, "skipped": None,
           "census": {k: v for k, v in fwd.census().items() if v},
           "violations": [str(v) for v in violations]}
    if extra:
        rec.update(extra)
    records.append(rec)


def _skip(records: List[dict], case: str, reason: str):
    records.append({"case": case, "skipped": reason, "census": {},
                    "violations": []})


def _sweep_allreduce_flat(records: List[dict], nranks: int,
                          nelem: int = 512):
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from .. import tune
    from ..compress import available_codecs, codec_rides_algorithm, \
        get_codec

    lower, comm = _flat_lowerer(nranks)
    x = jnp.ones((nelem,), jnp.float32)

    for algo in tune.available_algorithms():
        spec = tune.get_algorithm(algo)
        why = spec.why_not(nranks)
        if why is not None:
            _skip(records, f"({nranks},) allreduce.{algo}", why)
            continue
        codecs = [None] + [
            c for c in available_codecs()
            if codec_rides_algorithm(get_codec(c), algo)]
        for codec in codecs:
            tag = f"({nranks},) allreduce.{algo}" + (
                f".{codec}" if codec else "")

            def body(c, v, algo=algo, codec=codec):
                return c.Allreduce(v, mpi.MPI_SUM, algorithm=algo,
                                   compression=codec or False)

            def loss(c, v, body=body):
                return jax.value_and_grad(
                    lambda u: jnp.sum(body(c, u)))(v)

            _lint_case(records, tag, lower(body, x), lower(loss, x),
                       vjp_declaration=spec.vjp_census)

    # The bcast/reduce forms of the algorithms that serve them: the
    # adjoint of Bcast_ is a Reduce_ (and vice versa) — a cross-op
    # transpose test_hlo censuses — so these legs run the structural
    # lints on the forward lowering.
    for collective, op in (("bcast", "Bcast_"), ("reduce", "Reduce_")):
        for algo in tune.available_algorithms():
            spec = tune.get_algorithm(algo)
            if spec.why_not(nranks, collective) is not None:
                continue

            def body(c, v, algo=algo, op=op):
                if op == "Bcast_":
                    return c.Bcast_(v, root=0, algorithm=algo)
                return c.Reduce_(v, mpi.MPI_SUM, root=0,
                                 algorithm=algo)

            _lint_case(records, f"({nranks},) {collective}.{algo}",
                       lower(body, x))


def _sweep_allreduce_2d(records: List[dict], shape: Tuple[int, int],
                        nelem: int = 512):
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    lower, comm = _mesh2d_lowerer(shape)
    x = jnp.ones((nelem,), jnp.float32)
    label = f"{shape}"

    # The 2-axis hier backend owns its algorithm resolution: its native
    # grouped schedule, plus the explicit hier/torus forms it can
    # lower; no codec pipeline (supports_compression=False).
    for algo in (None, "hier", "torus"):
        tag = f"{label} allreduce." + (algo or "native")

        def body(c, v, algo=algo):
            return c.Allreduce(v, mpi.MPI_SUM, algorithm=algo)

        def loss(c, v, body=body):
            return jax.value_and_grad(
                lambda u: jnp.sum(body(c, u)))(v)

        _lint_case(records, tag, lower(body, x), lower(loss, x),
                   vjp_declaration="self")


def _reshard_factors(n: int) -> Optional[Tuple[int, int]]:
    for a in range(2, n):
        if n % a == 0 and n // a > 1:
            return (a, n // a)
    return None


def _sweep_reshard(records: List[dict], nranks: int):
    """Every reshard strategy on a transition that exercises it;
    returns the step kinds the planned forward+adjoint programs
    covered (the registry guard's sweep-coverage leg)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from .. import reshard as rs

    lower, comm = _flat_lowerer(nranks)
    n = nranks
    factors = _reshard_factors(n)
    G = (4 * n, n)
    exercised: set = set()

    cases: List[Tuple[str, object, object]] = [
        ("local", rs.layout((n,), None, None), rs.layout((n,), 0, None)),
        ("gather", rs.layout((n,), None, None),
         rs.layout((n,), 0, None)),
    ]
    if factors is not None:
        a, b = factors
        cases += [
            ("alltoall", rs.layout((n,), 0, None),
             rs.layout((a, b), 0, 1)),
            ("rounds", rs.layout((n,), 0, None),
             rs.layout((a, b), 0, 1)),
            ("allgather", rs.layout((n,), 0, None),
             rs.layout((a, b), (0,), None)),
            ("permute", rs.layout((a, b), (0, 1), None),
             rs.layout((a, b), (1, 0), None)),
            ("gather", rs.layout((n,), 0, None),
             rs.layout((a, b), 0, 1)),
        ]
    ran = set()
    for strategy, fl, tl in cases:
        tag = f"({nranks},) reshard.{strategy}"
        if tag in ran:
            tag += ".migrate"
        ran.add(tag)
        plan = rs.plan_reshard(fl, tl, G, np.float32, strategy)
        exercised |= {s.kind for s in plan.steps}
        exercised |= {s.kind for s in plan.adjoint().steps}

        def body(c, v, fl=fl, tl=tl, strategy=strategy):
            return c.Reshard(v, fl, tl, strategy=strategy)

        def loss(c, v, body=body):
            return jax.value_and_grad(
                lambda u: jnp.sum(body(c, u)))(v)

        x = jnp.zeros(fl.shard_shape(G), jnp.float32)
        _lint_case(records, tag, lower(body, x), lower(loss, x))

    missing = sorted(set(rs.STRATEGIES)
                     - {c[0] for c in cases})
    for strategy in missing:
        _skip(records, f"({nranks},) reshard.{strategy}",
              f"needs a 2-level factorization; {n} has none")
    return exercised, factors is not None


def _sweep_overlap(records: List[dict], nranks: int):
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from ..overlap import overlap_split_allreduce

    lower, comm = _flat_lowerer(nranks)

    tree = {f"p{i}": jnp.ones((192 + 8 * i,), jnp.float32)
            for i in range(4)}

    def fused(c, t):
        return c.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=1024,
                                overlap=2)

    txt = lower(fused, tree)
    _lint_case(records, f"({nranks},) overlap.allreduce_tree", txt,
               extra={"scheduled_exposure":
                      scheduled_exposure(txt)["exposed_fraction"]})

    def split(c, v):
        return overlap_split_allreduce(c, v, mpi.MPI_SUM, nsplits=3)

    txt = lower(split, jnp.ones((1536,), jnp.float32))
    _lint_case(records, f"({nranks},) overlap.split_allreduce", txt,
               extra={"scheduled_exposure":
                      scheduled_exposure(txt)["exposed_fraction"]})


def _sweep_serve(records: List[dict], nranks: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .._compat import lowered_text
    from ..models import transformer as T
    from ..serve import Engine, ServeConfig

    ndev = len(jax.devices())
    size = min(nranks, 4 if ndev >= 4 else (2 if ndev >= 2 else 1))
    cfg = T.TransformerConfig(vocab=37, d_model=16, n_heads=4,
                              n_layers=1, d_ff=32, max_seq=16)
    params = T.init_transformer(jax.random.PRNGKey(0), cfg,
                                dtype=jnp.float32)
    for name, ov in (("overlap", True), ("blocking", False)):
        eng = Engine(cfg, params, ServeConfig(slots=2, overlap=ov),
                     spmd=True, nranks=size)
        eng.submit(np.array([1, 2, 3]), max_new=2)
        eng.step()
        txt = lowered_text(eng.lower_step(), debug_info=True)
        _lint_case(
            records, f"({size},) serve.decode.{name}", txt,
            extra={"scheduled_exposure":
                   scheduled_exposure(txt)["exposed_fraction"]})


def run_sweep(world: Tuple[int, ...], include_serve: bool = True
              ) -> Dict:
    """Lint-sweep every registered schedule the ``world`` (a flat
    ``(n,)`` or two-axis ``(a, b)`` rank shape, served from the
    attached devices) can lower.  Returns ``{"world", "records",
    "n_cases", "n_skipped", "violations", "problems"}`` — ``problems``
    carries the standing registry-sync guards plus the reshard
    step-kind coverage of this sweep's own plans."""
    import jax

    from .registry import reshard_step_problems, standing_problems

    ndev = len(jax.devices())
    need = world[0] * (world[1] if len(world) > 1 else 1)
    if need > ndev:
        raise ValueError(
            f"world {world} needs {need} devices; {ndev} attached")

    records: List[dict] = []
    problems: List[str] = []
    if len(world) == 2:
        _sweep_allreduce_2d(records, world)
    else:
        n = world[0]
        _sweep_allreduce_flat(records, n)
        exercised, factorable = _sweep_reshard(records, n)
        problems += reshard_step_problems(
            exercised if factorable else None)
        if n >= 2:
            _sweep_overlap(records, n)
        if include_serve:
            _sweep_serve(records, n)
    problems += standing_problems()

    violations = [v for r in records for v in r["violations"]]
    return {
        "world": world,
        "records": records,
        "n_cases": sum(1 for r in records if r["skipped"] is None),
        "n_skipped": sum(1 for r in records if r["skipped"]),
        "violations": violations,
        "problems": problems,
    }

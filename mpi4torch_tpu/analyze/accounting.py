"""Unified deterministic accounting passes over the shared parse.

The repo's perf-evidence currency is deterministic estimators read off
the lowering (ROADMAP: HLO op counts, wire bytes, scheduled exposure,
peak liveness — the regression currency while wall-clock evidence is
CPU-smoke only).  This module re-expresses all three text-census
accountings as passes over :func:`mpi4torch_tpu.analyze.parse_program`;
the historical entry points (``bench._hlo_wire_bytes_per_device``,
``reshard.peak_live_bytes``, ``overlap.scheduled_exposure``) delegate
here, and their recorded BENCH/smoke numbers are regression-pinned
bit-identical in tests/test_analyze.py (q8-bidir 7280 B, the
(8,)->(2,4) reshard migration 98304 B vs the 917504 B gather, the serve
decode step's per-token wire bytes and exposure fractions).

* :func:`wire_bytes_per_device` — per-device bytes-on-wire under the
  standard ring accountings: a ``collective_permute`` ships its operand
  once; an ``all_gather`` over groups of size s ships the local shard
  (s-1) times; an ``all_reduce`` 2(s-1)/s of the payload; a
  ``reduce_scatter`` (s-1)/s; an ``all_to_all`` keeps 1/s local and
  ships the rest.
* :func:`peak_live_bytes` — last-use SSA liveness scan, censused per
  ``func.func`` (SSA names are function scopes; the maximum wins).
  An *estimator* — XLA buffer assignment can alias and fuse — but exact
  about what a planner controls: a program that materializes an
  ``N x shard`` gather carries that tensor through its liveness range
  no matter how it is scheduled.
* :func:`scheduled_exposure` — the split-phase window census: a bucket
  whose ``.start``/``.wait`` span has another collective's wire op in
  flight inside it is *hidden*; an empty window (or a blocking,
  zero-width one) is *exposed*.  Blocking programs census 1.0 by
  construction, windowed split-phase programs strictly lower.  Exact
  about the program, conservative about the runtime: it never claims
  wall-clock hiding, only that the schedule keeps >= 2 transfers in
  flight.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .parse import (WIRE_OPS, ParsedProgram, dtype_bytes,
                    parse_program, tensor_bytes)

__all__ = [
    "wire_contribution",
    "wire_bytes_per_device",
    "tier_wire_table",
    "weighted_wire_cost",
    "peak_live_bytes",
    "scheduled_exposure",
]


def _parsed(lowered_or_text) -> ParsedProgram:
    if isinstance(lowered_or_text, ParsedProgram):
        return lowered_or_text
    return parse_program(lowered_or_text)


# ------------------------------------------------------------- wire bytes

def _payload_bytes(op) -> int:
    """Operand bytes with the historical strictness: the wire table is
    a verdict surface, so an UNKNOWN payload element type is an error,
    not a silent zero — while a legitimately empty payload (a
    zero-sized dim) prices at 0, as it always did."""
    desc = op.operand_types[0] if op.operand_types else ""
    n = tensor_bytes(desc)
    if n == 0 and dtype_bytes(op.dtype or "") is None:
        raise ValueError(f"unknown element type in tensor<{desc}>")
    return n


def wire_contribution(kind: str, payload_bytes: float,
                      group_size: int = None) -> float:
    """Per-device bytes-on-wire of ONE collective under the standard
    ring accountings (module docstring): THE shared formula — the
    static pass below applies it to parsed StableHLO ops, and the
    runtime reconciler (:func:`mpi4torch_tpu.obs.reconcile`) applies it
    to censused Mode B chokepoint payloads, so the two sides can only
    agree or disagree about the *traffic*, never about the pricing
    rule."""
    if kind == "collective_permute":
        return float(payload_bytes)
    s = group_size
    if s is None or s < 1:
        raise ValueError(
            f"{kind} needs a replica-group size to price; got {s!r}")
    if kind == "all_gather":
        return (s - 1) * float(payload_bytes)
    if kind == "all_reduce":
        return 2 * (s - 1) / s * float(payload_bytes)
    if kind in ("reduce_scatter", "all_to_all"):
        return (s - 1) / s * float(payload_bytes)
    raise ValueError(f"unknown wire collective kind {kind!r}")


def wire_bytes_per_device(lowered_or_text) -> Tuple[int, Dict[str, int]]:
    """Deterministic per-device bytes-on-wire of a lowered program
    (see module docstring for the per-kind accountings).  Returns
    ``(total_bytes, per-op-kind counts)`` — the
    ``bench._hlo_wire_bytes_per_device`` contract, now a pass over the
    shared parse."""
    parsed = _parsed(lowered_or_text)
    wire = 0.0
    counts: Dict[str, int] = {}
    for op in parsed.collectives:
        if op.kind != "collective_permute" and op.group_size is None:
            continue  # no replica_groups: not a priceable transfer
        counts[op.kind] = counts.get(op.kind, 0) + 1
        wire += wire_contribution(op.kind, _payload_bytes(op),
                                  op.group_size)
    return int(round(wire)), counts


def _op_tier(op, tiers) -> int:
    """Tier of ONE parsed collective under the mixed-radix attribution
    rule (single source: :func:`mpi4torch_tpu.csched.census.tier_of_group`
    — the highest tier whose digit differs among any group's members).
    A ``collective_permute`` is attributed by its ``source_target_pairs``
    (each pair is a 2-member group); an op with no replica groups spans
    the whole axis and prices at the top tier."""
    from ..csched.census import tier_of_group

    top = len(tiers) - 1
    if op.kind == "collective_permute":
        pairs = op.source_target_pairs
        if not pairs:
            return top
        return max(tier_of_group(pair, tiers) for pair in pairs)
    if not op.replica_groups:
        return top
    return max(tier_of_group(g, tiers) for g in op.replica_groups)


def tier_wire_table(lowered_or_text, tiers) -> List[int]:
    """Per-tier split of :func:`wire_bytes_per_device` under a flat-world
    tier stack ``tiers`` (innermost first — the
    ``config.tier_stack()`` / ``tune.resolve_tier_stack`` grammar).

    Each parsed collective's whole wire contribution lands on the tier
    of its WIDEST replica-group span (an ``all_gather`` over an
    innermost-tier group is intra-pod traffic no matter how many such
    groups tile the axis; a group mixing outer-tier digits crosses the
    outer wire).  The returned ints sum to the
    :func:`wire_bytes_per_device` total, so this is a *breakdown*, not
    a second accounting — the same invariant
    :func:`mpi4torch_tpu.csched.census.program_tier_census` keeps on the
    IR side, which lets the ``--tiers`` lane assert the lowered text's
    table equals the program census exactly."""
    tiers = tuple(int(g) for g in tiers)
    if not tiers:
        raise ValueError("tier_wire_table needs a non-empty tier stack")
    parsed = _parsed(lowered_or_text)
    per = [0.0] * len(tiers)
    for op in parsed.collectives:
        if op.kind != "collective_permute" and op.group_size is None:
            continue
        per[_op_tier(op, tiers)] += wire_contribution(
            op.kind, _payload_bytes(op), op.group_size)
    return [int(round(w)) for w in per]


def weighted_wire_cost(lowered_or_text, tier_bandwidths,
                       tiers=None) -> float:
    """The bandwidth-weighted wire census of a lowered program:
    ``sum(tier_wire[l] / tier_bandwidths[l])`` — relative seconds-on-wire
    under the configured per-tier bandwidths, the ranking functional of
    tier-dimension synthesis (:func:`mpi4torch_tpu.csched.synthesize_tiers`)
    read off the ACTUAL lowering rather than the IR census.  ``tiers``
    defaults to ``config.tier_stack()`` (which must then be set)."""
    from ..csched.census import weighted_cost

    if tiers is None:
        from .. import config as _config

        tiers = _config.tier_stack()
        if tiers is None:
            raise ValueError(
                "weighted_wire_cost needs a tier stack: pass tiers= or "
                "set config.set_tier_stack(...)")
    return weighted_cost(tier_wire_table(lowered_or_text, tiers),
                         tier_bandwidths)


# ----------------------------------------------------------- peak liveness

import re as _re

_DEF_RE = _re.compile(r"^\s*(%[\w.#-]+)(?::\d+)?\s*=")
_ARG_RE = _re.compile(r"(%arg\d+):\s*tensor<([^>]*)>")
_VAL_RE = _re.compile(r"%[\w.#-]+")
_TENSOR_RE = _re.compile(r"tensor<([^>]*)>")


def _result_bytes(line: str) -> int:
    """Byte size of a definition line's result(s): the tensor types
    after ``->`` when the op spells a function type, else the trailing
    type annotation."""
    if "->" in line:
        tail = line.rsplit("->", 1)[1]
    elif ":" in line:
        tail = line.rsplit(":", 1)[1]
    else:
        return 0
    return sum(tensor_bytes(m.group(1))
               for m in _TENSOR_RE.finditer(tail))


def _peak_one(lines) -> int:
    size: Dict[str, int] = {}
    born: Dict[str, int] = {}
    last: Dict[str, int] = {}
    for i, ln in enumerate(lines):
        for m in _ARG_RE.finditer(ln):
            name, desc = m.group(1), m.group(2)
            if name not in size:
                size[name] = tensor_bytes(desc)
                born[name] = i
                last[name] = i
        d = _DEF_RE.match(ln)
        defined = d.group(1) if d else None
        if defined is not None and defined not in size:
            size[defined] = _result_bytes(ln)
            born[defined] = i
        for m in _VAL_RE.finditer(ln):
            name = m.group(0)
            if name in size:
                last[name] = max(last.get(name, i), i)

    events: Dict[int, Tuple[int, int]] = {}
    for name, b in size.items():
        s, e = events.get(born[name], (0, 0))
        events[born[name]] = (s + b, e)
        s, e = events.get(last[name], (0, 0))
        events[last[name]] = (s, e + b)
    live = peak = 0
    for i in sorted(events):
        add, drop = events[i]
        live += add
        peak = max(peak, live)
        live -= drop
    return peak


def peak_live_bytes(lowered_or_text) -> int:
    """Max over program points of the summed byte sizes of live SSA
    values (values live from definition to last textual use, function
    arguments included), censused per ``func.func`` chunk with the
    maximum winning — the ``reshard.peak_live_bytes`` contract on the
    shared parse."""
    parsed = _parsed(lowered_or_text)
    return max([0] + [_peak_one(chunk)
                      for chunk in parsed.function_chunks])


# ------------------------------------------------------ scheduled exposure

def scheduled_exposure(lowered_or_text) -> Dict:
    """Census a lowering for scheduled communication exposure.

    Returns ``{"n_buckets", "n_exposed", "exposed_fraction",
    "buckets"}`` where ``buckets`` maps ``"<Op>.bucket<i>of<n>"`` to
    ``{"split_phase": bool, "exposed": bool}``.  ``exposed_fraction``
    is ``None`` when the program contains no bucket collectives (e.g. a
    single-device world whose collectives lowered away).  The
    ``overlap.scheduled_exposure`` contract, now a pass over the shared
    parse's event stream."""
    parsed = _parsed(lowered_or_text)

    # One bucket_of() evaluation per event (the property regex-searches
    # the scope path on every access).
    by_bucket: Dict[tuple, Dict[str, List[int]]] = {}
    wire: List[tuple] = []
    for ev in parsed.events:
        b = ev.bucket
        if b is not None:
            slot = by_bucket.setdefault(b[:3], {"start": [], "wait": [],
                                                "plain": []})
            slot[b[3] or "plain"].append(ev.line)
        if ev.kind in WIRE_OPS:
            wire.append((ev.line, b[:3] if b is not None else None))

    buckets = {}
    n_exposed = 0
    for key in sorted(by_bucket):
        slot = by_bucket[key]
        split = bool(slot["start"] and slot["wait"])
        if split:
            lo, hi = max(slot["start"]), min(slot["wait"])
            hidden = any(lo < idx < hi and wkey != key
                         for idx, wkey in wire)
            exposed = not hidden
        else:
            # Blocking bucket (or a start that was never waited —
            # defensively exposed): zero-width completion window.
            exposed = True
        n_exposed += exposed
        op, i, n = key
        buckets[f"{op}.bucket{i}of{n}"] = {"split_phase": split,
                                           "exposed": exposed}

    nb = len(buckets)
    return {
        "n_buckets": nb,
        "n_exposed": n_exposed,
        "exposed_fraction": (round(n_exposed / nb, 4) if nb else None),
        "buckets": buckets,
    }

"""`python -m mpi4torch_tpu.analyze` — the analyze-smoke lane.

``--sweep``
    Registry-wide lint sweep (:mod:`.sweep`): every registered
    (algorithm × codec) Allreduce pair (forward + backward, with the
    VJP-symmetry declaration checked), the Bcast_/Reduce_ algorithm
    forms, every reshard strategy, the overlap schedules, and the
    serve decode step, lowered on the attached mesh and run through
    the full soundness lint set — plus the standing registry-sync
    guards.  Exits non-zero on ANY lint violation or registry drift.

``--defects``
    Seeded-defect corpus (:mod:`.defects`): mutated schedules —
    dropped wait, orphan/double wait, duplicated permute target,
    non-partitioning replica group, dropped backward — each of which
    must be caught BY ITS NAMED LINT, with the ledger check that every
    registered lint catches at least one mutant.  Exits non-zero when
    a lint fails to fire (a lint without a firing mutant reads as
    coverage but checks nothing).

The Makefile's ``analyze-smoke`` target runs both on the
8-virtual-device CPU mesh.
"""

from __future__ import annotations

import sys


def _corpus_programs():
    """Build the clean programs the defect corpus mutates, on the
    attached multi-device mesh: a windowed split-phase program, a
    permute-schedule program (bidir's dual ring), a grouped program
    (ring reduce-scatter + all-gather), and a ring forward /
    forward+backward pair."""
    import jax
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from .defects import DefectPrograms
    from .sweep import _flat_lowerer
    from ..overlap import overlap_split_allreduce

    n = len(jax.devices())
    if n < 2:
        raise RuntimeError(
            "the defect corpus mutates multi-device schedules; run via "
            "`make analyze-smoke` (8-virtual-device CPU mesh)")
    lower, comm = _flat_lowerer(n)
    x = jnp.ones((512,), jnp.float32)

    split = lower(lambda c, v: overlap_split_allreduce(
        c, v, mpi.MPI_SUM, nsplits=2), x)
    permute = lower(lambda c, v: c.Allreduce(v, mpi.MPI_SUM,
                                             algorithm="bidir"), x)
    grouped = lower(lambda c, v: c.Reduce_scatter(v, mpi.MPI_SUM, 0),
                    x)
    fwd = lower(lambda c, v: c.Allreduce(v, mpi.MPI_SUM), x)
    fwdbwd = lower(
        lambda c, v: jax.value_and_grad(
            lambda u: jnp.sum(c.Allreduce(u, mpi.MPI_SUM)))(v), x)
    return DefectPrograms(split_phase=split, permute=permute,
                          grouped=grouped, fwd=fwd, fwdbwd=fwdbwd)


def _defects() -> int:
    from .defects import defect_ledger_problems, run_defect_corpus

    records = run_defect_corpus(_corpus_programs())
    failures = 0
    for rec in records:
        ok = rec["clean_ok"] and rec["fired"]
        tag = f"{rec['defect']} -> {rec['lint']}"
        if ok:
            print(f"ok  : {tag}: fired ({rec['doc']})")
        else:
            failures += 1
            print(f"FAIL: {tag}: clean_ok={rec['clean_ok']} "
                  f"fired={rec['fired']}")
    for p in defect_ledger_problems(records):
        failures += 1
        print(f"FAIL[ledger]: {p}")
    print(f"defect corpus: {len(records)} mutants, "
          f"{failures} failure(s)")
    if failures:
        return 1
    print("defect corpus: OK — every lint fires on its mutant")
    return 0


def _sweep() -> int:
    import jax

    from .sweep import run_sweep, sweep_worlds

    ndev = len(jax.devices())
    print(f"analyze-sweep: {ndev} device(s), platform "
          f"{jax.devices()[0].platform}")
    failures = 0
    for world in sweep_worlds(ndev):
        # The serve decode leg compiles real engine steps; once, on
        # the full world, is the meaningful cell.
        res = run_sweep(world, include_serve=(world == (ndev,)))
        for rec in res["records"]:
            if rec["skipped"]:
                print(f"skip: {rec['case']}: {rec['skipped']}")
            elif rec["violations"]:
                failures += len(rec["violations"])
                for v in rec["violations"]:
                    print(f"FAIL: {rec['case']}: {v}")
            else:
                extra = ""
                if "scheduled_exposure" in rec:
                    extra = (" exposure="
                             f"{rec['scheduled_exposure']}")
                census = ",".join(f"{k}={v}"
                                  for k, v in rec["census"].items())
                print(f"ok  : {rec['case']}: "
                      f"[{census or 'no collectives'}]{extra}")
        for p in res["problems"]:
            failures += 1
            print(f"FAIL[registry]: {p}")
        print(f"world {world}: {res['n_cases']} cases linted, "
              f"{res['n_skipped']} skipped, "
              f"{len(res['violations'])} violation(s)")
    if failures:
        print(f"analyze-sweep: {failures} FAILURE(S)")
        return 1
    print("analyze-sweep: OK — every registered schedule lints clean")
    return 0


def main(argv) -> int:
    rc = 0
    ran = False
    if "--sweep" in argv:
        ran = True
        rc |= _sweep()
    if "--defects" in argv:
        ran = True
        rc |= _defects()
    if not ran:
        print(__doc__)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

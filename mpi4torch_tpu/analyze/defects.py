"""Seeded-defect corpus: mutated schedules proving every lint fires.

A lint that never fires is worse than no lint — it reads as coverage.
The resilience subsystem solved the same problem for fault injection
with the fired-fault ledger (a fault cell passes only if its fault
demonstrably acted); this module applies that discipline to static
analysis: for every lint in :data:`mpi4torch_tpu.analyze.LINT_NAMES`
the corpus carries at least one *mutated schedule* — a clean lowered
program with a targeted defect spliced into its text — and
:func:`run_defect_corpus` verifies that

1. the clean program lints clean,
2. the mutant is caught **by the named lint** (not incidentally by
   another), and
3. every registered lint catches at least one mutant (the ledger —
   :func:`defect_ledger_problems`).

The mutations are the static analogues of the runtime failure modes:

* ``dropped-wait`` — a bucket's ``.wait`` span vanishes (the un-waited
  handle that DeadlockError catches at run time);
* ``orphan-wait`` — a wait with no start (a completion for a handle
  nothing issued);
* ``double-wait`` — a bucket's completion collective duplicated (the
  BifurcationError double-Wait);
* ``duplicated-permute-target`` — two sources shipping into one target
  rank (a silently dropped shard);
* ``non-partitioning-group`` — a replica group that lists one rank
  twice and another not at all (a contribution that never merges);
* ``dropped-backward`` — a "value_and_grad" lowering that contains no
  backward collectives (AD transparency silently lost).

Both the ``make analyze-smoke`` lane (``python -m mpi4torch_tpu.analyze
--defects``) and tests/test_analyze.py run this one corpus.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .lints import LINT_NAMES, check_vjp_symmetry, run_lints
from .parse import parse_program

__all__ = [
    "DEFECTS",
    "Defect",
    "DefectPrograms",
    "run_defect_corpus",
    "defect_ledger_problems",
]


@dataclass(frozen=True)
class DefectPrograms:
    """The clean programs the corpus mutates: a windowed split-phase
    program (bucket ``.start``/``.wait`` spans), a permute-schedule
    program (``source_target_pairs`` tables), a grouped-collective
    program (``replica_groups``), and a forward / forward+backward
    lowering pair of one registered algorithm."""
    split_phase: str       # debug_info text with bucket start/wait spans
    permute: str           # text carrying >= 1 collective_permute
    grouped: str           # text carrying >= 1 replica_groups op
    fwd: str               # forward lowering of a registered algorithm
    fwdbwd: str            # value_and_grad lowering of the same program


@dataclass(frozen=True)
class Defect:
    """One seeded defect: which clean program it mutates, the mutation,
    and the lint that must catch it by name."""
    name: str
    lint: str                                  # LINT_NAMES entry
    program: str                               # DefectPrograms field
    doc: str
    mutate: Callable[[str], str]


def _first_bucket(text: str, phase: str) -> Optional[str]:
    """The ``<Op>.bucket<i>of<n>`` label of the first bucket span with
    ``phase`` in ``text``, or None."""
    m = re.search(
        r"mpi4torch\.([A-Za-z_]+\.bucket\d+of\d+)\." + phase, text)
    return m.group(1) if m is not None else None


def _mutate_drop_wait(text: str) -> str:
    """Erase one bucket's ``.wait`` phase suffix — its start span now
    dangles with no completion anywhere in the program."""
    label = _first_bucket(text, "wait")
    if label is None:
        raise ValueError("no split-phase wait span to drop")
    return text.replace(f"{label}.wait", label)


def _mutate_orphan_wait(text: str) -> str:
    """Erase one bucket's ``.start`` phase suffix — its wait span now
    completes a handle nothing issued."""
    label = _first_bucket(text, "start")
    if label is None:
        raise ValueError("no split-phase start span to orphan")
    return text.replace(f"{label}.start", label)


def _mutate_double_wait(text: str) -> str:
    """Duplicate the wire collective of one bucket's wait phase — the
    completion runs twice."""
    parsed = parse_program(text)
    for op in parsed.collectives:
        b = op.bucket
        if b is not None and b[3] == "wait":
            lines = parsed.lines
            lines = lines[:op.line + 1] + [lines[op.line]] \
                + lines[op.line + 1:]
            return "\n".join(lines)
    raise ValueError("no wait-phase wire collective to duplicate")


def _mutate_duplicate_permute_target(text: str) -> str:
    """Point two sources at one target rank in the first permute's
    ``source_target_pairs`` table."""
    m = re.search(
        r"source_target_pairs = dense<\[\[(-?\d+), (-?\d+)\], "
        r"\[(-?\d+), (-?\d+)\]", text)
    if m is None:
        raise ValueError("no >= 2-pair source_target_pairs to mutate")
    old = m.group(0)
    new = (f"source_target_pairs = dense<[[{m.group(1)}, {m.group(2)}], "
           f"[{m.group(3)}, {m.group(2)}]")
    return text.replace(old, new, 1)


def _mutate_non_partitioning_group(text: str) -> str:
    """Make the first replica-group table list one rank twice and drop
    another: the duplicated rank reduces twice, the dropped rank's
    contribution never merges."""
    m = re.search(r"replica_groups = dense<\[\[(-?\d+), (-?\d+)",
                  text)
    if m is None:
        raise ValueError("no >= 2-wide replica_groups to mutate")
    old = m.group(0)
    new = f"replica_groups = dense<[[{m.group(1)}, {m.group(1)}"
    return text.replace(old, new, 1)


DEFECTS: Dict[str, Defect] = {}


def _register(defect: Defect) -> Defect:
    DEFECTS[defect.name] = defect
    return defect


_register(Defect(
    name="dropped-wait", lint="split-phase", program="split_phase",
    doc="a split-phase bucket's wait span erased (un-waited handle)",
    mutate=_mutate_drop_wait))
_register(Defect(
    name="orphan-wait", lint="split-phase", program="split_phase",
    doc="a split-phase bucket's start span erased (wait without start)",
    mutate=_mutate_orphan_wait))
_register(Defect(
    name="double-wait", lint="split-phase", program="split_phase",
    doc="a bucket's completion collective duplicated (double Wait)",
    mutate=_mutate_double_wait))
_register(Defect(
    name="duplicated-permute-target", lint="permute-pairs",
    program="permute",
    doc="two sources shipping into one target rank",
    mutate=_mutate_duplicate_permute_target))
_register(Defect(
    name="non-partitioning-group", lint="replica-groups",
    program="grouped",
    doc="a replica group listing one rank twice, another not at all",
    mutate=_mutate_non_partitioning_group))
_register(Defect(
    name="dropped-backward", lint="vjp-symmetry", program="fwdbwd",
    doc="a value_and_grad lowering with the backward collectives gone",
    mutate=lambda text: text))  # special-cased: fwd stands in for fwdbwd


def run_defect_corpus(programs: DefectPrograms) -> List[dict]:
    """Apply every seeded defect and record whether its named lint
    fired.  Each record: ``{"defect", "lint", "clean_ok", "fired",
    "violations"}`` — a corpus cell passes only when the clean program
    lints clean AND the mutant is caught by the expected lint name."""
    records: List[dict] = []
    for name in sorted(DEFECTS):
        d = DEFECTS[name]
        clean = getattr(programs, d.program)
        if d.lint == "vjp-symmetry":
            # The mutant pair: forward census present, backward absent —
            # fwd standing in for the value_and_grad lowering.
            clean_v = check_vjp_symmetry(programs.fwd, programs.fwdbwd)
            viols = check_vjp_symmetry(programs.fwd, d.mutate(
                programs.fwd), context=name)
        else:
            clean_v = [v for v in run_lints(clean) if v.lint == d.lint]
            viols = run_lints(d.mutate(clean))
        fired = any(v.lint == d.lint for v in viols)
        records.append({
            "defect": name,
            "lint": d.lint,
            "doc": d.doc,
            "clean_ok": not clean_v,
            "fired": fired,
            "violations": [str(v) for v in viols],
        })
    return records


def defect_ledger_problems(records=None) -> List[str]:
    """The fired-defect ledger: every registered lint must be the named
    catcher of at least one corpus defect (a lint without a defect
    proving it fires is effectively untested), and — when ``records``
    from :func:`run_defect_corpus` are given — every defect must have
    fired on a clean baseline."""
    problems: List[str] = []
    covered = {d.lint for d in DEFECTS.values()}
    missing = sorted(set(LINT_NAMES) - covered)
    if missing:
        problems.append(
            f"lint(s) {missing} have no seeded defect in the corpus — "
            "a lint without a mutant proving it fires is effectively "
            "untested")
    unknown = sorted(covered - set(LINT_NAMES))
    if unknown:
        problems.append(
            f"defect(s) name unregistered lint(s) {unknown} — extend "
            "analyze.LINT_NAMES")
    for rec in records or []:
        if not rec["clean_ok"]:
            problems.append(
                f"{rec['defect']}: the CLEAN program already violates "
                f"{rec['lint']} — the corpus baseline is broken")
        if not rec["fired"]:
            problems.append(
                f"{rec['defect']}: lint {rec['lint']} did not fire on "
                "the mutated schedule")
    return problems

"""One home for the registry-sync guards.

Since PR 4 every subsystem that grew a registry also grew a guard
asserting registry == coverage — algorithms vs census matrices
(tests/test_tune.py), split-phase forms vs facade methods
(tests/test_overlap.py), fault kinds vs the fault matrix
(resilience), reshard step kinds vs both executors and the sweep
(reshard), serving policies vs the parity matrix (serve) — each as its
own copy of the same set-comparison shape.  This module dedupes them:
:func:`set_drift` is the shared core (compare two name sets, return
the caller's exact message on drift — the historical failure messages
are preserved verbatim), and one ``*_problems`` function per domain
rebuilds each guard on it.  The smoke lanes and the test files call
these; ``python -m mpi4torch_tpu.analyze --sweep`` additionally runs
every argument-free domain guard, so registry drift anywhere fails the
analyze lane too.

The coverage literals that pin what the *test matrices* cover (ALGOS,
CENSUS_COVERED, SPLIT_CENSUS_COVERED, PARITY_POLICIES, ...) stay in
the test/smoke files that own those matrices — a guard's job is to
force the literal and the registry to move together, which only works
if the literal lives next to the matrix it describes.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

__all__ = [
    "set_drift",
    "resilience_problems",
    "elastic_problems",
    "degrade_problems",
    "reshard_step_problems",
    "serve_policy_problems",
    "serve_paging_problems",
    "tune_problems",
    "overlap_split_phase_problems",
    "csched_problems",
    "tier_program_problems",
    "transport_problems",
    "ctl_problems",
    "standing_problems",
]


def set_drift(registered: Iterable, covered: Iterable,
              message: str) -> List[str]:
    """The shared core of every registry-sync guard: ``[message]`` when
    the two name sets differ, else ``[]``.  ``message`` may reference
    ``{registered}`` and ``{covered}`` (each formatted as the sorted
    list) so callers keep their historical failure texts."""
    r, c = set(registered), set(covered)
    if r == c:
        return []
    return [message.format(registered=sorted(r), covered=sorted(c))]


# ------------------------------------------------------------- resilience

def resilience_problems() -> List[str]:
    """Fault-kind registry vs the censused matrix coverage (the body of
    the historical ``resilience.__main__._check_registry_sync``, moved
    here; messages unchanged)."""
    from ..resilience.faults import FAULT_KINDS
    from ..resilience.matrix import COMM_SUBSYSTEMS, COVERAGE

    problems = set_drift(
        FAULT_KINDS, COVERAGE,
        "registry/coverage drift: registered={registered} "
        "covered={covered} — every fault kind needs a "
        "matrix row and vice versa")
    for kind, rows in COVERAGE.items():
        if kind not in FAULT_KINDS:
            continue
        sites = FAULT_KINDS[kind].sites
        if "checkpoint" in sites:
            if "checkpoint" not in rows:
                problems.append(f"{kind}: checkpoint-site kind without a "
                                "checkpoint cell")
        else:
            missing = set(COMM_SUBSYSTEMS) - set(rows)
            if missing:
                problems.append(f"{kind}: no cell for subsystem(s) "
                                f"{sorted(missing)}")
        if rows and all(v == "inert" for v in rows.values()):
            problems.append(f"{kind}: inert in EVERY subsystem — the "
                            "kind is effectively untested")
    return problems


# ---------------------------------------------------------------- elastic

def elastic_problems() -> List[str]:
    """Elastic matrix coverage vs its declared dimensions, and the
    bridge into the resilience registry: every failure kind the elastic
    matrix composes must itself be a registered fault kind with a plain
    fault-matrix row (the preempt satellite's guard)."""
    from ..elastic.matrix import (ACTIONS, CONSENSUS_COVERAGE, COVERAGE,
                                  EXPECTED_CONSENSUS_ERROR, KINDS,
                                  SUBSYSTEMS)
    from ..resilience.faults import FAULT_KINDS
    from ..resilience.matrix import COVERAGE as FAULT_COVERAGE

    declared = {(k, s, a) for k in KINDS for s in SUBSYSTEMS
                for a in ACTIONS}
    problems = set_drift(
        declared, set(COVERAGE),
        "elastic coverage drift: declared cells {registered} vs "
        "COVERAGE table {covered} — every (kind x subsystem x action) "
        "needs a cell and vice versa")
    for kind in KINDS:
        if kind not in FAULT_KINDS:
            problems.append(
                f"elastic kind {kind!r} is not a registered fault kind "
                "— register it (resilience.faults) so the injection "
                "grammar covers it")
        elif kind not in FAULT_COVERAGE:
            problems.append(
                f"elastic kind {kind!r} has no plain fault-matrix row — "
                "the resilience matrix must pin its unhandled "
                "(raise) behavior before the elastic matrix composes "
                "its handled one")
    problems += set_drift(
        CONSENSUS_COVERAGE, {(k, "membership", "consensus")
                             for k in EXPECTED_CONSENSUS_ERROR},
        "consensus-cell drift: coverage {registered} vs expected-error "
        "table {covered}")
    bad = [v for v in list(COVERAGE.values())
           + list(CONSENSUS_COVERAGE.values())
           if v not in ("recover", "raise")]
    if bad:
        problems.append(f"unknown elastic cell outcomes {sorted(set(bad))}")
    return problems


# ---------------------------------------------------------------- degrade

def degrade_problems() -> List[str]:
    """Gray-failure registry sync (ISSUE 15): the chaos matrix's
    coverage table vs the gray fault kinds (each of which must also be
    a registered fault kind WITH a plain fault-matrix row — the
    resilience matrix pins the transient behavior before the chaos
    matrix composes detection/degrade on top), and the degrade-policy
    registry vs the chaos matrix's degrade cells — a policy without a
    cell, or a covered cell whose policy is unregistered, fails
    ``make chaos-smoke`` AND ``make analyze-smoke``."""
    from ..resilience.chaos import (CHAOS_COVERAGE, CHAOS_SUBSYSTEMS,
                                    DEGRADE_COVERED, GRAY_KINDS)
    from ..resilience.degrade import DEGRADE_POLICIES
    from ..resilience.faults import FAULT_KINDS
    from ..resilience.matrix import COVERAGE as FAULT_COVERAGE

    problems = set_drift(
        GRAY_KINDS, CHAOS_COVERAGE,
        "gray-kind/chaos-coverage drift: kinds={registered} "
        "covered={covered} — every gray kind needs a chaos row and "
        "vice versa")
    for kind in GRAY_KINDS:
        if kind not in FAULT_KINDS:
            problems.append(
                f"gray kind {kind!r} is not a registered fault kind — "
                "register it (resilience.faults) so the injection "
                "grammar covers it")
        elif kind not in FAULT_COVERAGE:
            problems.append(
                f"gray kind {kind!r} has no plain fault-matrix row — "
                "the resilience matrix must pin its transient behavior "
                "before the chaos matrix composes the gray one")
        missing = set(CHAOS_SUBSYSTEMS) - set(CHAOS_COVERAGE.get(kind,
                                                                 {}))
        if missing:
            problems.append(f"{kind}: no chaos cell for subsystem(s) "
                            f"{sorted(missing)}")
    problems += set_drift(
        DEGRADE_POLICIES, set(DEGRADE_COVERED.values()),
        "degrade-policy registry {registered} != chaos-covered "
        "policies {covered} — every registered policy needs a degrade "
        "cell exercising it (DEGRADE_COVERED) and vice versa")
    for (kind, subsystem), policy in DEGRADE_COVERED.items():
        if CHAOS_COVERAGE.get(kind, {}).get(subsystem) != "degrade":
            problems.append(
                f"DEGRADE_COVERED names ({kind} x {subsystem}) for "
                f"policy {policy!r}, but the chaos coverage table does "
                "not declare that cell 'degrade'")
    bad = sorted({v for rows in CHAOS_COVERAGE.values()
                  for v in rows.values()
                  if v not in ("recover", "degrade", "escalate",
                               "inert")})
    if bad:
        problems.append(f"unknown chaos cell outcomes {bad}")
    return problems


# ---------------------------------------------------------------- reshard

def reshard_step_problems(exercised: Optional[Set[str]] = None
                          ) -> List[str]:
    """Step-kind registry vs both executor dispatch tables, plus —
    when the sweep passes the step kinds its forward+adjoint plans
    actually exercised — sweep coverage (messages from the historical
    reshard-smoke guard)."""
    from ..reshard import STEP_KINDS
    from ..reshard.executor import _EAGER_EXEC, _SPMD_EXEC

    kinds = set(STEP_KINDS)
    probs: List[str] = []
    if set(_SPMD_EXEC) != kinds:
        probs.append(f"SPMD executor serves {sorted(_SPMD_EXEC)}")
    if set(_EAGER_EXEC) != kinds:
        probs.append(f"eager executor serves {sorted(_EAGER_EXEC)}")
    if exercised is not None and set(exercised) != kinds:
        probs.append(
            f"sweep exercised {sorted(exercised)} of {sorted(kinds)}")
    return probs


# ------------------------------------------------------------------ serve

def serve_policy_problems(parity_policies: Iterable) -> List[str]:
    """Scheduling-policy registry vs the parity-covered set the
    engine-vs-oracle matrix enumerates (message from the historical
    serve-smoke guard)."""
    from ..serve import POLICIES

    return set_drift(
        POLICIES, parity_policies,
        "policy registry {registered} != parity-covered set {covered} "
        "— every scheduling policy needs oracle-parity coverage")


def serve_paging_problems() -> List[str]:
    """Paged-serving registry-sync guards (ISSUE 17): every scheduling
    policy must also hold engine-vs-oracle parity UNDER BLOCK CHURN
    (the paged matrix literal published by the serve-smoke lane), and
    every ServeStats counter must be mirrored into the
    ``mpi4torch_serve_*`` obs metrics surface (the mirror literal the
    smoke lane asserts against ``prometheus_text()``) — a new counter
    cannot ship unmirrored, a new policy cannot ship without paged
    parity coverage."""
    from ..serve import POLICIES
    from ..serve.__main__ import (MIRRORED_SERVE_COUNTERS,
                                  PAGED_PARITY_POLICIES)
    from ..utils.profiling import ServeStats

    problems = set_drift(
        POLICIES, PAGED_PARITY_POLICIES,
        "policy registry {registered} != paged-parity covered set "
        "{covered} — every scheduling policy needs oracle-parity "
        "coverage under block churn too")
    problems += set_drift(
        ServeStats._COUNTERS, MIRRORED_SERVE_COUNTERS,
        "ServeStats counters {registered} != obs-mirrored set "
        "{covered} — every serve counter must surface as an "
        "mpi4torch_serve_* metric (serve/__main__.py smoke asserts "
        "the exposition)")
    return problems


# ------------------------------------------------------------------- tune

def tune_problems(algos: Iterable, census_covered: Iterable,
                  codec_capable: Iterable) -> List[str]:
    """Algorithm registry vs the parity/census matrices and the
    codec-capability cross-declarations (messages from the historical
    tests/test_tune.py guard)."""
    from .. import tune
    from ..compress import available_codecs, get_codec

    registered = set(tune.available_algorithms())
    problems = set_drift(
        registered, algos,
        "registered algorithms {registered} out of sync with "
        "the parity/grads test matrix {covered} — extend "
        "ALGOS (and the tests it parametrizes)")
    problems += set_drift(
        registered, census_covered,
        "registered algorithms {registered} out of sync with "
        "the HLO census matrix {covered} — add a "
        "forward+backward census test and list the name in "
        "CENSUS_COVERED")
    capable = {a for a in registered
               if tune.get_algorithm(a).codec_capable}
    problems += set_drift(
        capable, codec_capable,
        "codec-capable algorithms {registered} out of sync with "
        "CODEC_CAPABLE {covered} — extend the literal "
        "(and check TestCodecAlgorithmCensus covers the new schedule)")
    for name in available_codecs():
        declared = set(get_codec(name).algorithms)
        if not declared <= capable:
            problems.append(
                f"codec {name!r} declares algorithms {sorted(declared)} "
                "outside the registry's codec_capable set — either mark "
                "the algorithm codec_capable (and census the pair) or "
                "fix the codec's declaration")
        if not declared:
            problems.append(
                f"codec {name!r} declares no algorithms — "
                "even exact-wire fallbacks need 'ring'")
    return problems


# ---------------------------------------------------------------- overlap

def overlap_split_phase_problems(census_covered: Iterable) -> List[str]:
    """Split-phase form registry vs the facade's ``*_start`` surface
    and the census matrix (messages from the historical
    tests/test_overlap.py guard)."""
    from ..comm import MPI_Communicator
    from ..overlap import SPLIT_PHASE_FORMS

    registered = set(SPLIT_PHASE_FORMS)
    facade_starts = {m[:-len("_start")] for m in dir(MPI_Communicator)
                     if m.endswith("_start") and not m.startswith("_")}
    problems = set_drift(
        facade_starts, registered,
        "facade *_start methods {registered} out of sync "
        "with overlap.SPLIT_PHASE_FORMS {covered}")
    problems += set_drift(
        registered, census_covered,
        "registered split-phase forms {registered} out of sync "
        "with the census matrix {covered} — add a "
        "start-precedes-compute census test and list the form")
    return problems


# ----------------------------------------------------------------- csched

def csched_problems() -> List[str]:
    """Schedule-IR registry sync (ISSUE 14): every registered collective
    algorithm either declares an IR program (csched.PROGRAM_ALGORITHMS)
    or an explicit native exemption, and every IR step kind is covered
    by the lowering, interpreter, transposition AND census dispatch
    tables — so extending the grammar without extending a table, or
    registering an algorithm outside the IR, fails ``make
    analyze-smoke`` (and ``make ir-smoke``) structurally."""
    from .. import csched, tune

    problems: List[str] = []
    registered = set(tune.available_algorithms())
    declared = set(csched.PROGRAM_ALGORITHMS) | set(csched.NATIVE_EXEMPT)
    missing = sorted(registered - declared)
    if missing:
        problems.append(
            f"algorithm(s) {missing} registered without an IR program "
            "or a csched.NATIVE_EXEMPT entry — every schedule must "
            "re-express through the IR or be exempted explicitly")
    stale = sorted(declared - registered)
    if stale:
        problems.append(
            f"csched declares program(s)/exemption(s) {stale} for "
            "algorithms the tune registry no longer knows")
    kinds = set(csched.STEP_KINDS)
    for table, covered in (
            ("lowering", csched.lowering_covers()),
            ("interpreter", csched.interpreter_covers()),
            ("transposition", csched.transposition_covers()),
            ("census", csched.census_covers())):
        problems += set_drift(
            kinds, covered,
            "IR step-kind registry {registered} out of sync with the "
            + table + " dispatch table {covered} — every step kind "
            "needs " + table + " coverage")
    return problems


def tier_program_problems() -> List[str]:
    """Tier-composition registry sync (ISSUE 18): every per-tier
    (algorithm x codec) composition the tier synthesis searches
    (``csched.TIER_COMPOSITIONS``) must hold a Mode A/B parity cell AND
    a per-tier census cell in the ``--tiers`` lane's coverage literals
    (``csched.__main__.TIER_PARITY_COVERED`` /
    ``TIER_CENSUS_COVERED``), and must transpose to a program with the
    forward's census (the declared ``"self"`` VJP every allreduce
    schedule ships) — so a new composition cannot enter the search
    space without bitwise and census evidence, structurally."""
    from .. import csched

    problems = set_drift(
        csched.TIER_COMPOSITIONS,
        _tier_lane_literals()[0],
        "tier compositions {registered} out of sync with the --tiers "
        "lane's parity matrix {covered} — every searched composition "
        "needs a Mode A/B bitwise parity cell (TIER_PARITY_COVERED)")
    problems += set_drift(
        csched.TIER_COMPOSITIONS,
        _tier_lane_literals()[1],
        "tier compositions {registered} out of sync with the --tiers "
        "lane's census matrix {covered} — every searched composition "
        "needs a per-tier census cell (TIER_CENSUS_COVERED)")
    tiers = (2, 2, 2)
    for comp in csched.TIER_COMPOSITIONS:
        prog = csched.fold_program(8, tiers, tiers)
        if comp == "q8-slow":
            prog = csched.rewrite_fold_codec(prog, (len(tiers) - 1,))
        fwd = csched.program_tier_census(prog, 1024, 4, tiers)
        bwd = csched.program_tier_census(csched.transpose(prog), 1024, 4,
                                         tiers)
        if fwd != bwd:
            problems.append(
                f"tier composition {comp!r} does not transpose to its "
                f"own per-tier census (fwd {fwd} vs bwd {bwd}) — the "
                "declared 'self' VJP no longer holds")
    return problems


def _tier_lane_literals():
    from ..csched.__main__ import (TIER_CENSUS_COVERED,
                                   TIER_PARITY_COVERED)

    return TIER_PARITY_COVERED, TIER_CENSUS_COVERED


# -------------------------------------------------------------- transport

def transport_problems() -> List[str]:
    """Transport registry sync (ISSUE 16): every backend registered in
    ``transport.TRANSPORTS`` must be in the transport-smoke lane's
    bitwise parity matrix (``transport.__main__.TESTED_BACKENDS``) —
    merging a third backend without parity coverage fails ``make
    transport-smoke`` AND ``make analyze-smoke`` structurally."""
    from ..transport import TRANSPORTS
    from ..transport.__main__ import TESTED_BACKENDS

    return set_drift(
        set(TRANSPORTS), set(TESTED_BACKENDS),
        "transport registry {registered} out of sync with the "
        "smoke-tested backend set {covered} — every registered "
        "backend must pass the bitwise parity matrix")


# -------------------------------------------------------------------- ctl

def ctl_problems() -> List[str]:
    """Self-tuning controller registry sync (ISSUE 19): the decision
    ledger's trigger vocabulary (``ctl.ledger.TRIGGER_KINDS``), the
    ctl-smoke lane's coverage literal (``ctl.__main__.LEDGER_COVERED``)
    and the degrade-policy delegation map
    (``ctl.controller.POLICY_TRIGGER``) must move together — a new
    trigger kind cannot ship without a smoke cell that records it, and
    a new degrade policy cannot ship outside the controller's ONE
    switching mechanism (every DEGRADE_POLICIES entry must delegate to
    a registered trigger)."""
    from ..ctl.__main__ import LEDGER_COVERED
    from ..ctl.controller import POLICY_TRIGGER
    from ..ctl.ledger import TRIGGER_KINDS
    from ..resilience.degrade import DEGRADE_POLICIES

    problems = set_drift(
        TRIGGER_KINDS, LEDGER_COVERED,
        "ledger trigger kinds {registered} out of sync with the "
        "ctl-smoke coverage literal {covered} — every trigger kind "
        "needs a smoke cell that records a ledgered switch")
    problems += set_drift(
        DEGRADE_POLICIES, POLICY_TRIGGER,
        "degrade-policy registry {registered} out of sync with the "
        "controller's delegation map {covered} — every policy must "
        "route through the controller's ratified switch "
        "(ctl.controller.POLICY_TRIGGER)")
    stray = sorted(set(POLICY_TRIGGER.values()) - set(TRIGGER_KINDS))
    if stray:
        problems.append(
            f"POLICY_TRIGGER delegates to unregistered trigger "
            f"kind(s) {stray} — the ledger would refuse the record")
    return problems


# ------------------------------------------------------------- everything

def standing_problems() -> List[str]:
    """Every registry-sync guard that needs no caller-side coverage
    literal (the test-matrix literals live with their matrices): the
    resilience fault matrix, the reshard executor tables, and the
    serve parity set published by its smoke lane.  The analyze sweep
    runs this, so a drift in ANY subsystem registry fails the
    ``make analyze-smoke`` lane too."""
    problems = [f"resilience: {p}" for p in resilience_problems()]
    problems += [f"elastic: {p}" for p in elastic_problems()]
    problems += [f"degrade: {p}" for p in degrade_problems()]
    problems += [f"reshard: {p}" for p in reshard_step_problems()]
    problems += [f"csched: {p}" for p in csched_problems()]
    problems += [f"csched: {p}" for p in tier_program_problems()]
    problems += [f"transport: {p}" for p in transport_problems()]
    problems += [f"ctl: {p}" for p in ctl_problems()]
    from ..serve.__main__ import PARITY_POLICIES
    problems += [f"serve: {p}"
                 for p in serve_policy_problems(PARITY_POLICIES)]
    problems += [f"serve: {p}" for p in serve_paging_problems()]
    return problems

"""The censused elastic matrix: every (failure kind × subsystem ×
action) cell ends **recovered-and-bitwise against the fresh-start
oracle on the new world** or in a typed, rank-attributed raise — never
a hang, never an unfired cell.

The PR 7 discipline applied to world resizing.  ONE implementation
shared by tests/test_elastic.py (fast subset tier-1, full matrix on the
``slow`` lane) and ``make elastic-smoke`` (:mod:`.__main__`);
:data:`COVERAGE` is the literal table the registry-sync guard
(``analyze.registry.elastic_problems``) cross-checks against the fault
registry and the declared subsystem/action sets.

Dimensions:

* **failure kind** — ``rank_death`` (no notice: recovery rewinds to
  the epoch-stamped phase-boundary checkpoint for the lost shard) and
  ``preempt`` (advance notice: the doomed rank answers through the
  drain, so recovery is the LIVE resize replan — no rewind).
* **subsystem** — ``plain`` (an axis-0-sharded TP-style parameter
  bank), ``zero`` (ZeRO-1 training: replicated params + sharded
  elementwise-momentum state through the real ``zero_step`` bucketed
  collectives), ``moe`` (an expert stack, with
  ``rebalance_experts`` re-dealing composed on the new world), and
  ``serve`` (a continuous-batching engine whose in-flight requests
  drain to tickets and re-admit through the admission POLICIES).
* **action** — ``shrink`` ((8,)→(6,); serve (4,)→(2,)), ``grow``
  (shrink then grow back — the round-trip), and ``spare`` (a hot-spare
  world: zero-reshard takeover from the mirror for plain/zero; moe and
  serve have no mirror and take the DOCUMENTED fallback — the planned
  drain path — with ``fallback: true`` recorded in the verdict).

Bitwise discipline: every training cell uses integer-valued
(dyadic-exact) data and SUM reduction, so the same global math is
exact under any world size and any fold association — the oracle is a
plain numpy replay of the schedule, and "recovered" means every new
world position's state equals the oracle's slice BIT FOR BIT.

The consensus cells (:func:`run_consensus_cell`) pin the failure side
of membership agreement itself: an injected proposal disagreement ends
in :class:`~.membership.ConsensusError` naming the disagreeing id, and
a rank dying MID-consensus ends in the runtime's attributed
``RankFailedError`` — typed raises both, never hangs.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..runtime import RankFailedError
from ..resilience.faults import FaultSpec, fault_scope
from .membership import ConsensusError, WorldView, agree_world_view
from .runtime import ElasticRuntime

__all__ = [
    "KINDS", "SUBSYSTEMS", "ACTIONS", "COVERAGE",
    "CONSENSUS_COVERAGE", "EXPECTED_CONSENSUS_ERROR", "SPARE_FALLBACK",
    "coverage_cells", "run_cell", "run_consensus_cell",
]

KINDS = ("rank_death", "preempt")
SUBSYSTEMS = ("plain", "zero", "moe", "serve")
ACTIONS = ("shrink", "grow", "spare")

# Subsystems whose `spare` action has no mirror and takes the
# documented fallback (the planned drain path) instead of takeover.
SPARE_FALLBACK = frozenset({"moe", "serve"})

# Every (kind x subsystem x action) cell recovers; the registry-sync
# guard fails CI if this literal and the dimension tuples drift apart.
COVERAGE: Dict[Tuple[str, str, str], str] = {
    (k, s, a): "recover"
    for k in KINDS for s in SUBSYSTEMS for a in ACTIONS
}

CONSENSUS_COVERAGE: Dict[Tuple[str, str, str], str] = {
    ("disagree", "membership", "consensus"): "raise",
    ("second_failure", "membership", "consensus"): "raise",
}

EXPECTED_CONSENSUS_ERROR = {
    "disagree": ConsensusError,
    "second_failure": RankFailedError,
}

# Cell timing: probes on worlds with absent ranks burn exactly the
# probe timeout; world timeouts bound every other wait.
PROBE_TIMEOUT_S = 0.6
WORLD_TIMEOUT_S = 20.0

# Tensor-subsystem geometry: 24 leading units re-dealt 8 -> 6 -> 8
# (spare worlds: 4 data + 1 spare, width 4 throughout).  One failure
# takes the world to 7 survivors, but 24 units have no 7-way deal — so
# the ratified view descales to the largest USABLE mesh (6,) by also
# draining a surplus rank (_EXTRA), the real-world mesh-divisibility
# decision an elastic scheduler makes.
_W, _M = 8, 6
_UNITS = 24
_DOOMED = 2          # the stable id that fails in shrink/grow cells
_EXTRA = 7           # the surplus id drained to reach the (6,) mesh
_SPARE_DATA = 4
_SPARE_DOOMED = 1


def coverage_cells():
    """Every declared cell, deterministic order (what the smoke lane
    iterates and the registry guard cross-checks)."""
    for key in sorted(COVERAGE):
        yield key
    for key in sorted(CONSENSUS_COVERAGE):
        yield key


def _rt(n: int) -> ElasticRuntime:
    return ElasticRuntime(n, probe_timeout=PROBE_TIMEOUT_S,
                          world_timeout=WORLD_TIMEOUT_S)


def _delta(t: int, rid: int, shape) -> np.ndarray:
    """Deterministic small-integer contribution of stable id ``rid``
    at step ``t`` — dyadic-exact under SUM on any membership."""
    n = int(np.prod(shape))
    base = (np.arange(n, dtype=np.int64) * (rid + 2) + (t + 1) * 7) % 9
    return (base - 4).astype(np.float32).reshape(shape)


def _sum_delta(t: int, ids, shape) -> np.ndarray:
    out = np.zeros(shape, np.float32)
    for rid in ids:
        out += _delta(t, rid, shape)
    return out


class _verdict:
    """Accumulates one cell's verdict record."""

    def __init__(self, kind, subsystem, action, expected):
        self.rec = {"kind": kind, "subsystem": subsystem,
                    "action": action, "expected": expected,
                    "fired": []}

    def fail(self, detail):
        self.rec.update(status="fail", detail=detail)
        return self.rec

    def ok(self, detail):
        self.rec.update(status="ok", detail=detail)
        return self.rec


def _spec_for(kind: str, rank: int, op, index: int) -> FaultSpec:
    if kind == "preempt":
        # A wide window: the notice posts at `index`, the death op sits
        # far past everything the drain will ever issue.
        return FaultSpec("preempt", rank=rank, op=op, index=index,
                         count=100_000)
    return FaultSpec("rank_death", rank=rank, op=op, index=index)


# ---------------------------------------------------------------------------
# plain / moe: an axis-0-sharded bank updated by summed deltas.
# ---------------------------------------------------------------------------


def _bank_body(shards_by_id, ts, row):
    """Phase body: each rank updates its axis-0 shard of the bank from
    the SUM of the membership's per-id integer deltas."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi

    def body(pos, rid):
        comm = mpi.COMM_WORLD
        size = comm.size
        per = _UNITS // size
        shard = jnp.asarray(shards_by_id[rid])
        for t in ts:
            d = comm.Allreduce(
                jnp.asarray(_delta(t, rid, (_UNITS,) + row)),
                mpi.MPI_SUM, compression=False)
            shard = shard + d[pos * per:(pos + 1) * per]
        return np.asarray(shard)

    return body


def _bank_oracle(bank0, schedule):
    """Numpy replay: ``schedule`` is a list of (ts, alive_ids)."""
    bank = np.array(bank0, copy=True)
    for ts, ids in schedule:
        for t in ts:
            bank += _sum_delta(t, ids, bank.shape)
    return bank


def _run_bank_cell(v, kind: str, action: str, *, moe: bool):
    """The plain/moe shrink+grow driver (spare handled separately)."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from . import replan as _replan

    row = (3,)
    bank0 = np.arange(_UNITS * 3, dtype=np.float32).reshape(
        _UNITS, 3)
    rt = _rt(_W)
    view0 = rt.view
    ids0 = view0.alive
    shards = {rid: bank0[rid * 3:(rid + 1) * 3] for rid in ids0}

    ts1, ts2, ts3 = (0, 1), (2,), (3,)
    # Phase 1 issues len(ts1) Allreduce calls per rank; the fault lands
    # on the first op after the boundary (rank_death) or posts its
    # notice during phase 1 (preempt).
    spec = _spec_for(kind, _DOOMED, "Allreduce",
                     index=(1 if kind == "preempt" else len(ts1)))
    with fault_scope([spec]) as plan:
        res1 = rt.run_phase(_bank_body(shards, ts1, row))
        shards = {ids0[p]: res1[p] for p in range(_W)}
        snapshot = _bank_oracle(bank0, [(ts1, ids0)])
        if not all(np.array_equal(shards[rid],
                                  snapshot[view0.position(rid) * 3:
                                           (view0.position(rid) + 1) * 3])
                   for rid in ids0):
            return v.fail("phase-1 state diverged from the replay "
                          "before any fault acted")

        if kind == "preempt":
            notices = rt.pending_preemptions()
            if _DOOMED not in notices:
                return v.fail("no preemption notice posted "
                              f"(board: {notices})")

            def drain_body(pos, rid, old_view, new_view):
                x = jnp.asarray(shards[rid])
                out = _replan.replan_axis0(
                    mpi.COMM_WORLD, x, _UNITS, old_view, new_view,
                    mode="drain")
                return np.asarray(out)

            outs = rt.drain(drain_body, leaving=[_DOOMED, _EXTRA])
            view1 = rt.view
            new_shards = {rid: outs[view0.position(rid)]
                          for rid in view1.alive}
        else:
            try:
                rt.run_phase(_bank_body(shards, ts2, row))
                return v.fail("rank_death never fired — the phase "
                              "completed")
            except RankFailedError as e:
                if _DOOMED not in e.ranks:
                    return v.fail(
                        f"RankFailedError unattributed: {sorted(e.ranks)}")
            view1 = rt.consensus(leaving=[_EXTRA])
            # Checkpoint rewind: the phase-boundary snapshot supplies
            # every new-world shard (the dead rank's memory is gone;
            # survivors rewind to the common point).
            per1 = _UNITS // view1.size
            new_shards = {
                rid: snapshot[view1.position(rid) * per1:
                              (view1.position(rid) + 1) * per1]
                for rid in view1.alive}
    v.rec["fired"] = sorted(plan.fired_kinds())
    if kind not in plan.fired_kinds():
        return v.fail("vacuous cell: the fault never fired")
    if view1.size != _M or _DOOMED in view1.alive or view1.epoch != 1:
        return v.fail(f"unexpected post-shrink view: {view1.describe()}")

    # Resume on the shrunk world (replaying ts2 after a rank_death
    # rewind; running it fresh after a drain — either way the schedule
    # below is what the oracle replays).
    resume_ts = ts2
    res2 = rt.run_phase(_bank_body(new_shards, resume_ts, row))
    new_shards = {view1.alive[p]: res2[p] for p in range(view1.size)}
    schedule = [(ts1, ids0), (resume_ts, view1.alive)]

    if action == "grow":
        view_pre = view1
        view2 = rt.consensus(joining=[_DOOMED, _EXTRA])
        if view2.size != _W or view2.epoch != 2:
            return v.fail(f"grow view wrong: {view2.describe()}")

        def grow_body(pos, rid, old=view_pre, new=view2):
            comm = mpi.COMM_WORLD
            per_old = _UNITS // old.size
            if rid in old.alive:
                x = jnp.asarray(new_shards[rid])
            else:
                x = jnp.zeros((per_old,) + row, jnp.float32)
            out = _replan.replan_axis0(comm, x, _UNITS, old, new,
                                       mode="grow")
            shard = np.asarray(out)
            per = _UNITS // new.size
            for t in ts3:
                d = comm.Allreduce(
                    jnp.asarray(_delta(t, rid, (_UNITS,) + row)),
                    mpi.MPI_SUM, compression=False)
                shard = shard + np.asarray(d)[pos * per:(pos + 1) * per]
            return shard

        res3 = rt.run_phase(lambda pos, rid: grow_body(pos, rid))
        final = {view2.alive[p]: res3[p] for p in range(view2.size)}
        schedule.append((ts3, view2.alive))
        view_final = view2
    else:
        final, view_final = new_shards, view1

    oracle = _bank_oracle(bank0, schedule)

    if moe:
        # Compose the MoE re-deal on the final world: experts sorted by
        # a deterministic load vector, snake-dealt, moved by the
        # planned block permutation (reshard.plan_permutation under
        # rebalance_experts).
        from ..parallel.moe import balanced_assignment, rebalance_experts

        loads = [(e * 7) % 11 for e in range(_UNITS)]
        perm = balanced_assignment(loads, view_final.size)

        def reb_body(pos, rid):
            out = rebalance_experts(
                mpi.COMM_WORLD, {"w": jnp.asarray(final[rid])}, perm)
            return np.asarray(out["w"])

        res4 = rt.run_phase(reb_body)
        final = {view_final.alive[p]: res4[p]
                 for p in range(view_final.size)}
        oracle = oracle[list(perm)]

    per = _UNITS // view_final.size
    for rid in view_final.alive:
        j = view_final.position(rid)
        if not np.array_equal(final[rid], oracle[j * per:(j + 1) * per]):
            return v.fail(
                f"recovered state of id {rid} (position {j}) diverges "
                "from the fresh-start oracle")
    return v.ok(
        f"recovered bitwise on {view_final.describe()} "
        f"({'live drain' if kind == 'preempt' else 'checkpoint rewind'}"
        f"{' + rebalance' if moe else ''})")


# ---------------------------------------------------------------------------
# zero: ZeRO-1 steps (replicated params, sharded momentum) end to end.
# ---------------------------------------------------------------------------


class _Momentum:
    """Minimal elementwise optax-style momentum (dyadic coefficients:
    exact on integer gradients for the few steps a cell runs)."""

    def init(self, params):
        import jax
        import jax.numpy as jnp

        return jax.tree.map(jnp.zeros_like, params)

    def update(self, grads, state, params=None):
        import jax
        import jax.numpy as jnp

        m = jax.tree.map(lambda mm, gg: mm * 0.5 + gg, state, grads)
        return jax.tree.map(lambda mm: mm * (-0.25), m), m


_ZSHAPES = {"w": (12, 5), "b": (8,)}


def _zero_grads(t, rid):
    return {k: _delta(t, rid, s) for k, s in _ZSHAPES.items()}


def _zero_oracle(schedule):
    """Replicated numpy replay of the ZeRO schedule; returns
    (params, momentum) as full arrays."""
    params = {k: np.arange(int(np.prod(s)), dtype=np.float32)
              .reshape(s) for k, s in _ZSHAPES.items()}
    m = {k: np.zeros(s, np.float32) for k, s in _ZSHAPES.items()}
    for ts, ids in schedule:
        for t in ts:
            for k in _ZSHAPES:
                g = _sum_delta(t, ids, _ZSHAPES[k])
                m[k] = m[k] * 0.5 + g
                params[k] = params[k] + m[k] * (-0.25)
    return params, m


def _np_shard(full: np.ndarray, size: int, pos: int) -> np.ndarray:
    flat = full.reshape(-1)
    per = -(-flat.size // size)
    padded = np.pad(flat, (0, per * size - flat.size))
    return padded[pos * per:(pos + 1) * per]


def _zero_body(params_in, states_by_id, ts):
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from ..parallel.zero import zero_step

    opt = _Momentum()

    def body(pos, rid):
        comm = mpi.COMM_WORLD
        p = {k: jnp.asarray(v) for k, v in params_in.items()}
        st = states_by_id[rid]
        for t in ts:
            p, st = zero_step(comm, opt, p,
                              {k: jnp.asarray(v) for k, v in
                               _zero_grads(t, rid).items()},
                              st, mean=False)
        return ({k: np.asarray(v) for k, v in p.items()},
                {k: np.asarray(v) for k, v in st.items()})

    return body


def _phase1_op_count(params0, init_states, view0, ts1) -> int:
    """The deterministic per-rank wire-op count of phase 1, measured
    once on a throwaway world under a never-firing counting spec
    (``_matching`` advances per-rank counters for every matching call
    regardless of the firing window) — so a rank_death lands exactly on
    phase 2's FIRST collective without hard-coding bucket counts."""
    probe_spec = FaultSpec("delay", rank=None, op=None, index=10 ** 6)
    with fault_scope([probe_spec]) as probe_plan:
        _rt(_W).run_phase(_zero_body(params0, init_states(view0), ts1))
        return max(probe_plan._counts.get((0, r), 0)
                   for r in range(_W))


def _run_zero_cell(v, kind: str, action: str, workdir: Optional[str]):
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from . import replan as _replan

    rt = _rt(_W)
    view0 = rt.view
    ids0 = view0.alive
    params0 = {k: np.arange(int(np.prod(s)), dtype=np.float32)
               .reshape(s) for k, s in _ZSHAPES.items()}

    def init_states(view):
        return {rid: {k: jnp.zeros(
            (-(-int(np.prod(_ZSHAPES[k])) // view.size),),
            jnp.float32) for k in _ZSHAPES}
            for rid in view.alive}

    states = init_states(view0)
    ts1, ts2 = (0, 1), (2,)
    if kind == "preempt":
        scope_spec = FaultSpec("preempt", rank=_DOOMED, op=None,
                               index=2, count=100_000)
    else:
        scope_spec = FaultSpec(
            "rank_death", rank=_DOOMED, op=None,
            index=_phase1_op_count(params0, init_states, view0, ts1))

    with fault_scope([scope_spec]) as plan:
        res1 = rt.run_phase(_zero_body(params0, states, ts1))
        params1 = res1[0][0]
        if any(not all(np.array_equal(res1[p][0][k], params1[k])
                       for k in _ZSHAPES) for p in range(_W)):
            return v.fail("phase-1 replicated params diverged "
                          "across ranks")
        states = {ids0[p]: {k: jnp.asarray(res1[p][1][k])
                            for k in _ZSHAPES} for p in range(_W)}
        m1_full = {k: np.concatenate(
            [np.asarray(states[rid][k]) for rid in ids0])
            for k in _ZSHAPES}

        if kind == "preempt":
            notices = rt.pending_preemptions()
            if _DOOMED not in notices:
                return v.fail(f"no preemption notice (board {notices})")

            def drain_body(pos, rid, old_view, new_view):
                out = _replan.replan_zero(
                    mpi.COMM_WORLD, states[rid],
                    params0, old_view, new_view, mode="drain")
                return {k: np.asarray(x) for k, x in out.items()}

            outs = rt.drain(drain_body, leaving=[_DOOMED, _EXTRA])
            view1 = rt.view
            new_states = {
                rid: {k: jnp.asarray(outs[view0.position(rid)][k])
                      for k in _ZSHAPES}
                for rid in view1.alive}
        else:
            try:
                rt.run_phase(_zero_body(params1, states, ts2))
                return v.fail("rank_death never fired")
            except RankFailedError as e:
                if _DOOMED not in e.ranks:
                    return v.fail(
                        f"RankFailedError unattributed: {sorted(e.ranks)}")
            view1 = rt.consensus(leaving=[_EXTRA])
            # The real checkpoint leg: the phase-boundary state was
            # saved with the epoch stamp; a stale-epoch resume must
            # raise, then the deliberate restore re-lays the momentum.
            from ..runtime import CommError
            from ..utils.checkpoint import CheckpointManager

            full_state = {"params": params1, "m": m1_full}
            with CheckpointManager(workdir) as mgr:
                mgr.save(0, full_state, force=True, epoch=0)
                mgr.wait_until_finished()
                try:
                    mgr.restore(0, template=full_state,
                                expect_epoch=view1.epoch)
                    return v.fail("stale-epoch restore did NOT raise")
                except CommError as e:
                    if "epoch 0" not in str(e):
                        return v.fail(
                            f"epoch fence names no epochs: {e}")
                restored = mgr.restore(0, template=full_state,
                                       expect_epoch=0)
            new_states = {
                rid: {k: jnp.asarray(_np_shard_from_flatcat(
                    restored["m"][k], view0.size, view1.size,
                    view1.position(rid), _ZSHAPES[k]))
                    for k in _ZSHAPES}
                for rid in view1.alive}
            params1 = restored["params"]
    v.rec["fired"] = sorted(plan.fired_kinds())
    if kind not in plan.fired_kinds():
        return v.fail("vacuous cell: the fault never fired")
    if view1.size != _M or view1.epoch != 1:
        return v.fail(f"unexpected post-shrink view: {view1.describe()}")

    res2 = rt.run_phase(_zero_body(params1, new_states, ts2))
    params2 = res2[0][0]
    new_states = {view1.alive[p]: {k: jnp.asarray(res2[p][1][k])
                                   for k in _ZSHAPES}
                  for p in range(view1.size)}
    schedule = [(ts1, ids0), (ts2, view1.alive)]
    view_final, params_final, states_final = view1, params2, new_states

    if action in ("grow",):
        view_pre = view1
        view2 = rt.consensus(joining=[_DOOMED, _EXTRA])

        def grow_body(pos, rid, old=view_pre, new=view2):
            comm = mpi.COMM_WORLD
            if rid in old.alive:
                st = states_final[rid]
            else:
                st = {k: jnp.zeros(
                    (-(-int(np.prod(_ZSHAPES[k])) // old.size),),
                    jnp.float32) for k in _ZSHAPES}
            out = _replan.replan_zero(comm, st, params0, old, new,
                                      mode="grow")
            return {k: np.asarray(x) for k, x in out.items()}

        res3 = rt.run_phase(lambda pos, rid: grow_body(pos, rid))
        states_grown = {view2.alive[p]: {k: jnp.asarray(res3[p][k])
                                         for k in _ZSHAPES}
                        for p in range(view2.size)}
        ts3 = (3,)
        res4 = rt.run_phase(_zero_body(params_final, states_grown, ts3))
        params_final = res4[0][0]
        states_final = {view2.alive[p]: {k: jnp.asarray(res4[p][1][k])
                                         for k in _ZSHAPES}
                        for p in range(view2.size)}
        schedule.append((ts3, view2.alive))
        view_final = view2

    o_params, o_m = _zero_oracle(schedule)
    for k in _ZSHAPES:
        if not np.array_equal(params_final[k], o_params[k]):
            return v.fail(f"params[{k}] diverge from the oracle")
    for rid in view_final.alive:
        j = view_final.position(rid)
        for k in _ZSHAPES:
            want = _np_shard(o_m[k], view_final.size, j)
            if not np.array_equal(np.asarray(states_final[rid][k]),
                                  want):
                return v.fail(
                    f"momentum shard [{k}] of id {rid} diverges from "
                    "the fresh-start oracle")
    return v.ok(
        f"recovered bitwise on {view_final.describe()} "
        f"({'live replan' if kind == 'preempt' else 'epoch-stamped checkpoint rewind'})")


def _np_shard_from_flatcat(full_flatcat: np.ndarray, old_size: int,
                           new_size: int, pos: int, shape) -> np.ndarray:
    """New-world momentum shard from the checkpointed FLAT-CONCAT form
    (the old world's padded per-rank segments back to back): unpad to
    the logical vector, re-pad for the new world, slice."""
    n = int(np.prod(shape))
    per_old = -(-n // old_size)
    logical = np.concatenate([
        full_flatcat[r * per_old:(r + 1) * per_old]
        for r in range(old_size)])[:n]
    per_new = -(-n // new_size)
    padded = np.pad(logical, (0, per_new * new_size - n))
    return padded[pos * per_new:(pos + 1) * per_new]


# ---------------------------------------------------------------------------
# spare: hot-spare worlds (4 data + 1 spare), zero-reshard takeover.
# ---------------------------------------------------------------------------


def _run_spare_cell(v, kind: str, subsystem: str):
    """True takeover for plain/zero; moe/serve fall back to the planned
    drain path (recorded) via their shrink drivers."""
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from . import spare as _spare

    n_data = _SPARE_DATA
    world = n_data + 1
    spare_id = n_data
    doomed = _SPARE_DOOMED
    rt = _rt(world)
    view0 = rt.view
    slots0 = {rid: (rid if rid < n_data else None)
              for rid in view0.alive}

    if subsystem == "plain":
        bank0 = np.arange(_UNITS * 3, dtype=np.float32).reshape(
            _UNITS, 3)

        def mk_state(rid):
            slot = slots0[rid]
            if slot is None:
                return bank0
            per = _UNITS // n_data
            return bank0[slot * per:(slot + 1) * per]

        def bank_body(states, slots, ts):
            def body(pos, rid):
                comm = mpi.COMM_WORLD
                slot = slots[rid]
                st = jnp.asarray(states[rid])
                for t in ts:
                    contrib = (_delta(t, slot, bank0.shape)
                               if slot is not None
                               else np.zeros(bank0.shape, np.float32))
                    st = _spare.bank_spare_step(
                        comm, st, jnp.asarray(contrib),
                        n_data=n_data, slot=slot)
                return np.asarray(st)
            return body

        states = {rid: mk_state(rid) for rid in view0.alive}
        ts1, ts2 = (0, 1), (2, 3)
        spec = _spec_for(kind, doomed, "Allreduce",
                         index=(1 if kind == "preempt" else len(ts1)))
        with fault_scope([spec]) as plan:
            res1 = rt.run_phase(bank_body(states, slots0, ts1))
            states = {view0.alive[p]: res1[p] for p in range(world)}
            if kind == "preempt":
                if doomed not in rt.pending_preemptions():
                    return v.fail("no preemption notice")
                view1 = rt.consensus(leaving=[doomed])
            else:
                try:
                    rt.run_phase(bank_body(states, slots0, ts2))
                    return v.fail("rank_death never fired")
                except RankFailedError as e:
                    if doomed not in e.ranks:
                        return v.fail(
                            f"unattributed: {sorted(e.ranks)}")
                view1 = rt.consensus()
        v.rec["fired"] = sorted(plan.fired_kinds())
        if kind not in plan.fired_kinds():
            return v.fail("vacuous cell: the fault never fired")
        if set(view1.alive) != {0, 2, 3, spare_id}:
            return v.fail(f"post-failure view wrong: {view1.describe()}")

        # Zero-reshard takeover: the spare assumes the doomed slot by a
        # LOCAL slice of its mirror; survivors keep their shards as-is.
        slots1 = {rid: slots0[rid] for rid in view1.alive
                  if rid != spare_id}
        slots1[spare_id] = slots0[doomed]
        states1 = {rid: states[rid] for rid in view1.alive
                   if rid != spare_id}
        states1[spare_id] = np.asarray(_spare.takeover_bank_slot(
            jnp.asarray(states[spare_id]), slots0[doomed], n_data))

        res2 = rt.run_phase(bank_body(states1, slots1, ts2))
        final = {view1.alive[p]: res2[p] for p in range(view1.size)}

        oracle = _bank_oracle(bank0, [(ts1 + ts2, range(n_data))])
        per = _UNITS // n_data
        for rid in view1.alive:
            slot = slots1[rid]
            want = oracle[slot * per:(slot + 1) * per]
            if not np.array_equal(final[rid], want):
                return v.fail(
                    f"slot {slot} (id {rid}) diverges after takeover")
        return v.ok("zero-reshard takeover bitwise (spare id "
                    f"{spare_id} assumed slot {slots0[doomed]})")

    # subsystem == "zero": the mirrored ZeRO step.
    opt = _Momentum()
    params0 = {k: np.arange(int(np.prod(s)), dtype=np.float32)
               .reshape(s) for k, s in _ZSHAPES.items()}

    def init_state(rid):
        slot = slots0[rid]
        return _spare.zero_spare_init(
            opt, {k: jnp.asarray(v_) for k, v_ in params0.items()},
            n_data, slot)

    def zero_body(params_in, states, slots, view, ts):
        pos_slots = tuple(slots[view.alive[p]]
                          for p in range(view.size))

        def body(pos, rid):
            comm = mpi.COMM_WORLD
            slot = slots[rid]
            p = {k: jnp.asarray(v_) for k, v_ in params_in.items()}
            st = states[rid]
            for t in ts:
                grads = ({k: jnp.asarray(v_) for k, v_ in
                          _zero_grads(t, slot).items()}
                         if slot is not None else
                         {k: jnp.zeros(s, jnp.float32)
                          for k, s in _ZSHAPES.items()})
                p, st = _spare.zero_spare_step(
                    comm, opt, p, grads, st, n_data=n_data, slot=slot,
                    slots=pos_slots)
            return ({k: np.asarray(v_) for k, v_ in p.items()}, st)
        return body

    states = {rid: init_state(rid) for rid in view0.alive}
    ts1, ts2 = (0, 1), (2,)
    per_step_ops = len(_ZSHAPES) * 2   # one allreduce + one allgather per leaf
    spec = _spec_for(kind, doomed, None,
                     index=(1 if kind == "preempt"
                            else len(ts1) * per_step_ops))
    with fault_scope([spec]) as plan:
        res1 = rt.run_phase(zero_body(params0, states, slots0,
                                      view0, ts1))
        params1 = res1[0][0]
        states = {view0.alive[p]: res1[p][1] for p in range(world)}
        if kind == "preempt":
            if doomed not in rt.pending_preemptions():
                return v.fail("no preemption notice")
            view1 = rt.consensus(leaving=[doomed])
        else:
            try:
                rt.run_phase(zero_body(params1, states, slots0,
                                       view0, ts2))
                return v.fail("rank_death never fired")
            except RankFailedError as e:
                if doomed not in e.ranks:
                    return v.fail(f"unattributed: {sorted(e.ranks)}")
            view1 = rt.consensus()
    v.rec["fired"] = sorted(plan.fired_kinds())
    if kind not in plan.fired_kinds():
        return v.fail("vacuous cell: the fault never fired")

    slots1 = {rid: slots0[rid] for rid in view1.alive
              if rid != spare_id}
    slots1[spare_id] = slots0[doomed]
    states1 = {rid: states[rid] for rid in view1.alive
               if rid != spare_id}
    states1[spare_id] = _spare.takeover_shard(
        states[spare_id], slots0[doomed], n_data,
        {k: jnp.asarray(v_) for k, v_ in params0.items()})

    res2 = rt.run_phase(zero_body(params1, states1, slots1,
                                  view1, ts2))
    params_final = res2[0][0]
    states_final = {view1.alive[p]: res2[p][1]
                    for p in range(view1.size)}
    o_params, o_m = _zero_oracle([(ts1 + ts2, range(n_data))])
    for k in _ZSHAPES:
        if not np.array_equal(params_final[k], o_params[k]):
            return v.fail(f"params[{k}] diverge after takeover")
    for rid in view1.alive:
        slot = slots1[rid]
        for k in _ZSHAPES:
            want = _np_shard(o_m[k], n_data, slot)
            if not np.array_equal(np.asarray(states_final[rid][k]),
                                  want):
                return v.fail(
                    f"momentum shard [{k}] of slot {slot} diverges "
                    "after takeover")
    return v.ok("zero-reshard takeover bitwise (mirrored optimizer "
                f"slices; spare id {spare_id} assumed slot "
                f"{slots0[doomed]})")


# ---------------------------------------------------------------------------
# serve: drain in-flight requests, re-admit on the new world.
# ---------------------------------------------------------------------------


_SERVE_W, _SERVE_M = 4, 2
_SERVE_DOOMED = 1
_SERVE_EXTRA = 3     # surplus id drained so the TP head deal fits (2,)


def _serve_cfg():
    from ..models.transformer import TransformerConfig

    return TransformerConfig(vocab=31, d_model=8, n_heads=4, n_layers=1,
                             d_ff=16, max_seq=32)


_SERVE_PROMPTS = ([3, 4, 5], [6, 7], [8, 9, 10, 11])
_SERVE_BUDGETS = (6, 5, 4)


def _serve_params(cfg):
    import jax

    from ..models.transformer import init_transformer

    return init_transformer(jax.random.PRNGKey(7), cfg)


def _serve_oracle(cfg, params):
    import jax.numpy as jnp

    from ..models.transformer import generate

    out = {}
    for i, (p, n) in enumerate(zip(_SERVE_PROMPTS, _SERVE_BUDGETS)):
        seq = generate(cfg, params,
                       jnp.asarray(p, jnp.int32)[None, :], n,
                       dtype=params["embed"].dtype)
        out[i] = np.asarray(seq[0])
    return out


def _serve_phase(params, cfg, tickets, steps):
    """Phase body: build an engine, (re-)admit, run ``steps`` steps,
    ledger a snapshot after every one (the survivor-held drain source a
    mid-step death needs)."""
    from ..serve import Engine, ServeConfig
    from . import replan as _replan

    ledger = {}

    def body(pos, rid):
        eng = Engine(cfg, params, ServeConfig(slots=2))
        if tickets is None:
            for i, (p, n) in enumerate(zip(_SERVE_PROMPTS,
                                           _SERVE_BUDGETS)):
                eng.submit(np.asarray(p), rid=i, max_new=n)
        else:
            _replan.readmit(eng, tickets)
        # Ledger the post-admission state BEFORE the first step: a
        # death inside step 1 must still leave the survivors a
        # re-admission source (zero progress is a valid drain point).
        ledger[pos] = (eng.snapshot_inflight(), dict(eng.results()))
        done = 0
        while eng.pending() and (steps is None or done < steps):
            eng.step()
            done += 1
            ledger[pos] = (eng.snapshot_inflight(),
                           dict(eng.results()))
        return (eng.snapshot_inflight(), eng.results())

    return body, ledger


def _run_serve_cell(v, kind: str, action: str):
    import mpi4torch_tpu as mpi  # noqa: F401 — engines resolve COMM_WORLD
    from . import replan as _replan

    cfg = _serve_cfg()
    params = _serve_params(cfg)
    oracle = _serve_oracle(cfg, params)
    rt = _rt(_SERVE_W)
    view0 = rt.view

    if kind == "preempt":
        spec = _spec_for("preempt", _SERVE_DOOMED, None, index=2)
    else:
        # Measure phase 1's deterministic per-rank op count on a
        # throwaway world so the death reliably lands MID-phase-1
        # (an overshooting literal index would fire in a later,
        # smaller world against an innocent position).
        probe_spec = FaultSpec("delay", rank=None, op=None,
                               index=10 ** 6)
        with fault_scope([probe_spec]) as probe_plan:
            b, _ = _serve_phase(params, cfg, None, steps=3)
            _rt(_SERVE_W).run_phase(b)
            n_ops = max(probe_plan._counts.get((0, r), 0)
                        for r in range(_SERVE_W))
        spec = _spec_for("rank_death", _SERVE_DOOMED, None,
                         index=max(1, n_ops // 2))
    body1, ledger1 = _serve_phase(params, cfg, None, steps=3)
    with fault_scope([spec]) as plan:
        if kind == "preempt":
            res1 = rt.run_phase(body1)
            snap, res_done = res1[0]
            if _SERVE_DOOMED not in rt.pending_preemptions():
                return v.fail("no preemption notice")
            view1 = rt.consensus(
                leaving=[_SERVE_DOOMED, _SERVE_EXTRA])
        else:
            try:
                rt.run_phase(body1)
                return v.fail("rank_death never fired mid-serving")
            except RankFailedError as e:
                if _SERVE_DOOMED not in e.ranks:
                    return v.fail(f"unattributed: {sorted(e.ranks)}")
            survivor = next(p for p in range(_SERVE_W)
                            if p != _SERVE_DOOMED and p in ledger1)
            snap, res_done = ledger1[survivor]
            view1 = rt.consensus(leaving=[_SERVE_EXTRA])
    v.rec["fired"] = sorted(plan.fired_kinds())
    if kind not in plan.fired_kinds():
        return v.fail("vacuous cell: the fault never fired")
    if view1.size != _SERVE_M:
        return v.fail(f"post-shrink view wrong: {view1.describe()}")

    tickets = [_replan.ServeTicket(rid=r["rid"], prompt=r["prompt"],
                                   emitted=list(r["emitted"]),
                                   max_new=r["max_new"], key=r["key"])
               for r in snap]
    results = dict(res_done)

    if action == "grow":
        body2, _ = _serve_phase(params, cfg, tickets, steps=2)
        res2 = rt.run_phase(body2)
        snap2, res2_done = res2[0]
        results.update(res2_done)
        tickets = [_replan.ServeTicket(rid=r["rid"], prompt=r["prompt"],
                                       emitted=list(r["emitted"]),
                                       max_new=r["max_new"],
                                       key=r["key"]) for r in snap2]
        rt.consensus(joining=[_SERVE_DOOMED, _SERVE_EXTRA])

    body3, _ = _serve_phase(params, cfg, tickets, steps=None)
    res3 = rt.run_phase(body3)
    _snap3, res3_done = res3[0]
    results.update(res3_done)

    stitched = _replan.stitched_results(results, tickets)
    for i in oracle:
        got = stitched.get(i)
        if got is None:
            return v.fail(f"request {i} never finished after the resize")
        if not np.array_equal(np.asarray(got, np.int64),
                              np.asarray(oracle[i], np.int64)):
            return v.fail(
                f"request {i}'s stitched tokens diverge from the "
                "per-request generate() oracle")
    return v.ok(
        f"in-flight requests drained and re-admitted on "
        f"{rt.view.describe()}; all token streams bitwise vs "
        "generate()")


# ---------------------------------------------------------------------------
# cell dispatch + consensus cells
# ---------------------------------------------------------------------------


def run_cell(kind: str, subsystem: str, action: str,
             workdir: Optional[str] = None) -> dict:
    """Run one elastic matrix cell; returns the verdict record
    (``status`` ok/fail, ``detail``, the fired-fault ledger, and
    ``fallback`` for the mirror-less spare subsystems).  ``workdir``
    (a scratch directory) is required by the cells that exercise the
    real epoch-stamped checkpoint leg (zero × rank_death)."""
    expected = COVERAGE.get((kind, subsystem, action))
    v = _verdict(kind, subsystem, action, expected)
    if expected is None:
        return v.fail("no COVERAGE row — the registry-sync guard "
                      "should have caught this")
    try:
        if action == "spare" and subsystem in SPARE_FALLBACK:
            v.rec["fallback"] = True
            if subsystem == "serve":
                return _run_serve_cell(v, kind, "shrink")
            return _run_bank_cell(v, kind, "shrink", moe=True)
        if action == "spare":
            return _run_spare_cell(v, kind, subsystem)
        if subsystem == "plain":
            return _run_bank_cell(v, kind, action, moe=False)
        if subsystem == "moe":
            return _run_bank_cell(v, kind, action, moe=True)
        if subsystem == "zero":
            import tempfile

            if workdir is not None or kind != "rank_death":
                return _run_zero_cell(v, kind, action, workdir)
            with tempfile.TemporaryDirectory() as d:
                return _run_zero_cell(v, kind, action, d)
        if subsystem == "serve":
            return _run_serve_cell(v, kind, action)
        return v.fail(f"unknown subsystem {subsystem!r}")
    except Exception as e:  # noqa: BLE001 — a cell must never hang the lane
        return v.fail(f"unexpected {type(e).__name__}: {str(e)[:300]}")


def run_consensus_cell(kind: str) -> dict:
    """The membership-failure cells: consensus must END — in a typed,
    rank-attributed raise — when a participant disagrees or dies
    mid-round."""
    import mpi4torch_tpu as mpi

    expected = EXPECTED_CONSENSUS_ERROR[kind]
    v = _verdict(kind, "membership", "consensus", "raise")
    rt = _rt(4)
    view = rt.view

    if kind == "disagree":
        def body(pos):
            def propose(p):
                if pos == 2:
                    return WorldView(p.epoch, p.alive,
                                     (2, len(p.alive) // 2))
                return p
            return agree_world_view(view, probe_timeout=PROBE_TIMEOUT_S,
                                    _propose=propose)

        try:
            mpi.run_ranks(body, 4, timeout=WORLD_TIMEOUT_S)
            return v.fail("disagreement went undetected")
        except ConsensusError as e:
            if 2 not in e.ranks:
                return v.fail(f"ConsensusError unattributed: "
                              f"{sorted(e.ranks)}")
            return v.ok(f"ConsensusError naming id(s) {sorted(e.ranks)}")
        except Exception as e:  # noqa: BLE001
            return v.fail(f"expected ConsensusError, got "
                          f"{type(e).__name__}: {e}")

    # second_failure: rank 3 passes the probe, then dies on its very
    # first consensus p2p (the proposal send) — the coordinator's recv
    # must surface the attributed RankFailedError, not hang.
    spec = FaultSpec("rank_death", rank=3, op="p2p", index=0)
    with fault_scope([spec]) as plan:
        def body(pos):
            return agree_world_view(view, probe_timeout=PROBE_TIMEOUT_S)

        try:
            mpi.run_ranks(body, 4, timeout=WORLD_TIMEOUT_S)
            rec = v.fail("second failure went undetected")
        except RankFailedError as e:
            if 3 not in e.ranks:
                rec = v.fail(f"RankFailedError unattributed: "
                             f"{sorted(e.ranks)}")
            else:
                rec = v.ok("mid-consensus death raised RankFailedError "
                           f"naming rank(s) {sorted(e.ranks)}")
        except Exception as e:  # noqa: BLE001
            rec = v.fail(f"expected {expected.__name__}, got "
                         f"{type(e).__name__}: {e}")
    v.rec["fired"] = sorted(plan.fired_kinds())
    if rec["status"] == "ok" and "rank_death" not in plan.fired_kinds():
        return v.fail("vacuous cell: the mid-consensus death never "
                      "fired")
    return rec

"""Hot-spare ranks: replicated state slices for zero-reshard takeover.

A spare is a world member that computes no gradients but RIDES the
training step's existing collectives, keeping a full replica of the
sharded state current at zero extra wire bytes — on the Mode B
rendezvous every collective already delivers each rank the material it
needs (an ``Allreduce`` hands every member the full fold), so the
spare's mirror is pure local post-processing of wire traffic the data
ranks were exchanging anyway.  When a data rank dies, the spare
promotes into its deal slot by SLICING its mirror — no reshard plan, no
wire, no checkpoint rewind: the zero-reshard takeover the elastic
matrix's ``spare`` cells certify bitwise.  When no spare is available,
recovery falls back to the planned resharding of :mod:`.replan` (and,
for a no-notice death, the epoch-stamped checkpoint rewind) — the
documented fallback the matrix also exercises.

Conventions:

* a spare world has ``n_data`` data ranks at positions ``0..n_data-1``
  and the spares ABOVE them (positions ``n_data..``) — the deal width
  is ``n_data``, decoupled from the world size, which is what makes
  same-width takeover possible at all;
* data ranks keep shard-sized state (``slot = position``); spares keep
  the full mirror (``slot = None``) — the spare pays replicated-state
  memory, which is its job;
* spares contribute ZEROS to the gradient collectives, so they are
  arithmetically invisible under SUM reduction (the elastic bitwise
  discipline) while completing every rendezvous.

On Mode A the same recipe costs real wire (an all-reduce where a
reduce-scatter would do); the mirror is a Mode B / host-runtime
feature by design — production spares would pin HBM replicas the same
way, trading memory and wire for instant takeover.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..constants import MPI_SUM
from .membership import ElasticError

__all__ = [
    "is_spare",
    "zero_spare_init",
    "zero_spare_step",
    "takeover_shard",
    "bank_spare_step",
    "takeover_bank_slot",
]


def is_spare(position: int, n_data: int) -> bool:
    return position >= n_data


def _flat_pad(x, n_data: int):
    import jax.numpy as jnp

    flat = jnp.asarray(x).reshape(-1)
    per = -(-flat.shape[0] // n_data)
    return jnp.pad(flat, (0, per * n_data - flat.shape[0])), per


def _seg(flat, per: int, slot: int):
    return flat[slot * per:(slot + 1) * per]


def zero_spare_init(opt, params, n_data: int, slot: Optional[int]):
    """Optimizer state for a spare-capable ZeRO world: data rank
    ``slot`` inits on its ``1/n_data`` flat segment, a spare
    (``slot=None``) on the FULL padded flat view — elementwise
    optimizers make the mirror's segment ``s`` bitwise identical to
    data rank ``s``'s state forever after."""
    import jax

    def view(p):
        flat, per = _flat_pad(p, n_data)
        return flat if slot is None else _seg(flat, per, slot)

    return opt.init(jax.tree.map(view, params))


def zero_spare_step(comm, opt, params, local_grads, opt_state, *,
                    n_data: int, slot: Optional[int], slots=None):
    """One spare-capable ZeRO step; every world member (data ranks AND
    spares) calls it collectively.  Returns ``(new_params,
    new_opt_state)`` — parameters fully replicated (as in ZeRO-1), the
    optimizer state shard-sized on data ranks and full on spares.

    ``slot`` is THIS rank's data deal slot (``None`` for a mirror);
    ``slots`` maps every world position to its slot (``None`` entries
    for spares) — required once takeover has permuted slots relative
    to world positions (a promoted spare carries the dead rank's slot
    from whatever position its stable id sorts to); the default is the
    identity convention (position ``p`` < ``n_data`` serves slot
    ``p``, spares above).

    Wire per step: ONE summed gradient all-reduce (spares contribute
    zeros — invisible under SUM) + ONE segment all-gather of the
    updated parameters (spares contribute an inert zeros segment,
    discarded by slot bookkeeping).  On the rendezvous backend that is
    the same wire the plain ZeRO-1 step pays; the spare's full-gradient
    view is local post-processing of the first collective — the
    piggyback."""
    import jax
    import jax.numpy as jnp

    size = comm.size
    if not (0 < n_data <= size):
        raise ElasticError(
            f"n_data must be in 1..world size ({size}); got {n_data}")
    if slot is not None and not (0 <= slot < n_data):
        raise ElasticError(
            f"data slot must be in 0..{n_data - 1}; got {slot}")
    if slots is None:
        slots = tuple(p if p < n_data else None for p in range(size))
    slots = tuple(slots)
    if len(slots) != size or sorted(
            s for s in slots if s is not None) != list(range(n_data)):
        raise ElasticError(
            f"slots must map the {size} world positions onto data "
            f"slots 0..{n_data - 1} (spares None); got {slots}")
    pos_of_slot = {s: p for p, s in enumerate(slots) if s is not None}

    # Wire 1: the full global gradient on every member.  compression
    # explicitly off — the mirror must hold the exact bits the owners
    # hold.
    g_full = jax.tree.map(
        lambda g: comm.Allreduce(jnp.asarray(g), MPI_SUM,
                                 compression=False),
        local_grads)

    def view(x):
        flat, per = _flat_pad(x, n_data)
        return flat if slot is None else _seg(flat, per, slot)

    p_view = jax.tree.map(view, params)
    g_view = jax.tree.map(view, g_full)
    pers = jax.tree.map(lambda p: _flat_pad(p, n_data)[1], params)
    updates, new_state = opt.update(g_view, opt_state, p_view)
    p_view = jax.tree.map(jnp.add, p_view, updates)

    # Wire 2: segment all-gather back to full replicated parameters.
    # Every member contributes a segment-shaped buffer (spares: zeros,
    # sliced away by position below), so the collective signature is
    # uniform across the world.
    def gather_leaf(pv, per, tmpl):
        contrib = pv if slot is not None else jnp.zeros((per,), pv.dtype)
        full = comm.Allgather(contrib, 0, compression=False)
        # Reassemble in SLOT order, not position order: takeover may
        # have permuted who serves which slot.
        flat = jnp.concatenate([
            full[pos_of_slot[s] * per:(pos_of_slot[s] + 1) * per]
            for s in range(n_data)])
        n = int(np.prod(np.shape(tmpl))) if np.shape(tmpl) else 1
        return flat[:n].reshape(np.shape(tmpl))

    # The gathered copy is the source of truth for everyone — on a
    # spare it is bitwise the segments of its own full update (same
    # elements through the same elementwise ops; the matrix's spare
    # cells pin that), so data ranks and mirrors replicate identically.
    new_params = jax.tree.map(gather_leaf, p_view, pers, params)
    return new_params, new_state


def takeover_shard(full_state, slot: int, n_data: int, template):
    """Zero-reshard takeover: slice data slot ``slot``'s shard out of a
    spare's FULL mirror state — the promoted spare's state in the new
    world, bitwise what the dead rank held.  ``template`` gives each
    leaf's global shape (the same convention as
    :func:`.replan.replan_zero`)."""
    import jax

    def one(full_flat, tmpl):
        n = int(np.prod(np.shape(tmpl))) if np.shape(tmpl) else 1
        per = -(-n // n_data)
        return _seg(full_flat, per, slot)

    return jax.tree.map(one, full_state, template)


# ---------------------------------------------------------------------------
# Dense / TP bank mirror: the same discipline for axis-0-sharded state.
# ---------------------------------------------------------------------------


def bank_spare_step(comm, bank, delta, *, n_data: int,
                    slot: Optional[int]):
    """One update of an axis-0-sharded parameter bank with a spare
    mirror: every member contributes its (zero-padded, full-shaped)
    ``delta`` to ONE summed all-reduce; data rank ``slot`` applies its
    axis-0 slice, a spare applies the whole thing to its full replica.
    Returns the updated shard (data) or full bank (spare)."""
    import jax.numpy as jnp

    d = comm.Allreduce(jnp.asarray(delta), MPI_SUM, compression=False)
    if slot is None:
        return jnp.asarray(bank) + d
    n_units = d.shape[0]
    if n_units % n_data:
        raise ElasticError(
            f"bank axis 0 ({n_units}) must divide by n_data ({n_data})")
    per = n_units // n_data
    return jnp.asarray(bank) + d[slot * per:(slot + 1) * per]


def takeover_bank_slot(full_bank, slot: int, n_data: int):
    """Slice data slot ``slot``'s axis-0 shard from a spare's full bank
    replica (the dense analogue of :func:`takeover_shard`)."""
    import jax.numpy as jnp

    bank = jnp.asarray(full_bank)
    per = bank.shape[0] // n_data
    return bank[slot * per:(slot + 1) * per]

"""mpi4torch_tpu.elastic — live world resize: shrink, grow, takeover.

ROADMAP item 4, the composition of the PR 7 and PR 8 halves: failures
are already *attributed* (``RankFailedError.ranks``, ``check_health``
probes) and state already re-lays onto any topology as memory-bounded
portable-collective plans (``reshard``).  This package wires them into
a runtime that survives membership changes without a full-job restart:

* :mod:`.membership` — ``WorldView(epoch, alive, mesh_shape)`` and the
  probe-then-ratify consensus (``agree_world_view``): survivors agree
  on the next membership, a monotonically increasing epoch fences
  stale traffic (consensus tags, checkpoint stamps, driver-side
  :class:`~.membership.StaleEpochError`), and disagreement or a second
  failure mid-round ends in a typed, rank-attributed raise — never a
  hang.
* :mod:`.replan` — replan-as-reshard: every state kind re-lays through
  :func:`mpi4torch_tpu.reshard.plan_resize` (the cross-world-size
  planner in the PR 8 step grammar: adjoint = the reverse resize, VJP
  intact), ZeRO shards and TP heads and MoE expert stacks alike; serve
  traffic drains to tickets and re-admits through the engine's
  admission POLICIES with token streams bitwise vs ``generate()``.
* :mod:`.spare` — hot-spare ranks riding the existing collectives to
  keep full replicas of the sharded state current at zero extra wire,
  for zero-reshard takeover (fallback: the planned drain).
* :mod:`.runtime` — :class:`~.runtime.ElasticRuntime`, the phase
  driver (run phase → observe failure/notice → consensus → replan →
  resume).
* :mod:`.matrix` — the censused (failure kind × subsystem × action)
  matrix: every cell recovered-and-bitwise vs the fresh-start oracle
  on the new world or a typed attributed raise, fired-fault-ledger
  proven (``make elastic-smoke``).

See ``doc/elasticity.md``.
"""

from .membership import (ConsensusError, ElasticError, StaleEpochError,
                         WorldView, agree_world_view, fence_tag,
                         initial_view)
from .replan import (ServeTicket, drain_tickets, readmit, replan_axis0,
                     replan_axis0_tree, replan_zero, resize_embeds,
                     stitched_results)
from .runtime import ElasticRuntime
from .spare import (bank_spare_step, is_spare, takeover_bank_slot,
                    takeover_shard, zero_spare_init, zero_spare_step)

__all__ = [
    "WorldView",
    "ElasticError",
    "ConsensusError",
    "StaleEpochError",
    "agree_world_view",
    "fence_tag",
    "initial_view",
    "ElasticRuntime",
    "resize_embeds",
    "replan_axis0",
    "replan_axis0_tree",
    "replan_zero",
    "ServeTicket",
    "drain_tickets",
    "readmit",
    "stitched_results",
    "is_spare",
    "zero_spare_init",
    "zero_spare_step",
    "takeover_shard",
    "bank_spare_step",
    "takeover_bank_slot",
]

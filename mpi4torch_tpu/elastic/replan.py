"""Replan-as-reshard: re-laying state onto an agreed new world.

Once membership consensus has ratified a transition (:mod:`.membership`)
the surviving state must land in the new world's deal.  Every training
state kind in the repo shards by **leading units** — ZeRO's padded flat
elements (parallel/zero.py), TP's heads, MoE's stacked experts — so one
planner covers them all: :func:`mpi4torch_tpu.reshard.plan_resize`,
the cross-world-size extension of the PR 8 portable-collective planner
(same step grammar, same executors, adjoint = the reverse resize, VJP
via ``reshard.apply_plan`` so training graphs crossing a resize stay
AD-transparent).  This module supplies the glue: embedding maps from
(old view, new view) pairs, the drain/grow execution conventions, and
the per-kind recipes:

* **dense / TP** (:func:`replan_axis0`) — one resize per array.
* **ZeRO shards** (:func:`replan_zero`) — per-leaf flat resize of the
  ceil-padded shard representation (parameter shards and elementwise
  optimizer-state shards alike, mapped over matching templates).
* **MoE experts** — the expert stack IS an axis-0 resize; re-dealing
  for balance afterwards is the existing
  :func:`~mpi4torch_tpu.parallel.moe.rebalance_experts` on the new
  world (the two compose; see the matrix's moe cells).
* **serve** (:func:`drain_tickets` / :func:`readmit` /
  :func:`stitched_results`) — in-flight requests drain to tickets
  (prompt + tokens emitted so far + the request's ADVANCED sampling
  key) and re-admit through the new engine's ordinary admission
  POLICIES as extended-prompt submissions, so the continuation rides
  the engine's own prefill/decode discipline and the stitched token
  streams stay bitwise equal to per-request ``generate()``.

Execution conventions (who runs the plan):

* ``mode="drain"`` — the OLD world executes, every source rank still
  answering (the preemption-notice window, or a planned descale):
  ``embed_from`` is the identity, ``embed_to`` places each new deal
  position on the surviving old rank that will carry it.
* ``mode="grow"`` — the NEW world executes after capacity returned:
  ``embed_to`` is the identity, ``embed_from`` locates each old deal
  position among the survivors' new positions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import reshard as _rs
from .membership import ElasticError, WorldView

__all__ = [
    "resize_embeds",
    "replan_axis0",
    "replan_axis0_tree",
    "replan_zero",
    "ServeTicket",
    "drain_tickets",
    "readmit",
    "stitched_results",
]


def resize_embeds(old_view: WorldView, new_view: WorldView, mode: str):
    """``(embed_from, embed_to, exec_size)`` for a resize between two
    consecutive views.

    * ``"drain"``: executes on the OLD world (all old positions alive);
      requires ``new_view.alive ⊆ old_view.alive``.
    * ``"grow"``: executes on the NEW world; requires
      ``old_view.alive ⊆ new_view.alive``.
    """
    if mode == "drain":
        missing = set(new_view.alive) - set(old_view.alive)
        if missing:
            raise ElasticError(
                f"drain target names ids {sorted(missing)} not alive in "
                f"the source epoch {old_view.epoch}")
        embed_from = tuple(range(old_view.size))
        embed_to = tuple(old_view.position(rid) for rid in new_view.alive)
        return embed_from, embed_to, old_view.size
    if mode == "grow":
        missing = set(old_view.alive) - set(new_view.alive)
        if missing:
            raise ElasticError(
                f"grow source names ids {sorted(missing)} not alive in "
                f"the target epoch {new_view.epoch}")
        embed_from = tuple(new_view.position(rid) for rid in old_view.alive)
        embed_to = tuple(range(new_view.size))
        return embed_from, embed_to, new_view.size
    raise ElasticError(f"unknown resize mode {mode!r} "
                       "(expected 'drain' or 'grow')")


def _resize(comm, x, n_units: int, old_view: WorldView,
            new_view: WorldView, mode: str, strategy,
            differentiable: bool):
    import jax.numpy as jnp

    x = jnp.asarray(x)
    embed_from, embed_to, exec_size = resize_embeds(old_view, new_view,
                                                    mode)
    if comm.size != exec_size:
        raise ElasticError(
            f"{mode} resize executes on a {exec_size}-rank world; this "
            f"communicator has {comm.size}")
    plan = _rs.plan_resize(
        n_units, tuple(x.shape[1:]), old_view.size, new_view.size,
        x.dtype, embed_from=embed_from, embed_to=embed_to,
        exec_size=exec_size, strategy=strategy)
    return _rs.apply_plan(comm, plan, x, differentiable=differentiable)


def replan_axis0(comm, x, n_units: int, old_view: WorldView,
                 new_view: WorldView, *, mode: str, strategy=None,
                 differentiable: bool = True):
    """Re-deal an axis-0-sharded array (TP heads, MoE expert stacks,
    any dense leading-unit deal) from the old view's split to the new
    view's.  ``x`` is this rank's old shard (``mode="drain"``) or its
    old shard if it is a survivor / a zeros buffer of the old shard
    shape if it is a joiner (``mode="grow"``); returns this rank's new
    shard (leavers get zeros)."""
    return _resize(comm, x, int(n_units), old_view, new_view, mode,
                   strategy, differentiable)


def replan_axis0_tree(comm, tree, n_units_tree, old_view, new_view, *,
                      mode: str, strategy=None):
    """Tree-mapped :func:`replan_axis0` (``n_units_tree``: one int per
    leaf, or one int broadcast over the tree)."""
    import jax

    if isinstance(n_units_tree, int):
        n_units_tree = jax.tree.map(lambda _: n_units_tree, tree)
    return jax.tree.map(
        lambda x, n: replan_axis0(comm, x, n, old_view, new_view,
                                  mode=mode, strategy=strategy),
        tree, n_units_tree)


def replan_zero(comm, shard_tree, template, old_view: WorldView,
                new_view: WorldView, *, mode: str, strategy=None):
    """Re-deal a tree of ZeRO flat shards (the ceil-padded per-leaf
    representation of :func:`~mpi4torch_tpu.parallel.zero.
    zero3_shard_params` / ``fused_reduce_scatter_tree``) onto the new
    world's split.  ``template`` supplies each leaf's GLOBAL shape (the
    logical element count; the paddings on both sides are derived, and
    pad slots move as the zeros they are).  Works unchanged for
    elementwise optimizer-state trees whose leaves mirror the shard
    tree — map each state field against the same template."""
    import jax

    def one(shard, tmpl):
        n = int(np.prod(tuple(np.shape(tmpl)))) if np.shape(tmpl) \
            else 1
        return replan_axis0(comm, shard, n, old_view, new_view,
                            mode=mode, strategy=strategy)

    return jax.tree.map(one, shard_tree, template)


# ---------------------------------------------------------------------------
# Serve: drain in-flight requests, re-admit through admission policies.
# ---------------------------------------------------------------------------


@dataclass
class ServeTicket:
    """One in-flight request drained out of an engine: everything the
    new world needs to CONTINUE it — the original prompt, the tokens
    already emitted (bitwise-final: they were selected before the
    resize), the remaining budget, and the request's advanced PRNG key
    (``generate()``'s key discipline: the stream continues where it
    stopped, so sampled continuations match the never-resized oracle
    too)."""
    rid: Any
    prompt: np.ndarray
    emitted: List[int] = field(default_factory=list)
    max_new: int = 0
    key: Any = None
    # REMAINING deadline budget in seconds at drain time (ISSUE 15),
    # None = no deadline.  Carried as a relative duration, not an
    # absolute instant: the destination engine's clock is a different
    # clock domain whenever either engine injects one (the fake-clock
    # tests, the chaos matrix), and mixing domains would wrongly expire
    # — or wrongly resurrect — the request.  The re-admitted request
    # keeps this remaining budget; a ticket whose budget was consumed
    # by resize downtime is surfaced as ``deadline_expired`` at
    # re-admission (see :func:`readmit`), never silently dropped.
    deadline_s: Optional[float] = None
    # Block-table state at drain time (ISSUE 17; None on dense
    # engines): ``{"block_ids": [...], "n_tokens": int}`` — the pages
    # that held the request's written rows.  A paged source engine
    # registers those pages in its content-addressed prefix index
    # before releasing them, so re-admitting into the SAME pool
    # prefix-matches them back (blocks intact: the re-prefill is one
    # COW copy + a one-token suffix, and the stitched stream stays
    # bitwise the generate() oracle).  Carried explicitly so an
    # elastic driver can census/assert page reuse across a resize.
    pages: Optional[dict] = None

    @property
    def remaining(self) -> int:
        return self.max_new - len(self.emitted)

    def extended_prompt(self) -> np.ndarray:
        """The re-admission prompt: original prompt + tokens already
        emitted.  The new engine prefills this prefix — the same
        per-element attention reductions the incremental decode
        performed — and decodes the continuation."""
        return np.concatenate([
            np.asarray(self.prompt, np.int64),
            np.asarray(self.emitted, np.int64)]).astype(
                np.asarray(self.prompt).dtype, copy=False)


def drain_tickets(engine, *, snapshot: bool = False
                  ) -> Tuple[List[ServeTicket], Dict[Any, np.ndarray]]:
    """Drain (or, with ``snapshot=True``, observe without evicting) an
    engine's in-flight requests as :class:`ServeTicket`\\ s, plus the
    results already finished.  Every Mode B rank's engine holds the
    identical host-side request state (tokens are selected host-side,
    deterministically, on every rank), so any SURVIVOR's drain is the
    authoritative one — which is exactly what rank-death recovery
    needs."""
    reqs = engine.snapshot_inflight() if snapshot \
        else engine.drain()
    # Deadlines convert absolute -> remaining HERE, on the draining
    # engine's own clock (the only clock the absolute instant is
    # meaningful on); the ticket then carries a plain duration any
    # destination engine can re-anchor.
    now = engine._clock()
    tickets = [ServeTicket(rid=r["rid"], prompt=r["prompt"],
                           emitted=list(r["emitted"]),
                           max_new=r["max_new"], key=r["key"],
                           deadline_s=(None if r.get("deadline") is None
                                       else r["deadline"] - now),
                           pages=r.get("pages"))
               for r in reqs]
    return tickets, engine.results()


def readmit(engine, tickets) -> List[Any]:
    """Re-admit drained tickets through the engine's ordinary admission
    path (the registered POLICIES pick the order, exactly like fresh
    traffic).  Already-finished tickets are skipped; a ticket whose
    remaining deadline budget is gone (consumed by resize downtime) is
    recorded on the engine as a typed ``deadline_expired`` result
    carrying the oracle-prefix tokens it had earned
    (:meth:`Engine.admit_expired` — never silently dropped, never
    burns a prefill).  Returns the rids actually re-submitted for
    decoding."""
    out = []
    for t in tickets:
        if t.remaining <= 0:
            continue
        if t.deadline_s is not None and t.deadline_s <= 0:
            engine.admit_expired(t.extended_prompt(), rid=t.rid)
            continue
        engine.submit(t.extended_prompt(), rid=t.rid,
                      max_new=t.remaining, key=t.key,
                      deadline_s=t.deadline_s)
        out.append(t.rid)
    return out


def stitched_results(engine_results: Dict[Any, np.ndarray],
                     tickets) -> Dict[Any, np.ndarray]:
    """Post-resize results re-expressed against the ORIGINAL prompts:
    the new engine returns ``extended_prompt + continuation``, which is
    literally ``original prompt + pre-resize tokens + post-resize
    tokens`` — the never-resized sequence.  Tickets that were already
    finished pass through unchanged."""
    out = dict(engine_results)
    for t in tickets:
        if t.remaining <= 0 and t.rid not in out:
            out[t.rid] = np.concatenate([
                np.asarray(t.prompt, np.int64),
                np.asarray(t.emitted, np.int64)])
    return out

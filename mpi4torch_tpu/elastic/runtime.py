"""The elastic driver: phase execution + membership transitions.

Mode B worlds are per-``run_ranks`` by construction (a world's threads
die with the call), so an elastic job is naturally a sequence of
**phases**: run a phase on the current membership, observe what it
reports (results, an attributed failure, a preemption notice on the
fault plan's board), agree on the next membership, re-lay state, run
the next phase.  :class:`ElasticRuntime` owns exactly that loop state:
the current :class:`~.membership.WorldView`, the set of stable ids
known dead (harvested from ``RankFailedError.ranks`` — the PR 7
attribution is what makes this loop possible), and the consensus verb
that turns both into the next agreed view.

Epoch fencing at this layer: :meth:`run_phase` refuses a view object
from a superseded epoch (:class:`~.membership.StaleEpochError` naming
both epochs) — the driver-side analogue of the consensus tag fence and
the checkpoint epoch stamp.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..runtime import RankFailedError, run_ranks
from .membership import (ElasticError, StaleEpochError, WorldView,
                         agree_world_view, initial_view)

__all__ = ["ElasticRuntime"]


class ElasticRuntime:
    """Drives an elastic Mode B job across world resizes.

    ::

        rt = ElasticRuntime(8)
        try:
            outs = rt.run_phase(train_phase)      # body(pos, rank_id)
        except RankFailedError:
            view = rt.consensus()                 # shrink past the dead
            ...replan state, resume on rt.view...

    ``run_phase`` bodies receive ``(position, rank_id)`` — the world
    position (this epoch's comm rank) and the stable id it acts for.
    Failures recorded by :meth:`run_phase` (or :meth:`note_dead`)
    become the absent side of the next :meth:`consensus`: their
    positions run no body (the Mode B stand-in for the machine being
    gone), the probe observes them as ``missing``, and the ratified
    view drops them.  ``note_dead`` is therefore an assertion, not a
    hint — a mistaken note evicts a healthy rank, so only record
    attributions the runtime handed you (``RankFailedError.ranks``)."""

    def __init__(self, n_ranks: Optional[int] = None, *,
                 view: Optional[WorldView] = None, mesh_shape=None,
                 probe_timeout: float = 1.0,
                 world_timeout: Optional[float] = None):
        if (n_ranks is None) == (view is None):
            raise ElasticError(
                "ElasticRuntime needs exactly one of n_ranks= or view=")
        self._view = view if view is not None \
            else initial_view(n_ranks, mesh_shape)
        self.probe_timeout = float(probe_timeout)
        self.world_timeout = world_timeout
        self._dead: Dict[int, str] = {}

    # ------------------------------------------------------------- state

    @property
    def view(self) -> WorldView:
        return self._view

    @property
    def epoch(self) -> int:
        return self._view.epoch

    @property
    def dead_ids(self) -> Dict[int, str]:
        """Stable ids known dead (id -> reason), pending the next
        consensus round."""
        return dict(self._dead)

    def note_dead(self, rank_id: int, reason: str = "reported dead"):
        self._dead[int(rank_id)] = reason

    # ------------------------------------------------------------ phases

    def run_phase(self, body, *, view: Optional[WorldView] = None,
                  timeout: Optional[float] = None) -> List:
        """Run ``body(position, rank_id)`` on every rank of the current
        world; returns the per-position results (the Mode B idiom —
        state rides through the driver between phases).

        A ``RankFailedError`` is harvested for attribution (positions
        mapped back to stable ids, recorded for the next consensus) and
        re-raised — the driver decides whether to shrink or give up.
        Passing ``view`` asserts the phase was built against the
        CURRENT epoch: a stale one raises :class:`StaleEpochError`
        instead of running collectives whose membership assumptions are
        wrong."""
        cur = self._view
        if view is not None and view.epoch != cur.epoch:
            raise StaleEpochError(
                f"phase was prepared against epoch {view.epoch}, but "
                f"the world is at epoch {cur.epoch} — re-lay the phase "
                "against the current view (stale traffic is fenced, "
                "not executed)", have=view.epoch, want=cur.epoch)

        def wrapper(pos):
            return body(pos, cur.alive[pos])

        try:
            return run_ranks(wrapper, cur.size,
                             timeout=timeout or self.world_timeout)
        except RankFailedError as e:
            for pos in e.ranks:
                if 0 <= pos < cur.size:
                    self._dead[cur.alive[pos]] = str(e)
            raise

    # --------------------------------------------------------- consensus

    def consensus(self, *, leaving: Sequence[int] = (),
                  joining: Sequence[int] = (), mesh_shape=None,
                  probe_timeout: Optional[float] = None) -> WorldView:
        """One membership-consensus round over the current world:
        positions whose ids are known dead run no body (the Mode B
        stand-in for a gone machine — they answer nothing, so the
        probe reports them missing and the ratified view drops them),
        live positions run :func:`~.membership.agree_world_view`, and
        the ratified view is adopted.  Returns the new view; typed
        raises propagate (disagreement, second failures) — the
        driver's callers handle or abort, never hang."""
        cur = self._view
        dead = set(self._dead)
        pt = self.probe_timeout if probe_timeout is None else probe_timeout

        def body(pos):
            if cur.alive[pos] in dead:
                return None
            return agree_world_view(
                cur, leaving=leaving, joining=joining,
                mesh_shape=mesh_shape, probe_timeout=pt)

        results = run_ranks(body, cur.size,
                            timeout=self.world_timeout)
        views = [v for v in results if v is not None]
        if not views:
            raise ElasticError(
                "consensus returned no views — every position was "
                "known dead")
        first = views[0]
        if any(v != first for v in views[1:]):
            # The protocol ratifies one modal view on every participant;
            # divergent adopted views mean the ratification itself is
            # broken — refuse to adopt.
            raise ElasticError(
                f"ratified views diverge across survivors: {views}")
        self._view = first
        # Ids that left the membership are settled: drop their death
        # bookkeeping, and consume any preemption notice they posted
        # (their death op will never run — they are out of the world).
        from .. import config as _cfg

        plan = _cfg.fault_plan()
        for rid in list(self._dead):
            if rid not in first.alive:
                self._dead.pop(rid)
        for pos in range(cur.size):
            if cur.alive[pos] not in first.alive:
                if plan is not None:
                    plan.clear_preemption(pos)
                else:
                    # A REAL preemption notice (a SIGTERMed transport
                    # worker) has no plan to clear through — consume it
                    # from the transport board directly.
                    from ..transport import clear_external_preemption
                    clear_external_preemption(pos)
        return first

    def drain(self, replan_body, *, leaving: Sequence[int] = (),
              mesh_shape=None) -> List:
        """The live-shrink round: consensus AND replan in ONE assembly
        of the CURRENT world — every member (including the ranks being
        drained out, who are still answering inside their notice
        window) ratifies the next view, then immediately executes
        ``replan_body(position, rank_id, old_view, new_view)`` while
        the old world is still standing — the drain collectives run
        with every source rank alive, which is what makes the planned
        resize (rather than a checkpoint rewind) possible at all.

        Adopts the ratified view and returns the per-OLD-position
        replan results (the driver re-indexes survivors onto the new
        world's positions)."""
        cur = self._view
        pt = self.probe_timeout

        def body(pos):
            rid = cur.alive[pos]
            new = agree_world_view(cur, leaving=leaving,
                                   mesh_shape=mesh_shape,
                                   probe_timeout=pt)
            return (new, replan_body(pos, rid, cur, new))

        try:
            results = run_ranks(body, cur.size,
                                timeout=self.world_timeout)
        except RankFailedError as e:
            # A drain that overruns a preemption budget meets the
            # doomed rank's death mid-replan: harvest the attribution
            # exactly like run_phase, so the driver's fallback
            # consensus sees the rank as dead instead of re-admitting
            # a gone machine.
            for pos in e.ranks:
                if 0 <= pos < cur.size:
                    self._dead[cur.alive[pos]] = str(e)
            raise
        views = {r[0] for r in results}
        if len(views) != 1:
            raise ElasticError(
                f"drain round ratified divergent views: {views}")
        new = views.pop()
        from .. import config as _cfg

        plan = _cfg.fault_plan()
        for pos in range(cur.size):
            if cur.alive[pos] not in new.alive:
                if plan is not None:
                    plan.clear_preemption(pos)
                else:
                    from ..transport import clear_external_preemption
                    clear_external_preemption(pos)
        self._view = new
        return [r[1] for r in results]

    def pending_preemptions(self) -> Dict[int, int]:
        """Preemption notices by STABLE ID (the fault plan's board is
        keyed by world position; translate through the current view)."""
        from ..resilience import pending_preemptions as _pending

        cur = self._view
        out = {}
        for pos, remaining in _pending().items():
            if 0 <= pos < cur.size:
                out[cur.alive[pos]] = remaining
        return out

"""Membership consensus: who is in the world, under which epoch.

The elastic runtime's first problem is agreement: after an attributed
failure (``RankFailedError.ranks``, PR 7) or a preemption notice
(:func:`mpi4torch_tpu.resilience.pending_preemptions`), the survivors
must all adopt the SAME shrunk (or grown) membership before any of them
re-lays state — two ranks replanning against different worlds is silent
corruption.  This module runs that agreement as a two-round protocol
built entirely on existing runtime primitives:

1. **probe** — every live rank calls ``World.health_check`` (the
   resettable attributed barrier of runtime.py): dead and hung ranks
   land in ``missing``, and the probe *returns* its report instead of
   tearing collective state, so consensus can run on a world whose
   collective barrier is already broken.
2. **ratify** — the arrived ranks exchange proposals over the p2p
   mailboxes (epoch-fenced tags — see :func:`fence_tag`): each
   proposes ``WorldView(epoch + 1, survivors, mesh)``; the lowest
   arrived rank collects, picks the modal proposal, and answers every
   participant with the verdict.  Disagreement raises a typed,
   rank-attributed :class:`ConsensusError` naming the ranks whose
   proposal lost; a SECOND failure mid-consensus surfaces as the
   runtime's own typed errors (a dead peer's ``RankFailedError``, a
   bounded-timeout ``DeadlockError``) — never a hang, because every
   wait in the protocol is the runtime's own bounded wait.

**Epoch fencing.**  ``WorldView.epoch`` increases by exactly one per
adopted transition.  Consensus traffic is tagged by the epoch it
transitions FROM (:func:`fence_tag`), so a straggler's stale round
cannot be consumed by a later one; checkpoint steps record the epoch
they were saved under (``utils/checkpoint.py``) so a stale-world resume
raises instead of loading shards whose meaning changed; and the elastic
driver (:class:`~mpi4torch_tpu.elastic.runtime.ElasticRuntime`) refuses
to run a phase against a view object whose epoch is not current
(:class:`StaleEpochError` naming both epochs).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..runtime import CommError, effective_rank_context

__all__ = [
    "WorldView",
    "ElasticError",
    "ConsensusError",
    "StaleEpochError",
    "fence_tag",
    "agree_world_view",
]


class ElasticError(CommError):
    """Base class for elastic world-resize errors."""


class ConsensusError(ElasticError):
    """Membership consensus failed: the participants did not propose
    the same next world view.  ``ranks`` names the STABLE IDS whose
    proposal disagreed with the ratified (modal) one — the
    rank-attribution discipline of PR 7 applied to coordination."""

    def __init__(self, message: str, ranks=()):
        super().__init__(message)
        self.ranks = frozenset(ranks)


class StaleEpochError(ElasticError):
    """An operation presented a world view from a superseded epoch.
    Carries both epochs — the one presented and the one current — the
    same both-sides attribution the checkpoint fence gives."""

    def __init__(self, message: str, have: int, want: int):
        super().__init__(message)
        self.have = int(have)
        self.want = int(want)


# Tag namespace for consensus p2p traffic: far above anything user code
# or the subsystems use.  Each epoch owns a disjoint block of
# _PHASES_PER_EPOCH tags, so a stale round's messages can never be
# consumed by a later epoch's ratification — the mailbox keys simply
# differ.
_TAG_BASE = 7_340_000
_PHASES_PER_EPOCH = 4
_PROPOSE, _VERDICT = 0, 1


def fence_tag(epoch: int, phase: int) -> int:
    """The p2p tag of consensus ``phase`` for the round transitioning
    FROM ``epoch`` — the epoch fence made concrete."""
    if not (0 <= phase < _PHASES_PER_EPOCH):
        raise ValueError(f"phase must be in [0, {_PHASES_PER_EPOCH})")
    return _TAG_BASE + int(epoch) * _PHASES_PER_EPOCH + phase


@dataclass(frozen=True)
class WorldView:
    """An agreed membership: monotonically increasing ``epoch``, the
    sorted tuple of STABLE rank ids that are alive, and the virtual mesh
    shape the survivors run as.  World positions are the indices of
    ``alive``: the rank-``j`` thread of an epoch's Mode B world acts for
    id ``alive[j]`` — ids persist across resizes, positions do not."""

    epoch: int
    alive: Tuple[int, ...]
    mesh_shape: Tuple[int, ...]

    def __post_init__(self):
        alive = tuple(int(r) for r in self.alive)
        mesh = tuple(int(m) for m in self.mesh_shape)
        object.__setattr__(self, "alive", alive)
        object.__setattr__(self, "mesh_shape", mesh)
        if self.epoch < 0:
            raise ElasticError(f"epoch must be >= 0, got {self.epoch}")
        if not alive:
            raise ElasticError("a WorldView needs at least one rank")
        if list(alive) != sorted(set(alive)):
            raise ElasticError(
                f"alive ids must be sorted and unique, got {alive}")
        if not mesh or any(m < 1 for m in mesh):
            raise ElasticError(f"invalid mesh shape {mesh}")
        if math.prod(mesh) != len(alive):
            raise ElasticError(
                f"mesh shape {mesh} spans {math.prod(mesh)} ranks but "
                f"{len(alive)} are alive")

    @property
    def size(self) -> int:
        return len(self.alive)

    def position(self, rank_id: int) -> int:
        """World position of stable id ``rank_id`` in this epoch."""
        try:
            return self.alive.index(int(rank_id))
        except ValueError:
            raise ElasticError(
                f"rank id {rank_id} is not alive in epoch {self.epoch} "
                f"(alive: {self.alive})") from None

    def id_at(self, position: int) -> int:
        return self.alive[position]

    def describe(self) -> str:
        mesh = "x".join(str(m) for m in self.mesh_shape)
        return f"epoch {self.epoch}: ({mesh}) over ids {list(self.alive)}"


def initial_view(n: int, mesh_shape=None) -> WorldView:
    """Epoch-0 view of a fresh ``n``-rank job (ids 0..n-1)."""
    return WorldView(0, tuple(range(int(n))),
                     tuple(mesh_shape) if mesh_shape else (int(n),))


def _emit_transition(view: WorldView, new: WorldView,
                     is_coordinator: bool) -> None:
    """Epoch-transition observability (mpi4torch_tpu.obs): one counter
    tick per adopted transition (the coordinator's), world gauges from
    every adopter (idempotent)."""
    from ..obs import metrics as _metrics

    if is_coordinator:
        _metrics.inc("elastic_epoch_transitions_total",
                     help="adopted elastic world-view transitions")
    _metrics.set_gauge("elastic_world_epoch", new.epoch,
                       help="current elastic world epoch")
    _metrics.set_gauge("elastic_world_size", new.size,
                       help="alive ranks in the current elastic world")


def agree_world_view(view: WorldView, *, leaving=(), joining=(),
                     mesh_shape=None, probe_timeout: Optional[float] = None,
                     _propose=None) -> WorldView:
    """Run one membership-consensus round; every live rank of the
    current world must call it (collectively, like ``check_health``).
    Returns the ratified next :class:`WorldView` on every arrived rank.

    * ``leaving`` — stable ids being drained out deliberately (a
      preemption notice's doomed rank): they PARTICIPATE in the round
      (they are still answering) but are excluded from the next view.
    * ``joining`` — stable ids re-admitted on a grow (capacity
      returned); must be disjoint from the current membership.
    * ``mesh_shape`` — the next view's mesh (default: flat).
    * ``probe_timeout`` — the health-probe bound; dead/hung ranks cost
      exactly this long to detect (``HealthReport.probe_duration_s``).

    Failure modes, all typed and bounded: proposal disagreement (or a
    stale-epoch proposal) raises :class:`ConsensusError` naming the
    losing ids on every participant; a rank dying mid-round surfaces as
    the runtime's attributed ``RankFailedError``/``DeadlockError``.
    ``_propose`` (testing) replaces this rank's proposal — the
    disagreement-injection hook the elastic matrix's consensus cells
    use."""
    ctx = effective_rank_context()
    world, pos = ctx.world, ctx.rank
    if world.size != view.size:
        raise ElasticError(
            f"agree_world_view must run on the world of {view.describe()} "
            f"(size {view.size}); this world has {world.size} ranks")
    leaving_ids = frozenset(int(r) for r in leaving)
    joining_ids = tuple(sorted(int(r) for r in joining))
    bad_leave = leaving_ids - set(view.alive)
    if bad_leave:
        raise ElasticError(
            f"leaving ids {sorted(bad_leave)} are not alive in "
            f"epoch {view.epoch}")
    overlap = set(joining_ids) & set(view.alive)
    if overlap:
        raise ElasticError(
            f"joining ids {sorted(overlap)} are already alive in "
            f"epoch {view.epoch}")

    report = world.health_check(pos, probe_timeout)
    arrived = sorted(report.arrived)
    if not arrived:
        raise ConsensusError(
            "health probe returned an empty arrival set", ranks=())
    survivors = [view.alive[p] for p in arrived
                 if view.alive[p] not in leaving_ids]
    new_alive = tuple(sorted(set(survivors) | set(joining_ids)))
    if not new_alive:
        raise ConsensusError(
            "no rank survives the proposed transition (every arrived "
            "rank is leaving)", ranks=frozenset(leaving_ids))
    proposal = WorldView(
        view.epoch + 1, new_alive,
        tuple(mesh_shape) if mesh_shape else (len(new_alive),))
    if _propose is not None:
        proposal = _propose(proposal)

    coord = arrived[0]
    tag_p = fence_tag(view.epoch, _PROPOSE)
    tag_v = fence_tag(view.epoch, _VERDICT)
    if pos == coord:
        proposals: Dict[int, WorldView] = {coord: proposal}
        for p in arrived[1:]:
            # A peer dying here raises the runtime's attributed
            # RankFailedError; a peer that never sends, the bounded
            # DeadlockError — the "second failure mid-consensus ends in
            # a typed raise" contract comes from the mailbox itself.
            proposals[p] = world.p2p_recv(p, coord, tag_p)
        verdict = _ratify(view, proposals)
        for p in arrived[1:]:
            world.p2p_send(coord, p, tag_v, verdict)
    else:
        world.p2p_send(pos, coord, tag_p, proposal)
        verdict = world.p2p_recv(coord, pos, tag_v)

    kind, payload = verdict
    if kind == "disagree":
        raise ConsensusError(
            f"membership consensus from epoch {view.epoch} failed: "
            f"rank id(s) {sorted(payload)} proposed a different next "
            "world view than the ratified one", ranks=payload)
    ratified: WorldView = payload
    _emit_transition(view, ratified, is_coordinator=(pos == coord))
    return ratified


def _ratify(view: WorldView, proposals: Dict[int, "WorldView"]):
    """The coordinator's verdict: the modal valid proposal wins
    (deterministic tie-break: the lowest proposing position); proposals
    from a different source epoch are stale by definition and can never
    win.  Returns ``("ok", view)`` or ``("disagree", frozenset(ids))``."""
    groups: Dict[object, list] = {}
    for p in sorted(proposals):
        prop = proposals[p]
        valid = (isinstance(prop, WorldView)
                 and prop.epoch == view.epoch + 1)
        groups.setdefault(prop if valid else ("stale", p), []).append(p)
    winner_key = max(
        (k for k in groups if isinstance(k, WorldView)),
        key=lambda k: (len(groups[k]), -min(groups[k])), default=None)
    if winner_key is None:
        # Nobody proposed a valid next view (all stale): attribute all.
        bad = frozenset(view.alive[p] for ps in groups.values()
                        for p in ps)
        return ("disagree", bad)
    losers = [p for k, ps in groups.items() if k != winner_key
              for p in ps]
    if losers:
        return ("disagree", frozenset(view.alive[p] for p in losers))
    return ("ok", winner_key)

"""`python -m mpi4torch_tpu.elastic --smoke` — the elastic-smoke lane.

Runs the FULL elastic matrix (:mod:`.matrix`): every (failure kind ×
subsystem × action) cell — rank_death and preempt across the plain /
ZeRO / MoE / serve subsystems under shrink, grow-after-shrink and
hot-spare takeover — plus the two membership-failure cells (injected
proposal disagreement; a rank dying mid-consensus).  A cell passes only
when it ends **recovered and bitwise against the fresh-start oracle on
the new world** (the fired-fault ledger proving the fault acted — no
vacuous passes) or in its typed, rank-attributed raise.  Exits non-zero
on ANY hang-shaped failure, unattributed error, non-bitwise recovery,
unfired cell, or registry drift (``analyze.registry.elastic_problems``
— the PR 4/6/7 registry-sync guard applied to the elastic coverage
table).

The Makefile's ``elastic-smoke`` target runs it on the 8-virtual-device
CPU harness.
"""

from __future__ import annotations

import sys


def _check_registry_sync() -> list:
    from ..analyze.registry import elastic_problems

    return elastic_problems()


def _smoke() -> int:
    import jax

    from .matrix import (CONSENSUS_COVERAGE, COVERAGE, run_cell,
                         run_consensus_cell)

    ndev = len(jax.devices())
    print(f"elastic-smoke: {ndev} device(s), platform "
          f"{jax.devices()[0].platform}, "
          f"{len(COVERAGE) + len(CONSENSUS_COVERAGE)} cells")

    problems = _check_registry_sync()
    for p in problems:
        print(f"FAIL[registry]: {p}")

    failures = len(problems)
    ran = 0
    for kind, subsystem, action in sorted(COVERAGE):
        rec = run_cell(kind, subsystem, action)
        ran += 1
        tag = f"{kind} x {subsystem} x {action}"
        if rec.get("fallback"):
            tag += " (fallback)"
        if rec["status"] == "ok":
            print(f"ok  : {tag}: {rec['detail']}")
        else:
            failures += 1
            print(f"FAIL: {tag}: {rec['detail']}")

    for kind, subsystem, action in sorted(CONSENSUS_COVERAGE):
        rec = run_consensus_cell(kind)
        ran += 1
        tag = f"{kind} x {subsystem}"
        if rec["status"] == "ok":
            print(f"ok  : {tag}: {rec['detail']}")
        else:
            failures += 1
            print(f"FAIL: {tag}: {rec['detail']}")

    print(f"elastic-smoke: {ran} cells, {failures} failure(s)")
    if failures:
        return 1
    print("elastic-smoke: OK — every cell recovered bitwise on the new "
          "world or raised typed+attributed; no hangs, no unfired "
          "cells")
    return 0


def main(argv) -> int:
    if "--smoke" in argv:
        return _smoke()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""mpi4torch_tpu — AD-transparent collective communication, TPU-native.

A brand-new JAX/XLA framework with the capabilities of mpi4torch
(helmholtz-analytics/mpi4torch): every communication op — Allreduce, Bcast_,
Reduce_, Gather, Allgather, Scatter, Alltoall, Send/Recv, Isend/Irecv/Wait —
is differentiable, with the backward pass being the *adjoint* communication
op, plus the JoinDummies/WaitHandle dependency-token machinery
(reference: README.md:5-10, src/__init__.py:5-25).

Two interchangeable backends behind one facade:

* eager thread-SPMD (:func:`run_ranks`) — the ``mpirun -np N`` analogue with
  concrete per-rank ranks/shapes; semantics/parity path and deterministic
  bit-exact oracle.
* SPMD mesh (:func:`run_spmd`, ``comm_from_mesh``) — single-trace ``shard_map``
  over a :class:`jax.sharding.Mesh`, lowering to XLA collectives over
  ICI/DCN; the TPU performance path.
"""

from .constants import (
    MPI_MAX,
    MPI_MIN,
    MPI_SUM,
    MPI_PROD,
    MPI_LAND,
    MPI_BAND,
    MPI_LOR,
    MPI_BOR,
    MPI_LXOR,
    MPI_BXOR,
    MPI_MINLOC,
    MPI_MAXLOC,
)
from .comm import (
    COMM_WORLD,
    JoinDummies,
    JoinDummiesHandle,
    MPI_Communicator,
    WaitHandle,
    comm_from_mesh,
    comm_from_mpi4py,
    deactivate_cuda_aware_mpi_support,
)
from .runtime import (
    BifurcationError,
    CollectiveMismatchError,
    CommError,
    DeadlockError,
    HealthReport,
    InPlaceReuseError,
    IntegrityError,
    RankFailedError,
    run_ranks,
)
from .mesh import device_mesh, hybrid_mesh
from .ops.spmd import PermRank, RankExpr, p2p_scope, run_spmd
from .distributed import (
    DistributedInfo,
    distributed_info,
    finalize_distributed,
    init_distributed,
    is_distributed,
    local_values,
)
from . import config
from . import compress
from . import fuse
from . import tune
from . import overlap
from . import resilience
from . import reshard
from . import serve
from . import analyze
from . import csched
from . import obs
from . import elastic
from . import ctl
from .config import (algorithm_scope, compression_scope, fusion_scope,
                     overlap_scope)
from .overlap import SpmdWaitHandle
from .resilience import FaultPlan, FaultSpec, fault_scope

__all__ = [
    # reference __all__ (src/__init__.py:5-25)
    "MPI_MAX",
    "MPI_MIN",
    "MPI_SUM",
    "MPI_PROD",
    "MPI_LAND",
    "MPI_BAND",
    "MPI_LOR",
    "MPI_BOR",
    "MPI_LXOR",
    "MPI_BXOR",
    "MPI_MINLOC",
    "MPI_MAXLOC",
    "WaitHandle",
    "JoinDummies",
    "JoinDummiesHandle",
    "MPI_Communicator",
    "COMM_WORLD",
    "comm_from_mpi4py",
    "deactivate_cuda_aware_mpi_support",
    # TPU-native additions
    "comm_from_mesh",
    "device_mesh",
    "hybrid_mesh",
    "run_ranks",
    "p2p_scope",
    "run_spmd",
    "DistributedInfo",
    "distributed_info",
    "finalize_distributed",
    "init_distributed",
    "is_distributed",
    "local_values",
    "RankExpr",
    "PermRank",
    "config",
    "compress",
    "fuse",
    "tune",
    "overlap",
    "resilience",
    "reshard",
    "serve",
    "analyze",
    "csched",
    "obs",
    "elastic",
    "ctl",
    "SpmdWaitHandle",
    "FaultPlan",
    "FaultSpec",
    "fault_scope",
    "algorithm_scope",
    "compression_scope",
    "fusion_scope",
    "overlap_scope",
    "CommError",
    "CollectiveMismatchError",
    "DeadlockError",
    "InPlaceReuseError",
    "BifurcationError",
    "RankFailedError",
    "IntegrityError",
    "HealthReport",
]

__version__ = "0.1.0"

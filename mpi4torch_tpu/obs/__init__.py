"""mpi4torch_tpu.obs — unified runtime observability.

The stack had rich *static* evidence (the ``analyze`` wire/peak/
exposure accountings) and scattered *runtime* counters
(``World.retry_events``, the guards' violation ledger, ``ServeStats``)
but no unified runtime layer: no wire timeline, no metrics export, no
postmortem of what the chokepoints actually did when a rank died.
This package is that layer, in five pieces:

* **chokepoint comm tracing** (:mod:`.trace`, :mod:`.events`) — typed
  :class:`CommEvent` records emitted at the two Mode B chokepoints
  every subsystem funnels through (``World.exchange`` + the p2p
  mailboxes: fuse/compress/overlap/reshard/serve traffic traced with
  zero per-subsystem hooks), plus Mode A step events via the
  named-scope/host-callback hook.  Off path: one attribute read per
  rendezvous, lowering bit-identical to an obs-less build (censused in
  ``bench._bench_obs_overhead``).
* a **metrics registry** (:mod:`.metrics`) — thread-safe counters/
  gauges/histograms with JSON snapshot and Prometheus text export,
  absorbing the ad-hoc surfaces (retry events, integrity violations,
  autotuner cache hits, serve counters) under one ``mpi4torch_*``
  namespace; also the shared :func:`percentile` rule and the weakref
  stats-source registry ``ServeStats`` aggregation re-homed onto.
* a **flight recorder** (:mod:`.flight`) — bounded per-rank rings of
  recent events, dumped as a rank-attributed postmortem (JSON + human
  table) when ``RankFailedError``/``DeadlockError``/``IntegrityError``
  is raised: the last N wire operations on each rank when it died.
* **Chrome-trace/Perfetto export** (:mod:`.export`) of the Mode B
  timeline, next to the existing ``utils.profiler_trace`` xplane
  capture.
* **static-vs-runtime reconciliation** (:mod:`.reconcile`) —
  :func:`reconcile` joins measured wire bytes / event counts against
  ``analyze.wire_bytes_per_device`` predictions, exact-match
  deterministic on Mode B (bytes are censused, not sampled): a
  CI-checkable contract, not a dashboard.

``python -m mpi4torch_tpu.obs --smoke`` / ``make obs-smoke`` run the
traced 8-virtual-device lane: reconcile on four representative
schedules, the flight-recorder rank-death postmortem, and the off-path
bit-identity census.  See doc/observability.md.
"""

# Module alias first: the `trace` attribute below is the context
# manager, which shadows the submodule on the package — `obs.tracing`
# is the patchable module handle (bench's obs-less-build census
# monkeypatches `tracing.spmd_collective_event`).
from . import trace as tracing  # noqa: F401  (module alias)
from .events import CommEvent, annotate_signature, payload_nbytes
from .export import chrome_trace, write_chrome_trace
from .flight import dump_postmortem, format_postmortem
from .metrics import (MetricsRegistry, StatsSourceRegistry, metrics_json,
                      percentile, prometheus_text, register_collector,
                      registry, reset_metrics, snapshot)
from .reconcile import (equivalent_tier_wire, equivalent_wire,
                        measured_wire_table, reconcile)
from .trace import (CommTracer, current_tracer, push_label,
                    spmd_collective_event, trace)

__all__ = [
    "tracing",
    "CommEvent",
    "CommTracer",
    "annotate_signature",
    "payload_nbytes",
    "trace",
    "current_tracer",
    "push_label",
    "spmd_collective_event",
    "MetricsRegistry",
    "StatsSourceRegistry",
    "registry",
    "snapshot",
    "metrics_json",
    "prometheus_text",
    "register_collector",
    "reset_metrics",
    "percentile",
    "format_postmortem",
    "dump_postmortem",
    "chrome_trace",
    "write_chrome_trace",
    "measured_wire_table",
    "reconcile",
    "equivalent_wire",
    "equivalent_tier_wire",
]

"""Chrome-trace / Perfetto JSON export of the Mode B event timeline.

The Mode A story already has a capture path — ``utils.profiler_trace``
writes xplane protobufs the TensorBoard profile plugin / xprof /
Perfetto read natively.  This module gives the Mode B chokepoint trace
the same viewer: :func:`chrome_trace` renders a
:class:`~.events.CommEvent` list as the Chrome Trace Event Format
(the ``traceEvents`` JSON Perfetto and ``chrome://tracing`` both load),
one timeline row per (world, rank), complete ("X") events with the
op/bytes/retries/status in ``args`` so a hung collective shows as the
row where every rank's lane goes quiet except the one that never
arrived.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

__all__ = ["chrome_trace", "write_chrome_trace"]


def chrome_trace(events: Iterable, label: str = "mpi4torch_tpu") -> dict:
    """Chrome Trace Event Format dict of an event list.

    Timestamps are microseconds relative to the earliest event (the
    absolute ``perf_counter`` epoch is meaningless across processes);
    ``pid`` is the world ordinal, ``tid`` the rank (Mode A step events
    land on the ``spmd`` pseudo-row), so Perfetto renders one lane per
    rank with the collective spans aligned."""
    evs = sorted(events, key=lambda e: (e.t_start, e.seq))
    t0 = evs[0].t_start if evs else 0.0
    out = {"displayTimeUnit": "ms", "traceEvents": [],
           "otherData": {"source": label}}
    named = set()
    for e in evs:
        pid = e.world if e.world >= 0 else 9999
        tid = e.rank if e.rank >= 0 else 0
        if (pid, tid) not in named:
            named.add((pid, tid))
            out["traceEvents"].append({
                "ph": "M", "pid": pid, "tid": tid,
                "name": "thread_name",
                "args": {"name": (f"rank{e.rank}" if e.rank >= 0
                                  else "spmd (Mode A)")}})
        name = e.op
        if e.codec:
            name += f".{e.codec}"
        if e.algorithm and e.algorithm != "ring":
            name += f".{e.algorithm}"
        out["traceEvents"].append({
            "ph": "X", "pid": pid, "tid": tid, "name": name,
            "cat": e.channel,
            "ts": (e.t_start - t0) * 1e6,
            "dur": max(e.duration_s, 0.0) * 1e6,
            "args": {
                "seq": e.seq,
                "payload_bytes": e.payload_bytes,
                "retries": e.retries,
                "status": e.status,
                "bucket": e.bucket,
                "signature": repr(e.signature),
            }})
    return out


def write_chrome_trace(path: str, events: Iterable,
                       label: str = "mpi4torch_tpu") -> str:
    """Write :func:`chrome_trace` JSON to ``path`` (load it in Perfetto
    via "Open trace file", or ``chrome://tracing``).  Returns the
    path."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(events, label=label), f)
    return path

"""`python -m mpi4torch_tpu.obs --smoke` — the obs-smoke lane.

Four verdict families, every one exit-coded (the census discipline:
a claim either reproduces exactly or the lane fails):

1. **Static-vs-runtime reconciliation** — four representative
   schedules run traced under the Mode B runtime and joined against
   the ``analyze`` predictions of their Mode A lowerings, all EXACT
   (wire bytes AND per-kind collective counts): a plain ring
   allreduce, a fused q8 bucket pair, the (8,)->(2,4) reshard
   migration (the PR 8 pinned 98304-byte plan), and an overlap serve
   decode step (split-phase RS+AG pairs, scheduled exposure riding
   along).
2. **Flight recorder** — an injected ``FaultSpec(kind="rank_death")``
   mid-collective must produce a postmortem NAMING the dead rank, with
   every survivor's event tail ending on the same torn collective
   signature, and the JSON + human-table dump written.
3. **Off-path census** — with no tracer (and with a Mode B-only
   tracer) the Mode A lowering is bit-identical to an obs-less build
   (hook monkeypatched out structurally); a ``mode_a`` tracer prices
   exactly one host callback per collective entry.
4. **Metrics surfaces** — retry events and integrity violations land
   in the unified registry next to their historical access paths, the
   serve collector aggregates, and the Prometheus exposition renders.

``make obs-smoke`` runs this on the 8-virtual-device CPU harness.
"""

from __future__ import annotations

import sys


def _fail(failures: list, msg: str) -> None:
    failures.append(msg)
    print(f"FAIL: {msg}")


def _ok(msg: str) -> None:
    print(f"ok  : {msg}")


def _lower(fn, *args):
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu._compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    return jax.jit(shard_map(lambda *a: fn(cm, *a), mesh=mesh,
                             in_specs=P(), out_specs=P(),
                             check_vma=False)).lower(*args)


def _reconcile_case(failures, name, mode_b_body, nranks, lowered):
    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs

    with obs.trace() as t:
        mpi.run_ranks(mode_b_body, nranks)
    rep = obs.reconcile(t.events, lowered, dropped=t.dropped)
    m, p = rep["measured"], rep["predicted"]
    detail = (f"measured {m['wire_bytes']} B {m['counts']} == "
              f"predicted {p['wire_bytes']} B {p['counts']}")
    if rep["ok"]:
        _ok(f"reconcile[{name}]: {detail}")
    else:
        _fail(failures, f"reconcile[{name}]: {detail} "
                        f"(matches={rep['matches']}, consistent="
                        f"{m['per_rank_consistent']}, dropped="
                        f"{rep['dropped_events']})")
    return rep


def _smoke_reconcile(failures) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import COMM_WORLD as comm

    # 1a. plain ring allreduce, 8 ranks.
    x8 = jnp.arange(1024, dtype=jnp.float32)

    def plain(rank):
        return comm.Allreduce(x8 * (rank + 1), mpi.MPI_SUM,
                              algorithm="ring")

    _reconcile_case(
        failures, "ring-allreduce", plain, 8,
        _lower(lambda cm, a: cm.Allreduce(a, mpi.MPI_SUM,
                                          algorithm="ring"), x8))

    # 1b. fused q8 buckets (two buckets; the in-schedule int8+scale
    # pipeline priced through the equivalent lowering).
    def tree_of(rank):
        return {"a": jnp.linspace(-1, 1, 768,
                                  dtype=jnp.float32) * (rank + 1),
                "b": jnp.linspace(-2, 2, 512,
                                  dtype=jnp.float32) * (rank + 1)}

    BB = 2048

    def fused(rank):
        return comm.Allreduce_tree(tree_of(rank), mpi.MPI_SUM,
                                   compression="q8", bucket_bytes=BB)

    _reconcile_case(
        failures, "fused-q8-buckets", fused, 8,
        _lower(lambda cm, tr: cm.Allreduce_tree(
            tr, mpi.MPI_SUM, compression="q8", bucket_bytes=BB),
            tree_of(0)))

    # 1c. the (8,)->(2,4) checkpoint-migration reshard (the PR 8
    # census shape: planned wire 98304 B vs the 917504 B gather).
    from mpi4torch_tpu import reshard as rs

    fl = rs.layout((8,), 0, None)
    tl = rs.layout((2, 4), 0, 1)
    G = (1024, 256)
    shard_shape = fl.shard_shape(G)

    def migrate(rank):
        x = jnp.arange(int(np.prod(shard_shape)), dtype=jnp.float32
                       ).reshape(shard_shape) * (rank + 1)
        return comm.Reshard(x, fl, tl)

    rep = _reconcile_case(
        failures, "reshard-(8,)->(2,4)", migrate, 8,
        _lower(lambda cm, a: cm.Reshard(a, fl, tl),
               jnp.zeros(shard_shape, jnp.float32)))
    if rep["predicted"]["wire_bytes"] != 98304:
        _fail(failures,
              f"reshard predicted wire {rep['predicted']['wire_bytes']}"
              " != the recorded 98304 B plan")

    # 1d. overlap serve decode step: one traced Mode B engine step per
    # rank (isolated behind a barrier sentinel) vs the Mode A
    # engine.lower_step() census.
    from mpi4torch_tpu import serve
    from mpi4torch_tpu.models import transformer as T
    from mpi4torch_tpu.runtime import current_rank_context

    cfg = T.TransformerConfig(vocab=61, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64, max_seq=32)
    params = T.init_transformer(jax.random.PRNGKey(0), cfg,
                                dtype=jnp.float32)
    prompts = [np.array([1, 2, 3]), np.array([4, 5, 6, 7, 8])]
    nranks = 4

    from mpi4torch_tpu import obs

    with obs.trace() as t:
        def body(rank):
            ctx = current_rank_context()
            eng = serve.Engine(cfg, params,
                               serve.ServeConfig(slots=2, overlap=True))
            for p in prompts:
                eng.submit(p, max_new=3)
            eng.step()                     # admission + prefill + decode
            ctx.world.barrier(ctx.rank)    # sentinel: next step isolated
            eng.step()
            return True
        mpi.run_ranks(body, nranks)

    decode = []
    for r in range(nranks):
        er = t.events_for(rank=r)
        cut = max(i for i, e in enumerate(er) if e.op == "Barrier")
        decode.extend(er[cut + 1:])

    eng_a = serve.Engine(cfg, params,
                         serve.ServeConfig(slots=2, overlap=True),
                         spmd=True, nranks=nranks)
    eng_a.submit(prompts[0], max_new=3)
    eng_a.step()
    rep = obs.reconcile(decode, eng_a.lower_step(), dropped=t.dropped)
    m, p = rep["measured"], rep["predicted"]
    detail = (f"measured {m['wire_bytes']} B {m['counts']} == "
              f"predicted {p['wire_bytes']} B {p['counts']}, "
              f"exposure {p['scheduled_exposure']}")
    if rep["ok"] and p["scheduled_exposure"] == 0.0:
        _ok(f"reconcile[serve-decode-step]: {detail}")
    else:
        _fail(failures, f"reconcile[serve-decode-step]: {detail} "
                        f"(matches={rep['matches']})")
    serve.reset_stats()


def _smoke_flight(failures, workdir) -> None:
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import COMM_WORLD as comm, obs
    from mpi4torch_tpu.obs.flight import last_event_signature
    from mpi4torch_tpu.resilience import fault_scope

    nranks, dead = 4, 1
    spec = mpi.FaultSpec("rank_death", rank=dead, op="Allreduce", index=2)
    err = None
    with obs.trace(ring=16) as t:
        with fault_scope([spec]):
            def body(rank):
                x = jnp.arange(64, dtype=jnp.float32) * (rank + 1)
                for _ in range(4):
                    x = comm.Allreduce(x, mpi.MPI_SUM)
                return x
            try:
                mpi.run_ranks(body, nranks, timeout=2.0)
            except mpi.RankFailedError as e:
                err = e
    if err is None:
        return _fail(failures, "flight: injected rank_death was not "
                               "raised as RankFailedError")
    pm = t.last_postmortem()
    if pm is None:
        return _fail(failures, "flight: no postmortem captured")
    if pm["failed_ranks"] != [dead]:
        return _fail(failures, f"flight: postmortem names "
                               f"{pm['failed_ranks']}, not [{dead}]")
    dead_sig = last_event_signature(pm, dead)
    bad = [r for r in range(nranks)
           if last_event_signature(pm, r) != dead_sig]
    if dead_sig is None or bad:
        return _fail(failures,
                     f"flight: survivor tails inconsistent with the "
                     f"dead rank's last event (ranks {bad})")
    paths = obs.dump_postmortem(pm, workdir)
    text = obs.format_postmortem(pm)
    if f"rank(s): [{dead}]" not in text:
        return _fail(failures, "flight: human table does not name the "
                               "dead rank")
    _ok(f"flight: rank_death postmortem names rank {dead}; all "
        f"{nranks} tails end on the torn collective "
        f"{dead_sig}; dumped {paths['json']}")
    # The timeline export renders the same trace.
    import json
    import os

    tpath = obs.write_chrome_trace(
        os.path.join(workdir, "modeb_trace.json"), t.events)
    with open(tpath, encoding="utf-8") as f:
        n = len(json.load(f)["traceEvents"])
    _ok(f"export: chrome/Perfetto trace with {n} events at {tpath}")


def _smoke_offpath(failures) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs
    from mpi4torch_tpu._compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    x = jnp.ones((1 << 12,), jnp.float32)

    def lowered(compression=False):
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM,
                                   compression=compression),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(x).as_text()

    text_off = lowered()
    text_off_q8 = lowered("q8")
    hook = obs.tracing.spmd_collective_event
    try:
        obs.tracing.spmd_collective_event = lambda v, where: v
        same = (lowered() == text_off and lowered("q8") == text_off_q8)
    finally:
        obs.tracing.spmd_collective_event = hook
    if not same:
        _fail(failures, "off-path: obs-disabled lowering differs from "
                        "the obs-less build")
    else:
        _ok("off-path: obs-disabled lowering bit-identical to the "
            "obs-less build (plain + q8)")

    with obs.trace():            # Mode B-only tracer: must not move A
        moved = lowered() != text_off
    if moved:
        _fail(failures, "off-path: a Mode B-only tracer moved the "
                        "Mode A lowering")
    else:
        _ok("off-path: Mode B-only tracer leaves the Mode A lowering "
            "untouched")

    with obs.trace(mode_a=True):
        delta = (lowered().count("stablehlo.custom_call")
                 - text_off.count("stablehlo.custom_call"))
    if delta != 1:
        _fail(failures, f"off-path: mode_a tracer priced {delta} "
                        "custom_calls per collective entry, expected 1")
    else:
        _ok("off-path: mode_a tracer prices exactly 1 host callback "
            "per collective entry")


def _smoke_metrics(failures) -> None:
    import jax.numpy as jnp

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import COMM_WORLD as comm, config, obs
    from mpi4torch_tpu.resilience import fault_scope, guards

    obs.reset_metrics()
    # Retry surfacing: a dropped p2p message recovered by retries must
    # land in BOTH the historical World.retry_events attribute and the
    # unified counter.
    spec = mpi.FaultSpec("drop_p2p", rank=0, op="p2p", index=0)
    retry_events = []
    config.set_comm_retries(4)
    config.set_comm_backoff(0.05)
    try:
        with obs.trace():
            def body(rank):
                from mpi4torch_tpu.runtime import current_rank_context
                ctx = current_rank_context()
                if rank == 0:
                    ctx.world.p2p_send(0, 1, 7, jnp.ones(4))
                if rank == 1:
                    got = ctx.world.p2p_recv(0, 1, 7)
                    retry_events.append(ctx.world.retry_events)
                    return got
                return None
            with fault_scope([spec]):
                mpi.run_ranks(body, 2, timeout=0.3)
    finally:
        config.set_comm_retries(0)
        config.set_comm_backoff(0.05)
    counters = obs.snapshot()["counters"]
    if not retry_events or retry_events[0] < 1:
        _fail(failures, "metrics: dropped p2p was not recovered via "
                        "retries (World.retry_events stayed 0)")
    elif counters.get("comm_retry_events_total", 0) < 1:
        _fail(failures, "metrics: comm_retry_events_total missing from "
                        f"the registry (counters={counters})")
    else:
        _ok(f"metrics: retry_events={retry_events[0]} mirrored as "
            f"comm_retry_events_total="
            f"{counters['comm_retry_events_total']}")

    # Integrity-violation surfacing next to the historical ledger.
    guards.clear_violations()
    config.set_comm_finite_guard("warn")
    try:
        import warnings

        def nan_body(rank):
            x = jnp.full(4, float("nan") if rank == 1 else 1.0)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                return comm.Allreduce(x, mpi.MPI_SUM)
        mpi.run_ranks(nan_body, 2)
    finally:
        config.set_comm_finite_guard("off")
    viol = guards.last_violation()
    counters = obs.snapshot()["counters"]
    if viol is None or counters.get("integrity_violations_total", 0) < 1:
        _fail(failures, "metrics: finite-guard violation not mirrored "
                        f"(ledger={viol}, counters={counters})")
    else:
        _ok("metrics: integrity violation in ledger AND "
            "integrity_violations_total="
            f"{counters['integrity_violations_total']}")
        guards.clear_violations()

    # Prometheus text renders the namespace.
    text = obs.prometheus_text()
    if "mpi4torch_comm_retry_events_total" not in text \
            or "mpi4torch_serve_" not in text:
        _fail(failures, "metrics: prometheus exposition missing "
                        "namespaced families")
    else:
        _ok("metrics: prometheus exposition carries the mpi4torch_* "
            "namespace (comm + serve families)")


def _smoke() -> int:
    import tempfile

    import jax

    print(f"obs-smoke: {len(jax.devices())} device(s), platform "
          f"{jax.devices()[0].platform}")
    failures: list = []
    _smoke_reconcile(failures)
    with tempfile.TemporaryDirectory() as d:
        _smoke_flight(failures, d)
    _smoke_offpath(failures)
    _smoke_metrics(failures)
    verdict = (f"FAIL — {len(failures)} problem(s)" if failures
               else "all verdicts exact")
    print(f"obs-smoke: {verdict}")
    return 1 if failures else 0


def main(argv) -> int:
    if "--smoke" in argv:
        return _smoke()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Static-vs-runtime reconciliation: the traced Mode B wire against the
``analyze`` predictions of the matching Mode A lowering.

The repo's perf-evidence currency is deterministic estimators read off
the lowering (wire bytes, op counts, scheduled exposure — ROADMAP).
This module closes the loop at runtime: :func:`reconcile` joins what
the Mode B chokepoints *measured* against what
:func:`mpi4torch_tpu.analyze.wire_bytes_per_device` *predicts* for the
equivalent Mode A program, and the match is EXACT, not statistical —
Mode B payload bytes are censused at the rendezvous, never sampled.

The join speaks the analyzer's language.  Every modeled Mode B logical
collective is converted to the per-device wire bytes and StableHLO
collective-kind counts its Mode A execution would census:

* uncompressed ring-path collectives use THE shared accounting formula
  (:func:`mpi4torch_tpu.analyze.wire_contribution` — one definition for
  the static pass and the runtime conversion) with a 1:1 logical→HLO
  count (an Allreduce is one ``all_reduce``, a reshard permute step one
  ``collective_permute``, ...);
* compressed or non-ring allreduce events carry their codec/algorithm
  labels in the rendezvous signature, and their conversion **lowers the
  equivalent single collective** (same shape/dtype/codec/algorithm/
  world) and censuses it with the same ``analyze`` pass — so the
  in-schedule q8 pipeline's int8+scale permute schedule is priced
  exactly, not modeled approximately.

``reconcile(events, lowered)`` then asserts two exact equalities:
total per-device wire bytes, and the per-kind collective counts.  A
passing report proves the runtime executed exactly the collectives the
static analysis predicts — no extra rendezvous, none missing, none
resized, the codec really on the wire.  It is a CI-checkable contract
(``make obs-smoke``), not a dashboard.

Caveats the report is explicit about: fold-once shares and barriers are
*bookkeeping* (thread-rendezvous artifacts with no Mode A wire op) and
are excluded but counted; root/varying-shape collectives (``Bcast_``,
``Gather``, ...) and raw p2p traffic are listed as *unmodeled* rather
than silently mispriced; exact byte equality needs payloads divisible
by the replica-group size (the fractional accountings round once on
each side).  ``scheduled_exposure`` of the lowering rides along in the
prediction section — exposure is a static schedule property with no
Mode B analogue (the rendezvous is blocking by construction).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["measured_wire_table", "reconcile", "equivalent_wire",
           "equivalent_tier_wire"]


# Cache of equivalent single-collective censuses, keyed by the logical
# signature (head, shape, dtype, codec, algorithm, world size).
_equiv_cache: Dict[tuple, Tuple[int, Dict[str, int]]] = {}

# Cache of equivalent lowerings' StableHLO text under the same keying —
# the tier breakdown (:func:`equivalent_tier_wire`) re-censuses the SAME
# text per tier stack instead of re-lowering.
_equiv_text_cache: Dict[tuple, str] = {}
_equiv_tier_cache: Dict[tuple, List[int]] = {}


# The heads the equivalent-lowering census can reproduce (their
# signatures carry the full shape/dtype and the facade call is a plain
# Allreduce); anything else that cannot take the formula path is
# classified unmodeled upstream (events._UNMODELED_HEADS), never
# crashed on.
_EQUIV_HEADS = ("Allreduce", "Allreduce.q8hop", "Allreduce.c")


def _needs_equivalent_lowering(ev) -> bool:
    if ev.op not in _EQUIV_HEADS:
        return False
    return (ev.codec is not None
            or ev.algorithm not in (None, "auto", "ring"))


def _equiv_key(ev) -> tuple:
    from .. import config as _config

    # The equivalent lowering depends on the same trace-time knobs the
    # jit cache keys on (quant hop impl, ring chunk bytes, hier group,
    # tier stack, ...) — fold the fingerprint in so a config change
    # never serves a stale census.
    return (ev.op, tuple(ev.shape or ()), ev.dtype, ev.codec,
            ev.algorithm, ev.world_size,
            _config.thresholds_fingerprint())


def _equivalent_text(ev, key: tuple) -> str:
    """StableHLO text of the Mode A lowering equivalent to one Mode B
    collective event (same facade call — shape, dtype, codec, algorithm
    — over an ``ev.world_size``-device mesh); cached per logical
    signature so the total census and every tier breakdown re-census
    ONE lowering.  Needs >= ``world_size`` local (virtual) devices."""
    got = _equiv_text_cache.get(key)
    if got is not None:
        return got
    if ev.shape is None or ev.dtype is None:
        raise ValueError(
            f"event {ev.op} carries no shape/dtype signature — cannot "
            "lower its equivalent collective")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import mpi4torch_tpu as mpi
    from .._compat import shard_map

    n = ev.world_size
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"equivalent lowering of a {n}-rank collective needs {n} "
            f"local devices; have {len(devs)} (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    mesh = Mesh(np.asarray(devs[:n]), ("obs_w",))
    cm = mpi.comm_from_mesh(mesh, "obs_w")
    codec = ev.codec if ev.codec is not None else False
    algo = None if ev.algorithm in (None, "auto") else ev.algorithm
    x = jnp.zeros(tuple(ev.shape), jnp.dtype(ev.dtype))

    def prog(v):
        return cm.Allreduce(v, mpi.MPI_SUM, compression=codec,
                            algorithm=algo)

    lowered = jax.jit(shard_map(prog, mesh=mesh, in_specs=P(),
                                out_specs=P(), check_vma=False)).lower(x)
    text = lowered.as_text()
    _equiv_text_cache[key] = text
    return text


def equivalent_wire(ev) -> Tuple[int, Dict[str, int]]:
    """Per-device wire bytes and collective-kind counts of the Mode A
    lowering equivalent to one Mode B collective event, censused with
    :func:`analyze.wire_bytes_per_device`.  Cached per logical
    signature; needs >= ``world_size`` local (virtual) devices."""
    from .. import analyze

    key = _equiv_key(ev)
    got = _equiv_cache.get(key)
    if got is not None:
        return got
    got = analyze.wire_bytes_per_device(_equivalent_text(ev, key))
    _equiv_cache[key] = got
    return got


def equivalent_tier_wire(ev, tiers) -> List[int]:
    """Per-tier wire bytes of the equivalent Mode A lowering of one
    Mode B collective event — :func:`analyze.tier_wire_table` over the
    SAME cached lowering text :func:`equivalent_wire` censuses, so the
    tier breakdown can only split the total, never disagree with it.
    This is how grouped/compressed schedules (hier, tier-stack folds,
    q8 pipelines) get their per-tier traffic priced EXACTLY: from the
    replica groups of the actual lowering, not a formula."""
    from .. import analyze

    tiers = tuple(int(g) for g in tiers)
    key = _equiv_key(ev) + (tiers,)
    got = _equiv_tier_cache.get(key)
    if got is not None:
        return got
    got = analyze.tier_wire_table(_equivalent_text(ev, key[:-1]), tiers)
    _equiv_tier_cache[key] = got
    return got


def _split_phase_start(ev) -> bool:
    """True when the event ran inside a split-phase ``.start`` bucket
    scope (the eager ``Allreduce_start`` runs its blocking rendezvous
    within the start span, carried into the event's bucket label by the
    tracer's label stack)."""
    if not ev.bucket:
        return False
    from ..analyze.parse import bucket_of

    b = bucket_of(ev.bucket)
    return b is not None and b[3] == "start"


def _formula_row(ev) -> Tuple[float, Dict[str, int]]:
    from ..analyze import wire_contribution

    s = ev.group_size if ev.group_size else ev.world_size
    if ev.family == "all_reduce" and _split_phase_start(ev):
        # A split-phase allreduce lowers in Mode A as the explicit
        # reduce_scatter + all_gather PAIR (start issues the RS, Wait
        # completes the AG) — same total wire, two ops in the census.
        return (wire_contribution("reduce_scatter", ev.payload_bytes, s)
                + wire_contribution("all_gather", ev.payload_bytes / s,
                                    s),
                {"reduce_scatter": 1, "all_gather": 1})
    return (wire_contribution(ev.family, ev.payload_bytes, s),
            {ev.family: 1})


def _formula_tier(ev, tiers: tuple) -> int:
    """Tier of a formula-priced event: formula rows are plain ring-path
    collectives whose replica group is a contiguous run of ranks, so a
    group size matching the product of the first j tier factors spans
    exactly tiers 0..j-1 (top differing digit j-1); anything else —
    including the whole world — crosses the top tier.  Grouped schedules
    whose groups are NOT contiguous runs (hier's strided inter-group
    stage, tier-stack folds) never take this path: their algorithm label
    routes them through the equivalent lowering, where the tier comes
    from the actual replica groups."""
    s = ev.group_size if ev.group_size else ev.world_size
    p = 1
    for j, g in enumerate(tiers):
        p *= g
        if s == p:
            return j
    return len(tiers) - 1


def measured_wire_table(events: Iterable, rank: Optional[int] = None,
                        tiers=None) -> dict:
    """Convert a Mode B event stream into the analyzer's census
    vocabulary: per-device wire bytes + per-kind collective counts.

    Uses ONE rank's events (``rank=None`` = the lowest rank present —
    wire accountings are per device) after checking every rank recorded
    the SAME logical collective sequence (op, family, bytes, group) —
    the determinism property that makes the census a contract.  Returns
    ``{"wire_bytes", "counts", "logical_events", "by_op",
    "per_rank_consistent", "excluded"}``; with a tier stack ``tiers``
    the report additionally carries ``"tier_wire"`` — the per-tier
    split of ``wire_bytes`` (equivalent-lowering rows read their tiers
    from the actual replica groups via :func:`equivalent_tier_wire`,
    formula rows from the contiguous-run rule), summing to the total
    exactly."""
    events = list(events)
    evs = [e for e in events if e.channel == "exchange"]
    ranks = sorted({e.rank for e in evs})
    n_spmd = sum(1 for e in events if e.channel == "spmd")

    def logical(seq):
        """Side-effect-free filter: the modeled, completed logical
        collectives of one rank's event sequence."""
        return [e for e in seq
                if e.status == "ok" and not e.bookkeeping
                and e.family is not None and not e.unmodeled]

    per_rank = {r: logical([e for e in evs if e.rank == r])
                for r in ranks}
    use = (rank if rank is not None else ranks[0]) if ranks else None
    rows = per_rank.get(use, [])

    # Exclusion accounting for the selected rank only (symmetric when
    # the consistency check below holds), except p2p and Mode A spmd
    # step events, which are reported trace-wide (p2p is inherently
    # asymmetric; spmd events have no rank) — EVERY dropped event
    # class is counted, never silently filtered.
    excluded = {"bookkeeping": 0, "errors": 0, "unmodeled": {},
                "p2p": sum(1 for e in events
                           if e.channel in ("p2p_send", "p2p_recv")),
                "spmd": n_spmd}
    for e in evs:
        if e.rank != use:
            continue
        if e.status != "ok":
            excluded["errors"] += 1
        elif e.unmodeled:
            excluded["unmodeled"][e.op] = \
                excluded["unmodeled"].get(e.op, 0) + 1
        elif e.bookkeeping or e.family is None:
            excluded["bookkeeping"] += 1

    def fingerprint(seq):
        return [(e.op, e.family, e.payload_bytes, e.group_size,
                 e.algorithm, e.codec, e.bucket) for e in seq]

    consistent = len({tuple(fingerprint(v)) for v in per_rank.values()}
                     ) <= 1

    tiers = tuple(int(g) for g in tiers) if tiers is not None else None
    tier_wire = [0.0] * len(tiers) if tiers is not None else None
    wire = 0.0
    counts: Dict[str, int] = {}
    by_op: Dict[str, dict] = {}
    for e in rows:
        if _needs_equivalent_lowering(e):
            b, c = equivalent_wire(e)
            if tiers is not None:
                for level, tw in enumerate(equivalent_tier_wire(e, tiers)):
                    tier_wire[level] += tw
        else:
            b, c = _formula_row(e)
            if tiers is not None:
                tier_wire[_formula_tier(e, tiers)] += b
        wire += b
        for k, v in c.items():
            counts[k] = counts.get(k, 0) + v
        slot = by_op.setdefault(e.op, {"events": 0, "wire_bytes": 0.0,
                                       "payload_bytes": 0})
        slot["events"] += 1
        slot["wire_bytes"] += b
        slot["payload_bytes"] += e.payload_bytes
    for slot in by_op.values():
        slot["wire_bytes"] = int(round(slot["wire_bytes"]))
    out = {
        "rank": use,
        "wire_bytes": int(round(wire)),
        "counts": counts,
        "logical_events": len(rows),
        "by_op": by_op,
        "per_rank_consistent": consistent,
        "ranks": ranks,
        "excluded": excluded,
    }
    if tiers is not None:
        out["tiers"] = list(tiers)
        out["tier_wire"] = [int(round(w)) for w in tier_wire]
    return out


def reconcile(events_or_tracer, lowered_or_text,
              rank: Optional[int] = None,
              dropped: Optional[int] = None, tiers=None) -> dict:
    """Join a traced Mode B event stream against the ``analyze``
    predictions of the matching Mode A lowering.

    ``events_or_tracer`` is the :class:`~.trace.CommTracer` itself
    (preferred — its ``dropped`` count is read automatically, so a
    truncated trace can never reconcile by omission) or a plain event
    list (then pass ``dropped=tracer.dropped`` yourself; it defaults
    to 0 only for event lists that never lived in a bounded tracer).

    Returns a report whose ``ok`` is True iff (1) every rank recorded
    the same logical collective sequence, (2) the measured per-device
    wire bytes equal :func:`analyze.wire_bytes_per_device` of the
    lowering EXACTLY, (3) the measured per-kind collective counts equal
    the parse's counts exactly, and (4) the tracer dropped nothing
    (a truncated census is not a census).  With a tier stack ``tiers``
    (innermost first) the join additionally prices per-tier traffic —
    measured (:func:`measured_wire_table` with ``tiers=``) against
    predicted (:func:`analyze.tier_wire_table` of the lowering) — and
    ``matches["tier_wire"]`` demands the split match EXACTLY too: the
    runtime put its bytes on the tiers the static census says, not just
    the right total.  See the module docstring for what is excluded and
    why."""
    from .. import analyze

    events = events_or_tracer
    if hasattr(events, "events") and hasattr(events, "dropped"):
        if dropped is None:
            dropped = events.dropped
        events = events.events
    if dropped is None:
        dropped = 0
    measured = measured_wire_table(events, rank=rank, tiers=tiers)
    pred_bytes, pred_counts = analyze.wire_bytes_per_device(
        lowered_or_text)
    try:
        exposure = analyze.scheduled_exposure(lowered_or_text)
    except Exception:  # noqa: BLE001 — exposure is advisory here
        exposure = None
    matches = {
        "wire_bytes": measured["wire_bytes"] == pred_bytes,
        "counts": measured["counts"] == pred_counts,
    }
    predicted = {
        "wire_bytes": pred_bytes,
        "counts": pred_counts,
        "scheduled_exposure": (exposure or {}).get(
            "exposed_fraction") if exposure else None,
    }
    if tiers is not None:
        predicted["tier_wire"] = analyze.tier_wire_table(
            lowered_or_text, tiers)
        matches["tier_wire"] = (measured["tier_wire"]
                                == predicted["tier_wire"])
    report = {
        "measured": measured,
        "predicted": predicted,
        "matches": matches,
        "dropped_events": int(dropped),
        "ok": bool(all(matches.values())
                   and measured["per_rank_consistent"]
                   and not dropped),
    }
    return report

"""The failure flight recorder: "what were the last N wire operations
on each rank when it died?"

The tracer keeps a bounded per-(world, rank) ring of recent
:class:`~.events.CommEvent` records; the moment a chokepoint raises one
of the attributed failure classes (``RankFailedError`` /
``DeadlockError`` / ``IntegrityError``), the first raising rank's
commit snapshots every rank's ring into a **postmortem**: the error
type and message, the failed/missing rank attribution the error already
carries (PR 7), and each rank's event tail — newest last, so the final
row of each rank's table is the operation it died in (or the last one
it completed before a peer tore the world down).

Two renderings: :func:`build_postmortem` (the JSON-friendly dict the
tracer stores, dumpable via :func:`dump_postmortem`) and
:func:`format_postmortem` (the human table).  The tail-consistency
property the fault matrix asserts: survivors of a ``rank_death`` all
end on the same collective signature the dead rank's tail ends on —
every participant of the torn collective recorded it before dying or
raising.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

__all__ = [
    "build_postmortem",
    "format_postmortem",
    "dump_postmortem",
]


def _error_ranks(error) -> List[int]:
    ranks = getattr(error, "ranks", None)
    if ranks:
        return sorted(ranks)
    missing = getattr(error, "missing", None)
    return sorted(missing) if missing else []


def build_postmortem(tracer, ev, error) -> dict:
    """Snapshot the tracer's ring state for ``ev.world`` into a
    postmortem dict (caller holds the tracer lock — first failing
    commit wins; see ``CommTracer._note_failure``)."""
    tails = {}
    for (world, rank), ring in tracer._rings.items():
        if world == ev.world:
            tails[rank] = [e.to_dict() for e in ring]
    return {
        "error": type(error).__name__,
        "message": str(error),
        "failed_ranks": _error_ranks(error),
        "first_observer_rank": ev.rank,
        "observers": 1,
        "observer_ranks": [ev.rank],
        "world": ev.world,
        "world_size": ev.world_size,
        "ring": tracer.ring,
        "tails": tails,
    }


def format_postmortem(pm: dict, width: int = 78) -> str:
    """Human table of a postmortem: header (error, attribution), then
    one section per rank with its event tail, newest last."""
    lines = [
        "=" * width,
        f"FLIGHT RECORDER POSTMORTEM — {pm['error']}",
        f"  failed/missing rank(s): {pm['failed_ranks'] or 'unattributed'}"
        f"   (first observed on rank {pm['first_observer_rank']}, "
        f"{pm['observers']} observer(s))",
        f"  world size {pm['world_size']}, last {pm['ring']} events/rank",
        f"  {pm['message'][:2 * width]}",
        "=" * width,
    ]
    header = (f"  {'seq':>6} {'channel':<9} {'op':<22} {'bytes':>10} "
              f"{'ms':>8} {'retries':>7} status")
    for rank in sorted(pm["tails"]):
        dead = rank in pm["failed_ranks"]
        lines.append(f"rank {rank}"
                     + ("   ** FAILED/MISSING **" if dead else ""))
        lines.append(header)
        for e in pm["tails"][rank]:
            lines.append(
                f"  {e['seq']:>6} {e['channel']:<9} {e['op']:<22} "
                f"{e['payload_bytes']:>10} "
                f"{e['duration_s'] * 1e3:>8.2f} {e['retries']:>7} "
                f"{e['status']}")
        if not pm["tails"][rank]:
            lines.append("  (no events recorded)")
    lines.append("=" * width)
    return "\n".join(lines)


def dump_postmortem(pm: dict, directory: str,
                    stem: str = "postmortem") -> dict:
    """Write a postmortem as ``<stem>.json`` + the human ``<stem>.txt``
    table under ``directory`` (created if needed); returns the two
    paths."""
    os.makedirs(directory, exist_ok=True)
    jpath = os.path.join(directory, f"{stem}.json")
    tpath = os.path.join(directory, f"{stem}.txt")
    with open(jpath, "w", encoding="utf-8") as f:
        json.dump(pm, f, indent=1, sort_keys=True)
    with open(tpath, "w", encoding="utf-8") as f:
        f.write(format_postmortem(pm) + "\n")
    return {"json": jpath, "table": tpath}


def last_event_signature(pm: dict, rank: int) -> Optional[str]:
    """The signature repr of ``rank``'s newest tail event (or None) —
    what the tail-consistency check compares across survivors."""
    tail = pm["tails"].get(rank) or []
    return tail[-1]["signature"] if tail else None

"""Typed comm-event records — the unit of runtime observability.

A :class:`CommEvent` is one operation observed at a Mode B chokepoint
(``World.exchange`` or the p2p mailboxes — the PR 7 discipline: every
subsystem's traffic funnels through those two sites, so one record type
covers plain / fused / compressed / overlap / reshard / serve traffic
with zero per-subsystem hooks) or one Mode A collective entry reported
by the named-scope/host-callback hook (:func:`..obs.trace.
spmd_collective_event`, the ``spmd_finite_value`` precedent).

The *annotation* layer lives here too: :func:`annotate_signature` reads
the eager rendezvous signature grammar (the tuples every call site
already deposits — ``("Allreduce", op, algo, (shape, dtype))``,
``("Allreduce.q8hop", codec, algo, reverse, sig)``,
``("Reshard.alltoall", step, group, shape, dtype)``, ...) into the
logical fields reconciliation needs: the wire *family* (which StableHLO
collective kind this rendezvous is the Mode B execution of), the
algorithm/codec labels, the replica-group size, and whether the event
is *bookkeeping* (fold-result shares, barriers — rendezvous rounds that
correspond to no Mode A wire op; see doc/observability.md for the
event schema table).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

__all__ = [
    "CommEvent",
    "FAMILY_OF",
    "annotate_signature",
    "payload_nbytes",
]


def payload_nbytes(payload: Any) -> int:
    """Total bytes of the array leaves of a rendezvous payload pytree
    (ints/None/strings in the meta carry no wire bytes).  Host-side and
    concrete by construction — Mode B payloads are concrete arrays at
    the chokepoint, so bytes are CENSUSED, not sampled."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(payload)
    except Exception:       # jax unavailable mid-teardown: best effort
        leaves = [payload]
    total = 0
    for leaf in leaves:
        n = getattr(leaf, "nbytes", None)
        if n is None:
            size = getattr(leaf, "size", None)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize",
                               None)
            n = size * itemsize if size is not None and itemsize else 0
        total += int(n)
    return total


# Signature-head -> wire family: which StableHLO collective kind the
# rendezvous is the Mode B execution of (analyze.COLLECTIVE_KINDS
# vocabulary, so the reconcile join speaks one language).  Heads absent
# here are reported as "unmodeled" by the reconciler rather than
# silently priced wrong; ``None`` marks bookkeeping rounds.
FAMILY_OF = {
    "Allreduce": "all_reduce",
    "Allreduce.q8hop": "all_reduce",
    "Allreduce.c": "all_reduce",
    "Allgather": "all_gather",
    "Allgather.c": "all_gather",
    # The eager Allgather backward ships the full upstream gradient and
    # every rank folds its own segment — a reduce-scatter, exactly the
    # psum_scatter its Mode A adjoint lowers to (and vice versa).
    "Allgather.bwd": "reduce_scatter",
    "Allgather.c.bwd": "reduce_scatter",
    "Reduce_scatter": "reduce_scatter",
    "Reduce_scatter.bwd": "all_gather",
    "Reshard.permute": "collective_permute",
    "Reshard.alltoall": "all_to_all",
    "Reshard.allgather": "all_gather",
    "Reshard.reduce_scatter": "reduce_scatter",
    # Bookkeeping rounds: fold-once result shares and barriers move no
    # Mode A wire bytes (in MPI terms: they are artifacts of the thread
    # rendezvous, not of the collective's wire schedule).
    "Allreduce.fold": None,
    "Allreduce.c.fold": None,
    "Barrier": None,
}

# Heads the reconciler lists as unmodeled instead of pricing: the
# root/varying-shape collectives have no single standard accounting
# row, and the compressed rendezvous-codec Allgather forms carry
# encoded wire bytes whose Mode A census (separate payload + meta
# gathers) cannot be reproduced from the event alone — traced and
# flight-recorded like everything else, excluded from the strict join
# (doc/observability.md documents the gap).
_UNMODELED_HEADS = ("Bcast_", "Bcast_.bwd", "Reduce_", "Reduce_.bwd",
                    "Gather", "Scatter", "Allgather.c",
                    "Allgather.c.bwd")

# Where each head keeps its (shape, dtype) signature element / labels.
_SHAPE_AT = {"Allreduce": 3, "Allreduce.q8hop": 4, "Allreduce.c": 3,
             "Allgather.bwd": 2, "Reduce_scatter": 3,
             "Reduce_scatter.bwd": 2}
_ALGO_AT = {"Allreduce": 2, "Allreduce.q8hop": 2}
_CODEC_AT = {"Allreduce.q8hop": 1, "Allreduce.c": 1, "Allgather.c": 1,
             "Allgather.c.bwd": 1}


def annotate_signature(signature) -> dict:
    """Logical annotation of a rendezvous signature tuple: ``op`` (the
    head), ``family`` (wire kind or None for bookkeeping), ``shape`` /
    ``dtype`` (when the grammar carries them), ``algorithm`` /
    ``codec`` labels, ``group_size`` (reshard grouped steps; None =
    whole communicator), and ``bookkeeping``."""
    if not isinstance(signature, tuple) or not signature \
            or not isinstance(signature[0], str):
        return {"op": repr(signature), "family": None,
                "bookkeeping": False, "unmodeled": True}
    head = signature[0]
    out: dict = {"op": head, "unmodeled": head in _UNMODELED_HEADS}
    family = FAMILY_OF.get(head)
    # A trailing "fold" (the hop-oracle / fold-once share rendezvous)
    # marks bookkeeping regardless of head; a trailing "crc" is the
    # checksummed WIRE exchange, still the real transfer.
    bookkeeping = (family is None and head in FAMILY_OF) \
        or (len(signature) > 1 and signature[-1] == "fold")
    out["family"] = None if bookkeeping else family
    out["bookkeeping"] = bookkeeping
    idx = _SHAPE_AT.get(head)
    if idx is not None and len(signature) > idx:
        sig = signature[idx]
        if isinstance(sig, tuple) and len(sig) == 2:
            out["shape"], out["dtype"] = sig
    if head.startswith("Reshard.") and len(signature) >= 5:
        out["group_size"] = signature[2]
        out["shape"], out["dtype"] = signature[3], signature[4]
    idx = _ALGO_AT.get(head)
    if idx is not None and len(signature) > idx:
        out["algorithm"] = signature[idx]
    idx = _CODEC_AT.get(head)
    if idx is not None and len(signature) > idx:
        out["codec"] = signature[idx]
    return out


@dataclass(frozen=True)
class CommEvent:
    """One observed communication operation.

    ``channel`` is the chokepoint: ``"exchange"`` (the rendezvous
    collective site), ``"p2p_send"``/``"p2p_recv"`` (the mailboxes), or
    ``"spmd"`` (a Mode A collective entry reported by the host
    callback).  ``payload_bytes`` is the censused byte count of what
    actually crossed the chokepoint (for compressed wires: the encoded
    bytes).  ``retries`` counts the retry extensions THIS wait consumed
    (the per-waiter semantics of ``World.retry_events``).  ``status``
    is ``"ok"`` or the raised error's class name — the flight
    recorder's rank-attributed tail is built from these."""

    seq: int
    rank: int
    world: int                       # tracer-assigned world ordinal
    world_size: int
    channel: str
    op: str
    signature: Tuple = ()
    payload_bytes: int = 0
    duration_s: float = 0.0
    # Time this rank spent BLOCKED on peers at the rendezvous barrier —
    # duration_s minus wait_s is the rank's own pre-barrier (local)
    # latency, the gray-failure detector's attribution signal
    # (mpi4torch_tpu.resilience.health): a slow rank shows high local
    # time and near-zero wait while every peer shows the inverse.
    wait_s: float = 0.0
    t_start: float = 0.0
    retries: int = 0
    status: str = "ok"
    family: Optional[str] = None     # wire kind, None = bookkeeping/n.a.
    bookkeeping: bool = False
    unmodeled: bool = False
    algorithm: Optional[str] = None
    codec: Optional[str] = None
    bucket: Optional[str] = None     # innermost bucket/step label scope
    group_size: Optional[int] = None  # replica group (None = world)
    shape: Optional[Tuple] = None
    dtype: Optional[str] = None
    peer: Optional[int] = None       # p2p destination/source
    tag: Optional[int] = None
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        """JSON-friendly form (signature repr'd; used by the flight
        recorder dump and the Chrome-trace exporter)."""
        d = {k: getattr(self, k) for k in (
            "seq", "rank", "world", "world_size", "channel", "op",
            "payload_bytes", "duration_s", "wait_s", "t_start",
            "retries", "status", "family", "bookkeeping", "algorithm",
            "codec", "bucket", "group_size", "peer", "tag")}
        d["signature"] = repr(self.signature)
        if self.shape is not None:
            d["shape"] = list(self.shape)
            d["dtype"] = self.dtype
        return d

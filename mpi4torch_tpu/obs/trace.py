"""Chokepoint comm tracing: the runtime half of the observability layer.

A :class:`CommTracer` installed via :func:`trace` (lexical) or
``config.set_comm_tracer`` (process-wide) observes every Mode B
communication operation at the two chokepoints all subsystems already
funnel through — ``World.exchange`` and the p2p mailboxes
(runtime.py) — so fused buckets, compressed wires, overlap pipelines,
reshard plans, and serving decode traffic are traced with ZERO
per-subsystem hooks (the PR 7 fault-injection discipline, applied to
observation instead of perturbation).

Off path: one attribute read per chokepoint (``config.comm_tracer()``
returning None), the same zero-overhead contract as the fault plan and
the integrity guards; ``bench._bench_obs_overhead`` censuses that the
obs-off Mode A lowering is bit-identical to an obs-less build.

Mode A coverage: :func:`spmd_collective_event` is a trace-time hook
(the ``spmd_finite_value`` precedent) at the SPMD collective entries —
with tracing off (or ``mode_a=False``) it returns its argument
untouched, adding zero ops; with ``mode_a=True`` it attaches a host
``jax.debug.callback`` that emits one step-level event per executed
collective entry.  The flag rides ``config.thresholds_fingerprint``,
so toggling retraces instead of silently reusing the old lowering.

The tracer also owns the **flight recorder** state: a bounded per-rank
ring of recent events, snapshotted into a rank-attributed postmortem
the moment a chokepoint raises ``RankFailedError`` / ``DeadlockError``
/ ``IntegrityError`` (see :mod:`.flight` for the report format).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from .. import config as _config
from .events import CommEvent, annotate_signature, payload_nbytes

__all__ = [
    "CommTracer",
    "trace",
    "current_tracer",
    "spmd_collective_event",
    "push_label",
    "current_label",
]

# Errors that trigger a flight-recorder postmortem snapshot.  Resolved
# lazily (runtime imports config; importing runtime here at module load
# would be circular through the package __init__).
_FAILURE_TYPES = None


def _failure_types():
    global _FAILURE_TYPES
    if _FAILURE_TYPES is None:
        from ..elastic.membership import ConsensusError
        from ..resilience.health import SlowRankError
        from ..runtime import (DeadlockError, IntegrityError,
                               RankFailedError)
        # ConsensusError rides the same reaper entry point every other
        # attributed failure does (run_ranks routes rank failures to
        # note_rank_failure) — a failed resize gets its flight-recorder
        # postmortem with zero new hooks.  SlowRankError (ISSUE 15)
        # joins the set the same way: a gray-failure escalation raised
        # inside a rank body snapshots a postmortem through the reaper,
        # and a driver-side escalation calls note_gray_failure directly.
        _FAILURE_TYPES = (RankFailedError, DeadlockError, IntegrityError,
                          ConsensusError, SlowRankError)
    return _FAILURE_TYPES


# ------------------------------------------------------- label context

# Thread-local label stack the bucket/step scopes push (see
# utils/profiling.bucket_scope): gives Mode B events their
# bucket/codec/phase label even though jax.named_scope is invisible to
# the eager chokepoints.  Pushed only while a tracer is installed, so
# the scopes stay free when observability is off.
_labels = threading.local()


def push_label(label: str):
    """Context manager pushing ``label`` onto this thread's scope-label
    stack (no-op object when no tracer is installed)."""
    return _LabelCtx(label)


class _LabelCtx:
    __slots__ = ("label", "_pushed")

    def __init__(self, label: str):
        self.label = label
        self._pushed = False

    def __enter__(self):
        if _config.comm_tracer() is not None:
            stack = getattr(_labels, "stack", None)
            if stack is None:
                stack = _labels.stack = []
            stack.append(self.label)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            _labels.stack.pop()
        return False


def current_label() -> Optional[str]:
    """Innermost bucket/step label pushed on this thread, or None."""
    stack = getattr(_labels, "stack", None)
    return stack[-1] if stack else None


class _Meter:
    """Per-operation measurement state handed through the chokepoint:
    the runtime's retry loops add into ``retries`` (the per-waiter
    semantics of ``World.retry_events``), commit computes the wall
    duration."""

    __slots__ = ("tracer", "world_ord", "world_size", "rank", "channel",
                 "signature", "payload_bytes", "peer", "tag", "t0",
                 "retries", "bucket", "wait_s")

    def __init__(self, tracer, world_ord, world_size, rank, channel,
                 signature, payload_bytes, peer, tag):
        self.tracer = tracer
        self.world_ord = world_ord
        self.world_size = world_size
        self.rank = rank
        self.channel = channel
        self.signature = signature
        self.payload_bytes = payload_bytes
        self.peer = peer
        self.tag = tag
        self.bucket = current_label()
        self.retries = 0
        self.wait_s = 0.0
        self.t0 = time.perf_counter()

    def add_retries(self, n: int) -> None:
        self.retries += n

    def add_wait(self, seconds: float) -> None:
        """Barrier-blocked time the runtime reports (both rendezvous
        barriers of an exchange add in) — the gray-failure detector's
        local-vs-wait split (resilience.health)."""
        self.wait_s += seconds


class CommTracer:
    """Thread-safe collector of :class:`CommEvent` records.

    * ``events`` — the global program-order list (bounded by
      ``max_events``; drops-oldest beyond it, counted in ``dropped`` —
      silent truncation would falsify the reconcile census, so the
      reconciler refuses a trace that dropped events).
    * per-``(world, rank)`` ring buffers of the last ``ring`` events —
      the flight recorder's tail state.
    * ``postmortems`` — rank-attributed failure snapshots (first
      failure per world wins; later observers of the same tear
      increment its ``observers`` count instead of re-dumping).
    * ``mode_a`` — whether :func:`spmd_collective_event` instruments
      Mode A lowerings (priced: one host callback per collective
      entry; part of the jit fingerprint).
    """

    def __init__(self, ring: int = 64, max_events: int = 200_000,
                 mode_a: bool = False):
        self.ring = int(ring)
        self.max_events = int(max_events)
        self.mode_a = bool(mode_a)
        # Bounded deque: O(1) drop-oldest past the cap (a list's
        # del [0] would shift the whole buffer under the lock on every
        # event of a long-running traced fleet).
        self.events: collections.deque = collections.deque(
            maxlen=self.max_events)
        self.dropped = 0
        self.postmortems: List[dict] = []
        self._rings: Dict[tuple, collections.deque] = {}
        self._worlds: Dict[int, int] = {}     # id(world) -> ordinal
        self._failed_worlds: Dict[int, int] = {}   # ordinal -> pm index
        self._world_ctr = itertools.count()
        self._seq = itertools.count()
        self._lock = threading.Lock()

    # ----------------------------------------------------------- plumbing

    def _world_ord(self, world) -> int:
        wid = id(world)
        with self._lock:
            got = self._worlds.get(wid)
            if got is None:
                got = self._worlds[wid] = next(self._world_ctr)
            return got

    def begin(self, world, rank: int, channel: str, signature,
              payload=None, peer: Optional[int] = None,
              tag: Optional[int] = None) -> _Meter:
        return _Meter(self, self._world_ord(world), world.size, rank,
                      channel, signature,
                      payload_nbytes(payload) if payload is not None
                      else 0, peer, tag)

    def commit(self, meter: _Meter, result_payload=None,
               error: Optional[BaseException] = None) -> None:
        """Finalize one operation into an event.  ``result_payload``
        (p2p receives) contributes the received bytes; ``error`` marks
        the status and — for the attributed failure classes — triggers
        the flight-recorder postmortem."""
        dur = time.perf_counter() - meter.t0
        if result_payload is not None:
            meter.payload_bytes += payload_nbytes(result_payload)
        ann = annotate_signature(meter.signature)
        ev = CommEvent(
            seq=next(self._seq), rank=meter.rank, world=meter.world_ord,
            world_size=meter.world_size, channel=meter.channel,
            op=ann["op"], signature=(meter.signature if isinstance(
                meter.signature, tuple) else (meter.signature,)),
            payload_bytes=meter.payload_bytes, duration_s=dur,
            wait_s=meter.wait_s, t_start=meter.t0, retries=meter.retries,
            status="ok" if error is None else type(error).__name__,
            family=ann.get("family"), bookkeeping=ann["bookkeeping"],
            unmodeled=ann.get("unmodeled", False),
            algorithm=ann.get("algorithm"), codec=ann.get("codec"),
            bucket=meter.bucket, group_size=ann.get("group_size"),
            shape=ann.get("shape"), dtype=ann.get("dtype"),
            peer=meter.peer, tag=meter.tag)
        self._append(ev)
        if error is not None and isinstance(error, _failure_types()):
            self._note_failure(ev, error)

    def _append(self, ev: CommEvent) -> None:
        with self._lock:
            if len(self.events) == self.max_events:
                self.dropped += 1   # deque maxlen drops the oldest
            self.events.append(ev)
            key = (ev.world, ev.rank)
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = collections.deque(
                    maxlen=self.ring)
            ring.append(ev)
        from . import metrics as _metrics
        _metrics.inc("obs_events_total",
                     help="CommEvents recorded by the comm tracer")

    def _note_failure(self, ev: CommEvent, error: BaseException) -> None:
        from .flight import build_postmortem
        with self._lock:
            idx = self._failed_worlds.get(ev.world)
            if idx is not None:
                # A later observer of an already-snapshotted tear:
                # refresh ITS tail (it has just committed its own view
                # of the torn collective — the first snapshot raced
                # peers still blocked in the barrier) and count it.
                pm = self.postmortems[idx]
                pm["observers"] += 1
                pm["observer_ranks"] = sorted(set(
                    pm["observer_ranks"] + [ev.rank]))
                ring = self._rings.get((ev.world, ev.rank))
                if ring:
                    pm["tails"][ev.rank] = [e.to_dict() for e in ring]
                return
            pm = build_postmortem(self, ev, error)
            self._failed_worlds[ev.world] = len(self.postmortems)
            self.postmortems.append(pm)
        from . import metrics as _metrics
        _metrics.inc("obs_postmortems_total",
                     help="flight-recorder postmortems captured")

    def note_rank_failure(self, world, rank: int,
                          error: BaseException) -> None:
        """Postmortem entry point for failures raised OUTSIDE the
        chokepoints (integrity guards verify the decoded list after the
        rendezvous returns; ``run_ranks``' reaper routes every rank
        failure here).  Only the attributed failure classes snapshot;
        the per-world dedup in ``_note_failure`` means a failure already
        captured at a chokepoint just gains an observer."""
        if not isinstance(error, _failure_types()):
            return
        ev = CommEvent(
            seq=next(self._seq), rank=rank,
            world=self._world_ord(world), world_size=world.size,
            channel="exchange", op=f"({type(error).__name__})",
            status=type(error).__name__)
        self._note_failure(ev, error)

    def note_gray_failure(self, world_ord: int, world_size: int,
                          rank: int, error: BaseException) -> None:
        """Postmortem entry point for DRIVER-side gray-failure
        escalations (mpi4torch_tpu.resilience.health): the detector
        runs between phases, outside any rank body, so there is no
        world object and no reaper — it names the traced world by the
        ordinal its events carry.  Same dedup/snapshot semantics as
        :meth:`note_rank_failure`."""
        if not isinstance(error, _failure_types()):
            return
        ev = CommEvent(
            seq=next(self._seq), rank=rank, world=world_ord,
            world_size=world_size, channel="exchange",
            op=f"({type(error).__name__})",
            status=type(error).__name__)
        self._note_failure(ev, error)

    # ------------------------------------------------------------- Mode A

    def record_spmd(self, label: str, nbytes: int) -> None:
        """Host-callback target of :func:`spmd_collective_event` — one
        step-level Mode A event per executed collective entry (per
        device under a multi-device lowering: each shard's runtime
        really entered the collective)."""
        ev = CommEvent(
            seq=next(self._seq), rank=-1, world=-1, world_size=0,
            channel="spmd", op=label, signature=(label,),
            payload_bytes=int(nbytes), t_start=time.perf_counter())
        self._append(ev)

    # -------------------------------------------------------------- reads

    def absorb(self, world, shards: List[Optional[dict]]) -> None:
        """Merge process-backend worker tracer dumps into THIS tracer —
        the parent-side half of the transport's observability contract
        (``reconcile`` over a process-backend trace must read EXACTLY
        like a thread-backend one).

        ``shards[rank]`` is the worker's shipped dump (``{"events",
        "postmortems", "dropped"}``) or None.  Events are re-sequenced
        into the parent's program order by their start timestamps
        (``perf_counter`` shares one monotonic base across processes on
        one host) under the parent's ordinal for ``world``; per-world
        postmortems dedup-merge exactly like concurrent observers of
        one tear do (first snapshot wins, later shards add their
        observers and their own rank's ring tail)."""
        ord_ = self._world_ord(world)
        merged: List[CommEvent] = []
        for sh in shards:
            if not sh:
                continue
            self.dropped += int(sh.get("dropped") or 0)
            merged.extend(sh.get("events") or ())
        merged.sort(key=lambda ev: ev.t_start)
        for ev in merged:
            self._append(dataclasses.replace(
                ev, seq=next(self._seq), world=ord_))
        for sh in shards:
            if not sh:
                continue
            for pm in sh.get("postmortems") or ():
                self._absorb_postmortem(ord_, pm)

    def _absorb_postmortem(self, ord_: int, pm: dict) -> None:
        with self._lock:
            idx = self._failed_worlds.get(ord_)
            if idx is None:
                pm = dict(pm)
                pm["world"] = ord_
                pm["tails"] = dict(pm.get("tails") or {})
                self._failed_worlds[ord_] = len(self.postmortems)
                self.postmortems.append(pm)
                return
            dst = self.postmortems[idx]
            dst["observers"] += pm.get("observers", 1)
            dst["observer_ranks"] = sorted(
                set(dst["observer_ranks"])
                | set(pm.get("observer_ranks") or ()))
            for r, tail in (pm.get("tails") or {}).items():
                dst["tails"][r] = tail
            if not dst.get("failed_ranks") and pm.get("failed_ranks"):
                dst["failed_ranks"] = pm["failed_ranks"]

    def events_for(self, rank: Optional[int] = None,
                   channel: Optional[str] = None) -> List[CommEvent]:
        with self._lock:
            evs = list(self.events)
        if rank is not None:
            evs = [e for e in evs if e.rank == rank]
        if channel is not None:
            evs = [e for e in evs if e.channel == channel]
        return evs

    def tails(self) -> Dict[tuple, List[CommEvent]]:
        """Per-(world, rank) flight-recorder ring contents (newest
        last)."""
        with self._lock:
            return {k: list(r) for k, r in self._rings.items()}

    def last_postmortem(self) -> Optional[dict]:
        with self._lock:
            return self.postmortems[-1] if self.postmortems else None

    def clear(self) -> None:
        with self._lock:
            self.events.clear()
            self.dropped = 0
            self._rings.clear()
            self.postmortems.clear()
            self._failed_worlds.clear()


def current_tracer() -> Optional[CommTracer]:
    """The installed tracer (or None) — ``config.comm_tracer`` re-read."""
    return _config.comm_tracer()


@contextmanager
def trace(ring: int = 64, max_events: int = 200_000,
          mode_a: bool = False, tracer: Optional[CommTracer] = None):
    """Install a :class:`CommTracer` for the block and yield it::

        with mpi.obs.trace() as t:
            mpi.run_ranks(step, 8)
        report = mpi.obs.reconcile(t, lowered)   # reads t.dropped too

    Process-wide like the fault plan (events must flow from
    ``run_ranks`` rank threads, which a thread-local scope opened
    outside them would miss); the previous tracer is restored on exit.
    ``mode_a=True`` additionally instruments Mode A lowerings traced
    inside the block (and retraces them, via the thresholds
    fingerprint)."""
    t = tracer if tracer is not None else CommTracer(
        ring=ring, max_events=max_events, mode_a=mode_a)
    prev = _config.comm_tracer()
    _config.set_comm_tracer(t)
    try:
        yield t
    finally:
        _config.set_comm_tracer(prev)


def spmd_collective_event(x, where: str):
    """Mode A step-event hook (the ``spmd_finite_value`` precedent):
    called at trace time on a collective entry's input value.  With no
    tracer installed — or ``mode_a=False`` (default) — returns ``x``
    untouched: ZERO ops added, the lowering is bit-identical to an
    obs-less build (censused in ``bench._bench_obs_overhead``).  With
    ``mode_a=True``, attaches a host callback that records one
    step-level event per execution, carrying the statically-known
    payload bytes."""
    tracer = _config.comm_tracer()
    if tracer is None or not tracer.mode_a:
        return x
    import functools

    import jax
    import jax.numpy as jnp

    xa = jnp.asarray(x)
    nbytes = int(xa.size) * xa.dtype.itemsize
    # Anchor the callback on one element so it is ordered with (and not
    # DCE'd away from) the collective it reports, without shipping the
    # whole payload to the host.
    anchor = xa.reshape(-1)[:1] if xa.size else jnp.zeros((1,), xa.dtype)
    jax.debug.callback(
        functools.partial(_spmd_emit, where=where, nbytes=nbytes), anchor)
    return x


def _spmd_emit(_anchor, *, where: str, nbytes: int) -> None:
    tracer = _config.comm_tracer()
    if tracer is not None:
        tracer.record_spmd(where, nbytes)

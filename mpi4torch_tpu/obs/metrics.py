"""The process-wide metrics registry: counters, gauges, histograms.

One namespace for the runtime counters that had grown as scattered
surfaces — ``World.retry_events`` (a bare attribute), the resilience
guards' violation ledger, the autotuner's cache hits, the serving
engines' ``ServeStats`` — with two exports: a JSON :func:`snapshot`
and Prometheus text exposition (:func:`prometheus_text`, metric names
prefixed ``mpi4torch_``).  Thread-safe with one lock, like
``ServeStats`` (Mode B runs one engine/world per rank thread).

Three pieces:

* the registry proper (:class:`MetricsRegistry` + the process default
  :func:`registry`): ``inc``/``set_gauge``/``observe`` write paths off
  the hot path — the comm fast path never touches the registry; only
  exceptional events (a retry extension, an integrity violation, a
  cache miss) do;
* **collectors** — callables polled at snapshot time, for subsystems
  that already keep their own live state (the serve engines register
  one aggregating :func:`~mpi4torch_tpu.serve.stats`), so "one
  registry" does not mean "one copy of every number";
* the :class:`StatsSourceRegistry` — the weakref live-object registry
  that ``ServeStats`` aggregation used to carry privately in
  utils/profiling.py, re-homed here as the single implementation (a
  discarded engine drops out of the aggregate and out of memory).

:func:`percentile` is the one percentile rule ``ServeStats.snapshot``
and ``bench.py`` share (nearest-rank floor: index ``min(int(q*n),
n-1)`` of the sorted sample — bench's historical rule, so recorded
BENCH numbers are unchanged).
"""

from __future__ import annotations

import json
import threading
import weakref
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "MetricsRegistry",
    "StatsSourceRegistry",
    "registry",
    "sources",
    "inc",
    "set_gauge",
    "observe",
    "register_collector",
    "snapshot",
    "metrics_json",
    "prometheus_text",
    "reset_metrics",
    "percentile",
]

PROM_PREFIX = "mpi4torch_"

# Default histogram bucket bounds (seconds-flavored: comm durations).
DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank-floor percentile of ``values`` (sorted internally):
    element ``min(int(q * n), n - 1)``.  Returns None on an empty
    sample.  THE shared rule — ``ServeStats.snapshot`` p50/p99 and the
    bench.py serve stanza both call this, so there is exactly one
    definition of "p99" in the repo."""
    vals = sorted(values)
    if not vals:
        return None
    return vals[min(int(q * len(vals)), len(vals) - 1)]


class _Histogram:
    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def to_dict(self) -> dict:
        return {"buckets": {("%g" % b): c
                            for b, c in zip(self.bounds, self.counts)},
                "inf": self.counts[-1],
                "sum": self.total, "count": self.count}


class MetricsRegistry:
    """Counters / gauges / histograms under one lock, plus snapshot-time
    collectors.  Names are bare (``comm_retry_events_total``); the
    Prometheus exposition adds the ``mpi4torch_`` prefix."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Histogram] = {}
        self._collectors: Dict[str, Callable[[], dict]] = {}
        self._help: Dict[str, str] = {}

    # ------------------------------------------------------------ writes

    def inc(self, name: str, n: float = 1, help: str = "") -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
            if help:
                self._help.setdefault(name, help)

    def set_gauge(self, name: str, value: float, help: str = "") -> None:
        with self._lock:
            self._gauges[name] = value
            if help:
                self._help.setdefault(name, help)

    def observe(self, name: str, value: float,
                buckets=DEFAULT_BUCKETS, help: str = "") -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(buckets)
            h.observe(value)
            if help:
                self._help.setdefault(name, help)

    def register_collector(self, name: str,
                           fn: Callable[[], dict]) -> None:
        """Register a snapshot-time collector: ``fn()`` returns a flat
        ``{metric_name: number}`` dict merged into the snapshot's
        ``collected`` section (and exported as Prometheus gauges).
        Re-registering a name replaces the collector (idempotent module
        reload)."""
        with self._lock:
            self._collectors[name] = fn

    # ------------------------------------------------------------- reads

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_dict()
                               for k, h in self._hists.items()},
            }
            collectors = list(self._collectors.items())
        collected: Dict[str, dict] = {}
        for name, fn in collectors:
            try:
                collected[name] = fn()
            except Exception as e:  # noqa: BLE001 — a broken collector
                # must not take the snapshot down with it.
                collected[name] = {"error": f"{type(e).__name__}: {e}"}
        out["collected"] = collected
        return out

    def json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True, default=str)

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4): counters as
        ``counter``, gauges and collector outputs as ``gauge``,
        histograms as the standard ``_bucket``/``_sum``/``_count``
        triple with cumulative ``le`` buckets."""
        snap = self.snapshot()
        lines: List[str] = []

        seen_headers = set()

        def emit(name, kind, value):
            # A name may carry a Prometheus label set (`..._total{result=
            # "ok"}` — the health-probe counters): the sample line keeps
            # it, the HELP/TYPE headers use the bare metric name (and are
            # emitted once per family, not once per label value).
            full = PROM_PREFIX + name
            bare = full.split("{", 1)[0]
            if bare not in seen_headers:
                seen_headers.add(bare)
                doc = self._help.get(name)
                if doc:
                    lines.append(f"# HELP {bare} {doc}")
                lines.append(f"# TYPE {bare} {kind}")
            lines.append(f"{full} {value:g}")

        for name in sorted(snap["counters"]):
            emit(name, "counter", snap["counters"][name])
        for name in sorted(snap["gauges"]):
            emit(name, "gauge", snap["gauges"][name])
        for group in sorted(snap["collected"]):
            for name, v in sorted(snap["collected"][group].items()):
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    full = f"{PROM_PREFIX}{group}_{name}"
                    lines.append(f"# TYPE {full} gauge")
                    lines.append(f"{full} {v:g}")
        with self._lock:
            hists = {k: h for k, h in self._hists.items()}
        for name in sorted(hists):
            h = hists[name]
            full = PROM_PREFIX + name
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for b, c in zip(h.bounds, h.counts):
                cum += c
                lines.append(f'{full}_bucket{{le="{b:g}"}} {cum}')
            cum += h.counts[-1]
            lines.append(f'{full}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{full}_sum {h.total:g}")
            lines.append(f"{full}_count {h.count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero counters/gauges/histograms (collectors stay registered —
        they are live views, their owners reset themselves)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class StatsSourceRegistry:
    """Weakref registry of live per-object stats sources, grouped by
    subsystem name — the single implementation of the pattern
    ``ServeStats`` aggregation introduced: an object registers at
    construction, aggregation reads the live set, a garbage-collected
    owner drops out of the set (and out of memory) instead of being
    summed forever by an append-only list."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, List[weakref.ref]] = {}

    def register(self, group: str, obj):
        with self._lock:
            self._groups.setdefault(group, []).append(weakref.ref(obj))
        return obj

    def live(self, group: str) -> list:
        with self._lock:
            refs = self._groups.get(group, [])
            live, keep = [], []
            for ref in refs:
                obj = ref()
                if obj is not None:
                    live.append(obj)
                    keep.append(ref)
            refs[:] = keep   # prune dead owners' slots
        return live

    def clear(self, group: str) -> list:
        """Empty the group, returning the objects that were live — the
        ``reset_serve_stats`` semantics: callers reset the returned
        objects in place; owners constructed before the clear keep
        counting on their own objects but leave the aggregate."""
        live = self.live(group)
        with self._lock:
            self._groups.pop(group, None)
        return live


# ----------------------------------------------------------- process-wide

_registry = MetricsRegistry()
_sources = StatsSourceRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry every subsystem reports to."""
    return _registry


def sources() -> StatsSourceRegistry:
    """The process-wide weakref stats-source registry (the ``ServeStats``
    registration home; see utils/profiling.py)."""
    return _sources


def inc(name: str, n: float = 1, help: str = "") -> None:
    _registry.inc(name, n, help=help)


def set_gauge(name: str, value: float, help: str = "") -> None:
    _registry.set_gauge(name, value, help=help)


def observe(name: str, value: float, buckets=DEFAULT_BUCKETS,
            help: str = "") -> None:
    _registry.observe(name, value, buckets=buckets, help=help)


def register_collector(name: str, fn: Callable[[], dict]) -> None:
    _registry.register_collector(name, fn)


def snapshot() -> dict:
    return _registry.snapshot()


def metrics_json() -> str:
    return _registry.json()


def prometheus_text() -> str:
    return _registry.prometheus_text()


def reset_metrics() -> None:
    """Zero the default registry (test/bench isolation; collectors and
    stats sources are untouched — their owners reset themselves, e.g.
    :func:`mpi4torch_tpu.serve.reset_stats`)."""
    _registry.reset()

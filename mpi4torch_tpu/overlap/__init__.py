"""Split-phase nonblocking collectives + the overlap scheduler.

The paper's core nonblocking machinery (``WaitHandle``, ``JoinDummies``,
``JoinDummiesHandle``) existed only on the eager Mode B path; Mode A
(SPMD) collectives were all blocking, so every Allreduce/bucket pair
serialized against the surrounding compute.  This package brings
AD-transparent *split-phase* collectives to Mode A — ``comm.
Allreduce_start`` / ``Reduce_scatter_start`` / ``Allgather_start``
return an :class:`SpmdWaitHandle` mirroring the eager ``WaitHandle``
API, completed by the same ``comm.Wait`` verb — and the scheduler
(:mod:`.scheduler`) that exploits them to hide ZeRO/DP/PP communication
behind compute:

* **split-phase ops** (ops/spmd.py): the *start* issues the
  collective's first phase at its trace position (ring-SUM: the
  reduce-scatter half; everything else: the whole blocking fold) and
  the *Wait* completes it through a differentiable
  ``optimization_barrier`` — compute issued in between can hide the
  transfer, and the HLO start/done straddles it ("The Big Send-off",
  PAPERS.md: after algorithm choice, the dominant win is overlap; GC3
  makes collective scheduling a first-class compiler optimization).
  The backward pass is itself split-phase with the wait chain
  REVERSED — the SPMD analogue of ``JoinDummiesHandle``'s
  deadlock-free chaining.
* **overlap scheduler** (:mod:`.scheduler`): consumes the fused bucket
  layouts (mpi4torch_tpu.fuse) and keeps a configurable window of
  bucket collectives in flight — bucket ``i``'s reduce-scatter launches
  while bucket ``i+1`` is still being started, and a double-buffered
  ZeRO parameter all-gather *prefetch* starts gathering shard ``k+1``
  while layer ``k``'s consumer compute is still ahead of its Wait.
  Wired into ``parallel/zero.py`` (``zero_step``/``zero3_params``),
  ``parallel/dp.py`` (``all_average_tree(overlap=...)``) and the
  fused tree facade (``comm.Allreduce_tree(..., overlap=...)``).
* **knobs**: ``config.default_overlap()`` / ``config.overlap_scope``
  (jit-cache-keyed by ``run_spmd`` like the det/compression/fusion
  scopes); ``overlap=True`` means 2 collectives in flight, an
  ``int >= 1`` sets the window depth.

Mode A and Mode B stay bit-identical under ``deterministic_mode``: the
split-phase form computes the same fold as the blocking form, only
scheduled differently (regression-tested bitwise and HLO-censused in
tests/test_overlap.py).  Composition follows the house degrade/raise
rule: split-phase transfers are exact — an explicit overlap request
plus an explicit codec raises, scope defaults degrade (a compressed
bucket takes the blocking codec pipeline while its exact neighbors
ride split-phase).

Fault tolerance (mpi4torch_tpu.resilience): the eager split-phase forms
and the fused ``overlap=`` Isend/Irecv pipeline funnel through the same
rendezvous/mailbox chokepoints the fault-injection layer instruments,
so a fault plan composes with deferred Waits without overlap-specific
hooks — a dead rank surfaces as a rank-attributed ``RankFailedError``,
a dropped pipeline message recovers under ``config.comm_retries``
redelivery, and a corrupt bucket is caught by the finite guard naming
its sender (the ``overlap`` column of the censused fault matrix,
``make faults-smoke``; see doc/resilience.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List

import jax.numpy as jnp

from .. import config as _config
from ..comm import WaitHandle
from ..ops.eager import join_dummies as _join_dummies
from ..runtime import BifurcationError, CommError

__all__ = [
    "SPLIT_PHASE_FORMS",
    "SpmdWaitHandle",
    "allreduce_start",
    "reduce_scatter_start",
    "allgather_start",
    "complete_generic",
    "resolve_overlap",
    "overlap_depth",
    "tier_window_depth",
    "overlap_allreduce_tree",
    "overlap_reduce_scatter_tree",
    "overlap_split_allreduce",
    "prefetch_allgather_tree",
    "scheduled_exposure",
]

# Every split-phase collective form the facade exposes (as
# `<Form>_start` methods).  tests/test_overlap.py carries a sync guard
# in the test_tune registry-guard mold: each form here must have HLO
# census coverage, so a future *_start shipped without census tests
# fails CI.
SPLIT_PHASE_FORMS = ("Allreduce", "Reduce_scatter", "Allgather")

_DESC_LEN = 8


@dataclass
class _SplitState:
    """Completion state of a split-phase handle on backends without a
    trace context (eager rank-threads, the 2-axis hier communicator,
    the size-1 default world): the blocking value was computed at start
    time; Wait is the exactly-once completion point."""
    opname: str
    result: Any
    waited: bool = False


class SpmdWaitHandle(WaitHandle):
    """Wait handle of a split-phase collective — the SPMD counterpart
    of the eager :class:`~mpi4torch_tpu.WaitHandle`, with the identical
    API surface: ``.dummy`` for :func:`~mpi4torch_tpu.JoinDummies`,
    :func:`~mpi4torch_tpu.JoinDummiesHandle` composes (dummies land on
    the descriptor slot and the Wait ties them into the completion
    barrier), and ``comm.Wait`` completes it exactly once.

    Under the SPMD mesh backend the completion state lives in the trace
    context (keyed by the phase-1 buffer tracer, like the p2p handles),
    so double-Wait and handle-splicing guards fire at trace time and an
    un-waited handle raises when the region closes.  On the other
    backends the handle carries its own :class:`_SplitState`, shared
    across :func:`JoinDummiesHandle` copies so a double Wait through
    either copy still raises."""

    def __init__(self, raw_handle: List, state: _SplitState = None):
        super().__init__(raw_handle)
        self._split_state = state

    def _with_raw(self, raw_handle: List) -> "SpmdWaitHandle":
        return SpmdWaitHandle(raw_handle, self._split_state)


def _is_spmd_backend(backend) -> bool:
    from ..ops.spmd import SpmdBackend
    return isinstance(backend, SpmdBackend)


def _start_generic(opname: str, value) -> SpmdWaitHandle:
    """Compute-at-start split-phase form for backends without a trace
    context: the blocking collective already ran (``value``); the
    handle's Wait returns it through a dependency-carrying JoinDummies,
    bit-identical to the blocking op."""
    desc = _join_dummies(jnp.zeros(_DESC_LEN, jnp.float32),
                         [jnp.asarray(value).reshape(-1)[:1]])
    state = _SplitState(opname=opname, result=value)
    return SpmdWaitHandle([desc, value, value], state)


def complete_generic(handle: SpmdWaitHandle):
    """Complete a state-carrying split-phase handle (``comm.Wait``
    dispatches here for non-SPMD backends)."""
    state = handle._split_state
    if state.waited:
        raise BifurcationError(
            "Detected bifurcation in Wait handle usage: this split-phase "
            f"{state.opname} was already waited on (a WaitHandle "
            "completes exactly once)")
    state.waited = True
    # Tie through the descriptor so JoinDummiesHandle chains survive.
    return _join_dummies(state.result, [handle._handle[0]])


def allreduce_start(comm, tensor, op: int, compression=None,
                    algorithm=None) -> SpmdWaitHandle:
    """Facade body of ``comm.Allreduce_start``: one resolution path with
    the blocking :meth:`~mpi4torch_tpu.MPI_Communicator.Allreduce`
    (``MPI_Communicator._allreduce_plan``), then the split-phase rule —
    split transfers are exact, so an explicit codec raises and a scope
    default degrades to the exact wire.

    Owns the op's named scope so the RESOLVED algorithm can suffix it
    (``mpi4torch.Allreduce_start.rhd``), exactly like the blocking
    ``Allreduce``'s scope: a lowered program then carries deterministic
    evidence of which wire schedule each split-phase transfer took —
    what ``make serve-smoke`` reads to prove decode collectives landed
    in the latency tier."""
    import jax as _jax

    backend, codec, algo, algo_explicit = comm._allreduce_plan(
        tensor, op, compression, algorithm)
    if codec is not None:
        if compression is not None:
            raise ValueError(
                f"compression={codec.name!r} cannot ride a split-phase "
                "Allreduce — the codec pipeline is a fused multi-step "
                "collective with no start/wait form; use the blocking "
                "Allreduce, or compression=False to split-phase exact")
        codec = None  # scope default yields: exact split-phase wire
    if algo is None and _is_spmd_backend(backend):
        # Resolve auto selection HERE (the same trace-time selector the
        # backend would run) so the scope suffix below reflects the
        # schedule the wire actually takes — the facade passing the
        # resolved name through changes nothing else: the backend's
        # pair/whole-fold dispatch treats an explicitly-passed selector
        # pick exactly like its own auto resolution.
        from ..ops.spmd import _auto_allreduce_algorithm
        algo = _auto_allreduce_algorithm(backend._ctx, tensor)
    scope = "mpi4torch.Allreduce_start"
    suffix = algo
    if suffix in ("hier", "torus") and not getattr(
            backend, "owns_algorithm_resolution", False):
        # A scope-default hier/torus can still degrade to ring INSIDE
        # the backend when the group rule fails for this communicator
        # (config.hier_group_size not dividing it); a span naming a
        # schedule the wire never ran would falsify the census, so the
        # suffix applies only when the group validation the backend
        # will run passes.  (Auto picks are pre-gated by select_auto;
        # explicit failures raise rather than degrade.)
        from ..tune import resolve_hier_group
        try:
            resolve_hier_group(backend.size)
        except CommError:
            suffix = None
    if suffix not in (None, "ring"):
        scope += f".{suffix}"
    with _jax.named_scope(scope):
        if _is_spmd_backend(backend):
            raw = backend.allreduce_start(
                tensor, op, algorithm=algo,
                algorithm_explicit=algo_explicit)
            return SpmdWaitHandle(raw)
        val = backend.allreduce(tensor, op, algorithm=algo,
                                algorithm_explicit=algo_explicit)
        return _start_generic("Allreduce", val)


def reduce_scatter_start(comm, tensor, op: int,
                         scatteraxis: int) -> SpmdWaitHandle:
    """Facade body of ``comm.Reduce_scatter_start``."""
    backend = comm._backend()
    if _is_spmd_backend(backend):
        return SpmdWaitHandle(
            backend.reduce_scatter_start(tensor, op, scatteraxis))
    return _start_generic(
        "Reduce_scatter", backend.reduce_scatter(tensor, op, scatteraxis))


def allgather_start(comm, tensor, gatheraxis: int) -> SpmdWaitHandle:
    """Facade body of ``comm.Allgather_start``."""
    backend = comm._backend()
    if _is_spmd_backend(backend):
        return SpmdWaitHandle(
            backend.allgather_start(tensor, gatheraxis))
    return _start_generic(
        "Allgather", backend.allgather(tensor, gatheraxis))


def resolve_overlap(overlap):
    """Resolve an ``overlap=`` argument: ``None`` defers to the
    :func:`mpi4torch_tpu.config.overlap_scope` / process default;
    explicit values are validated (``True``/``False``/depth ``>= 1``)."""
    if overlap is None:
        return _config.default_overlap()
    return _config._validated_overlap(overlap)


def overlap_depth(value, default: int = 2) -> int:
    """Prefetch window depth of a truthy overlap value (``True`` → the
    double-buffered default of 2)."""
    return default if value is True else max(int(value), 1)


def tier_window_depth():
    """The configured tier-stack overlap widening, or ``None`` when the
    config declares no bandwidth skew: with ``config.tier_stack`` AND
    ``config.tier_bandwidths`` set and the slowest tier strictly slower
    than the fastest, a bucket's collective spends ~``max(bw)/min(bw)``
    of its wall time on the slow tier — so the split-phase window must
    hold that many buckets (plus the double-buffer slot) in flight for
    the slow tier's pipe to stay full while faster phases turn over.
    Deterministic in the config fingerprint (both knobs ride
    ``thresholds_fingerprint``), so a jit retrace sees any change."""
    stack = _config.tier_stack()
    bws = _config.tier_bandwidths()
    if stack is None or bws is None or len(bws) != len(stack):
        return None
    lo, hi = min(bws), max(bws)
    if not lo < hi:
        return None
    return int(-(-hi // lo)) + 1


# Scheduler entry points (public API; the fused tree facade and the
# parallel/ helpers route through these).
from .scheduler import (overlap_allreduce_tree,            # noqa: E402
                        overlap_reduce_scatter_tree,
                        overlap_split_allreduce,
                        prefetch_allgather_tree)
from .census import scheduled_exposure                     # noqa: E402

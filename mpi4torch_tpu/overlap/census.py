"""Scheduled-exposure census: quantify, from a lowered program, how much
of its bucket communication the schedule leaves *exposed*.

Wall-clock exposed-comm measurements need hardware whose collective
runtime is actually asynchronous; on the CPU smoke mesh the in-process
rendezvous executes synchronously on the device threads, so blocking
and split-phase programs time within scheduler noise of each other
(bench._bench_overlap_zero documents this).  What IS deterministic on
every platform is the *schedule itself*: the lowered program either
gives the runtime something to hide a transfer behind, or it does not.

:func:`scheduled_exposure` parses a ``debug_info`` lowering (the
``jax.named_scope`` spans of :func:`~mpi4torch_tpu.utils.profiling.
bucket_scope` survive into the StableHLO location table) and classifies
every ``mpi4torch.<Op>.bucket<i>of<n>`` collective:

* a bucket whose scope carries the split-phase ``.start``/``.wait``
  suffixes owns a *window* — the span between its last start-phase op
  and its first wait-phase op.  If another collective's wire op lands
  inside that window, the transfer has in-flight company the runtime
  can overlap it with: **hidden**.  An empty window (nothing else in
  flight) is **exposed** — the schedule serialized it after all.
* a bucket with no phase suffix is a blocking collective: start and
  completion coincide, the window is zero-width, and the transfer is
  exposed by construction (the 100%-exposed baseline
  utils/profiling.bucket_scope documents).

The census is exact about the program, conservative about the runtime:
it never claims wall-clock hiding, only that the schedule keeps >= 2
transfers in flight (the same invariant tests/test_overlap.py's
ordering censuses assert op-by-op, folded down to one fraction).
``bench._bench_overlap_zero`` records it as the smoke-path
exposed-comm fraction — blocking programs census to 1.0, windowed
split-phase programs strictly lower — next to the wall-clock fractions
that become meaningful on real multi-chip hardware.

Since the static verifier landed (:mod:`mpi4torch_tpu.analyze`), the
parsing and the window classification live there as a pass over the
shared StableHLO parse — this module keeps the historical entry point
(and its recorded fractions, regression-pinned bit-identical in
tests/test_analyze.py) as a delegation.
"""

from __future__ import annotations

from typing import Dict

from ..analyze.accounting import scheduled_exposure as _scheduled_exposure
from ..analyze.parse import WIRE_OPS

__all__ = ["scheduled_exposure", "WIRE_OPS"]


def scheduled_exposure(lowered_or_text) -> Dict:
    """Census a lowering (a ``jax.stages.Lowered`` or its
    ``debug_info=True`` text) for scheduled communication exposure.

    Returns ``{"n_buckets", "n_exposed", "exposed_fraction", "buckets"}``
    where ``buckets`` maps ``"<Op>.bucket<i>of<n>"`` to
    ``{"split_phase": bool, "exposed": bool}``.  ``exposed_fraction`` is
    ``None`` when the program contains no bucket collectives (e.g. a
    single-device world whose collectives lowered away)."""
    return _scheduled_exposure(lowered_or_text)

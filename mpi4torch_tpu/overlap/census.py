"""Scheduled-exposure census: quantify, from a lowered program, how much
of its bucket communication the schedule leaves *exposed*.

Wall-clock exposed-comm measurements need hardware whose collective
runtime is actually asynchronous; on the CPU smoke mesh the in-process
rendezvous executes synchronously on the device threads, so blocking
and split-phase programs time within scheduler noise of each other
(bench._bench_overlap_zero documents this).  What IS deterministic on
every platform is the *schedule itself*: the lowered program either
gives the runtime something to hide a transfer behind, or it does not.

:func:`scheduled_exposure` parses a ``debug_info`` lowering (the
``jax.named_scope`` spans of :func:`~mpi4torch_tpu.utils.profiling.
bucket_scope` survive into the StableHLO location table) and classifies
every ``mpi4torch.<Op>.bucket<i>of<n>`` collective:

* a bucket whose scope carries the split-phase ``.start``/``.wait``
  suffixes owns a *window* — the span between its last start-phase op
  and its first wait-phase op.  If another collective's wire op lands
  inside that window, the transfer has in-flight company the runtime
  can overlap it with: **hidden**.  An empty window (nothing else in
  flight) is **exposed** — the schedule serialized it after all.
* a bucket with no phase suffix is a blocking collective: start and
  completion coincide, the window is zero-width, and the transfer is
  exposed by construction (the 100%-exposed baseline
  utils/profiling.bucket_scope documents).

The census is exact about the program, conservative about the runtime:
it never claims wall-clock hiding, only that the schedule keeps >= 2
transfers in flight (the same invariant tests/test_overlap.py's
ordering censuses assert op-by-op, folded down to one fraction).
``bench._bench_overlap_zero`` records it as the smoke-path
exposed-comm fraction — blocking programs census to 1.0, windowed
split-phase programs strictly lower — next to the wall-clock fractions
that become meaningful on real multi-chip hardware.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

__all__ = ["scheduled_exposure", "WIRE_OPS"]

# StableHLO op kinds that put bytes on the wire (or rendezvous ranks):
# a bucket window containing one of these from another collective has
# real in-flight company.
WIRE_OPS = frozenset({
    "reduce_scatter", "all_gather", "all_reduce", "collective_permute",
    "all_to_all",
})

_LOC_DEF = re.compile(r'^#loc(\d+) = loc\("([^"]*)"')
_LOC_REF = re.compile(r"loc\(#loc(\d+)\)")
_LOC_INLINE = re.compile(r'loc\("([^"]*)"')
_OP_KIND = re.compile(r'"?stablehlo\.([a-z_0-9]+)"?')
_BUCKET = re.compile(
    r"mpi4torch\.(?P<op>[A-Za-z_]+)\.bucket(?P<i>\d+)of(?P<n>\d+)"
    r"(?P<rest>(?:\.\w+)*)")


def _as_debug_text(lowered_or_text) -> str:
    if isinstance(lowered_or_text, str):
        return lowered_or_text
    from .._compat import lowered_text
    return lowered_text(lowered_or_text, debug_info=True)


def _bucket_of(scope: str):
    """(op, bucket, total, phase) of the outermost bucket_scope span in a
    location path, or None."""
    m = _BUCKET.search(scope)
    if m is None:
        return None
    rest = m.group("rest").split(".")
    phase = ("start" if "start" in rest
             else "wait" if "wait" in rest else None)
    return (m.group("op"), int(m.group("i")), int(m.group("n")), phase)


def scheduled_exposure(lowered_or_text) -> Dict:
    """Census a lowering (a ``jax.stages.Lowered`` or its
    ``debug_info=True`` text) for scheduled communication exposure.

    Returns ``{"n_buckets", "n_exposed", "exposed_fraction", "buckets"}``
    where ``buckets`` maps ``"<Op>.bucket<i>of<n>"`` to
    ``{"split_phase": bool, "exposed": bool}``.  ``exposed_fraction`` is
    ``None`` when the program contains no bucket collectives (e.g. a
    single-device world whose collectives lowered away)."""
    text = _as_debug_text(lowered_or_text)
    lines = text.splitlines()

    loc_names: Dict[str, str] = {}
    for ln in lines:
        m = _LOC_DEF.match(ln)
        if m is not None:
            loc_names[m.group(1)] = m.group(2)

    # Ordered op events: (line index, stablehlo kind, bucket key, phase).
    events: List[Tuple[int, str, object, object]] = []
    for idx, ln in enumerate(lines):
        if ln.startswith("#loc"):
            continue
        km = _OP_KIND.search(ln)
        if km is None:
            continue
        ref = _LOC_REF.search(ln)
        scope = (loc_names.get(ref.group(1), "") if ref is not None
                 else "")
        if not scope:
            im = _LOC_INLINE.search(ln)
            scope = im.group(1) if im is not None else ""
        b = _bucket_of(scope)
        key, phase = (None, None) if b is None else (b[:3], b[3])
        events.append((idx, km.group(1), key, phase))

    by_bucket: Dict[tuple, Dict[str, List[int]]] = {}
    for idx, kind, key, phase in events:
        if key is None:
            continue
        slot = by_bucket.setdefault(key, {"start": [], "wait": [],
                                          "plain": []})
        slot[phase or "plain"].append(idx)

    wire = [(idx, key) for idx, kind, key, _ in events
            if kind in WIRE_OPS]

    buckets = {}
    n_exposed = 0
    for key in sorted(by_bucket):
        slot = by_bucket[key]
        split = bool(slot["start"] and slot["wait"])
        if split:
            lo, hi = max(slot["start"]), min(slot["wait"])
            hidden = any(lo < idx < hi and wkey != key
                         for idx, wkey in wire)
            exposed = not hidden
        else:
            # Blocking bucket (or a start that was never waited —
            # defensively exposed): zero-width completion window.
            exposed = True
        n_exposed += exposed
        op, i, n = key
        buckets[f"{op}.bucket{i}of{n}"] = {"split_phase": split,
                                           "exposed": exposed}

    nb = len(buckets)
    return {
        "n_buckets": nb,
        "n_exposed": n_exposed,
        "exposed_fraction": (round(n_exposed / nb, 4) if nb else None),
        "buckets": buckets,
    }

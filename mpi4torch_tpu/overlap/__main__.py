"""`python -m mpi4torch_tpu.overlap --smoke` — the overlap-smoke lane.

Exercises the split-phase scheduler AND the ZeRO prefetch end to end on
whatever devices are attached (the Makefile's ``overlap-smoke`` target
runs it on the 8-virtual-device CPU mesh):

1. a DP gradient-tree allreduce through the windowed split-phase
   scheduler, checked BITWISE against the blocking fused form;
2. a full ZeRO step (windowed reduce-scatter + double-buffered
   parameter all-gather prefetch) vs the blocking step, bitwise;
3. a wall-clock probe of both schedules with the exposed-comm fraction
   of each (informational on CPU — the synchronous host collective
   runtime cannot hide wire time; see bench._bench_overlap_zero).

Exits non-zero on any parity mismatch, so the lane is a real check,
not a demo.
"""

from __future__ import annotations

import sys
import time


def _smoke() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mpi4torch_tpu as mpi
    from mpi4torch_tpu.parallel import zero as Z

    comm = mpi.COMM_WORLD
    n = len(jax.devices())
    print(f"overlap-smoke: {n} device(s), platform "
          f"{jax.devices()[0].platform}")

    rng = np.random.default_rng(0)
    tree = {f"layer{i}": jnp.asarray(
        rng.standard_normal(2048).astype(np.float32)) for i in range(6)}

    def avg(ov):
        return mpi.run_spmd(lambda t: comm.Allreduce_tree(
            t, mpi.MPI_SUM, bucket_bytes=4096, overlap=ov, mean=True))

    blocking = avg(None)(tree)
    overlapped = avg(True)(tree)
    for k in tree:
        if not np.array_equal(np.asarray(blocking[k]),
                              np.asarray(overlapped[k])):
            print(f"FAIL: scheduler allreduce tree diverges on {k}")
            return 1
    print("scheduler: 6-leaf tree, windowed split-phase == blocking "
          "fused (bitwise)")

    params = {"w": jnp.asarray(
        rng.standard_normal((64, 48)).astype(np.float32)),
        "b": jnp.asarray(rng.standard_normal(97).astype(np.float32))}
    grads = jax.tree.map(lambda p: p * 0.01, params)

    class _Sgd:
        def init(self, p):
            return None

        def update(self, g, s, p):
            return jax.tree.map(lambda x: -0.1 * x, g), None

    opt = _Sgd()

    def zstep(ov):
        def f(g):
            with mpi.config.fusion_scope(4096):
                st = Z.zero_init(comm, opt, params)
                return Z.zero_step(comm, opt, params, g, st,
                                   overlap=ov)[0]
        return mpi.run_spmd(f)

    zb = zstep(None)(grads)
    zo = zstep(True)(grads)
    for k in params:
        if not np.array_equal(np.asarray(zb[k]), np.asarray(zo[k])):
            print(f"FAIL: ZeRO overlap step diverges on {k}")
            return 1
    print("zero: windowed reduce-scatter + prefetched all-gather == "
          "blocking step (bitwise)")

    def timed(fn, arg, iters=5):
        fn(arg)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arg))
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    tb, to = timed(zstep(None), grads), timed(zstep(True), grads)
    print(f"zero step: blocking {tb * 1e3:.2f} ms, overlap "
          f"{to * 1e3:.2f} ms (speedup {tb / max(to, 1e-12):.2f}x; "
          "informational on CPU — synchronous collectives cannot hide "
          "wire time)")

    # The deterministic story: census both step schedules
    # (overlap.scheduled_exposure — what bench._bench_overlap_zero
    # records as the smoke-path exposed-comm fraction).
    from . import scheduled_exposure

    def lowered(ov):
        def f(g):
            with mpi.config.fusion_scope(4096):
                st = Z.zero_init(comm, opt, params)
                return Z.zero_step(comm, opt, params, g, st,
                                   overlap=ov)[0]
        return jax.jit(mpi.run_spmd(f)).lower(grads)

    cb = scheduled_exposure(lowered(None))
    co = scheduled_exposure(lowered(True))
    print(f"scheduled exposure: blocking {cb['exposed_fraction']} "
          f"({cb['n_buckets']} buckets), overlap {co['exposed_fraction']} "
          f"({co['n_buckets']} buckets)")
    if (n > 1 and cb["n_buckets"]
            and not (co["exposed_fraction"] < cb["exposed_fraction"])):
        print("FAIL: windowed schedule does not lower the scheduled "
              "exposed-comm fraction")
        return 1
    print("overlap-smoke: OK")
    return 0


def main(argv) -> int:
    if "--smoke" in argv or not argv:
        return _smoke()
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""The overlap scheduler: windowed split-phase bucket collectives.

Consumes the fused bucket layouts of :mod:`mpi4torch_tpu.fuse` and
replaces the blocking per-bucket collectives with *start/wait pairs*
held in a sliding window of ``depth`` buckets: bucket ``i``'s collective
is started as soon as its flat buffer exists, and its Wait is issued
only after bucket ``i + depth - 1``'s start — so at any point up to
``depth`` collectives are in flight, with every bucket's completion
point tied (via :func:`~mpi4torch_tpu.JoinDummiesHandle` onto the
youngest start) so neither XLA nor the autodiff transpose can collapse
the window.  The backward pass needs no extra scheduling: each phase is
a ``custom_vjp`` collective glued by differentiable barriers, so the
adjoint program is the same window with the wait chain reversed.

Three shapes, one discipline:

* :func:`overlap_allreduce_tree` — the DP gradient primitive
  (``comm.Allreduce_tree(..., overlap=...)`` routes here under the
  SPMD backend): per bucket, the reduce-scatter half starts early and
  the all-gather half completes late.
* :func:`overlap_reduce_scatter_tree` — the ZeRO-1/3 gradient-shard
  primitive (``zero_step``): one ``Reduce_scatter_start`` per block
  bucket, windowed.
* :func:`prefetch_allgather_tree` — the ZeRO-3 parameter *prefetch*
  (``zero3_params``): double-buffered ``Allgather_start`` — the gather
  of shard bucket ``k+1`` is issued before bucket ``k``'s Wait, so the
  next layer's parameters are already on the wire while the current
  layer's consumer compute is still between the Wait and its use.

Per-bucket composition follows the house rule: a bucket whose resolved
codec cannot split (every codec — the compressed pipeline is a fused
multi-step collective) takes the *blocking* compressed path at its
start slot while its exact neighbors ride split-phase; explicit
conflicts raise at the tree level (fuse/collectives.py).  Algorithm
picks compose per bucket exactly as on the blocking path — a non-ring
schedule runs whole in its phase 1, keeping its tuned wire while later
buckets' starts still slide past it.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .. import constants as C
from ..utils.profiling import bucket_scope


def _windowed(nb: int, depth: int, start, finish) -> None:
    """Run ``start(i)`` / ``finish(i)`` over ``nb`` buckets with up to
    ``depth`` starts ahead of the oldest unfinished bucket."""
    depth = max(int(depth), 1)
    for i in range(nb):
        start(i)
        j = i - (depth - 1)
        if j >= 0:
            finish(j)
    for j in range(max(nb - depth + 1, 0), nb):
        finish(j)


class _Window:
    """Shared start/wait bookkeeping: handles per bucket, plus the
    youngest started handle so each Wait can be order-tied after it.

    ``label_base``/``label_total`` offset the bucket-scope labels:
    :func:`overlap_split_allreduce` runs several windows within ONE
    program (one per decode collective site) and the scheduled-exposure
    census groups ops by their ``bucket<i>of<n>`` span, so every
    window's buckets must be globally distinct."""

    def __init__(self, comm, op: str, nb: int, label_base: int = 0,
                 label_total: int = None):
        self.comm = comm
        self.op = op
        self.nb = nb
        self.label_base = label_base
        self.label_total = nb if label_total is None else label_total
        self.handles = {}
        self.results = [None] * nb
        self.youngest = None

    def started(self, i: int, handle) -> None:
        self.handles[i] = handle
        self.youngest = handle

    def finish(self, i: int) -> None:
        h = self.handles.pop(i, None)
        if h is None:
            return  # blocking bucket (codec path): completed at start
        if self.youngest is not None and self.youngest is not h:
            # Pin the window: bucket i's completion cannot be hoisted
            # before the youngest start — the cross-bucket ordering tie
            # that keeps >= depth collectives in flight (and, reversed
            # by the transpose, orders the backward chain).
            from ..comm import JoinDummiesHandle
            h = JoinDummiesHandle(h, [self.youngest.dummy])
        with bucket_scope(self.op, self.label_base + i, self.label_total,
                          phase="wait"):
            self.results[i] = self.comm.Wait(h)


def overlap_split_allreduce(comm, x, op: int, *, nsplits: int = 2,
                            index_base: int = 0, index_total: int = None,
                            op_name: str = "Allreduce_split",
                            algorithm=None):
    """Split-phase allreduce of ONE payload as ``nsplits`` windowed
    chunk buckets — the decode-collective primitive of
    :mod:`mpi4torch_tpu.serve`.

    A per-token decode collective is a few KiB with nothing independent
    to hide behind (the next op consumes its result), so the overlap
    window is built WITHIN the call: the flat payload splits into
    ``nsplits`` chunks, every chunk's collective is started before any
    is waited on, and each Wait is order-tied behind the youngest start
    — so while chunk 0 completes, chunk 1's transfer is already on the
    wire (>= 2 in flight, the invariant :func:`~mpi4torch_tpu.overlap.
    scheduled_exposure` censuses).  An elementwise SUM is unchanged by
    chunking, so the result is BITWISE the blocking ``comm.Allreduce``
    on both backends (deterministic mode included: the per-element
    ascending-rank fold never crosses chunk boundaries).

    ``index_base``/``index_total`` make this call's bucket-scope labels
    globally unique when several sites run in one program (the serving
    decode step numbers ``2 * n_layers`` sites).  ``algorithm`` follows
    the ``Allreduce`` contract per chunk — auto selection keys on the
    CHUNK size, i.e. the real decode message the wire carries.
    Split-phase transfers are exact (a codec scope degrades, as in
    :meth:`~mpi4torch_tpu.MPI_Communicator.Allreduce_start`)."""
    x = jnp.asarray(x)
    flat = x.reshape(-1)
    n = flat.shape[0]
    nsplits = max(min(int(nsplits), max(n, 1)), 1)
    bounds = [n * i // nsplits for i in range(nsplits + 1)]
    chunks = [flat[bounds[i]:bounds[i + 1]] for i in range(nsplits)]
    total = nsplits if index_total is None else index_total
    win = _Window(comm, op_name, nsplits, label_base=index_base,
                  label_total=total)

    def start(i):
        with bucket_scope(op_name, index_base + i, total, phase="start"):
            win.started(i, comm.Allreduce_start(
                chunks[i], op, compression=False, algorithm=algorithm))

    # Full-depth window: all starts issued, then the waits — for a
    # handful of chunk buckets the maximal in-flight set is the point.
    _windowed(nsplits, nsplits, start, win.finish)
    return jnp.concatenate(win.results).reshape(x.shape)


def overlap_allreduce_tree(comm, buckets: Sequence, layout, op: int, *,
                           depth: int = 2, mean: bool = False,
                           plan=None, tier_window=None):
    """Windowed split-phase allreduce over pre-flattened buckets.

    ``plan(i, bucket) -> (codec, algorithm)`` is the per-bucket
    resolution the fused tree path already computes
    (fuse/collectives.py); compressed buckets take the blocking codec
    pipeline in their start slot, exact buckets ride start/wait pairs.
    Returns the reduced bucket list (``mean`` folds the rank-mean into
    one post-wait scale per bucket).

    ``tier_window`` is the tier-stack widening: on a communicator whose
    tier stack has a slow outermost tier (skewed
    ``config.tier_bandwidths`` — DCN under ICI), each bucket's
    collective spends most of its wall time in the outer-tier phase, so
    a ``depth``-bucket window drains to one transfer in flight while an
    outer phase completes.  A truthy ``tier_window`` widens the window
    to ``min(tier_window, nb)`` buckets (never narrows below ``depth``),
    so start→wait spans cross enough bucket boundaries to keep the slow
    tier's pipe full — statically visible as a strictly-below-blocking
    :func:`~mpi4torch_tpu.overlap.scheduled_exposure` fraction over the
    widened spans."""
    from ..fuse.bucketing import unflatten_buckets

    nb = len(buckets)
    if tier_window:
        depth = max(int(depth), min(int(tier_window), nb))
    size = comm.size
    win = _Window(comm, "Allreduce_tree", nb)

    def start(i):
        b = buckets[i]
        bcodec, balgo = plan(i, b) if plan is not None else (None, None)
        if bcodec is not None:
            with bucket_scope("Allreduce_tree", i, nb, codec=bcodec):
                win.results[i] = comm.Allreduce(b, op, compression=bcodec,
                                                algorithm=balgo)
            return
        with bucket_scope("Allreduce_tree", i, nb, phase="start"):
            win.started(i, comm.Allreduce_start(b, op, compression=False,
                                                algorithm=balgo))

    _windowed(nb, depth, start, win.finish)
    reduced = [r / size if mean else r for r in win.results]
    return unflatten_buckets(reduced, layout)


def overlap_reduce_scatter_tree(comm, tree, op: int, *, bucket_bytes: int,
                                depth: int = 2, mean: bool = False):
    """Windowed split-phase block-bucket reduce-scatter — the ZeRO
    gradient sharding of :func:`mpi4torch_tpu.fuse.
    fused_reduce_scatter_tree` with up to ``depth`` ``psum_scatter``
    collectives in flight.  Always exact (ZeRO internals are pinned
    exact); bit-identical to the blocking form."""
    from ..fuse.bucketing import flatten_shard_buckets, unflatten_shard_rows

    size = comm.size
    buckets, layout = flatten_shard_buckets(tree, size, bucket_bytes)
    nb = layout.num_buckets
    win = _Window(comm, "Reduce_scatter_tree", nb)

    def start(i):
        with bucket_scope("Reduce_scatter_tree", i, nb, phase="start"):
            win.started(i, comm.Reduce_scatter_start(buckets[i], op, 0))

    _windowed(nb, depth, start, win.finish)
    rows = [r.reshape(-1) / size if mean else r.reshape(-1)
            for r in win.results]
    return unflatten_shard_rows(rows, layout)


def prefetch_allgather_tree(comm, shard_tree, template, *,
                            bucket_bytes: int, depth: int = 2):
    """Double-buffered ZeRO-3 parameter all-gather prefetch: bucket
    ``k+1``'s ``Allgather_start`` is issued before bucket ``k``'s Wait,
    so while the consumer (layer ``k``'s forward, downstream of the
    Wait) runs, the next shard bucket is already on the wire.  The
    adjoint is the same window of reduce-scatters in reverse — ZeRO-3's
    gather-params/reduce-scatter-grads wire pattern, now overlapped in
    both directions.  Always exact; bit-identical to the blocking
    :func:`mpi4torch_tpu.fuse.fused_allgather_tree`."""
    from ..fuse.bucketing import (flatten_shard_rows, shard_layout,
                                  unflatten_gathered)

    size = comm.size
    layout = shard_layout(template, size, bucket_bytes)
    rows = flatten_shard_rows(shard_tree, layout)
    nb = layout.num_buckets
    win = _Window(comm, "Allgather_tree", nb)

    def start(i):
        with bucket_scope("Allgather_tree", i, nb, phase="start"):
            win.started(i, comm.Allgather_start(rows[i], 0))

    _windowed(nb, depth, start, win.finish)
    blocks = [full.reshape(size, -1) for full in win.results]
    out = unflatten_gathered(blocks, layout)
    return jax.tree.map(lambda x, t: x.astype(t.dtype), out, template)

"""Framework configuration flags.

The reference has no config system (SURVEY.md §5: three compile-time toggles
total).  This framework adds two semantic knobs:

``deterministic_reductions`` — when True, SPMD-mode SUM reductions are
computed as an all-gather followed by a fixed ascending-rank-order fold,
which is bit-identical to the eager thread-SPMD oracle (the 'MPI linear
order' reference) at the cost of bandwidth; when False (default), they lower
to ``lax.psum`` — the XLA/ICI-native reduction, fastest but with
compiler-chosen combining order (ulp-level differences possible).

``default_compression`` — the wire-compression codec applied by default to
``Allreduce``/``Allgather`` calls that do not pass an explicit
``compression=`` argument (mpi4torch_tpu.compress; None = exact fp wire).
Set it process-wide with :func:`set_default_compression` or lexically with
the :func:`compression_scope` context manager.  Like the deterministic
flag, the value is read at *trace* time: ``run_spmd`` makes it part of the
jit cache key so toggling retraces, but a user-managed ``jax.jit`` that
already traced keeps its lowering until it retraces.

``default_bucket_bytes`` — the target flat-bucket size of the fused tree
collectives (mpi4torch_tpu.fuse; the per-leaf→per-bucket launch
reduction).  ~4 MiB default, the production-stack sweet spot between
launch amortization and overlap granularity.  Set process-wide with
:func:`set_default_bucket_bytes` or lexically with :func:`fusion_scope`;
``fusion_scope(0)`` disables fusion (per-leaf collectives) for the
block.  Read at trace time like the other knobs; ``run_spmd`` keys its
jit cache on it.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

_state = threading.local()


def deterministic_reductions() -> bool:
    return getattr(_state, "deterministic", False)


def set_deterministic_reductions(value: bool) -> None:
    _state.deterministic = bool(value)


@contextmanager
def deterministic_mode(value: bool = True):
    prev = deterministic_reductions()
    set_deterministic_reductions(value)
    try:
        yield
    finally:
        set_deterministic_reductions(prev)


# Sentinel distinguishing "no scope active on this thread" from an explicit
# compression_scope(None) (which forces exact transfers within the block).
_UNSET = object()
_process_default = None


def default_compression():
    """The codec (object or registered name) facade ops use when
    ``compression=None`` is passed: the innermost active
    :func:`compression_scope` on this thread, else the process-wide
    :func:`set_default_compression` value (None = no compression)."""
    scoped = getattr(_state, "compression", _UNSET)
    return _process_default if scoped is _UNSET else scoped


def _validated(codec):
    if codec is None:
        return None
    from .compress import get_codec

    return get_codec(codec)  # resolve names; ad-hoc codec objects pass


def set_default_compression(codec) -> None:
    """Set the process-wide default wire-compression codec (a registered
    name, a Codec object, or None to disable).  Visible on every thread —
    including ``run_ranks`` rank-threads — unless a thread's own
    :func:`compression_scope` overrides it."""
    global _process_default
    _process_default = _validated(codec)


# Fused-collective bucket size (mpi4torch_tpu.fuse).  4 MiB: large enough
# to amortize per-collective launch + ring latency over hundreds of tiny
# leaves, small enough that a grad tree still splits into several buckets
# whose transfers the overlap scheduler can keep in flight concurrently.
DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024
_process_bucket_bytes = DEFAULT_BUCKET_BYTES


def default_bucket_bytes() -> int:
    """Bucket size (bytes) the fused tree collectives use when no
    explicit ``bucket_bytes=`` is passed: the innermost active
    :func:`fusion_scope` on this thread, else the process-wide
    :func:`set_default_bucket_bytes` value.  ``0`` disables fusion
    (per-leaf collectives)."""
    scoped = getattr(_state, "bucket_bytes", _UNSET)
    return _process_bucket_bytes if scoped is _UNSET else scoped


def _validated_bucket_bytes(nbytes) -> int:
    if nbytes is False:
        return 0
    nbytes = int(nbytes)
    if nbytes < 0:
        raise ValueError(f"bucket_bytes must be >= 0, got {nbytes}")
    return nbytes


def set_default_bucket_bytes(nbytes) -> None:
    """Set the process-wide fused-collective bucket size in bytes
    (``0``/``False`` = fusion off → per-leaf collectives)."""
    global _process_bucket_bytes
    _process_bucket_bytes = _validated_bucket_bytes(nbytes)


@contextmanager
def fusion_scope(bucket_bytes):
    """Lexically scoped bucket size for the fused tree collectives::

        with mpi.config.fusion_scope(1 << 20):   # 1 MiB buckets
            grads = comm.Allreduce_tree(grads, mpi.MPI_SUM, mean=True)

        with mpi.config.fusion_scope(0):         # per-leaf, unfused
            ...

    Per-thread like :func:`compression_scope` (a scope opened before
    ``run_ranks`` is not seen by the rank-threads — use
    :func:`set_default_bucket_bytes` or open the scope inside the rank
    body).  ``run_spmd`` re-reads the value at call time and makes it
    part of its jit cache key, so toggling retraces."""
    prev = getattr(_state, "bucket_bytes", _UNSET)
    _state.bucket_bytes = _validated_bucket_bytes(bucket_bytes)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.bucket_bytes
        else:
            _state.bucket_bytes = prev


# ---------------------------------------------------------------------------
# Split-phase overlap (mpi4torch_tpu.overlap)
# ---------------------------------------------------------------------------

_process_overlap = None


def default_overlap():
    """The overlap policy facade tree collectives and the parallel/
    helpers use when no explicit ``overlap=`` is passed: the innermost
    active :func:`overlap_scope` on this thread, else the process-wide
    :func:`set_default_overlap` value.

    ``None`` (default) keeps each backend's historical behavior (SPMD:
    barrier-staged bucket interleave; eager: blocking rendezvous);
    ``True`` enables the split-phase overlap scheduler
    (:mod:`mpi4torch_tpu.overlap`) with the default prefetch depth of
    2; an ``int >= 1`` enables it with that many collectives in
    flight; ``False`` forces fully blocking schedules."""
    scoped = getattr(_state, "overlap", _UNSET)
    return _process_overlap if scoped is _UNSET else scoped


def _validated_overlap(value):
    if value is None or value is False:
        return value
    if value is True:
        return True
    try:
        depth = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"overlap must be None, a bool, or a prefetch depth >= 1; "
            f"got {value!r}") from None
    if depth < 1:
        raise ValueError(
            f"overlap prefetch depth must be >= 1, got {depth}")
    return depth


def set_default_overlap(value) -> None:
    """Set the process-wide overlap policy (``None``/``True``/``False``
    or an integer prefetch depth — see :func:`default_overlap`)."""
    global _process_overlap
    _process_overlap = _validated_overlap(value)


@contextmanager
def overlap_scope(value):
    """Lexically scoped overlap policy for the split-phase scheduler::

        with mpi.config.overlap_scope(True):      # 2 buckets in flight
            grads = comm.Allreduce_tree(grads, mpi.MPI_SUM, mean=True)

        with mpi.config.overlap_scope(3):          # deeper prefetch
            params = mpi.parallel.zero.zero3_params(comm, shards, tmpl)

    Per-thread like :func:`compression_scope`; ``run_spmd`` re-reads the
    value at call time and makes it part of its jit cache key, so
    toggling retraces.  A scope default is a *preference*: buckets it
    cannot legally serve (e.g. a compressed bucket — the codec pipeline
    is a fused multi-step collective with no split form) degrade to the
    blocking path; an explicit ``overlap=`` plus an explicit conflicting
    argument raises instead, exactly like the compression scope's
    degrade/raise rule."""
    prev = getattr(_state, "overlap", _UNSET)
    _state.overlap = _validated_overlap(value)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.overlap
        else:
            _state.overlap = prev


# ---------------------------------------------------------------------------
# Collective-algorithm selection (mpi4torch_tpu.tune)
# ---------------------------------------------------------------------------

_process_algorithm = None


def default_algorithm():
    """The collective algorithm facade ops use when no explicit
    ``algorithm=`` is passed: the innermost active :func:`algorithm_scope`
    on this thread, else the process-wide :func:`set_default_algorithm`
    value.  ``None``/``"auto"`` defer to the :mod:`mpi4torch_tpu.tune`
    selector (measured cache winner where one exists, else ``ring``)."""
    scoped = getattr(_state, "algorithm", _UNSET)
    return _process_algorithm if scoped is _UNSET else scoped


def _validated_algorithm(name):
    if name is None or name == "auto":
        return None
    from .tune import get_algorithm

    return get_algorithm(name).name  # raises on unknown names


def set_default_algorithm(name) -> None:
    """Set the process-wide default collective algorithm (a registered
    algorithm name — ``ring``/``rhd``/``tree``/``hier`` — or
    ``None``/``"auto"`` for selector-driven choice).  A scope/process
    default is a *preference*: collectives it cannot legally serve
    (e.g. ``rhd`` on a non-power-of-two world, or a compressed transfer
    whose codec is ring-only) silently fall back to auto selection,
    exactly like the compression scope's degrade rule; an explicit
    per-call ``algorithm=`` raises instead."""
    global _process_algorithm
    _process_algorithm = _validated_algorithm(name)


@contextmanager
def algorithm_scope(name):
    """Lexically scoped collective-algorithm default::

        with mpi.config.algorithm_scope("rhd"):
            y = comm.Allreduce(x, mpi.MPI_SUM)   # latency-optimal wire

    Per-thread like :func:`compression_scope`; ``run_spmd`` re-reads the
    value at call time and makes it part of its jit cache key, so
    toggling retraces."""
    prev = getattr(_state, "algorithm", _UNSET)
    _state.algorithm = _validated_algorithm(name)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.algorithm
        else:
            _state.algorithm = prev


# ---------------------------------------------------------------------------
# Collective schedule thresholds (promoted from ops/spmd.py constants;
# ISSUE 3 satellite).  Process-wide, validated, and overridable from
# measurement by the mpi4torch_tpu.tune autotuner.
# ---------------------------------------------------------------------------

# The all-gather+fold form of the ordered reduction materializes size× the
# tensor per rank; below this many *gathered* bytes (payload × ranks) its
# latency advantage wins.  Above it, the chunked ring fold caps peak extra
# memory at ≈2× the tensor.  Both paths are bit-identical, so the switch
# is safe at any value.
DEFAULT_ORDERED_FOLD_GATHER_MAX_BYTES = 4 * 1024 * 1024
# Pipeline granularity of the deterministic ring fold.
DEFAULT_ORDERED_RING_CHUNK_BYTES = 8 * 1024 * 1024
# Payloads at or below this take the binomial-tree broadcast (log2(N)
# sequential full-payload hops); larger ones the root-masked psum (see
# ops/spmd.py _bcast_value for the wire accounting).
DEFAULT_BCAST_TREE_MAX_BYTES = 256 * 1024

_ordered_fold_gather_max_bytes = DEFAULT_ORDERED_FOLD_GATHER_MAX_BYTES
_ordered_ring_chunk_bytes = DEFAULT_ORDERED_RING_CHUNK_BYTES
_bcast_tree_max_bytes = DEFAULT_BCAST_TREE_MAX_BYTES


def _validated_threshold(nbytes, what: str, minimum: int = 0,
                         unit: str = "byte count") -> int:
    try:
        nbytes = int(nbytes)
    except (TypeError, ValueError):
        raise ValueError(f"{what} must be an integer {unit}, got "
                         f"{nbytes!r}") from None
    if nbytes < minimum:
        raise ValueError(f"{what} must be >= {minimum}, got {nbytes}")
    return nbytes


def ordered_fold_gather_max_bytes() -> int:
    """Gathered-bytes ceiling (payload × ranks) below which the
    deterministic ordered fold uses the all-gather+fold form instead of
    the chunked ring (ops/spmd.py)."""
    return _ordered_fold_gather_max_bytes


def set_ordered_fold_gather_max_bytes(nbytes) -> None:
    global _ordered_fold_gather_max_bytes
    _ordered_fold_gather_max_bytes = _validated_threshold(
        nbytes, "ordered_fold_gather_max_bytes")


def ordered_ring_chunk_bytes() -> int:
    """Chunk size of the deterministic ring-fold pipeline
    (ops/spmd.py)."""
    return _ordered_ring_chunk_bytes


def set_ordered_ring_chunk_bytes(nbytes) -> None:
    global _ordered_ring_chunk_bytes
    _ordered_ring_chunk_bytes = _validated_threshold(
        nbytes, "ordered_ring_chunk_bytes", minimum=1)


def bcast_tree_max_bytes() -> int:
    """Payload-bytes ceiling below which ``Bcast_`` takes the
    binomial-tree lowering instead of the root-masked psum
    (ops/spmd.py)."""
    return _bcast_tree_max_bytes


def set_bcast_tree_max_bytes(nbytes) -> None:
    global _bcast_tree_max_bytes
    _bcast_tree_max_bytes = _validated_threshold(
        nbytes, "bcast_tree_max_bytes")


# Measured latency/bandwidth crossover for allreduce algorithm selection.
# None = not measured: the selector never switches algorithms on a
# heuristic alone — it deviates from `ring` only on evidence (a cached
# per-key winner, or this crossover once the autotuner has measured it).
_latency_crossover_bytes = None


def latency_crossover_bytes():
    """Payload-bytes ceiling below which the tune selector prefers a
    latency-optimal algorithm (``rhd``, else ``tree``) for auto-selected
    allreduces.  ``None`` (default) = unmeasured: auto-selection stays
    on ``ring`` except where the autotuner cache names a winner.  Set
    from measurement by :func:`mpi4torch_tpu.tune.autotune_allreduce`
    or explicitly here."""
    return _latency_crossover_bytes


def set_latency_crossover_bytes(nbytes) -> None:
    global _latency_crossover_bytes
    _latency_crossover_bytes = (
        None if nbytes is None
        else _validated_threshold(nbytes, "latency_crossover_bytes"))


# Measured ring/multipath crossover for allreduce algorithm selection —
# the upper edge of the three-tier auto selection (latency algorithms
# below latency_crossover_bytes, plain ring in the middle, a multipath
# bandwidth algorithm at/above this).  None = not measured: like the
# latency crossover, auto-selection deviates from `ring` only on
# evidence.
_bandwidth_crossover_bytes = None


def bandwidth_crossover_bytes():
    """Payload-bytes floor at/above which the tune selector prefers a
    bandwidth-tier multipath algorithm (``bidir``, the dual-ring) for
    auto-selected allreduces.  ``None`` (default) = unmeasured: auto
    selection stays on ``ring`` for large payloads except where the
    autotuner cache names a winner.  Set from measurement by
    :func:`mpi4torch_tpu.tune.autotune_allreduce` or explicitly here."""
    return _bandwidth_crossover_bytes


def set_bandwidth_crossover_bytes(nbytes) -> None:
    global _bandwidth_crossover_bytes
    _bandwidth_crossover_bytes = (
        None if nbytes is None
        else _validated_threshold(nbytes, "bandwidth_crossover_bytes"))


# Phase pipelining of the deterministic chunked ring fold (ops/spmd.py
# _ring_fold_allreduce): when True (default) a chunk whose ascending-rank
# fold has completed starts its all-gather relay around the ring while
# later chunks are still folding — one fused scan, no trailing
# full-payload broadcast barrier.  False restores the fold-then-tree-
# broadcast two-phase form (the pre-pipelining baseline, kept for
# head-to-head measurement).  Bits are identical either way: the fold
# association is untouched and the relay is pure data movement.
_phase_pipelined_ring = True


def phase_pipelined_ring() -> bool:
    """Whether the deterministic chunked ring fold overlaps its
    all-gather head with the reduce-scatter tail (see ops/spmd.py
    ``_ring_fold_allreduce``)."""
    return _phase_pipelined_ring


def set_phase_pipelined_ring(value: bool) -> None:
    global _phase_pipelined_ring
    _phase_pipelined_ring = bool(value)


# Worlds up to this size unroll the explicit directional ring chains of
# the `bidir` schedule hop-by-hop (distinct permute ops — maximal
# scheduling freedom and the HLO-census surface); larger worlds roll
# each phase into a lax.scan so the compiled program does not grow with
# the rank count (a 256-rank pod would otherwise emit ~1000 permute ops
# per bidir allreduce).  Promoted from the ops/spmd.py module constant
# _CHAIN_UNROLL_MAX (ISSUE 5 satellite), matching the ISSUE 3
# threshold-promotion pattern: validated setter, run_spmd jit-cache
# fingerprint coverage, overridable from measurement.
DEFAULT_CHAIN_UNROLL_MAX = 32

_chain_unroll_max = DEFAULT_CHAIN_UNROLL_MAX


def chain_unroll_max() -> int:
    """Rank-count ceiling up to which the ``bidir`` directional ring
    chains unroll hop-by-hop; larger worlds take the O(1)-program
    ``lax.scan`` form (ops/spmd.py ``_ring_allreduce_chain``; bits are
    identical either way)."""
    return _chain_unroll_max


def set_chain_unroll_max(n) -> None:
    global _chain_unroll_max
    _chain_unroll_max = _validated_threshold(
        n, "chain_unroll_max", minimum=1, unit="rank count")


# Implementation of the fused dequantize→accumulate→requantize hop of the
# in-schedule quantized collectives (ops/quant_kernels.py, EQuARX-style):
# "auto" runs the Pallas TPU kernel on TPU and the bit-identical jnp
# fallback elsewhere; "jnp" forces the fallback everywhere; "pallas"
# forces the kernel (interpreted off-TPU — the bit-equivalence test
# surface).  Part of the run_spmd jit fingerprint: toggling retraces.
_QUANT_HOP_IMPLS = ("auto", "jnp", "pallas")
_quant_hop_impl = "auto"


def quant_hop_impl() -> str:
    """Which implementation serves the fused quantized ring hop
    (``ops/quant_kernels.py``): ``"auto"`` (Pallas kernel on TPU, jnp
    fallback elsewhere — both bit-identical), ``"jnp"`` (fallback
    everywhere), or ``"pallas"`` (kernel forced; interpreted off-TPU)."""
    return _quant_hop_impl


def set_quant_hop_impl(impl: str) -> None:
    global _quant_hop_impl
    if impl not in _QUANT_HOP_IMPLS:
        raise ValueError(
            f"quant_hop_impl must be one of {_QUANT_HOP_IMPLS}, got "
            f"{impl!r}")
    _quant_hop_impl = impl


# Split count of the serving decode step's per-layer TP collectives
# (mpi4torch_tpu.serve): each tiny per-token allreduce payload is split
# into this many windowed split-phase chunk buckets so >= 2 transfers
# stay in flight (the overlap scheduler's window, applied WITHIN one
# collective site — decode has no independent second collective stream
# to pair with).  2 (default) is the double-buffered sweet spot for
# payloads this small; 1 degenerates to a single split-phase pair
# (start/wait with an empty window — censuses exposed).  Only read when
# the engine's overlap policy is on; part of the trace-time fingerprint.
DEFAULT_SERVE_DECODE_BUCKETS = 2

_serve_decode_buckets = DEFAULT_SERVE_DECODE_BUCKETS


def serve_decode_buckets() -> int:
    """How many windowed split-phase chunk buckets one serving decode
    collective is split into (:mod:`mpi4torch_tpu.serve`; >= 1)."""
    return _serve_decode_buckets


def set_serve_decode_buckets(n) -> None:
    global _serve_decode_buckets
    _serve_decode_buckets = _validated_threshold(
        n, "serve_decode_buckets", minimum=1, unit="bucket count")


# Default planning strategy of the resharding subsystem
# (mpi4torch_tpu.reshard): "auto" lets the planner walk its preference
# order (local < permute < allgather < alltoall < rounds — gather, the
# full-materialization baseline, only ever wins through a measured tune
# cache entry); a concrete name pins every plan to that strategy and
# raises where it cannot serve the transition.  Part of the trace-time
# fingerprint: run_spmd retraces when it changes.
_reshard_strategy = None


def default_reshard_strategy():
    """The plan strategy :func:`mpi4torch_tpu.reshard.plan_reshard`
    uses when no explicit ``strategy=`` is passed (``None``/``"auto"``
    = preference order + transition-keyed autotuner winner)."""
    return _reshard_strategy


def set_default_reshard_strategy(name) -> None:
    global _reshard_strategy
    if name in (None, "auto"):
        _reshard_strategy = None
        return
    from .reshard.plan import STRATEGIES

    if name not in STRATEGIES:
        raise ValueError(
            f"reshard strategy must be one of {STRATEGIES} or "
            f"None/'auto', got {name!r}")
    _reshard_strategy = name


# Intra-group size of the 2-level `hier` allreduce on a single mesh axis.
# None = derive: the minor axis extent when the communicator was adopted
# from a multi-axis mesh, else the divisor of nranks closest to sqrt.
_hier_group_size = None


def hier_group_size():
    """Intra-group size of the single-axis ``hier`` allreduce (must
    divide the communicator size, 1 < g < size).  ``None`` = derive from
    topology (see :mod:`mpi4torch_tpu.tune`)."""
    return _hier_group_size


def set_hier_group_size(g) -> None:
    global _hier_group_size
    if g is None:
        _hier_group_size = None
        return
    g = _validated_threshold(g, "hier_group_size", minimum=2)
    _hier_group_size = g


# N-level tier factorization of a single-axis communicator, innermost
# (fastest interconnect) first — e.g. (4, 2) = groups of 4 inside a pod,
# 2 pods.  Generalizes _hier_group_size: a 2-level stack (g, n // g) is
# exactly hier_group_size=g.  None = derive (hier_group_size, else the
# sqrt-divisor 2-level split).  See mpi4torch_tpu.tune.resolve_tier_stack.
_tier_stack = None
# Relative bandwidth of each tier's interconnect, aligned with the tier
# stack (innermost first) — e.g. (1.0, 0.05) for fast ICI under slow DCN.
# The weights of the bandwidth-weighted wire census (csched.weighted_cost,
# analyze.weighted_wire_cost); None = uniform.
_tier_bandwidths = None


def tier_stack():
    """The configured tier-stack factorization (innermost first), or
    None to derive.  Each factor must be >= 2 and the product must equal
    the communicator size (validated where it is resolved)."""
    return _tier_stack


def set_tier_stack(stack) -> None:
    global _tier_stack
    if stack is None:
        _tier_stack = None
        return
    try:
        stack = tuple(int(g) for g in stack)
    except (TypeError, ValueError):
        raise ValueError(
            f"tier_stack must be a tuple of ints >= 2 or None, got "
            f"{stack!r}") from None
    if not stack or any(g < 2 for g in stack):
        raise ValueError(
            f"tier_stack factors must all be >= 2, got {stack!r}")
    _tier_stack = stack


def tier_bandwidths():
    """Per-tier relative bandwidths (innermost first), or None for
    uniform weights.  Aligned with the resolved tier stack."""
    return _tier_bandwidths


def set_tier_bandwidths(bws) -> None:
    global _tier_bandwidths
    if bws is None:
        _tier_bandwidths = None
        return
    try:
        bws = tuple(float(b) for b in bws)
    except (TypeError, ValueError):
        raise ValueError(
            f"tier_bandwidths must be a tuple of positive numbers or "
            f"None, got {bws!r}") from None
    if not bws or any(b <= 0 for b in bws):
        raise ValueError(
            f"tier_bandwidths must all be > 0, got {bws!r}")
    _tier_bandwidths = bws


# ---------------------------------------------------------------------------
# Fault tolerance (mpi4torch_tpu.resilience; ISSUE 7)
# ---------------------------------------------------------------------------

# Transient-fault retry budget of the eager rendezvous/p2p layer: a
# barrier or receive that finds nothing within the base timeout gets
# this many extra patience windows, each of capped-exponential-backoff
# length, before declaring DeadlockError — a slow-but-alive rank (GC
# pause, noisy neighbor, fault-injected delay) completes the collective
# inside the extended window instead of tearing the world down.  0
# (default) keeps the historical single-timeout behavior.
_comm_retries = 0
# Base backoff in seconds; retry k waits min(backoff * 2**(k-1), 30s).
_comm_backoff = 0.05


def comm_retries() -> int:
    """Retry extensions granted to a timed-out rendezvous barrier or p2p
    receive before it raises (mpi4torch_tpu.resilience)."""
    return _comm_retries


def set_comm_retries(n) -> None:
    global _comm_retries
    _comm_retries = _validated_threshold(n, "comm_retries",
                                         unit="retry count")


def comm_backoff() -> float:
    """Base seconds of the capped exponential backoff between comm
    retries (retry k waits ``min(comm_backoff * 2**(k-1), 30s)``)."""
    return _comm_backoff


def set_comm_backoff(seconds) -> None:
    global _comm_backoff
    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        raise ValueError(
            f"comm_backoff must be a number of seconds, got "
            f"{seconds!r}") from None
    if seconds < 0:
        raise ValueError(f"comm_backoff must be >= 0, got {seconds}")
    _comm_backoff = seconds


# Non-finite payload guard of the collective layer: "off" (default —
# the lowering is bit-identical to a guard-less build, HLO-censused in
# bench.py _bench_guard_overhead), "warn" (IntegrityWarning naming the
# offending rank(s) on the eager backend), or "raise" (IntegrityError).
_GUARD_MODES = ("off", "warn", "raise")
_comm_finite_guard = "off"


def comm_finite_guard() -> str:
    """Non-finite payload check mode of the collective ops
    (mpi4torch_tpu.resilience.guards): ``"off"``/``"warn"``/``"raise"``.
    Part of the trace-time fingerprint — toggling retraces Mode A."""
    return _comm_finite_guard


def set_comm_finite_guard(mode: str) -> None:
    global _comm_finite_guard
    if mode not in _GUARD_MODES:
        raise ValueError(
            f"comm_finite_guard must be one of {_GUARD_MODES}, got "
            f"{mode!r}")
    _comm_finite_guard = mode


# Checksum leg of the compressed rendezvous wire (compress/eager.py):
# when True, every encoded payload ships with a CRC of its wire bytes
# and decode verifies each rank's block, raising IntegrityError naming
# the corrupt contributor.  Off (default) keeps the wire format —
# and the Mode A lowering — bit-identical to a checksum-less build.
_comm_wire_checksum = False


def comm_wire_checksum() -> bool:
    """Whether the compressed eager wire carries a verified checksum
    (mpi4torch_tpu.resilience.guards.wire_checksum)."""
    return _comm_wire_checksum


def set_comm_wire_checksum(value: bool) -> None:
    global _comm_wire_checksum
    _comm_wire_checksum = bool(value)


# The active deterministic fault-injection plan
# (mpi4torch_tpu.resilience.faults.FaultPlan), or None (default: the
# zero-overhead fast path — one attribute read per rendezvous).
# PROCESS-wide, not thread-scoped: faults must be visible inside
# run_ranks rank-threads, which a thread-local scope opened outside
# them would miss; resilience.fault_scope() is the save/restore wrapper.
_fault_plan = None


def fault_plan():
    """The active fault-injection plan (or None).  See
    :mod:`mpi4torch_tpu.resilience`."""
    return _fault_plan


def set_fault_plan(plan) -> None:
    """Install a process-wide fault plan: a
    :class:`~mpi4torch_tpu.resilience.FaultPlan`, a sequence of
    :class:`~mpi4torch_tpu.resilience.FaultSpec`, or None to clear."""
    global _fault_plan
    if plan is None:
        _fault_plan = None
        return
    from .resilience.faults import as_plan

    _fault_plan = as_plan(plan)


# ---------------------------------------------------------------------------
# Mode B transport backend (mpi4torch_tpu.transport; ISSUE 16)
# ---------------------------------------------------------------------------

# Which registered transport serves run_ranks when no explicit
# ``backend=`` is passed: "thread" (N rank-threads in this process —
# the historical semantics and the tier-1 default) or "process" (N
# spawned worker processes over the pickle-framed socket wire — real
# parallelism, real SIGKILLs).  PROCESS-wide like the fault plan: the
# transport choice must be visible wherever run_ranks is called.
# Deliberately NOT part of thresholds_fingerprint(): the knob is Mode B
# (rendezvous wire) only and provably never moves a Mode A lowering —
# the _comm_wire_checksum precedent.
_comm_transport = os.environ.get("MPI4TORCH_TPU_TRANSPORT", "thread")


def comm_transport() -> str:
    """The default transport backend :func:`~mpi4torch_tpu.run_ranks`
    uses when no explicit ``backend=`` is passed (see
    :mod:`mpi4torch_tpu.transport`).  Initialized from the
    ``MPI4TORCH_TPU_TRANSPORT`` environment variable (``"thread"``
    when unset)."""
    return _comm_transport


def set_comm_transport(name) -> None:
    """Set the process-wide default transport backend (a name
    registered in :data:`mpi4torch_tpu.transport.TRANSPORTS`)."""
    global _comm_transport
    if name is None:
        name = "thread"
    from .transport import TRANSPORTS

    if name not in TRANSPORTS:
        raise ValueError(
            f"comm_transport must be one of {sorted(TRANSPORTS)}, got "
            f"{name!r}")
    _comm_transport = name


@contextmanager
def transport_scope(name):
    """Install a transport default for a ``with`` block (process-wide
    like :func:`set_fault_plan` — the choice must be visible to
    whatever thread calls ``run_ranks`` inside the block)::

        with mpi.config.transport_scope("process"):
            mpi.run_ranks(step, 8)      # real worker processes
    """
    global _comm_transport
    prev = _comm_transport
    set_comm_transport(name)
    try:
        yield
    finally:
        _comm_transport = prev


# Process-wide knobs a transport worker process must replicate so the
# rank body computes bit-identically to a rank-thread.  Thread-SCOPED
# state (deterministic_mode, compression_scope, ...) is deliberately
# absent: rank-threads spawned by run_ranks never see the launcher
# thread's scopes either, so shipping them would DIVERGE from the
# thread backend, not match it.
def snapshot_process_state() -> dict:
    """Picklable snapshot of every process-wide config knob that
    affects Mode B rank-body execution — what the process transport
    ships to its workers (mpi4torch_tpu.transport).  Codecs travel by
    registered name (an unregistered ad-hoc codec object travels as
    itself and must pickle)."""
    codec = _process_default
    if codec is not None:
        name = getattr(codec, "name", None)
        if name is not None:
            codec = name
    return {
        "compression": codec,
        "bucket_bytes": _process_bucket_bytes,
        "overlap": _process_overlap,
        "algorithm": _process_algorithm,
        "ordered_fold_gather_max_bytes": _ordered_fold_gather_max_bytes,
        "ordered_ring_chunk_bytes": _ordered_ring_chunk_bytes,
        "bcast_tree_max_bytes": _bcast_tree_max_bytes,
        "latency_crossover_bytes": _latency_crossover_bytes,
        "bandwidth_crossover_bytes": _bandwidth_crossover_bytes,
        "phase_pipelined_ring": _phase_pipelined_ring,
        "hier_group_size": _hier_group_size,
        "tier_stack": _tier_stack,
        "tier_bandwidths": _tier_bandwidths,
        "chain_unroll_max": _chain_unroll_max,
        "quant_hop_impl": _quant_hop_impl,
        "serve_decode_buckets": _serve_decode_buckets,
        "reshard_strategy": _reshard_strategy,
        "comm_retries": _comm_retries,
        "comm_backoff": _comm_backoff,
        "comm_finite_guard": _comm_finite_guard,
        "comm_wire_checksum": _comm_wire_checksum,
        "ctl_enabled": _ctl_enabled,
        "ctl_halflife": _ctl_halflife,
        "ctl_drift_thresholds": (_ctl_drift_low, _ctl_drift_high),
        "ctl_drift_patience": _ctl_drift_patience,
        "ctl_min_switch_epochs": _ctl_min_switch_epochs,
        "ctl_codec_crossover": _ctl_codec_crossover,
    }


def apply_process_state(state: dict) -> None:
    """Apply a :func:`snapshot_process_state` dict — the worker-process
    half of the config shipping contract."""
    set_default_compression(state["compression"])
    set_default_bucket_bytes(state["bucket_bytes"])
    set_default_overlap(state["overlap"])
    set_default_algorithm(state["algorithm"])
    set_ordered_fold_gather_max_bytes(
        state["ordered_fold_gather_max_bytes"])
    set_ordered_ring_chunk_bytes(state["ordered_ring_chunk_bytes"])
    set_bcast_tree_max_bytes(state["bcast_tree_max_bytes"])
    set_latency_crossover_bytes(state["latency_crossover_bytes"])
    set_bandwidth_crossover_bytes(state["bandwidth_crossover_bytes"])
    set_phase_pipelined_ring(state["phase_pipelined_ring"])
    set_hier_group_size(state["hier_group_size"])
    set_tier_stack(state["tier_stack"])
    set_tier_bandwidths(state["tier_bandwidths"])
    set_chain_unroll_max(state["chain_unroll_max"])
    set_quant_hop_impl(state["quant_hop_impl"])
    set_serve_decode_buckets(state["serve_decode_buckets"])
    set_default_reshard_strategy(state["reshard_strategy"])
    set_comm_retries(state["comm_retries"])
    set_comm_backoff(state["comm_backoff"])
    set_comm_finite_guard(state["comm_finite_guard"])
    set_comm_wire_checksum(state["comm_wire_checksum"])
    set_ctl_enabled(state["ctl_enabled"])
    set_ctl_halflife(state["ctl_halflife"])
    set_ctl_drift_thresholds(*state["ctl_drift_thresholds"])
    set_ctl_drift_patience(state["ctl_drift_patience"])
    set_ctl_min_switch_epochs(state["ctl_min_switch_epochs"])
    set_ctl_codec_crossover(state["ctl_codec_crossover"])


# ---------------------------------------------------------------------------
# Runtime observability (mpi4torch_tpu.obs; ISSUE 12)
# ---------------------------------------------------------------------------

# The active comm tracer (mpi4torch_tpu.obs.CommTracer), or None
# (default: the zero-overhead fast path — one attribute read per
# chokepoint, the fault-plan discipline).  PROCESS-wide like the fault
# plan: events must flow from run_ranks rank-threads, which a
# thread-local scope opened outside them would miss; obs.trace() is the
# save/restore wrapper.
_comm_tracer = None


def comm_tracer():
    """The active comm tracer (or None).  See
    :mod:`mpi4torch_tpu.obs`."""
    return _comm_tracer


def set_comm_tracer(tracer) -> None:
    """Install a process-wide comm tracer (an
    :class:`~mpi4torch_tpu.obs.CommTracer`, or None to disable).  With
    ``tracer.mode_a`` set, Mode A lowerings gain the step-event host
    callback — the flag rides :func:`thresholds_fingerprint`, so
    installing/removing such a tracer retraces instead of reusing the
    uninstrumented lowering."""
    global _comm_tracer
    _comm_tracer = tracer


def thresholds_fingerprint():
    """Hashable snapshot of every trace-time threshold/selection knob —
    ``run_spmd`` folds it into its jit cache key so overriding a
    threshold (or the autotuner writing a measured crossover) retraces
    instead of silently reusing the old lowering."""
    # _comm_wire_checksum is deliberately NOT here: it is a Mode B
    # (rendezvous wire) leg only and provably never moves the Mode A
    # lowering (censused in bench.py _bench_guard_overhead and
    # tests/test_resilience.py) — keying it in would force a full
    # retrace/recompile for zero semantic effect.
    # The obs tracer keys in only as "does Mode A get the step-event
    # callback": a Mode B-only tracer (mode_a=False, the default) never
    # moves the lowering, so it must not force a retrace either —
    # censused in bench.py _bench_obs_overhead, like _comm_wire_checksum.
    # The ctl knobs ride along even though they never move a lowering
    # directly: the controller's thresholds decide which winners get
    # INSTALLED (tune.record bumps the selection generation), so a
    # lowering's cache identity should be keyed to the policy that
    # selected it — and the ISSUE 19 process-shipping contract wants
    # one fingerprint covering the whole selection surface.
    return (_ordered_fold_gather_max_bytes, _ordered_ring_chunk_bytes,
            _bcast_tree_max_bytes, _latency_crossover_bytes,
            _bandwidth_crossover_bytes, _phase_pipelined_ring,
            _hier_group_size, _tier_stack, _tier_bandwidths,
            _chain_unroll_max, _quant_hop_impl,
            _comm_finite_guard, _reshard_strategy,
            _serve_decode_buckets,
            _ctl_enabled, _ctl_halflife,
            (_ctl_drift_low, _ctl_drift_high), _ctl_drift_patience,
            _ctl_min_switch_epochs, _ctl_codec_crossover,
            # The mode_a tracer flag stays LAST (tests/test_obs.py
            # reads it as fingerprint[-1]).
            bool(_comm_tracer is not None
                 and getattr(_comm_tracer, "mode_a", False)))


@contextmanager
def compression_scope(codec):
    """Lexically scoped compression default::

        with mpi.config.compression_scope("q8"):
            y = comm.Allreduce(g, mpi.MPI_SUM)   # rides the wire as int8

    ``compression_scope(None)`` forces exact transfers for the block even
    when a process default is set.  The scope itself is per-thread (like
    ``deterministic_mode``): a scope opened before ``run_ranks`` is not
    seen by the rank-threads — use :func:`set_default_compression`, open
    the scope inside the rank body, or pass ``compression=`` explicitly
    for collective agreement there.  ``run_spmd`` re-reads the value at
    call time and makes it part of its jit cache key, so toggling
    retraces."""
    prev = getattr(_state, "compression", _UNSET)
    _state.compression = _validated(codec)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.compression
        else:
            _state.compression = prev


# ---------------------------------------------------------------------------
# Online self-tuning controller (mpi4torch_tpu.ctl; ISSUE 19)
# ---------------------------------------------------------------------------

# Master switch: False (default) keeps SelfTuningController.poll to ONE
# knob read and guarantees the controller changes nothing — the
# fault-plan/obs off-path discipline, censused in bench.py _bench_ctl.
_ctl_enabled = False
# EWMA half-life of the bandwidth estimates, in SAMPLES (after this
# many events a value's weight has decayed to 1/2) — a deterministic
# unit: the smoke/test cells drive the estimator with known event
# counts, never wall-clock.
_ctl_halflife = 4.0
# Hysteresis watermarks on the live/baseline per-tier ratio: a tier
# degrades below `low`, recovers above `high`, and the band between
# them resets both patience counters — scheduler noise oscillating
# inside the band can never flap a switch.
_ctl_drift_low = 0.5
_ctl_drift_high = 0.8
# Consecutive monitor checks past a watermark before the state flips.
_ctl_drift_patience = 2
# Minimum consensus epochs between ratified switches (a second
# anti-flap leg, counted in the currency switches themselves advance).
_ctl_min_switch_epochs = 1
# Ratio below which the escalation is a CODEC escalation (exact ->
# compressed wire, the EQuARX regime) rather than an exact re-rank: at
# a quarter of baseline bandwidth the ~4x smaller q8 wire breaks even
# on the sagged tier.
_ctl_codec_crossover = 0.25


def ctl_enabled() -> bool:
    """Whether the online self-tuning controller acts
    (:mod:`mpi4torch_tpu.ctl`).  Off (default): ``poll`` is one
    attribute read and the build is bit-identical to a controller-less
    one."""
    return _ctl_enabled


def set_ctl_enabled(value: bool) -> None:
    global _ctl_enabled
    _ctl_enabled = bool(value)


def ctl_halflife() -> float:
    """EWMA half-life (in samples) of the controller's live bandwidth
    estimates (ctl.estimate)."""
    return _ctl_halflife


def set_ctl_halflife(halflife) -> None:
    global _ctl_halflife
    try:
        halflife = float(halflife)
    except (TypeError, ValueError):
        raise ValueError(
            f"ctl_halflife must be a number of samples, got "
            f"{halflife!r}") from None
    if not halflife > 0:
        raise ValueError(f"ctl_halflife must be > 0, got {halflife}")
    _ctl_halflife = halflife


def ctl_drift_thresholds():
    """The ``(low, high)`` hysteresis watermarks on the live/baseline
    bandwidth ratio (ctl.drift): degrade below ``low``, recover above
    ``high``, never flap inside the band."""
    return (_ctl_drift_low, _ctl_drift_high)


def set_ctl_drift_thresholds(low, high) -> None:
    global _ctl_drift_low, _ctl_drift_high
    try:
        low, high = float(low), float(high)
    except (TypeError, ValueError):
        raise ValueError(
            f"ctl_drift_thresholds must be numbers, got "
            f"({low!r}, {high!r})") from None
    if not (0.0 < low < high):
        raise ValueError(
            f"ctl_drift_thresholds need 0 < low < high, got "
            f"({low}, {high})")
    _ctl_drift_low, _ctl_drift_high = low, high


def ctl_drift_patience() -> int:
    """Consecutive monitor checks past a watermark before a tier's
    drift state flips (ctl.drift)."""
    return _ctl_drift_patience


def set_ctl_drift_patience(n) -> None:
    global _ctl_drift_patience
    _ctl_drift_patience = _validated_threshold(
        n, "ctl_drift_patience", minimum=1, unit="check count")


def ctl_min_switch_epochs() -> int:
    """Minimum consensus epochs between ratified controller switches
    (ctl.controller) — the anti-flap leg counted in epochs."""
    return _ctl_min_switch_epochs


def set_ctl_min_switch_epochs(n) -> None:
    global _ctl_min_switch_epochs
    _ctl_min_switch_epochs = _validated_threshold(
        n, "ctl_min_switch_epochs", minimum=0, unit="epoch count")


def ctl_codec_crossover() -> float:
    """Live/baseline ratio below which the controller escalates the
    CODEC (exact -> q8) instead of only re-ranking the exact winner
    (ctl.controller)."""
    return _ctl_codec_crossover


def set_ctl_codec_crossover(ratio) -> None:
    global _ctl_codec_crossover
    try:
        ratio = float(ratio)
    except (TypeError, ValueError):
        raise ValueError(
            f"ctl_codec_crossover must be a ratio in (0, 1], got "
            f"{ratio!r}") from None
    if not (0.0 < ratio <= 1.0):
        raise ValueError(
            f"ctl_codec_crossover must be in (0, 1], got {ratio}")
    _ctl_codec_crossover = ratio

"""Framework configuration flags.

The reference has no config system (SURVEY.md §5: three compile-time toggles
total).  This framework adds two semantic knobs:

``deterministic_reductions`` — when True, SPMD-mode SUM reductions are
computed as an all-gather followed by a fixed ascending-rank-order fold,
which is bit-identical to the eager thread-SPMD oracle (the 'MPI linear
order' reference) at the cost of bandwidth; when False (default), they lower
to ``lax.psum`` — the XLA/ICI-native reduction, fastest but with
compiler-chosen combining order (ulp-level differences possible).

``default_compression`` — the wire-compression codec applied by default to
``Allreduce``/``Allgather`` calls that do not pass an explicit
``compression=`` argument (mpi4torch_tpu.compress; None = exact fp wire).
Set it process-wide with :func:`set_default_compression` or lexically with
the :func:`compression_scope` context manager.  Like the deterministic
flag, the value is read at *trace* time: ``run_spmd`` makes it part of the
jit cache key so toggling retraces, but a user-managed ``jax.jit`` that
already traced keeps its lowering until it retraces.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_state = threading.local()


def deterministic_reductions() -> bool:
    return getattr(_state, "deterministic", False)


def set_deterministic_reductions(value: bool) -> None:
    _state.deterministic = bool(value)


@contextmanager
def deterministic_mode(value: bool = True):
    prev = deterministic_reductions()
    set_deterministic_reductions(value)
    try:
        yield
    finally:
        set_deterministic_reductions(prev)


# Sentinel distinguishing "no scope active on this thread" from an explicit
# compression_scope(None) (which forces exact transfers within the block).
_UNSET = object()
_process_default = None


def default_compression():
    """The codec (object or registered name) facade ops use when
    ``compression=None`` is passed: the innermost active
    :func:`compression_scope` on this thread, else the process-wide
    :func:`set_default_compression` value (None = no compression)."""
    scoped = getattr(_state, "compression", _UNSET)
    return _process_default if scoped is _UNSET else scoped


def _validated(codec):
    if codec is None:
        return None
    from .compress import get_codec

    return get_codec(codec)  # resolve names; ad-hoc codec objects pass


def set_default_compression(codec) -> None:
    """Set the process-wide default wire-compression codec (a registered
    name, a Codec object, or None to disable).  Visible on every thread —
    including ``run_ranks`` rank-threads — unless a thread's own
    :func:`compression_scope` overrides it."""
    global _process_default
    _process_default = _validated(codec)


@contextmanager
def compression_scope(codec):
    """Lexically scoped compression default::

        with mpi.config.compression_scope("q8"):
            y = comm.Allreduce(g, mpi.MPI_SUM)   # rides the wire as int8

    ``compression_scope(None)`` forces exact transfers for the block even
    when a process default is set.  The scope itself is per-thread (like
    ``deterministic_mode``): a scope opened before ``run_ranks`` is not
    seen by the rank-threads — use :func:`set_default_compression`, open
    the scope inside the rank body, or pass ``compression=`` explicitly
    for collective agreement there.  ``run_spmd`` re-reads the value at
    call time and makes it part of its jit cache key, so toggling
    retraces."""
    prev = getattr(_state, "compression", _UNSET)
    _state.compression = _validated(codec)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.compression
        else:
            _state.compression = prev

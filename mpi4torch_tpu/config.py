"""Framework configuration flags.

The reference has no config system (SURVEY.md §5: three compile-time toggles
total).  This framework adds two semantic knobs:

``deterministic_reductions`` — when True, SPMD-mode SUM reductions are
computed as an all-gather followed by a fixed ascending-rank-order fold,
which is bit-identical to the eager thread-SPMD oracle (the 'MPI linear
order' reference) at the cost of bandwidth; when False (default), they lower
to ``lax.psum`` — the XLA/ICI-native reduction, fastest but with
compiler-chosen combining order (ulp-level differences possible).

``default_compression`` — the wire-compression codec applied by default to
``Allreduce``/``Allgather`` calls that do not pass an explicit
``compression=`` argument (mpi4torch_tpu.compress; None = exact fp wire).
Set it process-wide with :func:`set_default_compression` or lexically with
the :func:`compression_scope` context manager.  Like the deterministic
flag, the value is read at *trace* time: ``run_spmd`` makes it part of the
jit cache key so toggling retraces, but a user-managed ``jax.jit`` that
already traced keeps its lowering until it retraces.

``default_bucket_bytes`` — the target flat-bucket size of the fused tree
collectives (mpi4torch_tpu.fuse; the per-leaf→per-bucket launch
reduction).  ~4 MiB default, the production-stack sweet spot between
launch amortization and overlap granularity.  Set process-wide with
:func:`set_default_bucket_bytes` or lexically with :func:`fusion_scope`;
``fusion_scope(0)`` disables fusion (per-leaf collectives) for the
block.  Read at trace time like the other knobs; ``run_spmd`` keys its
jit cache on it.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_state = threading.local()


def deterministic_reductions() -> bool:
    return getattr(_state, "deterministic", False)


def set_deterministic_reductions(value: bool) -> None:
    _state.deterministic = bool(value)


@contextmanager
def deterministic_mode(value: bool = True):
    prev = deterministic_reductions()
    set_deterministic_reductions(value)
    try:
        yield
    finally:
        set_deterministic_reductions(prev)


# Sentinel distinguishing "no scope active on this thread" from an explicit
# compression_scope(None) (which forces exact transfers within the block).
_UNSET = object()
_process_default = None


def default_compression():
    """The codec (object or registered name) facade ops use when
    ``compression=None`` is passed: the innermost active
    :func:`compression_scope` on this thread, else the process-wide
    :func:`set_default_compression` value (None = no compression)."""
    scoped = getattr(_state, "compression", _UNSET)
    return _process_default if scoped is _UNSET else scoped


def _validated(codec):
    if codec is None:
        return None
    from .compress import get_codec

    return get_codec(codec)  # resolve names; ad-hoc codec objects pass


def set_default_compression(codec) -> None:
    """Set the process-wide default wire-compression codec (a registered
    name, a Codec object, or None to disable).  Visible on every thread —
    including ``run_ranks`` rank-threads — unless a thread's own
    :func:`compression_scope` overrides it."""
    global _process_default
    _process_default = _validated(codec)


# Fused-collective bucket size (mpi4torch_tpu.fuse).  4 MiB: large enough
# to amortize per-collective launch + ring latency over hundreds of tiny
# leaves, small enough that a grad tree still splits into several buckets
# whose transfers the overlap scheduler can keep in flight concurrently.
DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024
_process_bucket_bytes = DEFAULT_BUCKET_BYTES


def default_bucket_bytes() -> int:
    """Bucket size (bytes) the fused tree collectives use when no
    explicit ``bucket_bytes=`` is passed: the innermost active
    :func:`fusion_scope` on this thread, else the process-wide
    :func:`set_default_bucket_bytes` value.  ``0`` disables fusion
    (per-leaf collectives)."""
    scoped = getattr(_state, "bucket_bytes", _UNSET)
    return _process_bucket_bytes if scoped is _UNSET else scoped


def _validated_bucket_bytes(nbytes) -> int:
    if nbytes is False:
        return 0
    nbytes = int(nbytes)
    if nbytes < 0:
        raise ValueError(f"bucket_bytes must be >= 0, got {nbytes}")
    return nbytes


def set_default_bucket_bytes(nbytes) -> None:
    """Set the process-wide fused-collective bucket size in bytes
    (``0``/``False`` = fusion off → per-leaf collectives)."""
    global _process_bucket_bytes
    _process_bucket_bytes = _validated_bucket_bytes(nbytes)


@contextmanager
def fusion_scope(bucket_bytes):
    """Lexically scoped bucket size for the fused tree collectives::

        with mpi.config.fusion_scope(1 << 20):   # 1 MiB buckets
            grads = comm.Allreduce_tree(grads, mpi.MPI_SUM, mean=True)

        with mpi.config.fusion_scope(0):         # per-leaf, unfused
            ...

    Per-thread like :func:`compression_scope` (a scope opened before
    ``run_ranks`` is not seen by the rank-threads — use
    :func:`set_default_bucket_bytes` or open the scope inside the rank
    body).  ``run_spmd`` re-reads the value at call time and makes it
    part of its jit cache key, so toggling retraces."""
    prev = getattr(_state, "bucket_bytes", _UNSET)
    _state.bucket_bytes = _validated_bucket_bytes(bucket_bytes)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.bucket_bytes
        else:
            _state.bucket_bytes = prev


@contextmanager
def compression_scope(codec):
    """Lexically scoped compression default::

        with mpi.config.compression_scope("q8"):
            y = comm.Allreduce(g, mpi.MPI_SUM)   # rides the wire as int8

    ``compression_scope(None)`` forces exact transfers for the block even
    when a process default is set.  The scope itself is per-thread (like
    ``deterministic_mode``): a scope opened before ``run_ranks`` is not
    seen by the rank-threads — use :func:`set_default_compression`, open
    the scope inside the rank body, or pass ``compression=`` explicitly
    for collective agreement there.  ``run_spmd`` re-reads the value at
    call time and makes it part of its jit cache key, so toggling
    retraces."""
    prev = getattr(_state, "compression", _UNSET)
    _state.compression = _validated(codec)
    try:
        yield
    finally:
        if prev is _UNSET:
            del _state.compression
        else:
            _state.compression = prev

"""Model zoo built ON the communication primitives.

The reference ships no models (SURVEY.md §0: "no models, no trainer") — its
examples hand-build data parallelism from `Allreduce`.  This package provides
the same thing at framework quality: small pure-JAX model families whose
*distribution* is expressed exclusively through the mpi4torch_tpu op surface
(`Allreduce`, `Alltoall`, `Isend/Irecv/Wait`, ...), so they double as
executable documentation of each parallelism strategy (SURVEY.md §2.5) and
as the flagship programs for the benchmark/graft entry points.
"""

from . import mlp, resnet, transformer, vit

__all__ = ["mlp", "resnet", "transformer", "vit"]

"""ResNet-18 (CIFAR variant), TPU-native, for data-parallel training.

BASELINE.md parity config #4: "Data-parallel ResNet-18/CIFAR-10,
per-param-grad Allreduce".  The reference ships no models (SURVEY.md §0) —
DP is a user pattern over its differentiable Allreduce (reference:
examples/simple_linear_regression.py:27-35, README.md:34-46); this module
provides the model the config names plus both DP recipes:

* :func:`dp_grad_train_step` — the classic DDP recipe the config asks for:
  local backward, then one ``Allreduce(grad, MPI_SUM)/size`` per parameter
  leaf.  Here the Allreduce runs on *gradient values* (no AD through it).
* :func:`dp_loss_train_step` — the reference's own pattern: collectives
  inside the loss, gradients produced by the *adjoint* Allreduce.

Both keep replicas bit-identical in lock-step (tests/test_resnet.py).

TPU-first design choices: NHWC activations and HWIO filters (the XLA/TPU
native convolution layout — no transposes around the MXU), all compute in
batched convs/matmuls, BatchNorm as a pure function threading running
statistics through the step (JAX is functional; there is no module state).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from ..constants import MPI_SUM

# NHWC / HWIO / NHWC: the TPU-native convolution dimension numbers.
_DIMNUMS = ("NHWC", "HWIO", "NHWC")


@dataclass(frozen=True)
class ResNetConfig:
    """CIFAR-style ResNet-18: 3x3 stem (no max-pool), 4 stages of 2 basic
    blocks at widths (64, 128, 256, 512), global average pool, linear head."""

    num_classes: int = 10
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    widths: Tuple[int, ...] = (64, 128, 256, 512)
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), dtype)
    return w * jnp.sqrt(jnp.asarray(2.0 / fan_in, dtype))


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state_init(c, dtype):
    return {"mean": jnp.zeros((c,), dtype), "var": jnp.ones((c,), dtype)}


def _block_stride(si: int, bi: int) -> int:
    """The single source of truth for block strides (init and forward must
    agree): the first block of every stage after the first downsamples."""
    return 2 if (bi == 0 and si > 0) else 1


def init_resnet(key, cfg: ResNetConfig, in_channels: int = 3,
                dtype=jnp.float32):
    """Returns ``(params, state)`` pytrees.

    ``params`` are the trainable leaves (conv filters, BN affine, head);
    ``state`` is the non-trainable BN running statistics, threaded through
    :func:`forward` functionally."""
    def next_key():
        nonlocal key
        key, sub = jax.random.split(key)
        return sub

    params = {"stem": {"conv": _conv_init(next_key(), 3, 3, in_channels,
                                          cfg.widths[0], dtype),
                       "bn": _bn_init(cfg.widths[0], dtype)}}
    state = {"stem": {"bn": _bn_state_init(cfg.widths[0], dtype)}}

    cin = cfg.widths[0]
    stages = []
    stages_state = []
    for si, (width, nblocks) in enumerate(zip(cfg.widths, cfg.stage_sizes)):
        blocks = []
        blocks_state = []
        for bi in range(nblocks):
            stride = _block_stride(si, bi)
            block = {
                "conv1": _conv_init(next_key(), 3, 3, cin, width, dtype),
                "bn1": _bn_init(width, dtype),
                "conv2": _conv_init(next_key(), 3, 3, width, width, dtype),
                "bn2": _bn_init(width, dtype),
            }
            bstate = {"bn1": _bn_state_init(width, dtype),
                      "bn2": _bn_state_init(width, dtype)}
            if stride != 1 or cin != width:
                block["proj"] = _conv_init(next_key(), 1, 1, cin, width,
                                           dtype)
                block["bn_proj"] = _bn_init(width, dtype)
                bstate["bn_proj"] = _bn_state_init(width, dtype)
            blocks.append(block)
            blocks_state.append(bstate)
            cin = width
        stages.append(blocks)
        stages_state.append(blocks_state)
    params["stages"] = stages
    state["stages"] = stages_state

    wk = next_key()
    params["head"] = {
        "w": jax.random.normal(wk, (cin, cfg.num_classes), dtype)
        / jnp.sqrt(jnp.asarray(cin, dtype)),
        "b": jnp.zeros((cfg.num_classes,), dtype),
    }
    return params, state


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=_DIMNUMS)


def _batch_norm(x, p, s, cfg: ResNetConfig, train: bool):
    """Pure-function BatchNorm over (N, H, W); returns (y, new_state).

    In train mode the normalizing statistics are the *local batch's* — under
    DP each rank normalizes its own shard (the standard non-synced-BN DDP
    semantics); running stats are an EMA carried in ``state``."""
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_s = {"mean": m * s["mean"] + (1 - m) * mean,
                 "var": m * s["var"] + (1 - m) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + cfg.bn_eps)
    y = (x - mean) * inv * p["scale"] + p["bias"]
    return y, new_s


def _basic_block(x, p, s, cfg, stride, train):
    y, s1 = _batch_norm(_conv(x, p["conv1"], stride), p["bn1"], s["bn1"],
                        cfg, train)
    y = jax.nn.relu(y)
    y, s2 = _batch_norm(_conv(y, p["conv2"]), p["bn2"], s["bn2"], cfg, train)
    new_s = {"bn1": s1, "bn2": s2}
    if "proj" in p:
        x, sp = _batch_norm(_conv(x, p["proj"], stride), p["bn_proj"],
                            s["bn_proj"], cfg, train)
        new_s["bn_proj"] = sp
    return jax.nn.relu(x + y), new_s


def forward(cfg: ResNetConfig, params, state, images, train: bool = True):
    """Logits for NHWC ``images``; returns ``(logits, new_state)``."""
    x, stem_s = _batch_norm(_conv(images, params["stem"]["conv"]),
                            params["stem"]["bn"], state["stem"]["bn"],
                            cfg, train)
    x = jax.nn.relu(x)
    new_state = {"stem": {"bn": stem_s}, "stages": []}
    for si, (blocks, bstates, width) in enumerate(
            zip(params["stages"], state["stages"], cfg.widths)):
        stage_s = []
        for bi, (bp, bs) in enumerate(zip(blocks, bstates)):
            x, ns = _basic_block(x, bp, bs, cfg, _block_stride(si, bi), train)
            stage_s.append(ns)
        new_state["stages"].append(stage_s)
    x = jnp.mean(x, axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def local_loss(cfg: ResNetConfig, params, state, batch, train: bool = True):
    """Mean softmax cross-entropy on the rank-local batch; returns
    ``(loss, new_state)``."""
    images, labels = batch
    logits, new_state = forward(cfg, params, state, images, train)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(ce), new_state


def dp_grad_train_step(comm, cfg: ResNetConfig, params, state, batch,
                       lr: float = 0.1):
    """One SGD step with the classic DDP recipe (BASELINE.md config #4):
    local backward first, then one ``Allreduce(g, MPI_SUM)/size`` per
    parameter gradient.  Returns ``(global_loss, new_params, new_state)``.

    The Allreduce here acts on already-computed gradient *values* — the
    same call as the reference's, just on the other side of backward.  BN
    running stats are likewise Allreduce-averaged so evaluation state stays
    replica-identical."""
    (loss, new_state), grads = jax.value_and_grad(
        lambda p: local_loss(cfg, p, state, batch), has_aux=True)(params)
    size = comm.size
    grads = jax.tree.map(lambda g: comm.Allreduce(g, MPI_SUM) / size, grads)
    global_loss = comm.Allreduce(loss, MPI_SUM) / size
    new_state = jax.tree.map(
        # compression=False: BN running stats are carried state — codec
        # error would accumulate across steps.
        lambda s: comm.Allreduce(s, MPI_SUM, compression=False) / size,
        new_state)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return global_loss, new_params, new_state


def dp_loss_train_step(comm, cfg: ResNetConfig, params, state, batch,
                       lr: float = 0.1):
    """One SGD step with the reference's in-loss recipe (parameter-averaging
    Allreduce + loss Allreduce; gradients come from the *adjoint* Allreduce
    — reference: doc/examples.rst:24-65).  Returns
    ``(global_loss, new_params, new_state)``."""
    size = comm.size

    def global_loss_fn(p):
        p = jax.tree.map(lambda t: comm.Allreduce(t, MPI_SUM) / size, p)
        loss, ns = local_loss(cfg, p, state, batch)
        return comm.Allreduce(loss, MPI_SUM) / size, ns

    (loss, new_state), grads = jax.value_and_grad(
        global_loss_fn, has_aux=True)(params)
    new_state = jax.tree.map(
        # compression=False: BN running stats are carried state — codec
        # error would accumulate across steps.
        lambda s: comm.Allreduce(s, MPI_SUM, compression=False) / size,
        new_state)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return loss, new_params, new_state

"""Flagship model: decoder-only transformer, distributed 2D (dp x sp).

The capstone composition of the framework's strategy layer (SURVEY.md
§2.5): data parallelism over one mesh axis via the reference's two-Allreduce
recipe, and long-context sequence/context parallelism over a second axis —
the sequence dimension is sharded across ranks and attention runs as ring
attention (blockwise, K/V circulating over the differentiable
Isend/Irecv ring) or Ulysses (head<->sequence Alltoall).  Every distributed
movement is an ``MPI_Communicator`` op, so the same model runs on the eager
thread-SPMD runtime, inside ``run_spmd``, or in a user-managed 2D
``shard_map`` via ``comm_from_mesh`` (the intended TPU deployment).

TPU-first shapes: all compute is batched matmul/einsum (MXU), parameters
and activations stay in the caller's dtype (bfloat16-ready), and the
sequence axis per rank is static so XLA tiles cleanly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..constants import MPI_SUM
from ..ops.flash import flash_attention, flash_block_attention
from ..parallel.attention import ring_attention, \
    ulysses_attention, zigzag_ring_attention
from ..parallel.dp import all_average_tree
from ..parallel.moe import init_moe, moe_ffn, moe_ffn_dense
from ..parallel.zero import zero3_step, zero_step
from ..parallel.ring import ring_shift


@dataclass(frozen=True)
class TransformerConfig:
    """Static model hyperparameters (kept OUT of the parameter pytree so
    grads/optimizer tree-maps see arrays only).

    ``n_experts > 0`` switches every block's FFN to an expert-parallel MoE
    (capacity-based top-1 routing over the differentiable ``Alltoall``,
    parallel/moe.py); ``capacity`` is the per-(expert, source-rank) slot
    count, ``aux_coef`` weights the load-balancing loss in :func:`lm_loss`.

    ``remat`` rematerializes each block in the backward pass
    (``jax.checkpoint``): activation memory drops from O(layers) to O(1)
    blocks at the cost of one extra forward — the HBM-for-FLOPs trade.
    Collectives inside a rematted block re-execute during backward, which
    is SPMD-symmetric (every rank reruns the same sequence, so no
    deadlock); it requires the traced (SPMD/jit) path — the eager
    thread-SPMD backend's ops execute imperatively and refuse tracing."""
    vocab: int
    d_model: int
    n_heads: int
    n_layers: int
    d_ff: int
    max_seq: int
    n_kv_heads: int = 0
    attn_window: int = 0
    rope: bool = False
    rope_theta: float = 10000.0
    norm: str = "layernorm"
    ffn: str = "gelu"
    n_experts: int = 0
    capacity: int = 0
    aux_coef: float = 0.01
    remat: bool = False

    def __post_init__(self):
        if self.n_experts > 0 and self.capacity <= 0:
            # capacity=0 would silently capacity-drop every token — the
            # model would train with no FFN path at all.
            raise ValueError(
                f"n_experts={self.n_experts} requires capacity > 0, got "
                f"{self.capacity}")
        if self.n_kv_heads:
            # Grouped-query attention (ops/flash.py): q head h reads KV
            # head h // (n_heads // n_kv_heads).  0 = plain MHA.
            if self.n_kv_heads < 0 or self.n_heads % self.n_kv_heads != 0:
                raise ValueError(
                    f"n_heads={self.n_heads} must be a positive multiple "
                    f"of n_kv_heads={self.n_kv_heads}")

        if self.attn_window < 0:
            raise ValueError(
                f"attn_window must be >= 0 (0 = full causal attention), "
                f"got {self.attn_window}")
        if self.rope and (self.d_model // self.n_heads) % 2 != 0:
            raise ValueError(
                f"rope requires an even head_dim, got "
                f"{self.d_model // self.n_heads}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown norm {self.norm!r}")
        if self.ffn not in ("gelu", "swiglu"):
            raise ValueError(f"unknown ffn {self.ffn!r}")
        if self.ffn == "swiglu" and self.n_experts > 0:
            raise ValueError(
                "ffn='swiglu' applies to the dense FFN; the MoE experts "
                "(n_experts > 0) keep their own gelu expert MLPs")

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def init_transformer(key, cfg: TransformerConfig,
                     dtype=jnp.float32) -> Dict[str, Any]:
    """Parameter pytree for a pre-LN decoder-only transformer."""
    vocab, d_model, d_ff = cfg.vocab, cfg.d_model, cfg.d_ff
    n_layers, max_seq = cfg.n_layers, cfg.max_seq
    def dense(key, m, n):
        return jax.random.normal(key, (m, n), dtype) / jnp.sqrt(
            jnp.asarray(m, dtype))

    def norm_p():
        p = {"scale": jnp.ones((d_model,), dtype)}
        if cfg.norm == "layernorm":
            p["bias"] = jnp.zeros((d_model,), dtype)
        return p

    keys = iter(jax.random.split(key, 4 + 7 * n_layers))
    params: Dict[str, Any] = {
        "embed": jax.random.normal(next(keys), (vocab, d_model), dtype) * 0.02,
        "blocks": [],
    }
    # The pos key is drawn UNCONDITIONALLY at its historical position in
    # the stream (and discarded under rope): making the draw conditional
    # would shift every later key and silently change all existing
    # non-rope initializations for the same seed.
    pos_key = next(keys)
    if not cfg.rope:
        # Learned absolute positions; under rope the encoding is applied
        # rotationally to q/k instead (no table, no max_seq cap on the
        # encoding itself).
        params["pos"] = jax.random.normal(
            pos_key, (max_seq, d_model), dtype) * 0.02
    params["ln_f"] = norm_p()
    params["unembed"] = dense(next(keys), d_model, vocab)
    for _ in range(n_layers):
        # Fused projection: h q-heads plus 2*h_kv KV heads (= 3*d_model
        # for plain MHA; smaller under GQA).
        hd = d_model // cfg.n_heads
        blk = {
            "ln1": norm_p(),
            "wqkv": dense(next(keys), d_model,
                          d_model + 2 * cfg.kv_heads * hd),
            "wo": dense(next(keys), d_model, d_model),
            "ln2": norm_p(),
        }
        if cfg.n_experts > 0:
            blk["moe"] = init_moe(next(keys), cfg.n_experts, d_model, d_ff,
                                  dtype)
        elif cfg.ffn == "swiglu":
            # Gate and up projections fused into one (d, 2*d_ff) matmul.
            blk["w1"] = dense(next(keys), d_model, 2 * d_ff)
            blk["w2"] = dense(next(keys), d_ff, d_model)
        else:
            blk["w1"] = dense(next(keys), d_model, d_ff)
            blk["w2"] = dense(next(keys), d_ff, d_model)
        params["blocks"].append(blk)
    return params


def _layer_norm(x, p):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * p["scale"] + p["bias"]


def _rms_norm(x, p):
    # No centering, no bias: normalize by the root-mean-square alone —
    # one fewer reduction and a smaller param set than LayerNorm.
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x / jnp.sqrt(ms + 1e-5) * p["scale"]


def _norm(cfg: TransformerConfig, x, p):
    return _rms_norm(x, p) if cfg.norm == "rmsnorm" else _layer_norm(x, p)


def _rope_rotate(cfg: TransformerConfig, x, positions):
    """Rotary position embedding (half-split convention): rotate each
    (x[i], x[i+hd/2]) pair of head-dim channels by ``pos * theta^(-2i/hd)``.
    Attention scores of two rotated vectors depend only on their position
    DIFFERENCE — the relative encoding that lets trained models attend
    beyond any absolute position table (the long-context default; the
    learned absolute table hard-caps at max_seq).  ``positions`` (s,) may
    be traced (rank-symbolic global offsets under SPMD), so the sharded
    shards of one sequence rotate consistently and ring/Ulysses need no
    special handling: q/k are rotated BEFORE any transport.

    ``positions`` may also be ``(b, s)`` — per-ROW positions, the
    continuous-batching decode path (:mod:`mpi4torch_tpu.serve`) where
    every slot of the batch sits at its own position.  The rotation is
    per head-dim channel, so tensor-parallel head sharding composes
    unchanged either way."""
    hd = x.shape[-1]
    half = hd // 2
    ct = _compute_dtype_rope(x)
    inv = cfg.rope_theta ** (-jnp.arange(half, dtype=ct) * 2.0 / hd)
    positions = jnp.asarray(positions)
    if positions.ndim == 1:
        ang = positions.astype(ct)[:, None] * inv[None, :]    # (s, half)
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    else:
        ang = positions.astype(ct)[..., None] * inv           # (b, s, half)
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half].astype(ct), x[..., half:].astype(ct)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def _compute_dtype_rope(x):
    # Angles at least f32 (bf16 positions would alias long-context
    # phases); f64 params keep f64 so oracle tests compare at 1e-12.
    return jnp.promote_types(x.dtype, jnp.float32)


def _split_qkv(cfg: TransformerConfig, blk, y, positions=None):
    """Project ``y`` (b, s, d) through the fused qkv matrix and split into
    ``q (b, s, h, hd)`` and ``k``/``v (b, s, kv_heads, hd)`` — the ONE
    place the asymmetric GQA projection layout lives (forward, prefill
    and decode all slice through here, so they cannot drift apart)."""
    b, s = y.shape[0], y.shape[1]
    h, h_kv = cfg.n_heads, cfg.kv_heads
    hd = cfg.d_model // h
    qkv = y @ blk["wqkv"]
    q = qkv[..., :h * hd].reshape(b, s, h, hd)
    k = qkv[..., h * hd:(h + h_kv) * hd].reshape(b, s, h_kv, hd)
    v = qkv[..., (h + h_kv) * hd:].reshape(b, s, h_kv, hd)
    if cfg.rope:
        if positions is None:
            raise ValueError("cfg.rope requires the caller's positions")
        q = _rope_rotate(cfg, q, positions)
        k = _rope_rotate(cfg, k, positions)
    return q, k, v


def _ffn_residual(cfg: TransformerConfig, blk, x, comm_ep):
    """Post-attention FFN (dense or MoE) with pre-LN and residual; shared
    by the training forward and the decode path.  Returns ``(x, aux)``.

    MoE routing note: capacity competition is over exactly the tokens in
    ``x`` — a whole (batch x seq) call during training/prefill, one
    position's batch during incremental decode.  When capacity binds,
    the two can therefore drop different tokens; teacher-forcing
    equivalence between :func:`forward` and :func:`decode_step` is exact
    whenever capacity does not bind (see :func:`decode_step`)."""
    b_s = x.shape[:-1]
    d = x.shape[-1]
    y = _norm(cfg, x, blk["ln2"])
    if cfg.n_experts > 0:
        flat = y.reshape(-1, d)
        if comm_ep is not None and comm_ep.size > 1:
            ff, aux = moe_ffn(comm_ep, flat, blk["moe"], cfg.capacity)
        else:
            ff, aux = moe_ffn_dense(flat, blk["moe"], cfg.capacity)
        return x + ff.reshape(*b_s, d), aux
    if cfg.ffn == "swiglu":
        gate_up = y @ blk["w1"]
        gate, up = jnp.split(gate_up, 2, axis=-1)
        return x + (jax.nn.silu(gate) * up) @ blk["w2"], \
            jnp.zeros((), x.dtype)
    return x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"], \
        jnp.zeros((), x.dtype)


def _zigzag_positions(comm_sp, s_local: int):
    """Global positions of this rank's zigzag sequence shard (symbolic
    rank safe) — by slicing the global position axis with the ONE
    layout-defining helper, so the transformer's position/label math can
    never drift from the data sharding in parallel/attention.py."""
    from ..parallel.attention import zigzag_slice

    return zigzag_slice(
        comm_sp, jnp.arange(comm_sp.size * s_local, dtype=jnp.int32),
        axis=0)


def _attention(q, k, v, comm_sp, attn: str, window: int = 0):
    if attn not in ("dense", "ring", "ulysses", "zigzag"):
        raise ValueError(f"unknown attention strategy {attn!r}")
    if comm_sp is None or comm_sp.size == 1:
        # The fused flash path: Pallas kernel on eligible TPU shapes
        # (scores never hit HBM), jnp otherwise — numerically the same
        # softmax as :func:`dense_attention`, which stays the test oracle.
        return flash_attention(q, k, v, causal=True, window=window)
    if attn == "dense":
        raise ValueError(
            "attn='dense' cannot see across sequence shards: with a "
            "size>1 sequence-parallel communicator each rank would attend "
            "only within its own block (and mask as if it started at "
            "position 0).  Use attn='ring' or attn='ulysses', or pass "
            "comm_sp=None with the full sequence."
        )
    if attn == "ring":
        return ring_attention(comm_sp, q, k, v, causal=True, window=window)
    if attn == "zigzag":
        if window:
            raise ValueError(
                "attn='zigzag' does not compose with attn_window: a "
                "sliding window already balances causal work (every "
                "query sees the same key count), which is the whole "
                "point of the zigzag layout — use attn='ring' for "
                "windowed sequence parallelism")
        return zigzag_ring_attention(comm_sp, q, k, v)
    return ulysses_attention(comm_sp, q, k, v, causal=True, window=window)


def forward(cfg: TransformerConfig, params, tokens, comm_sp=None,
            attn: str = "ring", comm_ep=None, return_aux: bool = False,
            return_hidden: bool = False):
    """Logits for a (batch, seq_local) shard of token ids.

    ``comm_sp`` is the sequence-parallel communicator (or None for a full
    unsharded sequence); ``tokens`` holds this rank's contiguous sequence
    block, rank-major.  With sp sharding, positional embeddings are indexed
    at *global* positions (rank offset may be a traced ``lax.axis_index``).

    With ``cfg.n_experts > 0`` each block's FFN is the expert-parallel MoE
    (experts sharded over ``comm_ep``; pass None to keep all experts
    local).  ``return_aux`` additionally returns the summed load-balancing
    loss.  ``return_hidden`` returns the post-``ln_f`` hidden states
    (batch, seq_local, d_model) INSTEAD of logits — the unembedding is
    skipped so :func:`lm_loss`'s chunked-vocab path can fold it into the
    online-logsumexp scan without ever materializing the logits.
    """
    b, s_local = tokens.shape
    h = cfg.n_heads
    if comm_sp is not None and comm_sp.size > 1:
        if not cfg.rope and comm_sp.size * s_local > cfg.max_seq:
            # Without this, the positional-table dynamic_slice would
            # clamp the high ranks' start offsets and silently reuse the
            # last positional block.  Under rope there is no table and
            # no cap: positions are computed directly, and training past
            # max_seq is exactly the beyond-table long-context case the
            # relative encoding exists for.
            raise ValueError(
                f"global sequence {comm_sp.size * s_local} (sp="
                f"{comm_sp.size} x s_local={s_local}) exceeds cfg.max_seq "
                f"{cfg.max_seq}")
        offset = jnp.asarray(comm_sp.rank) * s_local
    else:
        offset = 0
    zigzag_sharded = (attn == "zigzag" and comm_sp is not None
                      and comm_sp.size > 1)
    if zigzag_sharded:
        # This rank's tokens are the ZIGZAG shard (chunk r + mirror
        # chunk 2*sp-1-r; parallel.zigzag_slice produces it) — two
        # global position intervals, not one.
        positions = _zigzag_positions(comm_sp, s_local)
    else:
        positions = offset + jnp.arange(s_local, dtype=jnp.int32)
    x = params["embed"][tokens]
    if not cfg.rope:
        if zigzag_sharded:
            x = x + jnp.take(params["pos"], positions, axis=0)[None]
        else:
            x = x + jax.lax.dynamic_slice_in_dim(
                params["pos"], offset, s_local, 0)[None]
    d = x.shape[-1]
    aux_total = jnp.zeros((), x.dtype)

    def block_fn(x, blk):
        y = _norm(cfg, x, blk["ln1"])
        q, k, v = _split_qkv(cfg, blk, y, positions)
        o = _attention(q, k, v, comm_sp, attn, cfg.attn_window)
        x = x + o.reshape(b, s_local, d) @ blk["wo"]
        x, aux = _ffn_residual(cfg, blk, x, comm_ep)
        return x, aux

    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)
    for blk in params["blocks"]:
        x, aux = block_fn(x, blk)
        aux_total = aux_total + aux
    x = _norm(cfg, x, params["ln_f"])
    if return_hidden:
        out = x
    else:
        out = x @ params["unembed"]
    if return_aux:
        return out, aux_total
    return out


def init_kv_cache(cfg: TransformerConfig, batch: int, dtype=jnp.float32):
    """Per-layer K/V cache for incremental decoding, shaped
    ``(batch, max_seq, kv_heads, head_dim)`` — under GQA the cache holds
    only the KV heads (the whole point: at ``n_kv_heads = n_heads/8`` the
    decode-time cache is 8x smaller, which is the HBM-resident state that
    bounds TPU batch size during serving)."""
    hd = cfg.d_model // cfg.n_heads
    shape = (batch, cfg.max_seq, cfg.kv_heads, hd)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(cfg.n_layers)]


def decode_step(cfg: TransformerConfig, params, cache, tokens, pos):
    """One incremental decode step: logits for ``tokens`` (batch,) at
    position ``pos`` (scalar, may be traced), updating the KV cache.

    Returns ``(logits (batch, vocab), new_cache)``.  Attention runs the
    query against the full cache buffer with position-based masking
    (causal + ``cfg.attn_window``): slots beyond ``pos`` are masked as
    future, so the static ``max_seq`` buffer needs no length bookkeeping
    — the XLA-native shape discipline (no dynamic shapes, one compiled
    program for every step).  Jit-compatible: drive it under
    ``lax.scan`` (:func:`generate`).

    Teacher-forcing equivalence: feeding the training sequence token by
    token reproduces :func:`forward`'s logits exactly
    (tests/test_transformer.py TestDecoding) — with one carve-out: MoE
    capacity competition is per *call* (see :func:`_ffn_residual`), so
    with ``n_experts > 0`` the equivalence holds only while capacity
    does not bind (decode routes ``batch`` tokens per step vs a whole
    batch x seq during training)."""
    b = tokens.shape[0]
    try:
        # Concrete positions are checked eagerly: past max_seq the
        # dynamic slice/update would CLAMP — reusing the last positional
        # embedding and overwriting the last cache slot with plausible
        # but wrong results (the same hazard forward() guards).  Traced
        # positions (inside scan/jit) can't be checked here; generate()
        # enforces the bound before tracing.
        if not 0 <= int(pos) < cfg.max_seq:
            raise ValueError(
                f"decode position {int(pos)} out of range: cfg.max_seq "
                f"is {cfg.max_seq}")
    except jax.errors.ConcretizationTypeError:
        pass
    pos = jnp.asarray(pos, jnp.int32)

    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + jax.lax.dynamic_slice_in_dim(params["pos"], pos, 1, 0)[0]

    # Sliding-window serving win: with attn_window set, the query only
    # sees its last `window` positions, so attention runs on a
    # position-tracking STATIC slice of the cache (power-of-two bucket
    # >= window, one compiled program for all steps) instead of the full
    # max_seq buffer — each decoded token costs O(window), not
    # O(max_seq).  Without a window the full buffer is the visible set.
    win = cfg.attn_window
    bucket = cfg.max_seq
    if win:
        bucket = 1
        while bucket < win:
            bucket *= 2
        bucket = min(bucket, cfg.max_seq)

    new_cache = []
    for blk, c in zip(params["blocks"], cache):
        y = _norm(cfg, x, blk["ln1"])
        q, k_new, v_new = _split_qkv(cfg, blk, y[:, None, :], pos[None])
        # The cache dtype is authoritative (it may be an override, e.g. a
        # bf16 serving cache under f32 params — ADVICE r4): cast the
        # projected k/v to it before the in-place update.
        ck = jax.lax.dynamic_update_slice_in_dim(
            c["k"], k_new.astype(c["k"].dtype), pos, 1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            c["v"], v_new.astype(c["v"].dtype), pos, 1)
        new_cache.append({"k": ck, "v": cv})
        if bucket < cfg.max_seq:
            # Earliest slice start that still covers [pos-win+1, pos];
            # in-window masking inside the kernel does the rest.
            start = jnp.clip(pos - bucket + 1, 0, cfg.max_seq - bucket)
            kk = jax.lax.dynamic_slice_in_dim(ck, start, bucket, 1)
            vv = jax.lax.dynamic_slice_in_dim(cv, start, bucket, 1)
            kv_off = start
        else:
            kk, vv, kv_off = ck, cv, 0
        o, _ = flash_block_attention(
            q, kk, vv, causal=True, q_offset=pos, kv_offset=kv_off,
            window=win, impl="jnp")
        x = x + o.reshape(b, cfg.d_model).astype(x.dtype) @ blk["wo"]
        x, _ = _ffn_residual(cfg, blk, x, None)
    x = _norm(cfg, x, params["ln_f"])
    return x @ params["unembed"], new_cache


def prefill(cfg: TransformerConfig, params, cache, prompt):
    """Populate the KV cache from a whole prompt in ONE batched pass (the
    training forward's compute shape — MXU-sized matmuls over the full
    prompt — rather than prompt_len sequential single-token steps) and
    return ``(last_logits (batch, vocab), new_cache)``."""
    b, p_len = prompt.shape
    x = params["embed"][prompt]
    if not cfg.rope:
        x = x + params["pos"][None, :p_len]
    new_cache = []
    for blk, c in zip(params["blocks"], cache):
        y = _norm(cfg, x, blk["ln1"])
        q, k, v = _split_qkv(cfg, blk, y,
                             jnp.arange(p_len, dtype=jnp.int32))
        # Cache dtype is authoritative (possible serving override; see
        # decode_step) — attention itself runs on the params-dtype k/v
        # of this very pass, so prefill logits are unaffected by a
        # lower-precision cache.
        ck = jax.lax.dynamic_update_slice_in_dim(
            c["k"], k.astype(c["k"].dtype), 0, 1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            c["v"], v.astype(c["v"].dtype), 0, 1)
        new_cache.append({"k": ck, "v": cv})
        o = flash_attention(q, k, v, causal=True, window=cfg.attn_window)
        x = x + o.reshape(b, p_len, cfg.d_model) @ blk["wo"]
        x, _ = _ffn_residual(cfg, blk, x, None)
    x = _norm(cfg, x, params["ln_f"])
    return x[:, -1] @ params["unembed"], new_cache


def _select_token(logits, key, temperature: float, top_k: int, dtype):
    """One decoding choice from (batch, vocab) logits: greedy when
    ``temperature == 0``, else categorical sampling at the given
    temperature, optionally restricted to the ``top_k`` highest logits
    (0 = no restriction)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(dtype)
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    return jax.random.categorical(
        key, logits / temperature, axis=-1).astype(dtype)


def select_token(logits, key, temperature: float, top_k: int, dtype):
    """Public decoding-choice rule — THE sampling function of
    :func:`generate`, exported so the serving engine
    (:mod:`mpi4torch_tpu.serve`) samples every slot with the identical
    rule and key discipline: engine-vs-``generate()`` token parity holds
    by construction rather than by parallel edits."""
    return _select_token(logits, key, temperature, top_k, dtype)


def generate(cfg: TransformerConfig, params, prompt, n_new: int,
             dtype=None, temperature: float = 0.0, top_k: int = 0,
             key=None):
    """Autoregressive decoding: prefill the cache from ``prompt``
    (batch, prompt_len) in one batched pass, then emit ``n_new`` tokens
    incrementally.

    ``temperature == 0`` (default) is greedy argmax; ``temperature > 0``
    samples categorically (requires ``key``), optionally from only the
    ``top_k`` highest-logit tokens.  Generation is a single ``lax.scan``
    over :func:`decode_step` (each emitted token fed back in): every
    step within a generation shares one compiled step program (a
    distinct ``n_new`` still traces a new scan — fix the serving-side
    token budget to avoid recompiles).  The cache dtype follows the
    parameters unless ``dtype`` overrides it.  Returns
    (batch, prompt_len + n_new) tokens."""
    b, p_len = prompt.shape
    if p_len + n_new > cfg.max_seq:
        raise ValueError(
            f"prompt {p_len} + n_new {n_new} exceeds max_seq "
            f"{cfg.max_seq}")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or top_k > cfg.vocab:
        raise ValueError(
            f"top_k must be in [0, vocab={cfg.vocab}], got {top_k}")
    if temperature > 0 and key is None:
        raise ValueError("temperature > 0 requires a PRNG `key`")
    if n_new == 0:
        return prompt
    if dtype is None:
        dtype = params["embed"].dtype
    if key is None:
        key = jax.random.PRNGKey(0)  # unused on the greedy path

    logits, cache = prefill(cfg, params, init_kv_cache(cfg, b, dtype),
                            prompt)
    key, sub = jax.random.split(key)
    first = _select_token(logits, sub, temperature, top_k, prompt.dtype)

    # Each step feeds the token at position i and emits position i+1's
    # choice; feeding stops one short of the final position — the last
    # emitted token needs no decode of its own.
    def step(carry, i):
        cache, tok, key = carry
        logits, cache = decode_step(cfg, params, cache, tok, i)
        key, sub = jax.random.split(key)
        nxt = _select_token(logits, sub, temperature, top_k, prompt.dtype)
        return (cache, nxt, key), nxt

    (_, _, _), rest = jax.lax.scan(
        step, (cache, first, key),
        jnp.arange(p_len, p_len + n_new - 1, dtype=jnp.int32))
    gen = jnp.concatenate([first[None], rest], axis=0)   # (n_new, b)
    return jnp.concatenate([prompt, gen.T], axis=1)


def _chunked_ce(x, unembed, labels, vocab_chunk: int):
    """Per-token cross entropy ``logsumexp(z) - z[label]`` computed in
    vocab chunks under ``lax.scan``: the full (batch, seq, vocab) logits
    array never materializes — each step computes one (batch, seq,
    chunk) slab, folds it into a running online logsumexp, and picks the
    label logit if it falls in the chunk.  At the flagship bench config
    (vocab 32768, bf16) the dense logits alone are ~1 GiB of HBM per
    step; chunking caps the transient at chunk/vocab of that, and the
    backward rebuilds each slab from the O(d) residuals (XLA transposes
    the scan), trading one extra chunk matmul for the memory."""
    V = unembed.shape[1]
    n_chunks = V // vocab_chunk
    # The online logsumexp runs in at-least-f32 (bf16 running sums would
    # lose the tail mass the chunking is supposed to preserve exactly).
    ct = jnp.promote_types(x.dtype, jnp.float32)
    neg = jnp.asarray(-1e30, ct)
    m0 = jnp.full(labels.shape, neg, ct)
    se0 = jnp.zeros(labels.shape, ct)
    zt0 = jnp.zeros(labels.shape, ct)

    # checkpoint: without it the scan's VJP stacks each step's
    # (b, s, chunk) slab intermediates across ALL chunks — at the
    # flagship config that is ~2 GiB f32, i.e. WORSE than the dense
    # logits this function exists to avoid.  Rematerializing recomputes
    # one chunk matmul per backward step from the O(d) residuals
    # instead (same trade as the per-block remat at cfg.remat).
    @jax.checkpoint
    def body(carry, c):
        m, se, zt = carry
        w = jax.lax.dynamic_slice_in_dim(unembed, c * vocab_chunk,
                                         vocab_chunk, 1)
        z = (x @ w).astype(ct)                       # (b, s, chunk)
        m_new = jnp.maximum(m, jnp.max(z, axis=-1))
        se = se * jnp.exp(m - m_new) + \
            jnp.sum(jnp.exp(z - m_new[..., None]), axis=-1)
        lo = c * vocab_chunk
        in_chunk = (labels >= lo) & (labels < lo + vocab_chunk)
        idx = jnp.clip(labels - lo, 0, vocab_chunk - 1)
        zsel = jnp.take_along_axis(z, idx[..., None], axis=-1)[..., 0]
        zt = jnp.where(in_chunk, zsel, zt)
        return (m_new, se, zt), None

    (m, se, zt), _ = jax.lax.scan(
        body, (m0, se0, zt0), jnp.arange(n_chunks, dtype=jnp.int32))
    return m + jnp.log(se) - zt


def lm_loss(cfg: TransformerConfig, params, tokens, comm_sp=None,
            attn: str = "ring", seq_global: Optional[int] = None,
            comm_ep=None, vocab_chunk: int = 0):
    """Mean next-token cross-entropy over the GLOBAL sequence.

    The label for a shard's last token lives on the next sp rank — it is
    fetched with a one-element ``ring_shift`` (the boundary token rides the
    same differentiable transport as attention K/V; no gradient flows to a
    label, but the collective must appear in every rank's program —
    SURVEY.md §3.3).  The final global position has no successor and is
    masked out; the sp-summed loss is normalized by the static global token
    count."""
    b, s_local = tokens.shape
    sp = comm_sp.size if comm_sp is not None else 1
    s_global = seq_global or sp * s_local
    if vocab_chunk and (vocab_chunk <= 0
                        or cfg.vocab % vocab_chunk != 0):
        raise ValueError(
            f"vocab_chunk={vocab_chunk} must divide vocab={cfg.vocab}")

    want_hidden = bool(vocab_chunk) and vocab_chunk < cfg.vocab
    if cfg.n_experts > 0:
        out, aux = forward(cfg, params, tokens, comm_sp, attn,
                           comm_ep=comm_ep, return_aux=True,
                           return_hidden=want_hidden)
    else:
        out = forward(cfg, params, tokens, comm_sp, attn,
                      return_hidden=want_hidden)
        aux = None

    if sp > 1 and attn == "zigzag":
        # Zigzag shard = chunks (r, 2*sp-1-r).  Each chunk's last label
        # is the FIRST token of the globally-next chunk: chunk r+1 is
        # rank r+1's lo chunk (ring shift -1) except for the last rank,
        # whose lo chunk is followed by its OWN hi chunk; chunk 2*sp-r
        # is rank r-1's hi chunk (ring shift +1) — rank 0's hi chunk is
        # the global tail, already masked below.  Both shifts appear in
        # every rank's program (SPMD-symmetric), the where picks.
        c = s_local // 2
        lo, hi = tokens[:, :c], tokens[:, c:]
        r = jnp.asarray(comm_sp.rank)
        from_next_lo = ring_shift(comm_sp, lo[:, :1], shift=-1)
        from_prev_hi = ring_shift(comm_sp, hi[:, :1], shift=1)
        lo_last = jnp.where(r == sp - 1, hi[:, :1], from_next_lo)
        labels = jnp.concatenate(
            [lo[:, 1:], lo_last, hi[:, 1:], from_prev_hi], axis=1)
        global_pos = _zigzag_positions(comm_sp, s_local)
    elif sp > 1:
        nxt = ring_shift(comm_sp, tokens[:, :1], shift=-1)
        labels = jnp.concatenate([tokens[:, 1:], nxt], axis=1)
        global_pos = jnp.asarray(comm_sp.rank) * s_local \
            + jnp.arange(s_local)
    else:
        labels = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        global_pos = jnp.arange(s_local)
    mask = (global_pos < s_global - 1).astype(out.dtype)

    if want_hidden:
        ce = _chunked_ce(out, params["unembed"], labels, vocab_chunk)
    else:
        logp = jax.nn.log_softmax(out, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[..., None],
                                  axis=-1)[..., 0]
    local_sum = jnp.sum(ce * mask[None, :])
    if sp > 1:
        # compression=False on internal sums: softmax denominators, aux
        # stats and loss averages are numerical internals with exact-
        # parity contracts — a user gradient-compression scope must
        # not reach them.
        total = comm_sp.Allreduce(local_sum, MPI_SUM, compression=False)
    else:
        total = local_sum
    loss = total / (b * (s_global - 1))
    if aux is not None:
        if sp > 1:
            # Each sp rank's aux reflects only its own sequence shard's
            # routing; average it so the loss stays rank-identical (the
            # lock-step invariant every collective loss must keep).
            aux = comm_sp.Allreduce(aux, MPI_SUM, compression=False) / sp
        loss = loss + cfg.aux_coef * aux
    return loss


def zero_train_step(cfg: TransformerConfig, params, tokens, opt,
                    opt_state, comm_dp, comm_sp=None, attn: str = "ring",
                    comm_ep=None):
    """One optimizer step with ZeRO-1 sharded state over the dp axis;
    returns ``(loss, new_params, new_opt_state)``.

    The data-parallel reduction moves out of the loss and into
    :func:`~mpi4torch_tpu.parallel.zero.zero_step`'s reduce-scatter:
    each dp rank differentiates its LOCAL mean loss (no dp
    param-averaging, no dp loss-Allreduce — the un-reduced gradients
    are exactly what the reduce-scatter sums), the element-wise ``opt``
    update runs on this rank's 1/dp parameter shard, and the allgather
    re-replicates.  Sequence parallelism composes unchanged inside the
    local loss (the sp discipline of :func:`train_step`).  Trajectories
    match replicated-DP optax training exactly
    (tests/test_transformer.py); optimizer-state HBM is 1/dp of
    replicated — with Adam at scale, the dominant memory term.

    The ep axis composes like in :func:`train_step` (a data axis with
    the param-averaging adjoint + loss averaging), so every dp rank's
    local gradient is already ep-consistent before the dp
    reduce-scatter."""

    def local_loss(p):
        if comm_sp is not None and comm_sp.size > 1:
            p = all_average_tree(comm_sp, p)
        if comm_ep is not None and comm_ep.size > 1:
            p = all_average_tree(comm_ep, p)
        loss = lm_loss(cfg, p, tokens, comm_sp, attn, comm_ep=comm_ep)
        if comm_ep is not None and comm_ep.size > 1:
            loss = comm_ep.Allreduce(loss, MPI_SUM, compression=False) / comm_ep.size
        return loss

    loss, grads = jax.value_and_grad(local_loss)(params)
    # zero_step's reduce-scatter/size turns the un-reduced local grads
    # into the dp-MEAN gradient shard — the same mean the plain recipe's
    # Allreduce/size produces (no scaling here, or it would double).
    new_params, new_state = zero_step(comm_dp, opt, params, grads,
                                      opt_state)
    # Report the dp-global mean loss.
    loss = comm_dp.Allreduce(loss, MPI_SUM, compression=False) / comm_dp.size
    return loss, new_params, new_state


def zero3_train_step(cfg: TransformerConfig, p_shards, template, tokens,
                     opt, opt_state, comm_dp, comm_sp=None,
                     attn: str = "ring"):
    """One optimizer step with ZeRO-3 over the dp axis: the parameters
    live as 1/dp flat shards BETWEEN steps (parameter + optimizer HBM
    both / dp); returns ``(loss, new_p_shards, new_opt_state)``.

    The forward gathers shards on use (:func:`parallel.zero3_params`);
    the backward reduce-scatters the gradients through the Allgather
    adjoint — the dp reduction needs no explicit collective at all.
    Sequence parallelism composes inside the local loss exactly as in
    :func:`zero_train_step`.  Obtain ``(p_shards, opt_state)`` from
    :func:`parallel.zero3_init` and full parameters for evaluation from
    :func:`parallel.zero3_params`; trajectories match replicated-DP
    optax training exactly (tests/test_transformer.py)."""

    def local_loss(p):
        if comm_sp is not None and comm_sp.size > 1:
            p = all_average_tree(comm_sp, p)
        return lm_loss(cfg, p, tokens, comm_sp, attn)

    loss, new_shards, new_state = zero3_step(
        comm_dp, opt, p_shards, template, local_loss, opt_state)
    loss = comm_dp.Allreduce(loss, MPI_SUM, compression=False) / comm_dp.size
    return loss, new_shards, new_state


def train_step(cfg: TransformerConfig, params, tokens, comm_sp=None,
               comm_dp=None, attn: str = "ring", lr: float = 1e-2,
               comm_ep=None):
    """One SGD step; returns (loss, new_params).

    DP follows the reference recipe exactly (parameter-averaging Allreduce
    + loss Allreduce over the dp axis) so replicas stay in lock-step.  The
    parameters are averaged over the sp axis as well: the sp-summed loss
    (``Allreduce_sp`` in :func:`lm_loss`, with no ``1/sp``) scales each
    rank's cotangents by ``sp``, and only the ``1/sp`` in the sp
    param-averaging adjoint cancels it — the same load-bearing trick as the
    reference's DP example (doc/examples.rst:46-65), applied per axis.
    Jittable end-to-end — on a 2D mesh the whole step is one XLA program
    mixing psum (dp/sp), the ppermute ring and masked collectives.

    The ep axis is treated as a *data* axis with the same recipe (ep ranks
    hold different token shards): parameters are averaged over ep and the
    loss is ep-averaged too.  This keeps every replicated leaf — gate,
    embeddings, attention, and the (logically replicated) expert tensors
    that :func:`~mpi4torch_tpu.parallel.moe.moe_ffn` slices per rank — in
    lock-step, and makes gradients match the dense single-rank oracle
    (tests/test_transformer.py): adjoint-Allreduce sums each rank's
    cotangents, and an expert block's whole-mesh gradient already
    accumulates on its owner rank via the adjoint Alltoall."""

    def global_loss(p):
        if comm_dp is not None and comm_dp.size > 1:
            p = all_average_tree(comm_dp, p)
        if comm_sp is not None and comm_sp.size > 1:
            p = all_average_tree(comm_sp, p)
        if comm_ep is not None and comm_ep.size > 1:
            p = all_average_tree(comm_ep, p)
        loss = lm_loss(cfg, p, tokens, comm_sp, attn, comm_ep=comm_ep)
        if comm_dp is not None and comm_dp.size > 1:
            loss = comm_dp.Allreduce(loss, MPI_SUM, compression=False) / comm_dp.size
        if comm_ep is not None and comm_ep.size > 1:
            loss = comm_ep.Allreduce(loss, MPI_SUM, compression=False) / comm_ep.size
        return loss

    loss, grads = jax.value_and_grad(global_loss)(params)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return loss, new_params

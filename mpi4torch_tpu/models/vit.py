"""Vision Transformer — the model zoo's non-causal attention family.

The reference ships no models (SURVEY.md §0); this family exists to
exercise the framework surface the causal LM flagship cannot: the flash
kernels' NON-causal path inside a full model (every KV tile live for
every query tile — no diagonal cut), image patchification as pure
reshape/transpose + one MXU matmul (no gather), and the same DP recipe
as the ResNet family over the communicator ops.

TPU notes: patches are embedded by ONE (b*n_patches, p*p*c) @
(p*p*c, d) matmul — patchify itself is a free relayout, the compiler
fuses it into the projection's operand load.  Attention runs through
:func:`ops.flash.flash_attention` with ``causal=False``: eligible
shapes take the Pallas kernel, everything else the jnp blockwise path,
identically to the LM flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..constants import MPI_SUM
from ..ops.flash import flash_attention
from ..parallel.attention import ring_attention
from .transformer import _layer_norm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_hw: int
    patch: int
    d_model: int
    n_heads: int
    n_layers: int
    d_ff: int
    num_classes: int
    channels: int = 3

    def __post_init__(self):
        if self.image_hw % self.patch != 0:
            raise ValueError(
                f"image_hw={self.image_hw} not divisible by "
                f"patch={self.patch}")
        if self.d_model % self.n_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"n_heads={self.n_heads}")

    @property
    def n_patches(self) -> int:
        return (self.image_hw // self.patch) ** 2


def init_vit(key, cfg: ViTConfig, dtype=jnp.float32) -> Dict[str, Any]:
    """Parameter pytree for a pre-LN ViT with learned positions and a
    mean-pool classification head."""
    def dense(key, m, n):
        return jax.random.normal(key, (m, n), dtype) / jnp.sqrt(
            jnp.asarray(m, dtype))

    def norm_p():
        return {"scale": jnp.ones((cfg.d_model,), dtype),
                "bias": jnp.zeros((cfg.d_model,), dtype)}

    keys = iter(jax.random.split(key, 4 + 4 * cfg.n_layers))
    pdim = cfg.patch * cfg.patch * cfg.channels
    params: Dict[str, Any] = {
        "patch_proj": dense(next(keys), pdim, cfg.d_model),
        "pos": jax.random.normal(
            next(keys), (cfg.n_patches, cfg.d_model), dtype) * 0.02,
        "ln_f": norm_p(),
        "head": dense(next(keys), cfg.d_model, cfg.num_classes),
        "blocks": [],
    }
    for _ in range(cfg.n_layers):
        params["blocks"].append({
            "ln1": norm_p(),
            "wqkv": dense(next(keys), cfg.d_model, 3 * cfg.d_model),
            "wo": dense(next(keys), cfg.d_model, cfg.d_model),
            "ln2": norm_p(),
            "w1": dense(next(keys), cfg.d_model, cfg.d_ff),
            "w2": dense(next(keys), cfg.d_ff, cfg.d_model),
        })
    return params


def patchify(cfg: ViTConfig, images):
    """(b, hw, hw, c) -> (b, n_patches, patch*patch*c), rows in raster
    order.  Pure reshape/transpose — XLA folds it into the projection."""
    b = images.shape[0]
    g, p, c = cfg.image_hw // cfg.patch, cfg.patch, cfg.channels
    x = images.reshape(b, g, p, g, p, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p * p * c)


def forward(cfg: ViTConfig, params, images):
    """Logits ``(b, num_classes)`` (single-device attention).

    For patch parallelism — the non-causal face of context
    parallelism — shard the PATCHIFIED input across ranks and call
    :func:`forward_patches` with ``comm_sp`` instead: a whole-image
    ``forward`` has no valid sharded reading (each rank's ring
    contribution must be a distinct shard of ONE global patch
    sequence, not its own full image)."""
    return forward_patches(cfg, params, patchify(cfg, images))


def forward_patches(cfg: ViTConfig, params, patches, comm_sp=None,
                    patch_offset=None):
    """Forward from patchified input, optionally patch-sharded.

    With ``comm_sp``, each rank holds the contiguous equal shard
    ``(b, n_patches/size, patch*patch*c)`` of one global patch
    sequence in rank order (the layout ring attention fixes);
    attention runs as NON-causal ring attention over the shard ring
    (every query sees every key — no diagonal cut, so the ring is
    naturally load-balanced and needs no zigzag layout) and the
    mean-pool head closes with one ``Allreduce``.  The positional rows
    for the shard are derived from ``comm_sp.rank`` (works traced
    under SPMD); ``patch_offset`` overrides the derivation only."""
    sp = comm_sp is not None and comm_sp.size > 1
    pos = params["pos"]
    if sp:
        if patch_offset is None:
            # The ring layout pins shard r's first global patch at
            # r * s_local; deriving it here removes the silently-wrong
            # default-0 positional rows a forgetful caller would get.
            patch_offset = jnp.asarray(comm_sp.rank) * patches.shape[1]
        pos = jax.lax.dynamic_slice_in_dim(
            pos, patch_offset, patches.shape[1], 0)
    x = patches @ params["patch_proj"] + pos
    b, s, d = x.shape
    hd = d // cfg.n_heads
    for blk in params["blocks"]:
        y = _layer_norm(x, blk["ln1"])
        qkv = y @ blk["wqkv"]
        q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(
            b, s, cfg.n_heads, hd) for i in range(3))
        if sp:
            att = ring_attention(comm_sp, q, k, v, causal=False)
        else:
            att = flash_attention(q, k, v, causal=False)
        x = x + att.reshape(b, s, d) @ blk["wo"]
        y = _layer_norm(x, blk["ln2"])
        x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layer_norm(x, params["ln_f"])
    pooled = jnp.mean(x, axis=1)
    if sp:
        # Mean over the full patch axis = mean of equal-shard means.
        # compression=False: forward activations (sequence-parallel pool).
        pooled = comm_sp.Allreduce(pooled, MPI_SUM,
                                   compression=False) / comm_sp.size
    return pooled @ params["head"]


def local_loss(cfg: ViTConfig, params, batch):
    """Mean softmax cross-entropy on the rank-local batch."""
    images, labels = batch
    logp = jax.nn.log_softmax(forward(cfg, params, images), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=-1)[:, 0])


def dp_grad_train_step(comm, cfg: ViTConfig, params, batch,
                       lr: float = 0.1):
    """One SGD step with the classic DDP recipe: local backward, then one
    ``Allreduce(g, MPI_SUM)/size`` per gradient (the resnet family's
    recipe, reference doc/examples.rst:46-65 discipline).  Returns
    ``(global_loss, new_params)``."""
    loss, grads = jax.value_and_grad(
        lambda p: local_loss(cfg, p, batch))(params)
    size = comm.size
    grads = jax.tree.map(lambda g: comm.Allreduce(g, MPI_SUM) / size, grads)
    global_loss = comm.Allreduce(loss, MPI_SUM) / size
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return global_loss, new_params

"""Vision Transformer — the model zoo's non-causal attention family.

The reference ships no models (SURVEY.md §0); this family exists to
exercise the framework surface the causal LM flagship cannot: the flash
kernels' NON-causal path inside a full model (every KV tile live for
every query tile — no diagonal cut), image patchification as pure
reshape/transpose + one MXU matmul (no gather), and the same DP recipe
as the ResNet family over the communicator ops.

TPU notes: patches are embedded by ONE (b*n_patches, p*p*c) @
(p*p*c, d) matmul — patchify itself is a free relayout, the compiler
fuses it into the projection's operand load.  Attention runs through
:func:`ops.flash.flash_attention` with ``causal=False``: eligible
shapes take the Pallas kernel, everything else the jnp blockwise path,
identically to the LM flagship.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..constants import MPI_SUM
from ..ops.flash import flash_attention
from .transformer import _layer_norm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_hw: int
    patch: int
    d_model: int
    n_heads: int
    n_layers: int
    d_ff: int
    num_classes: int
    channels: int = 3

    def __post_init__(self):
        if self.image_hw % self.patch != 0:
            raise ValueError(
                f"image_hw={self.image_hw} not divisible by "
                f"patch={self.patch}")
        if self.d_model % self.n_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"n_heads={self.n_heads}")

    @property
    def n_patches(self) -> int:
        return (self.image_hw // self.patch) ** 2


def init_vit(key, cfg: ViTConfig, dtype=jnp.float32) -> Dict[str, Any]:
    """Parameter pytree for a pre-LN ViT with learned positions and a
    mean-pool classification head."""
    def dense(key, m, n):
        return jax.random.normal(key, (m, n), dtype) / jnp.sqrt(
            jnp.asarray(m, dtype))

    def norm_p():
        return {"scale": jnp.ones((cfg.d_model,), dtype),
                "bias": jnp.zeros((cfg.d_model,), dtype)}

    keys = iter(jax.random.split(key, 4 + 4 * cfg.n_layers))
    pdim = cfg.patch * cfg.patch * cfg.channels
    params: Dict[str, Any] = {
        "patch_proj": dense(next(keys), pdim, cfg.d_model),
        "pos": jax.random.normal(
            next(keys), (cfg.n_patches, cfg.d_model), dtype) * 0.02,
        "ln_f": norm_p(),
        "head": dense(next(keys), cfg.d_model, cfg.num_classes),
        "blocks": [],
    }
    for _ in range(cfg.n_layers):
        params["blocks"].append({
            "ln1": norm_p(),
            "wqkv": dense(next(keys), cfg.d_model, 3 * cfg.d_model),
            "wo": dense(next(keys), cfg.d_model, cfg.d_model),
            "ln2": norm_p(),
            "w1": dense(next(keys), cfg.d_model, cfg.d_ff),
            "w2": dense(next(keys), cfg.d_ff, cfg.d_model),
        })
    return params


def patchify(cfg: ViTConfig, images):
    """(b, hw, hw, c) -> (b, n_patches, patch*patch*c), rows in raster
    order.  Pure reshape/transpose — XLA folds it into the projection."""
    b = images.shape[0]
    g, p, c = cfg.image_hw // cfg.patch, cfg.patch, cfg.channels
    x = images.reshape(b, g, p, g, p, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p * p * c)


def forward(cfg: ViTConfig, params, images):
    """Logits ``(b, num_classes)``."""
    x = patchify(cfg, images) @ params["patch_proj"] + params["pos"]
    b, s, d = x.shape
    hd = d // cfg.n_heads
    for blk in params["blocks"]:
        y = _layer_norm(x, blk["ln1"])
        qkv = y @ blk["wqkv"]
        q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(
            b, s, cfg.n_heads, hd) for i in range(3))
        att = flash_attention(q, k, v, causal=False)
        x = x + att.reshape(b, s, d) @ blk["wo"]
        y = _layer_norm(x, blk["ln2"])
        x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layer_norm(x, params["ln_f"])
    return jnp.mean(x, axis=1) @ params["head"]


def local_loss(cfg: ViTConfig, params, batch):
    """Mean softmax cross-entropy on the rank-local batch."""
    images, labels = batch
    logp = jax.nn.log_softmax(forward(cfg, params, images), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=-1)[:, 0])


def dp_grad_train_step(comm, cfg: ViTConfig, params, batch,
                       lr: float = 0.1):
    """One SGD step with the classic DDP recipe: local backward, then one
    ``Allreduce(g, MPI_SUM)/size`` per gradient (the resnet family's
    recipe, reference doc/examples.rst:46-65 discipline).  Returns
    ``(global_loss, new_params)``."""
    loss, grads = jax.value_and_grad(
        lambda p: local_loss(cfg, p, batch))(params)
    size = comm.size
    grads = jax.tree.map(lambda g: comm.Allreduce(g, MPI_SUM) / size, grads)
    global_loss = comm.Allreduce(loss, MPI_SUM) / size
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return global_loss, new_params

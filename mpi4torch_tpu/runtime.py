"""Thread-SPMD eager runtime ("Mode B") — the `mpirun -np N` analogue.

The reference library is executed as N OS processes under ``mpirun``, each
running the whole user script with a concrete ``rank`` (SURVEY.md §4: CI runs
``mpirun -np {2,5,7} nose2`` with oversubscription).  This module provides the
TPU-framework analogue for a single host: N Python *threads*, each running the
per-rank function with a concrete Python-int rank, where every communication
op is a rendezvous across the threads.  This is the harness that lets the
reference's tests and examples — per-rank-varying shapes, ``if comm.rank == 0``
branches, eager ``jax.grad`` — run essentially verbatim.  The SPMD-traced
path over a real device mesh ("Mode A", mpi4torch_tpu/ops/spmd.py) is the
performance path; this executor is the semantics/parity path, exactly like
CI-oversubscribed MPI processes are for the reference.

Replaces (TPU-natively) these reference components:
  * MPI init-on-import + finalizer        (csrc/extension.cpp:1313-1394)
  * communicator wrapper / rank / size    (csrc/extension.cpp:140-187)
  * request-handle management             (csrc/extension.cpp:1089-1107,1220-1249)
  * error checking -> exceptions          (csrc/extension.cpp:131-138)

It is deliberately *stricter* than MPI: mismatched collectives raise a
``CollectiveMismatchError`` instead of deadlocking or corrupting data, stalls
raise ``DeadlockError`` after a timeout, and misuse of wait handles raises
immediately (the reference's guards: csrc/extension.cpp:395-403, 1196-1202,
1231-1237).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class CommError(RuntimeError):
    """Base class for communication-runtime errors (analogue of the
    reference's ``check_mpi_return_value`` -> std::runtime_error,
    csrc/extension.cpp:131-138)."""


class CollectiveMismatchError(CommError):
    """Raised when ranks disagree on which collective (or which parameters)
    they are executing.  MPI would deadlock or corrupt buffers; we detect."""


class DeadlockError(CommError):
    """Raised when a rendezvous times out — the analogue of an MPI hang."""


class InPlaceReuseError(CommError):
    """Raised when a tensor consumed by an in-place collective is passed to a
    later communication op (reference: 'Reuse of variables passed to in-place
    MPI kernels not supported', csrc/extension.cpp:395-403, 451-462)."""


class BifurcationError(CommError):
    """Raised when a wait handle is reused/spliced/waited twice (reference:
    'Detected bifurcation in MPIWait handle usage',
    csrc/extension.cpp:1196-1202, 1231-1237)."""


# Request descriptor op codes (descriptor layout mirrors the 7-element
# descriptor of csrc/extension.cpp:1094-1102).
REQ_ISEND = 1
REQ_IRECV = 2


@dataclass
class _PendingRequest:
    req_id: int
    kind: int                 # REQ_ISEND / REQ_IRECV
    rank: int                 # owning rank
    peer: int                 # dest (isend) or source (irecv)
    tag: int
    shape: Tuple[int, ...]
    dtype: Any
    fingerprint: int


def _fnv1a(parts) -> int:
    """FNV-1a hash over a string description — the analogue of the 32-bit
    data-pointer hash the reference smuggles into the request descriptor
    (csrc/extension.cpp:1100, re-checked at 1231-1237).  Kept pure-Python:
    the inputs are tiny and this sits on the request-creation hot path, so
    it must never wait on the native library's first build (the identical
    native fnv1a32 exists for bulk hashing and is tested bit-equal)."""
    h = 0x811C9DC5
    for ch in "|".join(str(p) for p in parts).encode():
        h ^= ch
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


class World:
    """A set of ``size`` rank-threads with rendezvous-based communication.

    One ``World`` is the analogue of an ``MPI_COMM_WORLD`` instance spanning N
    processes (csrc/extension.cpp:140-187).  All collective ops funnel through
    :meth:`exchange`, which is a barrier + all-to-all of per-rank payloads plus
    a signature consistency check.
    """

    def __init__(self, size: int, timeout: Optional[float] = None):
        if size < 1:
            raise ValueError("World size must be >= 1")
        self.size = size
        if timeout is None:
            # Deadlock-detection wall clock, not a performance knob: big
            # models on slow hosts can exceed any fixed default, so CI
            # and heavyweight runs may override via the environment.
            timeout = float(os.environ.get(
                "MPI4TORCH_TPU_WORLD_TIMEOUT", "60"))
        self.timeout = timeout
        self._barrier = threading.Barrier(size)
        self._slots: List[Any] = [None] * size
        self._sigs: List[Any] = [None] * size
        self._mailboxes: Dict[Tuple[int, int, int], "queue.Queue"] = {}
        self._mb_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._req_counter = 0
        self._pending: Dict[int, _PendingRequest] = {}
        # (rank, id(x)) -> strong ref: per-rank in-place guard (see
        # mark_consumed — ranks are threads, collectives may share one
        # result object across them).
        self._consumed: Dict[Tuple[int, int], Any] = {}
        self._failed = threading.Event()
        self._first_error: Optional[BaseException] = None
        self._err_lock = threading.Lock()

    # ---------------------------------------------------------------- errors

    def fail(self, exc: BaseException) -> None:
        """Mark the world failed and wake everyone blocked on the barrier."""
        with self._err_lock:
            if self._first_error is None:
                self._first_error = exc
        self._failed.set()
        self._barrier.abort()

    def _check_failed(self):
        if self._failed.is_set():
            raise CommError(
                "communication world already failed on another rank"
            ) from self._first_error

    # ----------------------------------------------------------- collectives

    def exchange(self, rank: int, signature: Tuple, payload: Any) -> List[Any]:
        """All ranks deposit (signature, payload); returns the list of all
        payloads in rank order.  Signature mismatch across ranks raises on
        every rank (MPI would deadlock/corrupt; see class docstring).
        """
        self._check_failed()
        self._sigs[rank] = signature
        self._slots[rank] = payload
        self._wait_barrier()
        sig0 = self._sigs[0]
        if any(s != sig0 for s in self._sigs):
            err = CollectiveMismatchError(
                "ranks disagree on the collective being executed: "
                + "; ".join(f"rank {i}: {s}" for i, s in enumerate(self._sigs))
            )
            # Everyone observes the same mismatch => everyone raises; no need
            # to abort the barrier.
            raise err
        out = list(self._slots)
        self._wait_barrier()  # all readers done before slots are reused
        return out

    def barrier(self, rank: int) -> None:
        self.exchange(rank, ("Barrier",), None)

    def _wait_barrier(self):
        try:
            self._barrier.wait(timeout=self.timeout)
        except threading.BrokenBarrierError:
            if self._first_error is not None:
                raise CommError(
                    "collective aborted because another rank failed"
                ) from self._first_error
            raise DeadlockError(
                f"collective rendezvous timed out after {self.timeout}s — a "
                "rank did not reach the matching collective (the analogue of "
                "an MPI deadlock; every rank must execute the same "
                "communication sequence, see SURVEY.md §3.3)"
            ) from None

    # ------------------------------------------------------------------ p2p

    def _mailbox(self, src: int, dst: int, tag: int) -> "queue.Queue":
        key = (src, dst, tag)
        with self._mb_lock:
            q = self._mailboxes.get(key)
            if q is None:
                q = queue.Queue()
                self._mailboxes[key] = q
            return q

    def p2p_send(self, src: int, dst: int, tag: int, payload: Any) -> None:
        """Buffered-mode send: never blocks (the eager analogue of MPI_Isend,
        csrc/extension.cpp:1071-1113)."""
        self._check_failed()
        if not (0 <= dst < self.size):
            raise CommError(f"invalid destination rank {dst} (size {self.size})")
        self._mailbox(src, dst, tag).put(payload)

    def p2p_recv(self, src: int, dst: int, tag: int) -> Any:
        """Blocking receive with deadlock timeout (analogue of MPI_Irecv+Wait,
        csrc/extension.cpp:1115-1157, 1245-1249)."""
        if not (0 <= src < self.size):
            raise CommError(f"invalid source rank {src} (size {self.size})")
        q = self._mailbox(src, dst, tag)
        deadline = time.monotonic() + self.timeout
        while True:
            self._check_failed()
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                if time.monotonic() > deadline:
                    raise DeadlockError(
                        f"receive (src={src}, dst={dst}, tag={tag}) timed out "
                        f"after {self.timeout}s — matching send never posted"
                    ) from None

    # ------------------------------------------------------------- requests

    def new_request(self, kind: int, rank: int, peer: int, tag: int,
                    shape: Tuple[int, ...], dtype: Any) -> _PendingRequest:
        with self._req_lock:
            self._req_counter += 1
            rid = self._req_counter
        fp = _fnv1a((rid, kind, peer, tag, shape, str(dtype)))
        req = _PendingRequest(rid, kind, rank, peer, tag, tuple(shape), dtype, fp)
        with self._req_lock:
            self._pending[rid] = req
        return req

    def complete_request(self, req_id: int, shape: Tuple[int, ...],
                         dtype: Any) -> _PendingRequest:
        """Pop a pending request, enforcing the reference's wait-handle
        guards (csrc/extension.cpp:1231-1237: descriptor hash re-check;
        1196-1202: backward-graph shape check)."""
        with self._req_lock:
            req = self._pending.pop(req_id, None)
        if req is None:
            raise BifurcationError(
                f"Detected bifurcation in Wait handle usage: request {req_id} "
                "is unknown or was already waited on (a WaitHandle must be "
                "waited on exactly once, and its parts must not be swapped "
                "between handles; reference guard csrc/extension.cpp:1231-1237)"
            )
        if tuple(shape) != req.shape or dtype != req.dtype:
            with self._req_lock:
                self._pending[req_id] = req  # restore for diagnostics
            raise BifurcationError(
                "Detected bifurcation in Wait handle usage: the buffer in the "
                f"handle (shape {tuple(shape)}, dtype {dtype}) does not match "
                f"the posted request (shape {req.shape}, dtype {req.dtype})"
            )
        return req

    # -------------------------------------------------- in-place reuse guard

    # Bound on the consumed-input guard table: entries beyond this are
    # evicted FIFO (dropping an entry only weakens detection for that old
    # tensor; it can never cause a false positive, because evicting also
    # drops the strong ref that pinned the id).
    _CONSUMED_CAP = 4096

    def mark_consumed(self, rank: int, x: Any) -> None:
        """Record ``x`` as consumed by an in-place collective ON ``rank``.
        The reference splices an ``MPINoInplaceBackward`` node onto the
        *input* of Reduce_ so any later use raises at backward time
        (csrc/extension.cpp:395-403, 451-462).  Functionally-pure JAX has
        no aliasing hazard, so this is a parity/discipline guard: later
        *communication* ops reject the value.  Keyed per rank because
        ranks are threads sharing one process — collectives may hand the
        SAME result object to every rank (Allreduce's fold-once path),
        and rank r consuming its copy must not taint rank s's (in MPI
        they would be distinct buffers in distinct processes).
        """
        self._consumed[(rank, id(x))] = x  # strong ref pins id while tracked
        while len(self._consumed) > self._CONSUMED_CAP:
            self._consumed.pop(next(iter(self._consumed)))

    def check_not_consumed(self, rank: int, *arrays: Any) -> None:
        for a in arrays:
            if (rank, id(a)) in self._consumed:
                raise InPlaceReuseError(
                    "Reuse of variables passed to in-place MPI kernels is not "
                    "supported (reference guard csrc/extension.cpp:451-462): "
                    "this tensor was consumed by Reduce_ — use its return "
                    "value instead"
                )


@dataclass
class RankContext:
    """Binds the current thread to (world, rank) — the eager analogue of the
    per-process MPI rank identity."""
    world: World
    rank: int


_tls = threading.local()


def current_rank_context() -> Optional[RankContext]:
    return getattr(_tls, "ctx", None)


class _bind_rank:
    def __init__(self, ctx: RankContext):
        self.ctx = ctx

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False


# A default single-rank world so that user scripts work without any launcher,
# exactly like running an MPI program without mpirun (world size 1).
_default_world = World(1)
_default_ctx = RankContext(_default_world, 0)


def effective_rank_context() -> RankContext:
    ctx = current_rank_context()
    return ctx if ctx is not None else _default_ctx


def run_ranks(fn: Callable, nranks: int, timeout: float = 60.0,
              return_results: bool = True) -> List[Any]:
    """Run ``fn`` on ``nranks`` rank-threads — the `mpirun -np N` analogue.

    ``fn`` is called either as ``fn()`` or ``fn(rank)`` (if it accepts one
    positional argument).  Inside, ``mpi4torch_tpu.COMM_WORLD`` resolves to
    this world with a concrete Python-int rank, so reference-style per-rank
    scripts (rank-conditional shapes and asserts) run unmodified in spirit
    (SURVEY.md §4 'What the rebuild needs').

    Exceptions: the first per-rank exception is re-raised on the caller
    after all threads have been reaped; other ranks' failures are attached
    as context.
    """
    import inspect

    world = World(nranks, timeout=timeout)
    results: List[Any] = [None] * nranks
    errors: List[Optional[BaseException]] = [None] * nranks

    try:
        nparams = len([
            p for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is p.empty
        ])
    except (TypeError, ValueError):
        nparams = 0

    def worker(rank: int):
        with _bind_rank(RankContext(world, rank)):
            try:
                results[rank] = fn(rank) if nparams >= 1 else fn()
            except BaseException as e:  # noqa: BLE001 — reaped below
                errors[rank] = e
                world.fail(e)

    threads = [threading.Thread(target=worker, args=(r,), name=f"rank{r}")
               for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    failed = [(r, e) for r, e in enumerate(errors) if e is not None]
    if failed:
        # Prefer the root-cause error over secondary abort noise, and attach
        # the other ranks' failures as context.
        primary = world._first_error
        if primary is None or primary not in errors:
            primary = failed[0][1]
        secondary = [(r, e) for r, e in failed if e is not primary]
        if secondary:
            note = ("other rank failures: "
                    + "; ".join(f"rank {r}: {type(e).__name__}: {e}"
                                for r, e in secondary))
            if hasattr(primary, "add_note"):    # PEP 678, Python >= 3.11
                primary.add_note(note)
            else:
                # 3.10: stash where debuggers can see it; tracebacks
                # render the primary error unchanged.
                primary.__notes__ = getattr(primary, "__notes__", []) + [note]
        raise primary
    return results if return_results else []

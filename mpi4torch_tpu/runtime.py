"""Thread-SPMD eager runtime ("Mode B") — the `mpirun -np N` analogue.

The reference library is executed as N OS processes under ``mpirun``, each
running the whole user script with a concrete ``rank`` (SURVEY.md §4: CI runs
``mpirun -np {2,5,7} nose2`` with oversubscription).  This module provides the
TPU-framework analogue for a single host: N Python *threads*, each running the
per-rank function with a concrete Python-int rank, where every communication
op is a rendezvous across the threads.  This is the harness that lets the
reference's tests and examples — per-rank-varying shapes, ``if comm.rank == 0``
branches, eager ``jax.grad`` — run essentially verbatim.  The SPMD-traced
path over a real device mesh ("Mode A", mpi4torch_tpu/ops/spmd.py) is the
performance path; this executor is the semantics/parity path, exactly like
CI-oversubscribed MPI processes are for the reference.

Replaces (TPU-natively) these reference components:
  * MPI init-on-import + finalizer        (csrc/extension.cpp:1313-1394)
  * communicator wrapper / rank / size    (csrc/extension.cpp:140-187)
  * request-handle management             (csrc/extension.cpp:1089-1107,1220-1249)
  * error checking -> exceptions          (csrc/extension.cpp:131-138)

It is deliberately *stricter* than MPI: mismatched collectives raise a
``CollectiveMismatchError`` instead of deadlocking or corrupting data, stalls
raise ``DeadlockError`` after a timeout, and misuse of wait handles raises
immediately (the reference's guards: csrc/extension.cpp:395-403, 1196-1202,
1231-1237).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from . import config as _cfg


class CommError(RuntimeError):
    """Base class for communication-runtime errors (analogue of the
    reference's ``check_mpi_return_value`` -> std::runtime_error,
    csrc/extension.cpp:131-138)."""


class CollectiveMismatchError(CommError):
    """Raised when ranks disagree on which collective (or which parameters)
    they are executing.  MPI would deadlock or corrupt buffers; we detect."""


class DeadlockError(CommError):
    """Raised when a rendezvous times out — the analogue of an MPI hang.

    When the timeout happened at an attributed rendezvous barrier, the
    error carries failure attribution (mpi4torch_tpu.resilience):
    ``arrived`` is the frozenset of ranks that reached the collective and
    ``missing`` the frozenset that never did — the first question an
    operator asks about a hung job.  Both are ``None`` for timeouts with
    no rank bookkeeping (e.g. a p2p receive whose peer is named in the
    message instead)."""

    def __init__(self, message: str, arrived=None, missing=None):
        super().__init__(message)
        self.arrived: Optional[FrozenSet[int]] = (
            None if arrived is None else frozenset(arrived))
        self.missing: Optional[FrozenSet[int]] = (
            None if missing is None else frozenset(missing))

    def __reduce__(self):
        # Attribution must survive the process-transport wire
        # (mpi4torch_tpu.transport): default pickling replays only
        # args[0] through __init__, silently dropping arrived/missing.
        return (DeadlockError, (str(self), self.arrived, self.missing))


class RankFailedError(CommError):
    """Raised when a rank is known to have *died* (preemption, injected
    ``rank_death`` fault, a crash mid-collective) — the permanent-failure
    counterpart of :class:`DeadlockError`'s "somebody is late".  ``ranks``
    names the failed rank(s); surviving ranks raise it too, so every
    participant of the torn collective learns WHO failed, not just that
    the world is broken (mpi4torch_tpu.resilience)."""

    def __init__(self, message: str, ranks=()):
        super().__init__(message)
        self.ranks: FrozenSet[int] = frozenset(ranks)

    def __reduce__(self):
        # Rank attribution must survive the process-transport wire.
        return (RankFailedError, (str(self), self.ranks))


class IntegrityError(CommError):
    """Raised when a payload fails an integrity guard — a non-finite
    contribution under ``config.comm_finite_guard="raise"`` or a
    compressed-wire checksum mismatch under
    ``config.comm_wire_checksum`` (mpi4torch_tpu.resilience).  ``ranks``
    names the rank(s) whose contribution was corrupt, so a lying rank is
    attributed instead of folding silently into everyone's result."""

    def __init__(self, message: str, ranks=()):
        super().__init__(message)
        self.ranks: FrozenSet[int] = frozenset(ranks)

    def __reduce__(self):
        # Rank attribution must survive the process-transport wire.
        return (IntegrityError, (str(self), self.ranks))


class InPlaceReuseError(CommError):
    """Raised when a tensor consumed by an in-place collective is passed to a
    later communication op (reference: 'Reuse of variables passed to in-place
    MPI kernels not supported', csrc/extension.cpp:395-403, 451-462)."""


class BifurcationError(CommError):
    """Raised when a wait handle is reused/spliced/waited twice (reference:
    'Detected bifurcation in MPIWait handle usage',
    csrc/extension.cpp:1196-1202, 1231-1237)."""


# Request descriptor op codes (descriptor layout mirrors the 7-element
# descriptor of csrc/extension.cpp:1094-1102).
REQ_ISEND = 1
REQ_IRECV = 2

# Sentinel a fault plan returns from on_p2p_send to swallow the message
# (mpi4torch_tpu.resilience `drop_p2p`): the payload goes to the world's
# dropped-ledger instead of the mailbox, redeliverable on recv retry.
_P2P_DROPPED = object()


@dataclass
class _PendingRequest:
    req_id: int
    kind: int                 # REQ_ISEND / REQ_IRECV
    rank: int                 # owning rank
    peer: int                 # dest (isend) or source (irecv)
    tag: int
    shape: Tuple[int, ...]
    dtype: Any
    fingerprint: int


@dataclass(frozen=True)
class HealthReport:
    """Result of :meth:`World.health_check` / ``comm.check_health()`` —
    a timeout-bounded *attributed* barrier probe: ``ok`` says whether
    every rank answered within the bound, ``arrived``/``missing`` name
    who did and who did not (mpi4torch_tpu.resilience).
    ``probe_duration_s`` is this caller's wall time inside the probe —
    a failed probe burns its timeout, a healthy one returns in
    microseconds, and the elastic consensus (mpi4torch_tpu.elastic)
    budgets its rounds off exactly that difference.

    ``arrival_s`` maps each ARRIVED rank to its arrival latency in
    seconds relative to the probe round's first arrival (ISSUE 15):
    a chronically slow rank shows up here with a large offset instead
    of being indistinguishable from a healthy one — and distinguishable
    from a DEAD one, which lands in ``missing`` with no entry at all.
    :meth:`slow_ranks` applies a threshold."""
    ok: bool
    size: int
    arrived: FrozenSet[int]
    missing: FrozenSet[int]
    probe_duration_s: float = 0.0
    arrival_s: Optional[Dict[int, float]] = None

    def __bool__(self) -> bool:
        return self.ok

    def slow_ranks(self, threshold_s: float) -> FrozenSet[int]:
        """Arrived ranks whose arrival latency (behind the round's
        first arrival) is at least ``threshold_s`` — slow but ALIVE,
        the gray counterpart of ``missing``."""
        if not self.arrival_s:
            return frozenset()
        return frozenset(r for r, dt in self.arrival_s.items()
                         if dt >= threshold_s)


class _BarrierTimeout(Exception):
    """Internal: this thread's attributed-barrier wait expired.  Carries
    the arrival snapshot of the broken generation (and the per-rank
    arrival timestamps, for slow-vs-dead attribution)."""

    def __init__(self, arrived: FrozenSet[int], arrive_t=None):
        super().__init__("barrier timeout")
        self.arrived = arrived
        self.arrive_t = dict(arrive_t or {})


class _BarrierBroken(Exception):
    """Internal: the attributed barrier was broken by another thread
    (a peer's timeout, or ``abort()`` after a rank failure)."""

    def __init__(self, arrived: Optional[FrozenSet[int]] = None,
                 arrive_t=None):
        super().__init__("barrier broken")
        self.arrived = arrived
        self.arrive_t = dict(arrive_t or {})


# Ceiling on one exponential-backoff pause (config.comm_backoff doubles
# per retry up to here) — retries extend patience, they must not turn a
# genuine deadlock into an unbounded hang.
_BACKOFF_CAP_S = 30.0


def _backoff_pause(attempt: int, backoff: float, base: float) -> float:
    """Length of retry ``attempt``'s patience window: capped exponential
    on ``backoff``, or the base timeout again when backoff is 0.  ONE
    rule for the rendezvous barrier and the p2p receive loop."""
    if backoff > 0:
        return min(backoff * (2 ** (attempt - 1)), _BACKOFF_CAP_S)
    return base


class _AttributedBarrier:
    """Generation-counted rendezvous barrier that knows WHO has arrived.

    ``threading.Barrier`` answers only "did everyone arrive in time?";
    failure *attribution* (ISSUE 7) needs the arrival set of the
    generation that timed out, and transient-fault *retry* needs a
    waiter to extend its patience in capped-exponential-backoff steps
    instead of breaking the barrier on the first expiry.  Semantics
    otherwise match ``threading.Barrier``: a final timeout breaks the
    barrier for every waiter (permanently — the world is torn), and
    ``abort()`` breaks it immediately.

    ``resettable=True`` (the health-probe barrier) relaxes the
    permanence: once every waiter of a broken round has drained, the
    next arrival starts a FRESH round — a failed liveness probe must
    not latch every later probe to ``ok=False`` after the slow rank
    recovers.  The collective barrier stays non-resettable: a torn
    rendezvous generation means lost payload exchanges, which no later
    round can repair."""

    def __init__(self, size: int, resettable: bool = False):
        self.size = size
        self.resettable = resettable
        self._cond = threading.Condition()
        self._gen = 0
        self._count = 0
        self._arrived: set = set()
        # Per-rank arrival timestamps of the CURRENT round (ISSUE 15:
        # slow-vs-dead attribution — a slow rank arrives late, a dead
        # one never does), snapshotted into _last_arrivals when a round
        # completes and into timeout_arrive_t when one breaks.
        self._arrive_t: Dict[int, float] = {}
        self._last_arrivals: Dict[int, float] = {}
        self._broken = False
        # Arrival snapshot of the generation a timeout broke — lets the
        # *other* waiters of that generation attribute the failure too.
        self.timeout_arrived: Optional[FrozenSet[int]] = None
        self.timeout_arrive_t: Dict[int, float] = {}

    def wait(self, rank: int, timeout: float, retries: int = 0,
             backoff: float = 0.0, collect_arrivals=None) -> int:
        """Arrive and wait for the generation to fill.  Returns the
        number of retry extensions this waiter consumed (0 = the base
        timeout sufficed).  Raises :class:`_BarrierTimeout` when patience
        (base timeout + ``retries`` backoff extensions) runs out, and
        :class:`_BarrierBroken` when another waiter broke the barrier.

        ``collect_arrivals`` (a list, health probes) receives the
        completed round's per-rank arrival-timestamp dict — appended
        UNDER the lock on the wake path, so every waiter of round k
        reads round k's snapshot even if round k+1 starts immediately."""
        with self._cond:
            if self._broken:
                if not self.resettable:
                    raise _BarrierBroken(self.timeout_arrived,
                                         self.timeout_arrive_t)
                # Wait (bounded) for the broken round's stragglers to
                # drain, then start fresh — an immediate raise here
                # would let a back-to-back probe race its peers' drain
                # and read stale failure.
                drain_deadline = time.monotonic() + timeout
                while self._broken and self._count > 0:
                    remaining = drain_deadline - time.monotonic()
                    if remaining <= 0:
                        raise _BarrierBroken(self.timeout_arrived,
                                             self.timeout_arrive_t)
                    self._cond.wait(remaining)
                if self._broken:
                    self._broken = False
                    self.timeout_arrived = None
                    self.timeout_arrive_t = {}
                    self._gen += 1
                # else: a concurrent resettable arrival already reset it.
            gen = self._gen
            self._arrived.add(rank)
            self._arrive_t[rank] = time.monotonic()
            self._count += 1
            if self._count == self.size:
                self._last_arrivals = dict(self._arrive_t)
                self._count = 0
                self._arrived = set()
                self._arrive_t = {}
                self._gen += 1
                self._cond.notify_all()
                if collect_arrivals is not None:
                    collect_arrivals.append(dict(self._last_arrivals))
                return 0
            attempt = 0
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if attempt < retries:
                        # Capped exponential backoff: one more patience
                        # window per retry — a slow-but-alive rank
                        # arriving inside the extended window completes
                        # the collective for everyone.
                        attempt += 1
                        deadline = time.monotonic() + _backoff_pause(
                            attempt, backoff, timeout)
                        continue
                    arrived = frozenset(self._arrived)
                    self.timeout_arrived = arrived
                    self.timeout_arrive_t = dict(self._arrive_t)
                    self._broken = True
                    self._drain(rank)
                    self._cond.notify_all()
                    raise _BarrierTimeout(arrived, self.timeout_arrive_t)
                self._cond.wait(remaining)
                if self._gen != gen:
                    if collect_arrivals is not None:
                        collect_arrivals.append(dict(self._last_arrivals))
                    return attempt
                if self._broken:
                    self._drain(rank)
                    raise _BarrierBroken(self.timeout_arrived,
                                         self.timeout_arrive_t)

    def _drain(self, rank: int) -> None:
        """Leave a broken round (caller holds the lock): once the count
        hits zero a resettable barrier may start a fresh round — wake
        any arrival waiting on the drain."""
        self._count -= 1
        self._arrived.discard(rank)
        self._arrive_t.pop(rank, None)
        if self._count == 0:
            self._cond.notify_all()

    def abort(self) -> None:
        with self._cond:
            if self.timeout_arrived is None:
                # Snapshot who HAD arrived: an aborted health probe must
                # still attribute correctly (waiting probers are
                # arrived, not missing).
                self.timeout_arrived = frozenset(self._arrived)
                self.timeout_arrive_t = dict(self._arrive_t)
            self._broken = True
            self._cond.notify_all()


def _fnv1a(parts) -> int:
    """FNV-1a hash over a string description — the analogue of the 32-bit
    data-pointer hash the reference smuggles into the request descriptor
    (csrc/extension.cpp:1100, re-checked at 1231-1237).  Kept pure-Python:
    the inputs are tiny and this sits on the request-creation hot path, so
    it must never wait on the native library's first build (the identical
    native fnv1a32 exists for bulk hashing and is tested bit-equal)."""
    h = 0x811C9DC5
    for ch in "|".join(str(p) for p in parts).encode():
        h ^= ch
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


class World:
    """A set of ``size`` rank-threads with rendezvous-based communication.

    One ``World`` is the analogue of an ``MPI_COMM_WORLD`` instance spanning N
    processes (csrc/extension.cpp:140-187).  All collective ops funnel through
    :meth:`exchange`, which is a barrier + all-to-all of per-rank payloads plus
    a signature consistency check.
    """

    def __init__(self, size: int, timeout: Optional[float] = None):
        if size < 1:
            raise ValueError("World size must be >= 1")
        self.size = size
        if timeout is None:
            # Deadlock-detection wall clock, not a performance knob: big
            # models on slow hosts can exceed any fixed default, so CI
            # and heavyweight runs may override via the environment.
            timeout = float(os.environ.get(
                "MPI4TORCH_TPU_WORLD_TIMEOUT", "60"))
        self.timeout = timeout
        self._barrier = _AttributedBarrier(size)
        self._health = _AttributedBarrier(size, resettable=True)
        self._slots: List[Any] = [None] * size
        self._sigs: List[Any] = [None] * size
        self._mailboxes: Dict[Tuple[int, int, int], "queue.Queue"] = {}
        self._mb_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._req_counter = 0
        self._pending: Dict[int, _PendingRequest] = {}
        # (rank, id(x)) -> strong ref: per-rank in-place guard (see
        # mark_consumed — ranks are threads, collectives may share one
        # result object across them).
        self._consumed: Dict[Tuple[int, int], Any] = {}
        self._failed = threading.Event()
        self._first_error: Optional[BaseException] = None
        self._err_lock = threading.Lock()
        # Resilience bookkeeping (mpi4torch_tpu.resilience): ranks known
        # dead (injected rank_death / crash), payloads the fault layer
        # dropped off the p2p wire (redelivered on retry — the eager
        # analogue of a NACK-triggered retransmission), and a counter of
        # retry extensions consumed by waiters whose wait eventually
        # completed (PER-WAITER, so one slow rank on an N-rank world
        # can add up to (N-1)×retries — nonzero means "retries rescued
        # something", not a rendezvous count).
        self._dead: Dict[int, BaseException] = {}
        self._dropped: Dict[Tuple[int, int, int], List[Any]] = {}
        self.retry_events = 0

    # ---------------------------------------------------------------- errors

    def fail(self, exc: BaseException) -> None:
        """Mark the world failed and wake everyone blocked on a barrier."""
        with self._err_lock:
            if self._first_error is None:
                self._first_error = exc
        self._failed.set()
        self._barrier.abort()
        self._health.abort()

    def mark_dead(self, rank: int, exc: BaseException) -> None:
        """Record ``rank`` as permanently failed (simulated preemption /
        crash) and tear the world down so blocked peers raise a
        rank-attributed :class:`RankFailedError` instead of burning their
        full deadlock timeout."""
        self._dead[rank] = exc
        self.fail(exc)

    def _check_failed(self):
        if self._failed.is_set():
            if self._dead:
                dead = sorted(self._dead)
                raise RankFailedError(
                    f"communication world already failed: rank(s) {dead} "
                    "died (preempted or crashed)", ranks=dead
                ) from next(iter(self._dead.values()))
            raise CommError(
                "communication world already failed on another rank"
            ) from self._first_error

    # ----------------------------------------------------------- collectives

    def exchange(self, rank: int, signature: Tuple, payload: Any) -> List[Any]:
        """All ranks deposit (signature, payload); returns the list of all
        payloads in rank order.  Signature mismatch across ranks raises on
        every rank (MPI would deadlock/corrupt; see class docstring).

        This is chokepoint #1 of the runtime observability layer
        (mpi4torch_tpu.obs): with a tracer installed, every rendezvous
        is recorded as a typed CommEvent (payload bytes censused,
        retries attributed, failures snapshotted by the flight
        recorder).  Off path: one attribute read — the fault-plan
        discipline.
        """
        tracer = _cfg.comm_tracer()
        if tracer is None:
            return self._exchange(rank, signature, payload, None)
        meter = tracer.begin(self, rank, "exchange", signature, payload)
        try:
            out = self._exchange(rank, signature, payload, meter)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            tracer.commit(meter, error=e)
            raise
        tracer.commit(meter)
        return out

    def _exchange(self, rank: int, signature: Tuple, payload: Any,
                  meter) -> List[Any]:
        self._check_failed()
        plan = _cfg.fault_plan()
        if plan is not None:
            # Deterministic fault injection (mpi4torch_tpu.resilience):
            # the plan may delay this rank, kill it (RankFailedError
            # raised here, peers attributed through mark_dead), or hand
            # back a corrupted payload — keyed by (rank, op-kind,
            # call-index), so every collective path that funnels through
            # the rendezvous (plain, fused buckets, compressed wire,
            # split-phase starts) shares one censused fault surface.
            payload = plan.on_exchange(self, rank, signature, payload)
        return self._exchange_wire(rank, signature, payload, meter)

    def _exchange_wire(self, rank: int, signature: Tuple, payload: Any,
                       meter) -> List[Any]:
        """The rendezvous WIRE: everything below the chokepoint's tracer
        wrapper and fault hook.  The transport seam
        (mpi4torch_tpu.transport): a transport backend replaces only
        this method (and the p2p/health wire siblings), so the
        chokepoint discipline — tracing, fault injection, retry
        accounting — is INHERITED code on every backend, never
        re-implemented per transport."""
        self._sigs[rank] = signature
        self._slots[rank] = payload
        self._wait_barrier(rank, meter)
        self._check_sig_agreement(self._sigs)
        out = list(self._slots)
        # all readers done before slots are reused
        self._wait_barrier(rank, meter)
        return out

    @staticmethod
    def _check_sig_agreement(sigs) -> None:
        sig0 = sigs[0]
        if any(s != sig0 for s in sigs):
            # Everyone observes the same mismatch => everyone raises; no
            # need to abort the barrier.
            raise CollectiveMismatchError(
                "ranks disagree on the collective being executed: "
                + "; ".join(f"rank {i}: {s}" for i, s in enumerate(sigs))
            )

    def barrier(self, rank: int) -> None:
        self.exchange(rank, ("Barrier",), None)

    def _count_retries(self, used: int, meter) -> None:
        """Retry-extension bookkeeping shared by the rendezvous barrier
        and the p2p receive loop: the world counter (the historical
        bare-attribute surface, kept), the obs metric
        (``mpi4torch_comm_retry_events_total``), and the per-operation
        meter when a tracer is active.  Off the hot path by
        construction — this only runs when a retry actually rescued a
        wait."""
        with self._err_lock:
            self.retry_events += used
        if meter is not None:
            meter.add_retries(used)
        from .obs import metrics as _metrics
        _metrics.inc("comm_retry_events_total", used,
                     help="retry extensions consumed by rendezvous/p2p "
                          "waits that eventually completed")

    def _wait_barrier(self, rank: int, meter=None):
        t0 = time.perf_counter() if meter is not None else 0.0
        try:
            used = self._barrier.wait(rank, self.timeout,
                                      retries=_cfg.comm_retries(),
                                      backoff=_cfg.comm_backoff())
        except _BarrierTimeout as t:
            self._raise_attributed_timeout(t.arrived)
        except _BarrierBroken as b:
            self._raise_broken(b.arrived)
        else:
            if meter is not None:
                # Time spent BLOCKED on peers (vs the event's total
                # duration, which includes this rank's own pre-barrier
                # latency) — the gray-failure detector's signal: the
                # slow rank is the one with high local time and ~zero
                # wait, while everyone else waits on it
                # (mpi4torch_tpu.resilience.health).
                meter.add_wait(time.perf_counter() - t0)
            if used:
                self._count_retries(used, meter)

    def _rank_failed_error(self, verb: str) -> RankFailedError:
        """The dead-rank attribution, shared by every raise site."""
        dead = sorted(self._dead)
        return RankFailedError(
            f"collective {verb}: rank(s) {dead} failed (preempted or "
            "crashed mid-collective)", ranks=dead)

    def _deadlock_error(
            self, arrived: Optional[FrozenSet[int]]) -> DeadlockError:
        """The attributed rendezvous-timeout error, shared by the
        timed-out waiter and its broken-generation peers."""
        arrived = frozenset() if arrived is None else arrived
        missing = frozenset(range(self.size)) - arrived
        return DeadlockError(
            f"collective rendezvous timed out after {self.timeout}s — a "
            "rank did not reach the matching collective (the analogue of "
            "an MPI deadlock; every rank must execute the same "
            "communication sequence, see SURVEY.md §3.3).  Ranks "
            f"{sorted(arrived)} arrived; ranks {sorted(missing)} did not",
            arrived=arrived, missing=missing)

    def _raise_attributed_timeout(self, arrived: FrozenSet[int]):
        """This thread's rendezvous patience (timeout + configured retry
        extensions) ran out: attribute the failure.  A known-dead rank
        explains the hang as a permanent failure; otherwise it is a
        deadlock carrying the arrived/missing rank sets."""
        if self._dead:
            raise self._rank_failed_error("cannot complete") \
                from next(iter(self._dead.values()))
        raise self._deadlock_error(arrived) from None

    def _raise_broken(self, arrived: Optional[FrozenSet[int]]):
        """Another thread broke the barrier: a rank died (attributed), a
        rank raised (context-chained), or a peer's timeout tore the
        generation (same attribution as the peer's)."""
        if self._dead:
            raise self._rank_failed_error("aborted") \
                from next(iter(self._dead.values()))
        if self._first_error is not None:
            raise CommError(
                "collective aborted because another rank failed"
            ) from self._first_error
        raise self._deadlock_error(arrived) from None

    # ----------------------------------------------------------- health

    def health_check(self, rank: int,
                     timeout: Optional[float] = None) -> HealthReport:
        """Timeout-bounded attributed barrier probe — ``ok`` iff every
        rank answered within ``timeout`` (default: the world timeout).
        Runs on a dedicated RESETTABLE barrier: a failed probe reports
        arrived/missing without tearing the collective rendezvous state,
        and once its round has drained the next collective probe starts
        fresh — so a recovered rank is observable as ``ok=True`` again.
        Like any barrier, every live rank must call it collectively.

        The probe ALWAYS runs, even with known-dead ranks: ``arrived``
        only ever contains ranks that really answered THIS probe, so a
        rank that is merely hung (wedged compute, no death recorded)
        lands in ``missing`` next to the dead ones instead of being
        fabricated as healthy."""
        timeout = self.timeout if timeout is None else float(timeout)
        everyone = frozenset(range(self.size))
        t0 = time.monotonic()
        ok, arrived, arrive_t = self._health_wire(rank, timeout)
        return self._health_report(ok, arrived, everyone, t0, arrive_t)

    def _health_wire(self, rank: int, timeout: float):
        """The health-probe WIRE (transport seam — see
        :meth:`_exchange_wire`): returns ``(ok, arrived, arrive_t)``
        from one resettable-barrier probe round."""
        everyone = frozenset(range(self.size))
        arrivals: List[Dict[int, float]] = []
        try:
            self._health.wait(rank, timeout, retries=0, backoff=0.0,
                              collect_arrivals=arrivals)
        except _BarrierTimeout as t:
            return False, t.arrived, t.arrive_t
        except _BarrierBroken as b:
            arrived = frozenset() if b.arrived is None else b.arrived
            return False, arrived, b.arrive_t
        return True, everyone, arrivals[0] if arrivals else {}

    def _health_report(self, ok: bool, arrived: FrozenSet[int],
                       everyone: FrozenSet[int], t0: float,
                       arrive_t: Optional[Dict[int, float]] = None
                       ) -> HealthReport:
        """Assemble a probe report and count it in the obs metrics
        registry (``comm_health_probes_total`` with an ok/failed result
        label) — probes are exceptional-path by construction, so the
        registry write is off the comm hot path like every other obs
        metric."""
        dur = time.monotonic() - t0
        from .obs import metrics as _metrics
        _metrics.inc(
            f'comm_health_probes_total{{result="{"ok" if ok else "failed"}"}}',
            help="health_check barrier probes by outcome")
        # Per-rank arrival latency relative to the round's FIRST arrival
        # (ISSUE 15): slow ranks carry a large offset, dead ranks carry
        # none — check_health distinguishes slow from dead instead of
        # collapsing both into `missing`.
        arrival_s: Dict[int, float] = {}
        if arrive_t:
            first = min(arrive_t.values())
            arrival_s = {r: t - first for r, t in arrive_t.items()
                         if r in arrived}
        return HealthReport(ok, self.size, frozenset(arrived),
                            everyone - frozenset(arrived),
                            probe_duration_s=dur, arrival_s=arrival_s)

    # ------------------------------------------------------------------ p2p

    def _mailbox(self, src: int, dst: int, tag: int) -> "queue.Queue":
        key = (src, dst, tag)
        with self._mb_lock:
            q = self._mailboxes.get(key)
            if q is None:
                q = queue.Queue()
                self._mailboxes[key] = q
            return q

    def p2p_send(self, src: int, dst: int, tag: int, payload: Any) -> None:
        """Buffered-mode send: never blocks (the eager analogue of MPI_Isend,
        csrc/extension.cpp:1071-1113).  Chokepoint #2a of the obs
        tracing layer (see :meth:`exchange`)."""
        tracer = _cfg.comm_tracer()
        if tracer is None:
            return self._p2p_send(src, dst, tag, payload, None)
        meter = tracer.begin(self, src, "p2p_send", ("p2p_send", tag),
                             payload, peer=dst, tag=tag)
        try:
            out = self._p2p_send(src, dst, tag, payload, meter)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            tracer.commit(meter, error=e)
            raise
        tracer.commit(meter)
        return out

    def _p2p_send(self, src: int, dst: int, tag: int, payload: Any,
                  meter) -> None:
        self._check_failed()
        if not (0 <= dst < self.size):
            raise CommError(f"invalid destination rank {dst} (size {self.size})")
        plan = _cfg.fault_plan()
        if plan is not None:
            # The fault layer may delay/kill/corrupt the send like an
            # exchange, or DROP the message entirely (stashed in
            # self._dropped for retry-triggered redelivery).
            payload = plan.on_p2p_send(self, src, dst, tag, payload)
            if payload is _P2P_DROPPED:
                # The stash already happened inside the plan hook
                # (world._dropped); a remote transport relocates it to
                # wherever its receiver-side redelivery lives.
                self._on_wire_drop(src, dst, tag)
                return
        self._p2p_send_wire(src, dst, tag, payload)

    def _p2p_send_wire(self, src: int, dst: int, tag: int,
                       payload: Any) -> None:
        """The p2p send WIRE (transport seam — see
        :meth:`_exchange_wire`)."""
        self._mailbox(src, dst, tag).put(payload)

    def _on_wire_drop(self, src: int, dst: int, tag: int) -> None:
        """Transport hook after a fault-injected drop: on the thread
        backend the dropped payload already sits in ``self._dropped``
        where the receiver's retry redelivers from — nothing to do."""

    def p2p_recv(self, src: int, dst: int, tag: int) -> Any:
        """Blocking receive with deadlock timeout (analogue of MPI_Irecv+Wait,
        csrc/extension.cpp:1115-1157, 1245-1249).  With
        ``config.comm_retries`` set, a receive that finds nothing within
        the base timeout retries with capped exponential backoff
        (``config.comm_backoff``), each retry first requesting
        redelivery of any fault-dropped message — the eager analogue of
        a NACK-triggered retransmission — so a transient message drop
        recovers instead of deadlocking.  Chokepoint #2b of the obs
        tracing layer (see :meth:`exchange`); the received payload's
        bytes are censused at completion."""
        tracer = _cfg.comm_tracer()
        if tracer is None:
            return self._p2p_recv(src, dst, tag, None)
        meter = tracer.begin(self, dst, "p2p_recv", ("p2p_recv", tag),
                             None, peer=src, tag=tag)
        try:
            out = self._p2p_recv(src, dst, tag, meter)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            tracer.commit(meter, error=e)
            raise
        tracer.commit(meter, result_payload=out)
        return out

    def _p2p_recv(self, src: int, dst: int, tag: int, meter) -> Any:
        if not (0 <= src < self.size):
            raise CommError(f"invalid source rank {src} (size {self.size})")
        return self._p2p_recv_wire(src, dst, tag, meter)

    def _p2p_recv_wire(self, src: int, dst: int, tag: int, meter) -> Any:
        """The p2p receive WIRE (transport seam — see
        :meth:`_exchange_wire`): the blocking wait, the retry/backoff
        patience windows, and the dropped-message redelivery."""
        q = self._mailbox(src, dst, tag)
        retries = _cfg.comm_retries()
        backoff = _cfg.comm_backoff()
        attempt = 0
        deadline = time.monotonic() + self.timeout
        while True:
            # The src-specific check runs BEFORE the generic world-failed
            # check: mark_dead() sets both, and the per-receive
            # attribution (which peer this receive was waiting on) is
            # the more useful error for a blocked receiver.
            if src in self._dead:
                raise RankFailedError(
                    f"receive (src={src}, dst={dst}, tag={tag}) cannot "
                    f"complete: rank {src} failed", ranks=(src,)
                ) from self._dead[src]
            self._check_failed()
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                if time.monotonic() > deadline:
                    if attempt < retries:
                        attempt += 1
                        if self._redeliver_dropped(src, dst, tag):
                            self._count_retries(1, meter)
                        deadline = time.monotonic() + _backoff_pause(
                            attempt, backoff, self.timeout)
                        continue
                    with self._mb_lock:
                        was_dropped = bool(self._dropped.get((src, dst, tag)))
                    raise DeadlockError(
                        f"receive (src={src}, dst={dst}, tag={tag}) timed "
                        f"out after {self.timeout}s — matching send never "
                        "posted" + (
                            " (a fault-injected drop consumed the message "
                            "and config.comm_retries is exhausted/unset)"
                            if was_dropped else "")
                    ) from None

    def _redeliver_dropped(self, src: int, dst: int, tag: int) -> bool:
        """Move one fault-dropped payload back onto the mailbox (the
        retransmission a real transport performs on NACK)."""
        with self._mb_lock:
            stash = self._dropped.get((src, dst, tag))
            if not stash:
                return False
            payload = stash.pop(0)
        self._mailbox(src, dst, tag).put(payload)
        return True

    # ------------------------------------------------------------- requests

    def new_request(self, kind: int, rank: int, peer: int, tag: int,
                    shape: Tuple[int, ...], dtype: Any) -> _PendingRequest:
        with self._req_lock:
            self._req_counter += 1
            rid = self._req_counter
        fp = _fnv1a((rid, kind, peer, tag, shape, str(dtype)))
        req = _PendingRequest(rid, kind, rank, peer, tag, tuple(shape), dtype, fp)
        with self._req_lock:
            self._pending[rid] = req
        return req

    def complete_request(self, req_id: int, shape: Tuple[int, ...],
                         dtype: Any) -> _PendingRequest:
        """Pop a pending request, enforcing the reference's wait-handle
        guards (csrc/extension.cpp:1231-1237: descriptor hash re-check;
        1196-1202: backward-graph shape check)."""
        with self._req_lock:
            req = self._pending.pop(req_id, None)
        if req is None:
            raise BifurcationError(
                f"Detected bifurcation in Wait handle usage: request {req_id} "
                "is unknown or was already waited on (a WaitHandle must be "
                "waited on exactly once, and its parts must not be swapped "
                "between handles; reference guard csrc/extension.cpp:1231-1237)"
            )
        if tuple(shape) != req.shape or dtype != req.dtype:
            with self._req_lock:
                self._pending[req_id] = req  # restore for diagnostics
            raise BifurcationError(
                "Detected bifurcation in Wait handle usage: the buffer in the "
                f"handle (shape {tuple(shape)}, dtype {dtype}) does not match "
                f"the posted request (shape {req.shape}, dtype {req.dtype})"
            )
        return req

    # -------------------------------------------------- in-place reuse guard

    # Bound on the consumed-input guard table: entries beyond this are
    # evicted FIFO (dropping an entry only weakens detection for that old
    # tensor; it can never cause a false positive, because evicting also
    # drops the strong ref that pinned the id).
    _CONSUMED_CAP = 4096

    def mark_consumed(self, rank: int, x: Any) -> None:
        """Record ``x`` as consumed by an in-place collective ON ``rank``.
        The reference splices an ``MPINoInplaceBackward`` node onto the
        *input* of Reduce_ so any later use raises at backward time
        (csrc/extension.cpp:395-403, 451-462).  Functionally-pure JAX has
        no aliasing hazard, so this is a parity/discipline guard: later
        *communication* ops reject the value.  Keyed per rank because
        ranks are threads sharing one process — collectives may hand the
        SAME result object to every rank (Allreduce's fold-once path),
        and rank r consuming its copy must not taint rank s's (in MPI
        they would be distinct buffers in distinct processes).
        """
        self._consumed[(rank, id(x))] = x  # strong ref pins id while tracked
        while len(self._consumed) > self._CONSUMED_CAP:
            self._consumed.pop(next(iter(self._consumed)))

    def check_not_consumed(self, rank: int, *arrays: Any) -> None:
        for a in arrays:
            if (rank, id(a)) in self._consumed:
                raise InPlaceReuseError(
                    "Reuse of variables passed to in-place MPI kernels is not "
                    "supported (reference guard csrc/extension.cpp:451-462): "
                    "this tensor was consumed by Reduce_ — use its return "
                    "value instead"
                )


@dataclass
class RankContext:
    """Binds the current thread to (world, rank) — the eager analogue of the
    per-process MPI rank identity."""
    world: World
    rank: int


_tls = threading.local()


def current_rank_context() -> Optional[RankContext]:
    return getattr(_tls, "ctx", None)


class _bind_rank:
    def __init__(self, ctx: RankContext):
        self.ctx = ctx

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False


# A default single-rank world so that user scripts work without any launcher,
# exactly like running an MPI program without mpirun (world size 1).
_default_world = World(1)
_default_ctx = RankContext(_default_world, 0)


def effective_rank_context() -> RankContext:
    ctx = current_rank_context()
    return ctx if ctx is not None else _default_ctx


def _fn_nparams(fn: Callable) -> int:
    """How many required positional parameters ``fn`` takes — decides
    the ``fn()`` vs ``fn(rank)`` calling convention of :func:`run_ranks`
    (shared with the transport backends, which must apply the SAME
    convention in a worker process)."""
    import inspect

    try:
        return len([
            p for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is p.empty
        ])
    except (TypeError, ValueError):
        return 0


def _raise_primary(errors: List[Optional[BaseException]],
                   first_error: Optional[BaseException]) -> None:
    """Re-raise the root-cause per-rank error with the other ranks'
    failures attached as a PEP-678 note — ONE rule for the thread
    backend and the process transport, so a failed run reads the same
    on every backend."""
    failed = [(r, e) for r, e in enumerate(errors) if e is not None]
    if not failed:
        return
    # Prefer the root-cause error over secondary abort noise, and attach
    # the other ranks' failures as context.
    primary = first_error
    if primary is None or primary not in errors:
        primary = failed[0][1]
    secondary = [(r, e) for r, e in failed if e is not primary]
    if secondary:
        note = ("other rank failures: "
                + "; ".join(f"rank {r}: {type(e).__name__}: {e}"
                            for r, e in secondary))
        if hasattr(primary, "add_note"):    # PEP 678, Python >= 3.11
            primary.add_note(note)
        else:
            # 3.10: stash where debuggers can see it; tracebacks
            # render the primary error unchanged.
            primary.__notes__ = getattr(primary, "__notes__", []) + [note]
    raise primary


def run_ranks(fn: Callable, nranks: int, timeout: Optional[float] = None,
              return_results: bool = True,
              backend: Optional[str] = None) -> List[Any]:
    """Run ``fn`` on ``nranks`` ranks — the `mpirun -np N` analogue.

    ``fn`` is called either as ``fn()`` or ``fn(rank)`` (if it accepts one
    positional argument).  Inside, ``mpi4torch_tpu.COMM_WORLD`` resolves to
    this world with a concrete Python-int rank, so reference-style per-rank
    scripts (rank-conditional shapes and asserts) run unmodified in spirit
    (SURVEY.md §4 'What the rebuild needs').

    ``backend`` selects the transport (mpi4torch_tpu.transport):
    ``"thread"`` — N rank-threads in this process, the historical
    semantics and the default; ``"process"`` — N spawned worker
    processes over the pickle-framed socket transport (real parallelism,
    real SIGKILLs).  ``None`` defers to ``config.comm_transport()``
    (itself defaulting to the ``MPI4TORCH_TPU_TRANSPORT`` environment
    variable, else ``"thread"``).

    ``timeout`` is the world's deadlock-detection wall clock;  ``None``
    (default) defers to ``World``'s own default, i.e. the
    ``MPI4TORCH_TPU_WORLD_TIMEOUT`` environment override or 60s — it
    used to pin 60.0 here, silently bypassing the env var that
    ``World(timeout=None)`` honors (ISSUE 7 satellite bugfix).

    Exceptions: the first per-rank exception is re-raised on the caller
    after all threads have been reaped; other ranks' failures are attached
    as context.
    """
    name = backend if backend is not None else _cfg.comm_transport()
    if name != "thread":
        from .transport import get_transport

        return get_transport(name).run_ranks(
            fn, nranks, timeout=timeout, return_results=return_results)

    world = World(nranks, timeout=timeout)
    results: List[Any] = [None] * nranks
    errors: List[Optional[BaseException]] = [None] * nranks
    nparams = _fn_nparams(fn)

    def worker(rank: int):
        with _bind_rank(RankContext(world, rank)):
            try:
                results[rank] = fn(rank) if nparams >= 1 else fn()
            except BaseException as e:  # noqa: BLE001 — reaped below
                errors[rank] = e
                tracer = _cfg.comm_tracer()
                if tracer is not None:
                    # Flight recorder (mpi4torch_tpu.obs): failures that
                    # surface OUTSIDE the chokepoints (integrity guards
                    # run on the decoded list after the rendezvous
                    # returns) still get a rank-attributed postmortem —
                    # this reaper is the one site that sees every rank
                    # failure with its world identity.
                    tracer.note_rank_failure(world, rank, e)
                world.fail(e)

    threads = [threading.Thread(target=worker, args=(r,), name=f"rank{r}")
               for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    _raise_primary(errors, world._first_error)
    return results if return_results else []

"""Schedule synthesis: an autotuner leg searching over IR programs.

The door GC3 / "The Big Send-off" open: once algorithms are programs,
new schedules are POINTS IN A SEARCH SPACE instead of hand-written
forks.  The bounded family here is the multi-level grouped ordered
fold — one ``level_fold`` tier per factor of an ordered factorization
chain of the world size (the named ``hier`` schedule is exactly the
2-level member; deeper chains are genuinely new programs).  Candidates
are scored on the deterministic census (:mod:`.census` — wire bytes,
then sequential rounds, then digest for a stable tie-break), so
synthesis is a pure function of ``(nranks, nbytes bucket)``: the same
inputs always pick the same winner.

Winners are cached under the existing tune cache key like algorithms
today: the entry's algorithm name is ``synth:<digest>`` and the entry
carries the serialized program, which installs into the in-process
registry on lookup — so a later process lowers/interprets the winner
with zero re-search.  ``select_auto`` honors installed synthesized
winners in deterministic mode (where the grouped fold family beats the
ordered gather fold's ``(N-1)·S`` wire); wall-clock-measured non-det
selection ignores them (a det-census verdict must not steer it).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..runtime import CommError
from .census import (program_census, program_tier_census,
                     tier_of_groups, weighted_cost)
from .ir import Phase, Program, Step

# In-process registry of installed synthesized programs, keyed by the
# full cache name ("synth:<digest>").  Entries arrive from synthesis
# runs in this process or from persisted tune-cache entries on lookup.
_INSTALLED: Dict[str, Program] = {}

SYNTH_PREFIX = "synth:"

# Search bound: factorization chains up to this many tiers.  Every
# chain member costs (factor-1)·S wire, so useful depth is log2(n);
# 4 tiers cover worlds to 16 ranks exhaustively.
MAX_LEVELS = 4


def is_synth_name(name) -> bool:
    return isinstance(name, str) and name.startswith(SYNTH_PREFIX)


def factorization_chains(n: int, max_levels: int = MAX_LEVELS
                         ) -> List[Tuple[int, ...]]:
    """Ordered factorizations of ``n`` into factors >= 2 (up to
    ``max_levels`` factors), sorted for determinism.  ``(n,)`` — the
    single flat tier — is always a member."""
    out = set()

    def rec(rem: int, chain: Tuple[int, ...]):
        if rem == 1:
            if chain:
                out.add(chain)
            return
        if len(chain) == max_levels - 1:
            out.add(chain + (rem,))
            return
        for f in range(2, rem + 1):
            if rem % f == 0:
                rec(rem // f, chain + (f,))

    rec(n, ())
    return sorted(out)


def chain_groups(n: int, chain: Tuple[int, ...]):
    """The per-tier rank groupings of a factorization chain: tier ``l``
    groups ranks that differ only in the ``l``-th mixed-radix digit —
    each group has one member per lower-tier block, every member
    holding its block's partial, so the tiers compose exactly like
    ``reduce_grouped``'s inner/outer pair (which IS the 2-level
    member)."""
    levels = []
    stride = 1
    for f in chain:
        block = stride * f
        groups = []
        for c in range(n // block):
            for o in range(stride):
                groups.append(tuple(c * block + j * stride + o
                                    for j in range(f)))
        levels.append((tuple(groups), f))
        stride = block
    return levels


def fold_program(n: int, chain: Tuple[int, ...],
                 tiers=None) -> Program:
    """The multi-level grouped ordered-fold program of a chain.  Each
    step carries its tier index: the chain position by default, or —
    when the PHYSICAL tier stack ``tiers`` is given — the stack tier
    its groups attribute to (:func:`.census.tier_of_groups`), so a
    chain that merges or splits physical tiers is labeled by the links
    its bytes actually cross."""
    if any(f < 2 for f in chain) or _prod(chain) != n:
        raise CommError(
            f"factorization chain {chain} does not factor a {n}-rank "
            "world into tiers of >= 2")
    steps = tuple(
        Step("level_fold", (groups, f),
             tier=(tier_of_groups(groups, tiers)
                   if tiers is not None else level))
        for level, (groups, f) in enumerate(chain_groups(n, chain)))
    return Program("allreduce", "synth", n, (Phase("seq", steps),))


def _prod(t) -> int:
    p = 1
    for f in t:
        p *= int(f)
    return p


def synthesize(n: int, nbytes: int, itemsize: int = 4) -> Dict:
    """Search the bounded family at one ``(nranks, nbytes)`` point.
    Returns the deterministic report: every candidate's census, the
    winner (name, program, census), and the ring baseline it is scored
    against (the DETERMINISTIC ring — the ordered fold, the schedule a
    synthesized winner would actually replace)."""
    from .programs import allreduce_program
    from .. import constants as C

    nelems = max(1, nbytes // itemsize)
    ring = allreduce_program("ring", n, C.MPI_SUM, deterministic=True,
                             nelems=nelems, itemsize=itemsize)
    ring_census = program_census(ring, nelems, itemsize)
    candidates = []
    for chain in factorization_chains(n):
        prog = fold_program(n, chain)
        cen = program_census(prog, nelems, itemsize)
        candidates.append((chain, prog, cen))
    if not candidates:
        # A 1-rank world has no schedule to synthesize.
        return {"nranks": n, "nbytes": int(nbytes), "winner": None,
                "chain": [], "program": None, "census": ring_census,
                "ring_census": ring_census,
                "synthesis_beats_ring": False, "candidates": []}
    # Deterministic ranking: wire bytes, then sequential rounds, then
    # the digest (content-stable, so ties can never flip across runs).
    ranked = sorted(
        candidates,
        key=lambda c: (c[2]["wire_bytes_per_rank"], c[2]["seq_steps"],
                       c[1].digest()))
    chain, prog, cen = ranked[0]
    name = SYNTH_PREFIX + prog.digest()
    beats = (cen["wire_bytes_per_rank"]
             < ring_census["wire_bytes_per_rank"]) or (
        cen["wire_bytes_per_rank"] == ring_census["wire_bytes_per_rank"]
        and cen["seq_steps"] < ring_census["seq_steps"])
    return {
        "nranks": n,
        "nbytes": int(nbytes),
        "winner": name,
        "chain": list(chain),
        "program": prog,
        "census": cen,
        "ring_census": ring_census,
        "synthesis_beats_ring": bool(beats),
        "candidates": [
            {"chain": list(ch), "wire_bytes_per_rank":
                c["wire_bytes_per_rank"], "seq_steps": c["seq_steps"]}
            for ch, _p, c in ranked],
    }


# ---------------------------------------------------------------------------
# Tier-dimension synthesis (ISSUE 18)
# ---------------------------------------------------------------------------

# The registered tier compositions — the per-tier (algorithm × codec)
# points the tier search emits.  "exact" is a tier-annotated grouped
# fold chain (every tier exact); "q8-slow" rewrites the chain's
# slow-tier folds (bandwidth strictly below the stack's fastest) to
# q8_level_fold codec hops — EQuARX's move of spending quantization
# where the link is slow.  The registry guard
# (analyze.registry.tier_program_problems) requires each name to hold a
# parity cell + census cell in the tiers lane and a declared VJP.
TIER_COMPOSITIONS = ("exact", "q8-slow")


def rewrite_fold_codec(program: Program, slow_tiers,
                       codec: str = "q8") -> Program:
    """Per-tier codec rewrite: every ``level_fold`` whose tier index is
    in ``slow_tiers`` becomes a ``q8_level_fold`` carrying ``codec`` —
    the same program-transformation discipline as
    :func:`.programs.rewrite_codec`, applied per tier instead of per
    channel."""
    slow = frozenset(slow_tiers)
    phases = tuple(
        Phase(ph.kind, tuple(
            Step("q8_level_fold", s.params, s.span, codec, s.tier)
            if s.kind == "level_fold" and s.tier in slow else s
            for s in ph.steps))
        for ph in program.phases)
    return Program(program.collective, program.algorithm,
                   program.nranks, phases, program.codec)


def _resolved_tiers(n: int, tiers):
    from .. import config as _config

    if tiers is None:
        tiers = _config.tier_stack()
    if tiers is None:
        return (n,)
    tiers = tuple(int(t) for t in tiers)
    if _prod(tiers) != n or any(t < 2 for t in tiers):
        raise CommError(
            f"tier_stack {tiers} does not factor a {n}-rank world "
            "into tiers of >= 2")
    return tiers


def synthesize_tiers(n: int, nbytes: int, itemsize: int = 4,
                     tiers=None, tier_bandwidths=None,
                     codec: str = "q8") -> Dict:
    """The tier-dimension search: per-tier (algorithm × codec)
    compositions ranked by the BANDWIDTH-WEIGHTED wire census
    (:func:`.census.weighted_cost` over
    :func:`.census.program_tier_census`), scored against the flat
    ``bidir`` schedule — the strongest flat exact baseline, whose
    whole-axis traffic all crosses the top (slowest) tier.  Candidates
    are every ordered factorization chain of ``n`` (tier merging IS an
    algorithm choice), each in its ``TIER_COMPOSITIONS`` variants.  The
    lossy ``q8-slow`` variants exist only when some tier's bandwidth is
    strictly below the fastest: with uniform bandwidths the search is
    all-exact and the ranking degenerates to the unweighted census —
    no regression by construction."""
    from .programs import allreduce_program
    from .. import config as _config
    from .. import constants as C

    nelems = max(1, nbytes // itemsize)
    tiers = _resolved_tiers(n, tiers)
    if tier_bandwidths is None:
        tier_bandwidths = _config.tier_bandwidths()
    if tier_bandwidths is None:
        tier_bandwidths = (1.0,) * len(tiers)
    bw = tuple(float(b) for b in tier_bandwidths)
    if len(bw) != len(tiers):
        raise CommError(
            f"tier_bandwidths {bw} has {len(bw)} entries for the "
            f"{len(tiers)}-tier stack {tiers}")
    base = {"nranks": n, "nbytes": int(nbytes), "tiers": list(tiers),
            "tier_bandwidths": list(bw)}
    if n <= 1:
        return dict(base, winner=None, exact_winner=None,
                    beats_bidir=False, candidates=[])
    bidir = allreduce_program("bidir", n, C.MPI_SUM,
                              deterministic=False, nelems=nelems,
                              itemsize=itemsize)
    bidir_tier = program_tier_census(bidir, nelems, itemsize, tiers)
    bidir_cost = weighted_cost(bidir_tier, bw)
    slow = tuple(level for level, b in enumerate(bw) if b < max(bw))
    candidates = []
    for chain in factorization_chains(n):
        exact = fold_program(n, chain, tiers)
        variants = [("exact", exact)]
        if slow and codec is not None:
            lossy = rewrite_fold_codec(exact, slow, codec)
            if lossy != exact:
                variants.append(("q8-slow", lossy))
        for comp, prog in variants:
            per_tier = program_tier_census(prog, nelems, itemsize,
                                           tiers)
            cen = program_census(prog, nelems, itemsize)
            candidates.append({
                "chain": chain, "composition": comp, "program": prog,
                "census": cen, "tier_wire": per_tier,
                "weighted_cost": weighted_cost(per_tier, bw)})
    ranked = sorted(
        candidates,
        key=lambda c: (c["weighted_cost"], c["census"]["seq_steps"],
                       c["program"].digest()))
    exact_ranked = [c for c in ranked if c["composition"] == "exact"]

    def _entry(c):
        return {"winner": SYNTH_PREFIX + c["program"].digest(),
                "chain": list(c["chain"]),
                "composition": c["composition"],
                "program": c["program"], "census": c["census"],
                "tier_wire": list(c["tier_wire"]),
                "weighted_cost": c["weighted_cost"]}

    win = _entry(ranked[0])
    exact_win = _entry(exact_ranked[0])
    return dict(
        base,
        bidir_tier_wire=list(bidir_tier),
        bidir_weighted_cost=bidir_cost,
        beats_bidir=bool(win["weighted_cost"] < bidir_cost),
        exact_beats_bidir=bool(
            exact_win["weighted_cost"] < bidir_cost),
        candidates=[
            {"chain": list(c["chain"]),
             "composition": c["composition"],
             "tier_wire": list(c["tier_wire"]),
             "weighted_cost": c["weighted_cost"],
             "seq_steps": c["census"]["seq_steps"]}
            for c in ranked],
        **{"winner": win["winner"], "chain": win["chain"],
           "composition": win["composition"],
           "program": win["program"], "census": win["census"],
           "tier_wire": win["tier_wire"],
           "weighted_cost": win["weighted_cost"],
           "exact_winner": exact_win["winner"],
           "exact_chain": exact_win["chain"],
           "exact_program": exact_win["program"],
           "exact_tier_wire": exact_win["tier_wire"],
           "exact_weighted_cost": exact_win["weighted_cost"]})


# ---------------------------------------------------------------------------
# Registry + tune-cache integration
# ---------------------------------------------------------------------------


def install(program: Program) -> str:
    """Install a synthesized program; returns its cache name."""
    name = SYNTH_PREFIX + program.digest()
    _INSTALLED[name] = program
    return name


def installed_program(name: str, nranks: Optional[int] = None) -> Program:
    prog = _INSTALLED.get(name)
    if prog is None:
        raise CommError(
            f"synthesized schedule {name!r} is not installed in this "
            "process — run csched.synth.synthesize/autotune_synthesis, "
            "or let a tune-cache lookup install the persisted winner")
    if nranks is not None and prog.nranks != nranks:
        raise CommError(
            f"synthesized schedule {name!r} was built for "
            f"{prog.nranks} ranks, not {nranks}")
    return prog


def synth_applicable(name, nranks: int) -> bool:
    prog = _INSTALLED.get(name)
    return prog is not None and prog.nranks == nranks


def validate_entry(name: str, program_json) -> None:
    """Tune-cache validation hook for ``synth:`` winners: the entry
    must carry a program whose digest matches the name; a valid entry
    installs, so a persisted winner is lowerable right after lookup.
    Raises ``ValueError`` (the autotuner's stale-entry signal) on any
    mismatch."""
    if not isinstance(program_json, dict):
        raise ValueError(
            f"synthesized winner {name!r} has no serialized program")
    try:
        prog = Program.from_json(program_json)
    except Exception as e:  # noqa: BLE001 — any defect means "stale"
        # A corrupt entry — or one written by a NEWER version whose
        # extended grammar this build does not know (Step/Phase raise
        # CommError on unknown kinds) — must surface as the autotuner's
        # stale-entry signal (ValueError, caught by lookup), never
        # crash deterministic auto-selection.
        raise ValueError(
            f"synthesized winner {name!r} carries a program this "
            f"build cannot load: {e}") from e
    if SYNTH_PREFIX + prog.digest() != name:
        raise ValueError(
            f"synthesized winner {name!r} does not match its program "
            f"digest {prog.digest()!r}")
    _INSTALLED[name] = prog


def clear_installed() -> None:
    _INSTALLED.clear()


def autotune_synthesis(nranks: Optional[int] = None,
                       sizes=(1 << 10, 1 << 14, 1 << 18),
                       dtype=None, persist: bool = True) -> Dict:
    """The synthesis autotuner leg: search each size bucket, install
    winners that beat the deterministic ring, and record them under the
    existing tune cache key (``synth:<digest>`` + the serialized
    program riding the entry).  Deterministic-mode auto selection then
    serves them like any measured winner."""
    import jax
    import jax.numpy as jnp

    from .. import tune as _tune

    if dtype is None:
        dtype = jnp.float32
    n = nranks or len(jax.devices())
    itemsize = jnp.dtype(dtype).itemsize
    report = {"collective": "allreduce", "nranks": n,
              "dtype": str(jnp.dtype(dtype)), "entries": {}}
    for nbytes in sizes:
        res = synthesize(n, int(nbytes), itemsize)
        ent = {k: res[k] for k in ("winner", "chain", "census",
                                   "ring_census",
                                   "synthesis_beats_ring")}
        if res["synthesis_beats_ring"] and n > 1:
            prog = res["program"]
            install(prog)
            # The codec key dimension keeps census-synthesized winners
            # in their own slot: they can never clobber — or be
            # clobbered by — wall-clock-measured winners of the same
            # bucket (the same separation compressed traffic uses).
            _tune.record("allreduce", dtype, int(nbytes), n,
                         res["winner"], persist=persist, codec="synth",
                         program=prog.to_json())
            ent["recorded"] = True
        report["entries"][str(int(nbytes))] = ent
    return report


def autotune_tier_synthesis(nranks: Optional[int] = None,
                            sizes=(1 << 10, 1 << 14, 1 << 18),
                            dtype=None, persist: bool = True,
                            tiers=None, tier_bandwidths=None) -> Dict:
    """The tier-synthesis autotuner leg: run the weighted search per
    size bucket, install the winners, and record them under the
    tier-keyed cache slot (``make_key(..., tiers=)``).  The EXACT
    winner records under ``codec="synth"`` — same slot discipline as
    the flat leg; a lossy ``q8-slow`` winner records under
    ``codec="synth_q8"``, a slot deterministic auto-selection never
    consults, so compressed tier schedules stay explicit opt-in
    (``algorithm="synth:<digest>"``) like every other codec."""
    import jax
    import jax.numpy as jnp

    from .. import tune as _tune

    if dtype is None:
        dtype = jnp.float32
    n = nranks or len(jax.devices())
    itemsize = jnp.dtype(dtype).itemsize
    tiers = _resolved_tiers(n, tiers)
    report = {"collective": "allreduce", "nranks": n,
              "tiers": list(tiers),
              "dtype": str(jnp.dtype(dtype)), "entries": {}}
    for nbytes in sizes:
        res = synthesize_tiers(n, int(nbytes), itemsize, tiers=tiers,
                               tier_bandwidths=tier_bandwidths)
        ent = {k: res[k] for k in
               ("winner", "chain", "composition", "tier_wire",
                "weighted_cost", "exact_winner", "exact_tier_wire",
                "exact_weighted_cost", "bidir_tier_wire",
                "bidir_weighted_cost", "beats_bidir")}
        if res["beats_bidir"] and n > 1:
            exact = res["exact_program"]
            install(exact)
            _tune.record("allreduce", dtype, int(nbytes), n,
                         res["exact_winner"], persist=persist,
                         codec="synth", tiers=tiers,
                         program=exact.to_json())
            if res["winner"] != res["exact_winner"]:
                prog = res["program"]
                install(prog)
                _tune.record("allreduce", dtype, int(nbytes), n,
                             res["winner"], persist=persist,
                             codec="synth_q8", tiers=tiers,
                             program=prog.to_json())
            ent["recorded"] = True
        report["entries"][str(int(nbytes))] = ent
    return report

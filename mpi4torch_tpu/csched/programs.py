"""Registered IR programs: one builder per collective algorithm.

Each builder re-expresses a hand-written schedule (ops/spmd.py) as an
IR program whose one-emitter lowering (:mod:`.lower`) is BIT-IDENTICAL
— same StableHLO text — to the original form, pinned by
``make ir-smoke`` and tests/test_csched.py.  Builders mirror the
original dispatch decisions exactly (op cases, deterministic mode, the
size thresholds from :mod:`mpi4torch_tpu.config`, applicability
raises), so a program is a pure function of the same static call data
the hand-written fork read — ``run_spmd``'s jit cache key already
covers all of it.
"""

from __future__ import annotations

from typing import Tuple

from .. import config as _config
from .. import constants as C
from ..runtime import CommError
from .ir import Phase, Program, Step

# Algorithms with a registered IR program builder.  An algorithm
# registered in tune.registry must appear here or in NATIVE_EXEMPT —
# the csched_problems registry-sync guard enforces it.
PROGRAM_ALGORITHMS = ("ring", "rhd", "tree", "hier", "bidir", "torus")

# Registered algorithms explicitly exempted from the IR (none today:
# all six allreduce schedules re-express through the grammar).
NATIVE_EXEMPT: Tuple[str, ...] = ()


def has_program(algorithm: str) -> bool:
    return algorithm in PROGRAM_ALGORITHMS or (
        isinstance(algorithm, str) and algorithm.startswith("synth:"))


def _ident(collective: str, algorithm: str, n: int) -> Program:
    return Program(collective, algorithm, n, ())


def _hier_groups(n: int, g: int):
    ngroups = n // g
    inner = tuple(tuple(b * g + i for i in range(g))
                  for b in range(ngroups))
    outer = tuple(tuple(i + b * g for b in range(ngroups))
                  for i in range(g))
    return inner, outer, ngroups


def _ordered_fold_program(algorithm: str, n: int, op: int, nelems: int,
                          itemsize: int) -> Program:
    """The deterministic ordered-fold dispatch of ops/spmd
    ``_ordered_fold_allreduce``: the all-gather+fold form below the
    gather threshold, the chunked scan ring above it."""
    if n == 1:
        return _ident("allreduce", algorithm, n)
    gathered = nelems * itemsize * n
    if gathered <= _config.ordered_fold_gather_max_bytes():
        step = Step("level_fold", (None, n))
    else:
        step = Step("ring_fold")
    return Program("allreduce", algorithm, n, (Phase("seq", (step,)),))


def allreduce_program(algorithm, n: int, op: int, *, deterministic: bool,
                      nelems: int, itemsize: int) -> Program:
    """The IR program computing ``Allreduce(op)`` with ``algorithm`` on
    an ``n``-rank axis — the branch-for-branch re-expression of
    ``ops/spmd._allreduce_fwd_value`` and the per-algorithm value
    functions it dispatched to.  Raises exactly where the hand-written
    forms raised (rhd on non-power-of-two worlds, hier/torus without a
    2-level factorization, MINLOC/MAXLOC everywhere)."""
    algorithm = algorithm or "ring"
    if isinstance(algorithm, str) and algorithm.startswith("synth:"):
        from . import synth as _synth

        return _synth.installed_program(algorithm, n)

    if algorithm == "rhd":
        if n == 1:
            return _ident("allreduce", "rhd", n)
        if n & (n - 1):
            raise CommError(
                f"the 'rhd' (recursive halving/doubling) schedule needs a "
                f"power-of-two world; got {n} ranks — use 'tree' for the "
                "logarithmic schedule at this size, or 'ring'")
        return Program("allreduce", "rhd", n,
                       (Phase("seq", (Step("butterfly"),)),))

    if algorithm == "tree":
        if n == 1:
            return _ident("allreduce", "tree", n)
        return Program("allreduce", "tree", n, (Phase("seq", (
            Step("tree_reduce", (0,)), Step("tree_bcast", (0,)))),))

    if algorithm == "hier":
        if n == 1:
            return _ident("allreduce", "hier", n)
        from ..tune import resolve_hier_group, resolve_tier_stack

        g = resolve_hier_group(n)
        inner, outer, ngroups = _hier_groups(n, g)
        if op == C.MPI_SUM and not deterministic:
            # A deeper config.tier_stack merges its outer tiers into
            # the inter-group stage here: grouped_sum IS the native
            # 2-level triple (the full N-level recursion lives on the
            # mesh-axis backend, ops/spmd._tier_sum_schedule).
            return Program("allreduce", "hier", n, (Phase("seq", (
                Step("grouped_sum", (g, inner, outer, inner)),)),))
        stack = resolve_tier_stack(n)
        if len(stack) > 2:
            # Deterministic N-level stack: the full tier-annotated
            # grouped-fold chain (one level_fold per configured tier) —
            # the flat-axis twin of ops/spmd._tier_ordered_fold.
            from .synth import chain_groups

            steps = tuple(
                Step("level_fold", (grp, f), tier=level)
                for level, (grp, f)
                in enumerate(chain_groups(n, stack)))
            return Program("allreduce", "hier", n,
                           (Phase("seq", steps),))
        return Program("allreduce", "hier", n, (Phase("seq", (
            Step("level_fold", (inner, g)),
            Step("level_fold", (outer, ngroups)))),))

    if algorithm == "bidir":
        if n == 1:
            return _ident("allreduce", "bidir", n)
        if op in (C.MPI_MINLOC, C.MPI_MAXLOC):
            C.combine2(op, None, None)  # raises with explanation
        if deterministic:
            return _ordered_fold_program("bidir", n, op, nelems, itemsize)
        m = C.multipath_split(nelems)
        steps = [Step("ring_chain", (1,), span=("half", 0))]
        if m < nelems:
            steps.append(Step("ring_chain", (-1,), span=("half", 1)))
        return Program("allreduce", "bidir", n,
                       (Phase("multipath", tuple(steps)),))

    if algorithm == "torus":
        if n == 1:
            return _ident("allreduce", "torus", n)
        if op in (C.MPI_MINLOC, C.MPI_MAXLOC):
            C.combine2(op, None, None)  # raises with explanation
        from ..tune import resolve_hier_group

        g = resolve_hier_group(n)
        inner, outer, ngroups = _hier_groups(n, g)
        m = C.multipath_split(nelems)
        if op == C.MPI_SUM and not deterministic:
            ch0 = (Step("grouped_sum", (g, inner, outer, inner),
                        span=("half", 0)),)
            ch1 = (Step("grouped_sum", (ngroups, outer, inner, outer),
                        span=("half", 1)),)
        else:
            ch0 = (Step("level_fold", (inner, g), span=("half", 0)),
                   Step("level_fold", (outer, ngroups), span=("half", 0)))
            ch1 = (Step("level_fold", (outer, ngroups), span=("half", 1)),
                   Step("level_fold", (inner, g), span=("half", 1)))
        steps = ch0 + (ch1 if m < nelems else ())
        return Program("allreduce", "torus", n,
                       (Phase("multipath", steps),))

    if algorithm == "ring":
        if op == C.MPI_SUM:
            if deterministic:
                return _ordered_fold_program("ring", n, op, nelems,
                                             itemsize)
            return Program("allreduce", "ring", n,
                           (Phase("seq", (Step("native_allreduce"),)),))
        if op in (C.MPI_MAX, C.MPI_MIN):
            return Program("allreduce", "ring", n,
                           (Phase("seq", (Step("native_allreduce"),)),))
        if op in (C.MPI_MINLOC, C.MPI_MAXLOC):
            C.combine2(op, None, None)  # raises with explanation
        return _ordered_fold_program("ring", n, op, nelems, itemsize)

    raise CommError(
        f"no IR program registered for collective algorithm "
        f"{algorithm!r} (registered: {', '.join(PROGRAM_ALGORITHMS)})")


# ---------------------------------------------------------------------------
# Bcast_/Reduce_ tree and ring forms
# ---------------------------------------------------------------------------


def bcast_program(algorithm, n: int, root: int, *, nbytes: int) -> Program:
    """The Bcast_ program: ``tree`` pins the binomial-tree form,
    ``ring`` the root-masked psum pair; ``None`` keeps the size
    dispatch (``config.bcast_tree_max_bytes``) — exactly
    ``ops/spmd._bcast_value``."""
    if n == 1:
        return _ident("bcast", algorithm or "auto", n)
    if algorithm == "tree" or (
            algorithm not in ("ring",)
            and nbytes <= _config.bcast_tree_max_bytes()):
        return Program("bcast", "tree", n, (Phase("seq", (
            Step("tree_bcast", (root,)),)),))
    return Program("bcast", "ring", n, (Phase("seq", (
        Step("mask_root", (root,)), Step("native_allreduce"))),))


def reduce_program(algorithm, n: int, op: int, root: int, *,
                   deterministic: bool, nelems: int,
                   itemsize: int) -> Program:
    """The Reduce_ program: ``tree`` is the binomial reduce (whose
    transpose is the tree Bcast_ — the derived-backward pair the
    acceptance pins); everything else is the allreduce program with a
    root mask appended, ``ops/spmd._reduce_value``."""
    if algorithm == "tree":
        return Program("reduce", "tree", n, (Phase("seq", (
            Step("tree_reduce", (root,)),)),))
    base = allreduce_program("ring", n, op, deterministic=deterministic,
                             nelems=nelems, itemsize=itemsize)
    steps = tuple(s for ph in base.phases for s in ph.steps)
    return Program("reduce", "ring", n, (Phase("seq", steps + (
        Step("mask_root", (root,)),)),))


# ---------------------------------------------------------------------------
# Codec rewrite: compression as a program transformation
# ---------------------------------------------------------------------------


def rewrite_codec(program: Program, codec_name: str,
                  block: int) -> Program:
    """Rewrite an exact allreduce program for the in-schedule block-q8
    pipeline: every multipath channel of the program becomes ONE
    ``q8_ring_channel`` step annotated with the codec — the per-step
    codec rewrite that replaces the per-algorithm forks the fused
    pipeline used to thread by hand.  The channel's ring walk is
    derived from the program structure: exact ``ring_chain`` steps keep
    their direction (and stay reversible — ``bidir``'s backward flips
    them); grouped torus channels ride the transposed-grid walk of
    :func:`constants.multipath_ring_orders` with the inner group size
    read off the channel's own first step."""
    if not program.phases:
        return Program("allreduce", program.algorithm, program.nranks,
                       (), codec=codec_name)
    phase = program.phases[0]
    steps = []
    if phase.kind == "seq":
        # Single-channel program (ring): one identity-walk channel.
        steps.append(Step("q8_ring_channel", (None, 1, 0, False),
                          span="all", codec=codec_name))
    elif phase.kind == "multipath":
        by_span = {}
        for s in phase.steps:
            by_span.setdefault(s.span, []).append(s)
        spans = sorted(by_span, key=lambda sp: sp[1])
        # Grouped torus channels: channel 0 walks the grid row-major
        # (identity), channel 1 column-major — the shared
        # multipath_ring_orders rule; inner = the row-major channel's
        # own intra-tier group size, read off the program structure.
        first0 = by_span[spans[0]][0]
        inner = None
        if first0.kind == "grouped_sum":
            inner = int(first0.params[0])
        elif first0.kind == "level_fold":
            inner = int(first0.params[1])
        for k, span in enumerate(spans):
            first = by_span[span][0]
            if first.kind == "ring_chain":
                (d,) = first.params
                steps.append(Step("q8_ring_channel", (None, d, k, True),
                                  span=span, codec=codec_name))
            elif k == 0:
                steps.append(Step("q8_ring_channel", (None, 1, 0, False),
                                  span=span, codec=codec_name))
            else:
                if inner is None:
                    raise CommError(
                        "torus codec rewrite needs the row-major "
                        "channel's group size")
                steps.append(Step(
                    "q8_ring_channel", (("torus_col", inner), 1, k,
                                        False),
                    span=span, codec=codec_name))
    else:
        raise CommError(
            f"codec rewrite does not serve phase kind {phase.kind!r}")
    return Program("allreduce", program.algorithm, program.nranks,
                   (Phase("q8_multipath", tuple(steps)),),
                   codec=codec_name)


def q8_allreduce_program(algorithm, n: int, codec_name: str,
                         block: int, *, reverse: bool = False
                         ) -> Program:
    """Build + rewrite in one call: the exact program of ``algorithm``
    (sum, non-deterministic — the fused pipeline's regime) rewritten
    for the block-q8 codec; ``reverse`` derives the backward via
    :func:`.ir.transpose` (``bidir``'s channel directions swap, exactly
    the hand-written ``reverse=True`` path)."""
    from .ir import transpose

    # nelems=2 keeps both multipath channels in the program; the
    # lowering skips the empty half for tiny payloads exactly like the
    # hand-written pipeline did (the k>0 break).
    prog = allreduce_program(algorithm, n, C.MPI_SUM,
                             deterministic=False, nelems=2, itemsize=4)
    prog = rewrite_codec(prog, codec_name, block)
    return transpose(prog) if reverse else prog


def resolve_sigma(spec, n: int):
    """Materialize a ``q8_ring_channel`` sigma spec: ``None`` is the
    identity walk; ``("torus_col", inner)`` the column-major grid walk
    of :func:`constants.multipath_ring_orders`."""
    if spec is None:
        return None
    tag, inner = spec
    if tag != "torus_col":
        raise CommError(f"unknown q8 channel walk {spec!r}")
    inner = int(inner)
    outer = n // inner
    return tuple((p % outer) * inner + p // outer for p in range(n))

"""Collective-schedule IR + compiler (GC3-style, arXiv:2201.11840).

One program grammar for every schedule: algorithms are typed IR
programs (:mod:`.ir`), ONE lowering emits the compiled Mode A schedule
(:mod:`.lower`), ONE transposition rule derives every backward
(:func:`.ir.transpose`), ONE interpreter is the Mode B /
deterministic-mode fold oracle (:mod:`.interp`), ONE census generator
produces the analyze-grade wire/step/HLO accounting (:mod:`.census`),
and schedule *synthesis* is a search over programs (:mod:`.synth`) —
replacing the seven hand-maintained per-algorithm forks of
``ops/spmd.py``/``ops/eager.py``/``constants.py``/``compress/`` that
grew up independently.

``python -m mpi4torch_tpu.csched --smoke`` (``make ir-smoke``) runs
the re-expression matrix — every registered algorithm's IR lowering
pinned bit-identical (lowered text + Mode A/B values) against the
hand-written forms — plus the registry-sync guard and a
synthesized-schedule census verdict.
"""

from __future__ import annotations

from .census import (census_covers, program_census,
                     program_tier_census, tier_of_group,
                     tier_of_groups, weighted_cost)
from .interp import interpret_allreduce, interpreter_covers, \
    level_fold_groups
from .ir import (Phase, Program, STEP_KINDS, Step, transpose,
                 transposition_covers)
from .lower import (lower_allreduce, lower_q8_allreduce, lower_value,
                    lowering_covers)
from .programs import (NATIVE_EXEMPT, PROGRAM_ALGORITHMS,
                       allreduce_program, bcast_program, has_program,
                       q8_allreduce_program, reduce_program,
                       rewrite_codec)
from .synth import (TIER_COMPOSITIONS, autotune_synthesis,
                    autotune_tier_synthesis, factorization_chains,
                    fold_program, install, installed_program,
                    is_synth_name, rewrite_fold_codec,
                    synth_applicable, synthesize, synthesize_tiers)

__all__ = [
    "Program", "Phase", "Step", "STEP_KINDS", "transpose",
    "allreduce_program", "bcast_program", "reduce_program",
    "q8_allreduce_program", "rewrite_codec", "has_program",
    "PROGRAM_ALGORITHMS", "NATIVE_EXEMPT",
    "lower_allreduce", "lower_value", "lower_q8_allreduce",
    "interpret_allreduce", "level_fold_groups",
    "program_census", "program_tier_census", "tier_of_group",
    "tier_of_groups", "weighted_cost",
    "synthesize", "fold_program", "factorization_chains",
    "autotune_synthesis", "install", "installed_program",
    "is_synth_name", "synth_applicable",
    "synthesize_tiers", "autotune_tier_synthesis",
    "rewrite_fold_codec", "TIER_COMPOSITIONS",
    "lowering_covers", "interpreter_covers", "transposition_covers",
    "census_covers",
    "declared_vjp_census",
]


def declared_vjp_census(algorithm: str, nranks: int = 8) -> str:
    """The VJP-symmetry declaration DERIVED from the transposition
    rule (feeding ``AlgorithmSpec.vjp_census`` structurally): ``"self"``
    when the transposed program's census equals the forward's — true
    for every shipped allreduce schedule, since allreduce(SUM) is
    self-adjoint and direction flips preserve the census."""
    import jax.numpy as jnp

    from .. import constants as C

    prog = allreduce_program(algorithm, nranks, C.MPI_SUM,
                             deterministic=False, nelems=1024,
                             itemsize=jnp.dtype(jnp.float32).itemsize)
    fwd = program_census(prog, 1024, 4)
    bwd = program_census(transpose(prog), 1024, 4)
    return "self" if fwd == bwd else {"mismatch": (fwd, bwd)}

"""The one analyze-grade census generator: program -> wire/step/HLO.

``program_census(program, nelems, itemsize)`` computes, from the IR
alone — no per-algorithm census tables — the three deterministic
regression currencies the repo uses for every perf claim:

* ``wire_bytes_per_rank`` — bytes received per rank over the whole
  schedule (the analyze/accounting convention);
* ``seq_steps`` — sequential wire rounds (the latency proxy: a ring is
  ~2(N-1) rounds, a tree ceil(log2 N) per direction);
* ``hlo`` — predicted per-kind StableHLO collective-op counts of the
  lowered program, honoring the same config knobs the emitters honor
  (``chain_unroll_max`` rolls a chain's permutes into scans,
  ``phase_pipelined_ring`` fuses the det ring's relay lane), verified
  EXACTLY against :func:`mpi4torch_tpu.analyze.parse_program` counts of
  the actual lowering by ``make ir-smoke`` and tests/test_csched.py.

Synthesis (:mod:`.synth`) scores candidate programs on this census —
wire bytes first, then steps — so a synthesized winner's advantage is
a deterministic, hardware-independent verdict.
"""

from __future__ import annotations

from typing import Dict

from .. import config as _config
from .. import constants as C
from ..runtime import CommError
from .ir import Program, Step

_HLO_KINDS = ("all_reduce", "all_gather", "reduce_scatter",
              "collective_permute", "all_to_all")


def _zero_counts() -> Dict[str, int]:
    return {k: 0 for k in _HLO_KINDS}


def _ceil_log2(n: int) -> int:
    return max(1, (n - 1).bit_length()) if n > 1 else 0


# ---------------------------------------------------------------------------
# Per-kind census.  Signature: (step, n, span_elems, itemsize)
#   -> (wire_bytes_per_rank, seq_steps, hlo_counts)
# ---------------------------------------------------------------------------


def _census_native_allreduce(step, n, elems, itemsize):
    s = elems * itemsize
    wire = 2.0 * s * (n - 1) / n if n > 1 else 0.0
    return wire, 2 * (n - 1), {"all_reduce": 1}


def _census_level_fold(step, n, elems, itemsize):
    groups, g = step.params
    # One all-gather over groups of g: each rank receives (g-1) shards.
    return (g - 1) * elems * itemsize, 1, {"all_gather": 1}


def _census_ring_fold(step, n, elems, itemsize):
    chunk = max(1, _config.ordered_ring_chunk_bytes() // itemsize)
    nchunks = -(-elems // chunk)
    cbytes = chunk * itemsize
    if _config.phase_pipelined_ring():
        steps = nchunks + 2 * (n - 1)
        # Two chunk-sized permutes per scan step (fold + relay lanes).
        return 2.0 * steps * cbytes, steps, {"collective_permute": 2}
    steps = n + nchunks - 1
    bcast = _ceil_log2(n)
    wire = steps * cbytes + bcast * elems * itemsize
    return wire, steps + bcast, {"collective_permute": 1 + bcast}


def _census_butterfly(step, n, elems, itemsize):
    s = elems * itemsize
    log = _ceil_log2(n)
    # Halving phase moves S/2 + S/4 + ... = S*(n-1)/n; doubling the same.
    return 2.0 * s * (n - 1) / n, 2 * log, {"collective_permute": 2 * log}


def _census_tree_reduce(step, n, elems, itemsize):
    s = elems * itemsize
    log = _ceil_log2(n)
    return float(log * s), log, {"collective_permute": log}


_census_tree_bcast = _census_tree_reduce


def _census_mask_root(step, n, elems, itemsize):
    return 0.0, 0, {}


def _census_ring_chain(step, n, elems, itemsize):
    s = elems * itemsize
    hops = 2 * (n - 1)
    permutes = hops if n <= _config.chain_unroll_max() else 2
    return 2.0 * s * (n - 1) / n, hops, {"collective_permute": permutes}


def _census_grouped_sum(step, n, elems, itemsize):
    g, rs, ar, ag = step.params
    s = elems * itemsize
    ng = n // g
    wire = s * (g - 1) / g                      # grouped reduce-scatter
    wire += 2.0 * (s / g) * (ng - 1) / ng if ng > 1 else 0.0
    wire += s * (g - 1) / g                     # grouped all-gather
    steps = (g - 1) + 2 * (ng - 1) + (g - 1)
    return wire, steps, {"reduce_scatter": 1, "all_reduce": 1,
                         "all_gather": 1}


def _census_q8_ring_channel(step, n, elems, itemsize):
    from ..compress import get_codec

    codec = get_codec(step.codec)
    base = codec.base()
    block = base.block
    # int8 payload + one f32 scale per block, both directions of the
    # quantized ring (RS hops + encoded gather), per EF round.
    per_elem = 1.0 + 4.0 / block
    wire_round = 2.0 * elems * per_elem * (n - 1) / n if n > 1 else 0.0
    rounds = codec.ef_rounds
    hlo = {"collective_permute": 2 * (n - 1) * rounds,
           "all_gather": 2 * rounds}
    return wire_round * rounds, 2 * (n - 1) * rounds, hlo


def _census_q8_level_fold(step, n, elems, itemsize):
    from ..compress import get_codec

    groups, g = step.params
    block = get_codec(step.codec or "q8").base().block
    nb = -(-max(elems, 1) // block)
    # One grouped gather of the encoded contribution: (g-1) members,
    # each a zero-padded int8 payload (lower.q8_fold_blocks — the
    # shared padding rule) plus one f32 scale per block, gathered as
    # two all-gathers (payload, scales).
    wire = (g - 1) * (nb * block + 4 * nb)
    return float(wire), 1, {"all_gather": 2}


CENSUS = {
    "native_allreduce": _census_native_allreduce,
    "level_fold": _census_level_fold,
    "ring_fold": _census_ring_fold,
    "butterfly": _census_butterfly,
    "tree_reduce": _census_tree_reduce,
    "tree_bcast": _census_tree_bcast,
    "mask_root": _census_mask_root,
    "ring_chain": _census_ring_chain,
    "grouped_sum": _census_grouped_sum,
    "q8_ring_channel": _census_q8_ring_channel,
    "q8_level_fold": _census_q8_level_fold,
}


def census_covers():
    """Step kinds the census table serves (registry-guard probe)."""
    return tuple(CENSUS)


def _span_elems(step: Step, nelems: int) -> int:
    if step.span == "all":
        return nelems
    m = C.multipath_split(nelems)
    return m if step.span[1] == 0 else max(0, nelems - m)


def program_census(program: Program, nelems: int, itemsize: int) -> Dict:
    """Wire/step/HLO census of a program at a payload size.  Multipath
    channels are concurrent: their wire bytes add (both ride the link),
    their sequential rounds MAX (the channels overlap)."""
    if program is None:
        return {"wire_bytes_per_rank": 0, "seq_steps": 0,
                "hlo": _zero_counts(), "nsteps": 0}
    wire = 0.0
    hlo = _zero_counts()
    seq = 0
    for phase in program.phases:
        chan_steps: Dict[object, int] = {}
        for step in phase.steps:
            fn = CENSUS.get(step.kind)
            if fn is None:
                raise CommError(
                    f"no census entry for IR step kind {step.kind!r}")
            elems = _span_elems(step, nelems)
            if elems == 0:
                continue
            w, s, h = fn(step, program.nranks, elems, itemsize)
            wire += w
            for k, v in h.items():
                hlo[k] = hlo.get(k, 0) + v
            chan_steps[step.span] = chan_steps.get(step.span, 0) + s
        if chan_steps:
            seq += max(chan_steps.values())
    return {"wire_bytes_per_rank": int(round(wire)), "seq_steps": seq,
            "hlo": hlo, "nsteps": program.nsteps}


# ---------------------------------------------------------------------------
# Tier attribution + the bandwidth-weighted census (ISSUE 18)
# ---------------------------------------------------------------------------


def _tier_digits(rank: int, tiers):
    """Mixed-radix decomposition of a rank over the tier stack
    (innermost radix first) — rank = sum(digit[l] * stride[l]) with
    stride[l] = prod(tiers[:l]), the row-major layout
    :func:`.synth.chain_groups` and ``TierStackBackend`` both use."""
    out = []
    q = int(rank)
    for radix in tiers:
        out.append(q % radix)
        q //= radix
    return out


def tier_of_group(group, tiers) -> int:
    """THE tier-attribution rule: a replica group's traffic belongs to
    the HIGHEST tier whose mixed-radix digit differs between any two
    members — bytes between ranks in different pods cross the inter-pod
    link no matter how fast the intra-pod hops are.  Shared verbatim by
    the program census here, the StableHLO census
    (:func:`mpi4torch_tpu.analyze.tier_wire_table`) and the obs
    reconciliation, so prediction and measurement can only disagree
    about traffic, never about pricing."""
    ds = [_tier_digits(r, tiers) for r in group]
    for pos in range(len(tiers) - 1, -1, -1):
        if any(d[pos] != ds[0][pos] for d in ds):
            return pos
    return 0


def tier_of_groups(groups, tiers) -> int:
    """Attribution of a grouped step: None (whole axis) is the top
    tier; an explicit table takes the max over its groups."""
    if groups is None:
        return len(tiers) - 1
    return max(tier_of_group(g, tiers) for g in groups)


def program_tier_census(program: Program, nelems: int, itemsize: int,
                        tiers):
    """Per-tier wire bytes of a program (innermost tier first; sums to
    ``program_census(...)['wire_bytes_per_rank']``).  Grouped steps
    attribute by their group tables; ``grouped_sum`` splits its RS / AR
    / AG legs by each leg's table; whole-axis schedules (native, ring,
    butterfly, trees, chains) span every tier and are charged to the
    slowest link they cross — the top tier."""
    tiers = tuple(int(t) for t in tiers)
    per = [0.0] * len(tiers)
    top = len(tiers) - 1
    if program is None:
        return [0] * len(tiers)
    for phase in program.phases:
        for step in phase.steps:
            elems = _span_elems(step, nelems)
            if elems == 0:
                continue
            n = program.nranks
            if step.kind in ("level_fold", "q8_level_fold"):
                groups, _g = step.params
                w, _, _ = CENSUS[step.kind](step, n, elems, itemsize)
                per[tier_of_groups(groups, tiers)] += w
            elif step.kind == "grouped_sum":
                g, rs, ar, ag = step.params
                s = elems * itemsize
                ng = n // g
                per[tier_of_groups(rs, tiers)] += s * (g - 1) / g
                if ng > 1:
                    per[tier_of_groups(ar, tiers)] += \
                        2.0 * (s / g) * (ng - 1) / ng
                per[tier_of_groups(ag, tiers)] += s * (g - 1) / g
            else:
                w, _, _ = CENSUS[step.kind](step, n, elems, itemsize)
                per[top] += w
    return [int(round(w)) for w in per]


def weighted_cost(per_tier, bandwidths=None) -> float:
    """The bandwidth-weighted wire cost: ``sum(bytes[l] /
    bandwidth[l])`` — relative seconds-on-the-wire under the configured
    per-tier bandwidths (None = uniform).  THE synthesis ranking key
    and the figure :func:`mpi4torch_tpu.analyze.weighted_wire_cost`
    computes from lowered text."""
    per_tier = tuple(per_tier)
    if bandwidths is None:
        bandwidths = (1.0,) * len(per_tier)
    bandwidths = tuple(float(b) for b in bandwidths)
    if len(bandwidths) != len(per_tier):
        raise CommError(
            f"tier_bandwidths has {len(bandwidths)} entries for a "
            f"{len(per_tier)}-tier stack")
    return float(sum(w / b for w, b in zip(per_tier, bandwidths)))

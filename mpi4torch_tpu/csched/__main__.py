"""``python -m mpi4torch_tpu.csched --smoke`` — the IR smoke lane.

Non-zero exit on ANY divergence.  Three legs (``make ir-smoke``):

1. **Registry guard** — ``analyze.registry.csched_problems``: every
   registered algorithm declares an IR program (or a native
   exemption), every step kind is covered by the lowering /
   interpreter / transposition / census dispatch tables.
2. **Re-expression matrix** — every registered allreduce algorithm,
   forward AND transposition-derived backward, deterministic and not:
   the IR lowering's StableHLO text equals the hand-written form's
   BIT FOR BIT on the 8-virtual-device mesh, and the interpreter
   equals the eager rendezvous fold bitwise; the q8 codec leg pins the
   per-step rewrite against the hand-composed fused pipeline the same
   way; the tree Bcast_/Reduce_ pair pins ``transpose(bcast) ==
   reduce`` at the text level.
3. **Synthesis verdict** — the census-ranked winner for the 8-device
   world beats the hand-written deterministic ring on wire bytes, its
   predicted HLO census matches ``analyze.parse_program`` of the
   actual lowering EXACTLY, and the search is deterministic.

``python -m mpi4torch_tpu.csched --tiers`` (``make tiers-smoke``) is
the multi-pod tier-stack lane (ISSUE 18): per nested factorization of
the 8-device world — (2,2,2), (4,2), (2,4), (8,) — the
bandwidth-weighted synthesis winner under skewed slow-outer
``tier_bandwidths`` beats the flat ``bidir`` baseline with the
outer-tier byte reduction confirmed by the per-tier census of the
ACTUAL lowering (``analyze.tier_wire_table``), every searched
composition (``TIER_PARITY_COVERED``/``TIER_CENSUS_COVERED``) holds
Mode A/B bitwise parity and a self-adjoint transposition, the 2-level
stack lowers text-identical to the historical hier forms, and
``obs.reconcile(..., tiers=)`` prices the measured Mode B per-tier
traffic EXACTLY.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List

# Coverage literals of the ``--tiers`` lane (``make tiers-smoke``):
# which per-tier (algorithm x codec) compositions of the tier synthesis
# search space hold a Mode A/B bitwise parity cell and a per-tier
# census cell below.  ``analyze.registry.tier_program_problems``
# compares these against ``csched.TIER_COMPOSITIONS`` — a composition
# added to the search without lane coverage fails ``make tiers-smoke``
# AND ``make analyze-smoke`` structurally.
TIER_PARITY_COVERED = ("exact", "q8-slow")
TIER_CENSUS_COVERED = ("exact", "q8-slow")

# The nested factorizations the lane exercises on the 8-virtual-device
# world ((8,) is the degenerate single-tier stack — everything is top
# tier and the weighted census reduces to the flat one).
TIER_STACKS = ((2, 2, 2), (4, 2), (2, 4), (8,))


def _lower_text(fn, n: int, x, det: bool) -> str:
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from .. import config as _config
    from .._compat import shard_map
    from ..ops.spmd import SpmdContext

    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    ctx = SpmdContext(axis_name="w", size=n)
    wrapped = shard_map(lambda v: fn(ctx, v), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    with _config.deterministic_mode(det):
        return jax.jit(wrapped).lower(x).as_text()


def _run_smoke() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import constants as C
    from .. import csched
    from ..analyze import parse_program
    from ..analyze.registry import csched_problems
    from ..compress import get_codec
    from ..compress import spmd as _cspmd
    from ..ops import eager as _eager
    from ..ops import spmd as _spmd

    failures: List[str] = []
    report = {"worlds": [8], "reexpression": {}, "codec": {},
              "bcast_reduce": {}, "synthesis": {}}

    def check(ok: bool, label: str):
        if not ok:
            failures.append(label)
        return bool(ok)

    n = 8
    x = jnp.arange(512, dtype=jnp.float32) / 3.0
    rng = np.random.default_rng(7)
    vals = [jnp.asarray(rng.standard_normal(257), jnp.float32)
            for _ in range(n)]

    # ---- leg 1: registry guard -------------------------------------
    problems = csched_problems()
    check(not problems, f"registry guard: {problems}")
    report["registry_problems"] = problems

    # ---- leg 2: re-expression matrix -------------------------------
    legacy = {
        "ring": lambda c, v, op, det:
            _spmd._ordered_fold_allreduce(c, v, op) if det
            else jax.lax.psum(v, c.axis_name),
        "rhd": lambda c, v, op, det: _spmd._rhd_allreduce_value(c, v, op),
        "tree": lambda c, v, op, det:
            _spmd._tree_allreduce_value(c, v, op),
        "hier": lambda c, v, op, det:
            _spmd._hier_allreduce_value(c, v, op),
        "bidir": lambda c, v, op, det:
            _spmd._bidir_allreduce_value(c, v, op),
        "torus": lambda c, v, op, det:
            _spmd._torus_allreduce_value(c, v, op),
    }
    legacy_bwd = dict(legacy)
    legacy_bwd["bidir"] = lambda c, v, op, det: (
        _spmd._ordered_fold_allreduce(c, v, op) if det
        else _spmd._bidir_allreduce_value(c, v, op, reverse=True))

    from .. import tune as _tune

    for algo in sorted(_tune.available_algorithms()):
        cell = {}
        for det in (False, True):
            t_legacy = _lower_text(
                lambda c, v: legacy[algo](c, v, C.MPI_SUM, det), n, x,
                det)
            t_ir = _lower_text(
                lambda c, v: _spmd._allreduce_fwd_value(
                    c, v, C.MPI_SUM, algo), n, x, det)
            cell[f"fwd_text_det={det}"] = check(
                t_legacy == t_ir, f"{algo} fwd text det={det}")
            tb_legacy = _lower_text(
                lambda c, v: legacy_bwd[algo](c, v, C.MPI_SUM, det), n,
                x, det)
            tb_ir = _lower_text(
                lambda c, v: _spmd._allreduce_bwd_value(c, v, algo), n,
                x, det)
            cell[f"bwd_text_det={det}"] = check(
                tb_legacy == tb_ir, f"{algo} bwd text det={det}")
        # interpreter == the eager rendezvous fold, bitwise
        prog = csched.allreduce_program(
            algo, n, C.MPI_SUM, deterministic=True, nelems=257,
            itemsize=4)
        _, fold = _eager._rendezvous_fold(n, algo)
        cell["interp_bitwise"] = check(
            bool(jnp.all(csched.interpret_allreduce(prog, C.MPI_SUM,
                                                    vals)
                         == fold(C.MPI_SUM, vals))),
            f"{algo} interpreter vs rendezvous fold")
        # transposition-derived vjp_census agreement
        cell["vjp_census"] = check(
            csched.declared_vjp_census(algo, n)
            == _tune.get_algorithm(algo).vjp_census,
            f"{algo} transposition vs declared vjp_census")
        report["reexpression"][algo] = cell

    # ---- leg 2b: the q8 codec rides per-step rewrites ---------------
    for cname in ("q8", "q8_ef_hop"):
        codec = get_codec(cname)
        for algo in ("ring", "bidir", "torus"):
            t_legacy = _lower_text(
                lambda c, v: _cspmd._fused_allreduce_value(
                    c, v, codec, algo, False), n, x, False)
            t_ir = _lower_text(
                lambda c, v: _cspmd._allreduce_value(c, v, codec, algo),
                n, x, False)
            report["codec"][f"{cname}/{algo}"] = check(
                t_legacy == t_ir, f"codec {cname}/{algo} text")
            base = codec.base()
            prog = csched.q8_allreduce_program(algo, n, cname,
                                               base.block)
            inner = _tune.resolve_hier_group(n) if algo == "torus" \
                else None
            ref = C.reduce_q8_hop(
                vals, block=base.block, algorithm=algo, inner=inner,
                stochastic=getattr(base, "stochastic", False),
                hop_ef=getattr(base, "hop_ef", False),
                ef_rounds=codec.ef_rounds)
            report["codec"][f"{cname}/{algo}/interp"] = check(
                bool(jnp.all(csched.interpret_allreduce(
                    prog, C.MPI_SUM, vals) == ref)),
                f"codec {cname}/{algo} interp vs reduce_q8_hop")

    # ---- leg 2c: tree Bcast_/Reduce_ transposition pair -------------
    t_bcast = _lower_text(
        lambda c, v: _spmd._tree_bcast_value(c, v, 1), n, x, False)
    t_bcast_ir = _lower_text(
        lambda c, v: csched.lower_value(
            csched.bcast_program("tree", n, 1, nbytes=x.size * 4),
            c, v), n, x, False)
    report["bcast_reduce"]["bcast_tree_text"] = check(
        t_bcast == t_bcast_ir, "tree Bcast_ text")
    t_reduce = _lower_text(
        lambda c, v: _spmd._tree_reduce_value(c, v, C.MPI_SUM, 1), n, x,
        False)
    t_red_transposed = _lower_text(
        lambda c, v: csched.lower_value(
            csched.transpose(csched.bcast_program(
                "tree", n, 1, nbytes=x.size * 4)), c, v), n, x, False)
    report["bcast_reduce"]["reduce_is_transposed_bcast"] = check(
        t_reduce == t_red_transposed,
        "transpose(tree Bcast_) == tree Reduce_")

    # ---- leg 3: synthesized-schedule census verdict -----------------
    res = csched.synthesize(n, 1 << 14, 4)
    res_again = csched.synthesize(n, 1 << 14, 4)
    synth_cell = {
        "winner": res["winner"],
        "chain": res["chain"],
        "wire_bytes_per_rank": res["census"]["wire_bytes_per_rank"],
        "ring_wire_bytes_per_rank":
            res["ring_census"]["wire_bytes_per_rank"],
        "synthesis_beats_ring": res["synthesis_beats_ring"],
    }
    check(res["synthesis_beats_ring"], "synthesis beats ring")
    synth_cell["deterministic"] = check(
        res["winner"] == res_again["winner"], "synthesis determinism")
    prog = res["program"]
    name = csched.install(prog)
    txt = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM, name),
        n, x, True)
    got = parse_program(txt).census()
    pred = csched.program_census(prog, x.size, 4)["hlo"]
    synth_cell["hlo_reconciles"] = check(
        all(got.get(k, 0) == v for k, v in pred.items()),
        f"synth census reconcile: parse={got} predicted={pred}")
    oracle = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
    t_val = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM, name),
        n, x, True)
    synth_cell["lowerable"] = check(len(t_val) > 0, "synth lowerable")
    synth_cell["interp_finite"] = check(
        bool(jnp.all(jnp.isfinite(oracle))), "synth interp finite")
    report["synthesis"] = synth_cell

    report["failures"] = failures
    report["ok"] = not failures
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if not failures else 1


def _mode_a_rows(name: str, n: int, vals, det: bool = True):
    """Execute an installed program Mode A over an ``n``-device mesh
    with per-rank values ``vals``; returns the per-rank result rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from .. import config as _config
    from .. import constants as C
    from .._compat import shard_map
    from ..ops.spmd import SpmdContext
    from ..ops import spmd as _spmd

    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    ctx = SpmdContext(axis_name="w", size=n)
    stacked = jnp.stack(vals)
    wrapped = shard_map(
        lambda v: _spmd._allreduce_fwd_value(ctx, v[0], C.MPI_SUM,
                                             name)[None],
        mesh=mesh, in_specs=P("w"), out_specs=P("w"), check_vma=False)
    with _config.deterministic_mode(det):
        return jax.jit(wrapped)(stacked)


def _run_tiers() -> int:
    """``--tiers`` (``make tiers-smoke``): the multi-pod tier-stack
    verdict lane.  Non-zero exit on ANY divergence."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import config as _config
    from .. import constants as C
    from .. import csched
    from .. import analyze
    from ..analyze.registry import tier_program_problems
    from ..ops import spmd as _spmd

    failures: List[str] = []
    report = {"nranks": 8, "stacks": [list(s) for s in TIER_STACKS],
              "synthesis": {}, "parity": {}, "census": {},
              "two_level": {}, "reconcile": {}}

    def check(ok: bool, label: str):
        if not ok:
            failures.append(label)
        return bool(ok)

    n = 8
    x = jnp.arange(1024, dtype=jnp.float32)
    nbytes = x.size * 4
    # Integer-valued per-rank payloads: po2-scale block-q8 round-trips
    # integer grids exactly, so the q8-slow composition's Mode A/B
    # bitwise check is meaningful rather than comparing two rounding
    # paths.
    rng = np.random.default_rng(18)
    vals = [jnp.asarray(rng.integers(-40, 40, 257), jnp.float32)
            for _ in range(n)]

    # ---- leg 1: registry guard -------------------------------------
    problems = tier_program_problems()
    check(not problems, f"tier registry guard: {problems}")
    report["registry_problems"] = problems

    # ---- leg 2: weighted-census synthesis verdict -------------------
    # Skewed slow-outer bandwidths: the outermost tier (DCN) 20x under
    # the inner tiers (ICI) — the multi-pod shape the weighted census
    # exists for.
    for stack in TIER_STACKS:
        skew = tuple([1.0] * (len(stack) - 1) + [0.05]) \
            if len(stack) > 1 else (1.0,)
        res = csched.synthesize_tiers(n, nbytes, 4, tiers=stack,
                                      tier_bandwidths=skew)
        res2 = csched.synthesize_tiers(n, nbytes, 4, tiers=stack,
                                       tier_bandwidths=skew)
        key = "x".join(map(str, stack))
        cell = {
            "winner": res["winner"], "chain": res["chain"],
            "composition": res["composition"],
            "tier_wire": res["tier_wire"],
            "weighted_cost": res["weighted_cost"],
            "bidir_tier_wire": res["bidir_tier_wire"],
            "bidir_weighted_cost": res["bidir_weighted_cost"],
            "beats_bidir": res["beats_bidir"],
            "exact_beats_bidir": res["exact_beats_bidir"],
        }
        cell["deterministic"] = check(
            res["winner"] == res2["winner"],
            f"tiers {key}: synthesis determinism")
        if len(stack) > 1:
            cell["beats_bidir"] = check(
                res["beats_bidir"],
                f"tiers {key}: synthesized winner beats flat bidir on "
                "the weighted census")
            cell["outer_tier_reduced"] = check(
                res["tier_wire"][-1] < res["bidir_tier_wire"][-1],
                f"tiers {key}: outer-tier bytes reduced vs bidir "
                f"({res['tier_wire'][-1]} vs "
                f"{res['bidir_tier_wire'][-1]})")
            # Uniform bandwidths: the lossy variants must vanish (no
            # regression by construction) and the ranking degenerate to
            # the unweighted census.
            uni = csched.synthesize_tiers(n, nbytes, 4, tiers=stack)
            cell["uniform_all_exact"] = check(
                all(c["composition"] == "exact"
                    for c in uni["candidates"]),
                f"tiers {key}: uniform bandwidths admit lossy variants")
        report["synthesis"][key] = cell

        # ---- leg 3: per-tier census of the ACTUAL lowering ----------
        for label, prog in (("winner", res["program"]),
                            ("exact", res["exact_program"])):
            name = csched.install(prog)
            txt = _lower_text(
                lambda c, v: _spmd._allreduce_fwd_value(
                    c, v, C.MPI_SUM, name), n, x, True)
            got = analyze.tier_wire_table(txt, stack)
            pred = csched.program_tier_census(prog, x.size, 4, stack)
            report["census"][f"{key}/{label}"] = check(
                got == pred,
                f"tiers {key}/{label}: analyze tier table {got} != "
                f"program tier census {pred}")
            wc = analyze.weighted_wire_cost(txt, skew, tiers=stack)
            report["census"][f"{key}/{label}/weighted"] = check(
                wc == csched.weighted_cost(pred, skew),
                f"tiers {key}/{label}: weighted_wire_cost mismatch")

    # ---- leg 4: Mode A/B bitwise parity per composition -------------
    stack = (2, 2, 2)
    for comp in TIER_PARITY_COVERED:
        prog = csched.fold_program(n, stack, stack)
        if comp == "q8-slow":
            prog = csched.rewrite_fold_codec(prog, (len(stack) - 1,))
        name = csched.install(prog)
        rows = _mode_a_rows(name, n, vals)
        oracle = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
        cell = {}
        cell["a_vs_b_bitwise"] = check(
            bool(jnp.all(rows[0] == oracle)),
            f"tiers parity {comp}: Mode A != Mode B bitwise")
        cell["ranks_agree"] = check(
            all(bool(jnp.all(rows[r] == rows[0])) for r in range(n)),
            f"tiers parity {comp}: ranks disagree")
        # The ONE transposition rule still derives the backward: the
        # transposed program lowers and censuses as the forward does
        # (allreduce(SUM) is self-adjoint).
        bwd = csched.transpose(prog)
        cell["vjp_self"] = check(
            csched.program_tier_census(bwd, x.size, 4, stack)
            == csched.program_tier_census(prog, x.size, 4, stack),
            f"tiers parity {comp}: transposed tier census differs")
        report["parity"][comp] = cell

    # ---- leg 5: 2-level tier stack == hier, text-identical ----------
    # (a) flat world: config.tier_stack=(2,4) must lower the 'hier'
    # schedule byte-identically to the pre-tier hier_group_size form.
    t_base = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                "hier"), n, x, True)
    _config.set_tier_stack((2, 4))
    try:
        t_tiered = _lower_text(
            lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                    "hier"), n, x, True)
    finally:
        _config.set_tier_stack(None)
    report["two_level"]["flat_hier_text"] = check(
        t_base == t_tiered,
        "2-level tier_stack changes the flat hier lowering")
    # (b) mesh world: the 2-axis TierStackBackend vs HierMeshBackend.
    from jax.sharding import Mesh, PartitionSpec as P
    from .._compat import shard_map
    from ..ops.spmd import HierMeshBackend, TierStackBackend

    mesh2 = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                 ("g", "l"))

    def lower_backend(back):
        wrapped = shard_map(lambda v: back.allreduce(v, C.MPI_SUM),
                            mesh=mesh2, in_specs=P(), out_specs=P(),
                            check_vma=False)
        return jax.jit(wrapped).lower(x).as_text()

    report["two_level"]["mesh_text"] = check(
        lower_backend(TierStackBackend(("g", "l"), (2, 4)))
        == lower_backend(HierMeshBackend(("g", "l"), (2, 4))),
        "2-axis TierStackBackend lowers differently from "
        "HierMeshBackend")

    # ---- leg 6: obs.reconcile prices per-tier traffic EXACTLY -------
    import mpi4torch_tpu as mpi
    from mpi4torch_tpu import obs

    stack = (2, 2, 2)
    res = csched.synthesize_tiers(n, nbytes, 4, tiers=stack,
                                  tier_bandwidths=(1.0, 1.0, 0.05))
    name = csched.install(res["program"])
    comm = mpi.COMM_WORLD

    with obs.trace() as t:
        def body(rank):
            return comm.Allreduce(x * (rank + 1), mpi.MPI_SUM,
                                  algorithm=name)
        mpi.run_ranks(body, n)
    lowered = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM, name),
        n, x, True)
    rep = obs.reconcile(t.events, lowered, dropped=t.dropped,
                        tiers=stack)
    report["reconcile"] = {
        "measured_tier_wire": rep["measured"].get("tier_wire"),
        "predicted_tier_wire": rep["predicted"].get("tier_wire"),
        "matches": rep["matches"],
        "ok": rep["ok"],
    }
    check(rep["ok"] and rep["matches"].get("tier_wire"),
          f"reconcile per-tier mismatch: measured "
          f"{rep['measured'].get('tier_wire')} vs predicted "
          f"{rep['predicted'].get('tier_wire')} "
          f"(matches={rep['matches']})")

    report["failures"] = failures
    report["ok"] = not failures
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if not failures else 1


def _main(argv: Iterable[str]) -> int:
    argv = list(argv)
    if "--smoke" in argv:
        return _run_smoke()
    if "--tiers" in argv:
        return _run_tiers()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))

"""``python -m mpi4torch_tpu.csched --smoke`` — the IR smoke lane.

Non-zero exit on ANY divergence.  Three legs (``make ir-smoke``):

1. **Registry guard** — ``analyze.registry.csched_problems``: every
   registered algorithm declares an IR program (or a native
   exemption), every step kind is covered by the lowering /
   interpreter / transposition / census dispatch tables.
2. **Re-expression matrix** — every registered allreduce algorithm,
   forward AND transposition-derived backward, deterministic and not:
   the IR lowering's StableHLO text equals the hand-written form's
   BIT FOR BIT on the 8-virtual-device mesh, and the interpreter
   equals the eager rendezvous fold bitwise; the q8 codec leg pins the
   per-step rewrite against the hand-composed fused pipeline the same
   way; the tree Bcast_/Reduce_ pair pins ``transpose(bcast) ==
   reduce`` at the text level.
3. **Synthesis verdict** — the census-ranked winner for the 8-device
   world beats the hand-written deterministic ring on wire bytes, its
   predicted HLO census matches ``analyze.parse_program`` of the
   actual lowering EXACTLY, and the search is deterministic.
"""

from __future__ import annotations

import json
import sys
from typing import Iterable, List


def _lower_text(fn, n: int, x, det: bool) -> str:
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from .. import config as _config
    from .._compat import shard_map
    from ..ops.spmd import SpmdContext

    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    ctx = SpmdContext(axis_name="w", size=n)
    wrapped = shard_map(lambda v: fn(ctx, v), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    with _config.deterministic_mode(det):
        return jax.jit(wrapped).lower(x).as_text()


def _run_smoke() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import constants as C
    from .. import csched
    from ..analyze import parse_program
    from ..analyze.registry import csched_problems
    from ..compress import get_codec
    from ..compress import spmd as _cspmd
    from ..ops import eager as _eager
    from ..ops import spmd as _spmd

    failures: List[str] = []
    report = {"worlds": [8], "reexpression": {}, "codec": {},
              "bcast_reduce": {}, "synthesis": {}}

    def check(ok: bool, label: str):
        if not ok:
            failures.append(label)
        return bool(ok)

    n = 8
    x = jnp.arange(512, dtype=jnp.float32) / 3.0
    rng = np.random.default_rng(7)
    vals = [jnp.asarray(rng.standard_normal(257), jnp.float32)
            for _ in range(n)]

    # ---- leg 1: registry guard -------------------------------------
    problems = csched_problems()
    check(not problems, f"registry guard: {problems}")
    report["registry_problems"] = problems

    # ---- leg 2: re-expression matrix -------------------------------
    legacy = {
        "ring": lambda c, v, op, det:
            _spmd._ordered_fold_allreduce(c, v, op) if det
            else jax.lax.psum(v, c.axis_name),
        "rhd": lambda c, v, op, det: _spmd._rhd_allreduce_value(c, v, op),
        "tree": lambda c, v, op, det:
            _spmd._tree_allreduce_value(c, v, op),
        "hier": lambda c, v, op, det:
            _spmd._hier_allreduce_value(c, v, op),
        "bidir": lambda c, v, op, det:
            _spmd._bidir_allreduce_value(c, v, op),
        "torus": lambda c, v, op, det:
            _spmd._torus_allreduce_value(c, v, op),
    }
    legacy_bwd = dict(legacy)
    legacy_bwd["bidir"] = lambda c, v, op, det: (
        _spmd._ordered_fold_allreduce(c, v, op) if det
        else _spmd._bidir_allreduce_value(c, v, op, reverse=True))

    from .. import tune as _tune

    for algo in sorted(_tune.available_algorithms()):
        cell = {}
        for det in (False, True):
            t_legacy = _lower_text(
                lambda c, v: legacy[algo](c, v, C.MPI_SUM, det), n, x,
                det)
            t_ir = _lower_text(
                lambda c, v: _spmd._allreduce_fwd_value(
                    c, v, C.MPI_SUM, algo), n, x, det)
            cell[f"fwd_text_det={det}"] = check(
                t_legacy == t_ir, f"{algo} fwd text det={det}")
            tb_legacy = _lower_text(
                lambda c, v: legacy_bwd[algo](c, v, C.MPI_SUM, det), n,
                x, det)
            tb_ir = _lower_text(
                lambda c, v: _spmd._allreduce_bwd_value(c, v, algo), n,
                x, det)
            cell[f"bwd_text_det={det}"] = check(
                tb_legacy == tb_ir, f"{algo} bwd text det={det}")
        # interpreter == the eager rendezvous fold, bitwise
        prog = csched.allreduce_program(
            algo, n, C.MPI_SUM, deterministic=True, nelems=257,
            itemsize=4)
        _, fold = _eager._rendezvous_fold(n, algo)
        cell["interp_bitwise"] = check(
            bool(jnp.all(csched.interpret_allreduce(prog, C.MPI_SUM,
                                                    vals)
                         == fold(C.MPI_SUM, vals))),
            f"{algo} interpreter vs rendezvous fold")
        # transposition-derived vjp_census agreement
        cell["vjp_census"] = check(
            csched.declared_vjp_census(algo, n)
            == _tune.get_algorithm(algo).vjp_census,
            f"{algo} transposition vs declared vjp_census")
        report["reexpression"][algo] = cell

    # ---- leg 2b: the q8 codec rides per-step rewrites ---------------
    for cname in ("q8", "q8_ef_hop"):
        codec = get_codec(cname)
        for algo in ("ring", "bidir", "torus"):
            t_legacy = _lower_text(
                lambda c, v: _cspmd._fused_allreduce_value(
                    c, v, codec, algo, False), n, x, False)
            t_ir = _lower_text(
                lambda c, v: _cspmd._allreduce_value(c, v, codec, algo),
                n, x, False)
            report["codec"][f"{cname}/{algo}"] = check(
                t_legacy == t_ir, f"codec {cname}/{algo} text")
            base = codec.base()
            prog = csched.q8_allreduce_program(algo, n, cname,
                                               base.block)
            inner = _tune.resolve_hier_group(n) if algo == "torus" \
                else None
            ref = C.reduce_q8_hop(
                vals, block=base.block, algorithm=algo, inner=inner,
                stochastic=getattr(base, "stochastic", False),
                hop_ef=getattr(base, "hop_ef", False),
                ef_rounds=codec.ef_rounds)
            report["codec"][f"{cname}/{algo}/interp"] = check(
                bool(jnp.all(csched.interpret_allreduce(
                    prog, C.MPI_SUM, vals) == ref)),
                f"codec {cname}/{algo} interp vs reduce_q8_hop")

    # ---- leg 2c: tree Bcast_/Reduce_ transposition pair -------------
    t_bcast = _lower_text(
        lambda c, v: _spmd._tree_bcast_value(c, v, 1), n, x, False)
    t_bcast_ir = _lower_text(
        lambda c, v: csched.lower_value(
            csched.bcast_program("tree", n, 1, nbytes=x.size * 4),
            c, v), n, x, False)
    report["bcast_reduce"]["bcast_tree_text"] = check(
        t_bcast == t_bcast_ir, "tree Bcast_ text")
    t_reduce = _lower_text(
        lambda c, v: _spmd._tree_reduce_value(c, v, C.MPI_SUM, 1), n, x,
        False)
    t_red_transposed = _lower_text(
        lambda c, v: csched.lower_value(
            csched.transpose(csched.bcast_program(
                "tree", n, 1, nbytes=x.size * 4)), c, v), n, x, False)
    report["bcast_reduce"]["reduce_is_transposed_bcast"] = check(
        t_reduce == t_red_transposed,
        "transpose(tree Bcast_) == tree Reduce_")

    # ---- leg 3: synthesized-schedule census verdict -----------------
    res = csched.synthesize(n, 1 << 14, 4)
    res_again = csched.synthesize(n, 1 << 14, 4)
    synth_cell = {
        "winner": res["winner"],
        "chain": res["chain"],
        "wire_bytes_per_rank": res["census"]["wire_bytes_per_rank"],
        "ring_wire_bytes_per_rank":
            res["ring_census"]["wire_bytes_per_rank"],
        "synthesis_beats_ring": res["synthesis_beats_ring"],
    }
    check(res["synthesis_beats_ring"], "synthesis beats ring")
    synth_cell["deterministic"] = check(
        res["winner"] == res_again["winner"], "synthesis determinism")
    prog = res["program"]
    name = csched.install(prog)
    txt = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM, name),
        n, x, True)
    got = parse_program(txt).census()
    pred = csched.program_census(prog, x.size, 4)["hlo"]
    synth_cell["hlo_reconciles"] = check(
        all(got.get(k, 0) == v for k, v in pred.items()),
        f"synth census reconcile: parse={got} predicted={pred}")
    oracle = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
    t_val = _lower_text(
        lambda c, v: _spmd._allreduce_fwd_value(c, v, C.MPI_SUM, name),
        n, x, True)
    synth_cell["lowerable"] = check(len(t_val) > 0, "synth lowerable")
    synth_cell["interp_finite"] = check(
        bool(jnp.all(jnp.isfinite(oracle))), "synth interp finite")
    report["synthesis"] = synth_cell

    report["failures"] = failures
    report["ok"] = not failures
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if not failures else 1


def _main(argv: Iterable[str]) -> int:
    argv = list(argv)
    if "--smoke" in argv:
        return _run_smoke()
    print(__doc__)
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))

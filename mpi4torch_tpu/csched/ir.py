"""The collective-schedule IR: one program grammar for every schedule.

Follow GC3 (PAPERS.md, arXiv:2201.11840): a collective algorithm is a
*program* — a sequence of :class:`Phase`\\ s of :class:`Step`\\ s drawn
from ONE closed step grammar — instead of a hand-maintained fork of
lowering + VJP + eager fold + census code per algorithm.  Everything
else in the package dispatches over :data:`STEP_KINDS`:

* :mod:`.lower`   — the one Mode A emitter (``collective_permute`` /
  ``lax.scan`` schedules over a mesh axis);
* :mod:`.interp`  — the one Mode B / deterministic-mode fold oracle;
* :func:`transpose` (here) — the one rule deriving every backward
  program from the forward program;
* :mod:`.census`  — the one analyze-grade wire/step/HLO accounting;
* :mod:`.synth`   — schedule synthesis as a search over IR programs.

The grammar (closed — the registry-sync guard
``analyze.registry.csched_problems`` fails when a kind exists without
lowering + interpreter + transposition + census coverage):

=================== ==================================================
kind                 meaning (params)
=================== ==================================================
``native_allreduce`` XLA's native whole-axis collective — ``lax.psum``
                     / ``pmax`` / ``pmin`` by reduction op ``()``
``level_fold``       all-gather over a rank grouping + ascending fold
                     — one tier of an ordered deterministic reduction
                     ``(groups|None, fold_count)``
``ring_fold``        the scan-pipelined chunked deterministic ring
                     (ops/spmd ``_ring_fold_allreduce``) ``()``
``butterfly``        the recursive-halving/doubling exchange schedule
                     (power-of-two worlds) ``()``
``tree_reduce``      binomial reduce-to-root rounds + root mask
                     ``(root,)``
``tree_bcast``       root mask + binomial broadcast rounds ``(root,)``
``mask_root``        zero every non-root rank's value ``(root,)``
``ring_chain``       one directional exact RS+AG ``collective_permute``
                     ring chain (the ``bidir`` half) ``(direction,)``
``grouped_sum``      the native 2-level triple: grouped reduce-scatter
                     → grouped allreduce → grouped all-gather
                     ``(g, rs_groups, ar_groups, ag_groups)``
``q8_ring_channel``  a codec-rewritten in-schedule quantized ring
                     channel (compress/spmd ``_fused_channel``)
                     ``(sigma_spec, direction, channel, reversible)``
``q8_level_fold``    a codec-compressed level fold: block-q8 encode →
                     grouped all-gather of (int8, scales) → decode →
                     ascending fold.  Deterministic and Mode A/B
                     bitwise like ``level_fold``; the wire is ~1.125
                     bytes/elem instead of 4 ``(groups|None,
                     fold_count)`` (codec rides ``Step.codec``)
=================== ==================================================

``Step.span`` places a step on the whole payload (``"all"``) or on a
multipath half (``("half", k)`` — split at
:func:`constants.multipath_split`, the shared Mode A/B rule).
``Step.tier`` is the tier index of a tier-stack composition (0 =
innermost/fastest interconnect; None = untiered) — annotation only for
lowering/interp (the groups already encode the placement), but the
per-tier census (:func:`.census.program_tier_census`) and the
bandwidth-weighted ranking key off the replica-group structure, so the
index is a label the weighted census can cross-check.
``Step.codec`` is the per-step codec-hop annotation: the codec rewrite
(:func:`.programs.rewrite_codec`) replaces exact channel steps with
``q8_ring_channel`` steps carrying it, so compression is a program
transformation instead of a per-algorithm fork.

Transposition rule (:func:`transpose`): allreduce programs are
self-adjoint — the backward is the same program with every directional
step's ring direction reversed (``ring_chain`` and reversible
``q8_ring_channel`` flip; everything else is order- and kind-fixed) —
while root collectives (bcast/reduce) reverse their phase/step list
under the kind map ``tree_reduce ↔ tree_bcast`` (``mask_root`` and
``native_allreduce`` are self-adjoint), the PR 8 reversed-step-list
discipline.  Both fixed points are proved structurally in
doc/schedule_ir.md and pinned by ``make ir-smoke``.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Optional, Tuple

from ..runtime import CommError

# The closed step grammar.  Extending it means extending the lowering,
# interpreter, transposition and census dispatch tables — the
# csched_problems registry guard fails `make analyze-smoke` otherwise.
STEP_KINDS = (
    "native_allreduce",
    "level_fold",
    "ring_fold",
    "butterfly",
    "tree_reduce",
    "tree_bcast",
    "mask_root",
    "ring_chain",
    "grouped_sum",
    "q8_ring_channel",
    "q8_level_fold",
)

# Phase kinds: "seq" runs its steps in order on the whole payload;
# "multipath" stripes the flat payload across per-span channels
# (disjoint halves at constants.multipath_split) whose step
# sub-sequences are independent — XLA schedules them concurrently;
# "q8_multipath" is the codec-rewritten multipath form (f32 wire
# staging + final astype, matching compress/spmd's fused pipeline).
PHASE_KINDS = ("seq", "multipath", "q8_multipath")


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    return v


@dataclass(frozen=True)
class Step:
    """One step of a schedule program.  ``params`` are static,
    JSON-serializable kind-specific arguments (group tables, roots,
    ring directions); ``span`` places the step on the payload;
    ``codec`` is the codec-hop annotation (None = exact wire)."""

    kind: str
    params: Tuple = ()
    span: object = "all"          # "all" | ("half", k)
    codec: Optional[str] = None
    tier: Optional[int] = None    # tier-stack index (0 = innermost)

    def __post_init__(self):
        if self.kind not in STEP_KINDS:
            raise CommError(
                f"unknown IR step kind {self.kind!r}; the grammar is "
                f"closed over {STEP_KINDS}")
        object.__setattr__(self, "params", _freeze(self.params))
        object.__setattr__(self, "span", _freeze(self.span))


@dataclass(frozen=True)
class Phase:
    kind: str
    steps: Tuple[Step, ...] = ()

    def __post_init__(self):
        if self.kind not in PHASE_KINDS:
            raise CommError(
                f"unknown IR phase kind {self.kind!r}; expected one of "
                f"{PHASE_KINDS}")
        object.__setattr__(self, "steps", tuple(self.steps))


@dataclass(frozen=True)
class Program:
    """A typed schedule program: ``collective`` names the op family the
    program computes, ``algorithm`` the source schedule name (a
    registered algorithm or ``synth``), ``nranks`` the world the
    program was built for (programs are world-specialized, like the
    schedules they express), ``codec`` the wire codec after a codec
    rewrite (None = exact)."""

    collective: str
    algorithm: str
    nranks: int
    phases: Tuple[Phase, ...] = ()
    codec: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "phases", tuple(self.phases))

    # -- structural accounting -------------------------------------------
    def steps(self) -> Tuple[Step, ...]:
        return tuple(s for ph in self.phases for s in ph.steps)

    @property
    def nsteps(self) -> int:
        return len(self.steps())

    # -- serialization ----------------------------------------------------
    def to_json(self) -> dict:
        return {
            "collective": self.collective,
            "algorithm": self.algorithm,
            "nranks": self.nranks,
            "codec": self.codec,
            "phases": [
                {"kind": ph.kind,
                 "steps": [
                     # "tier" only when set: untiered programs keep
                     # their pre-tier digests (synth:<digest> cache
                     # identities survive the tier dimension).
                     dict({"kind": s.kind, "params": s.params,
                           "span": s.span, "codec": s.codec},
                          **({"tier": s.tier}
                             if s.tier is not None else {}))
                     for s in ph.steps]}
                for ph in self.phases],
        }

    @classmethod
    def from_json(cls, data: dict) -> "Program":
        phases = tuple(
            Phase(ph["kind"], tuple(
                Step(s["kind"], _freeze(s.get("params", ())),
                     _freeze(s.get("span", "all")), s.get("codec"),
                     s.get("tier"))
                for s in ph["steps"]))
            for ph in data["phases"])
        return cls(collective=data["collective"],
                   algorithm=data["algorithm"],
                   nranks=int(data["nranks"]),
                   phases=phases, codec=data.get("codec"))

    def digest(self) -> str:
        """Canonical content digest — the identity of a synthesized
        program in the tune cache (``synth:<digest>``)."""
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"), default=list)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:10]


# ---------------------------------------------------------------------------
# Transposition
# ---------------------------------------------------------------------------

# Kind map applied when a root collective's step list is reversed.
# Every step kind must have an entry — csched_problems checks this
# table alongside the lowering/interpreter/census dispatch tables.
TRANSPOSE_KINDS = {
    "native_allreduce": "native_allreduce",
    "level_fold": "level_fold",
    "ring_fold": "ring_fold",
    "butterfly": "butterfly",          # halve↔double reversal fixed point
    "tree_reduce": "tree_bcast",
    "tree_bcast": "tree_reduce",
    "mask_root": "mask_root",
    "ring_chain": "ring_chain",        # + direction flip (below)
    "grouped_sum": "grouped_sum",      # RS↔AG reversal fixed point
    "q8_ring_channel": "q8_ring_channel",  # + flip when reversible
    "q8_level_fold": "q8_level_fold",  # gather+fold: direction-free
}


def _flip_step(step: Step) -> Step:
    """Directional adjoint of one step: ring chains reverse their ring
    direction (the adjoint of a ring segment is the reverse-direction
    ring), reversible quantized channels likewise; every other kind is
    direction-free."""
    if step.kind == "ring_chain":
        (d,) = step.params
        return Step("ring_chain", (-d,), step.span, step.codec,
                    step.tier)
    if step.kind == "q8_ring_channel":
        sigma, d, k, reversible = step.params
        if reversible:
            return Step("q8_ring_channel", (sigma, -d, k, reversible),
                        step.span, step.codec, step.tier)
    return step


def transpose(program: Optional[Program]) -> Optional[Program]:
    """THE backward-derivation rule (the PR 8 ``adjoint()`` discipline
    generalized).  Sum-allreduce programs are self-adjoint: the
    backward is the same program with each directional step flipped
    (``bidir``'s counter-rotating chains swap directions; everything
    else is its own adjoint — the rhd halve/double and hier RS/AR/AG
    step lists are palindromic under reversal + kind transpose, so the
    in-place form below is the normalized fixed point).  Root
    collectives (bcast/reduce) reverse their phase and step lists under
    :data:`TRANSPOSE_KINDS` — ``transpose(bcast) == reduce`` and back,
    per tree round and per masked-psum pair."""
    if program is None:
        return None
    if program.collective == "allreduce":
        phases = tuple(
            Phase(ph.kind, tuple(_flip_step(s) for s in ph.steps))
            for ph in program.phases)
        return Program(program.collective, program.algorithm,
                       program.nranks, phases, program.codec)
    phases = tuple(
        Phase(ph.kind, tuple(
            Step(TRANSPOSE_KINDS[s.kind], s.params, s.span, s.codec,
                 s.tier)
            for s in reversed(ph.steps)))
        for ph in reversed(program.phases))
    collective = {"bcast": "reduce", "reduce": "bcast"}.get(
        program.collective, program.collective)
    return Program(collective, program.algorithm, program.nranks,
                   phases, program.codec)


def transposition_covers() -> Tuple[str, ...]:
    """Step kinds the transposition table serves (the registry guard's
    coverage probe)."""
    return tuple(TRANSPOSE_KINDS)

"""The one Mode A emitter: IR program -> compiled SPMD schedule.

One lowering serves every registered program: it walks the phases,
dispatches each step through :data:`EMIT` (the closed per-kind emitter
table the registry guard checks), and handles the multipath payload
striping — flat view, :func:`constants.multipath_split`, per-channel
emission in span order, concat — in exactly the op order the
hand-written schedules used, so the lowered StableHLO text of every
re-expressed algorithm is BIT-IDENTICAL to its original form (pinned
by ``make ir-smoke``).  Step emitters reuse the schedule bodies in
:mod:`mpi4torch_tpu.ops.spmd` (scan forms honor
``config.chain_unroll_max`` and ``config.phase_pipelined_ring``
through them unchanged); the quantized channel emitter reuses
:func:`mpi4torch_tpu.compress.spmd._fused_channel`, so a codec rewrite
changes WHICH steps lower, never how a hop lowers.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import constants as C
from ..runtime import CommError
from .ir import Phase, Program, Step
from .programs import resolve_sigma


def _groups_arg(groups):
    if groups is None:
        return None
    return [list(g) for g in groups]


# ---------------------------------------------------------------------------
# Per-kind emitters.  Signature: (step, ctx, x, op) -> value.
# ---------------------------------------------------------------------------


def _emit_native_allreduce(step: Step, ctx, x, op: int):
    if op == C.MPI_SUM:
        return lax.psum(x, ctx.axis_name)
    if op == C.MPI_MAX:
        return lax.pmax(x, ctx.axis_name)
    if op == C.MPI_MIN:
        return lax.pmin(x, ctx.axis_name)
    raise CommError(
        f"no native XLA collective for {C.op_name(op)}; the program "
        "builder routes such ops through the ordered fold")


def _emit_level_fold(step: Step, ctx, x, op: int):
    groups, g = step.params
    from ..ops import spmd as _spmd

    if groups is None:
        return _spmd._gather_fold_allreduce(ctx, x, op)
    stacked = lax.all_gather(x, ctx.axis_name, axis=0, tiled=False,
                             axis_index_groups=_groups_arg(groups))
    out = stacked[0]
    for i in range(1, g):
        out = C.combine2(op, out, stacked[i])
    return out


def _emit_ring_fold(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    return _spmd._ring_fold_allreduce(ctx, x, op)


def _emit_butterfly(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    return _spmd._rhd_allreduce_value(ctx, x, op)


def _emit_tree_reduce(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    (root,) = step.params
    return _spmd._tree_reduce_value(ctx, x, op, root)


def _emit_tree_bcast(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    (root,) = step.params
    return _spmd._tree_bcast_value(ctx, x, root)


def _emit_mask_root(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    (root,) = step.params
    return _spmd._mask_to_root(ctx, x, root)


def _emit_ring_chain(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    (d,) = step.params
    return _spmd._ring_allreduce_chain(ctx, x, op, d)


def _emit_grouped_sum(step: Step, ctx, x, op: int):
    from ..ops import spmd as _spmd

    g, rs, ar, ag = step.params
    axis = ctx.axis_name
    return _spmd._grouped_sum_schedule(
        x, g, (axis, _groups_arg(rs)), (axis, _groups_arg(ar)),
        (axis, _groups_arg(ag)))


def _emit_q8_ring_channel(step: Step, ctx, x, op: int):
    raise CommError(
        "q8_ring_channel steps lower through lower_q8_allreduce (the "
        "codec-rewritten pipeline), not the exact emitter")


def q8_fold_blocks(flat, block: int):
    """The (nblocks, block) zero-padded block view of a flat f32
    payload — the ``q8_level_fold`` wire layout.  ONE padding rule for
    the Mode A emitter, the Mode B interpreter and the census (which
    prices the padded int8 payload + 4 bytes/block of scales), so the
    three can never disagree about bytes on the wire."""
    nb = -(-max(flat.size, 1) // block)
    pad = nb * block - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat.reshape(nb, block)


def q8_fold_roundtrip(x, block: int):
    """decode(encode(x)) through the ``q8_level_fold`` wire codec: what
    a peer's contribution looks like after the grouped gather.  Shared
    by the Mode A emitter (applied to each gathered member) and the
    Mode B interpreter (applied rank-locally before the fold) — the
    same ``quant_kernels.requant_blocks`` op sequence (power-of-two
    scales, exact dequantize products), so both modes fold
    bit-identical values."""
    from ..ops import quant_kernels as _qk

    shape, dtype = jnp.shape(x), jnp.asarray(x).dtype
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    q, scale = _qk.requant_blocks(q8_fold_blocks(flat, block))
    dec = q.astype(jnp.float32) * scale[:, None]
    return dec.reshape(-1)[:flat.size].reshape(shape).astype(dtype)


def _fold_block(step: Step) -> int:
    from ..compress import get_codec

    return get_codec(step.codec or "q8").base().block


def _emit_q8_level_fold(step: Step, ctx, x, op: int):
    from ..ops import quant_kernels as _qk

    groups, g = step.params
    block = _fold_block(step)
    shape, dtype = x.shape, x.dtype
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    q, scale = _qk.requant_blocks(q8_fold_blocks(flat, block))
    gather = dict(axis=0, tiled=False,
                  axis_index_groups=_groups_arg(groups))
    qs = lax.all_gather(q, ctx.axis_name, **gather)
    ss = lax.all_gather(scale, ctx.axis_name, **gather)
    out = None
    for i in range(g):
        dec = (qs[i].astype(jnp.float32) * ss[i][:, None]
               ).reshape(-1)[:flat.size].reshape(shape).astype(dtype)
        out = dec if out is None else C.combine2(op, out, dec)
    return out


EMIT = {
    "native_allreduce": _emit_native_allreduce,
    "level_fold": _emit_level_fold,
    "ring_fold": _emit_ring_fold,
    "butterfly": _emit_butterfly,
    "tree_reduce": _emit_tree_reduce,
    "tree_bcast": _emit_tree_bcast,
    "mask_root": _emit_mask_root,
    "ring_chain": _emit_ring_chain,
    "grouped_sum": _emit_grouped_sum,
    "q8_ring_channel": _emit_q8_ring_channel,
    "q8_level_fold": _emit_q8_level_fold,
}


def lowering_covers():
    """Step kinds the emitter table serves (registry-guard probe)."""
    return tuple(EMIT)


# ---------------------------------------------------------------------------
# Program lowering
# ---------------------------------------------------------------------------


def _span_channels(phase: Phase):
    """Group a multipath phase's steps by span, in span order; each
    channel's steps run sequentially, channels are independent."""
    by_span = {}
    for s in phase.steps:
        by_span.setdefault(s.span, []).append(s)

    def key(sp):
        return sp[1] if isinstance(sp, tuple) else -1

    return [(sp, by_span[sp]) for sp in sorted(by_span, key=key)]


def _emit_multipath(phase: Phase, ctx, x, op: int):
    shape = x.shape
    flat = x.reshape(-1)
    total = flat.size
    m = C.multipath_split(total)
    outs = []
    for k, (span, steps) in enumerate(_span_channels(phase)):
        if k > 0 and m >= total:
            break
        part = flat[:m] if k == 0 else flat[m:]
        for step in steps:
            part = EMIT[step.kind](step, ctx, part, op)
        outs.append(part)
    if len(outs) == 1:
        return outs[0].reshape(shape)
    return jnp.concatenate(outs).reshape(shape)


def lower_allreduce(program: Program, ctx, x, op: int):
    """Lower an allreduce program at the call site: the value this
    returns is what the hand-written schedule returned, op for op."""
    if program is None or not program.phases:
        return x
    if program.codec is not None:
        raise CommError(
            "codec-annotated programs lower through lower_q8_allreduce")
    for phase in program.phases:
        if phase.kind == "multipath":
            x = _emit_multipath(phase, ctx, x, op)
        else:
            for step in phase.steps:
                x = EMIT[step.kind](step, ctx, x, op)
    return x


def lower_value(program: Program, ctx, x, op: int = C.MPI_SUM):
    """Lower a bcast/reduce program (sequential phases only)."""
    if program is None or not program.phases:
        return x
    for phase in program.phases:
        for step in phase.steps:
            x = EMIT[step.kind](step, ctx, x, op)
    return x


def lower_q8_allreduce(program: Program, ctx, x, codec):
    """Lower a codec-rewritten allreduce program: the in-schedule
    block-q8 pipeline, channel for channel and salt for salt the byte
    layout of the fused hand-written form (compress/spmd.py) — f32
    staging, per-channel :func:`_fused_channel` with
    ``ring_salt(round, channel)``, the codec's error-feedback rounds,
    concat, final astype."""
    if program is None or not program.phases:
        return x
    from ..compress.spmd import _fused_channel
    from ..ops import quant_kernels as _qk

    base = codec.base()
    shape, dtype = x.shape, x.dtype
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    total = flat.size
    steps = program.phases[0].steps
    m = C.multipath_split(total) if len(steps) > 1 else total
    outs = []
    for k, step in enumerate(steps):
        if k > 0 and m >= total:
            break
        sigma_spec, d, chan, _reversible = step.params
        sigma = resolve_sigma(sigma_spec, ctx.size)
        part = flat[:m] if k == 0 else flat[m:]
        out, resid = _fused_channel(ctx, part, base,
                                    _qk.ring_salt(0, chan), sigma, d,
                                    track=codec.ef_rounds > 1)
        for r in range(1, codec.ef_rounds):
            last = r == codec.ef_rounds - 1
            more, resid = _fused_channel(ctx, resid, base,
                                         _qk.ring_salt(r, chan), sigma,
                                         d, track=not last)
            out = out + more
        outs.append(out)
    flat_out = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    return flat_out.reshape(shape).astype(dtype)
